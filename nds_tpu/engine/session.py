"""Session: the user-facing entry point of the SQL engine.

Plays the role SparkSession plays in the reference's workload jobs
(reference nds_power.py:221-245 builds the session and registers temp views;
run_one_query at :124-134 is `spark.sql(q).collect()`). Here tables register
from Arrow/Parquet and `sql()` parses, plans, and executes on the JAX engine.
"""
from __future__ import annotations

import os
import threading
from typing import Callable, Optional

import pyarrow as pa
import pyarrow.dataset as pa_dataset

from ..config import EngineConfig
from ..obs import metrics as _metrics
from ..obs.stats import ExecStats
from ..obs.trace import TRACER
from ..sql import parse_sql
from .column import Table
from .executor import Executor
from .planner import Catalog, Planner
from . import arrow_bridge


def _engine_table_stats(t: Table) -> dict:
    """{column: (lo, hi)} for an already-materialized engine Table (view
    registrations): engine units by construction."""
    import numpy as np

    from .column import is_dec

    out: dict = {}
    for name, c in zip(t.names, t.columns):
        if not (c.dtype in ("int", "date") or is_dec(c.dtype)):
            continue
        data = np.asarray(c.data)[c.validity]
        if data.size:
            out[name] = (int(data.min()), int(data.max()))
    return out


def _enc_tag(enc) -> str:
    """Human/JSON-stable encoding tag for stats/bench reporting."""
    if isinstance(enc, tuple):
        return f"{enc[0]}[{enc[1]}]"
    return str(enc)


def _engine_col_enc_stat(t: Table, col: str):
    """Encoding stats (cardinality/runs) for one column of an engine
    Table (view registrations): engine units by construction."""
    from .column import is_dec

    i = t.names.index(col)
    c = t.columns[i]
    if not (c.dtype in ("int", "date") or is_dec(c.dtype)):
        return None
    import numpy as np

    return arrow_bridge.column_enc_stat_values(
        np.asarray(c.data), c.validity)


def _and_conjuncts(node):
    """Top-level AND conjuncts of a WHERE AST (shared by the partition and
    file-stats delete pruners)."""
    from ..sql import ast_nodes as A
    if isinstance(node, A.BinOp) and node.op == "and":
        yield from _and_conjuncts(node.left)
        yield from _and_conjuncts(node.right)
    else:
        yield node


class Session:
    def __init__(self, config: Optional[EngineConfig] = None):
        self.config = config or EngineConfig()
        # -- concurrency contract (the query service, nds_tpu/service) ------
        # _sql_lock serializes whole statements: sql()/execute() bodies run
        # one at a time, so the executor, streaming state, and the
        # last_exec_stats* views stay consistent under multi-threaded entry
        # (service_run returns result+stats atomically under it).
        # _lock guards the lazily-built shared caches that CONCURRENT
        # non-statement work reads/writes — the service's planner threads
        # hit column_stats/column_enc_stats/load_table while the device
        # lane executes; both locks are RLocks, ordering _sql_lock -> _lock.
        self._sql_lock = threading.RLock()
        self._lock = threading.RLock()
        if self.config.fault_points:
            # arm the engine-level fault registry from config/property file
            # (nds.tpu.fault_points=point:action,...): the resilience layer's
            # injectable failures — see nds_tpu/resilience.py
            from ..resilience import FAULTS
            FAULTS.configure(self.config.fault_points)
        if self.config.query_log or self.config.query_log_path:
            # arm the process-wide durable query log (obs/query_log.py);
            # clear=False — a second session must not wipe the ring the
            # first one already filled
            from ..obs.query_log import QUERY_LOG
            QUERY_LOG.configure(
                enabled=True, capacity=self.config.query_log_capacity,
                path=self.config.query_log_path or None,
                max_bytes=self.config.query_log_max_bytes,
                max_files=self.config.query_log_max_files, clear=False)
        # -- adaptive execution (engine/feedback.py) ------------------------
        # the feedback stats store closing the loop from observed actuals
        # back into plans: armed only by config.adaptive_plans (default off
        # = no store, no counters, bit-identical plans). Persists beside
        # the query log when one is configured (crash-consistent JSON), or
        # at config.feedback_path; otherwise in-memory for the session.
        self._feedback = None
        if self.config.adaptive_plans:
            from .feedback import FeedbackStore
            fb_path = self.config.feedback_path
            if not fb_path and self.config.query_log_path:
                fb_path = os.path.join(
                    os.path.dirname(self.config.query_log_path) or ".",
                    "plan_feedback.json")
            self._feedback = FeedbackStore(
                path=fb_path or None,
                drift_ratio=self.config.feedback_drift_ratio)
        self.warehouse = None  # attached via attach_warehouse for DML
        self._loaders: dict[str, Callable[[], Table]] = {}
        self._schemas: dict[str, tuple[list[str], list[str]]] = {}
        self._est_rows: dict[str, int] = {}
        # declared single-column unique keys per table (late-materialization
        # legality); NDS table names default from schema.UNIQUE_KEYS
        self._unique_cols: dict[str, frozenset] = {}
        self._cache: dict[str, Table] = {}
        # optional streaming readers for out-of-core scans: name ->
        # fn(columns) yielding arrow tables/batches
        self._batch_sources: dict = {}
        # per-table column value-range stats for narrow-lane planning:
        # name -> callable() -> {column: (lo, hi) in engine units}, lazily
        # evaluated and cached (column_stats); registration/drop invalidates
        self._stats_sources: dict = {}
        self._col_stats: dict[str, dict] = {}
        # per-table per-column ENCODING stats (cardinality + run counts)
        # for encoded-execution planning: name -> callable(column) ->
        # {"distinct": ..., "runs": ...} or None, lazily evaluated and
        # cached per column (column_enc_stats); registration invalidates
        self._enc_stats_sources: dict = {}
        self._enc_stats: dict[str, dict] = {}
        # device-backend fallback observability, reset per sql() call
        self.last_fallbacks: list[str] = []
        # execution-mode/timing observability for the last sql() call:
        # last_exec_stats is the backward-compatible DICT VIEW of the typed
        # record in last_exec_stats_typed — both are installed by the single
        # builder _finish_exec_stats (obs.stats.ExecStats)
        self.last_exec_stats: dict = {}
        self.last_exec_stats_typed: Optional[ExecStats] = None
        # EXPLAIN ANALYZE (obs/profile.py): the PlanProfile of the last
        # profiled execution (explain_analyze() or config.profile_plans);
        # None until a statement runs profiled
        self.last_profile = None
        # raw per-run collection the streamed path always records (cheap
        # host counters it computes anyway: per-group walls + rows, per-
        # job partial/final rows, finalize wall) — the streamed profile
        # and ExecStats.node_stats are built from it
        self._last_stream_profile: Optional[dict] = None
        # label of the in-flight sql() call (runners pass the query name);
        # compiled programs inherit it for device-time attribution
        self._active_label: str = ""
        # query-log statement context (_sql_locked sets both per call):
        # wall start + whether this statement cuts its own log row
        self._stmt_t0: float = 0.0
        self._stmt_log: bool = True
        # catalog generation: bumped on any (re-)registration so the device
        # executor's scan cache and compiled plans never serve stale data
        self._generation = 0
        # per-table generations beside the global counter: the semantic
        # result cache invalidates entries by the generations of the base
        # tables a plan actually touches, so re-registering table A never
        # evicts cached results over table B (the global counter stays the
        # stream-cache/compiled-plan key — those embed cross-table state)
        self._table_generations: dict[str, int] = {}
        # snapshot-pinned warehouse reads (warehouse.py _snapshots log):
        # per-table MANIFEST versions of the pinned warehouse version
        # (Warehouse.register_all fills both; empty/None when unpinned —
        # no snapshot log, warehouse_transactions off, or the writer
        # session mid-transaction). The result cache stamps entries with
        # these, so a cached result is provably from the snapshot the
        # reader pinned, not merely "same session generation".
        self._table_snapshot_versions: dict[str, int] = {}
        self._warehouse_version: Optional[int] = None
        # source-content fingerprints for warehouse registrations: lets
        # Warehouse.register_all skip tables whose snapshot files did not
        # change (a maintenance INSERT into store_sales must not bump the
        # other 23 tables' generations and cold their caches)
        self._source_files: dict[str, tuple] = {}
        # maintenance-delta subscribers (result_cache IVM): called as
        # fn(table, inserts=arrow|None, deletes=arrow|None) AFTER the
        # warehouse commit re-registers, under the statement lock
        self._delta_subscribers: list = []
        # optional attached semantic result cache (engine/result_cache.py)
        self.result_cache = None
        self._jax_exec = None
        self._jax_exec_gen = -1
        # out-of-core: per-query streaming state (rewritten plan + compiled
        # morsel programs + executor with its scan cache); None = known
        # not-streamable. Invalidated when the catalog generation moves OR
        # any streaming-relevant config field changes (_stream_config_key):
        # cached plans/sentinels embed late_materialization, chunk_rows,
        # shared_scan..., so a live-session toggle must not replay them.
        self._stream_cache: dict[str, Optional[dict]] = {}
        self._stream_cache_cfg: Optional[tuple] = None
        # sharded morsel execution (config.mesh_shards): the data-parallel
        # replica mesh streamed scan groups dispatch over, built lazily
        self._morsel_mesh_obj = None
        # morsel-boundary preemption (service fair scheduler): the query
        # service installs a hook the streamed path calls between scan
        # groups / morsels; None (the default) keeps the streamed loop
        # bit-identical to before the hook existed (one attribute read).
        # _in_preempt guards against recursive preemption while a nested
        # statement runs inside preempt_scope on the SAME thread (the
        # RLocks make the nested entry legal; depth stays <= 1).
        self._preempt_hook = None
        self._in_preempt = False

    def _morsel_shards(self) -> int:
        """Effective replica count for sharded morsel execution: 0 when the
        knob is off (mesh_shards unset or 1) — the single-chip path then
        runs bit-identically to before the knob existed."""
        n = int(self.config.mesh_shards or 0)
        return n if n > 1 else 0

    def _morsel_mesh(self):
        """The data-parallel "shards" mesh streamed morsels partition over
        (parallel/mesh.make_mesh — the standalone primitives' mesh is now
        the engine's entry point). Raises ValueError when the backend has
        fewer devices than config.mesh_shards (for virtual-device testing
        set XLA_FLAGS=--xla_force_host_platform_device_count)."""
        n = self._morsel_shards()
        if not n:
            return None
        if self._morsel_mesh_obj is None or \
                self._morsel_mesh_obj.devices.size != n:
            from ..parallel import make_mesh
            self._morsel_mesh_obj = make_mesh(n)
        return self._morsel_mesh_obj

    def _device_mesh(self):
        """Build the SPMD mesh from config.mesh_shape (None = single device).

        Multi-chip execution shards fact scans over this mesh and lets
        GSPMD partition the compiled plan (all_to_all = shuffle, all_gather
        = broadcast join, psum = partial-aggregate merge — the XLA-native
        equivalents of Spark's executor shuffle, SURVEY.md §5)."""
        if not self.config.mesh_shape:
            return None
        import numpy as np

        import jax
        from jax.sharding import Mesh
        shape = tuple(self.config.mesh_shape)
        n = int(np.prod(shape))
        devices = np.asarray(jax.devices()[:n]).reshape(shape)
        return Mesh(devices, self.config.mesh_axis_names[:len(shape)])

    def _jax_executor(self):
        """The session-held device executor: device-resident scan cache and
        compiled plans persist across the whole query stream (the reference
        keeps tables hot on the executors across the 103-query power run)."""
        # invalidation key includes the kernel choice: toggling pallas_ops
        # on a live session (A/B runs) must rebuild the executor — its
        # cached programs/schedules embed which kernels they traced
        cfg = self.config
        exec_key = (self._generation, tuple(sorted(cfg.pallas_ops)))
        if self._jax_exec is None or self._jax_exec_gen != exec_key:
            from .jax_backend import JaxExecutor
            self._jax_exec = JaxExecutor(
                self.load_table, jit_plans=cfg.jit_plans,
                mesh=self._device_mesh(),
                shard_min_rows=cfg.shard_min_rows,
                segment_plan_nodes=cfg.segment_plan_nodes,
                segment_min_cte_nodes=cfg.segment_min_cte_nodes,
                segment_cache_entries=cfg.segment_cache_entries,
                scan_budget_bytes=int(cfg.scan_budget_gb * (1 << 30)),
                pallas_ops=cfg.pallas_ops)
            self._jax_exec_gen = exec_key
        return self._jax_exec

    def _dec_as_int(self) -> bool:
        """decimal_physical="i64": decimal columns load as exact scaled
        int64 ("decN" logical dtype) instead of f64 (SURVEY.md §7 scaled-
        int64 decimal plan; reference DecimalType, nds/nds_schema.py:43-47).
        """
        return self.config.decimal_physical == "i64"

    def _set_unique_cols(self, name: str, col_names,
                         unique_cols) -> None:
        """Record the table's declared single-column unique keys.

        None (the default) consults schema.UNIQUE_KEYS — NDS dimension
        surrogate keys are unique by the TPC-DS spec, so warehouse/power
        registrations get them automatically; an explicit tuple (possibly
        empty) overrides, so synthetic tables opt in or out deliberately."""
        if unique_cols is None:
            from ..schema import UNIQUE_KEYS
            unique_cols = UNIQUE_KEYS.get(name, ())
        have = set(col_names)
        self._unique_cols[name] = frozenset(
            c for c in unique_cols if c in have)

    def _bump_generation(self, name: str) -> None:
        """One (re-)registration or drop of `name`: the global generation
        moves (stream cache / compiled plans / executor scan cache) AND the
        table's own generation moves (result-cache invalidation scope)."""
        self._generation += 1
        self._table_generations[name] = \
            self._table_generations.get(name, 0) + 1

    def table_generation(self, name: str) -> int:
        """Current per-table catalog generation (0 = never registered)."""
        return self._table_generations.get(name, 0)

    def table_snapshot_version(self, name: str) -> Optional[int]:
        """Manifest version of `name` under the pinned warehouse
        snapshot, or None when the table's registration is unpinned
        (non-warehouse source, no snapshot log, or mid-transaction)."""
        return self._table_snapshot_versions.get(name)

    def warehouse_version(self) -> Optional[int]:
        """The warehouse version this session's registrations are
        pinned to (None = unpinned/manifest-latest)."""
        return self._warehouse_version

    def attach_result_cache(self, cache) -> None:
        """Bind a semantic ResultCache (engine/result_cache.py): the cache
        reads per-table generations for invalidation and subscribes to
        maintenance deltas for incremental view maintenance. Idempotent."""
        self.result_cache = cache
        if cache.apply_delta not in self._delta_subscribers:
            self._delta_subscribers.append(cache.apply_delta)

    def _publish_table_delta(self, table: str, inserts=None,
                             deletes=None) -> None:
        """Hand one maintenance statement's row delta to every subscriber
        (called after the warehouse commit re-registered the table, so
        subscribers see the post-statement catalog generations). Subscriber
        failures degrade to invalidation inside the subscriber — a delta
        must never fail the DML statement that produced it."""
        if not self._delta_subscribers or (inserts is None
                                           and deletes is None):
            return
        for fn in list(self._delta_subscribers):
            fn(table, inserts=inserts, deletes=deletes)

    # -- registration -------------------------------------------------------
    def register_arrow(self, name: str, table: pa.Table,
                       est_rows: Optional[int] = None,
                       unique_cols: Optional[tuple] = None) -> None:
        dec = self._dec_as_int()
        names, dtypes = arrow_bridge.engine_schema(table.schema, dec)
        self._schemas[name] = (names, dtypes)
        self._set_unique_cols(name, names, unique_cols)
        self._est_rows[name] = est_rows if est_rows is not None else table.num_rows
        self._loaders[name] = lambda columns=None, t=table, dec=dec: \
            arrow_bridge.from_arrow(t.select(list(columns)) if columns else t,
                                    dec)

        def batches(columns, t=table):
            yield t.select(list(columns)) if columns else t
        self._batch_sources[name] = batches
        self._stats_sources[name] = \
            lambda t=table, dec=dec: arrow_bridge.table_column_stats(t, dec)
        self._enc_stats_sources[name] = \
            lambda col, t=table, dec=dec: \
            arrow_bridge.column_enc_stat(t.column(col), dec)
        self._drop_cached(name)
        self._bump_generation(name)

    def register_parquet(self, name: str, path: str,
                         est_rows: Optional[int] = None,
                         unique_cols: Optional[tuple] = None) -> None:
        """Register a parquet file or partitioned directory as a table."""
        dataset = pa_dataset.dataset(path, format="parquet",
                                     partitioning="hive")
        # re-open with dictionary pass-through for the fully dictionary-
        # encoded string columns (metadata probe): the staging thread then
        # receives codes + dictionary instead of re-encoding every morsel
        fmt = arrow_bridge.parquet_dataset_format(list(dataset.files))
        if fmt is not None:
            dataset = pa_dataset.dataset(path, format=fmt,
                                         partitioning="hive")
        schema = dataset.schema
        dec = self._dec_as_int()
        names, dtypes = arrow_bridge.engine_schema(schema, dec)
        self._schemas[name] = (names, dtypes)
        self._set_unique_cols(name, names, unique_cols)
        if est_rows is None:
            est_rows = dataset.count_rows()
        self._est_rows[name] = est_rows

        def load(columns=None, ds=dataset, dec=dec):
            cols = list(columns) if columns is not None else None
            return arrow_bridge.from_arrow(ds.to_table(columns=cols), dec)
        self._loaders[name] = load

        def batches(columns, ds=dataset):
            cols = list(columns) if columns is not None else None
            yield from ds.to_batches(columns=cols)
        self._batch_sources[name] = batches
        # parquet row-group METADATA carries per-column min/max: lane
        # planning costs one metadata pass, no data read
        self._stats_sources[name] = \
            lambda ds=dataset, dec=dec: arrow_bridge.parquet_column_stats(
                list(ds.files), dec)
        # encoding stats need the values (cardinality/runs have no parquet
        # metadata): ONE vectorized single-column read, cached per column
        # per registration generation
        self._enc_stats_sources[name] = \
            lambda col, ds=dataset, dec=dec: arrow_bridge.column_enc_stat(
                ds.to_table(columns=[col]).column(col), dec)
        self._drop_cached(name)
        self._bump_generation(name)

    def register_csv(self, name: str, path: str, schema: pa.Schema,
                     est_rows: Optional[int] = None,
                     delimiter: str = "|",
                     unique_cols: Optional[tuple] = None) -> None:
        """Register a pipe-delimited file or directory of files lazily
        (the reference registers raw CSV as Spark temp views with explicit
        schema, nds_power.py:78-105)."""
        import pyarrow.csv as pa_csv

        files = ([os.path.join(path, f) for f in sorted(os.listdir(path))]
                 if os.path.isdir(path) else [path])
        dec = self._dec_as_int()
        names, dtypes = arrow_bridge.engine_schema(schema, dec)
        self._schemas[name] = (names, dtypes)
        self._set_unique_cols(name, names, unique_cols)
        self._est_rows[name] = est_rows if est_rows is not None else 10000

        def load(columns=None, files=tuple(files), schema=schema, dec=dec):
            convert = pa_csv.ConvertOptions(
                column_types={f.name: f.type for f in schema},
                null_values=[""], strings_can_be_null=True,
                include_columns=list(columns) if columns else None)
            read = pa_csv.ReadOptions(column_names=[f.name for f in schema])
            parse = pa_csv.ParseOptions(delimiter=delimiter)
            parts = [pa_csv.read_csv(f, read_options=read,
                                     parse_options=parse,
                                     convert_options=convert)
                     for f in files if os.path.getsize(f) > 0]
            return arrow_bridge.from_arrow(pa.concat_tables(parts), dec)
        self._loaders[name] = load

        def batches(columns, files=tuple(files), schema=schema):
            convert = pa_csv.ConvertOptions(
                column_types={f.name: f.type for f in schema},
                null_values=[""], strings_can_be_null=True,
                include_columns=list(columns) if columns else None)
            read = pa_csv.ReadOptions(column_names=[f.name for f in schema])
            parse = pa_csv.ParseOptions(delimiter=delimiter)
            for f in files:
                if os.path.getsize(f) > 0:
                    yield pa_csv.read_csv(f, read_options=read,
                                          parse_options=parse,
                                          convert_options=convert)
        self._batch_sources[name] = batches
        self._drop_cached(name)
        self._bump_generation(name)

    def register_view(self, name: str, table: Table,
                      dtypes: Optional[list[str]] = None,
                      unique_cols: Optional[tuple] = None) -> None:
        """Register an engine Table (e.g. a temp view) directly."""
        dts = dtypes or [c.dtype for c in table.columns]
        self._schemas[name] = (list(table.names), dts)
        self._set_unique_cols(name, table.names, unique_cols)
        self._est_rows[name] = table.num_rows
        self._loaders[name] = lambda columns=None, t=table: \
            t if columns is None else t.select(list(columns))
        self._stats_sources[name] = lambda t=table: _engine_table_stats(t)
        self._enc_stats_sources[name] = \
            lambda col, t=table: _engine_col_enc_stat(t, col)
        self._drop_cached(name)
        self._cache[(name, None)] = table
        self._bump_generation(name)

    def drop(self, name: str) -> None:
        self._schemas.pop(name, None)
        self._loaders.pop(name, None)
        self._batch_sources.pop(name, None)
        self._stats_sources.pop(name, None)
        self._enc_stats_sources.pop(name, None)
        self._drop_cached(name)
        self._est_rows.pop(name, None)
        self._unique_cols.pop(name, None)
        self._source_files.pop(name, None)
        self._table_snapshot_versions.pop(name, None)
        self._bump_generation(name)

    def table_names(self) -> list[str]:
        return list(self._schemas)

    def _drop_cached(self, name: str) -> None:
        with self._lock:
            for k in [k for k in self._cache if k[0] == name]:
                del self._cache[k]
            self._col_stats.pop(name, None)
            self._enc_stats.pop(name, None)

    def column_stats(self, name: str) -> dict:  # lint: thread-entry (service planner threads read stats concurrently)
        """{column: (lo, hi)} value-range stats in ENGINE units (scaled
        ints for decimals, epoch days for dates) for a registered table;
        {} when the registration has no stats source. Lazily computed and
        cached per registration generation — streaming derives the static
        per-column upload lane spec from these (device.plan_lanes), and the
        plan verifier proves declared lanes against the same ranges.
        Thread-safe: the generation cache is read and written under the
        session state lock (service planner threads race the device lane)."""
        with self._lock:
            if name in self._col_stats:
                return self._col_stats[name]
            src = self._stats_sources.get(name)
            stats = {}
            if src is not None:
                try:
                    stats = src() or {}
                except Exception:
                    stats = {}  # stats are an optimization, never a failure
            self._col_stats[name] = stats
            return stats

    def column_enc_stats(self, name: str, columns=None) -> dict:  # lint: thread-entry (service planner threads read stats concurrently)
        """{column: {"distinct": sorted int64 array or None, "runs": int}}
        encoding stats for (a subset of) a registered table's columns, in
        ENGINE units; {} when the registration has no encoding-stats
        source. Lazily computed and cached PER COLUMN per registration
        generation — only the columns a scan group actually streams pay
        the (one-time) cardinality/run pass. Feeds device.plan_encodings
        and the verifier's "encoding" findings. Thread-safe like
        column_stats: cache writes happen under the session state lock."""
        with self._lock:
            src = self._enc_stats_sources.get(name)
            if src is None:
                return {}
            if columns is None:
                columns = self._schemas.get(name, ([], []))[0]
            cache = self._enc_stats.setdefault(name, {})
            for c in columns:
                if c in cache:
                    continue
                try:
                    cache[c] = src(c)
                except Exception:
                    cache[c] = None  # stats are an optimization, never fatal
            return {c: cache[c] for c in columns if cache.get(c)}

    @staticmethod
    def _manifest_enc_source(wt, files, dataset, dec):
        """Per-column encoding-stats source for a warehouse registration:
        manifest-recorded per-file stats aggregate with no data read;
        columns the manifest predates fall back to one vectorized
        single-column dataset read."""
        agg: dict = {}

        def src(col):
            if "done" not in agg:
                try:
                    agg["stats"] = wt.column_enc_stats(list(files))
                except Exception:
                    agg["stats"] = {}
                agg["done"] = True
            st = agg["stats"].get(col)
            if st is not None:
                return st
            return arrow_bridge.column_enc_stat(
                dataset.to_table(columns=[col]).column(col), dec)
        return src

    def iter_morsels(self, name: str, columns: list[str], rows: int):
        """Yield host Tables of at most `rows` rows each, WITHOUT
        materializing the whole table (out-of-core scans). Parquet datasets
        stream record batches; arrow tables slice zero-copy; CSV falls back
        to per-file reads."""
        import pyarrow as pa

        def flush(pending):
            # a single pending slice (aligned source batches, the common
            # parquet row-group case) passes through zero-copy — concat
            # would re-chunk and copy for nothing
            return pending[0] if len(pending) == 1 \
                else pa.concat_tables(pending)

        def emit(batches):
            """Re-chunk a stream of arrow tables into `rows`-sized morsels."""
            pending: list[pa.Table] = []
            count = 0
            for b in batches:
                t = pa.Table.from_batches([b]) if isinstance(
                    b, pa.RecordBatch) else b
                while t.num_rows:
                    take = min(rows - count, t.num_rows)
                    pending.append(t.slice(0, take))
                    t = t.slice(take)
                    count += take
                    if count == rows:
                        yield flush(pending)
                        pending, count = [], 0
            if pending:
                yield flush(pending)

        src = self._batch_sources.get(name)
        if src is not None:
            batches = src(columns)
        else:  # fallback: full load, sliced (correct, not memory-bounded)
            batches = [arrow_bridge.to_arrow(self.load_table(name, columns))]
        for part in emit(batches):
            yield arrow_bridge.from_arrow(part, self._dec_as_int())

    def load_table(self, name: str, columns=None) -> Table:  # lint: thread-entry (streaming staging threads + service lanes load concurrently)
        """Load a table, optionally projected to `columns` (scan pruning:
        fact tables carry ~23 columns but a query touches a handful — the
        reference gets this from parquet column projection in Spark scans).
        Cached per projection; a cached full table serves any subset.
        Thread-safe: the projection cache is populated under the session
        state lock (staging threads and service lanes load concurrently)."""
        with self._lock:
            key = (name, tuple(columns) if columns is not None else None)
            if key in self._cache:
                return self._cache[key]
            if columns is not None and (name, None) in self._cache:
                full = self._cache[(name, None)]
                idx = {n: i for i, n in enumerate(full.names)}
                sub = Table(list(columns),
                            [full.columns[idx[c]] for c in columns])
                self._cache[key] = sub
                return sub
            self._cache[key] = self._loaders[name](columns)
            return self._cache[key]

    # -- query --------------------------------------------------------------
    def _est_rows_for(self, name: str, default: int,
                      label: Optional[str] = None) -> int:
        """Planning-time row estimate for ``name``: the registered static
        estimate, unless adaptive execution has OBSERVED this table's
        streamed row count under the same query template — the feedback
        store's ground truth then replaces the catalog guess, flipping
        streamed-vs-in-core and late-materialization decisions from what
        actually happened last time. ``label`` scopes the lookup (service
        planner threads pass the ticket's label explicitly — they run
        outside _sql_lock, so _active_label belongs to someone else)."""
        if self._feedback is not None:
            key = self._active_label if label is None else label
            observed = self._feedback.table_rows(key).get(name)
            if observed is not None:
                return int(observed)
        return self._est_rows.get(name, default)

    def _catalog(self, label: Optional[str] = None) -> Catalog:
        return Catalog({name: (sch[0], sch[1],
                               self._est_rows_for(name, 1000, label))
                        for name, sch in self._schemas.items()},
                       dec_enabled=self._dec_as_int(),
                       unique_cols=dict(self._unique_cols),
                       late_mat=self.config.late_materialization,
                       late_mat_min_rows=self.config.late_mat_min_rows,
                       verify_plans=self.config.verify_plans,
                       stats_source=self.column_stats)

    def sql(self, query: str, backend: Optional[str] = None,  # lint: thread-entry (service clients call sql concurrently)
            label: Optional[str] = None) -> Table:
        """Run a query; backend "jax" (device) or "numpy" (host oracle).

        Defaults to the config's use_jax flag — the device path is the
        product path, the numpy path is the differential-validation oracle
        (the role CPU-Spark plays against GPU-Spark in the reference,
        nds/nds_validate.py).

        label: human-stable query name for observability (runners pass
        "query9" etc.); spans and per-program device-time attribution key
        on it. Defaults to a short content hash of the SQL text.

        Thread-safe: concurrent callers serialize on _sql_lock (whole
        statements are the unit). Note last_exec_stats* describe the last
        COMPLETED statement of ANY caller — concurrent callers wanting
        their own stats use service_run (result + stats atomically).

        ``system.*`` statements (obs/system_tables.py) route to the
        host-only introspection path WITHOUT taking the statement lock:
        an operator poll must answer while the device lane is mid-
        statement, and must never perturb the workload it measures. The
        disabled-path cost is this one substring branch.
        """
        if "system." in query or "SYSTEM." in query:
            result = self._maybe_system_query(query, label)
            if result is not None:
                return result
        with self._sql_lock:
            return self._sql_locked(query, backend, label)

    def abandon_inflight(self) -> None:
        """A deadline just ABANDONED a worker thread mid-statement
        (resilience.run_with_deadline: python threads cannot be killed).
        The zombie may still hold this session's statement/state locks —
        install fresh ones so the stream continues immediately instead of
        queueing behind the zombie's hang. The zombie then races the next
        statement exactly as it did before the locks existed (the
        documented containment posture: bounded by the hang, the caller
        already recorded the query Failed); runners that cannot accept
        that race should use process isolation (throughput process mode).
        """
        self._sql_lock = threading.RLock()
        self._lock = threading.RLock()

    def service_run(self, query: str, backend: Optional[str] = None,
                    label: Optional[str] = None, plan=None):
        """Query-service entry: like sql() but returns (Table, ExecStats)
        ATOMICALLY (per-query state isolation under multi-client entry —
        reading last_exec_stats after sql() returns races other clients),
        and accepts a pre-built plan from the service's planner stage so
        a first-sighting execution skips re-parsing/re-planning."""
        with self._sql_lock:
            # log_row=False: the SERVICE cuts the query-log row per ticket
            # (tenant/template/phase walls/error class), so the session
            # must not log a bare duplicate of the same statement
            table = self._sql_locked(query, backend, label, plan=plan,
                                     log_row=False)
            return table, self.last_exec_stats_typed

    # -- morsel-boundary preemption (service fair scheduler) ------------------
    def _maybe_preempt(self) -> None:
        """Yield point the streamed path calls between scan groups and
        between morsels: when the query service installed a preemption
        hook, hand the device lane over so short interactive tickets run
        NOW instead of convoying behind this scan's whole wall. No hook
        (the default) is one attribute read — the streamed loop stays
        bit-identical to before the hook existed. Never re-enters while a
        preempted statement is already running (depth <= 1)."""
        hook = self._preempt_hook
        if hook is not None and not self._in_preempt:
            hook()

    def preempt_scope(self):
        """Context manager the service wraps around a NESTED statement
        dispatched at a yield point: saves/restores every statement-scoped
        attribute ``_sql_locked`` writes (the outer streamed statement
        must resume exactly the view it had) plus the device-memory peak
        window, and arms ``_in_preempt`` so the nested statement cannot
        itself be preempted. The nested dispatch runs on the SAME thread
        that holds ``_sql_lock`` — the RLock re-entry is what makes the
        yield legal without unwinding the outer stream's state."""
        import contextlib

        @contextlib.contextmanager
        def _scope():
            from ..obs.profile import DEVICE_MEM
            saved = (self.last_fallbacks, self.last_exec_stats,
                     self.last_exec_stats_typed, self.last_profile,
                     self._last_stream_profile, self._active_label,
                     self._stmt_t0, self._stmt_log)
            win = DEVICE_MEM.window_peak()
            self._in_preempt = True
            try:
                yield self
            finally:
                self._in_preempt = False
                (self.last_fallbacks, self.last_exec_stats,
                 self.last_exec_stats_typed, self.last_profile,
                 self._last_stream_profile, self._active_label,
                 self._stmt_t0, self._stmt_log) = saved
                # restore the outer statement's peak window: the nested
                # statement re-marked it, and the outer stream's
                # mem_peak_bytes must cover its own whole wall
                DEVICE_MEM.restore_window(win)
        return _scope()

    def explain_analyze(self, query: str, backend: Optional[str] = None,
                        label: Optional[str] = None):
        """EXPLAIN ANALYZE: execute ``query`` in profiled mode and return
        its :class:`~nds_tpu.obs.profile.PlanProfile` — the annotated plan
        tree (per-node wall/rows/bytes with stable TypeName#k identities),
        the estimate-vs-actual cardinality audit, and the device-memory
        watermark block. The result Table rides on ``profile.table`` and
        is BIT-IDENTICAL to ``sql(query)``: in-core plans walk the same
        executor eagerly node by node (children memoized, so each node's
        wall is its own work), streamed plans run the unchanged morsel
        path and only read counters. One statement only; the standing
        flag is ``EngineConfig.profile_plans`` (``power --explain``)."""
        with self._sql_lock:
            prev = self.config.profile_plans
            self.config.profile_plans = True
            try:
                self._sql_locked(query, backend, label)
            finally:
                self.config.profile_plans = prev
            return self.last_profile

    # -- system tables (obs/system_tables.py) --------------------------------
    def _maybe_system_query(self, query: str,
                            label: Optional[str]) -> Optional[Table]:
        """Route a statement that mentions ``system.`` — returns the
        result Table when every referenced table is a system table, None
        when none is (caller proceeds on the normal path; the marker was
        a literal/comment), and raises on a mix: the host snapshot
        executor must never pull warehouse-scale user tables."""
        from ..obs import system_tables as _st
        ast = parse_sql(query)
        refs = _st.collect_table_refs(ast)
        sys_refs = {r for r in refs if _st.is_system_table(r)}
        if not sys_refs:
            return None
        if refs - sys_refs:
            raise ValueError(
                "system.* tables cannot join user tables "
                f"(statement references {sorted(refs - sys_refs)}); "
                "run the introspection query separately")
        return self._system_query_ast(ast, sys_refs, label)

    def system_query(self, query: str, label: Optional[str] = None
                     ) -> Table:
        """Run one ``system.*`` introspection statement on the HOST
        executor over atomic registry snapshots — no statement lock, no
        planner workers, no device dispatch, so it answers during
        overload, open circuits, and mid-statement device work without
        perturbing any of them. Raises when the statement touches a
        non-system table."""
        from ..obs import system_tables as _st
        ast = parse_sql(query)
        refs = _st.collect_table_refs(ast)
        bad = {r for r in refs if not _st.is_system_table(r)}
        if bad or not refs:
            raise ValueError(
                f"system_query serves system.* tables only (got "
                f"{sorted(refs) or 'no tables'})")
        return self._system_query_ast(ast, refs, label)

    def _system_query_ast(self, ast, refs: set,
                          label: Optional[str]) -> Table:
        """Plan against the dedicated system catalog and execute on the
        host backend over per-statement snapshots. Deliberately out of
        band: no QUERIES_RUN/last_exec_stats/query-log movement — an
        operator poll must not clobber a concurrent client's stats view
        or log itself into the surface it is reading."""
        from ..obs import system_tables as _st
        _metrics.SYSTEM_QUERIES.inc()
        with TRACER.span("system_query", label=label or "system"):
            catalog = Catalog(_st.catalog_entries(), dec_enabled=False,
                              late_mat=False, verify_plans="off")
            plan = Planner(catalog).plan_query(ast)
            # snapshots cut NOW, one per referenced table, each under its
            # own registry lock (atomic rows; see system_tables docstring)
            snaps = {name: _st.snapshot_engine_table(name, self)
                     for name in refs}

            def load(name, columns=None):
                t = snaps[name]
                if columns is None:
                    return t
                idx = {n: i for i, n in enumerate(t.names)}
                return Table(list(columns),
                             [t.columns[idx[c]] for c in columns])
            return Executor(load).execute(plan)

    def _sql_locked(self, query: str, backend: Optional[str],
                    label: Optional[str], plan=None,
                    log_row: bool = True) -> Table:
        import time as _time
        use_jax = (backend == "jax") if backend else self.config.use_jax
        self.last_fallbacks = []
        self.last_exec_stats = {}
        self.last_exec_stats_typed = None
        self._active_label = label or self._auto_label(query)
        # query-log context for _finish_exec_stats: statement wall start
        # + whether THIS statement cuts its own row (the service logs per
        # ticket instead — richer context, no duplicates)
        self._stmt_t0 = _time.perf_counter()
        self._stmt_log = log_row
        from ..obs.profile import DEVICE_MEM
        DEVICE_MEM.mark_window()   # per-query device-memory peak window
        _metrics.QUERIES_RUN.inc()
        if self.config.profile_plans and plan is None:
            return self._profiled_locked(query, use_jax)
        with TRACER.span("query", label=self._active_label,
                         backend="jax" if use_jax else "numpy"):
            if use_jax:
                from .jax_backend import to_host
                if self.config.out_of_core:
                    result = self._sql_streaming(query)
                    if result is not None:
                        return result
                jexec = self._jax_executor()
                jexec.query_label = self._active_label

                def factory():
                    if plan is not None:
                        return plan
                    with TRACER.span("plan", label=self._active_label):
                        with TRACER.span("parse"):
                            ast = parse_sql(query)
                        return Planner(self._catalog()).plan_query(ast)
                result = to_host(jexec.run_query(("sql", query), factory))
                self.last_fallbacks = list(jexec.fallback_nodes)
                # the REASON a query is not fully on-device (operator + why)
                # rides the stats so runners can enumerate the remaining
                # host/in-core queries per run without scraping status text
                self._finish_exec_stats(ExecStats.from_executor(
                    jexec.last_stats, self.last_fallbacks),
                    rows=result.num_rows)
                return result
            with TRACER.span("plan", label=self._active_label):
                if plan is None:
                    plan = Planner(self._catalog()).plan_query(
                        parse_sql(query))
            executor = Executor(self.load_table)
            return executor.execute(plan)

    @staticmethod
    def _auto_label(query: str) -> str:
        import hashlib
        return "q" + hashlib.sha1(query.encode()).hexdigest()[:8]

    # -- EXPLAIN ANALYZE (obs/profile.py) ------------------------------------
    def _profiled_locked(self, query: str, use_jax: bool) -> Table:
        """Profiled execution of one statement (config.profile_plans /
        explain_analyze): a streamable query runs the UNCHANGED morsel
        path (bit-identity by construction — profiling only reads the
        counters the stream already computes), everything else walks the
        plan eagerly node by node through the existing executor. Installs
        self.last_profile and returns the result Table."""
        import time as _time

        _metrics.PROFILED_QUERIES.inc()
        with TRACER.span("query", label=self._active_label,
                         backend="jax" if use_jax else "numpy",
                         profiled=True):
            if use_jax and self.config.out_of_core:
                t0 = _time.perf_counter()
                result = self._sql_streaming(query)
                if result is not None:
                    prof = self._stream_profile(
                        result, (_time.perf_counter() - t0) * 1000.0)
                    return self._finish_profile(prof, result)
            with TRACER.span("plan", label=self._active_label):
                plan = Planner(self._catalog()).plan_query(parse_sql(query))
            prof, result = self._profile_walk(plan, use_jax)
        return self._finish_profile(prof, result)

    def _finish_profile(self, prof, result: Table) -> Table:
        """Audit + memory block + metrics for a freshly built profile;
        installs it as last_profile."""
        from ..obs import profile as _prof

        prof.findings = _prof.cardinality_audit(
            prof, self.config.profile_misestimate_ratio)
        if prof.findings:
            _metrics.CARDINALITY_MISESTIMATES.inc(len(prof.findings))
        st = self.last_exec_stats_typed
        prof.memory = _prof.memory_block(
            int(self.config.scan_budget_gb * (1 << 30))
            if self.config.scan_budget_gb > 0 else None)
        if st is not None and st.mem_peak_bytes is not None:
            prof.memory["query_peak_bytes"] = st.mem_peak_bytes
        prof.table = result
        self.last_profile = prof
        return result

    def _profile_walk(self, plan, use_jax: bool):
        """The eager node-by-node profiled walk: children-first execution
        through the EXISTING executor, so each node's wall measures only
        its own work (children are memoized) and the root result is the
        same eager evaluation a first-sighting record pass performs —
        bit-identical to compiled replay by the engine's record/replay
        discipline. Per-node rows are exact (alive counts); bytes are the
        node's device (or host) output footprint."""
        import contextlib
        import time as _time

        from ..obs import profile as _prof
        from ..obs.stats import ExecStats

        labels, children, order = _prof.plan_tree(plan)
        ests = _prof.estimate_rows(
            plan, lambda t: self._est_rows.get(t))
        prof = _prof.PlanProfile(
            query=self._active_label,
            backend="jax" if use_jax else "numpy",
            mode="in-core" if use_jax else "numpy",
            root=labels[id(plan)])
        node_rows: dict = {}
        t_all = _time.perf_counter()
        if use_jax:
            import jax as _jax

            from .jax_backend import to_host
            from .jax_backend.device import device_bytes
            jexec = self._jax_executor()
            jexec.query_label = self._active_label
            jexec.fallback_nodes = []
            jexec._memo = {}
            ctx = _jax.default_device(jexec._eager_device) \
                if jexec._eager_device is not None \
                else contextlib.nullcontext()
            with ctx:
                for node in order:
                    t0 = _time.perf_counter()
                    out = jexec.execute(node)
                    _jax.block_until_ready(out)
                    # the alive-count sync is profiled-mode work this node
                    # caused: it stays inside the node's wall, so per-node
                    # walls sum to the profiled total (>= 90% acceptance)
                    rows = int(_jax.device_get(out.count()))
                    wall = (_time.perf_counter() - t0) * 1000.0
                    lbl = labels[id(node)]
                    node_rows[lbl] = rows
                    prof.nodes[lbl] = _prof.NodeStat(
                        label=lbl, op=type(node).__name__,
                        detail=_prof.node_detail(node),
                        est_rows=ests.get(id(node)), rows=rows,
                        wall_ms=round(wall, 3), bytes=device_bytes(out),
                        children=children.get(lbl, []))
            prof.total_ms = round((_time.perf_counter() - t_all) * 1000.0,
                                  3)
            result = to_host(out)
            self.last_fallbacks = list(jexec.fallback_nodes)
        else:
            executor = Executor(self.load_table)
            for node in order:
                t0 = _time.perf_counter()
                out = executor.execute(node)
                wall = (_time.perf_counter() - t0) * 1000.0
                lbl = labels[id(node)]
                node_rows[lbl] = out.num_rows
                prof.nodes[lbl] = _prof.NodeStat(
                    label=lbl, op=type(node).__name__,
                    detail=_prof.node_detail(node),
                    est_rows=ests.get(id(node)), rows=out.num_rows,
                    wall_ms=round(wall, 3),
                    bytes=sum(getattr(c.data, "nbytes", 0)
                              for c in out.columns),
                    children=children.get(lbl, []))
            result = out
        if not prof.total_ms:
            prof.total_ms = round((_time.perf_counter() - t_all) * 1000.0,
                                  3)
        stats = ExecStats(mode="profiled", node_stats=node_rows,
                          device_ms=round(prof.profiled_ms(), 3),
                          fallback_reasons=list(self.last_fallbacks))
        self._finish_exec_stats(stats)
        return prof, result

    def _stream_profile(self, result: Table, total_ms: float):
        """Build the streamed-execution profile from the counters the
        morsel path just recorded (_last_stream_profile): per-group walls
        land on the group's scan nodes, per-job merge/final walls on the
        original aggregate nodes, the finalize wall on the root. Row
        counts are exact (host-side morsel/partial/final counts); nodes
        the stream never materializes individually carry no wall."""
        from ..obs import profile as _prof

        rec = self._last_stream_profile or {}
        plan = rec.get("plan")
        prof = _prof.PlanProfile(query=self._active_label, backend="jax",
                                 mode="streaming", total_ms=round(
                                     total_ms, 3))
        if plan is None:
            return prof
        from .plan import ScanNode
        labels, children, order = _prof.plan_tree(plan)
        ests = _prof.estimate_rows(plan, lambda t: self._est_rows.get(t))
        prof.root = labels[id(plan)]
        group_rows = {g["table"]: g for g in rec.get("groups", ())}
        agg_stats = {aid: j for j in rec.get("jobs", ())
                     for aid in [j["agg_id"]]}
        walled: set[str] = set()   # group wall lands on ONE scan per table
        for node in order:
            lbl = labels[id(node)]
            ns = _prof.NodeStat(
                label=lbl, op=type(node).__name__,
                detail=_prof.node_detail(node),
                est_rows=ests.get(id(node)),
                children=children.get(lbl, []))
            if isinstance(node, ScanNode) and node.table in group_rows:
                g = group_rows[node.table]
                ns.rows = g["rows"]
                if node.table not in walled:
                    walled.add(node.table)
                    ns.wall_ms = g["wall_ms"]
                    ns.bytes = g.get("bytes")
            elif id(node) in agg_stats:
                j = agg_stats[id(node)]
                ns.rows = j["final_rows"]
                ns.wall_ms = j["wall_ms"]
            if id(node) == id(plan):
                ns.rows = result.num_rows
                ns.wall_ms = (ns.wall_ms or 0.0) + rec.get(
                    "finalize_ms", 0.0)
            prof.nodes[lbl] = ns
        return prof

    def _finish_exec_stats(self, stats: ExecStats,
                           rows: Optional[int] = None,
                           log: Optional[bool] = None) -> None:
        """THE single point where a query's execution stats land (both the
        in-core executor path and the streaming path build an ExecStats and
        come through here): installs the typed record, its backward-
        compatible dict view, rolls the run into the process-wide
        metrics registry, and — when the durable query log is enabled —
        flattens the record into one O(row) log row (``rows`` carries the
        result row count when the caller has it; ``log`` overrides the
        statement's log_row flag — the service passes False for its
        last-dispatch view and logs per ticket instead)."""
        from ..obs.profile import DEVICE_MEM
        # device-memory watermarks: the statement's peak window was opened
        # in _sql_locked; headroom is measured against the HBM scan budget
        stats.mem_peak_bytes = DEVICE_MEM.window_peak()
        stats.mem_live_bytes = DEVICE_MEM.live
        if self.config.scan_budget_gb > 0:
            stats.mem_headroom_bytes = \
                int(self.config.scan_budget_gb * (1 << 30)) - \
                stats.mem_peak_bytes
        if self.config.pallas_ops:
            from .jax_backend import pallas_kernels as _pk
            ops = sorted(_pk.parse_ops(self.config.pallas_ops))
            if self._device_mesh() is not None:
                # the GSPMD whole-plan mesh path still forces the XLA
                # lowering (kernels are not partitionable operands); the
                # sharded-MORSEL path (mesh_shards) runs them shard-local
                # inside shard_map, so only mesh_shape lands here
                stats.pallas_fallback_reason = "mesh"
            else:
                stats.pallas_ops = ops
                reason = _pk.fallback_reason()
                if reason:
                    # graceful degradation (one warning already logged by
                    # pallas_kernels): record WHY the XLA lowering served
                    stats.pallas_fallback_reason = reason
        self.last_exec_stats_typed = stats
        self.last_exec_stats = stats.to_dict()
        if self._feedback is not None:
            # every completed statement's per-node actuals feed the
            # template's profile (the query log records the same map, so
            # replay_log over a saved JSONL reconstructs this store)
            self._feedback.observe_nodes(self._active_label,
                                         stats.node_stats)
        from ..obs.query_log import QUERY_LOG
        if QUERY_LOG.enabled and \
                (self._stmt_log if log is None else log):
            import time as _time
            QUERY_LOG.record(
                stats, source="session", label=self._active_label,
                wall_ms=round((_time.perf_counter() - self._stmt_t0)
                              * 1000.0, 3) if self._stmt_t0 else None,
                rows=rows)
        if stats.fallback_reasons:
            _metrics.HOST_FALLBACKS.inc(len(stats.fallback_reasons))
        if stats.prefetch_error_details:
            _metrics.PREFETCH_ERRORS.inc(len(stats.prefetch_error_details))
        if stats.scan_passes:
            _metrics.SCAN_PASSES.inc(stats.scan_passes)
        if stats.morsels:
            _metrics.MORSELS.inc(stats.morsels)
        if stats.bytes_uploaded:
            _metrics.BYTES_UPLOADED.inc(stats.bytes_uploaded)
        if stats.host_decode_ms:
            # the staging-thread wall, registry-visible per process (the
            # per-table split stays in the stats record)
            _metrics.HOST_DECODE_MS.inc(
                round(sum(stats.host_decode_ms.values()), 3))

    def _stream_config_key(self) -> tuple:
        """Streaming-state cache validity fingerprint: the cached rewritten
        plans, scan groups, compiled morsel programs, and not-streamable
        sentinels are all functions of the catalog generation AND these
        config fields — toggling any of them on a live session (A/B runs,
        tests) must not replay a stale entry."""
        cfg = self.config
        return (self._generation, cfg.out_of_core_min_rows, cfg.chunk_rows,
                cfg.stream_compact_rows, cfg.shared_scan,
                cfg.stream_fusion_max_branches, cfg.late_materialization,
                cfg.late_mat_min_rows, cfg.decimal_physical, cfg.use_jax,
                cfg.narrow_lanes, cfg.encoded_exec, tuple(cfg.mesh_shape),
                int(cfg.mesh_shards or 0),
                tuple(sorted(cfg.pallas_ops)), bool(cfg.adaptive_plans))

    def _sql_streaming(self, query: str):  # lint: thread-entry (called under _sql_lock; stream-cache writes additionally take the state lock)
        """Out-of-core execution (generalized round 5, shared-scan round 7):
        every MAXIMAL streamable aggregate subtree in the plan — top-level,
        below joins, inside CTE bodies, scalar subqueries, with UNION ALL
        fact-channel branches — streams its big scan(s) through the device
        in chunk_rows morsels. All branches of a query that scan the SAME
        big table form one ScanGroup (streaming.plan_scan_groups): the
        union of their pruned column sets uploads once per morsel and each
        branch reads zero-copy views of the staged buffer, so q9-class
        plans with 15 scalar-subquery jobs over store_sales pay the scan +
        upload cost once instead of 15 times. Per-morsel partial aggregates
        merge on host (periodically compacted to bound memory for
        customer-grained groups), and a MaterializedNode replaces each
        aggregate subtree before the remaining (small) plan runs in-core.
        Reference analog: maxPartitionBytes chunked scans + shuffle spill,
        power_run_gpu.template. Returns None if nothing is streamable."""
        from . import streaming

        cfg_key = self._stream_config_key()
        with self._lock:
            if self._stream_cache_cfg != cfg_key:
                self._stream_cache = {}
                self._stream_cache_cfg = cfg_key
            sent = self._stream_cache.get(query, "miss")
        if sent is None:          # known not-streamable: skip the re-plan
            return None
        if sent != "miss" and self._feedback is not None and \
                sent.get("fb_stamp") != \
                self._feedback.stamp(self._active_label):
            # drift sentinel: the feedback store's profile generation for
            # this template moved since the cached streaming state was
            # built (new observations at bucket scale, or a drift
            # refresh) — replaying the stale schedule would either keep
            # the overprovision or trip ReplayMismatch per morsel.
            # Re-plan from the moved profile instead.
            _metrics.ADAPTIVE_REPLANS.inc()
            from ..obs.flight import FLIGHT
            FLIGHT.record("adaptive_replan", label=self._active_label,
                          reason="profile_generation")
            with self._lock:
                self._stream_cache.pop(query, None)
            sent = "miss"
        if sent == "miss":
            plan = Planner(self._catalog()).plan_query(parse_sql(query))
            jobs = streaming.find_streaming_jobs(
                plan, lambda t: self._est_rows_for(t, 0),
                self.config.out_of_core_min_rows)
            if not jobs:
                with self._lock:
                    self._stream_cache[query] = None
                return None
            groups = streaming.plan_scan_groups(jobs,
                                                self.config.shared_scan)
            if self.config.narrow_lanes:
                # choose each group's per-column upload lanes ONCE from
                # table-wide column stats: static for every morsel of the
                # pass (a per-morsel choice would be a width change =
                # recompile mid-stream), recorded on the morsel ScanNodes
                # so the verifier can prove them against the same stats
                from .jax_backend.device import (bucket, plan_encodings,
                                                 plan_lanes)
                for g in groups:
                    st = self.column_stats(g.table)
                    streaming.set_group_lanes(g, plan_lanes(
                        g.dtypes, [st.get(c) for c in g.columns]))
                    if not self.config.encoded_exec or g.lanes is None:
                        continue
                    # generalize lanes from width to ENCODING: dictionary
                    # codes / run-length pairs chosen once per group from
                    # cardinality/run stats, static like the lanes are
                    est = self.column_enc_stats(g.table, g.columns)
                    planned = plan_encodings(
                        g.dtypes, g.lanes, [est.get(c) for c in g.columns],
                        bucket(self.config.chunk_rows))
                    if planned is not None:
                        streaming.set_group_encodings(g, *planned)
            if self.config.verify_plans == "per-pass":
                # fused shared-scan partial plans are plan-IR rewrites that
                # never pass through planner.PassPipeline — verify them here
                streaming.verify_groups(groups, col_stats=self.column_stats,
                                        enc_stats=self.column_enc_stats)
            # ONE executor serves every group of every job: groups run
            # sequentially, and sharing the scan cache uploads each
            # dimension table once instead of per branch
            shared = self._new_stream_executor()
            sent = {"plan": plan, "jobs": jobs, "groups": groups,
                    "exec": shared,
                    "gstates": [{"cqs": None, "ents": None, "fused": False}
                                for _ in groups],
                    # profile generation this state was planned from: a
                    # later generation move invalidates it (drift sentinel)
                    "fb_stamp": self._feedback.stamp(self._active_label)
                    if self._feedback is not None else 0}
            with self._lock:
                self._stream_cache[query] = sent

        plan, jobs, groups = sent["plan"], sent["jobs"], sent["groups"]
        import time as _time

        from .jax_backend.device import decode_stats
        dec0 = decode_stats()
        # per-run profile collection (cheap: host counters the loop already
        # computes + one perf_counter pair per group/job) — feeds
        # ExecStats.node_stats on every streamed run and the full
        # PlanProfile under EXPLAIN ANALYZE (_stream_profile)
        stream_rec: dict = {"plan": plan, "groups": [], "jobs": [],
                            "finalize_ms": 0.0}
        self._last_stream_profile = stream_rec  # lint: lock-exempt (statement-scoped: written and read under _sql_lock)
        mapping: dict = {}
        total_morsels = 0
        re_records = 0
        bytes_uploaded = 0
        fused_groups = 0
        sharded_groups = 0
        shard_stats: dict = {}   # collective_bytes / collective_ms across groups
        morsels_per_table: dict[str, int] = {}
        host_decode_ms: dict[str, float] = {}
        enc_bytes_saved = 0
        prefetch_errs: list[str] = []
        from .plan import MaterializedNode
        partials: list[list] = [[] for _ in jobs]
        for ji, job in enumerate(jobs):
            for branch in job.branches:
                if branch.big_table is None:
                    # no big scan in this branch: one-shot in-core partial —
                    # on the DEVICE when the session runs jax (a just-under-
                    # threshold channel can still be tens of millions of
                    # rows; the host executor is the 1-core fallback)
                    partials[ji].append(arrow_bridge.to_arrow(
                        self._incore_partial(sent["exec"], branch)))
        for group, gstate in zip(groups, sent["gstates"]):
            sinks = [(jobs[ji], partials[ji]) for ji, _bi in group.members]
            # scan-group boundary: yield the device lane to preempting
            # tickets (no hook installed = one attribute read, no-op)
            self._maybe_preempt()
            g_t0 = _time.perf_counter()
            out = self._stream_group(group, sent["exec"], gstate, sinks,
                                     prefetch_errs, shard_stats)
            if out is None:
                with self._lock:
                    self._stream_cache[query] = None
                return None     # not device-runnable: in-core path
            morsels_run, rr, ub, sharded, host_ms, rows_streamed = out
            stream_rec["groups"].append({
                "table": group.table, "rows": rows_streamed, "bytes": ub,
                "wall_ms": round((_time.perf_counter() - g_t0) * 1000, 3)})
            total_morsels += morsels_run
            re_records += rr
            bytes_uploaded += ub
            fused_groups += 1 if gstate["fused"] else 0
            sharded_groups += 1 if sharded else 0
            morsels_per_table[group.table] = \
                morsels_per_table.get(group.table, 0) + morsels_run
            host_decode_ms[group.table] = round(
                host_decode_ms.get(group.table, 0.0) + host_ms, 3)
            if group.encodings is not None and group.plain_lanes is not None:
                from .jax_backend.device import (bucket, enc_lane_bytes,
                                                 lane_bytes)
                cap = bucket(self.config.chunk_rows)
                enc_bytes_saved += morsels_run * (
                    lane_bytes(group.plain_lanes, cap) -
                    enc_lane_bytes(group.lanes, cap, group.encodings))
        if self._feedback is not None:
            # exact rows streamed per big table: ground truth the next
            # sighting's catalog prefers over the static est_rows
            self._feedback.observe_tables(
                self._active_label,
                {g["table"]: g["rows"] for g in stream_rec["groups"]})
        for ji, job in enumerate(jobs):
            if not partials[ji]:
                with self._lock:
                    self._stream_cache[query] = None
                return None
            j_t0 = _time.perf_counter()
            with TRACER.span("merge.partials", job=ji,
                             parts=len(partials[ji])):
                merged_arrow = pa.concat_tables(partials[ji],
                                                promote_options="permissive")
                merged = arrow_bridge.from_arrow(merged_arrow,
                                                 self._dec_as_int())
                mat = MaterializedNode(table=merged,
                                       label="streamed-partials",
                                       out_names=list(job.partial_names),
                                       out_dtypes=list(job.partial_dtypes))
                final_sub = job.build_final(mat)
                sub_res = Executor(self.load_table).execute(final_sub)
            stream_rec["jobs"].append({
                "agg_id": id(job.agg), "partial_rows": merged.num_rows,
                "final_rows": sub_res.num_rows,
                "wall_ms": round((_time.perf_counter() - j_t0) * 1000, 3)})
            mat_node = MaterializedNode(
                table=sub_res, label="streamed-agg",
                out_names=list(job.agg.out_names),
                out_dtypes=list(job.agg.out_dtypes))
            if job.join_patch is not None:
                # semi/anti build side: probe the materialized key set
                from .plan import BCol
                keys = [BCol(job.agg.out_dtypes[i], i, job.agg.out_names[i])
                        for i in range(len(job.join_patch.right_keys))]
                mapping[id(job.join_patch)] = {"right": mat_node,
                                               "right_keys": keys}
            else:
                mapping[id(job.agg)] = mat_node
        final_plan = streaming.substitute_nodes(plan, mapping)
        f_t0 = _time.perf_counter()
        with TRACER.span("finalize", label=self._active_label,
                         jobs=len(jobs)):
            result = Executor(self.load_table).execute(final_plan)
        stream_rec["finalize_ms"] = round(
            (_time.perf_counter() - f_t0) * 1000, 3)
        # scan_passes counts morsel loops (== tables_streamed when
        # shared_scan serves every branch from one pass; == branches_served
        # per-branch without it); lane_spec records which physical lane each
        # streamed column rode (bytes_uploaded measures the win); EVERY
        # prefetch failure is recorded — they degrade to synchronous staging,
        # correct but slower, so the degradation must be observable
        dec1 = decode_stats()
        self._finish_exec_stats(ExecStats.streaming(
            jobs=len(jobs),
            morsels=total_morsels,
            morsel_rows=self.config.chunk_rows,
            re_records=re_records,
            shared_scan=bool(self.config.shared_scan),
            scan_passes=len(groups),
            tables_streamed=len(morsels_per_table),
            branches_served=sum(len(g.members) for g in groups),
            fused_groups=fused_groups,
            bytes_uploaded=bytes_uploaded,
            morsels_per_table=morsels_per_table,
            narrow_lanes=bool(self.config.narrow_lanes),
            lane_spec={g.table: dict(zip(g.columns, g.lanes))
                       for g in groups if g.lanes is not None},
            encoded_exec=bool(self.config.encoded_exec
                              and self.config.narrow_lanes),
            enc_spec={g.table: dict(zip(g.columns, [_enc_tag(e) for e in
                                                    g.encodings]))
                      for g in groups if g.encodings is not None} or None,
            enc_bytes_saved=enc_bytes_saved or None,
            decode_sites=dec1["sites"] - dec0["sites"],
            decode_rows=dec1["rows"] - dec0["rows"],
            host_decode_ms=host_decode_ms,
            mesh_shards=self._morsel_shards() if sharded_groups else None,
            sharded_groups=sharded_groups or None,
            collective_bytes=shard_stats.get("collective_bytes"),
            collective_ms=shard_stats.get("collective_ms"),
            node_stats=self._stream_node_stats(plan, stream_rec, result),
            prefetch_error_details=prefetch_errs,
            fallbacks=self.last_fallbacks), rows=result.num_rows)
        return result

    def _stream_node_stats(self, plan, rec: dict, result: Table) -> dict:
        """{TypeName#k: actual rows} a streamed run records for free —
        rows streamed per big scan, final group counts per streamed
        aggregate, result rows at the root. Labels are verify.node_labels
        over the session's plan, the same identities profiles and
        verifier findings use (obs/profile.plan_tree)."""
        from .plan import ScanNode, iter_plan_nodes
        from .verify import node_labels
        labels = node_labels(plan)
        rows_by_table = {g["table"]: g["rows"] for g in rec["groups"]}
        out: dict = {}
        for n in iter_plan_nodes(plan):
            if isinstance(n, ScanNode) and n.table in rows_by_table:
                out[labels[id(n)]] = rows_by_table[n.table]
        for j in rec["jobs"]:
            lbl = labels.get(j["agg_id"])
            if lbl is not None:     # synthesized semi-join aggs are not
                out[lbl] = j["final_rows"]   # nodes of the session plan
        out[labels[id(plan)]] = result.num_rows
        return out

    def _new_stream_executor(self) -> dict:
        """One JaxExecutor (+ morsel slot) shared by every streamed branch
        of a query; kept across repeated executions."""
        from . import streaming
        from .jax_backend import JaxExecutor

        current: dict = {}

        def load(name, columns=None):
            if name == streaming.MORSEL_TABLE:
                t = current["table"]
                return t.select(list(columns)) if columns else t
            return self.load_table(name, columns)

        cfg = self.config
        jexec = JaxExecutor(
            load, jit_plans=True, mesh=self._device_mesh(),
            shard_min_rows=cfg.shard_min_rows,
            segment_plan_nodes=cfg.segment_plan_nodes,
            segment_min_cte_nodes=cfg.segment_min_cte_nodes,
            segment_cache_entries=cfg.segment_cache_entries,
            scan_budget_bytes=int(cfg.scan_budget_gb * (1 << 30)),
            pallas_ops=cfg.pallas_ops)
        return {"jexec": jexec, "current": current}

    def _incore_partial(self, shared: dict, branch):
        """One-shot partial aggregate for a branch without a big scan."""
        if not self.config.use_jax:
            return Executor(self.load_table).execute(branch.partial_plan)
        from .jax_backend import to_host
        from .jax_backend.executor import _plan_fingerprint
        jexec = shared["jexec"]
        key = ("stream-incore", _plan_fingerprint(branch.partial_plan))
        out = jexec.run_query(key, lambda: branch.partial_plan)
        return to_host(out)

    def _combine_partials(self, job, partials: list) -> "pa.Table":
        """Re-aggregate accumulated partial tables into one (partial-schema
        preserving; associative, so repeatable)."""
        from .plan import MaterializedNode
        merged_arrow = pa.concat_tables(partials,
                                        promote_options="permissive")
        merged = arrow_bridge.from_arrow(merged_arrow, self._dec_as_int())
        mat = MaterializedNode(table=merged, label="stream-compact",
                               out_names=list(job.partial_names),
                               out_dtypes=list(job.partial_dtypes))
        out = Executor(self.load_table).execute(job.build_combine(mat))
        return arrow_bridge.to_arrow(out)

    def _stream_group(self, group, shared: dict, state: dict,
                      sinks: list, prefetch_errs: list,
                      shard_stats: Optional[dict] = None):
        """Morsel loop for one shared-scan group: ONE morsel iterator and
        ONE double-buffered upload per morsel serve EVERY member branch (a
        worker thread packs + stages morsel i+1 while the device runs
        morsel i — the tunnel charges a fixed RTT per transfer, so overlap
        is the lever SF100 q3 was missing). Member partial programs read
        zero-copy views of the staged union buffer; a group within the
        fusion budget runs as ONE multi-output program per morsel (one
        dispatch RTT for all members, streaming.fuse_group + multi-plan
        CompiledQuery), larger groups run per-member programs over the
        same buffer. `sinks[i]` is (job, partials_list) for member i:
        per-morsel partial arrow tables append there, compacting IN the
        loop whenever a job's accumulated rows outgrow stream_compact_rows
        (q4-class customer-grained groups at SF100 would otherwise peak
        host memory before any compaction ran). Worker-thread staging
        failures are recorded into `prefetch_errs` (the morsel restages
        synchronously — a silent degradation otherwise, ADVICE r5).
        With mesh_shards > 1 the group dispatches SHARDED: the staged
        morsel upload lands row-sharded over the replica mesh (one
        device_put of per-replica packed payload blocks), every replica
        replays the same recorded per-morsel schedule on its rows inside
        shard_map, and one all_gather moves the bounded decomposed
        partials before the unchanged host merge
        (jax_backend/shard_exec.ShardedMorselQuery). Returns (morsels,
        re_records, bytes_uploaded, sharded, host_decode_ms, rows_streamed)
        or None when some member is not device-runnable."""
        import threading

        from . import streaming
        from .jax_backend import to_host
        from .jax_backend.device import (bucket, device_bytes, free_dtable,
                                         pack_table, to_device)
        from .jax_backend.executor import CompiledQuery, ReplayMismatch

        morsel_rows = self.config.chunk_rows
        cap = bucket(morsel_rows)
        n_shards = self._morsel_shards()
        mesh = self._morsel_mesh() if n_shards else None
        shard_cap = streaming.shard_capacity(morsel_rows, n_shards) \
            if mesh is not None else None
        jexec, current = shared["jexec"], shared["current"]
        mkey = group.morsel_key
        morsels = self.iter_morsels(group.table, group.columns, morsel_rows)
        fuse_max = self.config.stream_fusion_max_branches
        fuse = len(group.plans) > 1 and \
            (fuse_max <= 0 or len(group.plans) <= fuse_max)
        re_records = 0
        count = 0
        bytes_uploaded = 0
        rows_streamed = 0

        adaptive = self._feedback is not None and mesh is None

        def adapt(decisions_raw, member: int):
            """One member's replay schedule: morsel-bound inflation, or —
            when the feedback store holds a structurally matching profile
            for this (template, table, member) — observed maxima instead
            (streaming.adapt_schedule; a ceiling hint, ReplayMismatch
            catches under-observation). Also seeds the per-decision
            observation row from the record pass's RAW actuals."""
            kinds = [k for k, _v in decisions_raw]
            if not adaptive:
                return streaming.inflate_schedule(decisions_raw,
                                                  morsel_rows), kinds
            state.setdefault("kinds", {})[member] = kinds
            obs_row = [int(v) for _k, v in decisions_raw]
            prev = state.setdefault("obs", {}).get(member)
            if prev is not None and len(prev) == len(obs_row):
                obs_row = [max(a, b) for a, b in zip(prev, obs_row)]
            state["obs"][member] = obs_row
            caps = self._feedback.member_caps(
                self._active_label, group.table, member, kinds,
                morsel_rows, fuse, 0)
            adapted = streaming.adapt_schedule(decisions_raw, morsel_rows,
                                               caps)
            if caps is not None:
                state["adapted"] = True
                before = after = 0
                for (k, v), (_k2, a) in zip(
                        streaming.inflate_schedule(decisions_raw,
                                                   morsel_rows), adapted):
                    if k == "cap":
                        before += bucket(max(int(v), 1))
                        after += bucket(max(int(a), 1))
                _metrics.FEEDBACK_HITS.inc()
                from ..obs.flight import FLIGHT
                FLIGHT.record("feedback_hit", label=self._active_label,
                              table=group.table, member=member,
                              cells_before=before, cells_after=after)
                self._feedback.note_applied(self._active_label, before,
                                            after)
            return adapted, kinds

        def record_first(morsel) -> bool:
            if mesh is not None:
                return record_first_sharded(morsel)
            current["table"] = morsel
            jexec.fallback_nodes = []
            if fuse:
                _outs, decisions, scan_keys = jexec.record_plans(group.plans)
                if jexec.fallback_nodes:
                    return False
                decisions, _kinds = adapt(decisions, 0)
                state["cqs"] = [CompiledQuery(
                    list(group.plans), decisions, scan_keys,
                    mesh=jexec._mesh,
                    shard_min_rows=jexec._shard_min_rows,
                    label=f"{self._active_label}/morsel:{group.table}",
                    pallas_ops=jexec._pallas_ops)]
                state["ents"] = [{"scan_keys": scan_keys}]
            else:
                # fusion over budget (or single member): per-member
                # programs, each with its own schedule, all resolving the
                # shared staged buffer through the same morsel scan key
                cqs, ents = [], []
                for bi, p in enumerate(group.plans):
                    _out, decisions, scan_keys = jexec.record_plan(p)
                    if jexec.fallback_nodes:
                        return False
                    decisions, _kinds = adapt(decisions, bi)
                    cqs.append(CompiledQuery(
                        p, decisions, scan_keys, mesh=jexec._mesh,
                        shard_min_rows=jexec._shard_min_rows,
                        label=f"{self._active_label}/morsel:"
                              f"{group.table}#{bi}",
                        pallas_ops=jexec._pallas_ops))
                    ents.append({"scan_keys": scan_keys})
                state["cqs"], state["ents"] = cqs, ents
            state["fused"] = fuse
            return True

        def record_first_sharded(morsel) -> bool:
            """Record the per-REPLICA schedule on a representative shard-
            sized slice of the first morsel (shard-local gates: no data-
            dependent tier probes, so later replicas/morsels verify against
            capacity bounds only) and build the shard_map-dispatched
            ShardedMorselQuery program(s)."""
            from .jax_backend.shard_exec import ShardedMorselQuery
            spans = streaming.partition_morsel_rows(morsel.num_rows,
                                                    n_shards)
            current["table"] = morsel.slice(0, spans[0][1])
            jexec.fallback_nodes = []
            ops = jexec._pallas_ops
            if fuse:
                _o, decisions, scan_keys = jexec.record_plans(
                    group.plans, shard_local=True)
                if jexec.fallback_nodes:
                    return False
                decisions = streaming.inflate_schedule(decisions, shard_cap)
                state["cqs"] = [ShardedMorselQuery(
                    list(group.plans), decisions, scan_keys, mesh, mkey,
                    label=f"{self._active_label}/morsel:{group.table}",
                    pallas_ops=ops)]
                state["ents"] = [{"scan_keys": scan_keys}]
            else:
                cqs, ents = [], []
                for bi, p in enumerate(group.plans):
                    _o, decisions, scan_keys = jexec.record_plan(
                        p, shard_local=True)
                    if jexec.fallback_nodes:
                        return False
                    decisions = streaming.inflate_schedule(decisions,
                                                           shard_cap)
                    cqs.append(ShardedMorselQuery(
                        p, decisions, scan_keys, mesh, mkey,
                        label=f"{self._active_label}/morsel:"
                              f"{group.table}#{bi}",
                        pallas_ops=ops))
                    ents.append({"scan_keys": scan_keys})
                state["cqs"], state["ents"] = cqs, ents
            state["fused"] = fuse
            return True

        def stage(morsel):
            """Pack + upload one union-column morsel into a fresh buffer
            (group.lanes = the static narrow-lane spec, group.encodings =
            the static dict/rle encoding spec; None = legacy layouts under
            --no_narrow_lanes / --no_encoded_exec). Sharded mode uploads
            the same payload row-sharded over the replica mesh instead."""
            if mesh is not None:
                from .jax_backend.shard_exec import stage_sharded
                sub = morsel.select(group.columns)
                return stage_sharded(sub, mesh, shard_cap,
                                     lanes=group.lanes,
                                     encs=group.encodings,
                                     codebooks=group.codebooks)
            with TRACER.span("morsel.stage", cat="upload",
                             table=group.table, rows=morsel.num_rows):
                sub = morsel.select(group.columns)
                packed = pack_table(sub, capacity=cap, lanes=group.lanes,
                                    encs=group.encodings,
                                    codebooks=group.codebooks)
                return packed if packed is not None else \
                    to_device(sub, capacity=cap)

        def merge_obs(member: int, actuals) -> None:
            """Elementwise max-merge one replay/record pass's per-decision
            actuals into the group's observation rows."""
            if not adaptive or actuals is None:
                return
            row = [int(a) for a in actuals]
            prev = state.setdefault("obs", {}).get(member)
            if prev is not None and len(prev) == len(row):
                row = [max(a, b) for a, b in zip(prev, row)]
            state["obs"][member] = row

        def run_one(member: int, cq, ent):
            """One member dispatch; under adaptation the pre-seeded
            decision_rows sentinel pulls the replay's raw check scalars
            back out (the per-decision actuals the feedback store merges)."""
            if not adaptive:
                return cq.run(jexec._scans_for(ent))
            st = {"decision_rows": None}
            out = cq.run(jexec._scans_for(ent), stats=st)
            merge_obs(member, st.get("decision_rows"))
            return out

        def run_members():
            """Every member program against the staged buffer: one fused
            dispatch, or per-member dispatches. Returns member outputs in
            group.plans order."""
            nonlocal re_records
            try:
                if mesh is not None:
                    if state["fused"]:
                        return list(state["cqs"][0].run(
                            jexec._scans_for(state["ents"][0]),
                            stats=shard_stats))
                    return [cq.run(jexec._scans_for(ent), stats=shard_stats)
                            for cq, ent in zip(state["cqs"], state["ents"])]
                if state["fused"]:
                    return list(run_one(0, state["cqs"][0],
                                        state["ents"][0]))
                return [run_one(bi, cq, ent)
                        for bi, (cq, ent) in enumerate(zip(state["cqs"],
                                                           state["ents"]))]
            except ReplayMismatch:
                # a morsel genuinely exceeded the schedule (the inflated
                # bound, or an adapted ceiling hint a grown actual
                # overflowed): run it eagerly after evicting stale
                # record-side buffers — correctness never depends on the
                # hint. The fresh record pass's actuals feed the store so
                # the next sighting provisions for what was seen.
                free_dtable(jexec._scan_cache_rec.pop(mkey, None))
                re_records += 1
                if adaptive and state.get("adapted"):
                    _metrics.ADAPTIVE_REPLANS.inc()
                    from ..obs.flight import FLIGHT
                    FLIGHT.record("adaptive_replan",
                                  label=self._active_label,
                                  table=group.table,
                                  reason="schedule_overflow")
                if state["fused"]:
                    outs, d2, _ = jexec.record_plans(group.plans)
                    if adaptive:
                        state.setdefault("kinds", {})[0] = \
                            [k for k, _v in d2]
                    merge_obs(0, [int(v) for _k, v in d2])
                    return outs
                outs = []
                for bi, p in enumerate(group.plans):
                    out, d2, _ = jexec.record_plan(p)
                    if adaptive:
                        state.setdefault("kinds", {})[bi] = \
                            [k for k, _v in d2]
                    merge_obs(bi, [int(v) for _k, v in d2])
                    outs.append(out)
                return outs

        staged = {}
        stage_thread = None
        host_ms = 0.0

        def pull(it):
            """Next morsel, with the host-side Arrow->engine decode wall
            (IO + dictionary/validity materialization, arrow_bridge.
            from_arrow inside iter_morsels) accounted per table — the
            staging-thread bottleneck encoded execution is shrinking must
            be measurable (ExecStats.host_decode_ms)."""
            nonlocal host_ms
            import time as _time
            t0 = _time.perf_counter()
            m = next(it, None)
            host_ms += (_time.perf_counter() - t0) * 1000.0
            return m

        try:
            it = iter(morsels)
            morsel = pull(it)
            while morsel is not None:
                # morsel boundary: the stage thread is joined and the
                # previous morsel's partials are on the host — yield the
                # device lane to preempting tickets before the next run
                self._maybe_preempt()
                if state["cqs"] is None and not record_first(morsel):
                    return None
                if "buf" in staged:
                    buf = staged.pop("buf")
                else:
                    err = staged.pop("err", None)
                    if err is not None:
                        prefetch_errs.append(
                            f"{type(err).__name__}: {err}")
                    buf = stage(morsel)
                nxt = pull(it)
                if nxt is not None:
                    # stage the NEXT morsel concurrently with this run
                    def work(m=nxt):
                        try:
                            staged["buf"] = stage(m)
                        except BaseException as e:  # surfaced via prefetch_errs
                            staged["err"] = e
                    stage_thread = threading.Thread(target=work, daemon=True)
                    stage_thread.start()
                buf_bytes = device_bytes(buf)
                bytes_uploaded += buf_bytes
                prev = jexec._scan_cache.get(mkey)
                jexec._scan_cache[mkey] = buf
                current["table"] = morsel
                with TRACER.span("morsel.exec", cat="device",
                                 table=group.table, morsel=count,
                                 rows=morsel.num_rows, bytes=buf_bytes):
                    outs = run_members()
                free_dtable(prev)
                for (job, plist), out in zip(sinks, outs):
                    plist.append(arrow_bridge.to_arrow(to_host(out)))
                    if sum(p.num_rows for p in plist) > \
                            self.config.stream_compact_rows:
                        plist[:] = [self._combine_partials(job, plist)]
                count += 1
                rows_streamed += morsel.num_rows
                if stage_thread is not None:
                    stage_thread.join()
                    stage_thread = None
                morsel = nxt
        finally:
            # free every morsel-sized buffer even on a mid-stream failure
            # (device OOM on the next query otherwise): the current buffer,
            # the record-side copy, the host morsel reference, and whatever
            # the staging thread uploaded
            if stage_thread is not None:
                stage_thread.join()
            free_dtable(staged.pop("buf", None))
            free_dtable(jexec._scan_cache.pop(mkey, None))
            free_dtable(jexec._scan_cache_rec.pop(mkey, None))
            current.pop("table", None)
        if count == 0:
            return None   # empty source: the in-core path handles it
        if adaptive and state.get("obs"):
            # the group's observed schedule profile: per-member per-
            # decision maxima across every morsel of this pass (record
            # actuals + replay check scalars), keyed on the program
            # structure so only a like-for-like sighting consumes it
            members = sorted(state["obs"])
            self._feedback.observe_group(
                self._active_label, group.table, bound=morsel_rows,
                fused=state["fused"], shards=0,
                kinds=[state["kinds"][m] for m in members],
                caps=[state["obs"][m] for m in members])
        return (count, re_records, bytes_uploaded, mesh is not None,
                host_ms, rows_streamed)

    def sql_arrow(self, query: str) -> pa.Table:
        return arrow_bridge.to_arrow(self.sql(query))

    # -- statements (DML/DDL for the maintenance test) -----------------------
    def attach_warehouse(self, warehouse,
                         at_version: Optional[int] = None) -> None:
        """Bind a Warehouse so INSERT/DELETE statements commit snapshots
        (the reference runs these against Iceberg/Delta catalogs,
        nds_maintenance.py:107-116). With a published snapshot log the
        registrations pin to ONE warehouse version; ``at_version`` time-
        travels the whole warehouse to an older published version
        (``AS OF``-style reads — the rollback machinery generalized to
        warehouse level, read-only: no new snapshot is committed)."""
        self.warehouse = warehouse
        warehouse.register_all(self, at_version=at_version)

    def refresh_warehouse(self) -> None:
        """Advance a snapshot-pinned reader to the latest PUBLISHED
        warehouse version. Serialized on the statement lock, so an
        in-flight statement finishes against the snapshot it pinned and
        the next statement resolves against the new one."""
        if self.warehouse is None:
            return
        with self._sql_lock:
            self.warehouse.register_all(self)

    def execute(self, sql_text: str, backend: Optional[str] = None):
        """Execute one or more ';'-separated statements; returns the last
        query's Table (or None for pure DML). Serialized on _sql_lock like
        sql() — statements are the unit of the concurrency contract."""
        with self._sql_lock:
            return self._execute_locked(sql_text, backend)

    def _execute_locked(self, sql_text: str, backend: Optional[str]):
        from ..sql import parse_statements
        from ..sql.ast_nodes import CreateView, Delete, DropView, Insert, Query

        result = None
        for stmt in parse_statements(sql_text):
            if isinstance(stmt, Query):
                result = self._run_query_ast(stmt, backend)
            elif isinstance(stmt, CreateView):
                table = self._run_query_ast(stmt.query, backend)
                self.register_view(stmt.name, table)
            elif isinstance(stmt, DropView):
                self.drop(stmt.name)
            elif isinstance(stmt, Insert):
                self._insert(stmt, backend)
            elif isinstance(stmt, Delete):
                self._delete(stmt, backend)
            else:
                raise TypeError(type(stmt).__name__)
        return result

    def _run_query_ast(self, ast, backend: Optional[str]):
        planner = Planner(self._catalog())
        plan = planner.plan_query(ast)
        use_jax = (backend == "jax") if backend else self.config.use_jax
        if use_jax:
            from .jax_backend import to_host
            jexec = self._jax_executor()
            # one-shot statements (DML bodies, view definitions) skip the
            # compiled-plan cache: key=None runs the recorded eager path
            out = to_host(jexec.run_query(None, lambda: plan))
            self.last_fallbacks = list(jexec.fallback_nodes)
            return out
        return Executor(self.load_table).execute(plan)

    def _insert(self, stmt, backend: Optional[str]) -> None:
        if self.warehouse is None:
            raise RuntimeError("INSERT requires an attached warehouse")
        rows = self._run_query_ast(stmt.query, backend)
        target_names, _ = self._schemas[stmt.table]
        data = arrow_bridge.to_arrow(rows).rename_columns(target_names)
        self.warehouse.table(stmt.table).insert(data)
        self.warehouse.register_all(self)  # refresh snapshot binding
        # LF_* delta publication: the inserted rows ARE the delta —
        # subscribers (result-cache IVM) merge per-group partials from
        # them instead of recomputing the warm dashboards they feed
        self._publish_table_delta(stmt.table, inserts=data)

    def _delete(self, stmt, backend: Optional[str]) -> None:
        """DELETE FROM <table> WHERE <pred>: rewrite warehouse files keeping
        rows that do NOT satisfy the predicate (NULL predicate => kept,
        standard SQL DELETE semantics). Subqueries in the predicate see the
        session's other registered tables."""
        if self.warehouse is None:
            raise RuntimeError("DELETE requires an attached warehouse")
        import numpy as np

        from ..sql import parse_sql

        wt = self.warehouse.table(stmt.table)
        # DF_* delta publication: wrap the keep filter so the rows each
        # batch DROPS are captured as the statement's delete delta
        # (subscribers recompute only delta-touched groups); capture only
        # when someone is listening — the rows are otherwise dead weight
        deleted_parts: list = []

        def capture_deletes(t: pa.Table, keep):
            if self._delta_subscribers:
                import pyarrow.compute as pc
                dropped = t.filter(pc.invert(pa.array(keep,
                                                      type=pa.bool_())))
                if dropped.num_rows:
                    deleted_parts.append(dropped)
            return keep

        def publish_deletes():
            if deleted_parts:
                self._publish_table_delta(
                    stmt.table,
                    deletes=pa.concat_tables(deleted_parts,
                                             promote_options="permissive"))

        if stmt.where is None:
            wt.delete_where(lambda t: capture_deletes(
                t, pa.array([False] * t.num_rows)))
            self.warehouse.register_all(self)
            publish_deletes()
            return

        def _references_target(node) -> bool:
            """Does the WHERE reference the target table (via a subquery)?
            Batched evaluation would then see only a slice of the table and
            compute the subquery wrongly — force one whole-table batch."""
            import dataclasses as _dc

            from ..sql import ast_nodes as A
            stack = [node]
            while stack:
                x = stack.pop()
                if isinstance(x, A.TableRef) and x.name == stmt.table:
                    return True
                if _dc.is_dataclass(x):
                    stack.extend(getattr(x, f.name) for f in _dc.fields(x))
                elif isinstance(x, (list, tuple)):
                    stack.extend(x)
            return False

        batch_rows = (2 ** 62 if _references_target(stmt.where)
                      else 4_000_000)
        part_prune = self._partition_prune(stmt.table, stmt.where,
                                           _references_target)

        def keep_filter(t: pa.Table):
            # per-file scoped session: the target table IS this file's rows,
            # extended with a rowid so the engine tells us which rows matched
            tmp = Session(self.config)
            for other in self._schemas:
                if other == stmt.table:
                    continue
                tmp._schemas[other] = self._schemas[other]
                tmp._loaders[other] = self._loaders[other]
                tmp._est_rows[other] = self._est_rows.get(other, 1000)
            with_id = t.append_column(
                "__rowid", pa.array(np.arange(t.num_rows, dtype=np.int64)))
            tmp.register_arrow(stmt.table, with_id)
            q = parse_sql(f"SELECT __rowid FROM {stmt.table}")
            q.body.where = stmt.where
            hit = tmp._run_query_ast(q, backend="numpy")
            deleted = np.zeros(t.num_rows, dtype=bool)
            ids = np.asarray(hit.columns[0].data, dtype=np.int64)
            deleted[ids[hit.columns[0].validity]] = True
            return capture_deletes(t, pa.array(~deleted))

        # skip the (subquery-evaluating) stats analysis entirely when the
        # warehouse predates file stats — nothing could prune
        stats_prune = self._stats_prune(
            stmt.table, stmt.where, _references_target) \
            if wt.file_stats() else None
        wt.delete_where(keep_filter, batch_rows=batch_rows,
                        part_prune=part_prune, stats_prune=stats_prune)
        self.warehouse.register_all(self)
        publish_deletes()

    def _stats_prune(self, table: str, where, _references_target):
        """File-stats pruning rule for a DELETE: if some AND-conjunct is
        `col IN (subquery|list)` over a stats-tracked integer column
        (ticket/order numbers), files whose recorded [min, max] for that
        column contains NONE of the values provably hold no deletable
        rows. Returns callable(stats dict|None) -> process?, or None.
        The DF_* ticket-number deletes cannot date-prune — per-file column
        metrics are the reference's remaining Iceberg lever
        (nds/nds_maintenance.py:146-185)."""
        import numpy as np

        from ..sql import ast_nodes as A
        from ..warehouse import TABLE_PARTITIONING

        if where is None or _references_target(where):
            return None
        part_col = TABLE_PARTITIONING.get(table)

        for c in _and_conjuncts(where):
            col = None
            values = None
            if isinstance(c, A.InSubquery) and not c.negated and \
                    isinstance(c.expr, A.ColumnRef):
                col = c.expr.name
                if col == part_col:
                    continue        # partition pruning already covers it
                out = self._run_query_ast(c.query, backend="numpy")
                oc = out.columns[0]
                vals = np.asarray(oc.data)
                if oc.validity is not None:
                    vals = vals[oc.validity]
                values = vals
            elif isinstance(c, A.InList) and not c.negated and \
                    isinstance(c.expr, A.ColumnRef) and \
                    all(isinstance(i, A.Literal) and
                        isinstance(i.value, int) for i in c.items):
                col = c.expr.name
                if col == part_col:
                    continue
                values = np.asarray([i.value for i in c.items])
            if col is None or values is None:
                continue
            if not np.issubdtype(values.dtype, np.integer):
                continue
            svals = np.sort(values)

            def prune(st, col=col, svals=svals):
                if st is None or col not in st:
                    return True          # no stats: must process
                mn, mx = st[col]
                lo = np.searchsorted(svals, mn, side="left")
                hi = np.searchsorted(svals, mx, side="right")
                return bool(hi > lo)     # some value inside [mn, mx]
            return prune
        return None

    def _partition_prune(self, table: str, where, _references_target):
        """File-level pruning rule for a DELETE over a partitioned fact
        table: if some AND-conjunct of the predicate constrains the
        partition key to a computable value set/range, files of other
        partition values provably hold no deletable rows (a false/NULL
        conjunct makes the whole predicate non-TRUE). Returns
        callable(part_val_str) -> process?, or None when no conjunct is
        prunable. The DF_* refresh deletes are `key IN (SELECT d_date_sk
        ...)` — the date-partitioned layout makes them metadata-pruned like
        the reference's Iceberg deletes (nds/nds_maintenance.py:146-185)."""
        import numpy as np

        from ..sql import ast_nodes as A
        from ..warehouse import TABLE_PARTITIONING

        part_col = TABLE_PARTITIONING.get(table)
        if part_col is None or where is None:
            return None
        if _references_target(where):
            # keep_filter's whole-table-batch invariant: a self-referencing
            # subquery anywhere in the predicate must see EVERY file, so no
            # conjunct may prune the read set
            return None

        def is_part_col(e) -> bool:
            return isinstance(e, A.ColumnRef) and e.name == part_col

        def lit(e):
            return e.value if isinstance(e, A.Literal) else None

        for c in _and_conjuncts(where):
            if isinstance(c, A.InSubquery) and not c.negated and \
                    is_part_col(c.expr):
                # evaluate ONCE in this session, where the full target
                # table is still registered (uncorrelated per-file)
                out = self._run_query_ast(c.query, backend="numpy")
                col = out.columns[0]
                vals = np.asarray(col.data)[col.validity] \
                    if col.validity is not None else np.asarray(col.data)
                allowed = {str(v) for v in vals.tolist()}
                # v None = unpartitioned file: could hold anything, process.
                # The "null" partition never matches IN/=/BETWEEN: prune.
                return lambda v: v is None or v in allowed
            if isinstance(c, A.InList) and not c.negated and \
                    is_part_col(c.expr) and \
                    all(isinstance(i, A.Literal) for i in c.items):
                allowed = {str(lit(i)) for i in c.items}
                return lambda v: v is None or v in allowed
            if isinstance(c, A.Between) and not c.negated and \
                    is_part_col(c.expr) and lit(c.low) is not None \
                    and lit(c.high) is not None:
                lo, hi = lit(c.low), lit(c.high)

                def in_range(v, lo=lo, hi=hi):
                    if v is None:
                        return True
                    if v == "null":
                        return False       # NULL key never matches BETWEEN
                    try:
                        return lo <= int(v) <= hi
                    except (TypeError, ValueError):
                        return True        # unparseable: process the file
                return in_range
            if isinstance(c, A.BinOp) and c.op == "=":
                pair = ((c.left, c.right) if is_part_col(c.left)
                        else (c.right, c.left) if is_part_col(c.right)
                        else None)
                if pair is not None and lit(pair[1]) is not None:
                    allowed = {str(lit(pair[1]))}
                    return lambda v: v is None or v in allowed
        return None

    def explain(self, query: str) -> str:
        ast = parse_sql(query)
        planner = Planner(self._catalog())
        plan = planner.plan_query(ast)
        lines: list[str] = []

        def render(node, depth):
            label = type(node).__name__.replace("Node", "")
            detail = ""
            if hasattr(node, "table"):
                detail = f" {getattr(node, 'table', '')}"
            if hasattr(node, "kind"):
                detail = f" [{node.kind}]"
            lines.append("  " * depth + f"{label}{detail}"
                         f" -> {len(node.out_names)} cols")
            for f in ("child", "left", "right"):
                sub = getattr(node, f, None)
                if sub is not None and hasattr(sub, "out_names"):
                    render(sub, depth + 1)
        render(plan, 0)
        return "\n".join(lines)
