"""Plan executor: walks a bound plan and produces columnar Tables.

CTE plans are shared subtrees; results are memoized by node identity so each
CTE executes once per query (the reference gets this from Spark's lazy DAG;
here it is explicit).
"""
from __future__ import annotations

from typing import Callable, Optional

import numpy as np

from . import ops
from .column import Column, Table
from .exprs import evaluate
from .plan import (
    AggregateNode, BExpr, DistinctNode, FilterNode, JoinNode, LimitNode,
    MaterializedNode, PlanNode, ProjectNode, ScanNode, SetOpNode, SortNode,
    WindowNode,
)


def _loader_takes_columns(loader) -> bool:
    import inspect
    try:
        sig = inspect.signature(loader)
    except (TypeError, ValueError):
        return False
    params = list(sig.parameters.values())
    positional = [p for p in params
                  if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)]
    return len(positional) >= 2 or \
        any(p.kind == p.VAR_POSITIONAL for p in params)


def load_columns(loader: Callable, table: str, columns) -> Table:
    """Column-pruned load when the loader supports projection (scan pruning;
    plain single-argument callables keep working for tests/fallback nodes).
    Shared by the host and device executors."""
    try:
        return loader(table, tuple(columns))
    except TypeError:
        if _loader_takes_columns(loader):
            raise    # genuine TypeError inside a projection-aware loader
        return loader(table)


class Executor:
    def __init__(self, load_table: Callable[[str], Table],
                 trace: Optional[Callable[[str, float, int], None]] = None):
        self._load_table = load_table
        self._memo: dict[int, Table] = {}
        self._trace = trace

    def _load_columns(self, table: str, columns) -> Table:
        return load_columns(self._load_table, table, columns)

    def execute(self, node: PlanNode) -> Table:
        key = id(node)
        if key in self._memo:
            return self._memo[key]
        result = self._run(node)
        self._memo[key] = result
        return result

    def _eval(self, expr: BExpr, table: Table) -> Column:
        return evaluate(expr, table, subquery_eval=self._scalar)

    def _scalar(self, plan: PlanNode):
        t = self.execute(plan)
        if t.num_rows == 0:
            return None
        col = t.columns[0]
        if not bool(col.validity[0]):
            return None
        if col.dtype == "str":
            return col.decode()[0]
        return np.asarray(col.data)[0].item()

    def _run(self, node: PlanNode) -> Table:
        if isinstance(node, MaterializedNode):
            return node.table
        if isinstance(node, ScanNode):
            t = self._load_columns(node.table, node.columns)
            index = {n: i for i, n in enumerate(t.names)}
            cols = [t.columns[index[c]] for c in node.columns]
            return Table(list(node.out_names), cols)
        if isinstance(node, FilterNode):
            child = self.execute(node.child)
            mask = self._eval(node.predicate, child)
            return ops.filter_table(child, mask)
        if isinstance(node, ProjectNode):
            child = self.execute(node.child)
            cols = [self._eval(e, child) for e in node.exprs]
            return Table(list(node.out_names), cols)
        if isinstance(node, JoinNode):
            return self._run_join(node)
        if isinstance(node, AggregateNode):
            return self._run_aggregate(node)
        if isinstance(node, WindowNode):
            return self._run_window(node)
        if isinstance(node, SortNode):
            child = self.execute(node.child)
            key_cols = [self._eval(k.expr, child) for k in node.keys]
            return ops.sort_table(child, key_cols, node.keys)
        if isinstance(node, LimitNode):
            return self.execute(node.child).head(node.n)
        if isinstance(node, DistinctNode):
            return ops.distinct(self.execute(node.child))
        if isinstance(node, SetOpNode):
            left = self.execute(node.left)
            right = self.execute(node.right)
            out = ops.set_op(node.op, node.all, left, right)
            return Table(list(node.out_names), out.columns)
        raise NotImplementedError(type(node).__name__)

    def _run_join(self, node: JoinNode) -> Table:
        left = self.execute(node.left)
        right = self.execute(node.right)
        lkeys = [self._eval(e, left) for e in node.left_keys]
        rkeys = [self._eval(e, right) for e in node.right_keys]
        residual_eval = None
        if node.residual is not None:
            residual_eval = lambda combined: self._eval(node.residual, combined)
        out, _, _ = ops.join(left, right, node.kind, lkeys, rkeys, residual_eval,
                             null_aware=node.null_aware)
        return Table(list(node.out_names), out.columns)

    def _run_aggregate(self, node: AggregateNode) -> Table:
        child = self.execute(node.child)
        group_cols = [self._eval(e, child) for e in node.group_exprs]
        agg_args = [None if a.arg is None else self._eval(a.arg, child)
                    for a in node.aggs]
        g_out, a_out, gid_col = ops.aggregate(child, group_cols, node.aggs,
                                              agg_args, rollup=node.rollup,
                                              levels=node.rollup_levels)
        cols = g_out + a_out
        if node.rollup:
            cols.append(gid_col)
        return Table(list(node.out_names), cols)

    def _run_window(self, node: WindowNode) -> Table:
        child = self.execute(node.child)
        part_cols = [[self._eval(e, child) for e in f.partition_by]
                     for f in node.funcs]
        order_cols = [[self._eval(k.expr, child) for k in f.order_by]
                      for f in node.funcs]
        arg_cols = [None if f.arg is None else self._eval(f.arg, child)
                    for f in node.funcs]
        extra = ops.window(child, node.funcs, part_cols, order_cols, arg_cols)
        return Table(list(node.out_names), list(child.columns) + extra)
