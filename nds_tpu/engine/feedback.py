"""Feedback stats store: observed actuals close the loop back to plans.

Every run already measures itself exactly — ``ExecStats.node_stats``
records per-node actual row counts under the verifier's stable
``TypeName#k`` labels, the streamed morsel path host-fetches one check
scalar per capacity decision on every replay, and the durable query log
persists all of it. This module is the part that ACTS on what the engine
sees (ROADMAP item 2, the history-based optimization "Accelerating
Presto with GPUs" treats as table stakes): a per-template store of
observed cardinalities that the NEXT sighting of the same template
consumes.

Three observation surfaces, one store:

- **nodes** — ``{TypeName#k: max rows}`` per template, fed from
  ``Session._finish_exec_stats`` (and therefore the service ticket path,
  which lands there too). Reconstructable OFFLINE from a query-log JSONL
  via :meth:`FeedbackStore.replay_log` — the log's ``node_stats`` column
  carries the same map, and replaying it yields the same per-node
  actuals the live session recorded (a tested property).
- **tables** — exact rows streamed per big table per template: the
  planner's catalog prefers these over the registered static
  ``est_rows`` on the next sighting (``Session._est_rows_for``), so a
  mis-registered estimate flips streamed-vs-in-core and
  late-materialization decisions back to what the data actually is.
- **groups** — per-decision observed MAXIMA of each streamed scan
  group's capacity schedule, merged across every morsel of every
  sighting (record-pass actuals + replay check scalars). The next
  sighting right-sizes its capacity-ladder buckets from these instead of
  inflating every cap to the morsel bound (``streaming.adapt_schedule``)
  — the q9-class 0-group aggregate drops from the 32768-row morsel
  bucket to the minimal ladder bucket.

Discipline (the house default-off contract):

- An observed cap is a **ceiling hint**, never a correctness input: an
  under-observed actual overflows the adapted schedule's check at
  replay, raises ``ReplayMismatch``, and the morsel re-records eagerly —
  exactly the machinery morsel-bound inflation already relies on. A
  stale profile can cost a re-record; it can never mis-answer.
- **Drift sentinel**: when a template's observed profile diverges from
  its own history past ``drift_ratio`` (on the bucket scale, either
  direction), the store refreshes the history and bumps the template
  generation, so the next sighting re-records instead of replaying a
  stale schedule (``feedback_refreshes``; stamp-driven re-records count
  ``adaptive_replans``).
- ``EngineConfig.adaptive_plans=False`` (the default) never constructs a
  store: zero new counters, bit-identical plans and schedules.

Persistence is one crash-consistent JSON document beside the query log,
written with the warehouse's atomic-rename discipline
(``warehouse._atomic_write_json``: temp file -> fsync -> rename ->
directory fsync) and loaded at session attach. The store is advisory, so
an unreadable document degrades to an empty store with a warning — the
engine re-observes; it never refuses to start over a hint file.
"""
from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Optional

from ..obs import metrics as _metrics
from ..obs.flight import FLIGHT

log = logging.getLogger(__name__)

#: observations between automatic flushes of the JSON document (a flush
#: is two fsyncs — the same price as one warehouse manifest commit — so
#: per-statement flushing would tax the hot path; close/bench flush
#: explicitly)
FLUSH_EVERY = 16

DOC_VERSION = 1


def _bucket(n: int) -> int:
    from .jax_backend.device import bucket
    return bucket(max(int(n), 1))


def _new_template() -> dict:
    return {"sightings": 0, "refreshes": 0, "gen": 0, "updated": 0.0,
            "nodes": {}, "tables": {}, "groups": {}}


class FeedbackStore:
    """Per-template observed-cardinality store (one per adaptive session).

    Thread-safe: observations land under the session statement lock, but
    ``system.plan_feedback`` snapshots and the service's planner threads
    read concurrently, so every accessor cuts under the store's own lock.
    """

    def __init__(self, path: Optional[str] = None,
                 drift_ratio: float = 4.0) -> None:
        self.path = path
        self.drift_ratio = max(float(drift_ratio), 1.0)
        self._lock = threading.Lock()
        self._templates: dict[str, dict] = {}
        #: per-template last-applied right-sizing summary (bench's
        #: "adaptive" block): capacity cells the morsel-bound inflation
        #: would have provisioned vs what the adapted schedule did
        self.applied: dict[str, dict] = {}
        self._dirty = 0
        if path and os.path.exists(path):
            self._load(path)

    # -- persistence ---------------------------------------------------------
    def _load(self, path: str) -> None:
        try:
            with open(path) as f:
                doc = json.load(f)
            if doc.get("version") != DOC_VERSION:
                raise ValueError(f"unknown version {doc.get('version')!r}")
            self._templates = doc.get("templates", {})
        except (OSError, ValueError) as e:
            # advisory store: a bad hint file must not block the engine —
            # start empty and re-observe (the next flush rewrites it)
            log.warning("feedback store %s unreadable (%s); starting empty",
                        path, e)
            self._templates = {}

    def flush(self) -> None:
        """Write the document crash-consistently (atomic rename + dir
        fsync, the warehouse manifest discipline). No-op without a path."""
        if not self.path:
            return
        from ..warehouse import _atomic_write_json
        with self._lock:
            doc = {"version": DOC_VERSION,
                   "templates": json.loads(json.dumps(self._templates))}
            self._dirty = 0
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        _atomic_write_json(self.path, doc)

    def _note_dirty_locked(self) -> bool:
        self._dirty += 1
        return bool(self.path) and self._dirty >= FLUSH_EVERY

    # -- observation ---------------------------------------------------------
    def observe_nodes(self, template: str,
                      node_stats: Optional[dict]) -> None:
        """One completed statement's per-node actuals (TypeName#k -> rows).
        Max-merge against history; a bucket-scale downward divergence past
        drift_ratio refreshes the stored value instead (stale history)."""
        if not template or not node_stats:
            return
        flush = False
        with self._lock:
            t = self._templates.setdefault(template, _new_template())
            t["sightings"] += 1
            t["updated"] = round(time.time(), 3)
            nodes = t["nodes"]
            refreshed = False
            for lbl, rows in node_stats.items():
                rows = int(rows)
                old = nodes.get(lbl)
                if old is None or rows > old:
                    nodes[lbl] = rows
                elif _bucket(old) >= self.drift_ratio * _bucket(rows):
                    nodes[lbl] = rows       # history is stale: refresh down
                    refreshed = True
            if refreshed:
                t["refreshes"] += 1
            flush = self._note_dirty_locked()
        if refreshed:
            _metrics.FEEDBACK_REFRESHES.inc()
            FLIGHT.record("feedback_refresh", label=template, kind="nodes")
        if flush:
            self.flush()

    def observe_tables(self, template: str, rows_by_table: dict) -> None:
        """Exact rows streamed per big table this sighting. Stored as the
        LATEST observation (a full scan is ground truth, not a lower
        bound); a bucket-scale change bumps the template generation so
        cached streamed state re-plans against the corrected estimate."""
        if not template or not rows_by_table:
            return
        flush = False
        bumped = False
        with self._lock:
            t = self._templates.setdefault(template, _new_template())
            for name, rows in rows_by_table.items():
                rows = int(rows)
                old = t["tables"].get(name)
                t["tables"][name] = rows
                if old is None or _bucket(old) != _bucket(rows):
                    bumped = True
            if bumped:
                t["gen"] += 1
            flush = self._note_dirty_locked()
        if flush:
            self.flush()

    def observe_group(self, template: str, table: str, bound: int,
                      fused: bool, shards: int, kinds: list,
                      caps: list) -> None:
        """One streamed scan group's per-decision observed maxima (one row
        per member program; fused groups have a single shared schedule).
        Structure mismatch (different kinds/bound/fusion/sharding)
        replaces the profile; growth max-merges; a bucket-scale downward
        divergence past drift_ratio on any cap refreshes the profile —
        each of those bumps the generation, so the stream cache's stamp
        check re-records the template instead of replaying stale caps."""
        if not template:
            return
        kinds_l = [list(k) for k in kinds]
        caps_l = [[int(c) for c in row] for row in caps]
        refreshed = False
        flush = False
        with self._lock:
            t = self._templates.setdefault(template, _new_template())
            g = t["groups"].get(table)
            if g is None or g["kinds"] != kinds_l or g["bound"] != bound \
                    or g["fused"] != fused or g["shards"] != shards \
                    or [len(r) for r in g["caps"]] != \
                    [len(r) for r in caps_l]:
                t["groups"][table] = {
                    "bound": int(bound), "fused": bool(fused),
                    "shards": int(shards), "kinds": kinds_l, "caps": caps_l}
                t["gen"] += 1
            else:
                bumped = False
                for stored, seen, ks in zip(g["caps"], caps_l, kinds_l):
                    for i, k in enumerate(ks):
                        if k != "cap":
                            continue
                        if seen[i] > stored[i]:
                            if _bucket(seen[i]) != _bucket(stored[i]):
                                bumped = True
                            stored[i] = seen[i]
                        elif _bucket(stored[i]) >= \
                                self.drift_ratio * _bucket(seen[i]):
                            refreshed = True
                if refreshed:
                    # stale history: replace wholesale with this run's
                    # faithful profile rather than keeping inflated maxima
                    g["caps"] = caps_l
                    t["refreshes"] += 1
                    bumped = True
                if bumped:
                    t["gen"] += 1
            t["updated"] = round(time.time(), 3)
            flush = self._note_dirty_locked()
        if refreshed:
            _metrics.FEEDBACK_REFRESHES.inc()
            FLIGHT.record("feedback_refresh", label=template, table=table,
                          kind="schedule")
        if flush:
            self.flush()

    # -- consumption ---------------------------------------------------------
    def stamp(self, template: str) -> int:
        """The template's profile generation: cached streamed state
        records the stamp it was built under, and a moved stamp means
        observations changed enough to warrant a re-record."""
        with self._lock:
            t = self._templates.get(template)
            return t["gen"] if t is not None else 0

    def node_rows(self, template: str) -> dict:
        with self._lock:
            t = self._templates.get(template)
            return dict(t["nodes"]) if t is not None else {}

    def table_rows(self, template: str) -> dict:
        with self._lock:
            t = self._templates.get(template)
            return dict(t["tables"]) if t is not None else {}

    def member_caps(self, template: str, table: str, member: int,
                    kinds: list, bound: int, fused: bool,
                    shards: int) -> Optional[list]:
        """Observed per-decision maxima for one member program of one
        group, or None when no STRUCTURALLY MATCHING profile exists (the
        recorded kinds sequence, morsel bound, fusion and sharding mode
        must all match — anything else is a different program shape and
        adapting it would be guessing, not feedback)."""
        with self._lock:
            t = self._templates.get(template)
            g = t["groups"].get(table) if t is not None else None
            if g is None or g["bound"] != bound or g["fused"] != fused \
                    or g["shards"] != shards or member >= len(g["caps"]):
                return None
            if g["kinds"][member] != list(kinds):
                return None
            return list(g["caps"][member])

    def note_applied(self, template: str, cells_before: int,
                     cells_after: int) -> None:
        """Record one right-sizing application (bench's "adaptive" block:
        capacity cells the morsel-bound inflation would have provisioned
        vs the adapted schedule)."""
        with self._lock:
            a = self.applied.setdefault(
                template, {"groups": 0, "cap_cells_before": 0,
                           "cap_cells_after": 0})
            a["groups"] += 1
            a["cap_cells_before"] += int(cells_before)
            a["cap_cells_after"] += int(cells_after)

    # -- offline seeding ------------------------------------------------------
    def replay_log(self, rows) -> int:
        """Seed the store from saved query-log rows (read_jsonl / ring
        rows): each row's ``node_stats`` column replays through the SAME
        observe path the live session fed, so offline reconstruction
        yields identical per-node actuals. Returns rows consumed."""
        n = 0
        for r in rows:
            ns = r.get("node_stats")
            if not ns or not r.get("label"):
                continue
            if isinstance(ns, str):
                try:
                    ns = json.loads(ns)
                except ValueError:
                    continue
            self.observe_nodes(r["label"], ns)
            n += 1
        return n

    # -- introspection (system.plan_feedback) ---------------------------------
    def snapshot_rows(self) -> list[dict]:
        """One row per observed fact, under the store lock (the atomic-cut
        contract every system.* provider keeps): kind "node" rows carry
        TypeName#k actuals, kind "table" rows the observed scan rows, and
        kind "cap" rows each schedule decision's observed maximum."""
        out = []
        with self._lock:
            for name, t in sorted(self._templates.items()):
                base = {"template": name, "sightings": t["sightings"],
                        "refreshes": t["refreshes"], "gen": t["gen"]}
                for lbl, rows in sorted(t["nodes"].items()):
                    out.append({**base, "kind": "node", "node": lbl,
                                "table": None, "rows": rows})
                for tab, rows in sorted(t["tables"].items()):
                    out.append({**base, "kind": "table", "node": None,
                                "table": tab, "rows": rows})
                for tab, g in sorted(t["groups"].items()):
                    for mi, (ks, cs) in enumerate(zip(g["kinds"],
                                                      g["caps"])):
                        for di, k in enumerate(ks):
                            if k != "cap":
                                continue
                            out.append({**base, "kind": "cap",
                                        "node": f"m{mi}:d{di}",
                                        "table": tab, "rows": cs[di]})
        return out
