"""AST -> bound logical plan.

Responsibilities:
- name resolution (qualifiers, aliases, CTEs, self-joins) to column positions;
- join-graph extraction from comma-joins + WHERE equalities, with a
  size-heuristic greedy join order (facts probe, dimensions build);
- subquery handling: uncorrelated scalars (runtime-evaluated), IN/EXISTS as
  semi/anti joins, and decorrelation of equality-correlated scalar aggregate
  subqueries into grouped left joins (the TPC-DS q1/q6/q44 pattern);
- aggregate & window rebinding: aggregate calls and group expressions become
  positional columns for post-agg expressions (HAVING/SELECT/ORDER BY).

The reference delegates all of this to Spark Catalyst (nds_power.py:129
`spark.sql(query)`); this module is the TPU framework's Catalyst analog.
"""
from __future__ import annotations

import datetime as _dt
import os
import re
from dataclasses import dataclass, field, replace
from typing import Callable, Optional

import numpy as np

from ..sql import ast_nodes as A
from . import plan as P
from .column import dec_dtype, dec_scale, is_dec


class PlanError(ValueError):
    pass


class PassPipeline:
    """Runs the planner's top-level rewrite passes with machine-checked IR
    invariants between them (engine/verify.py), under
    ``EngineConfig.verify_plans``:

    - ``off``: zero verification cost — passes run exactly as before;
    - ``final``: the fully rewritten plan is verified once per statement
      (cheap safety net for CI);
    - ``per-pass``: every pass output is verified, each pass's input is
      fingerprint-snapshotted so in-place mutation of surviving (shared)
      nodes is caught (the `_exact_rational_keys` hazard class, ADVICE r5),
      and a violation raises PlanVerifyError naming the offending node AND
      the pass that introduced it — the pass whose output first fails.

    Two of the last three rounds shipped fixes for bugs rewrite passes
    introduced silently; this is the safety net cheaper than a SQLite
    differential run."""

    def __init__(self, mode: str, catalog: Optional["Catalog"] = None):
        if mode not in ("off", "final", "per-pass"):
            raise PlanError(f"unknown verify_plans mode {mode!r} "
                            "(expected off, final, or per-pass)")
        self.mode = mode
        self.catalog = catalog
        # rolling fingerprint snapshot of the last verified plan (per-pass
        # mode): each pass's freeze scan doubles as the next pass's
        # snapshot, so verification pays one fingerprint walk per pass
        self._snap: Optional[dict] = None

    def _verify(self, plan, pass_name: str, deep: bool = False) -> None:
        from ..obs.trace import TRACER
        from .verify import PlanVerifyError, node_labels, verify_plan
        with TRACER.span("plan.verify", **{"pass": pass_name}):
            labels = node_labels(plan)
            findings = verify_plan(plan, self.catalog, deep=deep,
                                   labels=labels)
        if findings:
            raise PlanVerifyError(findings, pass_name)

    def check(self, pass_name: str, plan):
        """Verify a pass-less snapshot (the freshly bound plan)."""
        if self.mode == "per-pass":
            self._verify(plan, pass_name)
            from .verify import snapshot
            self._snap = snapshot(plan)
        return plan

    def run(self, pass_name: str, fn, plan):
        """Run one rewrite pass; in per-pass mode, prove surviving nodes
        are structurally frozen and the output plan verifies clean. Every
        pass (and its verification, via _verify) is a traced span, so a
        Perfetto view of planning shows per-pass cost."""
        from ..obs.trace import TRACER
        if self.mode != "per-pass":
            with TRACER.span("plan.pass", **{"pass": pass_name}):
                return fn(plan)
        from .verify import PlanVerifyError, frozen_scan, verify_plan
        before = self._snap if self._snap is not None else \
            frozen_scan(plan, None)[1]
        with TRACER.span("plan.pass", **{"pass": pass_name}):
            out = fn(plan)
        findings, after = frozen_scan(out, before)
        if findings:
            raise PlanVerifyError(findings, pass_name)
        self._snap = after
        if out is plan:
            # same root object and zero mutated survivors: the pass output
            # is byte-identical to its (already verified) input
            return out
        findings = verify_plan(out, self.catalog)
        if findings:
            raise PlanVerifyError(findings, pass_name)
        return out

    def finish(self, plan):
        """Final verification: in ``final`` mode this is the only check; in
        ``per-pass`` mode the shape checks already ran after every pass, so
        only the deep checks (parameter-hoisting round-trip) remain — they
        run once per statement, not per pass."""
        if self.mode == "off":
            return plan
        if self.mode == "final":
            self._verify(plan, "final", deep=True)
            return plan
        from .verify import PlanVerifyError, _fill_labels, check_params
        findings = check_params(plan)
        _fill_labels(findings, plan, None)
        if findings:
            raise PlanVerifyError(findings, "final")
        return plan


# engine dtype helpers -------------------------------------------------------

_AGG_FUNCS = {"sum", "avg", "min", "max", "count", "stddev_samp", "stddev"}
_WINDOW_ONLY = {"rank", "dense_rank", "row_number"}


def _date_to_days(text: str) -> int:
    y, m, d = text.split("-")
    return (_dt.date(int(y), int(m), int(d)) - _dt.date(1970, 1, 1)).days


@dataclass
class ScopeEntry:
    qualifier: Optional[str]
    name: str
    dtype: str
    index: int


@dataclass
class Scope:
    entries: list[ScopeEntry] = field(default_factory=list)
    parent: Optional["Scope"] = None  # outer query scope (correlation)

    def resolve_local(self, name: str, qualifier: Optional[str]
                      ) -> Optional[ScopeEntry]:
        hits = [e for e in self.entries
                if e.name == name and (qualifier is None or e.qualifier == qualifier)]
        if len(hits) > 1:
            # identical source column visible through one qualifier twice is fine
            if len({h.index for h in hits}) > 1:
                raise PlanError(f"ambiguous column {qualifier + '.' if qualifier else ''}{name}")
        return hits[0] if hits else None

    def width(self) -> int:
        return max((e.index for e in self.entries), default=-1) + 1


@dataclass
class Catalog:
    """Maps table names to (schema, row-count estimate, loader)."""
    tables: dict = field(default_factory=dict)  # name -> (names, dtypes, est_rows)
    # decimal_physical="i64": CAST(x AS DECIMAL(p,s)) binds to "dec{s}"
    # instead of float (exact scaled-int64 decimals)
    dec_enabled: bool = False
    # table -> columns declared single-column unique (dimension surrogate
    # keys; schema.UNIQUE_KEYS or an explicit register_* declaration). The
    # late-materialization legality analysis requires the deferred join key
    # to be provably unique — a non-unique build side would double-count
    # through the post-aggregation attribute join.
    unique_cols: dict = field(default_factory=dict)
    # late-materialization rewrite toggle + size gate (EngineConfig mirrors)
    late_mat: bool = True
    late_mat_min_rows: int = 1 << 20
    # static plan-IR verification mode (EngineConfig.verify_plans mirror):
    # off | final | per-pass — see PassPipeline / engine/verify.py
    verify_plans: str = "off"
    # callable(table) -> {column: (lo, hi)} value-range stats in engine
    # units (None = no stats source). The verifier proves declared narrow
    # upload lanes (ScanNode.lanes) wide enough for the recorded ranges;
    # streaming chooses the lanes from the same source (Session.column_stats)
    stats_source: object = None

    def col_stats(self, name: str) -> dict:
        if self.stats_source is None:
            return {}
        try:
            return self.stats_source(name) or {}
        except Exception:
            return {}

    def schema(self, name: str) -> tuple[list[str], list[str]]:
        if name not in self.tables:
            raise PlanError(f"unknown table {name!r}")
        names, dtypes, _ = self.tables[name]
        return names, dtypes

    def est_rows(self, name: str) -> int:
        return self.tables[name][2] if name in self.tables else 1000

    def is_unique(self, table: str, column: str) -> bool:
        return column in self.unique_cols.get(table, ())


# ---------------------------------------------------------------------------


@dataclass
class _Unit:
    """One relation participating in the FROM join graph."""
    plan: P.PlanNode
    entries: list[ScopeEntry]      # local indices 0..w-1
    est_rows: float
    filters: list[A.Node] = field(default_factory=list)


class Planner:
    def __init__(self, catalog: Catalog):
        self.catalog = catalog
        # CTE compile-segmentation candidates: (fingerprint, plan node) in
        # definition order (definition-before-use => topological). The
        # fingerprint is STABLE across planner instances (AST-derived), so
        # q14/q23-style multi-part statements sharing a WITH clause map to
        # the same segment cache slots.
        self.cte_segments: list[tuple[str, P.PlanNode]] = []
        self._cte_fp: dict[int, str] = {}

    # -- public ------------------------------------------------------------
    def plan_query(self, q: A.Query, outer: Optional[Scope] = None,
                   ctes: Optional[dict] = None) -> P.PlanNode:
        top = ctes is None
        ctes = dict(ctes or {})
        for name, cq in q.ctes:
            ctes[name] = self._plan_cte(name, cq, ctes)
        node = self._plan_body(q.body, outer, ctes, q.order_by, q.limit)
        if top:
            # fresh root annotation, never a shared node's field
            node.cte_segments = list(self.cte_segments)  # lint: frozen-exempt (root annotation)
            pipe = PassPipeline(self.catalog.verify_plans, self.catalog)
            pipe.check("bind", node)
            if self.catalog.late_mat and \
                    not os.environ.get("NDS_TPU_NO_LATE_MAT"):
                # BEFORE pruning: the declaration-order permutation projects
                # are still full-width bijections, so the surrogate join key
                # is expressible in the aggregate's input space (pruning
                # would have dropped it — nothing above the join consumes it)
                node = pipe.run("late_materialization",
                                lambda p: self._seg_live(
                                    p, _late_materialization(p, self.catalog)),
                                node)
            if not os.environ.get("NDS_TPU_NO_COLPRUNE"):
                from .colprune import prune_plan
                node = pipe.run("colprune", prune_plan, node)
            if not os.environ.get("NDS_TPU_NO_SELFJOIN_REWRITE"):
                # AFTER pruning (dead columns would hide the single-column
                # key-set shape), and pruned again when it fired (the
                # rewrite kills the pair-expansion column uses)
                node2 = pipe.run("selfjoin_distinct",
                                 lambda p: self._seg_live(
                                     p, _selfjoin_distinct_rewrite(p)),
                                 node)
                if node2 is not node:
                    node = node2
                    if not os.environ.get("NDS_TPU_NO_COLPRUNE"):
                        from .colprune import prune_plan
                        node = pipe.run("colprune", prune_plan, node)
            node = pipe.finish(node)
        return node

    @staticmethod
    def _seg_live(old: P.PlanNode, new: P.PlanNode) -> P.PlanNode:
        """Carry cte_segments across a rewrite, dropping entries no longer
        reachable from the rewritten root."""
        if new is old:
            return new
        segs = getattr(old, "cte_segments", [])
        live = {id(n) for n in P.iter_plan_nodes(new)}
        new.cte_segments = [(fp, n) for fp, n in segs if id(n) in live]  # lint: frozen-exempt (root annotation)
        return new

    def _plan_cte(self, name: str, cq: A.Query, ctes: dict) -> P.PlanNode:
        """Plan one WITH entry and register it as a segmentation candidate."""
        import hashlib

        node = self.plan_query(cq, outer=None, ctes=ctes)
        visible = ";".join(f"{n}:{self._cte_fp.get(id(p), '')}"
                           for n, p in sorted(ctes.items()))
        fp = hashlib.sha1(f"{name}|{cq!r}|{visible}".encode()).hexdigest()[:16]
        self._cte_fp[id(node)] = fp
        self.cte_segments.append((fp, node))
        return node

    # -- query body ---------------------------------------------------------
    def _plan_body(self, body, outer, ctes, order_by, limit) -> P.PlanNode:
        if isinstance(body, A.SetOp):
            left = self._plan_body(body.left, outer, ctes, [], None)
            right = self._plan_body(body.right, outer, ctes, [], None)
            if len(left.out_names) != len(right.out_names):
                raise PlanError("set operation column count mismatch")
            # positionally coerce branches to a common dtype (decimal scales
            # in particular must match: scaled ints of different scales must
            # never concatenate raw)
            target = [a if a == b else _common_dtype([a, b])
                      for a, b in zip(left.out_dtypes, right.out_dtypes)]
            left = self._coerce_branch(left, target)
            right = self._coerce_branch(right, target)
            node = P.SetOpNode(body.op, body.all, left, right,
                               out_names=list(left.out_names),
                               out_dtypes=list(target))
            node = self._order_limit_by_position(node, order_by, limit)
            return node
        if isinstance(body, A.Query):
            sub = self.plan_query(body, outer, ctes)
            return self._order_limit_by_position(sub, order_by, limit)
        if isinstance(body, A.Select):
            return self._plan_select(body, outer, ctes, order_by, limit)
        raise PlanError(f"unsupported query body {type(body).__name__}")

    def _coerce_branch(self, node: P.PlanNode, target: list[str]) -> P.PlanNode:
        """Project a set-op branch onto the positional target dtypes."""
        if list(node.out_dtypes) == list(target):
            return node
        exprs = [_coerce_to(P.BCol(d, i, node.out_names[i]), t)
                 for i, (d, t) in enumerate(zip(node.out_dtypes, target))]
        return P.ProjectNode(node, exprs, out_names=list(node.out_names),
                             out_dtypes=list(target))

    def _order_limit_by_position(self, node: P.PlanNode, order_by, limit):
        if order_by:
            scope = Scope([ScopeEntry(None, n, d, i)
                           for i, (n, d) in enumerate(zip(node.out_names,
                                                          node.out_dtypes))])
            keys = []
            for si in order_by:
                e = self._bind_output_sort(si.expr, scope, node)
                keys.append(P.SortKey(e, si.asc, si.nulls_first))
            node = P.SortNode(node, keys=keys, out_names=list(node.out_names),
                              out_dtypes=list(node.out_dtypes))
        if limit is not None:
            node = P.LimitNode(node, n=limit, out_names=list(node.out_names),
                               out_dtypes=list(node.out_dtypes))
        return node

    def _bind_output_sort(self, expr, scope, node):
        if isinstance(expr, A.Literal) and isinstance(expr.value, int):
            idx = expr.value - 1
            if not (0 <= idx < len(node.out_names)):
                raise PlanError(f"ORDER BY position {expr.value} out of range")
            return P.BCol(node.out_dtypes[idx], idx, node.out_names[idx])
        binder = _Binder(self, scope, ctes={}, allow_outer=False)
        return binder.bind(expr)

    # -- SELECT ------------------------------------------------------------
    def _plan_select(self, sel: A.Select, outer, ctes, order_by, limit
                     ) -> P.PlanNode:
        # FROM + WHERE (join graph)
        rel, scope, deferred = self._plan_from_where(sel, outer, ctes)

        # expand stars
        items: list[A.SelectItem] = []
        for it in sel.items:
            if isinstance(it.expr, A.Star):
                for e in scope.entries:
                    if it.expr.qualifier is None or e.qualifier == it.expr.qualifier:
                        items.append(A.SelectItem(
                            A.ColumnRef((e.qualifier, e.name) if e.qualifier
                                        else (e.name,)), None))
            else:
                items.append(it)

        # aggregate detection
        agg_calls = []
        for it in items:
            _collect_aggs(it.expr, agg_calls)
        if sel.having is not None:
            _collect_aggs(sel.having, agg_calls)
        for si in order_by:
            _collect_aggs(si.expr, agg_calls)
        has_agg = bool(agg_calls) or sel.group_by is not None

        binder = _Binder(self, scope, ctes, outer=outer)

        if has_agg:
            ngroup = len(sel.group_by.exprs) if sel.group_by else 0
            rel, scope, rebound = self._plan_aggregate(
                rel, scope, sel, items, agg_calls, binder, ctes, outer)
            binder = _Binder(self, scope, ctes, outer=outer,
                             rewrites=rebound, num_group_cols=ngroup)

        # windows
        win_calls: list[A.FuncCall] = []
        for it in items:
            _collect_windows(it.expr, win_calls)
        for si in order_by:
            _collect_windows(si.expr, win_calls)
        if win_calls:
            rel, scope, binder = self._plan_windows(rel, scope, win_calls, binder,
                                                    ctes, outer)

        # HAVING
        if sel.having is not None:
            pred = binder.bind(sel.having)
            rel = P.FilterNode(rel, pred, out_names=list(rel.out_names),
                               out_dtypes=list(rel.out_dtypes))

        # SELECT projection
        proj_exprs, proj_names = [], []
        for it in items:
            e = binder.bind(it.expr)
            proj_exprs.append(e)
            proj_names.append(it.alias or _display_name(it.expr))
        project = P.ProjectNode(rel, proj_exprs,
                                out_names=proj_names,
                                out_dtypes=[e.dtype for e in proj_exprs])

        node: P.PlanNode = project
        if sel.distinct:
            node = P.DistinctNode(node, out_names=list(node.out_names),
                                  out_dtypes=list(node.out_dtypes))
            node = self._order_limit_output(node, order_by, limit, items,
                                            proj_exprs)
            return node

        # ORDER BY below-project binding: sort keys are exprs over project input
        if order_by:
            keys = []
            for si in order_by:
                e = self._bind_sort_key(si.expr, items, proj_exprs, binder,
                                        project)
                keys.append(P.SortKey(e, si.asc, si.nulls_first))
            # sort the project INPUT, so keys may use non-projected columns
            sorted_child = P.SortNode(rel, keys=keys,
                                      out_names=list(rel.out_names),
                                      out_dtypes=list(rel.out_dtypes))
            project = P.ProjectNode(sorted_child, proj_exprs,
                                    out_names=proj_names,
                                    out_dtypes=[e.dtype for e in proj_exprs])
            node = project
        if limit is not None:
            node = P.LimitNode(node, n=limit, out_names=list(node.out_names),
                               out_dtypes=list(node.out_dtypes))
        return node

    def _order_limit_output(self, node, order_by, limit, items, proj_exprs):
        """ORDER BY over the (distinct) projected output, by alias/position."""
        if order_by:
            scope = Scope([ScopeEntry(None, n, d, i)
                           for i, (n, d) in enumerate(zip(node.out_names,
                                                          node.out_dtypes))])
            keys = []
            for si in order_by:
                e = self._bind_output_sort_item(si.expr, scope, node, items)
                keys.append(P.SortKey(e, si.asc, si.nulls_first))
            node = P.SortNode(node, keys=keys, out_names=list(node.out_names),
                              out_dtypes=list(node.out_dtypes))
        if limit is not None:
            node = P.LimitNode(node, n=limit, out_names=list(node.out_names),
                               out_dtypes=list(node.out_dtypes))
        return node

    def _bind_output_sort_item(self, expr, scope, node, items):
        if isinstance(expr, A.Literal) and isinstance(expr.value, int):
            idx = expr.value - 1
            return P.BCol(node.out_dtypes[idx], idx, node.out_names[idx])
        for i, it in enumerate(items):
            if it.alias and expr == A.ColumnRef((it.alias,)):
                return P.BCol(node.out_dtypes[i], i, node.out_names[i])
            if it.expr == expr:
                return P.BCol(node.out_dtypes[i], i, node.out_names[i])
        binder = _Binder(self, scope, ctes={}, allow_outer=False)
        return binder.bind(expr)

    def _bind_sort_key(self, expr, items, proj_exprs, binder, project):
        # ordinal -> projected expr
        if isinstance(expr, A.Literal) and isinstance(expr.value, int):
            idx = expr.value - 1
            if not (0 <= idx < len(proj_exprs)):
                raise PlanError(f"ORDER BY position {expr.value} out of range")
            return proj_exprs[idx]
        # alias or identical expression -> projected expr
        for it, bound in zip(items, proj_exprs):
            if it.alias is not None and expr == A.ColumnRef((it.alias,)):
                return bound
            if it.expr == expr:
                return bound
        try:
            return binder.bind(expr)
        except PlanError:
            # aliases nested inside the sort expression (q36's
            # `CASE WHEN lochierarchy = 0 THEN i_category END`)
            return binder.bind(_substitute_aliases(expr, items))

    # -- FROM/WHERE join graph ----------------------------------------------
    def _plan_from_where(self, sel: A.Select, outer, ctes):
        if sel.from_ is None:
            raise PlanError("SELECT without FROM is not supported")
        # explicit INNER JOIN chains flatten into the same unit/edge machinery
        # as comma joins (inner joins commute): ON conjuncts classify exactly
        # like WHERE conjuncts, giving filter pushdown and size-ordered join
        # placement to JOIN-syntax templates (reference query72's
        # cs JOIN inventory ON item would otherwise expand row-count-first in
        # syntax order). Top-level LEFT joins peel into an ordered tail
        # applied after the greedy join.
        tail_specs: list = []
        root = self._peel_outer_tail(sel.from_, tail_specs)
        on_conjs: list = []
        units = self._flatten_from(root, ctes, outer, on_conjs)
        tail_units = [(kind, self._plan_relation(rnode, ctes, outer), on_ast)
                      for kind, rnode, on_ast in tail_specs]
        n_inner = len(units)
        all_units = units + [tu for _, tu, _ in tail_units]

        # full scope in declaration order
        scope_entries, offset = [], 0
        unit_offsets = []
        for u in all_units:
            unit_offsets.append(offset)
            for e in u.entries:
                scope_entries.append(replace(e, index=offset + e.index))
            offset += len(u.entries)
        scope = Scope(scope_entries, parent=outer)

        conjuncts = _split_and(sel.where) if sel.where is not None else []
        conjuncts = conjuncts + on_conjs
        conjuncts = conjuncts + _or_implied_conjuncts(conjuncts)
        edges, residuals, subq_conjs = [], [], []
        for c in conjuncts:
            if _has_subquery(c):
                subq_conjs.append(c)
                continue
            refs = self._referenced_units(c, all_units, scope, unit_offsets)
            if refs is None:
                residuals.append(c)  # references outer scope: bind later
            elif refs and max(refs) >= n_inner:
                # touches a LEFT-join tail unit: filtering inside/below the
                # outer join would change null-extension semantics
                residuals.append(c)
            elif len(refs) <= 1:
                if refs:
                    units[next(iter(refs))].filters.append(c)
                else:
                    residuals.append(c)  # constant predicate
            elif (len(refs) == 2 and isinstance(c, A.BinOp) and c.op == "="):
                lrefs = self._referenced_units(c.left, all_units, scope,
                                               unit_offsets)
                rrefs = self._referenced_units(c.right, all_units, scope,
                                               unit_offsets)
                if lrefs is not None and rrefs is not None and \
                        len(lrefs) == 1 and len(rrefs) == 1 and lrefs != rrefs:
                    la, rb = next(iter(lrefs)), next(iter(rrefs))
                    edges.append((la, rb, c.left, c.right))
                else:
                    residuals.append(c)
            else:
                residuals.append(c)

        # push single-unit filters
        for u in units:
            for f in u.filters:
                local_scope = Scope(u.entries, parent=outer)
                b = _Binder(self, local_scope, ctes, outer=outer)
                pred = b.bind(f)
                u.plan = P.FilterNode(u.plan, pred,
                                      out_names=list(u.plan.out_names),
                                      out_dtypes=list(u.plan.out_dtypes))
                u.est_rows = max(1.0, u.est_rows / 5.0)
            u.filters = []

        rel, col_map = self._join_units(units, edges, ctes, outer)

        # LEFT-join tail, in syntax order, over the greedy-joined group
        width = sum(len(u.entries) for u in units)
        for t_idx, (kind, tu, on_ast) in enumerate(tail_units):
            joined_entries = self._joined_entries(all_units, col_map)
            nleft = width
            combined = joined_entries + [
                replace(e, index=nleft + e.index) for e in tu.entries]
            scope2 = Scope(combined, parent=outer)
            binder2 = _Binder(self, scope2, ctes, outer=outer)
            lkeys, rkeys, res_parts = [], [], []
            for c in _split_and(on_ast):
                pair = self._equi_pair(c, scope2, nleft, binder2)
                if pair is not None:
                    lkeys.append(pair[0])
                    rkeys.append(pair[1])
                else:
                    res_parts.append(binder2.bind(c))
            rel = P.JoinNode(
                rel, tu.plan, kind, lkeys, rkeys, _and_all(res_parts),
                out_names=rel.out_names + tu.plan.out_names,
                out_dtypes=rel.out_dtypes + tu.plan.out_dtypes)
            col_map[n_inner + t_idx] = width
            width += len(tu.entries)

        # permutation back to declaration order
        perm = [None] * len(scope_entries)
        for ui, u in enumerate(all_units):
            for e in u.entries:
                perm[unit_offsets[ui] + e.index] = col_map[ui] + e.index
        exprs = [P.BCol(scope_entries[i].dtype, perm[i], scope_entries[i].name)
                 for i in range(len(scope_entries))]
        rel = P.ProjectNode(rel, exprs,
                            out_names=[e.name for e in scope_entries],
                            out_dtypes=[e.dtype for e in scope_entries])

        binder = _Binder(self, scope, ctes, outer=outer)
        for c in residuals:
            pred = binder.bind(c)
            rel = P.FilterNode(rel, pred, out_names=list(rel.out_names),
                               out_dtypes=list(rel.out_dtypes))

        deferred = []
        for c in subq_conjs:
            rel = self._apply_subquery_conjunct(rel, scope, c, ctes, outer)
        return rel, scope, deferred

    def _peel_outer_tail(self, node, tail: list):
        """Peel top-level LEFT joins into an ordered tail (deepest first);
        returns the inner root. `(G JOIN… ) LEFT JOIN p ON … LEFT JOIN r`
        becomes greedy(G) + tail [p, r] — outer joins are order barriers,
        inner groups beneath them are not."""
        if isinstance(node, A.Join) and node.kind == "left" \
                and node.on is not None:
            inner = self._peel_outer_tail(node.left, tail)
            tail.append((node.kind, node.right, node.on))
            return inner
        return node

    def _flatten_from(self, node, ctes, outer, on_acc: list) -> list[_Unit]:
        """Comma/cross joins AND explicit inner joins become separate units
        (their ON conjuncts accumulate into on_acc for edge classification);
        everything else is one unit."""
        if isinstance(node, A.Join) and node.kind == "cross" and node.on is None:
            return self._flatten_from(node.left, ctes, outer, on_acc) + \
                self._flatten_from(node.right, ctes, outer, on_acc)
        if isinstance(node, A.Join) and node.kind == "inner" \
                and node.on is not None and not _has_subquery(node.on):
            on_acc.extend(_split_and(node.on))
            return self._flatten_from(node.left, ctes, outer, on_acc) + \
                self._flatten_from(node.right, ctes, outer, on_acc)
        return [self._plan_relation(node, ctes, outer)]

    def _plan_relation(self, node, ctes, outer) -> _Unit:
        if isinstance(node, A.TableRef):
            qual = node.alias or node.name
            if node.name in ctes:
                sub = ctes[node.name]
                entries = [ScopeEntry(qual, n, d, i)
                           for i, (n, d) in enumerate(zip(sub.out_names,
                                                          sub.out_dtypes))]
                return _Unit(sub, entries, est_rows=10_000.0)
            names, dtypes = self.catalog.schema(node.name)
            scan = P.ScanNode(node.name, list(names),
                              out_names=list(names), out_dtypes=list(dtypes))
            entries = [ScopeEntry(qual, n, d, i)
                       for i, (n, d) in enumerate(zip(names, dtypes))]
            return _Unit(scan, entries, est_rows=float(self.catalog.est_rows(node.name)))
        if isinstance(node, A.SubqueryRef):
            sub = self.plan_query(node.query, outer=outer, ctes=ctes)
            entries = [ScopeEntry(node.alias, n, d, i)
                       for i, (n, d) in enumerate(zip(sub.out_names,
                                                      sub.out_dtypes))]
            return _Unit(sub, entries, est_rows=10_000.0)
        if isinstance(node, A.Join):
            left = self._plan_relation(node.left, ctes, outer)
            right = self._plan_relation(node.right, ctes, outer)
            combined_entries = list(left.entries) + [
                replace(e, index=e.index + len(left.entries))
                for e in right.entries]
            scope = Scope(combined_entries, parent=outer)
            kind = node.kind
            lkeys, rkeys, residual = [], [], None
            if node.on is not None:
                binder = _Binder(self, scope, ctes, outer=outer)
                nleft = len(left.entries)
                res_parts = []
                for c in _split_and(node.on):
                    pair = self._equi_pair(c, scope, nleft, binder)
                    if pair is not None:
                        lkeys.append(pair[0])
                        rkeys.append(pair[1])
                    else:
                        res_parts.append(binder.bind(c))
                residual = _and_all(res_parts)
            elif kind not in ("cross",):
                kind = "cross"
            out_names = [e.name for e in combined_entries]
            out_dtypes = [e.dtype for e in combined_entries]
            jn = P.JoinNode(left.plan, right.plan, kind, lkeys, rkeys, residual,
                            out_names=out_names, out_dtypes=out_dtypes)
            return _Unit(jn, combined_entries,
                         est_rows=max(left.est_rows, right.est_rows))
        raise PlanError(f"unsupported FROM element {type(node).__name__}")

    def _equi_pair(self, c, scope, nleft, binder):
        if not (isinstance(c, A.BinOp) and c.op == "="):
            return None
        try:
            lb = binder.bind(c.left)
            rb = binder.bind(c.right)
        except PlanError:
            return None
        lcols, rcols = _col_indices(lb), _col_indices(rb)
        if lcols and rcols:
            if max(lcols) < nleft and min(rcols) >= nleft:
                return lb, _shift(rb, -nleft)
            if max(rcols) < nleft and min(lcols) >= nleft:
                return rb, _shift(lb, -nleft)
        return None

    def _referenced_units(self, node, units, scope, unit_offsets):
        """Set of unit ids referenced by the AST; None if outer refs present."""
        refs: set[int] = set()
        outer_seen = [False]

        def visit(x):
            if isinstance(x, A.ColumnRef):
                e = scope.resolve_local(x.name, x.qualifier)
                if e is None:
                    outer_seen[0] = True
                    return
                ui = 0
                for i, off in enumerate(unit_offsets):
                    if e.index >= off:
                        ui = i
                refs.add(ui)
            for child in _children(x):
                visit(child)
        visit(node)
        if outer_seen[0]:
            return None
        return refs

    def _join_units(self, units, edges, ctes, outer):
        """Greedy join: start from the largest (fact) unit, attach connected
        units smallest-first (dimension build sides)."""
        n = len(units)
        if n == 1:
            return units[0].plan, {0: 0}
        remaining = set(range(n))
        start = max(remaining, key=lambda i: units[i].est_rows)
        current_plan = units[start].plan
        col_map = {start: 0}
        width = len(units[start].entries)
        remaining.discard(start)
        placed = {start}
        while remaining:
            connected = [i for i in remaining
                         if any((a in placed and b == i) or (b in placed and a == i)
                                for a, b, _, _ in edges)]
            pick = min(connected, key=lambda i: units[i].est_rows) if connected \
                else min(remaining, key=lambda i: units[i].est_rows)
            unit = units[pick]
            lkeys, rkeys = [], []
            for a, b, lexpr, rexpr in edges:
                if a in placed and b == pick:
                    okey, ikey = lexpr, rexpr
                elif b in placed and a == pick:
                    okey, ikey = rexpr, lexpr
                else:
                    continue
                lkeys.append(self._bind_in_joined(okey, units, col_map, ctes, outer))
                rkeys.append(self._bind_in_unit(ikey, unit, ctes, outer))
            kind = "inner" if lkeys else "cross"
            out_names = current_plan.out_names + unit.plan.out_names
            out_dtypes = current_plan.out_dtypes + unit.plan.out_dtypes
            current_plan = P.JoinNode(current_plan, unit.plan, kind,
                                      lkeys, rkeys, None,
                                      out_names=out_names, out_dtypes=out_dtypes)
            col_map[pick] = width
            width += len(unit.entries)
            placed.add(pick)
            remaining.discard(pick)
        return current_plan, col_map

    @staticmethod
    def _joined_entries(units, col_map):
        """Scope entries of the joined-so-far relation, offset per col_map."""
        entries = []
        for ui, off in col_map.items():
            for e in units[ui].entries:
                entries.append(replace(e, index=off + e.index))
        return entries

    def _bind_in_joined(self, expr, units, col_map, ctes, outer):
        entries = self._joined_entries(units, col_map)
        return _Binder(self, Scope(entries, parent=outer), ctes,
                       outer=outer).bind(expr)

    def _bind_in_unit(self, expr, unit, ctes, outer):
        return _Binder(self, Scope(unit.entries, parent=outer), ctes,
                       outer=outer).bind(expr)

    # -- subquery conjuncts --------------------------------------------------
    def _apply_subquery_conjunct(self, rel, scope, c, ctes, outer):
        binder = _Binder(self, scope, ctes, outer=outer)
        width = len(rel.out_names)

        neg = False
        node = c
        while isinstance(node, A.UnaryOp) and node.op == "not":
            neg = not neg
            node = node.operand

        if isinstance(node, A.Exists):
            if node.negated:
                neg = not neg
            return self._semi_anti(rel, scope, node.query, None, neg, ctes)
        if isinstance(node, A.InSubquery):
            neg2 = neg ^ node.negated
            return self._semi_anti(rel, scope, node.query, node.expr, neg2, ctes)

        # EXISTS/IN nested below the conjunct level (e.g. q10/q35's
        # `EXISTS(...) OR EXISTS(...)`, q45's `zip IN (...) OR id IN (subq)`):
        # mark joins — each subquery left-joins a distinct key set and is
        # replaced by an IS NOT NULL test on the joined mark column
        marks: dict[int, P.BExpr] = {}
        for sub in _nested_subqueries(node):
            rel, mark = self._mark_join(rel, scope, sub, ctes)
            marks[id(sub)] = mark

        # comparison containing scalar subqueries
        rel2, scope2, rewritten = self._decorrelate_scalars(rel, scope, node,
                                                            ctes)
        binder2 = _Binder(self, scope2, ctes, outer=outer,
                          subquery_cols={**rewritten, **marks})
        pred = binder2.bind(node)
        if neg:
            pred = P.BCall("bool", "not", [pred])
        filtered = P.FilterNode(rel2, pred, out_names=list(rel2.out_names),
                                out_dtypes=list(rel2.out_dtypes))
        if len(rel2.out_names) != width:
            exprs = [P.BCol(rel2.out_dtypes[i], i, rel2.out_names[i])
                     for i in range(width)]
            return P.ProjectNode(filtered, exprs,
                                 out_names=list(rel2.out_names[:width]),
                                 out_dtypes=list(rel2.out_dtypes[:width]))
        return filtered

    def _mark_join(self, rel, scope, sub, ctes):
        """Mark join: left-join a distinct correlated key set and return the
        widened relation plus a boolean expression that is TRUE iff the
        subquery matched (two-valued logic; NOT IN null semantics are only
        guaranteed in the conjunct-level path)."""
        in_expr = sub.expr if isinstance(sub, A.InSubquery) else None
        negated = getattr(sub, "negated", False)
        if negated and in_expr is not None:
            # A mark join evaluates NOT IN with two-valued logic: a NULL
            # outer probe or NULLs in the subquery result would yield TRUE
            # instead of UNKNOWN. No TPC-DS template hits this; reject it
            # rather than silently produce wrong rows.
            raise PlanError("negated IN subquery in a nested (OR-level) "
                            "position requires three-valued NOT IN "
                            "semantics, which mark joins do not provide")
        sub_plan, corr_pairs, inner_keys, mixed, _inner_scope = \
            self._plan_correlated(sub.query, scope, ctes)
        if mixed:
            raise PlanError("non-equality correlation in a nested subquery "
                            "is unsupported")
        outer_binder = _Binder(self, scope, ctes, outer=scope.parent)
        lkeys = [outer_binder.bind(oe) for oe, _ in corr_pairs]
        rkeys = list(inner_keys)
        if in_expr is not None:
            lkeys.append(outer_binder.bind(in_expr))
            rkeys.append(P.BCol(sub_plan.out_dtypes[0], 0,
                                sub_plan.out_names[0]))
        if not lkeys:
            raise PlanError("uncorrelated EXISTS in a nested position "
                            "is unsupported")
        key_exprs = [P.BCol(k.dtype, k.index, sub_plan.out_names[k.index])
                     for k in rkeys]
        names = [f"mk{i}" for i in range(len(key_exprs))]
        dtypes = [k.dtype for k in rkeys]
        proj = P.ProjectNode(sub_plan, key_exprs, out_names=names,
                             out_dtypes=dtypes)
        dist = P.DistinctNode(proj, out_names=names, out_dtypes=dtypes)
        new_rkeys = [P.BCol(d, i, names[i]) for i, d in enumerate(dtypes)]
        nleft = len(rel.out_names)
        joined = P.JoinNode(rel, dist, "left", lkeys, new_rkeys, None,
                            out_names=list(rel.out_names) + names,
                            out_dtypes=list(rel.out_dtypes) + dtypes)
        mark = P.BCall("bool", "isnotnull",
                       [P.BCol(dtypes[0], nleft, names[0])])
        if negated:
            mark = P.BCall("bool", "not", [mark])
        return joined, mark

    def _semi_anti(self, rel, scope, subq: A.Query, in_expr, negated, ctes):
        """EXISTS/IN subqueries as semi/anti joins with correlation keys.

        Mixed outer/inner conjuncts that aren't equality correlations (e.g.
        q16's cs1.cs_warehouse_sk <> cs2.cs_warehouse_sk) become a residual
        predicate evaluated over matched [outer row | subquery row] pairs
        before the semi/anti reduction (ops.join residual_eval contract).
        """
        sub_plan, corr_pairs, inner_keys, mixed, inner_scope = \
            self._plan_correlated(subq, scope, ctes)
        outer_binder = _Binder(self, scope, ctes, outer=scope.parent)
        lkeys = [outer_binder.bind(oe) for oe, _ in corr_pairs]
        rkeys = list(inner_keys)
        if in_expr is not None:
            lkeys.append(outer_binder.bind(in_expr))
            rkeys.append(P.BCol(sub_plan.out_dtypes[0], 0,
                                sub_plan.out_names[0]))
        if not lkeys:
            raise PlanError("EXISTS subquery without correlation is unsupported")
        residual = None
        if mixed:
            # combined schema = outer columns, then sub_plan columns; inner
            # entries shadow outer ones (innermost scope wins for unqualified
            # names), with indices offset past the outer width
            nleft = len(rel.out_names)
            ncore = len(sub_plan.out_names) - len(inner_scope.entries)
            entries = [ScopeEntry(e.qualifier, e.name, e.dtype,
                                  nleft + ncore + i)
                       for i, e in enumerate(inner_scope.entries)]
            entries += list(scope.entries)
            combined = Scope(entries, parent=scope.parent)
            rbinder = _Binder(self, combined, ctes, outer=scope.parent)
            for c in mixed:
                pred = rbinder.bind(c)
                residual = pred if residual is None else \
                    P.BCall("bool", "and", [residual, pred])
        kind = "anti" if negated else "semi"
        # NOT IN (subquery) needs SQL null semantics; NOT EXISTS does not
        null_aware = negated and in_expr is not None
        if null_aware and residual is not None:
            # The executors test build-side NULL keys before the residual is
            # applied, so a NOT IN whose mixed conjuncts would exclude the
            # NULL-key build rows would still empty the result. No TPC-DS
            # template combines these; reject instead of diverging.
            raise PlanError("NOT IN subquery with non-equality correlated "
                            "conjuncts (null-aware anti join with residual) "
                            "is unsupported")
        return P.JoinNode(rel, sub_plan, kind, lkeys, rkeys, residual,
                          null_aware=null_aware,
                          out_names=list(rel.out_names),
                          out_dtypes=list(rel.out_dtypes))

    def _decorrelate_scalars(self, rel, scope, node, ctes):
        """Replace correlated scalar agg subqueries in `node` with columns
        appended to `rel` via grouped left joins. Uncorrelated scalars stay as
        runtime BScalarSubquery (handled by the binder)."""
        rewritten: dict[int, P.BCol] = {}

        subqs: list[A.ScalarSubquery] = []

        def find(x):
            if isinstance(x, A.ScalarSubquery):
                subqs.append(x)
                return
            for ch in _children(x):
                find(ch)
        find(node)

        cur = rel
        for sq in subqs:
            if not _is_correlated(sq.query, scope, self, ctes):
                continue
            derived, corr_pairs, inner_keys, value_dtype = \
                self._plan_scalar_agg_subquery(sq.query, scope, ctes)
            outer_binder = _Binder(self, scope, ctes, outer=scope.parent)
            lkeys = [outer_binder.bind(oe) for oe, _ in corr_pairs]
            width = len(cur.out_names)
            cur = P.JoinNode(cur, derived, "left", lkeys, inner_keys, None,
                             out_names=cur.out_names + derived.out_names,
                             out_dtypes=cur.out_dtypes + derived.out_dtypes)
            # value column is the last output of derived
            value_idx = width + len(derived.out_names) - 1
            rewritten[id(sq)] = P.BCol(value_dtype, value_idx,
                                       derived.out_names[-1])
        # keep original entries (with qualifiers) and extend with joined cols
        entries = list(scope.entries)
        for i in range(len(scope.entries), len(cur.out_names)):
            entries.append(ScopeEntry(None, cur.out_names[i],
                                      cur.out_dtypes[i], i))
        return cur, Scope(entries, parent=scope.parent), rewritten

    def _plan_correlated(self, subq: A.Query, outer_scope, ctes):
        """Plan an EXISTS/IN subquery body; extract equality correlations.

        Returns (plan, [(outer_ast, inner_ast)], [bound inner key exprs]).
        The plan outputs the subquery's select items first, then one column
        per correlation key (so callers can use them as join keys).
        """
        if subq.ctes:
            ctes = dict(ctes)
            for nm, cq in subq.ctes:
                ctes[nm] = self._plan_cte(nm, cq, ctes)
        body = subq.body
        if not isinstance(body, A.Select):
            raise PlanError("unsupported subquery form")
        corr, mixed, inner_where = _extract_correlation(body.where,
                                                        outer_scope, self,
                                                        ctes, body)
        inner_sel = replace(body, where=inner_where)
        rel, inner_scope, _ = self._plan_from_where(inner_sel, None, ctes)
        binder = _Binder(self, inner_scope, ctes, outer=None)
        sel_exprs = []
        for it in inner_sel.items:
            if isinstance(it.expr, A.Star):
                sel_exprs.append(P.BLit("int", 1))  # EXISTS (select *): row marker
            else:
                sel_exprs.append(binder.bind(it.expr))
        extra_exprs = [binder.bind(ie) for _, ie in corr]
        all_exprs = sel_exprs + extra_exprs
        # output names mirror what each column IS — select items as c{i},
        # correlation keys as k{i}, exposed inner columns by their own
        # names — so downstream key/residual references resolve by name too
        all_names = [f"c{i}" for i in range(len(sel_exprs))] + \
                    [f"k{i}" for i in range(len(extra_exprs))]
        if mixed:
            # expose every inner column so the caller can bind the residual
            # over the combined [outer | subquery] schema
            all_exprs = all_exprs + [
                P.BCol(e.dtype, e.index, e.name) for e in inner_scope.entries]
            all_names = all_names + [e.name for e in inner_scope.entries]
        plan = P.ProjectNode(rel, all_exprs,
                             out_names=all_names,
                             out_dtypes=[e.dtype for e in all_exprs])
        inner_keys = [P.BCol(e.dtype, len(sel_exprs) + i, f"k{i}")
                      for i, e in enumerate(extra_exprs)]
        return plan, corr, inner_keys, mixed, inner_scope

    def _plan_scalar_agg_subquery(self, subq: A.Query, outer_scope, ctes):
        """Decorrelate `(select AGG-expr from ... where corr-eqs and filters)`.

        Returns (derived_plan, corr_pairs, inner_group_key_cols, value_dtype);
        derived outputs [group keys..., value].
        """
        if subq.ctes:
            ctes = dict(ctes)
            for nm, cq in subq.ctes:
                ctes[nm] = self._plan_cte(nm, cq, ctes)
        body = subq.body
        if not isinstance(body, A.Select) or len(body.items) != 1:
            raise PlanError("unsupported correlated scalar subquery")
        corr, mixed, inner_where = _extract_correlation(body.where,
                                                        outer_scope, self,
                                                        ctes, body)
        if mixed:
            raise PlanError("non-equality correlation in scalar subquery "
                            "is unsupported")
        if not corr:
            raise PlanError("scalar subquery marked correlated but no equality "
                            "correlation found")
        inner_sel = replace(body, where=inner_where)
        rel, scope, _ = self._plan_from_where(inner_sel, None, ctes)
        binder = _Binder(self, scope, ctes, outer=None)
        group_exprs = [binder.bind(ie) for _, ie in corr]
        agg_calls: list[A.FuncCall] = []
        _collect_aggs(body.items[0].expr, agg_calls)
        if not agg_calls:
            raise PlanError("correlated scalar subquery must aggregate")
        aggs = [self._make_aggspec(fc, binder) for fc in agg_calls]
        agg_node = P.AggregateNode(
            rel, group_exprs, aggs, False,
            out_names=[f"g{i}" for i in range(len(group_exprs))] +
                      [f"a{i}" for i in range(len(aggs))],
            out_dtypes=[e.dtype for e in group_exprs] +
                       [a.dtype for a in aggs])
        # value expression over [group keys, agg results]
        rewrites = {}
        for i, fc in enumerate(agg_calls):
            rewrites[_ast_key(fc)] = P.BCol(aggs[i].dtype,
                                            len(group_exprs) + i, f"a{i}")
        post_scope = Scope([ScopeEntry(None, n, d, i)
                            for i, (n, d) in enumerate(zip(agg_node.out_names,
                                                           agg_node.out_dtypes))])
        post_binder = _Binder(self, post_scope, ctes, outer=None,
                              rewrites=rewrites)
        value = post_binder.bind(body.items[0].expr)
        exprs = [P.BCol(e.dtype, i, f"g{i}") for i, e in enumerate(group_exprs)]
        exprs.append(value)
        derived = P.ProjectNode(
            agg_node, exprs,
            out_names=[f"g{i}" for i in range(len(group_exprs))] + ["__value"],
            out_dtypes=[e.dtype for e in exprs])
        inner_keys = [P.BCol(e.dtype, i, f"g{i}")
                      for i, e in enumerate(group_exprs)]
        return derived, corr, inner_keys, value.dtype

    # -- aggregation ---------------------------------------------------------
    def _make_aggspec(self, fc: A.FuncCall, binder) -> P.AggSpec:
        func = fc.name
        if func == "stddev":
            func = "stddev_samp"
        if func == "count" and fc.args and isinstance(fc.args[0], A.Star):
            return P.AggSpec("count_star", None, False, "count(1)")
        arg = binder.bind(fc.args[0]) if fc.args else None
        return P.AggSpec(func, arg, fc.distinct, _display_name(fc))

    def _plan_aggregate(self, rel, scope, sel, items, agg_calls, binder, ctes,
                        outer):
        group_asts = list(sel.group_by.exprs) if sel.group_by else []
        rollup = bool(sel.group_by.rollup) if sel.group_by else False
        # group by alias / ordinal -> replace with select expr
        resolved_groups = []
        for g in group_asts:
            if isinstance(g, A.Literal) and isinstance(g.value, int):
                resolved_groups.append(items[g.value - 1].expr)
            elif isinstance(g, A.ColumnRef) and g.qualifier is None and \
                    scope.resolve_local(g.name, None) is None:
                hit = next((it.expr for it in items if it.alias == g.name), None)
                resolved_groups.append(hit if hit is not None else g)
            else:
                resolved_groups.append(g)
        group_bound = [binder.bind(g) for g in resolved_groups]
        # dedupe agg calls by AST
        uniq_aggs: list[A.FuncCall] = []
        for fc in agg_calls:
            if not any(fc == u for u in uniq_aggs):
                uniq_aggs.append(fc)
        aggs = [self._make_aggspec(fc, binder) for fc in uniq_aggs]
        out_names = [_display_name(g) for g in resolved_groups] + \
                    [a.name or a.func for a in aggs]
        out_dtypes = [e.dtype for e in group_bound] + [a.dtype for a in aggs]
        if rollup:
            out_names.append("__grouping_id")
            out_dtypes.append("int")
        node = P.AggregateNode(rel, group_bound, aggs, rollup,
                               out_names=out_names, out_dtypes=out_dtypes)
        # rewrites: group ASTs and agg ASTs -> positional columns
        rewrites: dict = {}
        for i, g in enumerate(resolved_groups):
            rewrites[_ast_key(g)] = P.BCol(group_bound[i].dtype, i,
                                           out_names[i])
        for i, fc in enumerate(uniq_aggs):
            rewrites[_ast_key(fc)] = P.BCol(aggs[i].dtype,
                                            len(group_bound) + i,
                                            out_names[len(group_bound) + i])
        new_entries = []
        for i, g in enumerate(resolved_groups):
            nm = g.name if isinstance(g, A.ColumnRef) else out_names[i]
            qual = g.qualifier if isinstance(g, A.ColumnRef) else None
            new_entries.append(ScopeEntry(qual, nm, group_bound[i].dtype, i))
        for i in range(len(aggs)):
            new_entries.append(ScopeEntry(None, out_names[len(group_bound) + i],
                                          aggs[i].dtype, len(group_bound) + i))
        if rollup:
            new_entries.append(ScopeEntry(None, "__grouping_id", "int",
                                          len(out_names) - 1))
        new_scope = Scope(new_entries, parent=outer)
        return node, new_scope, rewrites

    # -- windows -------------------------------------------------------------
    def _exact_rational_keys(self, rel, key: "P.SortKey"
                             ) -> tuple["P.PlanNode", list]:
        """Rank order keys that are float divisions of integer-typed values
        (ints or scaled-int decimals) are replaced by TWO exact integer
        keys — floor(p/q) and 56 binary fraction digits — so rank ties are
        decided by exact rational equality on every backend. Float division
        is not correctly rounded under TPU f64 emulation, so equal rationals
        reached through different operand pairs (2/3 vs 4/6) can land 1 ULP
        apart and flip ties the host oracle keeps (the failure class the
        reference validator carves out per-query for floats,
        nds/nds_validate.py:231-244; exact keys remove the need for any
        q49 carve-out here). The operands are hoisted through the
        intervening ProjectNode chain as hidden columns; the chain rebuilds
        COPY-ON-WRITE (returning the possibly-new rel) — chain nodes can be
        shared CTE plan objects, and widening them in place would shift
        positional bindings for every other consumer (ADVICE r5)."""
        chain: list[P.ProjectNode] = []
        e, node = key.expr, rel
        while isinstance(e, P.BCol) and isinstance(node, P.ProjectNode):
            chain.append(node)
            e = node.exprs[e.index]
            node = node.child
        if not (isinstance(e, P.BCall) and e.op == "div"):
            return rel, [key]

        def strip_cast(x):
            while isinstance(x, P.BCall) and x.op == "cast" \
                    and x.dtype == "float":
                x = x.args[0]
            return x if x.dtype == "int" or is_dec(x.dtype) else None

        num, den = strip_cast(e.args[0]), strip_cast(e.args[1])
        if num is None or den is None:
            return rel, [key]

        appends: list[list] = [[] for _ in chain]  # per chain node

        def append_col(ci: int, expr, name: str) -> int:
            proj = chain[ci]
            for i, ex in enumerate(proj.exprs):
                if repr(ex) == repr(expr):
                    return i
            for k, (ex, _nm) in enumerate(appends[ci]):
                if repr(ex) == repr(expr):
                    return len(proj.exprs) + k
            appends[ci].append((expr, name))
            return len(proj.exprs) + len(appends[ci]) - 1

        cols = []
        for opnd, tag in ((num, "num"), (den, "den")):
            if not chain:
                cols.append(opnd)   # already in rel's scope
                continue
            idx = append_col(len(chain) - 1, opnd, f"__rat_{tag}")
            for ci in range(len(chain) - 2, -1, -1):
                idx = append_col(ci, P.BCol(opnd.dtype, idx, f"__rat_{tag}"),
                                 f"__rat_{tag}")
            cols.append(P.BCol(opnd.dtype, idx, f"__rat_{tag}"))
        if chain:
            rebuilt: Optional[P.PlanNode] = None
            for ci in range(len(chain) - 1, -1, -1):
                proj = chain[ci]
                child = rebuilt if rebuilt is not None else proj.child
                if appends[ci] or child is not proj.child:
                    rebuilt = replace(
                        proj, child=child,
                        exprs=list(proj.exprs) + [ex for ex, _ in appends[ci]],
                        out_names=list(proj.out_names) +
                                  [nm for _, nm in appends[ci]],
                        out_dtypes=list(proj.out_dtypes) +
                                   [ex.dtype for ex, _ in appends[ci]])
                else:
                    rebuilt = proj
            rel = rebuilt
        return rel, [P.SortKey(P.BCall("int", op, list(cols)),
                               key.asc, key.nulls_first)
                     for op in ("ratdiv_hi", "ratdiv_lo")]

    def _plan_windows(self, rel, scope, win_calls, binder, ctes, outer):
        uniq: list[A.FuncCall] = []
        for fc in win_calls:
            if not any(fc == u for u in uniq):
                uniq.append(fc)
        funcs = []
        for fc in uniq:
            arg = None
            if fc.args and not isinstance(fc.args[0], A.Star):
                arg = binder.bind(fc.args[0])
            func = fc.name
            if func == "count" and fc.args and isinstance(fc.args[0], A.Star):
                func = "count_star"
            part = [binder.bind(e) for e in fc.over.partition_by]
            okeys = [P.SortKey(binder.bind(si.expr), si.asc, si.nulls_first)
                     for si in fc.over.order_by]
            funcs.append(P.WindowFunc(func, arg, part, okeys,
                                      name=_display_name(fc)))
        for i, f in enumerate(funcs):
            if f.func in ("rank", "dense_rank") and f.order_by:
                new_keys = []
                for k in f.order_by:
                    rel, ks = self._exact_rational_keys(rel, k)
                    new_keys.extend(ks)
                # copy-on-write, like every other plan-IR rewrite: mutating
                # the WindowFunc in place would trip the freeze lint even
                # though this list is planner-local
                funcs[i] = replace(f, order_by=new_keys)
        out_names = list(rel.out_names) + [f.name for f in funcs]
        out_dtypes = list(rel.out_dtypes) + [f.dtype for f in funcs]
        node = P.WindowNode(rel, funcs, out_names=out_names,
                            out_dtypes=out_dtypes)
        rewrites = dict(binder.rewrites)
        base = len(rel.out_names)
        for i, fc in enumerate(uniq):
            rewrites[_ast_key(fc)] = P.BCol(funcs[i].dtype, base + i,
                                            funcs[i].name)
        entries = list(scope.entries)
        for i, f in enumerate(funcs):
            entries.append(ScopeEntry(None, f.name, f.dtype, base + i))
        new_scope = Scope(entries, parent=outer)
        new_binder = _Binder(self, new_scope, ctes, outer=outer,
                             rewrites=rewrites,
                             num_group_cols=binder.num_group_cols)
        return node, new_scope, new_binder


# ---------------------------------------------------------------------------
# binder: AST expression -> bound expression
# ---------------------------------------------------------------------------

# ---------------------------------------------------------------------------
# late materialization (q72-class): group by surrogate keys, gather dimension
# attributes after aggregation
# ---------------------------------------------------------------------------

def _lm_compose(chain: list, depth: int, idx: int) -> int:
    """Map a column index through the pure-BCol project chain below `depth`
    (later chain entries are deeper), landing in join-tree output space."""
    for p in chain[depth:]:
        idx = p.exprs[idx].index
    return idx


def _lm_refs(expr, chain: list, depth: int) -> set[int]:
    """Join-space column indices referenced by an expression bound at chain
    depth `depth`. Embedded subquery plans are closed (decorrelated) and
    reference their own spaces — ignored."""
    from .colprune import _expr_refs
    refs: set[int] = set()
    _expr_refs(expr, refs, [])
    return {_lm_compose(chain, depth, r) for r in refs}


def _lm_shared_nodes(plan: P.PlanNode) -> set[int]:
    """Node ids with more than one plan-DAG parent, plus every node of a
    registered CTE segment subtree: the attribute-join side must be cloned,
    and cloning shared work (or a segment-cache slot) would silently
    duplicate it."""
    from .streaming import _expr_subplans
    counts: dict[int, int] = {}
    for nd in P.iter_plan_nodes(plan):
        for f in ("child", "left", "right"):
            sub = getattr(nd, f, None)
            if isinstance(sub, P.PlanNode):
                counts[id(sub)] = counts.get(id(sub), 0) + 1
        for sp in _expr_subplans(nd):
            counts[id(sp)] = counts.get(id(sp), 0) + 1
    out = {i for i, c in counts.items() if c > 1}
    for _fp, seg in getattr(plan, "cte_segments", None) or []:
        out.update(id(x) for x in P.iter_plan_nodes(seg))
    return out


def _lm_clonable(node: P.PlanNode, shared: set[int]) -> bool:
    """A dimension subtree we may duplicate for the post-agg gather: scans,
    filters, and projects only; no shared nodes; no embedded subquery plans
    (cloning would fork their execution)."""
    from .streaming import _expr_subplans
    for x in P.iter_plan_nodes(node):
        if not isinstance(x, (P.ScanNode, P.FilterNode, P.ProjectNode)):
            return False
        if id(x) in shared or _expr_subplans(x):
            return False
    return True


def _lm_clone(node: P.PlanNode) -> P.PlanNode:
    """Fresh node objects for a Scan/Filter/Project subtree (expressions are
    shared — they are treated immutably everywhere). Distinct identity keeps
    colprune's needed-set union from widening the pre-agg build side with the
    post-agg attribute columns."""
    if isinstance(node, P.ScanNode):
        return replace(node, columns=list(node.columns),
                       out_names=list(node.out_names),
                       out_dtypes=list(node.out_dtypes))
    return replace(node, child=_lm_clone(node.child),
                   out_names=list(node.out_names),
                   out_dtypes=list(node.out_dtypes))


def _lm_key_scan(node: P.PlanNode, idx: int):
    """Trace output column `idx` of a dim subtree down to its source scan
    column; (table, column) or None when the path is not a pure passthrough."""
    while True:
        if isinstance(node, P.ProjectNode):
            e = node.exprs[idx]
            if not isinstance(e, P.BCol):
                return None
            idx = e.index
            node = node.child
        elif isinstance(node, P.FilterNode):
            node = node.child
        elif isinstance(node, P.ScanNode):
            return node.table, node.columns[idx]
        else:
            return None


def _try_late_mat(agg: P.AggregateNode, catalog: "Catalog",
                  shared: set[int]) -> Optional[P.PlanNode]:
    """Rewrite one aggregate-over-join to late-materialized form, or None.

    Legality: each deferred dimension joins inner on a single catalog-unique
    key with no residual, and its columns are consumed ONLY as plain-column
    group keys (pre-agg filters, aggregate arguments, other joins' keys, and
    computed group expressions keep a dimension pinned). Exactness: grouping
    by the surrogate key is finer than grouping by its attributes (the key
    functionally determines them through a unique-key join), so a merge
    aggregate over the original group values — the streaming partial/final
    decomposition — restores the exact result, including avg (sum+count) and
    all-NULL sums."""
    from .streaming import _decompose, _final_builder, _mergeable

    if agg.rollup or agg.rollup_levels is not None or not agg.group_exprs:
        return None
    if not _mergeable(agg):
        return None

    # descend pure-BCol projects and filters to the join tree
    chain: list[P.ProjectNode] = []
    filters: list[tuple] = []
    node = agg.child
    while True:
        if isinstance(node, P.ProjectNode) and \
                all(isinstance(e, P.BCol) for e in node.exprs):
            chain.append(node)
            node = node.child
        elif isinstance(node, P.FilterNode):
            filters.append((node.predicate, len(chain)))
            node = node.child
        else:
            break
    if not isinstance(node, P.JoinNode):
        return None

    # size gate: the fact-scale gathers are the win; tiny plans only pay the
    # extra join + merge aggregate
    if catalog.late_mat_min_rows > 0:
        big = max((catalog.est_rows(s.table)
                   for s in P.iter_plan_nodes(agg.child)
                   if isinstance(s, P.ScanNode)), default=0)
        if big < catalog.late_mat_min_rows:
            return None

    # flatten the left spine; every spine join's output keeps its left side
    # as a positional prefix, so right-side spans are valid in the top space
    cands: list[dict] = []
    consumed: set[int] = set()
    cur = node
    while isinstance(cur, (P.JoinNode, P.FilterNode)):
        if isinstance(cur, P.FilterNode):
            filters.append((cur.predicate, len(chain)))
            cur = cur.child
            continue
        for k in cur.left_keys:
            consumed |= _lm_refs(k, chain, len(chain))
        if cur.residual is not None:
            consumed |= _lm_refs(cur.residual, chain, len(chain))
        if cur.kind in ("full", "right"):
            # null-extended left rows below would carry NULL surrogate keys
            # the post-agg inner join could not reproduce: stop here
            break
        if cur.kind == "inner" and not cur.late_mat \
                and cur.residual is None \
                and len(cur.left_keys) == 1 and len(cur.right_keys) == 1 \
                and isinstance(cur.right_keys[0], P.BCol):
            cands.append({"join": cur, "off": len(cur.left.out_names),
                          "w": len(cur.right.out_names),
                          "kidx": cur.right_keys[0].index})
        cur = cur.left
    if not cands:
        return None

    for pred, depth in filters:
        consumed |= _lm_refs(pred, chain, depth)
    for s in agg.aggs:
        if s.arg is not None:
            consumed |= _lm_refs(s.arg, chain, 0)

    def find_cand(gcol: int) -> Optional[int]:
        for ci, c in enumerate(cands):
            if c["off"] <= gcol < c["off"] + c["w"]:
                return ci
        return None

    # classify group exprs: a plain dim-column BCol may defer; anything else
    # consumes its columns pre-agg
    gclass: list = []
    for g in agg.group_exprs:
        ci = None
        if isinstance(g, P.BCol):
            gcol = _lm_compose(chain, 0, g.index)
            ci = find_cand(gcol)
        if ci is None:
            consumed |= _lm_refs(g, chain, 0)
            gclass.append(None)
        else:
            gclass.append((ci, gcol))

    elig: dict[int, dict] = {}
    for ci, c in enumerate(cands):
        span = set(range(c["off"], c["off"] + c["w"]))
        if consumed & span:
            continue
        keyg = c["off"] + c["kidx"]
        if not any(cl is not None and cl[0] == ci and cl[1] != keyg
                   for cl in gclass):
            continue            # no deferred attribute: nothing to gain
        if not _lm_clonable(c["join"].right, shared):
            continue
        traced = _lm_key_scan(c["join"].right, c["kidx"])
        if traced is None or not catalog.is_unique(*traced):
            continue
        elig[ci] = c

    # the surrogate key must be expressible in the aggregate's input space
    # (pre-prune permutation projects are full-width, so it normally is);
    # prefer the fact-side key column — the gathered dim key then dies in
    # the compiled program's DCE
    inv: dict[int, int] = {}
    for t in range(len(agg.child.out_names)):
        inv.setdefault(_lm_compose(chain, 0, t), t)
    for ci in list(elig):
        c = elig[ci]
        lk = c["join"].left_keys[0]
        src = inv.get(lk.index) if isinstance(lk, P.BCol) else None
        if src is None:
            src = inv.get(c["off"] + c["kidx"])
        if src is None:
            del elig[ci]
        else:
            c["key_top"] = src
    if not elig:
        return None

    # assemble: partial agg by surrogate keys -> attribute joins against
    # cloned dims -> projection into the partial schema -> merge aggregate
    n = len(agg.group_exprs)
    partial_specs, recipes, p_names, p_dtypes = _decompose(agg)
    pkeys: list[P.BExpr] = []
    slot: dict[int, int] = {}        # candidate -> partial key slot
    plain_slot: dict[int, int] = {}  # group expr index -> partial key slot
    for i, (g, cl) in enumerate(zip(agg.group_exprs, gclass)):
        if cl is not None and cl[0] in elig:
            ci = cl[0]
            if ci not in slot:
                slot[ci] = len(pkeys)
                src = elig[ci]["key_top"]
                pkeys.append(P.BCol(agg.child.out_dtypes[src], src,
                                    agg.child.out_names[src]))
        else:
            plain_slot[i] = len(pkeys)
            pkeys.append(g)
    m = len(pkeys)
    partial = P.AggregateNode(
        child=agg.child, group_exprs=pkeys, aggs=list(partial_specs),
        out_names=[f"__lm_k{i}" for i in range(m)] +
                  [s.name for s in partial_specs],
        out_dtypes=[e.dtype for e in pkeys] +
                   [s.dtype for s in partial_specs])
    cur2: P.PlanNode = partial
    width = m + len(partial_specs)
    dim_off: dict[int, int] = {}
    for ci in sorted(slot, key=lambda c: slot[c]):
        c = elig[ci]
        rc = _lm_clone(c["join"].right)
        kidx = c["kidx"]
        cur2 = P.JoinNode(
            cur2, rc, "inner",
            left_keys=[P.BCol(pkeys[slot[ci]].dtype, slot[ci],
                              f"__lm_k{slot[ci]}")],
            right_keys=[P.BCol(rc.out_dtypes[kidx], kidx,
                               rc.out_names[kidx])],
            residual=None, late_mat=True,
            out_names=list(cur2.out_names) + list(rc.out_names),
            out_dtypes=list(cur2.out_dtypes) + list(rc.out_dtypes))
        dim_off[ci] = width
        width += len(rc.out_names)
    exprs: list[P.BExpr] = []
    for i, (g, cl) in enumerate(zip(agg.group_exprs, gclass)):
        if cl is not None and cl[0] in elig:
            ci, gcol = cl
            idx = dim_off[ci] + (gcol - elig[ci]["off"])
        else:
            idx = plain_slot[i]
        exprs.append(P.BCol(g.dtype, idx, cur2.out_names[idx]))
    for j in range(len(partial_specs)):
        exprs.append(P.BCol(p_dtypes[n + j], m + j, cur2.out_names[m + j]))
    proj = P.ProjectNode(cur2, exprs, out_names=list(p_names),
                         out_dtypes=list(p_dtypes))
    return _final_builder(agg, recipes, p_names, p_dtypes)(proj)


def _late_materialization(plan: P.PlanNode, catalog: "Catalog") -> P.PlanNode:
    """q72-class late materialization: an aggregate over fact⋈dimension whose
    dimension columns are consumed only as group keys regroups by the
    dimension's surrogate join key; the (small) aggregated result then joins
    the dimension to gather attributes, and a merge aggregate over the
    original group values restores the exact answer. The fact-scale random-
    access gathers materializing attribute columns before aggregation — the
    measured 10-25 ns/element cost class dominating query72 — disappear; the
    reference leaves this to Spark, which materializes the joined columns
    literally (nds_power.py:124-134 runs the stock template). GPU SQL
    engines lean on the same strategy (PAPERS.md: Accelerating Presto with
    GPUs; Flare keeps hot loops narrow the same way)."""
    from .streaming import substitute_nodes

    for _ in range(8):
        shared = _lm_shared_nodes(plan)
        mapping: dict[int, P.PlanNode] = {}
        aggs = [nd for nd in P.iter_plan_nodes(plan)
                if isinstance(nd, P.AggregateNode)]
        for a in aggs:
            out = _try_late_mat(a, catalog, shared)
            if out is not None:
                mapping[id(a)] = out
        if not mapping:
            return plan
        # innermost-first: an outer rewrite would freeze the stale original
        # of a nested rewritten aggregate inside its replacement subtree
        for a in aggs:
            if id(a) not in mapping:
                continue
            if any(id(x) in mapping and x is not a
                   for x in P.iter_plan_nodes(a)):
                del mapping[id(a)]
        if not mapping:
            return plan
        segs = getattr(plan, "cte_segments", None)
        plan = substitute_nodes(plan, mapping)
        if segs is not None and not hasattr(plan, "cte_segments"):
            plan.cte_segments = segs  # lint: frozen-exempt (root annotation)
    return plan


def _selfjoin_distinct_rewrite(plan: P.PlanNode) -> P.PlanNode:
    """q95-class exact rewrite: a CTE like

        SELECT ws1.ws_order_number FROM web_sales ws1, web_sales ws2
        WHERE ws1.ws_order_number = ws2.ws_order_number
          AND ws1.ws_warehouse_sk <> ws2.ws_warehouse_sk

    consumed ONLY as a key set (semi/anti-join build sides — IN/EXISTS
    subqueries) is equivalent to

        SELECT ws_order_number FROM web_sales GROUP BY ws_order_number
        HAVING MIN(ws_warehouse_sk) < MAX(ws_warehouse_sk)

    because `exists a pair with different x` == `more than one distinct
    non-null x in the key group` (SQL `<>` is null-rejecting, and MIN/MAX
    skip nulls). The literal self-join expands to |key-group|^2 pairs —
    the single hottest buffer class in the whole stream (the q95 expand
    join's 16M-row gathers spill to host memory); the aggregate form is a
    couple of segment scans. The reference leaves this to Spark, which
    executes the join literally (nds_power runs the stock template) — this
    engine plans it away."""
    refs: dict[int, list] = {}
    for n in P.iter_plan_nodes(plan):
        for f in ("child", "left", "right"):
            sub = getattr(n, f, None)
            if isinstance(sub, P.PlanNode):
                refs.setdefault(id(sub), []).append((n, f))

    # transitively-consumed column sets, from colprune's needed-set pass:
    # a candidate qualifies when its consumers provably read ONLY the key
    # column (other columns — a CTE root kept full-width for segment
    # fingerprints — may exist but are dead)
    from .colprune import _Pruner
    pr = _Pruner()
    pr.collect(plan, set(range(len(plan.out_names))))

    def match(r: P.PlanNode):
        """r -> (scan, key_idx, x_idx, key_pos) when r is the pattern."""
        # walk down pure-BCol projects and ne-filters, composing the map
        # from current output positions back to the join output space
        node = r
        proj_chain: list = []
        filters: list = []
        while True:
            if isinstance(node, P.ProjectNode) and \
                    all(isinstance(e, P.BCol) for e in node.exprs):
                proj_chain.append([e.index for e in node.exprs])
                node = node.child
            elif isinstance(node, P.FilterNode):
                filters.append((node.predicate, len(proj_chain)))
                node = node.child
            else:
                break
        if not isinstance(node, P.JoinNode) or node.kind != "inner" \
                or node.residual is not None:
            return None
        jl, jr = node.left, node.right
        if not (isinstance(jl, P.ScanNode) and isinstance(jr, P.ScanNode)
                and jl.table == jr.table
                and list(jl.columns) == list(jr.columns)):
            return None
        if len(node.left_keys) != 1 or len(node.right_keys) != 1:
            return None
        lk, rk = node.left_keys[0], node.right_keys[0]
        if not (isinstance(lk, P.BCol) and isinstance(rk, P.BCol)
                and lk.index == rk.index):
            return None
        w = len(jl.out_names)
        k = lk.index

        def to_join_space(idx: int, depth: int) -> int:
            # compose through projects BELOW depth (later entries are
            # deeper): proj_chain[depth:] maps r-space -> join-space
            for m in proj_chain[depth:]:
                idx = m[idx]
            return idx

        # consumers must read exactly one column of r, and it must be the
        # join key (dedup-safety licenses multiplicity changes only —
        # value columns must be provably dead)
        consumed = pr.needed.get(id(r))
        if consumed is None or len(consumed) != 1:
            return None
        key_pos = next(iter(consumed))
        if to_join_space(key_pos, 0) not in (k, w + k):
            return None
        # exactly one ne(x_left, x_right) filter over the same column
        if len(filters) != 1:
            return None
        pred, depth = filters[0]
        if not (isinstance(pred, P.BCall) and pred.op == "ne"
                and len(pred.args) == 2
                and all(isinstance(a, P.BCol) for a in pred.args)):
            return None
        i, j = (to_join_space(a.index, depth) for a in pred.args)
        if i > j:
            i, j = j, i
        if j != w + i or i == k:
            return None
        return jl, k, i, key_pos

    # A node is DEDUP-SAFE when every path from it to an output passes
    # through a set-semantics consumer (semi/anti build side, DISTINCT,
    # non-ALL set op) via multiplicity-preserving nodes — then changing its
    # row multiplicities (the rewrite dedups) cannot change any result.
    safe_memo: dict[int, bool] = {}

    def dedup_safe(node: P.PlanNode) -> bool:
        if id(node) in safe_memo:
            return safe_memo[id(node)]
        safe_memo[id(node)] = False          # cycle guard, conservative
        rs = refs.get(id(node))
        if not rs:          # plan root / subquery root: rows reach output
            out = False
        else:
            def ok(p, f):
                if isinstance(p, P.JoinNode) and p.kind in ("semi", "anti") \
                        and f == "right":
                    return True
                if isinstance(p, P.DistinctNode):
                    return True      # output multiplicity is 1 regardless
                if isinstance(p, P.SetOpNode) and not p.all:
                    return True      # set semantics dedup anyway
                if isinstance(p, (P.ProjectNode, P.FilterNode, P.JoinNode)):
                    return dedup_safe(p)
                if isinstance(p, P.SetOpNode) and p.op == "union" and p.all:
                    return dedup_safe(p)
                return False
            out = all(ok(p, f) for p, f in rs)
        safe_memo[id(node)] = out
        return out

    mapping: dict[int, P.PlanNode] = {}
    for r in P.iter_plan_nodes(plan):
        if id(r) in mapping or not dedup_safe(r):
            continue
        m = match(r)
        if m is None:
            continue
        scan, k, x, key_pos = m
        dk = scan.out_dtypes[k]
        dx = scan.out_dtypes[x]
        key_name = r.out_names[key_pos]
        agg = P.AggregateNode(
            child=scan, group_exprs=[P.BCol(dk, k, scan.out_names[k])],
            aggs=[P.AggSpec("min", P.BCol(dx, x), False, "__mn"),
                  P.AggSpec("max", P.BCol(dx, x), False, "__mx")],
            out_names=[key_name, "__mn", "__mx"],
            out_dtypes=[dk, dx, dx])
        # key IS NOT NULL: the literal self-join's equality can never match
        # NULL keys, but GROUP BY keeps the NULL group — without the filter
        # a NOT IN consumer (null-aware anti join) would see a spurious
        # NULL and return zero rows
        flt = P.FilterNode(
            agg, P.BCall("bool", "and", [
                P.BCall("bool", "isnotnull", [P.BCol(dk, 0, key_name)]),
                P.BCall("bool", "lt", [P.BCol(dx, 1, "__mn"),
                                       P.BCol(dx, 2, "__mx")])]),
            out_names=list(agg.out_names), out_dtypes=list(agg.out_dtypes))
        # same width as r: non-key columns are PROVEN dead (consumed set
        # is exactly the key), so they carry typed NULLs
        exprs = [P.BCol(dk, 0, key_name) if i == key_pos
                 else P.BLit(r.out_dtypes[i], None)
                 for i in range(len(r.out_names))]
        proj = P.ProjectNode(flt, exprs, out_names=list(r.out_names),
                             out_dtypes=list(r.out_dtypes))
        mapping[id(r)] = proj
    if not mapping:
        return plan
    from .streaming import substitute_nodes
    return substitute_nodes(plan, mapping)


def _ast_key(node) -> str:
    return repr(node)


class _Binder:
    def __init__(self, planner: Planner, scope: Scope, ctes,
                 outer: Optional[Scope] = None, rewrites=None,
                 subquery_cols=None, allow_outer: bool = True,
                 num_group_cols: Optional[int] = None):
        self.planner = planner
        self.scope = scope
        self.ctes = ctes
        self.outer = outer
        self.rewrites = rewrites or {}   # repr(ast) -> BCol
        self.subquery_cols = subquery_cols or {}  # id(ScalarSubquery) -> BCol
        self.allow_outer = allow_outer
        self.num_group_cols = num_group_cols

    def bind(self, node) -> P.BExpr:
        key = _ast_key(node)
        if key in self.rewrites:
            return self.rewrites[key]
        method = getattr(self, f"_bind_{type(node).__name__.lower()}", None)
        if method is None:
            raise PlanError(f"cannot bind {type(node).__name__}")
        return method(node)

    # -- leaves -------------------------------------------------------------
    def _bind_literal(self, node: A.Literal) -> P.BExpr:
        v = node.value
        if node.type_hint == "date":
            return P.BLit("date", _date_to_days(v))
        if v is None:
            return P.BLit("int", None)
        if isinstance(v, bool):
            return P.BLit("bool", v)
        if isinstance(v, int):
            return P.BLit("int", v)
        if isinstance(v, float):
            return P.BLit("float", v)
        return P.BLit("str", v)

    def _bind_columnref(self, node: A.ColumnRef) -> P.BExpr:
        e = self.scope.resolve_local(node.name, node.qualifier)
        if e is not None:
            return P.BCol(e.dtype, e.index, e.name)
        raise PlanError(f"cannot resolve column "
                        f"{'.'.join(p for p in node.parts)}")

    # -- operators ----------------------------------------------------------
    _OPMAP = {"=": "eq", "<>": "ne", "<": "lt", "<=": "le", ">": "gt",
              ">=": "ge", "+": "add", "-": "sub", "*": "mul", "/": "div",
              "%": "mod", "and": "and", "or": "or", "||": "concat"}

    def _bind_binop(self, node: A.BinOp) -> P.BExpr:
        op = self._OPMAP[node.op]
        # interval arithmetic folds/date ops
        if op in ("add", "sub") and isinstance(node.right, A.Interval):
            return self._bind_date_interval(node, op)
        left = self.bind(node.left)
        right = self.bind(node.right)
        # mul/div/mod keep decimal operands unscaled: dec(s)*int multiplies
        # raw int64s (scale s), div/mod go through float — aligning scales
        # first would only waste int64 range (SF1000 money sums approach it)
        if not (op in ("mul", "div", "mod")
                and (is_dec(left.dtype) or is_dec(right.dtype))):
            left, right = _coerce_pair(left, right)
        if op in ("eq", "ne", "lt", "le", "gt", "ge"):
            return P.BCall("bool", op, [left, right])
        if op in ("and", "or"):
            return P.BCall("bool", op, [left, right])
        if op == "concat":
            return P.BCall("str", "concat", _flatten_concat(left, right))
        dtype = _arith_dtype(op, left, right)
        return P.BCall(dtype, op, [left, right])

    def _bind_date_interval(self, node: A.BinOp, op: str) -> P.BExpr:
        base = self.bind(node.left)
        iv = node.right
        value = iv.value
        if isinstance(value, A.Literal):
            amount = int(value.value)
        elif isinstance(value, A.UnaryOp) and isinstance(value.operand, A.Literal):
            amount = -int(value.operand.value)
        else:
            raise PlanError("interval amount must be literal")
        if op == "sub":
            amount = -amount
        if iv.unit == "day":
            if isinstance(base, P.BLit):
                return P.BLit("date", base.value + amount)
            return P.BCall("date", "add", [base, P.BLit("int", amount)])
        if iv.unit in ("month", "year"):
            months = amount * (12 if iv.unit == "year" else 1)
            if isinstance(base, P.BLit):
                d = _dt.date(1970, 1, 1) + _dt.timedelta(days=base.value)
                total = d.year * 12 + (d.month - 1) + months
                y, m = divmod(total, 12)
                day = min(d.day, _days_in_month(y, m + 1))
                return P.BLit("date", _date_to_days(f"{y:04d}-{m+1:02d}-{day:02d}"))
            raise PlanError("month/year interval on non-literal date")
        raise PlanError(f"unsupported interval unit {iv.unit}")

    def _bind_unaryop(self, node: A.UnaryOp) -> P.BExpr:
        a = self.bind(node.operand)
        if node.op == "not":
            return P.BCall("bool", "not", [a])
        if node.op == "-":
            if isinstance(a, P.BLit) and a.value is not None:
                return P.BLit(a.dtype, -a.value)
            return P.BCall(a.dtype, "neg", [a])
        return a

    def _bind_between(self, node: A.Between) -> P.BExpr:
        e = self.bind(node.expr)
        lo = self.bind(node.low)
        hi = self.bind(node.high)
        e1, lo = _coerce_pair(e, lo)
        e2, hi = _coerce_pair(e, hi)
        ge = P.BCall("bool", "ge", [e1, lo])
        le = P.BCall("bool", "le", [e2, hi])
        both = P.BCall("bool", "and", [ge, le])
        if node.negated:
            return P.BCall("bool", "not", [both])
        return both

    def _bind_inlist(self, node: A.InList) -> P.BExpr:
        e = self.bind(node.expr)
        values = []
        for item in node.items:
            b = _const_fold(self.bind(item))
            if not isinstance(b, P.BLit):
                raise PlanError("IN list values must be literals")
            v = b.value
            if e.dtype == "date" and b.dtype == "str":
                v = _date_to_days(v)
            if is_dec(b.dtype) and v is not None:
                # executors expect LOGICAL in-list values (they re-scale to
                # the probed column's scale); dec BLits hold scaled ints.
                # Dec-typed probes keep exact Decimals (_scaled_in_values
                # round-trips str(Decimal) losslessly); float probes get
                # float (their comparison is float anyway, and jnp.asarray
                # cannot take Decimal objects)
                import decimal
                d = decimal.Decimal(v).scaleb(-dec_scale(b.dtype))
                if d == d.to_integral_value():
                    v = int(d)
                else:
                    v = d if is_dec(e.dtype) else float(d)
            values.append(v)
        call = P.BCall("bool", "in_list", [e], extra=values)
        if node.negated:
            return P.BCall("bool", "not", [call])
        return call

    def _bind_like(self, node: A.Like) -> P.BExpr:
        e = self.bind(node.expr)
        p = self.bind(node.pattern)
        if not isinstance(p, P.BLit):
            raise PlanError("LIKE pattern must be a literal")
        call = P.BCall("bool", "like", [e], extra=p.value)
        if node.negated:
            return P.BCall("bool", "not", [call])
        return call

    def _bind_isnull(self, node: A.IsNull) -> P.BExpr:
        e = self.bind(node.expr)
        return P.BCall("bool", "isnotnull" if node.negated else "isnull", [e])

    def _bind_case(self, node: A.Case) -> P.BExpr:
        args = []
        branches = []
        for cond, val in node.whens:
            if node.operand is not None:
                cond = A.BinOp("=", node.operand, cond)
            args.append(self.bind(cond))
            branches.append(self.bind(val))
        else_b = self.bind(node.else_) if node.else_ is not None \
            else P.BLit("int", None)
        dtype = _common_dtype([b.dtype for b in branches] + [else_b.dtype])
        branches = [_coerce_to(b, dtype) for b in branches]
        else_b = _coerce_to(else_b, dtype)
        flat = []
        for c, b in zip(args, branches):
            flat += [c, b]
        flat.append(else_b)
        return P.BCall(dtype, "case", flat)

    def _bind_cast(self, node: A.Cast) -> P.BExpr:
        e = self.bind(node.expr)
        t = node.to_type
        if t.startswith("decimal") and self.planner.catalog.dec_enabled:
            m = re.match(r"decimal\s*\(\s*\d+\s*,\s*(\d+)\s*\)", t)
            target = dec_dtype(int(m.group(1)) if m else 0)
        elif t.startswith("decimal") or t in ("double", "float", "real"):
            target = "float"
        elif t in ("int", "integer", "bigint", "long", "smallint", "tinyint"):
            target = "int"
        elif t == "date":
            target = "date"
        elif t in ("string", "varchar", "char") or t.startswith(("varchar", "char")):
            target = "str"
        else:
            raise PlanError(f"unsupported cast target {t}")
        if isinstance(e, P.BLit):
            return _fold_cast_literal(e, target)
        return P.BCall(target, "cast", [e])

    def _bind_funccall(self, node: A.FuncCall) -> P.BExpr:
        name = node.name
        if node.over is not None:
            raise PlanError(f"window function {name} outside window planning")
        if name in _AGG_FUNCS or name in _WINDOW_ONLY:
            raise PlanError(f"aggregate {name} in non-aggregate context")
        args = [self.bind(a) for a in node.args]
        if name in ("substr", "substring"):
            start = args[1].value if isinstance(args[1], P.BLit) else None
            length = args[2].value if len(args) > 2 and \
                isinstance(args[2], P.BLit) else None
            if start is None:
                raise PlanError("substr start must be literal")
            return P.BCall("str", "substr", [args[0]], extra=(start, length))
        if name == "coalesce":
            dtype = _common_dtype([a.dtype for a in args])
            return P.BCall(dtype, "coalesce",
                           [_coerce_to(a, dtype) for a in args])
        if name == "abs":
            return P.BCall(args[0].dtype, "abs", args)
        if name == "round":
            digits = args[1].value if len(args) > 1 and \
                isinstance(args[1], P.BLit) else 0
            out = dec_dtype(max(int(digits), 0)) \
                if is_dec(args[0].dtype) else "float"
            return P.BCall(out, "round", [args[0]], extra=digits)
        if name == "nullif":
            if is_dec(args[0].dtype) or is_dec(args[1].dtype):
                a0, a1 = _coerce_pair(args[0], args[1])
                return P.BCall(a0.dtype, "nullif", [a0, a1])
            return P.BCall(args[0].dtype, "nullif", args)
        if name == "grouping":
            e = self.scope.resolve_local("__grouping_id", None)
            if e is None or self.num_group_cols is None:
                raise PlanError("grouping() outside rollup aggregation")
            target = self.rewrites.get(_ast_key(node.args[0]))
            if target is None:
                raise PlanError("grouping() argument is not a group expression")
            gid_col = P.BCol("int", e.index, "__grouping_id")
            # Spark convention: bit 0 is the LAST group expression
            bit = self.num_group_cols - 1 - target.index
            return P.BCall("int", "grouping_bit", [gid_col], extra=bit)
        if name == "concat":
            return P.BCall("str", "concat", args)
        if name in ("upper", "lower"):
            return P.BCall("str", name, args)
        raise PlanError(f"unsupported function {name}")

    def _bind_scalarsubquery(self, node: A.ScalarSubquery) -> P.BExpr:
        if id(node) in self.subquery_cols:
            return self.subquery_cols[id(node)]
        plan = self.planner.plan_query(node.query, outer=None, ctes=self.ctes)
        if len(plan.out_dtypes) != 1:
            raise PlanError("scalar subquery must return one column")
        return P.BScalarSubquery(plan.out_dtypes[0], plan)

    def _bind_exists(self, node: A.Exists):
        if id(node) in self.subquery_cols:
            return self.subquery_cols[id(node)]
        raise PlanError("EXISTS is only supported as a WHERE conjunct")

    def _bind_insubquery(self, node: A.InSubquery):
        if id(node) in self.subquery_cols:
            return self.subquery_cols[id(node)]
        raise PlanError("IN <subquery> is only supported as a WHERE conjunct")

    def _bind_star(self, node: A.Star):
        raise PlanError("* outside SELECT list")

    def _bind_interval(self, node: A.Interval):
        raise PlanError("interval literal outside +/- expression")


# ---------------------------------------------------------------------------
# small AST utilities
# ---------------------------------------------------------------------------

def _children(node):
    if isinstance(node, A.BinOp):
        return (node.left, node.right)
    if isinstance(node, A.UnaryOp):
        return (node.operand,)
    if isinstance(node, A.FuncCall):
        extra = []
        if node.over is not None:
            extra = list(node.over.partition_by) + \
                [si.expr for si in node.over.order_by]
        return tuple(node.args) + tuple(extra)
    if isinstance(node, A.Case):
        out = []
        if node.operand is not None:
            out.append(node.operand)
        for c, v in node.whens:
            out += [c, v]
        if node.else_ is not None:
            out.append(node.else_)
        return tuple(out)
    if isinstance(node, A.Cast):
        return (node.expr,)
    if isinstance(node, A.Between):
        return (node.expr, node.low, node.high)
    if isinstance(node, A.InList):
        return (node.expr, *node.items)
    if isinstance(node, A.InSubquery):
        return (node.expr,)
    if isinstance(node, A.Like):
        return (node.expr, node.pattern)
    if isinstance(node, A.IsNull):
        return (node.expr,)
    if isinstance(node, A.Interval):
        return (node.value,)
    return ()


def _split_and(node) -> list:
    if isinstance(node, A.BinOp) and node.op == "and":
        return _split_and(node.left) + _split_and(node.right)
    return [node]


def _split_or(node) -> list:
    if isinstance(node, A.BinOp) and node.op == "or":
        return _split_or(node.left) + _split_or(node.right)
    return [node]


def _or_implied_conjuncts(conjuncts: list) -> list:
    """Predicates common to every branch of an OR conjunct are implied by it
    and can be lifted to top level: (A ∧ x) ∨ (A ∧ y) ⇒ A. TPC-DS-style
    queries (e.g. reference query13/query48 templates) bury their equi-join
    conditions inside OR blocks; without lifting, those joins plan as cross
    products. The OR itself stays as a residual filter, so this is purely
    an implication — never a rewrite."""
    implied = []
    for c in conjuncts:
        branches = _split_or(c)
        if len(branches) < 2:
            continue
        branch_maps = [{_ast_key(p): p for p in _split_and(b)}
                       for b in branches]
        common = set(branch_maps[0])
        for bm in branch_maps[1:]:
            common &= set(bm)
        implied.extend(branch_maps[0][k] for k in sorted(common))
    return implied


def _and_all(parts):
    if not parts:
        return None
    out = parts[0]
    for p in parts[1:]:
        out = P.BCall("bool", "and", [out, p])
    return out


def _has_subquery(node) -> bool:
    if isinstance(node, (A.ScalarSubquery, A.InSubquery, A.Exists)):
        return True
    return any(_has_subquery(c) for c in _children(node))


def _collect_aggs(node, out: list):
    if isinstance(node, A.FuncCall):
        if node.over is not None:
            # window call itself is not an aggregate, but aggregates may
            # appear inside its args / PARTITION BY / ORDER BY (rank over sum)
            for c in _children(node):
                _collect_aggs(c, out)
            return
        if node.name in _AGG_FUNCS:
            out.append(node)
            return
    if isinstance(node, (A.ScalarSubquery, A.InSubquery, A.Exists)):
        return
    for c in _children(node):
        _collect_aggs(c, out)


def _collect_windows(node, out: list):
    if isinstance(node, A.FuncCall) and node.over is not None:
        out.append(node)
        return
    for c in _children(node):
        _collect_windows(c, out)


def _is_correlated(q: A.Query, outer_scope: Scope, planner, ctes) -> bool:
    """Does the subquery's WHERE reference a column only the outer resolves?"""
    body = q.body
    if not (isinstance(body, A.Select) and body.where is not None):
        return False
    inner_quals = _relation_aliases(body)
    inner_cols = _inner_columns(body, planner, ctes)
    found = [False]

    def visit(x):
        if isinstance(x, A.ColumnRef):
            if x.qualifier is not None:
                if x.qualifier not in inner_quals and \
                        outer_scope.resolve_local(x.name, x.qualifier) is not None:
                    found[0] = True
            elif x.name not in inner_cols and \
                    outer_scope.resolve_local(x.name, None) is not None:
                found[0] = True
        for c in _children(x):
            visit(c)
    visit(body.where)
    return found[0]


def _inner_columns(sel: A.Select, planner, ctes) -> set:
    """Column names visible from the subquery's own FROM relations."""
    cols: set = set()

    def visit(n):
        if isinstance(n, A.TableRef):
            if n.name in ctes:
                cols.update(ctes[n.name].out_names)
            else:
                try:
                    names, _ = planner.catalog.schema(n.name)
                    cols.update(names)
                except PlanError:
                    pass
        elif isinstance(n, A.SubqueryRef):
            pass  # alias-qualified access only; unqualified matches are rare
        elif isinstance(n, A.Join):
            visit(n.left)
            visit(n.right)
    if sel.from_ is not None:
        visit(sel.from_)
    return cols


def _relation_aliases(sel: A.Select) -> set:
    out = set()

    def visit(n):
        if isinstance(n, A.TableRef):
            out.add(n.alias or n.name)
        elif isinstance(n, A.SubqueryRef):
            out.add(n.alias)
        elif isinstance(n, A.Join):
            visit(n.left)
            visit(n.right)
    if sel.from_ is not None:
        visit(sel.from_)
    return out


def _extract_correlation(where, outer_scope, planner, ctes, inner_sel):
    """Split subquery WHERE into correlation equality pairs and inner-only rest.

    Returns ([(outer_ast, inner_ast)], remaining_where_ast).
    """
    if where is None:
        return [], [], None
    inner_quals = _relation_aliases(inner_sel)
    inner_cols = _inner_columns(inner_sel, planner, ctes)

    def side_is_outer(x) -> Optional[bool]:
        """True if expr references outer scope, False if inner, None if unclear."""
        verdict = []

        def visit(y):
            if isinstance(y, A.ColumnRef):
                if y.qualifier is not None:
                    if y.qualifier in inner_quals:
                        verdict.append(False)
                    elif outer_scope.resolve_local(y.name, y.qualifier) is not None:
                        verdict.append(True)
                    else:
                        verdict.append(False)
                else:
                    if y.name in inner_cols:
                        verdict.append(False)
                    elif outer_scope.resolve_local(y.name, None) is not None:
                        verdict.append(True)
                    else:
                        verdict.append(False)
            for c in _children(y):
                visit(c)
        visit(x)
        if not verdict:
            return None
        if all(verdict):
            return True
        if not any(verdict):
            return False
        return None

    corr = []
    mixed = []
    rest = []
    for c in _split_and(where):
        if isinstance(c, A.BinOp) and c.op == "=":
            ls, rs = side_is_outer(c.left), side_is_outer(c.right)
            if ls is True and rs is False:
                corr.append((c.left, c.right))
                continue
            if ls is False and rs is True:
                corr.append((c.right, c.left))
                continue
        # non-extractable conjuncts that still reference the outer scope
        # (e.g. q16's cs1.cs_warehouse_sk <> cs2.cs_warehouse_sk) become
        # residual predicates on the semi/anti join
        if side_is_outer(c) in (True, None):
            mixed.append(c)
        else:
            rest.append(c)
    remaining = None
    for c in rest:
        remaining = c if remaining is None else A.BinOp("and", remaining, c)
    return corr, mixed, remaining


def _substitute_aliases(expr, items):
    """Rewrite bare ColumnRefs naming a select alias into the aliased
    expression (for ORDER BY expressions referencing output aliases)."""
    import dataclasses

    aliases = {it.alias: it.expr for it in items if it.alias}

    def walk(x):
        if isinstance(x, A.ColumnRef) and x.qualifier is None and \
                x.name in aliases:
            return aliases[x.name]
        if dataclasses.is_dataclass(x) and not isinstance(x, type):
            changes = {}
            for f in dataclasses.fields(x):
                v = getattr(x, f.name)
                if isinstance(v, tuple):
                    nv = tuple(walk(e) if dataclasses.is_dataclass(e) else e
                               for e in v)
                    if nv != v:
                        changes[f.name] = nv
                elif isinstance(v, list):
                    nv = [walk(e) if dataclasses.is_dataclass(e) else
                          (tuple(walk(s) if dataclasses.is_dataclass(s) else s
                                 for s in e) if isinstance(e, tuple) else e)
                          for e in v]
                    if nv != v:
                        changes[f.name] = nv
                elif dataclasses.is_dataclass(v) and not isinstance(v, type):
                    nv = walk(v)
                    if nv is not v:
                        changes[f.name] = nv
            return dataclasses.replace(x, **changes) if changes else x
        return x

    return walk(expr)


def _nested_subqueries(node) -> list:
    """Exists/InSubquery nodes anywhere in `node` (the conjunct itself is
    never returned — callers handle the top level); does not descend into
    subquery bodies."""
    out = []

    def visit(x, top):
        if isinstance(x, (A.Exists, A.InSubquery)):
            if not top:
                out.append(x)
            return
        if isinstance(x, A.ScalarSubquery):
            return
        for ch in _children(x):
            visit(ch, False)
    visit(node, True)
    return out


def _trunc_mod(a, b):
    """Truncated (sign-of-dividend) mod, matching the runtime fmod — Python's
    % is floored and diverges on negative operands."""
    r = abs(a) % abs(b)
    return r if a >= 0 else -r


def _const_fold(e: P.BExpr) -> P.BExpr:
    """Fold arithmetic over literals (e.g. the IN-list element [YEAR] + 1
    instantiated as 1999 + 1) into a single literal."""
    if not isinstance(e, P.BCall):
        return e
    ops = {"add": lambda a, b: a + b, "sub": lambda a, b: a - b,
           "mul": lambda a, b: a * b, "neg": lambda a: -a,
           "div": lambda a, b: a / b, "mod": _trunc_mod}
    fn = ops.get(e.op)
    if fn is None:
        return e
    args = [_const_fold(a) for a in e.args]
    if all(isinstance(a, P.BLit) and a.value is not None for a in args):
        if e.dtype == "float" and any(is_dec(a.dtype) for a in args):
            # dec literals carry ALREADY-SCALED ints; a float-typed result
            # (mul/div/mod with a float operand) must fold on descaled values
            # or it comes out 10^scale too large
            args = [_fold_cast_literal(a, "float") if is_dec(a.dtype) else a
                    for a in args]
        try:
            return P.BLit(e.dtype, fn(*[a.value for a in args]))
        except (TypeError, ZeroDivisionError):
            return e
    return e


# -- dtype coercion ----------------------------------------------------------

def _common_dtype(dtypes: list[str]) -> str:
    s = set(dtypes)
    if "str" in s and s - {"str"}:
        non_null = s - {"str"}
        # NULL literals bind as int; treat mixed str/int-null as str
        if non_null <= {"int"}:
            return "str"
    if len(s) == 1:
        return next(iter(s))
    decs = {d for d in s if is_dec(d)}
    if decs:
        rest = s - decs
        if rest <= {"int"}:              # dec + int -> widest decimal scale
            return dec_dtype(max(dec_scale(d) for d in decs))
        if rest <= {"int", "float"}:     # dec + float -> float
            return "float"
        raise PlanError(f"no common type for {sorted(s)}")
    if s <= {"int", "float"}:
        return "float"
    if s <= {"int", "date"}:
        return "date"
    if s <= {"int", "bool"}:
        return "bool"
    if s <= {"int", "str"}:
        return "str"
    if s <= {"int", "float", "date"}:
        return "float"
    raise PlanError(f"no common type for {sorted(s)}")


def _coerce_to(e: P.BExpr, dtype: str) -> P.BExpr:
    if e.dtype == dtype:
        return e
    if isinstance(e, P.BLit):
        if e.value is None:
            return P.BLit(dtype, None)
        return _fold_cast_literal(e, dtype)
    return P.BCall(dtype, "cast", [e])


def _fold_cast_literal(e: P.BLit, target: str) -> P.BLit:
    v = e.value
    if v is None:
        return P.BLit(target, None)
    if target == "date" and isinstance(v, str):
        return P.BLit("date", _date_to_days(v))
    if target == "float":
        if is_dec(e.dtype):
            return P.BLit("float", v / 10 ** dec_scale(e.dtype))
        return P.BLit("float", float(v))
    if target == "int":
        if is_dec(e.dtype):
            # integer truncation toward zero, matching the runtime cast
            # (float division would round above 2^53)
            s = 10 ** dec_scale(e.dtype)
            return P.BLit("int", (1 if v >= 0 else -1) * (abs(int(v)) // s))
        return P.BLit("int", int(v))
    if target == "str":
        return P.BLit("str", str(v))
    if is_dec(target):
        # decN literal value convention: the ALREADY-SCALED integer
        import decimal
        src = decimal.Decimal(v).scaleb(-dec_scale(e.dtype)) \
            if is_dec(e.dtype) else decimal.Decimal(str(v))
        scaled = int(src.scaleb(dec_scale(target)).to_integral_value(
            rounding=decimal.ROUND_HALF_UP))
        return P.BLit(target, scaled)
    return P.BLit(target, v)


def _dec_representable(v, scale: int) -> bool:
    """Is literal v exact at decimal scale (Decimal-based: float math would
    report 1.1*100 != 110)?"""
    import decimal
    d = decimal.Decimal(str(v)).scaleb(scale)
    return d == d.to_integral_value()


def _coerce_pair(a: P.BExpr, b: P.BExpr) -> tuple[P.BExpr, P.BExpr]:
    if a.dtype == b.dtype:
        return a, b
    # date vs string literal
    if a.dtype == "date" and isinstance(b, P.BLit) and b.dtype == "str":
        return a, P.BLit("date", _date_to_days(b.value))
    if b.dtype == "date" and isinstance(a, P.BLit) and a.dtype == "str":
        return P.BLit("date", _date_to_days(a.value)), b
    # decimal alignment: dec vs dec/int stays exact on scaled integers;
    # dec vs float literal folds the literal to the decimal scale when it is
    # exactly representable there, else both sides go to float
    da, db = is_dec(a.dtype), is_dec(b.dtype)
    if da or db:
        if da and db:
            t = dec_dtype(max(dec_scale(a.dtype), dec_scale(b.dtype)))
            return _coerce_to(a, t), _coerce_to(b, t)
        dec_e, other = (a, b) if da else (b, a)
        t = dec_e.dtype
        if other.dtype == "int" or (
                isinstance(other, P.BLit) and other.dtype == "float"
                and other.value is not None
                and _dec_representable(other.value, dec_scale(t))):
            return _coerce_to(a, t), _coerce_to(b, t)
        return _coerce_to(a, "float"), _coerce_to(b, "float")
    # numeric widening
    if {a.dtype, b.dtype} <= {"int", "float"}:
        return _coerce_to(a, "float"), _coerce_to(b, "float")
    if {a.dtype, b.dtype} <= {"int", "date"}:
        return a, b  # date arithmetic/comparison on day numbers
    # string vs numeric literal comparisons: cast literal to string
    if a.dtype == "str" and isinstance(b, P.BLit):
        return a, P.BLit("str", str(b.value))
    if b.dtype == "str" and isinstance(a, P.BLit):
        return P.BLit("str", str(a.value)), b
    # string column vs numeric column: cast string to float
    if a.dtype == "str":
        return P.BCall("float", "cast", [a]), _coerce_to(b, "float")
    if b.dtype == "str":
        return _coerce_to(a, "float"), P.BCall("float", "cast", [b])
    return a, b


def _arith_dtype(op: str, a: P.BExpr, b: P.BExpr) -> str:
    if op == "div":
        return "float"
    if a.dtype == "date" or b.dtype == "date":
        # date +/- int -> date; date - date -> int
        if a.dtype == "date" and b.dtype == "date":
            return "int"
        return "date"
    da, db = is_dec(a.dtype), is_dec(b.dtype)
    if da or db:
        if a.dtype == "float" or b.dtype == "float" or op == "mod":
            return "float"
        if op == "mul":    # scaled-int product: scales add; dec*int keeps s
            return dec_dtype((dec_scale(a.dtype) if da else 0) +
                             (dec_scale(b.dtype) if db else 0))
        # add/sub arrive scale-aligned from _coerce_pair
        return a.dtype if da else b.dtype
    if a.dtype == "float" or b.dtype == "float":
        return "float"
    return "int"


def _flatten_concat(left: P.BExpr, right: P.BExpr) -> list[P.BExpr]:
    parts = []
    for e in (left, right):
        if isinstance(e, P.BCall) and e.op == "concat":
            parts.extend(e.args)
        else:
            parts.append(e)
    return parts


def _col_indices(e: P.BExpr) -> list[int]:
    out = []

    def visit(x):
        if isinstance(x, P.BCol):
            out.append(x.index)
        if isinstance(x, P.BCall):
            for a in x.args:
                visit(a)
    visit(e)
    return out


def _shift(e: P.BExpr, delta: int) -> P.BExpr:
    if isinstance(e, P.BCol):
        return P.BCol(e.dtype, e.index + delta, e.name)
    if isinstance(e, P.BCall):
        return P.BCall(e.dtype, e.op, [_shift(a, delta) for a in e.args],
                       e.extra)
    return e


def _display_name(node) -> str:
    if isinstance(node, A.ColumnRef):
        return node.name
    if isinstance(node, A.FuncCall):
        inner = ", ".join(_display_name(a) for a in node.args) if node.args else ""
        if node.args and isinstance(node.args[0], A.Star):
            inner = "*"
        return f"{node.name}({inner})"
    if isinstance(node, A.Star):
        return "*"
    if isinstance(node, A.Literal):
        return str(node.value)
    if isinstance(node, A.BinOp):
        return f"({_display_name(node.left)} {node.op} {_display_name(node.right)})"
    if isinstance(node, A.Case):
        return "case"
    if isinstance(node, A.Cast):
        return _display_name(node.expr)
    return type(node).__name__.lower()
