"""JAX/XLA columnar SQL engine.

Replaces the reference's Spark/RAPIDS execution layer (the work measured by
nds_power.py / nds_transcode.py) with a TPU-first design:

- columnar tables: device arrays + validity masks; strings dictionary-encoded
  so all relational compute is integer/float math the MXU/VPU can run;
- host does shape discovery (group cardinalities, join sizes), XLA does the
  FLOPs (segment reductions, sort, gather/scatter) — no data-dependent shapes
  inside compiled code;
- multi-chip scaling via jax.sharding over a Mesh with psum/all_gather/
  all_to_all collectives (see nds_tpu.parallel), not executor shuffles.
"""
from .result_cache import ResultCache, ResultCacheConfig
from .session import Session

__all__ = ["Session", "ResultCache", "ResultCacheConfig"]
