"""Out-of-core execution: morsel-streamed scan -> filter/join -> partial agg.

The single-chip answer to "the table does not fit" (SURVEY.md §5 long-context
analog; the reference bounds scans with
spark.sql.files.maxPartitionBytes=2gb chunking + shuffle spill,
power_run_gpu.template SPARK_CONF): when a plan aggregates over ONE large
scan through per-row operators (filters, projections, joins whose build
sides are dimension-sized), the large table streams through the device in
fixed-capacity morsels. Each morsel runs the SAME compiled XLA program
(capacities inflated to the morsel bound, so the schedule holds for every
morsel); per-morsel partial aggregates merge on host, and a final plan
recomputes the query's aggregate output from the partials.

Eligibility is decided on the BOUND plan; ineligible plans (windows,
distinct aggs, stddev, big-scan string payloads, multiple big scans) simply
run the normal in-core path.
"""
from __future__ import annotations

import dataclasses
from dataclasses import replace
from typing import Optional

from . import plan as P
from .plan import (AggregateNode, AggSpec, BCall, BCol, FilterNode, JoinNode,
                   LimitNode, MaterializedNode, PlanNode, ProjectNode,
                   ScanNode, SortNode, walk)

MORSEL_TABLE = "__morsel__"


@dataclasses.dataclass
class StreamingPlan:
    """A rewritten plan pair: per-morsel partial plan + final merge plan."""
    big_table: str                 # source table being streamed
    big_columns: list[str]         # projected columns of the big scan
    partial_plan: PlanNode         # aggregates one morsel (scan = MORSEL_TABLE)
    partial_names: list[str]
    partial_dtypes: list[str]
    build_final: "callable"        # (partials Materialized) -> final PlanNode
    path: list = dataclasses.field(default_factory=list)
    # post-aggregate nodes above the original aggregate (for rebuild_above)


def _path_to_aggregate(plan: PlanNode):
    """Locate the single AggregateNode with only post-agg nodes above it.

    Windows ABOVE the aggregate are allowed (rank-over-aggregated shapes):
    they run in the final phase over the merged partials, which are
    group-cardinality-sized."""
    path = []
    node = plan
    while True:
        if isinstance(node, AggregateNode):
            return path, node
        if isinstance(node, (SortNode, LimitNode, ProjectNode, FilterNode,
                             P.WindowNode)) \
                and not isinstance(node, AggregateNode):
            path.append(node)
            node = node.child
            continue
        return None, None


def _big_scan(sub: PlanNode, est_rows, threshold: int
              ) -> Optional[ScanNode]:
    """The unique streaming-eligible big scan under the aggregate, if any.

    The big scan must sit on the LEFT spine (probe side): every JoinNode on
    the path from the aggregate to it must have the big lineage as `left`
    with an inner/left/semi/anti kind, and all other scans must be small.
    """
    scans = [n for n in walk(sub) if isinstance(n, ScanNode)]
    big = [s for s in scans if est_rows(s.table) > threshold]
    if len(big) != 1:
        return None
    target = big[0]

    def on_left_spine(node) -> bool:
        if node is target:
            return True
        if isinstance(node, (FilterNode, ProjectNode)):
            return on_left_spine(node.child)
        if isinstance(node, JoinNode):
            if node.kind not in ("inner", "left", "semi", "anti"):
                return False
            # the big scan must not hide in the build side
            if any(n is target for n in walk(node.right)):
                return False
            return on_left_spine(node.left)
        return False

    return target if on_left_spine(sub) else None


def _contains_unsupported(sub: PlanNode, big: ScanNode) -> bool:
    for n in walk(sub):
        if isinstance(n, (P.WindowNode, P.DistinctNode, P.SetOpNode,
                          AggregateNode)):
            return True
    # string payloads from the big scan would need per-morsel dictionaries
    # (one compiled program could not be reused); group keys and filters on
    # dimension strings are fine
    for i, dt in enumerate(big.out_dtypes):
        if dt == "str":
            return True
    return False


def try_streaming_plan(plan: PlanNode, est_rows, threshold: int
                       ) -> Optional[StreamingPlan]:
    path, agg = _path_to_aggregate(plan)
    if agg is None:
        return None
    if any(s.distinct for s in agg.aggs):
        return None
    if any(s.func not in ("sum", "count", "count_star", "min", "max", "avg")
           for s in agg.aggs):
        return None
    big = _big_scan(agg.child, est_rows, threshold)
    if big is None or _contains_unsupported(agg.child, big):
        return None
    if any(isinstance(n, MaterializedNode) for n in walk(agg.child)):
        return None

    # ---- partial aggregate: decompose each agg into mergeable pieces ----
    ngroups = len(agg.group_exprs)
    partial_specs: list[AggSpec] = []
    # merge recipe per original agg: list of (piece kind, partial col index)
    recipes: list[tuple[str, list[int]]] = []
    for spec in agg.aggs:
        base = len(partial_specs) + ngroups
        if spec.func == "count_star":
            partial_specs.append(replace(spec, name=f"{spec.name}__cs"))
            recipes.append(("sum_int", [base]))
        elif spec.func == "count":
            partial_specs.append(replace(spec, name=f"{spec.name}__c"))
            recipes.append(("sum_int", [base]))
        elif spec.func in ("min", "max"):
            partial_specs.append(spec)
            recipes.append((spec.func, [base]))
        elif spec.func == "sum":
            partial_specs.append(replace(spec, name=f"{spec.name}__s"))
            partial_specs.append(AggSpec("count", spec.arg, False,
                                         f"{spec.name}__n"))
            recipes.append(("sum_guarded", [base, base + 1]))
        else:  # avg
            partial_specs.append(AggSpec("sum", spec.arg, False,
                                         f"{spec.name}__s"))
            partial_specs.append(AggSpec("count", spec.arg, False,
                                         f"{spec.name}__n"))
            recipes.append(("avg", [base, base + 1]))

    # swap the big scan for the morsel pseudo-table
    def swap(node: PlanNode) -> PlanNode:
        if node is big:
            return replace(node, table=MORSEL_TABLE)
        repl = {}
        for f in ("child", "left", "right"):
            sub = getattr(node, f, None)
            if isinstance(sub, PlanNode):
                repl[f] = swap(sub)
        return replace(node, **repl) if repl else node

    p_names = ([f"g{i}" for i in range(ngroups)] +
               [s.name for s in partial_specs])
    p_dtypes = ([e.dtype for e in agg.group_exprs] +
                [s.dtype for s in partial_specs])
    if agg.rollup:
        # per-prefix partials: the partial aggregate emits every rollup
        # grouping set per morsel (rolled-up cols NULL + __grouping_id),
        # and the merge re-groups on (group cols..., __grouping_id)
        p_names = p_names + ["__grouping_id"]
        p_dtypes = p_dtypes + ["int"]
    partial_plan = AggregateNode(
        child=swap(agg.child), group_exprs=list(agg.group_exprs),
        aggs=partial_specs, rollup=agg.rollup,
        out_names=p_names, out_dtypes=p_dtypes)

    def build_final(partials: MaterializedNode) -> PlanNode:
        """Re-aggregate the unioned partials, then restore A's schema."""
        nmerge = ngroups + (1 if agg.rollup else 0)   # + __grouping_id
        gidx = list(range(ngroups))
        if agg.rollup:
            gidx.append(len(p_names) - 1)
        group_refs = [BCol(p_dtypes[i], i, p_names[i]) for i in gidx]
        merge_specs: list[AggSpec] = []
        for spec, (kind, idxs) in zip(agg.aggs, recipes):
            if kind in ("min", "max"):
                merge_specs.append(AggSpec(
                    kind, BCol(p_dtypes[idxs[0]], idxs[0]), False, spec.name))
            else:
                for j in idxs:
                    merge_specs.append(AggSpec(
                        "sum", BCol(p_dtypes[j], j), False, p_names[j]))
        m_names = ([p_names[i] for i in gidx] +
                   [s.name for s in merge_specs])
        m_dtypes = ([p_dtypes[i] for i in gidx] +
                    [s.dtype for s in merge_specs])
        merged = AggregateNode(child=partials, group_exprs=group_refs,
                               aggs=merge_specs,
                               out_names=m_names, out_dtypes=m_dtypes)
        # project back to A's output schema
        exprs: list = [BCol(m_dtypes[i], i, m_names[i])
                       for i in range(ngroups)]
        col = nmerge
        for spec, (kind, idxs) in zip(agg.aggs, recipes):
            if kind in ("min", "max", "sum_int"):
                exprs.append(BCol(spec.dtype, col))
                col += 1
            elif kind == "sum_guarded":
                # SUM is NULL iff no non-null input existed anywhere
                s_ref = BCol(m_dtypes[col], col)
                n_ref = BCol("int", col + 1)
                cond = BCall("bool", "gt", [n_ref, P.BLit("int", 0)])
                exprs.append(BCall(spec.dtype, "case",
                                   [cond, s_ref, P.BLit(spec.dtype, None)]))
                col += 2
            else:  # avg = total sum / total count (NULL when count == 0)
                s_ref = BCol(m_dtypes[col], col)
                n_ref = BCol("int", col + 1)
                exprs.append(BCall("float", "div", [s_ref, n_ref]))
                col += 2
        if agg.rollup:     # __grouping_id is the LAST output column
            exprs.append(BCol("int", ngroups, "__grouping_id"))
        return ProjectNode(merged, exprs, out_names=list(agg.out_names),
                           out_dtypes=list(agg.out_dtypes))

    return StreamingPlan(big.table, list(big.columns), partial_plan,
                         p_names, p_dtypes, build_final, path)


def rebuild_above(path: list[PlanNode], new_agg_out: PlanNode) -> PlanNode:
    """Re-hang the post-aggregate nodes (sort/limit/having/project) over the
    merged aggregate output."""
    node = new_agg_out
    for parent in reversed(path):
        node = replace(parent, child=node)
    return node


def inflate_schedule(decisions: list, morsel_cap: int) -> list:
    """Round every capacity decision up to the morsel bound so ONE compiled
    program serves every morsel (filters/joins against unique dimension keys
    cannot exceed the morsel row count; a genuine expansion beyond it is
    caught by the schedule check and re-recorded)."""
    return [(kind, max(int(v), morsel_cap) if kind == "cap" else v)
            for kind, v in decisions]
