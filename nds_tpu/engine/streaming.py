"""Out-of-core execution: morsel-streamed scan -> filter/join -> partial agg.

The single-chip answer to "the table does not fit" (SURVEY.md §5 long-context
analog; the reference bounds scans with
spark.sql.files.maxPartitionBytes=2gb chunking + shuffle spill,
power_run_gpu.template SPARK_CONF): when a plan aggregates over ONE large
scan through per-row operators (filters, projections, joins whose build
sides are dimension-sized), the large table streams through the device in
fixed-capacity morsels. Each morsel runs the SAME compiled XLA program
(capacities inflated to the morsel bound, so the schedule holds for every
morsel); per-morsel partial aggregates merge on host, and a final plan
recomputes the query's aggregate output from the partials.

Eligibility is decided on the BOUND plan; ineligible plans (windows,
distinct aggs, stddev, big-scan string payloads, multiple big scans) simply
run the normal in-core path.
"""
from __future__ import annotations

import dataclasses
from dataclasses import replace
from typing import Optional

from . import plan as P
from .plan import (AggregateNode, AggSpec, BCall, BCol, FilterNode, JoinNode,
                   LimitNode, MaterializedNode, PlanNode, ProjectNode,
                   ScanNode, SortNode, walk)

MORSEL_TABLE = "__morsel__"


@dataclasses.dataclass
class StreamingPlan:
    """A rewritten plan pair: per-morsel partial plan + final merge plan."""
    big_table: str                 # source table being streamed
    big_columns: list[str]         # projected columns of the big scan
    partial_plan: PlanNode         # aggregates one morsel (scan = MORSEL_TABLE)
    partial_names: list[str]
    partial_dtypes: list[str]
    build_final: "callable"        # (partials Materialized) -> final PlanNode
    path: list = dataclasses.field(default_factory=list)
    # post-aggregate nodes above the original aggregate (for rebuild_above)


def _path_to_aggregate(plan: PlanNode):
    """Locate the single AggregateNode with only post-agg nodes above it.

    Windows ABOVE the aggregate are allowed (rank-over-aggregated shapes):
    they run in the final phase over the merged partials, which are
    group-cardinality-sized."""
    path = []
    node = plan
    while True:
        if isinstance(node, AggregateNode):
            return path, node
        if isinstance(node, (SortNode, LimitNode, ProjectNode, FilterNode,
                             P.WindowNode)) \
                and not isinstance(node, AggregateNode):
            path.append(node)
            node = node.child
            continue
        return None, None


def _big_scan(sub: PlanNode, est_rows, threshold: int
              ) -> Optional[ScanNode]:
    """The unique streaming-eligible big scan under the aggregate, if any.

    The big scan must sit on the LEFT spine (probe side): every JoinNode on
    the path from the aggregate to it must have the big lineage as `left`
    with an inner/left/semi/anti kind, and all other scans must be small.
    Scans inside expression subqueries count too (iter_plan_nodes): a
    scalar subquery over the big table would otherwise embed a full
    big-table scan in every morsel program.
    """
    scans = [n for n in P.iter_plan_nodes(sub) if isinstance(n, ScanNode)]
    big = [s for s in scans if est_rows(s.table) > threshold]
    if len(big) != 1:
        return None
    target = big[0]

    def on_left_spine(node) -> bool:
        if node is target:
            return True
        if isinstance(node, (FilterNode, ProjectNode)):
            return on_left_spine(node.child)
        if isinstance(node, JoinNode):
            if node.kind not in ("inner", "left", "semi", "anti"):
                return False
            # the big scan must not hide in the build side
            if any(n is target for n in walk(node.right)):
                return False
            return on_left_spine(node.left)
        return False

    return target if on_left_spine(sub) else None


def _contains_unsupported(sub: PlanNode, big: ScanNode) -> bool:
    """Unsupported nodes block streaming ONLY when the big scan flows
    through them (the morsel boundary would split their semantics).
    Window/distinct/setop/aggregate shapes on the small side — q6/q8-class
    scalar-subquery joins over dimensions — execute whole inside every
    morsel program and stay correct."""
    for n in P.iter_plan_nodes(sub):
        if isinstance(n, (P.WindowNode, P.DistinctNode, P.SetOpNode,
                          AggregateNode)) \
                and any(m is big for m in P.iter_plan_nodes(n)):
            return True
    # string payloads from the big scan would need per-morsel dictionaries
    # (one compiled program could not be reused); group keys and filters on
    # dimension strings are fine
    for i, dt in enumerate(big.out_dtypes):
        if dt == "str":
            return True
    return False


def try_streaming_plan(plan: PlanNode, est_rows, threshold: int
                       ) -> Optional[StreamingPlan]:
    """Single top-path streamable aggregate (the original API, kept for
    eligibility tests): a thin view over the generalized _try_job
    machinery — one branch, one big scan, post-agg path preserved."""
    path, agg = _path_to_aggregate(plan)
    if agg is None:
        return None
    job = _try_job(agg, est_rows, threshold)
    if job is None or len(job.branches) != 1 \
            or job.branches[0].big_table is None:
        return None
    b = job.branches[0]
    return StreamingPlan(b.big_table, list(b.big_columns), b.partial_plan,
                         job.partial_names, job.partial_dtypes,
                         job.build_final, path)



# ---------------------------------------------------------------------------
# generalized streaming (round 5): materialize EVERY maximal streamable
# aggregate subtree anywhere in the plan — not just a single top-path
# aggregate — with UNION ALL branch support, so multi-fact-channel queries
# (q2/q4/q5-class ss+cs+ws unions) and aggregates below joins stream too.
# Reference frame: Spark chunks every scan via maxPartitionBytes and spills
# shuffles regardless of plan position (power_run_gpu.template SPARK_CONF).
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class BranchStream:
    """One UNION ALL branch of a streamable aggregate."""
    partial_plan: PlanNode          # partial agg over this branch
    big_table: Optional[str]        # None => in-core one-shot branch
    big_columns: list[str]


@dataclasses.dataclass
class StreamJob:
    """A streamable aggregate subtree: stream each branch, union the
    partials, combine/merge, substitute a MaterializedNode for `agg`.

    For semi/anti joins whose BUILD side holds the big scan (q10/q16-class
    EXISTS subqueries), `agg` is a SYNTHESIZED distinct-key aggregate over
    the join's right side: `join_patch` names the join whose right/
    right_keys get patched to the materialized key set (semi/anti only
    consume the right-side key SET, so dedup preserves semantics, including
    null-aware NOT IN — the NULL group survives the group-by)."""
    agg: AggregateNode
    branches: list[BranchStream]
    partial_names: list[str]
    partial_dtypes: list[str]
    build_final: "callable"        # (partials Materialized) -> final PlanNode
    build_combine: "callable"      # (partials Materialized) -> partial-schema
    # re-aggregation plan for periodic compaction of accumulated partials
    join_patch: Optional[JoinNode] = None


def _mergeable(agg: AggregateNode) -> bool:
    if any(s.distinct for s in agg.aggs):
        return False
    return all(s.func in ("sum", "count", "count_star", "min", "max", "avg")
               for s in agg.aggs)


def _decompose(agg: AggregateNode):
    """Per-branch partial agg specs + merge recipes (shared logic with the
    single-path flow)."""
    ngroups = len(agg.group_exprs)
    partial_specs: list[AggSpec] = []
    recipes: list[tuple[str, list[int]]] = []
    for spec in agg.aggs:
        base = len(partial_specs) + ngroups
        if spec.func == "count_star":
            partial_specs.append(replace(spec, name=f"{spec.name}__cs"))
            recipes.append(("sum_int", [base]))
        elif spec.func == "count":
            partial_specs.append(replace(spec, name=f"{spec.name}__c"))
            recipes.append(("sum_int", [base]))
        elif spec.func in ("min", "max"):
            partial_specs.append(spec)
            recipes.append((spec.func, [base]))
        elif spec.func == "sum":
            partial_specs.append(replace(spec, name=f"{spec.name}__s"))
            partial_specs.append(AggSpec("count", spec.arg, False,
                                         f"{spec.name}__n"))
            recipes.append(("sum_guarded", [base, base + 1]))
        else:  # avg
            partial_specs.append(AggSpec("sum", spec.arg, False,
                                         f"{spec.name}__s"))
            partial_specs.append(AggSpec("count", spec.arg, False,
                                         f"{spec.name}__n"))
            recipes.append(("avg", [base, base + 1]))
    p_names = ([f"g{i}" for i in range(ngroups)] +
               [s.name for s in partial_specs])
    p_dtypes = ([e.dtype for e in agg.group_exprs] +
                [s.dtype for s in partial_specs])
    if agg.rollup:
        p_names = p_names + ["__grouping_id"]
        p_dtypes = p_dtypes + ["int"]
    return partial_specs, recipes, p_names, p_dtypes


def _final_builder(agg: AggregateNode, recipes, p_names, p_dtypes):
    """The merge-plan factory over unioned partials (identical semantics to
    the single-path flow's build_final)."""
    ngroups = len(agg.group_exprs)

    def build_final(partials: MaterializedNode) -> PlanNode:
        nmerge = ngroups + (1 if agg.rollup else 0)
        gidx = list(range(ngroups))
        if agg.rollup:
            gidx.append(len(p_names) - 1)
        group_refs = [BCol(p_dtypes[i], i, p_names[i]) for i in gidx]
        merge_specs: list[AggSpec] = []
        for spec, (kind, idxs) in zip(agg.aggs, recipes):
            if kind in ("min", "max"):
                merge_specs.append(AggSpec(
                    kind, BCol(p_dtypes[idxs[0]], idxs[0]), False, spec.name))
            else:
                for j in idxs:
                    merge_specs.append(AggSpec(
                        "sum", BCol(p_dtypes[j], j), False, p_names[j]))
        m_names = ([p_names[i] for i in gidx] +
                   [s.name for s in merge_specs])
        m_dtypes = ([p_dtypes[i] for i in gidx] +
                    [s.dtype for s in merge_specs])
        merged = AggregateNode(child=partials, group_exprs=group_refs,
                               aggs=merge_specs,
                               out_names=m_names, out_dtypes=m_dtypes)
        exprs: list = [BCol(m_dtypes[i], i, m_names[i])
                       for i in range(ngroups)]
        col = nmerge
        for spec, (kind, idxs) in zip(agg.aggs, recipes):
            if kind in ("min", "max", "sum_int"):
                exprs.append(BCol(spec.dtype, col))
                col += 1
            elif kind == "sum_guarded":
                s_ref = BCol(m_dtypes[col], col)
                n_ref = BCol("int", col + 1)
                cond = BCall("bool", "gt", [n_ref, P.BLit("int", 0)])
                exprs.append(BCall(spec.dtype, "case",
                                   [cond, s_ref, P.BLit(spec.dtype, None)]))
                col += 2
            else:  # avg
                s_ref = BCol(m_dtypes[col], col)
                n_ref = BCol("int", col + 1)
                exprs.append(BCall("float", "div", [s_ref, n_ref]))
                col += 2
        if agg.rollup:
            exprs.append(BCol("int", ngroups, "__grouping_id"))
        return ProjectNode(merged, exprs, out_names=list(agg.out_names),
                           out_dtypes=list(agg.out_dtypes))
    return build_final


def _combine_builder(agg: AggregateNode, recipes, p_names, p_dtypes):
    """Partial-schema-preserving re-aggregation: compacts accumulated
    partials mid-stream (bounds host memory when group cardinality is
    large, e.g. customer-grained q4-class aggregates at SF100). Associative
    and idempotent — safe to apply any number of times before build_final."""
    ngroups = len(agg.group_exprs)

    def build_combine(partials: MaterializedNode) -> PlanNode:
        gidx = list(range(ngroups))
        if agg.rollup:
            gidx.append(len(p_names) - 1)
        group_refs = [BCol(p_dtypes[i], i, p_names[i]) for i in gidx]
        specs: list[AggSpec] = []
        piece_cols = []
        for _spec, (kind, idxs) in zip(agg.aggs, recipes):
            for pos, j in enumerate(idxs):
                func = kind if kind in ("min", "max") else "sum"
                specs.append(AggSpec(func, BCol(p_dtypes[j], j), False,
                                     p_names[j]))
                piece_cols.append(j)
        a_names = [p_names[i] for i in gidx] + [s.name for s in specs]
        a_dtypes = [p_dtypes[i] for i in gidx] + [s.dtype for s in specs]
        merged = AggregateNode(child=partials, group_exprs=group_refs,
                               aggs=specs, out_names=a_names,
                               out_dtypes=a_dtypes)
        # project back into the exact partial column order
        exprs: list = []
        for i in range(len(p_names)):
            if i < ngroups:
                exprs.append(BCol(p_dtypes[i], i, p_names[i]))
            elif agg.rollup and i == len(p_names) - 1:
                exprs.append(BCol("int", ngroups, "__grouping_id"))
            else:
                pos = piece_cols.index(i)
                src = len(gidx) + pos
                exprs.append(BCol(a_dtypes[src], src, p_names[i]))
        return ProjectNode(merged, exprs, out_names=list(p_names),
                           out_dtypes=list(p_dtypes))
    return build_combine


def _union_branches(child: PlanNode) -> list[PlanNode]:
    """Flatten a UNION ALL found on the LEFT spine (through Project/Filter
    nodes and probe sides of joins — the q2/q5 shape is
    agg(join(union(ss,cs,ws), dims))) into per-branch plans with the spine
    cloned atop each branch; [child] when there is no union."""
    spine: list[tuple[PlanNode, str]] = []
    node = child
    while True:
        if isinstance(node, (ProjectNode, FilterNode)):
            spine.append((node, "child"))
            node = node.child
        elif isinstance(node, JoinNode) and node.kind in (
                "inner", "left", "semi", "anti"):
            spine.append((node, "left"))
            node = node.left
        else:
            break
    if not (isinstance(node, P.SetOpNode) and node.op == "union" and node.all):
        return [child]
    branches: list[PlanNode] = []

    def flat(n: PlanNode) -> None:
        if isinstance(n, P.SetOpNode) and n.op == "union" and n.all:
            flat(n.left)
            flat(n.right)
        else:
            branches.append(n)

    flat(node)
    out = []
    for b in branches:
        nb = b
        for parent, field in reversed(spine):
            nb = replace(parent, **{field: nb})
        out.append(nb)
    return out


def _commute_join(join: JoinNode) -> PlanNode:
    """Swap an INNER join's sides (keys swapped, residual remapped) and
    restore the original column order with a Project, so the big scan
    lands on the probe (left) spine."""
    from .colprune import _remap_expr

    wl, wr = len(join.left.out_names), len(join.right.out_names)
    mapping = {i: wr + i for i in range(wl)}
    mapping.update({wl + j: j for j in range(wr)})
    residual = None if join.residual is None else \
        _remap_expr(join.residual, mapping)
    swapped = JoinNode(
        join.right, join.left, "inner",
        left_keys=list(join.right_keys), right_keys=list(join.left_keys),
        residual=residual, null_aware=join.null_aware,
        late_mat=join.late_mat,
        out_names=list(join.right.out_names) + list(join.left.out_names),
        out_dtypes=list(join.right.out_dtypes) + list(join.left.out_dtypes))
    perm = [BCol(join.out_dtypes[i], wr + i, join.out_names[i])
            for i in range(wl)] + \
           [BCol(join.out_dtypes[wl + j], j, join.out_names[wl + j])
            for j in range(wr)]
    return ProjectNode(swapped, perm, out_names=list(join.out_names),
                       out_dtypes=list(join.out_dtypes))


def _rotate_big_left(node: PlanNode, est_rows, threshold: int) -> PlanNode:
    """Canonicalize the probe spine: INNER joins whose BUILD side holds the
    big scan commute (q2-class date_dim-join-union plans), so the
    left-spine rule sees the streamable orientation. Descends Project/
    Filter chains, union branches, and probe sides."""
    def has_big(n: PlanNode) -> bool:
        return any(isinstance(m, ScanNode) and est_rows(m.table) > threshold
                   for m in P.iter_plan_nodes(n))

    if isinstance(node, (ProjectNode, FilterNode)):
        child = _rotate_big_left(node.child, est_rows, threshold)
        return node if child is node.child else replace(node, child=child)
    if isinstance(node, P.SetOpNode) and node.op == "union" and node.all:
        left = _rotate_big_left(node.left, est_rows, threshold)
        right = _rotate_big_left(node.right, est_rows, threshold)
        if left is node.left and right is node.right:
            return node
        return replace(node, left=left, right=right)
    if isinstance(node, JoinNode):
        if node.kind == "inner" and has_big(node.right) \
                and not has_big(node.left):
            return _rotate_big_left(_commute_join(node), est_rows, threshold)
        if node.kind in ("inner", "left", "semi", "anti"):
            left = _rotate_big_left(node.left, est_rows, threshold)
            return node if left is node.left else replace(node, left=left)
    return node


def _swap_scan(plan: PlanNode, big: ScanNode) -> PlanNode:
    def swap(node: PlanNode) -> PlanNode:
        if node is big:
            return replace(node, table=MORSEL_TABLE)
        repl = {}
        for f in ("child", "left", "right"):
            sub = getattr(node, f, None)
            if isinstance(sub, PlanNode):
                repl[f] = swap(sub)
        return replace(node, **repl) if repl else node
    return swap(plan)


def _try_job(agg: AggregateNode, est_rows, threshold: int
             ) -> Optional[StreamJob]:
    if not _mergeable(agg):
        return None
    branches = _union_branches(
        _rotate_big_left(agg.child, est_rows, threshold))
    partial_specs, recipes, p_names, p_dtypes = _decompose(agg)
    bstreams: list[BranchStream] = []
    saw_big = False
    for b in branches:
        if any(isinstance(n, MaterializedNode) for n in P.iter_plan_nodes(b)):
            return None
        bigs = [n for n in P.iter_plan_nodes(b) if isinstance(n, ScanNode)
                and est_rows(n.table) > threshold]
        if not bigs:
            bstreams.append(BranchStream(
                AggregateNode(child=b, group_exprs=list(agg.group_exprs),
                              aggs=list(partial_specs), rollup=agg.rollup,
                              out_names=list(p_names),
                              out_dtypes=list(p_dtypes)),
                None, []))
            continue
        big = _big_scan(b, est_rows, threshold)
        if big is None or _contains_unsupported(b, big):
            return None
        saw_big = True
        bstreams.append(BranchStream(
            AggregateNode(child=_swap_scan(b, big),
                          group_exprs=list(agg.group_exprs),
                          aggs=list(partial_specs), rollup=agg.rollup,
                          out_names=list(p_names), out_dtypes=list(p_dtypes)),
            big.table, list(big.columns)))
    if not saw_big:
        return None
    return StreamJob(agg, bstreams, p_names, p_dtypes,
                     _final_builder(agg, recipes, p_names, p_dtypes),
                     _combine_builder(agg, recipes, p_names, p_dtypes))


# ---------------------------------------------------------------------------
# shared-scan morsel fusion (round 7): all streaming branches of one query
# that scan the same big table share ONE morsel pass. The union of their
# pruned column sets is packed/uploaded once per morsel; each branch's
# partial program reads its subset as zero-copy views (a ProjectNode of
# BCol references over the shared staged buffer — column selection fuses
# into the compiled program, no copies). q9-class plans carry 15 scalar-
# subquery jobs over store_sales: without sharing, the dominant scan +
# upload cost is paid 15 times per query (PERF.md r5 headroom #3; the
# Flare/shared-scan lineage, ISSUE round 7).
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class ScanGroup:
    """The streaming branches of one query that scan the same big table.

    `plans[i]` is members[i]'s partial plan rewritten (fuse_group) to read
    the shared union-column morsel scan; `members[i]` is the (job_index,
    branch_index) it serves. One morsel iterator + one staged upload per
    morsel serves every member. `lanes` is the STATIC per-column upload
    lane spec (device.plan_lanes, chosen once from table-wide column stats
    and held for every morsel of the pass — widths recorded in the plan,
    never decided per morsel, so they cannot cause mid-stream recompiles);
    None = the legacy wide int64 layout (narrow_lanes off)."""
    table: str
    columns: list[str]             # union of member pruned column sets
    dtypes: list[str]
    members: list[tuple]           # (job_index, branch_index)
    plans: list[PlanNode]
    lanes: Optional[tuple] = None
    # encoded execution (device.plan_encodings): per-column wire encoding
    # tags + host codebooks, chosen ONCE per group from cardinality/run
    # stats like the lane spec is from range stats. When set, `lanes`
    # already carries the dict columns' CODE lanes and `plain_lanes` keeps
    # the value-lane spec for bytes-saved accounting / A-B comparison.
    encodings: Optional[tuple] = None
    codebooks: Optional[tuple] = None
    plain_lanes: Optional[tuple] = None

    @property
    def morsel_key(self) -> str:
        """The executor scan-cache key every member's program reads."""
        return MORSEL_TABLE + "//" + ",".join(self.columns)


def set_group_lanes(group: ScanGroup, lanes: Optional[tuple]) -> None:
    """Attach a lane spec to a scan group: recorded on the group (the
    packer's static per-morsel contract) AND on every member plan's morsel
    ScanNode (width metadata the plan verifier checks against column
    stats). Copy-on-write — morsel scans may be shared across members."""
    if lanes is None:
        return
    group.lanes = tuple(lanes)
    for i, p in enumerate(group.plans):
        scan = _morsel_scan(p)
        group.plans[i] = substitute_nodes(
            p, {id(scan): replace(scan, lanes=tuple(lanes))})


def set_group_encodings(group: ScanGroup, encs: tuple, lanes: tuple,
                        codebooks: tuple) -> None:
    """Attach an encoding spec to a scan group (device.plan_encodings
    output): recorded on the group (the packer's static per-morsel
    contract) AND on every member plan's morsel ScanNode (encoding
    metadata the verifier proves against the same cardinality/run stats,
    and which program fingerprints include). `lanes` is the WIRE lane
    spec — dict columns ride their code lane."""
    group.plain_lanes = group.lanes
    group.lanes = tuple(lanes)
    group.encodings = tuple(encs)
    group.codebooks = tuple(codebooks)
    for i, p in enumerate(group.plans):
        scan = _morsel_scan(p)
        group.plans[i] = substitute_nodes(
            p, {id(scan): replace(scan, lanes=tuple(lanes),
                                  encodings=tuple(encs))})


def _morsel_scan(plan: PlanNode) -> ScanNode:
    return next(n for n in P.iter_plan_nodes(plan)
                if isinstance(n, ScanNode) and n.table == MORSEL_TABLE)


def fuse_group(branches: list[BranchStream]
               ) -> tuple[list[str], list[str], list[PlanNode]]:
    """Union the branches' pruned big-scan column sets and rewrite each
    partial plan so its morsel scan reads the UNION with a projection back
    to the branch's subset: every member then resolves against one staged
    device buffer per morsel (one pack + one upload), and the projection is
    zero-copy column selection inside the traced program. A branch already
    reading exactly the union keeps its plan unchanged (the single-branch /
    shared_scan=off case degenerates to the old per-branch behavior)."""
    union: list[str] = []
    dty: dict[str, str] = {}
    scans = []
    for b in branches:
        scan = _morsel_scan(b.partial_plan)
        scans.append(scan)
        for c, d in zip(scan.columns, scan.out_dtypes):
            if c not in dty:
                union.append(c)
                dty[c] = d
    dtypes = [dty[c] for c in union]
    idx = {c: i for i, c in enumerate(union)}
    plans = []
    for b, scan in zip(branches, scans):
        if list(scan.columns) == union:
            plans.append(b.partial_plan)
            continue
        shared = ScanNode(table=MORSEL_TABLE, columns=list(union),
                          out_names=list(union), out_dtypes=list(dtypes))
        view = P.column_view(shared, [idx[c] for c in scan.columns],
                             list(scan.out_names), list(scan.out_dtypes))
        plans.append(substitute_nodes(b.partial_plan, {id(scan): view}))
    return union, dtypes, plans


def plan_scan_groups(jobs: list[StreamJob], shared: bool) -> list[ScanGroup]:
    """Partition every streaming branch of `jobs` into ScanGroups: by big
    table when `shared` (one morsel pass per table per query), one group
    per branch otherwise (the pre-round-7 behavior, kept reachable for A/B
    via shared_scan=False / --no_shared_scan). Branch order inside a group
    is (job, branch) order, so partial-merge order is deterministic."""
    from ..obs.trace import TRACER

    keyed: dict = {}
    order: list = []
    for ji, job in enumerate(jobs):
        for bi, b in enumerate(job.branches):
            if b.big_table is None:
                continue
            key = b.big_table if shared else (ji, bi)
            if key not in keyed:
                keyed[key] = []
                order.append(key)
            keyed[key].append((ji, bi, b))
    groups = []
    with TRACER.span("stream.plan_groups", shared=shared,
                     branches=sum(len(m) for m in keyed.values())):
        for key in order:
            members = keyed[key]
            cols, dtypes, plans = fuse_group([b for _, _, b in members])
            groups.append(ScanGroup(members[0][2].big_table, cols, dtypes,
                                    [(ji, bi) for ji, bi, _ in members],
                                    plans))
    return groups


def verify_groups(groups: list[ScanGroup], col_stats=None,
                  enc_stats=None) -> None:
    """Static verification of shared-scan fused partial plans: fuse_group
    rewrites every member's morsel scan into a union-column view, which is
    a plan-IR transform like any planner pass — a bad column mapping there
    silently serves one branch another branch's columns. With `col_stats`
    (callable table -> {column: (lo, hi)}), the group's upload lane spec is
    additionally proven wide enough for every column's recorded value range
    (a lane too narrow would otherwise only surface as a pack-time
    LaneOverflowError mid-stream); with `enc_stats` (callable
    (table, columns) -> {column: {"distinct": ..., "runs": ...}}), every
    dict/rle encoding is proven against the recorded cardinality/run stats
    the same way (new "encoding" findings). Run by the session when
    EngineConfig.verify_plans == "per-pass" (the groups never flow through
    planner.PassPipeline); raises PlanVerifyError naming the group/member
    as the offending pass."""
    from ..obs.trace import TRACER

    with TRACER.span("stream.verify_groups", groups=len(groups)):
        return _verify_groups(groups, col_stats, enc_stats)


def _verify_groups(groups: list[ScanGroup], col_stats=None,
                   enc_stats=None) -> None:
    from .verify import (PlanVerifyError, check_scan_encodings,
                         check_scan_lanes, verify_plan)

    for gi, g in enumerate(groups):
        for mi, p in enumerate(g.plans):
            findings = verify_plan(p)
            if findings:
                raise PlanVerifyError(
                    findings, f"stream_fusion[group {gi} member {mi}]")
        if g.lanes is not None and col_stats is not None:
            stats = col_stats(g.table)
            findings = check_scan_lanes(
                _morsel_scan(g.plans[0]),
                {c: stats.get(c) for c in g.columns})
            if findings:
                raise PlanVerifyError(findings,
                                      f"narrow_lanes[group {gi}]")
        if g.encodings is not None and enc_stats is not None:
            findings = check_scan_encodings(
                _morsel_scan(g.plans[0]), enc_stats(g.table, g.columns))
            if findings:
                raise PlanVerifyError(findings,
                                      f"encoded_exec[group {gi}]")


def _expr_subplans(node: PlanNode):
    """Plans embedded in this node's EXPRESSIONS (BScalarSubquery) —
    q9-class scalar-subquery aggregates over big scans live there."""
    out: list[PlanNode] = []

    def rec(x) -> None:
        if isinstance(x, P.BScalarSubquery):
            out.append(x.plan)
            return
        if isinstance(x, PlanNode):
            return                    # child plans handled by the visitor
        if dataclasses.is_dataclass(x) and not isinstance(x, type):
            for f in dataclasses.fields(x):
                rec(getattr(x, f.name))
        elif isinstance(x, (list, tuple)):
            for v in x:
                rec(v)

    for f in dataclasses.fields(node):
        if f.name in ("child", "left", "right"):
            continue
        rec(getattr(node, f.name))
    return out


def _try_semi_join_job(join: JoinNode, est_rows, threshold: int
                       ) -> Optional[StreamJob]:
    """Semi/anti join whose RIGHT (build) side holds the big scan: stream a
    synthesized distinct-key aggregate of the right side, then patch the
    join to probe the materialized key set."""
    if join.kind not in ("semi", "anti") or join.residual is not None:
        return None
    if not join.right_keys:
        return None
    bigs = [n for n in P.iter_plan_nodes(join.right) if isinstance(n, ScanNode)
            and est_rows(n.table) > threshold]
    if not bigs:
        return None
    key_names = [f"k{i}" for i in range(len(join.right_keys))]
    key_dtypes = [e.dtype for e in join.right_keys]
    synth = AggregateNode(
        child=join.right, group_exprs=list(join.right_keys),
        aggs=[AggSpec("count_star", None, False, "__n")],
        out_names=key_names + ["__n"], out_dtypes=key_dtypes + ["int"])
    job = _try_job(synth, est_rows, threshold)
    if job is None:
        return None
    job.join_patch = join
    return job


def find_streaming_jobs(plan: PlanNode, est_rows, threshold: int
                        ) -> list[StreamJob]:
    """Every MAXIMAL streamable aggregate subtree in the plan — including
    scalar-subquery plans (q9) and semi/anti-join build sides (q10) —
    pre-order; a qualifying aggregate claims its whole subtree. Shared
    nodes (CTE DAGs) yield one job serving every parent."""
    jobs: list[StreamJob] = []
    seen: set[int] = set()

    def visit(node: PlanNode) -> None:
        if id(node) in seen:
            return
        seen.add(id(node))
        claimed = False
        if isinstance(node, AggregateNode):
            job = _try_job(node, est_rows, threshold)
            if job is not None:
                jobs.append(job)
                claimed = True
        if not claimed and isinstance(node, JoinNode):
            job = _try_semi_join_job(node, est_rows, threshold)
            if job is not None:
                jobs.append(job)
                visit(node.left)      # probe side still gets its chance
                claimed = True
        if not claimed:
            for f in ("child", "left", "right"):
                sub = getattr(node, f, None)
                if isinstance(sub, PlanNode):
                    visit(sub)
        for sub in _expr_subplans(node):
            visit(sub)

    visit(plan)
    return jobs


def substitute_nodes(root: PlanNode, mapping: dict) -> PlanNode:
    """Rebuild `root` with nodes replaced by id. Mapping values are either
    a replacement PlanNode (subtree swap, no descent) or a dict of field
    patches applied AFTER children rebuild (semi-join right-side swap).
    Descends expression-embedded subquery plans too; shared nodes rebuild
    once, preserving DAG sharing."""
    memo: dict[int, PlanNode] = {}

    def rw_any(x):
        if isinstance(x, PlanNode):
            return rw(x)
        if isinstance(x, P.BScalarSubquery):
            p = rw(x.plan)
            return x if p is x.plan else replace(x, plan=p)
        if isinstance(x, MaterializedNode):
            return x
        if dataclasses.is_dataclass(x) and not isinstance(x, type):
            changes = {}
            for f in dataclasses.fields(x):
                v = getattr(x, f.name)
                nv = rw_any(v)
                if nv is not v:
                    changes[f.name] = nv
            return replace(x, **changes) if changes else x
        if isinstance(x, list):
            out = [rw_any(v) for v in x]
            return out if any(a is not b for a, b in zip(out, x)) else x
        if isinstance(x, tuple):
            out = tuple(rw_any(v) for v in x)
            return out if any(a is not b for a, b in zip(out, x)) else x
        return x

    def rw(node: PlanNode) -> PlanNode:
        patch = mapping.get(id(node))
        if isinstance(patch, PlanNode):
            return patch
        if id(node) in memo:
            return memo[id(node)]
        if isinstance(node, MaterializedNode):
            memo[id(node)] = node
            return node
        repl = {}
        for f in dataclasses.fields(node):
            v = getattr(node, f.name)
            nv = rw_any(v)
            if nv is not v:
                repl[f.name] = nv
        out = replace(node, **repl) if repl else node
        if isinstance(patch, dict):
            out = replace(out, **patch)
        memo[id(node)] = out
        return out

    return rw(root)


def rebuild_above(path: list[PlanNode], new_agg_out: PlanNode) -> PlanNode:
    """Re-hang the post-aggregate nodes (sort/limit/having/project) over the
    merged aggregate output."""
    node = new_agg_out
    for parent in reversed(path):
        node = replace(parent, child=node)
    return node


def partition_morsel_rows(num_rows: int, n_shards: int
                          ) -> list[tuple[int, int]]:
    """Contiguous per-replica row spans [(lo, hi), ...] of one morsel for
    sharded morsel execution: ceil-balanced blocks, trailing replicas may
    be empty (a skewed last morsel smaller than the shard count leaves
    whole replicas with zero alive rows — the compiled per-morsel program
    handles the all-dead block like any filtered-empty morsel)."""
    per = -(-num_rows // n_shards) if num_rows else 0
    return [(min(k * per, num_rows), min((k + 1) * per, num_rows))
            for k in range(n_shards)]


def shard_capacity(morsel_rows: int, n_shards: int) -> int:
    """Per-replica padded row capacity: the morsel bound split n ways and
    re-bucketed, so every replica's block is a ladder capacity and the
    row-sharded upload divides the device buffer evenly (total staged
    capacity = shard_capacity * n_shards >= bucket(morsel_rows))."""
    from .jax_backend.device import bucket
    return bucket(-(-bucket(morsel_rows) // n_shards))


def inflate_schedule(decisions: list, morsel_cap: int) -> list:
    """Round every capacity decision up to the morsel bound so ONE compiled
    program serves every morsel (filters/joins against unique dimension keys
    cannot exceed the morsel row count; a genuine expansion beyond it is
    caught by the schedule check and re-recorded)."""
    return [(kind, max(int(v), morsel_cap) if kind == "cap" else v)
            for kind, v in decisions]


def adapt_schedule(decisions: list, morsel_cap: int,
                   observed) -> list:
    """Feedback-driven inflate_schedule (EngineConfig.adaptive_plans):
    each cap decision is clamped to the LARGER of its record-pass actual
    and the feedback store's observed maximum for that decision, instead
    of the morsel bound — the q9-class 0-group aggregate then provisions
    the minimal ladder bucket, not the 32768-row morsel bucket, and every
    downstream gather shrinks with it. ``observed`` is the index-aligned
    per-decision maxima (FeedbackStore.member_caps); None (or a
    length-drifted list — a structurally different schedule) falls back
    to plain morsel-bound inflation. An observed cap is a CEILING HINT:
    a later morsel exceeding it fails the replay's schedule check
    (ReplayMismatch) and re-records eagerly, so under-observation costs a
    re-record, never a wrong answer."""
    if observed is None or len(observed) != len(decisions):
        return inflate_schedule(decisions, morsel_cap)
    return [(kind, max(int(v), int(o)) if kind == "cap" else v)
            for (kind, v), o in zip(decisions, observed)]
