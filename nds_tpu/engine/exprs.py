"""Vectorized evaluation of bound expressions over a Table.

Null semantics follow Spark SQL: three-valued AND/OR, null-propagating
arithmetic/comparisons, divide-by-zero yields NULL. String predicates
(equality, LIKE, IN) are evaluated against the column dictionary on the host
and applied to device-side codes — strings never reach the accelerator.
"""
from __future__ import annotations

import re
from typing import Callable, Optional

import numpy as np

from .column import (_NULL_CODE, Column, Table, dec_dtype, dec_scale, is_dec,
                     merge_dictionaries, phys_np)
from .plan import BCall, BCol, BExpr, BLit, BScalarSubquery

# signature: subquery_eval(plan) -> python scalar (or None)
SubqueryEval = Callable[[object], object]


def evaluate(expr: BExpr, table: Table,
             subquery_eval: Optional[SubqueryEval] = None) -> Column:
    n = table.num_rows
    if isinstance(expr, BCol):
        return table.columns[expr.index]
    if isinstance(expr, BLit):
        return Column.constant(expr.dtype, expr.value, n)
    if isinstance(expr, BScalarSubquery):
        if subquery_eval is None:
            raise RuntimeError("scalar subquery encountered without evaluator")
        value = subquery_eval(expr.plan)
        return Column.constant(expr.dtype, value, n)
    if isinstance(expr, BCall):
        return _call(expr, table, subquery_eval)
    raise TypeError(f"cannot evaluate {type(expr).__name__}")


def _eval_args(expr: BCall, table: Table, sq) -> list[Column]:
    return [evaluate(a, table, sq) for a in expr.args]


def _call(expr: BCall, table: Table, sq) -> Column:
    op = expr.op
    handler = _HANDLERS.get(op)
    if handler is None:
        raise NotImplementedError(f"expression op {op!r}")
    return handler(expr, table, sq)


# -- helpers ----------------------------------------------------------------

def _both_valid(a: Column, b: Column) -> Optional[np.ndarray]:
    if a.valid is None and b.valid is None:
        return None
    return a.validity & b.validity


def _numeric(col: Column) -> np.ndarray:
    return np.asarray(col.data)


def _result_num_dtype(a: Column, b: Column) -> str:
    if a.dtype == "float" or b.dtype == "float":
        return "float"
    if a.dtype == "date" or b.dtype == "date":
        return "date"
    return "int"


def _as_float(col: Column) -> np.ndarray:
    out = np.asarray(col.data, dtype=np.float64)
    if is_dec(col.dtype):
        return out / 10.0 ** dec_scale(col.dtype)
    return out


def _align_strings(a: Column, b: Column) -> tuple[np.ndarray, np.ndarray]:
    """Remap two string columns onto a common dictionary; returns code arrays."""
    _, (ca, cb) = merge_dictionaries([a, b])
    return ca, cb


# -- arithmetic -------------------------------------------------------------

def _arith(op):
    def run(expr: BCall, table: Table, sq) -> Column:
        a, b = _eval_args(expr, table, sq)
        valid = _both_valid(a, b)
        if op == "div":
            da, db = _as_float(a), _as_float(b)
            zero = db == 0
            with np.errstate(divide="ignore", invalid="ignore"):
                out = np.where(zero, np.nan, da / np.where(zero, 1.0, db))
            v = valid if valid is not None else np.ones(len(out), dtype=bool)
            return Column.from_values("float", out, v & ~zero)
        if a.dtype == "float" or b.dtype == "float" or expr.dtype == "float":
            da, db = _as_float(a), _as_float(b)
            out = {"add": np.add, "sub": np.subtract, "mul": np.multiply,
                   "mod": np.fmod}[op](da, db)
            return Column.from_values("float", out, valid)
        da, db = _numeric(a), _numeric(b)
        out = {"add": np.add, "sub": np.subtract, "mul": np.multiply,
               "mod": np.fmod}[op](da.astype(np.int64), db.astype(np.int64))
        if is_dec(expr.dtype):
            # operands arrive scale-aligned (add/sub) or raw (mul: scales
            # add); the scaled-int result is already in the output scale
            return Column.from_values(expr.dtype, out, valid)
        dtype = expr.dtype if expr.dtype in ("int", "date") else "int"
        return Column.from_values(dtype, out, valid)
    return run


def _neg(expr: BCall, table: Table, sq) -> Column:
    a = evaluate(expr.args[0], table, sq)
    return Column.from_values(a.dtype, -np.asarray(a.data), a.valid)


def _ratdiv(which: str):
    """Exact rational order key (planner._exact_rational_keys): "hi" =
    floor(p/q), "lo" = 56 binary fraction digits, matching the jax backend's
    jexprs._ratdiv bit-for-bit so rank ties agree across backends."""
    def run(expr: BCall, table: Table, sq) -> Column:
        a, b = _eval_args(expr, table, sq)
        sa = dec_scale(a.dtype) if is_dec(a.dtype) else 0
        sb = dec_scale(b.dtype) if is_dec(b.dtype) else 0
        p = np.asarray(a.data, dtype=np.int64) * (10 ** sb)
        q = np.asarray(b.data, dtype=np.int64) * (10 ** sa)
        neg = q < 0
        p = np.where(neg, -p, p)
        q = np.where(neg, -q, q)
        bv = _both_valid(a, b)
        valid = (np.ones(len(p), bool) if bv is None else np.asarray(bv)) \
            & (q != 0)
        qs = np.where(q == 0, 1, q)
        hi = p // qs
        if which == "hi":
            return Column.from_values("int", np.where(valid, hi, 0), valid)
        r = p - hi * qs
        lo = np.zeros_like(r)
        for _ in range(8):
            r = r << 7
            d = r // qs
            r = r - d * qs
            lo = (lo << 7) | d
        return Column.from_values("int", np.where(valid, lo, 0), valid)
    return run


# -- comparisons ------------------------------------------------------------

_CMP_FN = {
    "eq": np.equal, "ne": np.not_equal, "lt": np.less,
    "le": np.less_equal, "gt": np.greater, "ge": np.greater_equal,
}


def _compare(op):
    def run(expr: BCall, table: Table, sq) -> Column:
        a, b = _eval_args(expr, table, sq)
        valid = _both_valid(a, b)
        if a.dtype == "str" or b.dtype == "str":
            if op in ("eq", "ne"):
                ca, cb = _align_strings(a, b)
                out = _CMP_FN[op](ca, cb)
            else:
                # inequality: compare decoded values (rank spaces differ per column)
                da, db = a.decode(), b.decode()
                da = np.asarray([x if x is not None else "" for x in da], dtype=str)
                db = np.asarray([x if x is not None else "" for x in db], dtype=str)
                out = _CMP_FN[op](da, db)
            return Column.from_values("bool", out, valid)
        da, db = _numeric(a), _numeric(b)
        out = _CMP_FN[op](da, db)
        return Column.from_values("bool", out, valid)
    return run


# -- boolean ----------------------------------------------------------------

def _and(expr: BCall, table: Table, sq) -> Column:
    a, b = _eval_args(expr, table, sq)
    da = np.asarray(a.data, dtype=bool) & a.validity
    db = np.asarray(b.data, dtype=bool) & b.validity
    false_a = ~np.asarray(a.data, dtype=bool) & a.validity
    false_b = ~np.asarray(b.data, dtype=bool) & b.validity
    out = da & db
    valid = out | false_a | false_b  # definite true or definite false
    return Column.from_values("bool", out, valid)


def _or(expr: BCall, table: Table, sq) -> Column:
    a, b = _eval_args(expr, table, sq)
    true_a = np.asarray(a.data, dtype=bool) & a.validity
    true_b = np.asarray(b.data, dtype=bool) & b.validity
    false_a = ~np.asarray(a.data, dtype=bool) & a.validity
    false_b = ~np.asarray(b.data, dtype=bool) & b.validity
    out = true_a | true_b
    valid = out | (false_a & false_b)
    return Column.from_values("bool", out, valid)


def _not(expr: BCall, table: Table, sq) -> Column:
    a = evaluate(expr.args[0], table, sq)
    return Column.from_values("bool", ~np.asarray(a.data, dtype=bool), a.valid)


def _isnull(expr: BCall, table: Table, sq) -> Column:
    a = evaluate(expr.args[0], table, sq)
    return Column.from_values("bool", ~a.validity, None)


def _isnotnull(expr: BCall, table: Table, sq) -> Column:
    a = evaluate(expr.args[0], table, sq)
    return Column.from_values("bool", a.validity, None)


# -- predicates -------------------------------------------------------------

def _scaled_in_values(values, s: int) -> list[int]:
    """Exact scaled-int IN-list values; literals not representable at scale
    s can never equal a decN column value, so they drop out. Decimal-exact
    (float(v)*10**s carries binary noise: 1.1*100 == 110.00000000000001)."""
    import decimal
    out = []
    for v in values:
        if v is None:
            continue
        d = decimal.Decimal(str(v)).scaleb(s)
        if d == d.to_integral_value():
            out.append(int(d))
    return out


def _in_list(expr: BCall, table: Table, sq) -> Column:
    a = evaluate(expr.args[0], table, sq)
    values = expr.extra  # list of python literals
    has_null = any(v is None for v in values)
    if a.dtype == "str":
        d = a.dictionary if a.dictionary is not None else np.empty(0, dtype=object)
        vset = {v for v in values if v is not None}
        hit = np.asarray([val in vset for val in d], dtype=bool)
        codes = np.asarray(a.data)
        safe = np.where(codes >= 0, codes, 0)
        out = np.where(codes >= 0, hit[safe] if len(hit) else False, False)
    elif is_dec(a.dtype):
        vals = _scaled_in_values(values, dec_scale(a.dtype))
        out = np.isin(np.asarray(a.data), np.asarray(vals, dtype=np.int64))
    else:
        vals = [v for v in values if v is not None]
        out = np.isin(np.asarray(a.data), np.asarray(vals))
    valid = a.validity
    if has_null:
        # x IN (..., NULL): TRUE on match, else NULL (so NOT IN never fires)
        valid = valid & out
    return Column.from_values("bool", out, valid)


def _like_to_regex(pattern: str) -> re.Pattern:
    out = []
    for ch in pattern:
        if ch == "%":
            out.append(".*")
        elif ch == "_":
            out.append(".")
        else:
            out.append(re.escape(ch))
    return re.compile("^" + "".join(out) + "$", re.DOTALL)


def _like(expr: BCall, table: Table, sq) -> Column:
    a = evaluate(expr.args[0], table, sq)
    pattern = _like_to_regex(str(expr.extra))
    if a.dtype != "str":
        raise NotImplementedError("LIKE on non-string column")
    d = a.dictionary if a.dictionary is not None else np.empty(0, dtype=object)
    hit = np.asarray([bool(pattern.match(v)) for v in d], dtype=bool)
    codes = np.asarray(a.data)
    safe = np.where(codes >= 0, codes, 0)
    out = np.where(codes >= 0, hit[safe] if len(hit) else False, False)
    return Column.from_values("bool", out, a.valid)


# -- conditional ------------------------------------------------------------

def _case(expr: BCall, table: Table, sq) -> Column:
    """args: cond1, val1, cond2, val2, ..., else_val (always present)."""
    n = table.num_rows
    pairs = expr.args[:-1]
    else_col = evaluate(expr.args[-1], table, sq)
    result_dtype = expr.dtype
    out = np.array(np.zeros(n), dtype=_phys(result_dtype))
    valid = np.zeros(n, dtype=bool)
    decided = np.zeros(n, dtype=bool)
    dictionary = None
    branch_cols = []
    for i in range(0, len(pairs), 2):
        branch_cols.append(evaluate(pairs[i + 1], table, sq))
    branch_cols.append(else_col)
    if result_dtype == "str":
        merged, codes_list = merge_dictionaries(branch_cols)
        dictionary = merged
        branch_data = codes_list
    else:
        branch_data = [np.asarray(c.data, dtype=_phys(result_dtype)) for c in branch_cols]
    for i in range(0, len(pairs), 2):
        cond = evaluate(pairs[i], table, sq)
        fire = np.asarray(cond.data, dtype=bool) & cond.validity & ~decided
        bi = i // 2
        out[fire] = branch_data[bi][fire]
        valid[fire] = branch_cols[bi].validity[fire]
        decided |= fire
    rest = ~decided
    out[rest] = branch_data[-1][rest]
    valid[rest] = else_col.validity[rest]
    return Column.from_values(result_dtype, out, valid, dictionary)


def _coalesce(expr: BCall, table: Table, sq) -> Column:
    cols = _eval_args(expr, table, sq)
    result_dtype = expr.dtype
    n = table.num_rows
    dictionary = None
    if result_dtype == "str":
        dictionary, datas = merge_dictionaries(cols)
    else:
        datas = [np.asarray(c.data, dtype=_phys(result_dtype)) for c in cols]
    out = np.zeros(n, dtype=_phys(result_dtype))
    valid = np.zeros(n, dtype=bool)
    decided = np.zeros(n, dtype=bool)
    for c, d in zip(cols, datas):
        fire = c.validity & ~decided
        out[fire] = d[fire]
        valid[fire] = True
        decided |= fire
    return Column.from_values(result_dtype, out, valid, dictionary)


# -- casts & scalar functions ----------------------------------------------

def _phys(dtype: str):
    return phys_np(dtype)


def _halfup_rescale(data: np.ndarray, from_scale: int,
                    to_scale: int) -> np.ndarray:
    """Rescale scaled ints, SQL half-up on downscale (sign-symmetric)."""
    if to_scale >= from_scale:
        return data * 10 ** (to_scale - from_scale)
    factor = 10 ** (from_scale - to_scale)
    return np.sign(data) * ((np.abs(data) + factor // 2) // factor)


def _cast(expr: BCall, table: Table, sq) -> Column:
    a = evaluate(expr.args[0], table, sq)
    target = expr.dtype
    if target == a.dtype:
        return a
    if is_dec(target):
        s = dec_scale(target)
        if is_dec(a.dtype):
            out = _halfup_rescale(np.asarray(a.data), dec_scale(a.dtype), s)
            return Column.from_values(target, out, a.valid)
        if a.dtype in ("int", "bool"):
            return Column.from_values(
                target, np.asarray(a.data, dtype=np.int64) * 10 ** s, a.valid)
        if a.dtype == "float":
            d = np.asarray(a.data, dtype=np.float64) * 10.0 ** s
            out = (np.floor(np.abs(d) + 0.5) * np.sign(d)).astype(np.int64)
            return Column.from_values(target, out, a.valid)
        if a.dtype == "str":
            import decimal
            vals = a.decode()
            out = np.zeros(len(a), dtype=np.int64)
            valid = a.validity.copy()
            for i, v in enumerate(vals):
                if v is None:
                    continue
                try:
                    out[i] = int(decimal.Decimal(v).scaleb(s)
                                 .to_integral_value(decimal.ROUND_HALF_UP))
                except decimal.InvalidOperation:
                    valid[i] = False
            return Column.from_values(target, out, valid)
        raise NotImplementedError(f"cast {a.dtype} -> {target}")
    if is_dec(a.dtype):
        s = dec_scale(a.dtype)
        data = np.asarray(a.data)
        if target == "float":
            return Column.from_values(
                "float", data.astype(np.float64) / 10.0 ** s, a.valid)
        if target == "int":  # Spark truncates decimal -> int toward zero
            out = np.sign(data) * (np.abs(data) // 10 ** s)
            return Column.from_values("int", out, a.valid)
        # fall through for "str": decode() yields Decimal objects below
    if target in ("int", "float"):
        if a.dtype == "str":
            vals = a.decode()
            out = np.zeros(len(a), dtype=_phys(target))
            valid = a.validity.copy()
            for i, v in enumerate(vals):
                if v is None:
                    continue
                try:
                    out[i] = int(float(v)) if target == "int" else float(v)
                except ValueError:
                    valid[i] = False
            return Column.from_values(target, out, valid)
        return Column.from_values(target, np.asarray(a.data, dtype=_phys(target)), a.valid)
    if target == "date":
        if a.dtype == "str":
            vals = a.decode()
            out = np.zeros(len(a), dtype=np.int32)
            valid = a.validity.copy()
            for i, v in enumerate(vals):
                if v is None:
                    continue
                try:
                    out[i] = np.datetime64(v, "D").astype(np.int32)
                except ValueError:
                    valid[i] = False
            return Column.from_values("date", out, valid)
        return Column.from_values("date", np.asarray(a.data, dtype=np.int32), a.valid)
    if target == "str":
        vals = a.decode()
        strs = np.asarray([None if v is None else _sql_str(v) for v in vals],
                          dtype=object)
        uniq, codes = np.unique(
            np.asarray([s if s is not None else "" for s in strs]), return_inverse=True)
        return Column.from_values("str", codes.astype(np.int32), a.validity.copy(),
                                  uniq.astype(object))
    raise NotImplementedError(f"cast to {target}")


def _sql_str(v) -> str:
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    import decimal
    if isinstance(v, decimal.Decimal):
        return format(v, "f")    # no scientific notation (Spark cast format)
    return str(v)


def _substr(expr: BCall, table: Table, sq) -> Column:
    a = evaluate(expr.args[0], table, sq)
    start = expr.extra[0]
    length = expr.extra[1]
    d = a.dictionary if a.dictionary is not None else np.empty(0, dtype=object)
    lo = start - 1 if start > 0 else 0
    hi = None if length is None else lo + length
    newd = np.asarray([v[lo:hi] for v in d.astype(str)], dtype=object)
    uniq, remap = np.unique(newd.astype(str), return_inverse=True)
    codes = np.asarray(a.data)
    safe = np.where(codes >= 0, codes, 0)
    out = np.where(codes >= 0,
                   remap[safe] if len(remap) else 0, _NULL_CODE).astype(np.int32)
    return Column.from_values("str", out, a.valid, uniq.astype(object))


def _case_map_str(a: Column, fn) -> Column:
    """Apply a python string transform over the dictionary only."""
    d = a.dictionary if a.dictionary is not None else np.empty(0, dtype=object)
    newd = np.asarray([fn(v) for v in d.astype(str)], dtype=object)
    uniq, remap = np.unique(newd.astype(str), return_inverse=True)
    codes = np.asarray(a.data)
    safe = np.where(codes >= 0, codes, 0)
    out = np.where(codes >= 0,
                   remap[safe] if len(remap) else 0, _NULL_CODE).astype(np.int32)
    return Column.from_values("str", out, a.valid, uniq.astype(object))


def _upper(expr: BCall, table: Table, sq) -> Column:
    return _case_map_str(evaluate(expr.args[0], table, sq), str.upper)


def _lower(expr: BCall, table: Table, sq) -> Column:
    return _case_map_str(evaluate(expr.args[0], table, sq), str.lower)


def _concat(expr: BCall, table: Table, sq) -> Column:
    cols = _eval_args(expr, table, sq)
    parts = []
    valid = None
    for c in cols:
        v = c.validity
        valid = v if valid is None else (valid & v)
        dec = c.decode()
        parts.append(np.asarray(
            ["" if x is None else _sql_str(x) for x in dec], dtype=object))
    joined = parts[0]
    for p in parts[1:]:
        joined = np.asarray([a + b for a, b in zip(joined, p)], dtype=object)
    uniq, codes = np.unique(joined.astype(str), return_inverse=True)
    return Column.from_values("str", codes.astype(np.int32), valid,
                              uniq.astype(object))


def _abs(expr: BCall, table: Table, sq) -> Column:
    a = evaluate(expr.args[0], table, sq)
    return Column.from_values(a.dtype, np.abs(np.asarray(a.data)), a.valid)


def _round(expr: BCall, table: Table, sq) -> Column:
    a = evaluate(expr.args[0], table, sq)
    digits = expr.extra if expr.extra is not None else 0
    if is_dec(a.dtype) and is_dec(expr.dtype):
        # round to `digits` (may be negative: round-to-hundreds), then
        # restore the output scale (clamped at 0 — decN has no negative
        # scale, so round(x,-2) yields dec0 values like 12300)
        out = _halfup_rescale(np.asarray(a.data), dec_scale(a.dtype),
                              int(digits))
        out = out * 10 ** (dec_scale(expr.dtype) - int(digits))
        return Column.from_values(expr.dtype, out, a.valid)
    data = _as_float(a)
    # SQL half-up rounding (numpy rounds half-to-even)
    scale = 10.0 ** digits
    out = np.floor(np.abs(data) * scale + 0.5) / scale * np.sign(data)
    if expr.dtype == "int":
        return Column.from_values("int", out.astype(np.int64), a.valid)
    return Column.from_values("float", out, a.valid)


def _grouping_bit(expr: BCall, table: Table, sq) -> Column:
    a = evaluate(expr.args[0], table, sq)
    bit = int(expr.extra)
    out = (np.asarray(a.data, dtype=np.int64) >> bit) & 1
    return Column.from_values("int", out, a.valid)


def _nullif(expr: BCall, table: Table, sq) -> Column:
    a, b = _eval_args(expr, table, sq)
    # equal and both valid -> null
    if a.dtype == "str" or b.dtype == "str":
        ca, cb = _align_strings(a, b)
        same = ca == cb
    else:
        same = _numeric(a) == _numeric(b)
    same = same & a.validity & b.validity
    return a.with_valid(a.validity & ~same)


_HANDLERS = {
    "add": _arith("add"), "sub": _arith("sub"), "mul": _arith("mul"),
    "div": _arith("div"), "mod": _arith("mod"), "neg": _neg,
    "ratdiv_hi": _ratdiv("hi"), "ratdiv_lo": _ratdiv("lo"),
    "eq": _compare("eq"), "ne": _compare("ne"), "lt": _compare("lt"),
    "le": _compare("le"), "gt": _compare("gt"), "ge": _compare("ge"),
    "and": _and, "or": _or, "not": _not,
    "isnull": _isnull, "isnotnull": _isnotnull,
    "in_list": _in_list, "like": _like,
    "case": _case, "coalesce": _coalesce, "cast": _cast,
    "substr": _substr, "concat": _concat, "abs": _abs, "round": _round,
    "upper": _upper, "lower": _lower,
    "nullif": _nullif, "grouping_bit": _grouping_bit,
}
