"""Columnar data representation.

A Column is a flat physical array plus an optional validity mask. Strings are
dictionary-encoded (int32 codes into a host-side value array) so device-side
relational compute never touches bytes — the TPU analog of the reference's
cuDF string columns on GPU.

Engine logical dtypes:
    "int"    int64 values
    "float"  float64 values (decimals map here under decimal_physical="f64")
    "decN"   scaled int64: value * 10^N stored exactly (decimal_physical=
             "i64"; the TPU-exact decimal story — XLA has no decimal type,
             so SUM/MIN/MAX/compare run on integers, divisions on float.
             Reference keeps DecimalType end-to-end, nds/nds_schema.py:43-47)
    "bool"   bool values
    "date"   int32 days since Unix epoch
    "str"    int32 dictionary codes, `dictionary` holds the values
"""
from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Optional

import numpy as np

_NULL_CODE = -1  # dictionary code reserved for NULL strings

_PHYS_DTYPE = {
    "int": np.int64,
    "float": np.float64,
    "bool": np.bool_,
    "date": np.int32,
    "str": np.int32,
}


def is_dec(dtype: str) -> bool:
    """True for scaled-decimal logical dtypes ("dec0", "dec2", ...)."""
    return dtype.startswith("dec") and dtype[3:].isdigit()


def dec_scale(dtype: str) -> int:
    return int(dtype[3:])


def dec_dtype(scale: int) -> str:
    return f"dec{int(scale)}"


def phys_np(dtype: str):
    """Physical numpy dtype for a logical dtype (decN -> scaled int64)."""
    if is_dec(dtype):
        return np.int64
    return _PHYS_DTYPE[dtype]


@dataclass
class Column:
    dtype: str                      # logical dtype, see module docstring
    data: np.ndarray                # physical values
    valid: Optional[np.ndarray] = None   # bool mask, None == all valid
    dictionary: Optional[np.ndarray] = None  # object array of str, for dtype == "str"

    def __post_init__(self):
        assert self.dtype in _PHYS_DTYPE or is_dec(self.dtype), self.dtype

    def __len__(self) -> int:
        return len(self.data)

    @property
    def validity(self) -> np.ndarray:
        """Materialized validity mask."""
        if self.valid is None:
            return np.ones(len(self.data), dtype=bool)
        return self.valid

    def has_nulls(self) -> bool:
        return self.valid is not None and not bool(self.valid.all())

    def take(self, indices: np.ndarray) -> "Column":
        valid = None if self.valid is None else self.valid[indices]
        return Column(self.dtype, np.asarray(self.data)[indices], valid, self.dictionary)

    def with_valid(self, valid: Optional[np.ndarray]) -> "Column":
        if valid is not None and bool(valid.all()):
            valid = None
        return replace(self, valid=valid)

    def decode(self) -> np.ndarray:
        """Host object array with None for nulls (output materialization only)."""
        v = self.validity
        if is_dec(self.dtype):
            import decimal
            s = dec_scale(self.dtype)
            out = np.empty(len(self), dtype=object)
            data = np.asarray(self.data)
            for i in range(len(self)):
                out[i] = decimal.Decimal(int(data[i])).scaleb(-s) if v[i] \
                    else None
            return out
        if self.dtype == "str":
            out = np.empty(len(self), dtype=object)
            codes = np.asarray(self.data)
            ok = v & (codes >= 0)
            out[~ok] = None
            if self.dictionary is not None and ok.any():
                out[ok] = self.dictionary[codes[ok]]
            return out
        if self.dtype == "date":
            out = np.empty(len(self), dtype=object)
            days = np.asarray(self.data)
            dates = days.astype("datetime64[D]")
            for i in range(len(self)):
                out[i] = dates[i].item() if v[i] else None
            return out
        out = np.asarray(self.data).astype(object)
        out[~v] = None
        return out

    @staticmethod
    def from_values(dtype: str, values: np.ndarray,
                    valid: Optional[np.ndarray] = None,
                    dictionary: Optional[np.ndarray] = None) -> "Column":
        values = np.asarray(values, dtype=phys_np(dtype))
        if valid is not None and bool(valid.all()):
            valid = None
        return Column(dtype, values, valid, dictionary)

    @staticmethod
    def constant(dtype: str, value, n: int,
                 dictionary: Optional[np.ndarray] = None) -> "Column":
        if value is None:
            return Column(dtype, np.zeros(n, dtype=phys_np(dtype)),
                          np.zeros(n, dtype=bool), dictionary)
        if dtype == "str" and dictionary is None:
            dictionary = np.asarray([value], dtype=object)
            value = 0
        if is_dec(dtype) and not isinstance(value, (int, np.integer)):
            # python scalar (e.g. scalar-subquery Decimal result) -> scaled
            import decimal
            value = int(decimal.Decimal(str(value))
                        .scaleb(dec_scale(dtype)).to_integral_value(
                            rounding=decimal.ROUND_HALF_UP))
        return Column(dtype, np.full(n, value, dtype=phys_np(dtype)), None,
                      dictionary)


@dataclass
class Table:
    """A batch of rows: ordered named columns of equal length."""
    names: list[str]
    columns: list[Column]

    def __post_init__(self):
        assert len(self.names) == len(self.columns)

    @property
    def num_rows(self) -> int:
        return len(self.columns[0]) if self.columns else 0

    @property
    def num_columns(self) -> int:
        return len(self.columns)

    def column(self, i: int) -> Column:
        return self.columns[i]

    def take(self, indices: np.ndarray) -> "Table":
        return Table(self.names, [c.take(indices) for c in self.columns])

    def select(self, names: list[str]) -> "Table":
        idx = {n: i for i, n in enumerate(self.names)}
        return Table(list(names), [self.columns[idx[n]] for n in names])

    def slice(self, lo: int, hi: int) -> "Table":
        """Zero-copy row window [lo, hi) (numpy views; sharded morsel
        staging partitions each morsel into per-replica row blocks)."""
        return Table(self.names,
                     [Column(c.dtype, np.asarray(c.data)[lo:hi],
                             None if c.valid is None else c.valid[lo:hi],
                             c.dictionary)
                      for c in self.columns])

    def head(self, n: int) -> "Table":
        if self.num_rows <= n:
            return self
        return Table(self.names, [Column(c.dtype, np.asarray(c.data)[:n],
                                         None if c.valid is None else c.valid[:n],
                                         c.dictionary)
                                  for c in self.columns])

    def to_pylist(self) -> list[tuple]:
        decoded = [c.decode() for c in self.columns]
        return [tuple(d[i] for d in decoded) for i in range(self.num_rows)]

    @staticmethod
    def empty_like(names: list[str], columns: list[Column]) -> "Table":
        idx = np.empty(0, dtype=np.int64)
        return Table(list(names), [c.take(idx) for c in columns])


def concat_columns(cols: list[Column]) -> Column:
    """Concatenate columns of the same logical dtype (dictionary-merging strings)."""
    assert cols, "concat of zero columns"
    dtype = cols[0].dtype
    if dtype == "str":
        merged, remapped = merge_dictionaries(cols)
        data = np.concatenate(remapped)
    else:
        merged = None
        data = np.concatenate([np.asarray(c.data) for c in cols])
    if any(c.valid is not None for c in cols):
        valid = np.concatenate([c.validity for c in cols])
    else:
        valid = None
    return Column.from_values(dtype, data, valid, merged)


def merge_dictionaries(cols: list[Column]) -> tuple[np.ndarray, list[np.ndarray]]:
    """Build a common dictionary for string columns; returns (dict, per-col codes)."""
    value_to_code: dict[str, int] = {}
    remapped: list[np.ndarray] = []
    for c in cols:
        codes = np.asarray(c.data)
        d = c.dictionary if c.dictionary is not None else np.empty(0, dtype=object)
        lut = np.empty(len(d) + 1, dtype=np.int32)
        lut[-1] = _NULL_CODE
        for j, v in enumerate(d):
            if v not in value_to_code:
                value_to_code[v] = len(value_to_code)
            lut[j] = value_to_code[v]
        safe = np.where(codes >= 0, codes, len(d))
        remapped.append(lut[safe])
    merged = np.empty(len(value_to_code), dtype=object)
    for v, j in value_to_code.items():
        merged[j] = v
    return merged, remapped
