"""Static plan-IR verifier: machine-checked invariants between rewrite passes.

The planner is the riskiest layer of the engine — five rewrite passes
(binder typing/coercion, column pruning, self-join distinct rewrite, late
materialization, parameter hoisting) plus shared-scan grouping all transform
one plan IR, and a pass that silently violates an invariant (a dangling
column index, an in-place widening of a shared CTE subtree, a dtype that no
longer matches the binder's declaration) executes into wrong answers or
shape errors far from the cause. Flare-class native SQL compilers live or
die on IR invariants holding between passes (PAPERS.md); this module checks
each plan WITHOUT executing it:

- output-schema/arity consistency per node kind (a JoinNode's output is
  exactly left‖right, a FilterNode is width-preserving, ...), which also
  catches the in-place shared-subtree widening hazard (the parent's stored
  schema no longer matches its mutated child);
- column references: every BCol resolves against its input relation by
  index, dtype, AND (when the reference carries one) name;
- dtype inference agreement: an independent re-implementation of the
  binder's coercion rules (`_common_dtype`, `_arith_dtype`, decimal scale
  arithmetic) re-derives every BCall's dtype from its arguments and compares
  with the declared dtype — double-entry bookkeeping against binder bugs;
- aggregate/window legality: group keys and aggregate arguments bind in the
  child's space, aggregate functions/argument dtypes are legal, and for
  streaming-mergeable aggregates the partial/final decomposition round-trips
  to the aggregate's exact output schema;
- join-key dtype compatibility (a float-vs-int key pair compares IEEE key
  bits against raw integers in the executors — silently empty joins);
- DAG-sharing discipline: `snapshot`/`check_frozen` fingerprint every node
  before a pass and prove nodes surviving the pass (same object identity)
  are structurally unchanged — the exact class of bug `_exact_rational_keys`
  had before it rebuilt chains copy-on-write (ADVICE r5);
- parameter round-trip: `parameterize_plan`/`deparameterize_plan`
  reconstruct a structurally identical plan.

`planner.PassPipeline` runs these checks between passes under
`EngineConfig.verify_plans = off|final|per-pass`; a violation raises
`PlanVerifyError` naming the offending node and the pass that introduced it.
"""
from __future__ import annotations

import dataclasses
from typing import Optional

from . import plan as P
from .column import dec_dtype, dec_scale, is_dec

_SIMPLE_DTYPES = frozenset({"int", "float", "bool", "date", "str"})

# ops the expression evaluators implement (exprs._HANDLERS / jexprs): an op
# outside this set can never execute
_KNOWN_OPS = frozenset({
    "add", "sub", "mul", "div", "mod", "neg", "ratdiv_hi", "ratdiv_lo",
    "eq", "ne", "lt", "le", "gt", "ge", "and", "or", "not",
    "isnull", "isnotnull", "in_list", "like", "case", "coalesce", "cast",
    "substr", "concat", "abs", "round", "upper", "lower", "nullif",
    "grouping_bit",
})

_BOOL_OPS = frozenset({"eq", "ne", "lt", "le", "gt", "ge", "and", "or",
                       "not", "isnull", "isnotnull", "in_list", "like"})

_AGG_FUNCS = frozenset({"sum", "count", "count_star", "avg", "min", "max",
                        "stddev_samp"})
_WINDOW_FUNCS = frozenset({"rank", "dense_rank", "row_number", "sum", "avg",
                           "min", "max", "count", "count_star"})
_JOIN_KINDS = frozenset({"inner", "left", "right", "full", "cross", "semi",
                         "anti"})


@dataclasses.dataclass
class Finding:
    """One invariant violation, anchored to a plan node."""
    node: object            # the offending PlanNode
    label: str              # stable preorder label, e.g. "ProjectNode#4"
    kind: str               # arity | colref | colname | dtype | agg | window
    #                       | joinkey | setop | scan | lane | encoding
    #                       | frozen | params
    message: str

    def __str__(self) -> str:
        return f"[{self.label}] {self.kind}: {self.message}"


class PlanVerifyError(ValueError):
    """A rewrite pass produced (or started from) an invalid plan."""

    def __init__(self, findings: list[Finding], pass_name: str):
        self.findings = findings
        self.pass_name = pass_name
        head = "; ".join(str(f) for f in findings[:3])
        more = f" (+{len(findings) - 3} more)" if len(findings) > 3 else ""
        super().__init__(
            f"plan verification failed after pass {pass_name!r}: "
            f"{len(findings)} finding(s): {head}{more}")


def node_labels(root: P.PlanNode) -> dict[int, str]:
    """Stable preorder labels for every distinct node: 'TypeName#k'. The
    same plan object always labels identically, so errors and tests can
    name nodes without relying on id() values."""
    labels: dict[int, str] = {}
    counts: dict[str, int] = {}
    for n in P.iter_plan_nodes(root):
        t = type(n).__name__
        counts[t] = counts.get(t, 0) + 1
        labels[id(n)] = f"{t}#{counts[t] - 1}"
    return labels


def plan_fingerprint(node, _memo: Optional[dict] = None) -> int:
    """Structural fingerprint of a plan/expression subtree, memoized on
    object identity so shared-CTE DAGs hash in linear time. An int hash
    (not cryptographic): two structurally identical trees always agree;
    disagreement proves a structural difference within this process.
    Identity-hashes MaterializedNode payloads (their Tables hold data,
    not structure)."""
    memo: dict[int, int] = _memo if _memo is not None else {}

    def rec(x) -> int:
        if isinstance(x, (str, int, float, bool)) or x is None:
            return hash((type(x).__name__, x))
        if isinstance(x, (list, tuple)):
            return hash(tuple(map(rec, x)))
        if dataclasses.is_dataclass(x) and not isinstance(x, type):
            got = memo.get(id(x))
            if got is not None:
                return got
            if isinstance(x, P.MaterializedNode):
                out = hash(("mat", id(x)))
            else:
                out = hash((type(x).__name__,) + tuple(
                    rec(getattr(x, name)) for name in P.type_fields(x)))
            memo[id(x)] = out
            return out
        return hash(repr(x))

    return rec(node)


def snapshot(root: P.PlanNode) -> dict[int, tuple]:
    """Per-node structural fingerprints BEFORE a rewrite pass, keyed by
    object identity — input to check_frozen. Holds a reference to each
    node: a pass may drop subtrees, and a recycled id of a freed node
    colliding with a new node would otherwise corrupt the comparison."""
    return frozen_scan(root, None)[1]


def frozen_scan(root: P.PlanNode, before: Optional[dict],
                labels: Optional[dict[int, str]] = None
                ) -> tuple[list[Finding], dict[int, tuple]]:
    """One fingerprint walk doing double duty: compare surviving nodes
    against `before` (None = first scan, nothing to compare) AND return the
    new plan's own snapshot, so a pass pipeline pays ONE walk per pass
    instead of a snapshot walk plus a check walk.

    Copy-on-write passes must REPLACE nodes, never mutate them — a shared
    subtree widened in place shifts positional bindings for every other
    consumer. Reports the DEEPEST mutated node(s): an ancestor's
    fingerprint changes whenever a descendant's does, so only nodes with no
    mutated surviving plan-child are named."""
    memo: dict[int, int] = {}
    after: dict[int, tuple] = {}
    mutated: dict[int, P.PlanNode] = {}
    for n in P.iter_plan_nodes(root):
        fp = plan_fingerprint(n, memo)
        after[id(n)] = (fp, n)
        old = before.get(id(n)) if before is not None else None
        if old is not None and old[1] is n and fp != old[0]:
            mutated[id(n)] = n
    out: list[Finding] = []
    for n in mutated.values():
        subs = [getattr(n, f, None) for f in ("child", "left", "right")]
        if any(isinstance(s, P.PlanNode) and id(s) in mutated for s in subs):
            continue
        out.append(Finding(n, "", "frozen",
                           "node mutated in place by a rewrite pass "
                           "(shared subtrees are structurally frozen; "
                           "rebuild copy-on-write instead)"))
    _fill_labels(out, root, labels)
    return out, after


def check_frozen(root: P.PlanNode, before: dict[int, tuple],
                 labels: Optional[dict[int, str]] = None) -> list[Finding]:
    """Findings-only view of frozen_scan against a prior snapshot()."""
    return frozen_scan(root, before, labels)[0]


def _fill_labels(findings: list[Finding], root: P.PlanNode,
                 labels: Optional[dict[int, str]]) -> None:
    """Assign node labels AFTER checking: findings are the rare case, so
    the labeling walk is deferred until one exists."""
    if not findings:
        return
    if labels is None:
        labels = node_labels(root)
    for f in findings:
        if not f.label:
            f.label = labels.get(id(f.node), type(f.node).__name__)


# ---------------------------------------------------------------------------
# dtype rules — an independent re-implementation of the binder's coercion
# conventions (planner._arith_dtype / _common_dtype / _coerce_pair)
# ---------------------------------------------------------------------------

def _dtype_ok(dtype: str) -> bool:
    return dtype in _SIMPLE_DTYPES or is_dec(dtype)


def _numeric(dtype: str) -> bool:
    return dtype in ("int", "float") or is_dec(dtype)


def _comparable(a: str, b: str) -> bool:
    """May two dtypes meet in a comparison? Lenient where the executors are
    (mixed numerics compare fine), strict where they are not: a string can
    only meet a string, and two decimals must share a scale (their physical
    values are scale-dependent integers)."""
    if a == b:
        return True
    if "str" in (a, b):
        return False
    if is_dec(a) and is_dec(b):
        return dec_scale(a) == dec_scale(b)
    return True


def _join_key_ok(a: str, b: str) -> bool:
    """Equi-join keys factorize through ops.key_array into one int64 space:
    float keys map to IEEE order-preserving bit patterns, int/date keys to
    raw values, decimals to scaled integers. Mixed representations compare
    garbage, so key pairs must agree on representation."""
    if a == b:
        return True
    if {a, b} <= {"int", "date"}:
        return True        # both raw integer day numbers / surrogate keys
    if is_dec(a) and is_dec(b):
        return dec_scale(a) == dec_scale(b)
    return False


def _arith_result(op: str, a: str, b: str) -> Optional[set[str]]:
    """Acceptable result dtypes of a binary arithmetic op, or None when the
    operand pair itself is illegal. Mirrors planner._arith_dtype."""
    if "str" in (a, b) or "bool" in (a, b):
        return None
    if op == "div":
        return {"float"}
    if a == "date" or b == "date":
        if a == "date" and b == "date":
            return {"int"}
        if "float" in (a, b) or is_dec(a) or is_dec(b):
            return None
        return {"date"}
    da, db = is_dec(a), is_dec(b)
    if da or db:
        if a == "float" or b == "float" or op == "mod":
            return {"float"}
        if op == "mul":
            return {dec_dtype((dec_scale(a) if da else 0) +
                              (dec_scale(b) if db else 0))}
        # add/sub: operands must arrive scale-aligned (dec vs dec) or be
        # dec vs int folded by the binder; result keeps the dec scale
        if da and db and dec_scale(a) != dec_scale(b):
            return None
        return {a if da else b}
    if a == "float" or b == "float":
        return {"float"}
    return {"int"}


def _check_call(e: P.BCall, add) -> None:
    """Op-specific dtype agreement for one BCall (args already checked)."""
    op = e.op
    a = [x.dtype for x in e.args]
    if op not in _KNOWN_OPS:
        add("dtype", f"unknown op {op!r}")
        return
    if op in _BOOL_OPS and e.dtype != "bool":
        add("dtype", f"{op} declares {e.dtype!r}, expected 'bool'")
    if op in ("eq", "ne", "lt", "le", "gt", "ge"):
        if len(a) == 2 and not _comparable(a[0], a[1]):
            add("dtype", f"{op} over incomparable dtypes {a[0]!r}/{a[1]!r}")
    elif op in ("and", "or", "not"):
        for d in a:
            if d != "bool":
                add("dtype", f"{op} argument dtype {d!r}, expected 'bool'")
    elif op == "like":
        if a and a[0] != "str":
            add("dtype", f"like over non-string dtype {a[0]!r}")
    elif op in ("add", "sub", "mul", "div", "mod"):
        if len(a) == 2:
            ok = _arith_result(op, a[0], a[1])
            if ok is None:
                add("dtype", f"{op} over illegal dtypes {a[0]!r}/{a[1]!r}")
            elif e.dtype not in ok:
                add("dtype", f"{op}({a[0]}, {a[1]}) declares {e.dtype!r}, "
                             f"expected one of {sorted(ok)}")
    elif op in ("neg", "abs"):
        if a and e.dtype != a[0]:
            add("dtype", f"{op} declares {e.dtype!r} != arg {a[0]!r}")
        if a and not _numeric(a[0]):
            add("dtype", f"{op} over non-numeric dtype {a[0]!r}")
    elif op in ("ratdiv_hi", "ratdiv_lo"):
        if e.dtype != "int":
            add("dtype", f"{op} declares {e.dtype!r}, expected 'int'")
    elif op == "case":
        if len(e.args) % 2 == 0:
            add("dtype", f"case with even arg count {len(e.args)}")
        else:
            for i in range(0, len(e.args) - 1, 2):
                if a[i] != "bool":
                    add("dtype", f"case condition {i // 2} dtype {a[i]!r}, "
                                 "expected 'bool'")
            for i in list(range(1, len(e.args) - 1, 2)) + [len(e.args) - 1]:
                if a[i] != e.dtype:
                    add("dtype", f"case branch dtype {a[i]!r} != declared "
                                 f"{e.dtype!r}")
    elif op == "coalesce":
        for d in a:
            if d != e.dtype:
                add("dtype", f"coalesce argument dtype {d!r} != declared "
                             f"{e.dtype!r}")
    elif op == "nullif":
        if a and e.dtype != a[0]:
            add("dtype", f"nullif declares {e.dtype!r} != arg {a[0]!r}")
    elif op in ("substr", "concat", "upper", "lower"):
        if e.dtype != "str":
            add("dtype", f"{op} declares {e.dtype!r}, expected 'str'")
    elif op == "round":
        if e.dtype != "float" and not is_dec(e.dtype):
            add("dtype", f"round declares {e.dtype!r}, expected float/dec")
    elif op == "grouping_bit":
        if e.dtype != "int":
            add("dtype", f"grouping_bit declares {e.dtype!r}, expected 'int'")
    # cast/isnull/isnotnull/in_list: declared dtype is the contract itself


class _Verifier:
    def __init__(self, catalog=None):
        self.catalog = catalog
        self.findings: list[Finding] = []

    def _add(self, node, kind: str, message: str) -> None:
        # labels are filled in bulk by verify_plan iff findings exist
        self.findings.append(Finding(node, "", kind, message))

    # -- expressions --------------------------------------------------------
    def _expr(self, node, e, names: list[str], dtypes: list[str],
              where: str) -> None:
        """Check one expression bound against the input schema
        (names/dtypes); `where` situates the message (predicate, key, ...)."""
        if isinstance(e, P.BCol):
            if not (0 <= e.index < len(dtypes)):
                self._add(node, "colref",
                          f"{where}: BCol index {e.index} out of range "
                          f"(input width {len(dtypes)})")
                return
            if e.dtype != dtypes[e.index]:
                self._add(node, "dtype",
                          f"{where}: BCol #{e.index} declares {e.dtype!r} "
                          f"but input column "
                          f"{names[e.index]!r} is {dtypes[e.index]!r}")
            if e.name and e.name != names[e.index]:
                self._add(node, "colname",
                          f"{where}: BCol #{e.index} named {e.name!r} but "
                          f"input column is {names[e.index]!r}")
            return
        if isinstance(e, P.BLit):
            if not _dtype_ok(e.dtype):
                self._add(node, "dtype",
                          f"{where}: literal dtype {e.dtype!r} unknown")
            return
        if isinstance(e, P.BParam):
            if not _dtype_ok(e.dtype):
                self._add(node, "dtype",
                          f"{where}: param dtype {e.dtype!r} unknown")
            return
        if isinstance(e, P.BScalarSubquery):
            # the subplan itself is verified by the node sweep
            # (iter_plan_nodes descends expression-embedded plans)
            w = len(e.plan.out_dtypes)
            if w != 1:
                self._add(node, "arity",
                          f"{where}: scalar subquery returns {w} columns")
            elif e.dtype != e.plan.out_dtypes[0]:
                self._add(node, "dtype",
                          f"{where}: scalar subquery declares {e.dtype!r} "
                          f"but plan yields {e.plan.out_dtypes[0]!r}")
            return
        if isinstance(e, P.BCall):
            for arg in e.args:
                self._expr(node, arg, names, dtypes, where)
            if isinstance(e.extra, list):    # in_list param slots
                for v in e.extra:
                    if isinstance(v, P.BParam) and not _dtype_ok(v.dtype):
                        self._add(node, "dtype",
                                  f"{where}: in_list param dtype "
                                  f"{v.dtype!r} unknown")
            _check_call(e, lambda kind, msg: self._add(
                node, kind, f"{where}: {msg}"))
            return
        self._add(node, "dtype",
                  f"{where}: unexpected expression {type(e).__name__}")

    # -- nodes --------------------------------------------------------------
    def check_node(self, n: P.PlanNode) -> None:
        if len(n.out_names) != len(n.out_dtypes):
            self._add(n, "arity",
                      f"{len(n.out_names)} names vs "
                      f"{len(n.out_dtypes)} dtypes")
            return
        for d in n.out_dtypes:
            if not _dtype_ok(d):
                self._add(n, "dtype", f"output dtype {d!r} unknown")
        w = len(n.out_names)
        meth = getattr(self, "_chk_" + type(n).__name__, None)
        if meth is not None:
            meth(n, w)

    def _require_passthrough(self, n, w: int) -> None:
        c = n.child
        if w != len(c.out_names):
            self._add(n, "arity",
                      f"width {w} != child width {len(c.out_names)} "
                      "(width-preserving node)")
            return
        if list(n.out_dtypes) != list(c.out_dtypes):
            self._add(n, "dtype", "output dtypes diverge from child's "
                                  "(width-preserving node)")

    def _chk_ScanNode(self, n: P.ScanNode, w: int) -> None:
        from .streaming import MORSEL_TABLE  # lazy: streaming is heavier
        if len(n.columns) != w:
            self._add(n, "arity",
                      f"{len(n.columns)} physical columns vs width {w}")
            return
        if list(n.out_names) != list(n.columns):
            self._add(n, "scan", "out_names diverge from physical columns")
        self._chk_lanes(n)
        if self.catalog is None or n.table.startswith(MORSEL_TABLE):
            return
        try:
            names, dtypes = self.catalog.schema(n.table)
        except Exception:
            self._add(n, "scan", f"unknown table {n.table!r}")
            return
        pos = {c: i for i, c in enumerate(names)}
        for c, d in zip(n.columns, n.out_dtypes):
            if c not in pos:
                self._add(n, "scan",
                          f"column {c!r} not in table {n.table!r}")
            elif dtypes[pos[c]] != d:
                self._add(n, "dtype",
                          f"column {n.table}.{c} is {dtypes[pos[c]]!r} in "
                          f"the catalog but scans as {d!r}")

    def _chk_lanes(self, n: P.ScanNode) -> None:
        """Width metadata legality: every declared upload lane must be able
        to carry its column's logical dtype at all, and (when the catalog
        records value-range stats) be wide enough for the column's actual
        range — a too-narrow lane would truncate values on the wire.
        Dict-encoded columns carry their CODE lane instead: value-range
        legality does not apply (codes are bounded by cardinality, checked
        by _chk_encodings), but the code lane must hold the declared
        cardinality."""
        if n.lanes is None:
            return
        from .jax_backend.device import lane_legal
        if len(n.lanes) != len(n.columns):
            self._add(n, "lane",
                      f"{len(n.lanes)} lanes vs {len(n.columns)} columns")
            return
        self._chk_encodings(n)
        encs = n.encodings or ("plain",) * len(n.columns)
        if len(encs) != len(n.columns):
            return                    # arity finding already added
        for c, d, lane, enc in zip(n.columns, n.out_dtypes, n.lanes, encs):
            if isinstance(enc, tuple) and enc[0] == "dict":
                continue              # code lane: legality is card-based
            if not lane_legal(lane, d):
                self._add(n, "lane",
                          f"column {c!r}: lane {lane!r} cannot carry "
                          f"dtype {d!r}")
        from .streaming import MORSEL_TABLE
        stats_of = getattr(self.catalog, "col_stats", None)
        if stats_of is not None and not n.table.startswith(MORSEL_TABLE):
            self.findings.extend(_lane_stat_findings(n, stats_of(n.table),
                                                     n.encodings))

    def _chk_encodings(self, n: P.ScanNode) -> None:
        """Encoding metadata legality (static, stats-free): tags well-
        formed, dict only on dictionary-capable dtypes with a code lane
        wide enough for the declared cardinality, rle never on bit-packed
        bool lanes."""
        if n.encodings is None:
            return
        if len(n.encodings) != len(n.columns):
            self._add(n, "encoding",
                      f"{len(n.encodings)} encodings vs "
                      f"{len(n.columns)} columns")
            return
        from .jax_backend.device import _LANE_BOUNDS
        for c, d, lane, enc in zip(n.columns, n.out_dtypes, n.lanes,
                                   n.encodings):
            if enc == "plain":
                continue
            if not (isinstance(enc, tuple) and len(enc) == 2
                    and enc[0] in ("dict", "rle")):
                self._add(n, "encoding",
                          f"column {c!r}: malformed encoding tag {enc!r}")
                continue
            if d in ("str", "bool", "float") and enc[0] == "dict":
                self._add(n, "encoding",
                          f"column {c!r}: dict encoding illegal for "
                          f"dtype {d!r}")
            if enc[0] == "dict":
                bounds = _LANE_BOUNDS.get(lane)
                if bounds is None or int(enc[1]) > bounds[1] + 1:
                    self._add(n, "encoding",
                              f"column {c!r}: cardinality {enc[1]} "
                              f"overflows code lane {lane!r}")
            if enc[0] == "rle":
                if lane == "b1":
                    self._add(n, "encoding",
                              f"column {c!r}: rle illegal on the "
                              "bit-packed bool lane")
                elif int(enc[1]) < 1:
                    self._add(n, "encoding",
                              f"column {c!r}: rle runs bound {enc[1]} "
                              "must be positive")

    def _chk_FilterNode(self, n: P.FilterNode, w: int) -> None:
        self._require_passthrough(n, w)
        c = n.child
        self._expr(n, n.predicate, c.out_names, c.out_dtypes, "predicate")
        if n.predicate.dtype != "bool":
            self._add(n, "dtype",
                      f"predicate dtype {n.predicate.dtype!r}, "
                      "expected 'bool'")

    def _chk_ProjectNode(self, n: P.ProjectNode, w: int) -> None:
        if len(n.exprs) != w:
            self._add(n, "arity", f"{len(n.exprs)} exprs vs width {w}")
            return
        c = n.child
        for i, e in enumerate(n.exprs):
            self._expr(n, e, c.out_names, c.out_dtypes, f"expr {i}")
            if e.dtype != n.out_dtypes[i]:
                self._add(n, "dtype",
                          f"expr {i} ({n.out_names[i]!r}) has dtype "
                          f"{e.dtype!r} but output declares "
                          f"{n.out_dtypes[i]!r}")

    def _chk_JoinNode(self, n: P.JoinNode, w: int) -> None:
        lw, rw = len(n.left.out_names), len(n.right.out_names)
        if n.kind not in _JOIN_KINDS:
            self._add(n, "arity", f"unknown join kind {n.kind!r}")
        if n.kind in ("semi", "anti"):
            if w != lw or list(n.out_dtypes) != list(n.left.out_dtypes):
                self._add(n, "arity",
                          f"{n.kind} join output must equal its left "
                          f"schema (width {w} vs {lw})")
        else:
            if w != lw + rw:
                self._add(n, "arity",
                          f"join width {w} != left {lw} + right {rw}")
            elif list(n.out_dtypes) != \
                    list(n.left.out_dtypes) + list(n.right.out_dtypes):
                self._add(n, "dtype",
                          "join output dtypes diverge from left‖right")
        if n.null_aware and n.kind != "anti":
            self._add(n, "arity", "null_aware on a non-anti join")
        if len(n.left_keys) != len(n.right_keys):
            self._add(n, "joinkey",
                      f"{len(n.left_keys)} left keys vs "
                      f"{len(n.right_keys)} right keys")
        for i, k in enumerate(n.left_keys):
            self._expr(n, k, n.left.out_names, n.left.out_dtypes,
                       f"left key {i}")
        for i, k in enumerate(n.right_keys):
            self._expr(n, k, n.right.out_names, n.right.out_dtypes,
                       f"right key {i}")
        for i, (lk, rk) in enumerate(zip(n.left_keys, n.right_keys)):
            if not _join_key_ok(lk.dtype, rk.dtype):
                self._add(n, "joinkey",
                          f"key {i} dtypes {lk.dtype!r} vs {rk.dtype!r} "
                          "factorize into different int64 key spaces")
        if n.residual is not None:
            comb_names = list(n.left.out_names) + list(n.right.out_names)
            comb_dtypes = list(n.left.out_dtypes) + list(n.right.out_dtypes)
            self._expr(n, n.residual, comb_names, comb_dtypes, "residual")
            if n.residual.dtype != "bool":
                self._add(n, "dtype",
                          f"residual dtype {n.residual.dtype!r}, "
                          "expected 'bool'")

    def _chk_AggregateNode(self, n: P.AggregateNode, w: int) -> None:
        c = n.child
        ng, na = len(n.group_exprs), len(n.aggs)
        expect = ng + na + (1 if n.rollup else 0)
        if w != expect:
            self._add(n, "arity",
                      f"aggregate width {w} != {ng} groups + {na} aggs"
                      f"{' + __grouping_id' if n.rollup else ''}")
            return
        for i, g in enumerate(n.group_exprs):
            self._expr(n, g, c.out_names, c.out_dtypes, f"group key {i}")
            if g.dtype != n.out_dtypes[i]:
                self._add(n, "dtype",
                          f"group key {i} dtype {g.dtype!r} != output "
                          f"{n.out_dtypes[i]!r}")
        for i, s in enumerate(n.aggs):
            if s.func not in _AGG_FUNCS:
                self._add(n, "agg", f"unknown aggregate {s.func!r}")
                continue
            if s.func == "count_star":
                if s.arg is not None:
                    self._add(n, "agg", "count_star with an argument")
            elif s.arg is None:
                self._add(n, "agg", f"{s.func} without an argument")
            if s.arg is not None:
                self._expr(n, s.arg, c.out_names, c.out_dtypes,
                           f"agg {i} ({s.func})")
                if s.func in ("sum", "avg", "stddev_samp") \
                        and not _numeric(s.arg.dtype) \
                        and s.arg.dtype != "bool":
                    self._add(n, "agg",
                              f"{s.func} over non-numeric dtype "
                              f"{s.arg.dtype!r}")
            if s.dtype != n.out_dtypes[ng + i]:
                self._add(n, "dtype",
                          f"agg {i} ({s.func}) dtype {s.dtype!r} != output "
                          f"{n.out_dtypes[ng + i]!r}")
        if n.rollup and n.out_dtypes[-1] != "int":
            self._add(n, "dtype", "__grouping_id output dtype must be 'int'")
        if n.rollup_levels is not None:
            if not n.rollup:
                self._add(n, "agg", "rollup_levels on a non-rollup aggregate")
            for lvl in n.rollup_levels:
                if not (0 <= lvl <= ng):
                    self._add(n, "agg",
                              f"rollup level {lvl} out of range 0..{ng}")
        self._chk_decompose(n)

    def _chk_decompose(self, n: P.AggregateNode) -> None:
        """Streaming mergeability round-trip: the partial/final decomposition
        of a mergeable aggregate must rebuild EXACTLY the aggregate's output
        schema (the merge plan runs over materialized partials — a schema
        drift here surfaces as silent mis-merged results mid-stream)."""
        from . import streaming
        if not streaming._mergeable(n):
            return
        try:
            specs, recipes, p_names, p_dtypes = streaming._decompose(n)
            mat = P.MaterializedNode(table=None, label="verify",
                                     out_names=list(p_names),
                                     out_dtypes=list(p_dtypes))
            final = streaming._final_builder(n, recipes, p_names,
                                             p_dtypes)(mat)
        except Exception as e:
            self._add(n, "agg",
                      f"mergeable-agg decomposition failed: "
                      f"{type(e).__name__}: {e}")
            return
        if list(final.out_names) != list(n.out_names) or \
                list(final.out_dtypes) != list(n.out_dtypes):
            self._add(n, "agg",
                      "mergeable-agg decomposition does not round-trip to "
                      "the aggregate's output schema")

    def _chk_WindowNode(self, n: P.WindowNode, w: int) -> None:
        c = n.child
        cw = len(c.out_names)
        if w != cw + len(n.funcs):
            self._add(n, "arity",
                      f"window width {w} != child {cw} + "
                      f"{len(n.funcs)} funcs")
            return
        if list(n.out_dtypes[:cw]) != list(c.out_dtypes):
            self._add(n, "dtype", "window passthrough dtypes diverge "
                                  "from child's")
        for i, f in enumerate(n.funcs):
            if f.func not in _WINDOW_FUNCS:
                self._add(n, "window", f"unknown window func {f.func!r}")
                continue
            if f.func in ("rank", "dense_rank", "row_number"):
                if f.arg is not None:
                    self._add(n, "window", f"{f.func} takes no argument")
                if f.func in ("rank", "dense_rank") and not f.order_by:
                    self._add(n, "window", f"{f.func} without ORDER BY")
            if f.arg is not None:
                self._expr(n, f.arg, c.out_names, c.out_dtypes,
                           f"window {i} arg")
            for j, e in enumerate(f.partition_by):
                self._expr(n, e, c.out_names, c.out_dtypes,
                           f"window {i} partition {j}")
            for j, k in enumerate(f.order_by):
                self._expr(n, k.expr, c.out_names, c.out_dtypes,
                           f"window {i} order {j}")
            if f.dtype != n.out_dtypes[cw + i]:
                self._add(n, "dtype",
                          f"window {i} ({f.func}) dtype {f.dtype!r} != "
                          f"output {n.out_dtypes[cw + i]!r}")

    def _chk_SortNode(self, n: P.SortNode, w: int) -> None:
        self._require_passthrough(n, w)
        c = n.child
        for j, k in enumerate(n.keys):
            self._expr(n, k.expr, c.out_names, c.out_dtypes, f"sort key {j}")

    def _chk_LimitNode(self, n: P.LimitNode, w: int) -> None:
        self._require_passthrough(n, w)
        if n.n < 0:
            self._add(n, "arity", f"negative limit {n.n}")

    def _chk_DistinctNode(self, n: P.DistinctNode, w: int) -> None:
        self._require_passthrough(n, w)

    def _chk_SetOpNode(self, n: P.SetOpNode, w: int) -> None:
        if n.op not in ("union", "intersect", "except"):
            self._add(n, "setop", f"unknown set op {n.op!r}")
        for side, b in (("left", n.left), ("right", n.right)):
            if len(b.out_names) != w:
                self._add(n, "arity",
                          f"{side} branch width {len(b.out_names)} != {w}")
            elif list(b.out_dtypes) != list(n.out_dtypes):
                self._add(n, "setop",
                          f"{side} branch dtypes diverge positionally "
                          "(decimal scales must match before concat)")

    def _chk_MaterializedNode(self, n: P.MaterializedNode, w: int) -> None:
        t = n.table
        if t is not None and getattr(t, "num_columns", w) != w:
            self._add(n, "arity",
                      f"materialized table has {t.num_columns} columns, "
                      f"node declares {w}")

    def _chk_VirtualScanNode(self, n: P.VirtualScanNode, w: int) -> None:
        if not n.key:
            self._add(n, "scan", "virtual scan without a segment key")


def _lane_stat_findings(n: P.ScanNode, stats: dict,
                        encodings=None) -> list[Finding]:
    """Lane-vs-value-range findings for one scan with declared lanes.
    stats: {column: (lo, hi) in engine units, or None = unknown}. Unknown
    ranges only pass on lanes that are range-free for the dtype (the
    widest legal lane); a NARROW lane without stats is itself a finding —
    nothing proves the column fits. Dict-encoded columns are skipped:
    their lane carries codes bounded by cardinality, not values."""
    from .jax_backend.device import _LANE_BOUNDS, plan_lanes

    out: list[Finding] = []
    encs = encodings or ("plain",) * len(n.columns)
    for c, d, lane, enc in zip(n.columns, n.out_dtypes, n.lanes, encs):
        if isinstance(enc, tuple) and enc[0] == "dict":
            continue
        bounds = _LANE_BOUNDS.get(lane)
        if bounds is None:      # b1 / f64: dtype legality already checked
            continue
        st = stats.get(c)
        if st is None:
            widest = plan_lanes([d], [None])
            if widest is not None and lane != widest[0] and d != "str":
                out.append(Finding(
                    n, "", "lane",
                    f"column {c!r}: narrow lane {lane!r} declared but no "
                    f"value-range stats prove it fits"))
            continue
        lo, hi = int(st[0]), int(st[1])
        if lo < bounds[0] or hi > bounds[1]:
            out.append(Finding(
                n, "", "lane",
                f"column {c!r}: recorded range [{lo}, {hi}] overflows "
                f"lane {lane!r} bounds {list(bounds)}"))
    return out


def check_scan_lanes(scan: P.ScanNode, stats: dict) -> list[Finding]:
    """Standalone lane/stats legality check for a (morsel) scan whose
    table is not in any catalog — streaming.verify_groups feeds it the
    big table's column stats keyed by the scan's column names."""
    if scan.lanes is None:
        return []
    findings = _lane_stat_findings(scan, stats, scan.encodings)
    _fill_labels(findings, scan, None)
    return findings


def check_scan_encodings(scan: P.ScanNode, enc_stats: dict) -> list[Finding]:
    """Standalone encoding-vs-stats legality check for a (morsel) scan:
    every dict/rle spec must be PROVEN against recorded cardinality/run
    stats before a morsel ships on it — a dictionary smaller than the
    column's distinct set packs to EncodingOverflowError mid-stream, and a
    run bound below the recorded total could overflow the static run
    capacity on an adversarial morsel window. enc_stats: {column:
    {"distinct": values-or-None, "runs": int-or-None}} from the SAME
    source the planner chose the encodings from
    (Session.column_enc_stats)."""
    if scan.encodings is None:
        return []
    out: list[Finding] = []
    for c, enc in zip(scan.columns, scan.encodings):
        if enc == "plain" or not isinstance(enc, tuple):
            continue
        st = enc_stats.get(c) or {}
        if enc[0] == "dict":
            dv = st.get("distinct")
            if dv is None:
                out.append(Finding(
                    scan, "", "encoding",
                    f"column {c!r}: dict encoding declared but no "
                    "distinct-value stats prove the dictionary covers it"))
            elif len(dv) > max(int(enc[1]), 1):
                out.append(Finding(
                    scan, "", "encoding",
                    f"column {c!r}: recorded cardinality {len(dv)} exceeds "
                    f"the declared dictionary size {enc[1]}"))
        elif enc[0] == "rle":
            runs = st.get("runs")
            if runs is None:
                out.append(Finding(
                    scan, "", "encoding",
                    f"column {c!r}: rle encoding declared but no run-count "
                    "stats bound the per-morsel run capacity"))
            elif int(runs) > int(enc[1]):
                out.append(Finding(
                    scan, "", "encoding",
                    f"column {c!r}: recorded run count {runs} exceeds the "
                    f"declared bound {enc[1]}"))
    _fill_labels(out, scan, None)
    return out


def check_params(root: P.PlanNode) -> list[Finding]:
    """parameterize_plan/deparameterize_plan round-trip integrity: the
    hoisted plan must carry one slot per value and substitute back into a
    structurally identical plan (a drift here means stream variants of one
    template compile DIFFERENT programs — the whole point of hoisting)."""
    out: list[Finding] = []
    if any(isinstance(e, P.BParam)
           for n in P.iter_plan_nodes(root)
           for e in _node_exprs(n)):
        return out            # already parameterized: nothing to round-trip
    p, values, dtypes = P.parameterize_plan(root)
    if len(values) != len(dtypes):
        out.append(Finding(root, "", "params",
                           f"{len(values)} hoisted values vs "
                           f"{len(dtypes)} dtypes"))
        return out
    for n in P.iter_plan_nodes(p):
        for e in _node_exprs(n):
            for prm in _iter_params(e):
                if not (0 <= prm.index < len(values)):
                    out.append(Finding(
                        n, "", "params",
                        f"param slot {prm.index} out of range "
                        f"({len(values)} values)"))
    back = P.deparameterize_plan(p, values)
    if plan_fingerprint(back) != plan_fingerprint(root):
        out.append(Finding(root, "", "params",
                           "parameterize/deparameterize round-trip does not "
                           "reconstruct the plan"))
    return out


def _node_exprs(n: P.PlanNode):
    """Every expression object held directly by a plan node."""
    for name in P.type_fields(n):
        if name in ("child", "left", "right", "table"):
            continue
        v = getattr(n, name)
        stack = [v]
        while stack:
            x = stack.pop()
            if isinstance(x, P.BExpr):
                yield x
            elif isinstance(x, (P.AggSpec, P.SortKey, P.WindowFunc)):
                for g in dataclasses.fields(x):
                    stack.append(getattr(x, g.name))
            elif isinstance(x, (list, tuple)):
                stack.extend(x)


def _iter_params(e):
    stack = [e]
    while stack:
        x = stack.pop()
        if isinstance(x, P.BParam):
            yield x
        elif isinstance(x, P.BCall):
            stack.extend(x.args)
            if isinstance(x.extra, list):
                stack.extend(v for v in x.extra if isinstance(v, P.BParam))


def verify_plan(root: P.PlanNode, catalog=None, deep: bool = False,
                labels: Optional[dict[int, str]] = None) -> list[Finding]:
    """Statically check every invariant of a bound plan; returns findings
    (empty = verified). `deep` adds the parameter round-trip check (one
    extra structural pass — PassPipeline runs it on the final plan only)."""
    v = _Verifier(catalog)
    for n in P.iter_plan_nodes(root):
        v.check_node(n)
    if deep and not v.findings:
        v.findings.extend(check_params(root))
    _fill_labels(v.findings, root, labels)
    return v.findings
