"""Projection pushdown: prune unused columns from a bound plan.

The planner binds scans to EVERY table column and joins concatenate full
schemas, so without this pass a star join carries fact-table-wide rows
through the whole pipeline (query72's 10-table join is 218 columns wide
while its aggregate needs 8). The reference gets this from Spark's
ColumnPruning + parquet column projection (reference
nds/nds_power.py:124-134 delegates to the Catalyst optimizer); here it is
an explicit plan rewrite shared by all executors (host oracle, device,
streaming), cutting scan IO, device upload, join gather width, and
record-pass memory at once.

Two passes over the plan DAG:
1. collect: per-node set of needed output indices, monotonically grown to
   a fixpoint (shared CTE subtrees take the UNION over all consumers so a
   shared node is still materialized once);
2. rebuild: bottom-up reconstruction where each node keeps only needed
   outputs, with every expression's column indices remapped. Relative
   column order is preserved (kept index lists are ascending), so the root
   output is unchanged.

Nodes whose semantics span the full row (DISTINCT, non-ALL set ops) force
all their input columns needed. Aggregate/Window function lists are kept
as-is (their children still prune — that is where the width lives).
"""
from __future__ import annotations

import dataclasses
from typing import Optional

from .plan import (
    AggregateNode, BCol, BExpr, BScalarSubquery, DistinctNode, FilterNode,
    JoinNode, LimitNode, MaterializedNode, PlanNode, ProjectNode, ScanNode,
    SetOpNode, SortNode, VirtualScanNode, WindowNode, iter_plan_nodes,
)


def _expr_refs(x, out: set[int], subplans: list) -> None:
    """Column indices referenced by an expression tree; embedded subquery
    plans are collected separately (their indices live in their own space)."""
    if isinstance(x, BCol):
        out.add(x.index)
        return
    if isinstance(x, BScalarSubquery):
        subplans.append(x.plan)
        return
    if isinstance(x, BExpr) or (dataclasses.is_dataclass(x)
                                and not isinstance(x, type)):
        for f in dataclasses.fields(x):
            _expr_refs(getattr(x, f.name), out, subplans)
        return
    if isinstance(x, (list, tuple)):
        for v in x:
            _expr_refs(v, out, subplans)


def _remap_expr(x, mapping: dict[int, int], rebuild_plan=None):
    """Functionally rewrite BCol indices through `mapping`; embedded
    subquery plans are rewritten via rebuild_plan (their own index space)."""
    if isinstance(x, BCol):
        return dataclasses.replace(x, index=mapping[x.index])
    if isinstance(x, BScalarSubquery):
        if rebuild_plan is None:
            return x
        p = rebuild_plan(x.plan)
        return x if p is x.plan else dataclasses.replace(x, plan=p)
    if isinstance(x, PlanNode):
        raise AssertionError("plan node in expression position")
    if dataclasses.is_dataclass(x) and not isinstance(x, type):
        changes = {}
        for f in dataclasses.fields(x):
            v = getattr(x, f.name)
            nv = _remap_expr(v, mapping, rebuild_plan)
            if nv is not v:
                changes[f.name] = nv
        return dataclasses.replace(x, **changes) if changes else x
    if isinstance(x, list):
        out = [_remap_expr(v, mapping, rebuild_plan) for v in x]
        return out if any(a is not b for a, b in zip(out, x)) else x
    if isinstance(x, tuple):
        out = tuple(_remap_expr(v, mapping, rebuild_plan) for v in x)
        return out if any(a is not b for a, b in zip(out, x)) else x
    return x


def _width(node: PlanNode) -> int:
    return len(node.out_names)


class _Pruner:
    def __init__(self) -> None:
        self.needed: dict[int, set[int]] = {}
        self.by_id: dict[int, PlanNode] = {}
        self.built: dict[int, tuple[PlanNode, dict[int, int]]] = {}

    # -- pass 1: needed-set fixpoint ----------------------------------------
    def collect(self, node: PlanNode, req: set[int]) -> None:
        self.by_id[id(node)] = node
        if id(node) not in self.needed:
            self.needed[id(node)] = set(req)
            self._propagate(node, self.needed[id(node)])
            return
        cur = self.needed[id(node)]
        if req <= cur:
            return
        cur |= req
        self._propagate(node, cur)

    def _exprs_req(self, *exprs) -> set[int]:
        refs: set[int] = set()
        subs: list = []
        for e in exprs:
            _expr_refs(e, refs, subs)
        for p in subs:
            self.collect(p, set(range(_width(p))))
        return refs

    def _propagate(self, node: PlanNode, need: set[int]) -> None:
        if isinstance(node, (ScanNode, MaterializedNode, VirtualScanNode)):
            return
        if isinstance(node, FilterNode):
            self.collect(node.child,
                         need | self._exprs_req(node.predicate))
            return
        if isinstance(node, ProjectNode):
            keep = sorted(need) or [0]   # must mirror _keep's normalization
            self.collect(node.child, self._exprs_req(
                *[node.exprs[i] for i in keep]))
            return
        if isinstance(node, JoinNode):
            w = _width(node.left)
            lreq = {i for i in need if i < w} if node.kind not in (
                "semi", "anti") else set(need)
            rreq = {i - w for i in need if i >= w} if node.kind not in (
                "semi", "anti") else set()
            lreq |= self._exprs_req(*node.left_keys)
            rreq |= self._exprs_req(*node.right_keys)
            if node.residual is not None:
                res = self._exprs_req(node.residual)
                lreq |= {i for i in res if i < w}
                rreq |= {i - w for i in res if i >= w}
            self.collect(node.left, lreq)
            self.collect(node.right, rreq)
            return
        if isinstance(node, AggregateNode):
            self.collect(node.child, self._exprs_req(
                node.group_exprs, [a.arg for a in node.aggs
                                   if a.arg is not None]))
            return
        if isinstance(node, WindowNode):
            w = _width(node.child)
            req = {i for i in need if i < w}
            req |= self._exprs_req(
                [f.arg for f in node.funcs if f.arg is not None],
                [f.partition_by for f in node.funcs],
                [[k.expr for k in f.order_by] for f in node.funcs])
            self.collect(node.child, req)
            return
        if isinstance(node, SortNode):
            self.collect(node.child, need | self._exprs_req(
                [k.expr for k in node.keys]))
            return
        if isinstance(node, LimitNode):
            self.collect(node.child, set(need))
            return
        if isinstance(node, DistinctNode):
            self.collect(node.child, set(range(_width(node.child))))
            return
        if isinstance(node, SetOpNode):
            if node.op == "union" and node.all:
                req = set(need) or {0}   # must mirror _keep's normalization
                self.collect(node.left, req)
                self.collect(node.right, req)
            else:  # row-equality semantics: every column participates
                self.collect(node.left, set(range(_width(node.left))))
                self.collect(node.right, set(range(_width(node.right))))
            return
        raise AssertionError(f"unhandled plan node {type(node).__name__}")

    # -- pass 2: rebuild ----------------------------------------------------
    def _keep(self, node: PlanNode) -> list[int]:
        need = self.needed.get(id(node), set())
        if not need:
            need = {0}  # row-presence carrier (e.g. COUNT(*) over a scan)
        return sorted(need)

    def rebuild(self, node: PlanNode) -> tuple[PlanNode, dict[int, int]]:
        if id(node) in self.built:
            return self.built[id(node)]
        out = self._rebuild(node)
        self.built[id(node)] = out
        return out

    def _sub(self, plan: PlanNode) -> PlanNode:
        return self.rebuild(plan)[0]

    def _remap(self, x, mapping: dict[int, int]):
        return _remap_expr(x, mapping, rebuild_plan=self._sub)

    def _passthrough(self, node: PlanNode, cmap: dict[int, int],
                     new_child: PlanNode, **extra):
        """Rebuild a width-preserving node: output follows the pruned child."""
        kept = sorted(cmap, key=lambda i: cmap[i])
        return dataclasses.replace(
            node, child=new_child,
            out_names=[node.out_names[i] for i in kept],
            out_dtypes=[node.out_dtypes[i] for i in kept], **extra), dict(cmap)

    def _rebuild(self, node: PlanNode) -> tuple[PlanNode, dict[int, int]]:
        if isinstance(node, (MaterializedNode, VirtualScanNode)):
            return node, {i: i for i in range(_width(node))}
        if isinstance(node, ScanNode):
            keep = self._keep(node)
            if len(keep) == _width(node):
                return node, {i: i for i in keep}
            return ScanNode(
                node.table, [node.columns[i] for i in keep],
                out_names=[node.out_names[i] for i in keep],
                out_dtypes=[node.out_dtypes[i] for i in keep]), \
                {i: p for p, i in enumerate(keep)}
        if isinstance(node, FilterNode):
            child, cmap = self.rebuild(node.child)
            return self._passthrough(node, cmap, child,
                                     predicate=self._remap(node.predicate,
                                                           cmap))
        if isinstance(node, ProjectNode):
            child, cmap = self.rebuild(node.child)
            keep = self._keep(node)
            return ProjectNode(
                child, [self._remap(node.exprs[i], cmap) for i in keep],
                out_names=[node.out_names[i] for i in keep],
                out_dtypes=[node.out_dtypes[i] for i in keep]), \
                {i: p for p, i in enumerate(keep)}
        if isinstance(node, JoinNode):
            left, lmap = self.rebuild(node.left)
            right, rmap = self.rebuild(node.right)
            w, nw = _width(node.left), _width(left)
            comb = dict(lmap)
            comb.update({w + j: nw + rmap[j] for j in rmap})
            residual = None if node.residual is None else \
                self._remap(node.residual, comb)
            if node.kind in ("semi", "anti"):
                out_map = dict(lmap)
                names = list(left.out_names)
                dtypes = list(left.out_dtypes)
            else:
                out_map = comb
                names = list(left.out_names) + list(right.out_names)
                dtypes = list(left.out_dtypes) + list(right.out_dtypes)
            return JoinNode(
                left, right, node.kind,
                [self._remap(k, lmap) for k in node.left_keys],
                [self._remap(k, rmap) for k in node.right_keys],
                residual, null_aware=node.null_aware,
                late_mat=node.late_mat,
                out_names=names, out_dtypes=dtypes), out_map
        if isinstance(node, AggregateNode):
            child, cmap = self.rebuild(node.child)
            return dataclasses.replace(
                node, child=child,
                group_exprs=[self._remap(e, cmap) for e in node.group_exprs],
                aggs=[self._remap(a, cmap) for a in node.aggs]), \
                {i: i for i in range(_width(node))}
        if isinstance(node, WindowNode):
            child, cmap = self.rebuild(node.child)
            w, nw = _width(node.child), _width(child)
            kept = sorted(cmap, key=lambda i: cmap[i])
            out_map = dict(cmap)
            out_map.update({w + k: nw + k for k in range(len(node.funcs))})
            return dataclasses.replace(
                node, child=child,
                funcs=[self._remap(f, cmap) for f in node.funcs],
                out_names=[node.out_names[i] for i in kept] +
                          list(node.out_names[w:]),
                out_dtypes=[node.out_dtypes[i] for i in kept] +
                           list(node.out_dtypes[w:])), out_map
        if isinstance(node, SortNode):
            child, cmap = self.rebuild(node.child)
            return self._passthrough(
                node, cmap, child,
                keys=[self._remap(k, cmap) for k in node.keys])
        if isinstance(node, LimitNode):
            child, cmap = self.rebuild(node.child)
            return self._passthrough(node, cmap, child)
        if isinstance(node, DistinctNode):
            child, cmap = self.rebuild(node.child)
            return self._passthrough(node, cmap, child)
        if isinstance(node, SetOpNode):
            left, lmap = self.rebuild(node.left)
            right, rmap = self.rebuild(node.right)
            keep = (self._keep(node) if node.op == "union" and node.all
                    else list(range(_width(node))))
            left = _project_onto(left, lmap, keep, node)
            right = _project_onto(right, rmap, keep, node)
            return SetOpNode(
                node.op, node.all, left, right,
                out_names=[node.out_names[i] for i in keep],
                out_dtypes=[node.out_dtypes[i] for i in keep]), \
                {i: p for p, i in enumerate(keep)}
        raise AssertionError(f"unhandled plan node {type(node).__name__}")


def _project_onto(branch: PlanNode, bmap: dict[int, int], keep: list[int],
                  setop: SetOpNode) -> PlanNode:
    """Force a set-op branch onto exactly the kept positional layout (both
    branches must line up column-for-column even when one carries extra
    passthrough columns, e.g. a Filter child keeping its predicate cols)."""
    want = [bmap[i] for i in keep]
    if want == list(range(_width(branch))):
        return branch
    return ProjectNode(
        branch,
        [BCol(branch.out_dtypes[j], j, branch.out_names[j]) for j in want],
        out_names=[branch.out_names[j] for j in want],
        out_dtypes=[branch.out_dtypes[j] for j in want])


def prune_plan(root: PlanNode) -> PlanNode:
    """Return an equivalent plan reading/carrying only needed columns.

    The root's output schema is preserved exactly; `cte_segments` (compile
    segmentation candidates) transfer to the rebuilt nodes under their
    original fingerprints — CTE outputs stay full-width so the segment
    cache slot is identical across statements sharing a WITH clause."""
    pr = _Pruner()
    segs = getattr(root, "cte_segments", None)
    if segs:
        # CTE segmentation candidates keep their FULL output width: their
        # compile-segment fingerprints are shared across statements (q14/q23
        # parts), and consumer-dependent pruning would fork the segment
        # cache slot per statement, re-materializing shared CTEs. The CTE's
        # internals still prune (that is where the join/scan width lives).
        reachable = {id(n) for n in iter_plan_nodes(root)}
        for _fp, node in segs:
            if id(node) in reachable:
                pr.collect(node, set(range(_width(node))))
    pr.collect(root, set(range(_width(root))))
    new_root, rmap = pr.rebuild(root)
    if [rmap.get(i) for i in range(_width(root))] != \
            list(range(_width(root))):
        # a passthrough root kept extra expression-only columns: restore the
        # exact original output layout
        new_root = ProjectNode(
            new_root,
            [BCol(root.out_dtypes[i], rmap[i], root.out_names[i])
             for i in range(_width(root))],
            out_names=list(root.out_names),
            out_dtypes=list(root.out_dtypes))
    if segs is not None:
        new_segs = []
        for fp, node in segs:
            if id(node) not in pr.built:
                continue  # CTE never referenced by the pruned plan
            built, _ = pr.built[id(node)]
            new_segs.append((fp, built))
        new_root.cte_segments = new_segs
    return new_root
