"""Bound-expression evaluation on device columns.

Same Spark-SQL null semantics as engine/exprs.py (the numpy oracle), but as
traceable JAX compute. String work never touches the device: predicates,
substrings and parses are computed once over the host-side dictionary and
become gather LUTs; only int32 codes flow through XLA. Ops with genuinely
row-wise string output (concat) produce lazy compound columns.

Raises NotImplementedError for the few host-only cases; the executor falls
back to the numpy backend for that plan node.
"""
from __future__ import annotations

import re
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..column import dec_scale, is_dec
from ..plan import BCall, BCol, BExpr, BLit, BParam, BScalarSubquery
from .device import (DCol, DTable, decode_col, phys_dtype, string_rank_lut,
                     widen_col)

SubqueryEval = Callable[[object], object]


class EvalCtx:
    """Evaluation callbacks bundle, threaded opaquely through handlers in
    the `subquery_eval` position: `subquery` resolves BScalarSubquery
    plans, `param` resolves BParam slots (hoisted stream literals)."""
    __slots__ = ("subquery", "param")

    def __init__(self, subquery=None, param=None):
        self.subquery = subquery
        self.param = param


def _float_dtype():
    return jnp.float64 if jax.config.read("jax_enable_x64") else jnp.float32


def _to_float(c: DCol) -> jax.Array:
    """Numeric column as float (decN descales: scaled int -> value)."""
    out = c.data.astype(_float_dtype())
    if is_dec(c.dtype):
        out = out / 10.0 ** dec_scale(c.dtype)
    return out


def evaluate(expr: BExpr, table: DTable,
             subquery_eval: Optional[SubqueryEval] = None) -> DCol:
    n = table.alive.shape[0]
    if isinstance(expr, BCol):
        return table.cols[expr.index]
    if isinstance(expr, BLit):
        return constant(expr.dtype, expr.value, n)
    if isinstance(expr, BParam):
        param = subquery_eval.param \
            if isinstance(subquery_eval, EvalCtx) else None
        if param is None:
            raise RuntimeError("parameter slot encountered without values")
        return param(expr, n)
    if isinstance(expr, BScalarSubquery):
        sq = subquery_eval.subquery \
            if isinstance(subquery_eval, EvalCtx) else subquery_eval
        if sq is None:
            raise RuntimeError("scalar subquery encountered without evaluator")
        value, valid = sq(expr.plan)
        return constant(expr.dtype, value, n, valid)
    if isinstance(expr, BCall):
        handler = _HANDLERS.get(expr.op)
        if handler is None:
            raise NotImplementedError(f"device expression op {expr.op!r}")
        return handler(expr, table, subquery_eval)
    raise TypeError(type(expr).__name__)


def constant(dtype: str, value, n: int, valid=None) -> DCol:
    """Broadcast a scalar to a column. `valid` None => nullness from `value`
    (host python scalar); otherwise a traced 0-d validity (scalar subqueries
    inlined into a compiled plan)."""
    pd = phys_dtype(dtype)
    if valid is not None:
        data = jnp.broadcast_to(jnp.asarray(value).astype(pd), (n,))
        return DCol(dtype, data, jnp.broadcast_to(valid, (n,)))
    if value is None:
        return DCol(dtype, jnp.zeros(n, pd), jnp.zeros(n, bool))
    if dtype == "str":
        return DCol("str", jnp.zeros(n, jnp.int32), jnp.ones(n, bool),
                    np.asarray([value], dtype=object))
    if dtype == "bool":
        value = bool(value)
    return DCol(dtype, jnp.full(n, value, dtype=pd), jnp.ones(n, bool))


def _args(expr: BCall, table: DTable, sq) -> list[DCol]:
    """Evaluated arguments with encoded columns DECODED: every generic
    handler computes on values. Encoding-aware handlers (_compare/_in_list
    literal remaps) evaluate raw instead and stay on codes."""
    return [decode_col(evaluate(a, table, sq)) for a in expr.args]


def _both(a: DCol, b: DCol) -> jax.Array:
    return a.valid & b.valid


# -- string dictionary helpers (host-side, trace-time constants) -------------

def _dict(c: DCol) -> np.ndarray:
    if c.parts is not None:
        raise NotImplementedError("compound string used in unsupported op")
    return c.dictionary if c.dictionary is not None else np.empty(0, dtype=object)


def _lut_gather(codes: jax.Array, lut: np.ndarray) -> jax.Array:
    dlut = jnp.asarray(lut)
    if dlut.shape[0] == 0:
        return jnp.zeros(codes.shape, dlut.dtype)
    return dlut[jnp.clip(codes, 0, dlut.shape[0] - 1)]


def _merge_dicts(*dicts: np.ndarray) -> tuple[np.ndarray, list[np.ndarray]]:
    """Common dictionary + per-input code remap LUTs (host)."""
    seen: dict[str, int] = {}
    luts = []
    for d in dicts:
        lut = np.empty(len(d), dtype=np.int32)
        for i, v in enumerate(d):
            if v not in seen:
                seen[v] = len(seen)
            lut[i] = seen[v]
        luts.append(lut)
    merged = np.empty(len(seen), dtype=object)
    for v, i in seen.items():
        merged[i] = v
    return merged, luts


def _string_pair_keys(a: DCol, b: DCol) -> tuple[jax.Array, jax.Array]:
    """Comparable int keys for two string columns (merged lexicographic rank)."""
    merged, (la, lb) = _merge_dicts(_dict(a), _dict(b))
    ranks = string_rank_lut(merged)
    ka = _lut_gather(_lut_gather(a.data, la), ranks)
    kb = _lut_gather(_lut_gather(b.data, lb), ranks)
    return ka, kb


# -- arithmetic --------------------------------------------------------------

def _arith(op: str):
    def run(expr: BCall, table: DTable, sq) -> DCol:
        a, b = _args(expr, table, sq)
        valid = _both(a, b)
        if op == "div":
            da, db = _to_float(a), _to_float(b)
            zero = db == 0
            out = da / jnp.where(zero, 1.0, db)
            return DCol("float", jnp.where(valid & ~zero, out, 0.0),
                        valid & ~zero)
        fns = {"add": jnp.add, "sub": jnp.subtract, "mul": jnp.multiply,
               "mod": jnp.fmod}
        if a.dtype == "float" or b.dtype == "float" or expr.dtype == "float":
            out = fns[op](_to_float(a), _to_float(b))
            return DCol("float", jnp.where(valid, out, 0.0), valid)
        pd = phys_dtype("int")
        out = fns[op](a.data.astype(pd), b.data.astype(pd))
        if is_dec(expr.dtype):
            # scale-aligned (add/sub) or raw scaled-int product (mul)
            return DCol(expr.dtype, jnp.where(valid, out, 0), valid)
        dtype = expr.dtype if expr.dtype in ("int", "date") else "int"
        out = out.astype(phys_dtype(dtype))
        return DCol(dtype, jnp.where(valid, out, 0), valid)
    return run


def _neg(expr: BCall, table: DTable, sq) -> DCol:
    a = widen_col(evaluate(expr.args[0], table, sq))
    return DCol(a.dtype, -a.data, a.valid)


def _ratdiv(which: str):
    """Exact rational order key for num/den (planner._exact_rational_keys):
    "hi" = floor(p/q), "lo" = binary fraction digits, both via exact integer
    divmod (device int64 // and % ARE exact under emulation, unlike f64
    division). Decimal scales fold into p and q so the value is the true
    rational. Invalid where either input is null or den == 0 — the same
    validity the float `div` produces, so null ordering is unchanged."""
    def run(expr: BCall, table: DTable, sq) -> DCol:
        a, b = _args(expr, table, sq)
        pd = phys_dtype("int")
        sa = dec_scale(a.dtype) if is_dec(a.dtype) else 0
        sb = dec_scale(b.dtype) if is_dec(b.dtype) else 0
        p = a.data.astype(pd) * (10 ** sb)
        q = b.data.astype(pd) * (10 ** sa)
        neg = q < 0
        p = jnp.where(neg, -p, p)
        q = jnp.where(neg, -q, q)
        valid = _both(a, b) & (q != 0)
        qs = jnp.where(q == 0, 1, q)
        hi = jnp.floor_divide(p, qs)
        if which == "hi":
            return DCol("int", jnp.where(valid, hi, 0), valid)
        r = p - hi * qs                       # in [0, q)
        if jnp.dtype(pd).itemsize < 8:
            # no-x64 tier (approximate by config contract): 24 fraction
            # bits via f32 — r << k would overflow int32 for q >= 2^25
            frac = r.astype(jnp.float32) / qs.astype(jnp.float32)
            lo = jnp.floor(frac * (1 << 24)).astype(pd)
            return DCol("int", jnp.where(valid, lo, 0), valid)
        # 8 x 7-bit digits (56 fraction bits > the 53 the host's double
        # keys resolve); r << 7 stays in int64 range while q < 2^56,
        # far above any NDS-scale aggregate magnitude
        lo = jnp.zeros_like(r)
        for _ in range(8):
            r = r << 7
            d = jnp.floor_divide(r, qs)
            r = r - d * qs
            lo = (lo << 7) | d
        return DCol("int", jnp.where(valid, lo, 0), valid)
    return run


# -- comparisons -------------------------------------------------------------

_CMP = {"eq": jnp.equal, "ne": jnp.not_equal, "lt": jnp.less,
        "le": jnp.less_equal, "gt": jnp.greater, "ge": jnp.greater_equal}


_FLIP = {"eq": "eq", "ne": "ne", "lt": "gt", "le": "ge", "gt": "lt",
         "ge": "le"}


def _code_space_compare(op: str, c: DCol, value) -> Optional[jax.Array]:
    """col <op> literal ON CODES: the sorted codebook is order-isomorphic
    to the values, so the literal remaps to a code-space threshold at
    trace time (exact — a value between dictionary entries lands on the
    searchsorted boundary, one absent from an eq/ne on the right constant
    answer). Returns the raw compare output (validity handled by caller),
    or None when the op cannot remap."""
    # compare in int64: a literal outside the (i32) codebook dtype's range
    # must land on the correct boundary, not overflow
    book = np.asarray(c.codebook, dtype=np.int64)
    value = np.int64(max(min(int(value), np.iinfo(np.int64).max),
                         np.iinfo(np.int64).min))
    i = int(np.searchsorted(book, value, side="left"))
    present = i < len(book) and book[i] == value
    codes = c.data
    if op == "eq":
        return (codes == i) if present else jnp.zeros(codes.shape, bool)
    if op == "ne":
        return (codes != i) if present else jnp.ones(codes.shape, bool)
    if op == "lt":
        return codes < i
    if op == "ge":
        return codes >= i
    hi = int(np.searchsorted(book, value, side="right"))
    if op == "le":
        return codes < hi
    if op == "gt":
        return codes >= hi
    return None


def _lit_value(e, dtype: str):
    """The engine-unit literal of a BLit comparable against `dtype`, or
    None when the expression is not a safely-remappable literal."""
    if not isinstance(e, BLit) or e.value is None:
        return None
    if e.dtype != dtype or dtype not in ("int", "date") and not is_dec(dtype):
        return None
    return int(e.value)


def _compare(op: str):
    def run(expr: BCall, table: DTable, sq) -> DCol:
        a, b = [evaluate(x, table, sq) for x in expr.args]
        # encoded execution: column-vs-literal compares remap the literal
        # into code space at trace time instead of decoding every row
        out = None
        if a.codebook is not None and b.codebook is None:
            v = _lit_value(expr.args[1], a.dtype)
            if v is not None:
                out = _code_space_compare(op, a, v)
        elif b.codebook is not None and a.codebook is None:
            v = _lit_value(expr.args[0], b.dtype)
            if v is not None:
                out = _code_space_compare(_FLIP[op], b, v)
        if out is not None:
            valid = _both(a, b)
            return DCol("bool", out & valid, valid)
        a, b = decode_col(a), decode_col(b)
        valid = _both(a, b)
        if a.dtype == "str" or b.dtype == "str":
            ka, kb = _string_pair_keys(a, b)
            out = _CMP[op](ka, kb)
        else:
            da, db = a.data, b.data
            if da.dtype != db.dtype:
                ct = jnp.promote_types(da.dtype, db.dtype)
                da, db = da.astype(ct), db.astype(ct)
            out = _CMP[op](da, db)
        return DCol("bool", out & valid, valid)
    return run


# -- boolean -----------------------------------------------------------------

def _and(expr: BCall, table: DTable, sq) -> DCol:
    a, b = _args(expr, table, sq)
    ta, tb = a.data.astype(bool) & a.valid, b.data.astype(bool) & b.valid
    fa, fb = ~a.data.astype(bool) & a.valid, ~b.data.astype(bool) & b.valid
    out = ta & tb
    return DCol("bool", out, out | fa | fb)


def _or(expr: BCall, table: DTable, sq) -> DCol:
    a, b = _args(expr, table, sq)
    ta, tb = a.data.astype(bool) & a.valid, b.data.astype(bool) & b.valid
    fa, fb = ~a.data.astype(bool) & a.valid, ~b.data.astype(bool) & b.valid
    out = ta | tb
    return DCol("bool", out, out | (fa & fb))


def _not(expr: BCall, table: DTable, sq) -> DCol:
    a = evaluate(expr.args[0], table, sq)
    return DCol("bool", ~a.data.astype(bool) & a.valid, a.valid)


def _isnull(expr: BCall, table: DTable, sq) -> DCol:
    a = evaluate(expr.args[0], table, sq)
    n = table.alive.shape[0]
    return DCol("bool", ~a.valid, jnp.ones(n, bool))


def _isnotnull(expr: BCall, table: DTable, sq) -> DCol:
    a = evaluate(expr.args[0], table, sq)
    n = table.alive.shape[0]
    return DCol("bool", a.valid, jnp.ones(n, bool))


# -- predicates --------------------------------------------------------------

def _in_list(expr: BCall, table: DTable, sq) -> DCol:
    a = evaluate(expr.args[0], table, sq)
    values = expr.extra
    if any(isinstance(v, BParam) for v in values):
        # hoisted int/date items: resolve to (possibly traced) scalars so
        # the membership test stays stream-invariant in the program
        param = sq.param if isinstance(sq, EvalCtx) else None
        if param is None:
            raise NotImplementedError("in_list params without values")
        values = [param(v, 1).data[0] if isinstance(v, BParam) else v
                  for v in values]
    has_null = any(v is None for v in values)
    traced = any(isinstance(v, jax.Array) or
                 isinstance(v, jax.core.Tracer) for v in values)
    if a.codebook is not None and traced:
        a = decode_col(a)    # traced params cannot remap at trace time
    if a.dtype == "str":
        d = _dict(a)
        vset = {v for v in values if v is not None}
        hit = np.asarray([v in vset for v in d], dtype=bool)
        out = _lut_gather(a.data, hit) if len(d) else jnp.zeros(len(a), bool)
    elif a.codebook is not None:
        # membership ON CODES: list items remap through the sorted codebook
        # at trace time; absent values simply contribute no code
        if is_dec(a.dtype):
            from ..exprs import _scaled_in_values
            vals = _scaled_in_values(values, dec_scale(a.dtype))
        else:
            vals = [int(v) for v in values if v is not None]
        book = a.codebook.astype(np.int64)
        varr = np.asarray(vals, dtype=np.int64) if vals \
            else np.zeros(0, dtype=np.int64)
        idx = np.searchsorted(book, varr)
        safe = np.clip(idx, 0, max(len(book) - 1, 0))
        codes = safe[(idx < len(book)) & (book[safe] == varr)] \
            if len(book) else safe[:0]
        out = jnp.isin(a.data, jnp.asarray(codes, jnp.int32)) \
            if codes.size else jnp.zeros(a.data.shape, bool)
    elif is_dec(a.dtype):
        from ..exprs import _scaled_in_values
        vals = _scaled_in_values(values, dec_scale(a.dtype))
        # membership at PHYSICAL width: scaled values cast down to a narrow
        # lane dtype would wrap and alias unrelated rows
        pd = phys_dtype(a.dtype)
        out = jnp.isin(a.data.astype(pd), jnp.asarray(vals, pd)) if vals \
            else jnp.zeros(a.data.shape, bool)
    else:
        vals = [v for v in values if v is not None]
        if not vals:
            out = jnp.zeros(a.data.shape, bool)
        else:
            arr = jnp.asarray(vals)
            ct = jnp.promote_types(a.data.dtype, arr.dtype)
            out = jnp.isin(a.data.astype(ct), arr.astype(ct))
    valid = a.valid
    if has_null:
        valid = valid & out
    return DCol("bool", out & valid, valid)


def _like_to_regex(pattern: str) -> re.Pattern:
    out = []
    for ch in pattern:
        out.append(".*" if ch == "%" else "." if ch == "_" else re.escape(ch))
    return re.compile("^" + "".join(out) + "$", re.DOTALL)


def _like(expr: BCall, table: DTable, sq) -> DCol:
    a = evaluate(expr.args[0], table, sq)
    if a.dtype != "str":
        raise NotImplementedError("LIKE on non-string column")
    pattern = _like_to_regex(str(expr.extra))
    d = _dict(a)
    hit = np.asarray([bool(pattern.match(v)) for v in d], dtype=bool)
    out = _lut_gather(a.data, hit) if len(d) else jnp.zeros(len(a), bool)
    return DCol("bool", out & a.valid, a.valid)


# -- conditional -------------------------------------------------------------

def _case(expr: BCall, table: DTable, sq) -> DCol:
    pairs = expr.args[:-1]
    else_col = decode_col(evaluate(expr.args[-1], table, sq))
    result_dtype = expr.dtype
    branch_cols = [decode_col(evaluate(pairs[i + 1], table, sq))
                   for i in range(0, len(pairs), 2)]
    branch_cols.append(else_col)
    dictionary = None
    if result_dtype == "str":
        dictionary, datas = _merge_branch_strings(branch_cols)
    else:
        pd = phys_dtype(result_dtype)
        datas = [c.data.astype(pd) for c in branch_cols]
    out = datas[-1]
    valid = branch_cols[-1].valid
    # fold branches in reverse so earlier WHENs win
    for i in range(len(pairs) - 2, -1, -2):
        cond = evaluate(pairs[i], table, sq)
        fire = cond.data.astype(bool) & cond.valid
        bi = i // 2
        out = jnp.where(fire, datas[bi], out)
        valid = jnp.where(fire, branch_cols[bi].valid, valid)
    return DCol(result_dtype, out, valid, dictionary)


def _merge_branch_strings(cols: list[DCol]) -> tuple[np.ndarray, list]:
    """Recode string columns into one shared dictionary (device codes)."""
    merged, luts = _merge_dicts(*[_dict(c) for c in cols])
    datas = [_lut_gather(c.data, lut) if len(lut)
             else jnp.zeros(len(c), jnp.int32)
             for c, lut in zip(cols, luts)]
    return merged, datas


def _coalesce(expr: BCall, table: DTable, sq) -> DCol:
    cols = _args(expr, table, sq)
    result_dtype = expr.dtype
    dictionary = None
    if result_dtype == "str":
        dictionary, datas = _merge_branch_strings(cols)
    else:
        pd = phys_dtype(result_dtype)
        datas = [c.data.astype(pd) for c in cols]
    out = datas[-1]
    valid = cols[-1].valid
    for i in range(len(cols) - 2, -1, -1):
        out = jnp.where(cols[i].valid, datas[i], out)
        valid = cols[i].valid | valid
    return DCol(result_dtype, out, valid, dictionary)


def _nullif(expr: BCall, table: DTable, sq) -> DCol:
    a, b = _args(expr, table, sq)
    if a.dtype == "str" or b.dtype == "str":
        ka, kb = _string_pair_keys(a, b)
        same = ka == kb
    else:
        ct = jnp.promote_types(a.data.dtype, b.data.dtype)
        same = a.data.astype(ct) == b.data.astype(ct)
    same = same & a.valid & b.valid
    return DCol(a.dtype, a.data, a.valid & ~same, a.dictionary, a.parts)


# -- casts & scalar functions ------------------------------------------------

def _halfup_rescale(data: jax.Array, from_scale: int,
                    to_scale: int) -> jax.Array:
    if to_scale >= from_scale:
        return data * 10 ** (to_scale - from_scale)
    factor = 10 ** (from_scale - to_scale)
    return jnp.sign(data) * ((jnp.abs(data) + factor // 2) // factor)


def _cast(expr: BCall, table: DTable, sq) -> DCol:
    # rescaling (decN targets/sources) multiplies by 10^k: widen narrow
    # lanes up front so the scale arithmetic runs at physical width
    a = widen_col(evaluate(expr.args[0], table, sq))
    target = expr.dtype
    if target == a.dtype:
        return a
    if a.dtype == "str":
        return _cast_from_str(a, target)
    if target == "str":
        return _cast_to_str(a)
    if is_dec(target):
        s = dec_scale(target)
        if is_dec(a.dtype):
            out = _halfup_rescale(a.data, dec_scale(a.dtype), s)
        elif a.dtype == "float":
            d = a.data.astype(_float_dtype()) * 10.0 ** s
            out = (jnp.floor(jnp.abs(d) + 0.5) * jnp.sign(d)) \
                .astype(phys_dtype(target))
        else:   # int/bool
            out = a.data.astype(phys_dtype(target)) * 10 ** s
        return DCol(target, out, a.valid)
    if is_dec(a.dtype):
        s = dec_scale(a.dtype)
        if target == "float":
            return DCol("float", a.data.astype(_float_dtype()) / 10.0 ** s,
                        a.valid)
        if target == "int":   # truncate toward zero (Spark decimal -> int)
            out = jnp.sign(a.data) * (jnp.abs(a.data) // 10 ** s)
            return DCol("int", out.astype(phys_dtype("int")), a.valid)
        raise NotImplementedError(f"cast {a.dtype} -> {target}")
    if target in ("int", "float", "date"):
        return DCol(target, a.data.astype(phys_dtype(target)), a.valid)
    raise NotImplementedError(f"cast to {target}")


def _cast_to_str(a: DCol) -> DCol:
    """Numeric/date -> string: dictionary-encode the distinct values on host.

    The output dictionary is data-dependent, so this runs eagerly only; a
    traced input aborts plan compilation (executor falls back to eager for
    such plans).
    """
    if isinstance(a.data, jax.core.Tracer):
        raise NotImplementedError(
            "cast to string needs a data-dependent dictionary (host)")
    from ..exprs import _sql_str

    data = np.asarray(a.data)
    uniq_raw, inverse = np.unique(data, return_inverse=True)
    if a.dtype == "date":
        strs = [str(np.datetime64(int(v), "D").item()) for v in uniq_raw]
    elif is_dec(a.dtype):
        import decimal
        strs = [_sql_str(decimal.Decimal(int(v)).scaleb(-dec_scale(a.dtype)))
                for v in uniq_raw]
    else:
        strs = [_sql_str(v) for v in uniq_raw]
    uniq, remap = np.unique(np.asarray(strs, dtype=object).astype(str),
                            return_inverse=True)
    codes = remap.astype(np.int32)[inverse]
    return DCol("str", jnp.asarray(codes), a.valid, uniq.astype(object))


def _cast_from_str(a: DCol, target: str) -> DCol:
    """Parse the dictionary on the host; codes gather the parsed values."""
    import decimal
    d = _dict(a)
    vals = np.zeros(max(len(d), 1),
                    dtype=np.int64 if is_dec(target) else
                    {"int": np.int64, "float": np.float64,
                     "date": np.int32}[target])
    ok = np.zeros(max(len(d), 1), dtype=bool)
    for i, v in enumerate(d):
        try:
            if target == "date":
                vals[i] = np.datetime64(v, "D").astype(np.int32)
            elif target == "int":
                vals[i] = int(float(v))
            elif is_dec(target):
                vals[i] = int(decimal.Decimal(v).scaleb(dec_scale(target))
                              .to_integral_value(decimal.ROUND_HALF_UP))
            else:
                vals[i] = float(v)
            ok[i] = True
        except (ValueError, TypeError, decimal.InvalidOperation):
            pass
    out = _lut_gather(a.data, vals).astype(phys_dtype(target))
    valid = a.valid & _lut_gather(a.data, ok)
    return DCol(target, jnp.where(valid, out, 0), valid)


def _substr(expr: BCall, table: DTable, sq) -> DCol:
    a = evaluate(expr.args[0], table, sq)
    start, length = expr.extra
    d = _dict(a)
    lo = start - 1 if start > 0 else 0
    hi = None if length is None else lo + length
    newd = np.asarray([v[lo:hi] for v in d.astype(str)], dtype=object)
    if len(newd) == 0:
        return DCol("str", a.data, a.valid, np.empty(0, dtype=object))
    uniq, remap = np.unique(newd.astype(str), return_inverse=True)
    codes = _lut_gather(a.data, remap.astype(np.int32))
    return DCol("str", codes, a.valid, uniq.astype(object))


def _case_map(fn):
    """Row-wise string transform as a dictionary transform (host-side map
    over the distinct values; codes re-gather on device — strings never
    reach the accelerator)."""
    def run(expr: BCall, table: DTable, sq) -> DCol:
        a = evaluate(expr.args[0], table, sq)
        if a.dtype != "str":
            raise NotImplementedError("string transform on non-string")
        d = _dict(a)
        if len(d) == 0:
            return DCol("str", a.data, a.valid, np.empty(0, dtype=object))
        newd = np.asarray([fn(v) for v in d.astype(str)], dtype=object)
        uniq, remap = np.unique(newd.astype(str), return_inverse=True)
        codes = _lut_gather(a.data, remap.astype(np.int32))
        return DCol("str", codes, a.valid, uniq.astype(object))
    return run


def _concat(expr: BCall, table: DTable, sq) -> DCol:
    cols = _args(expr, table, sq)
    parts: list[DCol] = []
    valid = None
    for c in cols:
        if c.dtype != "str":
            raise NotImplementedError("device concat of non-string")
        valid = c.valid if valid is None else (valid & c.valid)
        parts.extend(c.parts if c.parts is not None else (c,))
    return DCol("str", jnp.zeros(len(cols[0]), jnp.int32), valid,
                None, tuple(parts))


def _abs(expr: BCall, table: DTable, sq) -> DCol:
    a = widen_col(evaluate(expr.args[0], table, sq))
    return DCol(a.dtype, jnp.abs(a.data), a.valid)


def _round(expr: BCall, table: DTable, sq) -> DCol:
    a = widen_col(evaluate(expr.args[0], table, sq))
    digits = expr.extra if expr.extra is not None else 0
    if is_dec(a.dtype) and is_dec(expr.dtype):
        # negative digits: round to tens/hundreds, then restore scale 0
        out = _halfup_rescale(a.data, dec_scale(a.dtype), int(digits))
        out = out * 10 ** (dec_scale(expr.dtype) - int(digits))
        return DCol(expr.dtype, out, a.valid)
    data = _to_float(a)
    scale = 10.0 ** digits
    out = jnp.floor(jnp.abs(data) * scale + 0.5) / scale * jnp.sign(data)
    if expr.dtype == "int":
        return DCol("int", out.astype(phys_dtype("int")), a.valid)
    return DCol("float", out, a.valid)


def _grouping_bit(expr: BCall, table: DTable, sq) -> DCol:
    a = decode_col(evaluate(expr.args[0], table, sq))
    bit = int(expr.extra)
    out = (a.data.astype(phys_dtype("int")) >> bit) & 1
    return DCol("int", out, a.valid)


_HANDLERS = {
    "add": _arith("add"), "sub": _arith("sub"), "mul": _arith("mul"),
    "div": _arith("div"), "mod": _arith("mod"), "neg": _neg,
    "ratdiv_hi": _ratdiv("hi"), "ratdiv_lo": _ratdiv("lo"),
    "eq": _compare("eq"), "ne": _compare("ne"), "lt": _compare("lt"),
    "le": _compare("le"), "gt": _compare("gt"), "ge": _compare("ge"),
    "and": _and, "or": _or, "not": _not,
    "isnull": _isnull, "isnotnull": _isnotnull,
    "in_list": _in_list, "like": _like,
    "case": _case, "coalesce": _coalesce, "cast": _cast,
    "substr": _substr, "concat": _concat, "abs": _abs, "round": _round,
    "upper": _case_map(str.upper), "lower": _case_map(str.lower),
    "nullif": _nullif, "grouping_bit": _grouping_bit,
}
