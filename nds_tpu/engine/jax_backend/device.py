"""Device-resident columnar data for the JAX execution backend.

Static-shape discipline (the XLA contract): every table lives in a padded
buffer of `capacity` rows with an `alive` row mask; relational ops never
change capacity mid-kernel, so each kernel compiles once per shape bucket.
Strings are dictionary codes (int32) on device; dictionaries stay on the
host and string compute happens on the dictionary (trace-time LUTs).

This is the TPU analog of the reference's cuDF columns on GPU (reference
nds/nds_transcode.py + RAPIDS plugin do columnar compute on device; here
the columnar compute is XLA programs over padded arrays).
"""
from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..column import Column, Table, is_dec, phys_np

_NULL_CODE = -1


# Below this row count, capacities are powers of two (few program shapes,
# compile-cache friendly). Above it, gather/sort cost scales with CAP and a
# 2x step overshoots the actual row count by 1.5x on average (PERF.md r5
# headroom #2), so the ladder gains 3*2^(k-1) midpoints — 4M, 6M, 8M, 12M,
# 16M, 24M... — bounding overshoot at 1.5x for a bounded set of extra
# program shapes. Midpoints keep every power-of-two divisor up to 2^(k-1),
# so mesh sharding (capacity % mesh.size == 0) is unaffected.
CAP_LADDER_MIN = 4 << 20


def bucket(n: int, minimum: int = 8) -> int:
    """Round a row count up to the capacity ladder: powers of two, plus
    3*2^(k-1) midpoints above CAP_LADDER_MIN rows."""
    c = max(int(n), minimum)
    p = 1 << (c - 1).bit_length()
    if p > CAP_LADDER_MIN:
        mid = 3 * (p >> 2)          # 0.75 * p, the step between p/2 and p
        if c <= mid:
            return mid
    return p


def phys_dtype(logical: str):
    x64 = jax.config.read("jax_enable_x64")
    if is_dec(logical):
        # scaled-int decimal: exact under x64 (TPU S64 is emulated dual-i32
        # — adds/compares, no MXU needed); i32 without x64 bounds SF (the
        # bench path keeps decimal_physical="f64" there)
        return jnp.int64 if x64 else jnp.int32
    return {
        "int": jnp.int64 if x64 else jnp.int32,
        "float": jnp.float64 if x64 else jnp.float32,
        "bool": jnp.bool_,
        "date": jnp.int32,
        "str": jnp.int32,
    }[logical]


@dataclass
class DCol:
    """A device column: padded values + always-materialized validity mask.

    Invariant: slots that are null (or dead rows) hold canonical zeros so
    grouping/sorting kernels see deterministic payloads.
    """
    dtype: str                 # logical: int | float | bool | date | str
    data: jax.Array
    valid: jax.Array           # bool, same length
    dictionary: Optional[np.ndarray] = None  # host object array for "str"
    parts: Optional[tuple] = None  # compound string: tuple[DCol] (lazy concat)

    def __len__(self) -> int:
        return int(self.data.shape[0])

    def canon(self) -> "DCol":
        zero = jnp.zeros((), dtype=self.data.dtype)
        return replace(self, data=jnp.where(self.valid, self.data, zero))


@dataclass
class DTable:
    names: list[str]
    cols: list[DCol]
    alive: jax.Array           # bool row mask, length == capacity

    @property
    def capacity(self) -> int:
        return int(self.alive.shape[0])

    def count(self) -> jax.Array:
        return jnp.sum(self.alive.astype(jnp.int32))


# -- pytree registration ------------------------------------------------------
# DCol/DTable flow through jax.jit as arguments and results of compiled whole
# -plan programs (executor.CompiledQuery). Dictionaries are host-side objects:
# they ride in aux_data, hashable by identity (scan caches keep them stable
# across calls, so jit cache keys match).

class _ById:
    """Identity-hashed wrapper so host objects can sit in pytree aux_data."""
    __slots__ = ("obj",)

    def __init__(self, obj):
        self.obj = obj

    def __hash__(self):
        return id(self.obj)

    def __eq__(self, other):
        return isinstance(other, _ById) and other.obj is self.obj


def _dcol_flatten(c: DCol):
    return (c.data, c.valid, c.parts), (c.dtype, _ById(c.dictionary))


def _dcol_unflatten(aux, children):
    data, valid, parts = children
    return DCol(aux[0], data, valid, aux[1].obj, parts)


def _dtable_flatten(t: DTable):
    return (t.cols, t.alive), tuple(t.names)


def _dtable_unflatten(aux, children):
    cols, alive = children
    return DTable(list(aux), cols, alive)


jax.tree_util.register_pytree_node(DCol, _dcol_flatten, _dcol_unflatten)
jax.tree_util.register_pytree_node(DTable, _dtable_flatten, _dtable_unflatten)


# -- host <-> device bridging ------------------------------------------------

def to_device(table: Table, capacity: Optional[int] = None,
              device=None) -> DTable:
    from ...resilience import FAULTS
    FAULTS.fire("device.put")
    n = table.num_rows
    cap = capacity if capacity is not None else bucket(n)

    def put(arr):
        return jnp.asarray(arr) if device is None \
            else jax.device_put(arr, device)

    cols = []
    for c in table.columns:
        data = np.asarray(c.data)
        dt = phys_dtype(c.dtype)
        buf = np.zeros(cap, dtype=np.dtype(dt))
        v = np.zeros(cap, dtype=bool)
        v[:n] = c.validity
        buf[:n] = np.where(c.validity, data, 0)
        if c.dtype == "str":
            # canonical null slot for codes is 0 (valid=False marks them)
            buf[:n] = np.where(c.validity & (data >= 0), data, 0)
        cols.append(DCol(c.dtype, put(buf), put(v), c.dictionary))
    alive = np.zeros(cap, dtype=bool)
    alive[:n] = True
    return DTable(list(table.names), cols, put(alive))


@dataclass
class PackedTable:
    """A columnar table packed for ONE-transfer upload through a tunneled
    device link: all column payloads ride in a single (ncols, cap) int64
    matrix (floats bit-cast, narrow ints widened) and all masks in one
    (ncols+1, cap) bool matrix whose last row is the alive mask. Per-column
    transfers cost a fixed RTT each on tunneled platforms — a streamed
    morsel paid ~2*ncols RTTs per dispatch; packed it pays 2. Columns
    unpack INSIDE the traced program (slice/bitcast fuse into the compiled
    plan). Requires x64 (the i64 carrier) and no string columns (morsel
    eligibility already excludes big-scan strings)."""
    names: list[str]
    dtypes: list[str]           # logical dtypes
    modes: tuple                # per column: "i64" | "f64bits" | "i32"
    data: jax.Array             # (ncols, cap) int64
    masks: jax.Array            # (ncols + 1, cap) bool; last row = alive

    @property
    def capacity(self) -> int:
        return int(self.masks.shape[1])


def _packed_flatten(p: PackedTable):
    return (p.data, p.masks), (tuple(p.names), tuple(p.dtypes), p.modes)


def _packed_unflatten(aux, children):
    data, masks = children
    return PackedTable(list(aux[0]), list(aux[1]), aux[2], data, masks)


jax.tree_util.register_pytree_node(PackedTable, _packed_flatten,
                                   _packed_unflatten)


def pack_table(table: Table, capacity: Optional[int] = None
               ) -> Optional[PackedTable]:
    """Host-side packing for upload; None if the table can't pack (strings,
    or x32 mode where the i64 carrier is unavailable)."""
    if not jax.config.read("jax_enable_x64"):
        return None
    # gate on every column BEFORE allocating the carrier (a mid-loop bail
    # would waste the (ncols, cap) allocation per morsel on the fallback)
    if any(c.dtype == "str" or np.dtype(phys_dtype(c.dtype)) not in
           (np.dtype(np.int64), np.dtype(np.float64), np.dtype(np.int32))
           for c in table.columns):
        return None
    n = table.num_rows
    cap = capacity if capacity is not None else bucket(n)
    ncols = len(table.columns)
    data = np.zeros((ncols, cap), dtype=np.int64)
    masks = np.zeros((ncols + 1, cap), dtype=bool)
    masks[ncols, :n] = True
    modes = []
    for i, c in enumerate(table.columns):
        pd = np.dtype(phys_dtype(c.dtype))
        buf = np.zeros(cap, dtype=pd)
        buf[:n] = np.where(c.validity, np.asarray(c.data), 0)
        if pd == np.float64:
            data[i] = buf.view(np.int64)
            modes.append("f64bits")
        elif pd == np.int32:
            data[i] = buf.astype(np.int64)
            modes.append("i32")
        else:
            data[i] = buf
            modes.append("i64")
        masks[i, :n] = c.validity
    return PackedTable(list(table.names), [c.dtype for c in table.columns],
                       tuple(modes), jnp.asarray(data), jnp.asarray(masks))


def unpack_table(p: PackedTable) -> DTable:
    """Traced (or concrete) unpacking back into per-column device arrays."""
    from jax import lax

    cols = []
    for i, (dtype, mode) in enumerate(zip(p.dtypes, p.modes)):
        row = p.data[i]
        if mode == "f64bits":
            d = lax.bitcast_convert_type(row, jnp.float64)
        elif mode == "i32":
            d = row.astype(jnp.int32)
        else:
            d = row
        cols.append(DCol(dtype, d, p.masks[i]))
    return DTable(list(p.names), cols, p.masks[len(p.dtypes)])


def device_bytes(dt: "Optional[DTable | PackedTable]") -> int:
    """Device bytes held by a table (DTable or PackedTable — any pytree of
    device arrays). Streaming uses it to account uploaded morsel bytes
    (last_exec_stats.bytes_uploaded): on tunneled platforms upload volume
    is the cost the shared scan divides by the branch count."""
    if dt is None:
        return 0
    return sum(int(leaf.size) * leaf.dtype.itemsize
               for leaf in jax.tree_util.tree_leaves(dt)
               if hasattr(leaf, "size") and hasattr(leaf, "dtype"))


def free_dtable(dt: "Optional[DTable | PackedTable]") -> None:
    """Explicitly release a cached entry's device buffers (DTable or
    PackedTable — any pytree of device arrays).

    Dropping the Python reference leaves freeing to gc timing, and tunneled
    platforms can pin uploads client-side — streaming loops that rebind a
    morsel buffer hundreds of times must free eagerly or accumulate the
    whole scan on the host."""
    if dt is None:
        return
    for leaf in jax.tree_util.tree_leaves(dt):
        if hasattr(leaf, "delete"):
            try:
                leaf.delete()
            except Exception:
                pass


def to_host(dt: DTable, count: Optional[int] = None) -> Table:
    """Materialize a device table back into a host Table (compacted).

    All buffers come back in ONE device_get: on tunneled platforms each
    D2H transfer pays a fixed RTT, so per-column np.asarray would multiply
    that latency by the column count.
    """
    dt = jax.device_get(dt)
    alive = np.asarray(dt.alive)
    idx = np.flatnonzero(alive)
    if count is not None:
        idx = idx[:count]
    cols = []
    for c in dt.cols:
        c = _flatten_compound(c)
        data = np.asarray(c.data)[idx]
        valid = np.asarray(c.valid)[idx]
        if c.dtype == "str":
            data = np.where(valid, data, _NULL_CODE).astype(np.int32)
        host_dtype = phys_np(c.dtype)
        cols.append(Column(c.dtype, data.astype(host_dtype),
                           None if bool(valid.all()) else valid, c.dictionary))
    return Table(list(dt.names), cols)


def _flatten_compound(c: DCol) -> DCol:
    """Materialize a lazy-concat compound string column into a real dictionary.

    Concrete path: string appends run once per *distinct* part-code tuple
    (rows deduplicated over stacked codes). Traced path (inside a compiled
    plan): the output dictionary must be data-INdependent, so it becomes the
    mixed-radix cross product of the part dictionaries (+ an empty-string
    slot per part for null/invalid codes) and row codes are computed on
    device — sized like the id-column dictionary for the typical
    literal||column||literal concat.
    """
    if c.parts is None:
        return c
    if any(isinstance(p.data, jax.core.Tracer) for p in c.parts) or \
            isinstance(c.valid, jax.core.Tracer):
        return _flatten_compound_traced(c)
    code_mat = np.stack([np.where(np.asarray(p.valid), np.asarray(p.data), -1)
                         for p in c.parts], axis=1)
    uniq_rows, inverse = np.unique(code_mat, axis=0, return_inverse=True)
    joined = np.full(len(uniq_rows), "", dtype=object)
    for j, p in enumerate(c.parts):
        d = p.dictionary if p.dictionary is not None else np.empty(0, dtype=object)
        codes = uniq_rows[:, j]
        safe = np.clip(codes, 0, max(len(d) - 1, 0))
        vals = np.where(codes >= 0,
                        d[safe] if len(d) else "", "")
        joined = np.asarray([a + b for a, b in zip(joined, vals)], dtype=object)
    uniq, remap = np.unique(joined.astype(str), return_inverse=True)
    codes = remap.astype(np.int32)[inverse]
    return DCol("str", jnp.asarray(codes), c.valid, uniq.astype(object))


def _flatten_compound_traced(c: DCol) -> DCol:
    """Trace-safe compound flatten: cross-product dictionary, device codes."""
    dicts = []
    for p in c.parts:
        d = p.dictionary if p.dictionary is not None \
            else np.empty(0, dtype=object)
        # slot len(d) holds "" for null/invalid part codes
        dicts.append(np.concatenate([d.astype(object),
                                     np.asarray([""], dtype=object)]))
    total = 1
    for d in dicts:
        total *= len(d)
    if total > (1 << 20):
        raise NotImplementedError(
            f"compound string cross dictionary too large ({total})")
    # mixed-radix joined dictionary, last part fastest-varying
    joined = np.asarray([""], dtype=object)
    for d in dicts:
        joined = np.asarray([a + b for a in joined for b in d], dtype=object)
    code = jnp.zeros(c.parts[0].data.shape, jnp.int32)
    for p, d in zip(c.parts, dicts):
        n = len(d)
        eff = jnp.where(p.valid & (p.data >= 0),
                        jnp.clip(p.data, 0, n - 2 if n > 1 else 0),
                        n - 1).astype(jnp.int32)
        code = code * n + eff
    return DCol("str", code, c.valid, joined)


def string_rank_maps(dictionary: Optional[np.ndarray]
                     ) -> tuple[np.ndarray, np.ndarray]:
    """Host LUTs for a string dictionary: (code -> dense lexicographic rank,
    dense rank -> representative code).

    Equal strings get EQUAL ranks (dictionaries from compound cross products
    may contain duplicates; distinct ranks would break equality compares),
    so mapping an aggregated rank back to a code must go through the
    rank->code table — NOT through argsort position.
    """
    if dictionary is None or len(dictionary) == 0:
        return np.zeros(1, dtype=np.int32), np.zeros(1, dtype=np.int32)
    vals = dictionary.astype(str)
    order = np.argsort(vals, kind="stable")
    svals = vals[order]
    dense = np.cumsum(np.concatenate(
        [[0], (svals[1:] != svals[:-1]).astype(np.int32)])).astype(np.int32)
    ranks = np.empty(len(vals), dtype=np.int32)
    ranks[order] = dense
    rank_to_code = np.zeros(int(dense[-1]) + 1, dtype=np.int32)
    # reversed assignment => the FIRST occurrence in sorted order wins
    rank_to_code[dense[::-1]] = order[::-1].astype(np.int32)
    return ranks, rank_to_code


def string_rank_lut(dictionary: Optional[np.ndarray]) -> np.ndarray:
    """Host LUT: dictionary code -> dense lexicographic rank."""
    return string_rank_maps(dictionary)[0]


def rank_key(c: DCol) -> jax.Array:
    """Device array usable as a grouping/ordering key for any logical dtype."""
    c = _flatten_compound(c)
    if c.dtype == "str":
        lut = jnp.asarray(string_rank_lut(c.dictionary))
        safe = jnp.clip(c.data, 0, lut.shape[0] - 1)
        return jnp.where(c.valid, lut[safe], 0)
    if c.dtype == "bool":
        return jnp.where(c.valid, c.data.astype(jnp.int32), 0)
    return jnp.where(c.valid, c.data, jnp.zeros((), dtype=c.data.dtype))
