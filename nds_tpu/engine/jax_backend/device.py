"""Device-resident columnar data for the JAX execution backend.

Static-shape discipline (the XLA contract): every table lives in a padded
buffer of `capacity` rows with an `alive` row mask; relational ops never
change capacity mid-kernel, so each kernel compiles once per shape bucket.
Strings are dictionary codes (int32) on device; dictionaries stay on the
host and string compute happens on the dictionary (trace-time LUTs).

This is the TPU analog of the reference's cuDF columns on GPU (reference
nds/nds_transcode.py + RAPIDS plugin do columnar compute on device; here
the columnar compute is XLA programs over padded arrays).
"""
from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..column import Column, Table, is_dec, phys_np

_NULL_CODE = -1


# Below this row count, capacities are powers of two (few program shapes,
# compile-cache friendly). Above it, gather/sort cost scales with CAP and a
# 2x step overshoots the actual row count by 1.5x on average (PERF.md r5
# headroom #2), so the ladder gains 3*2^(k-1) midpoints — 4M, 6M, 8M, 12M,
# 16M, 24M... — bounding overshoot at 1.5x for a bounded set of extra
# program shapes. Midpoints keep every power-of-two divisor up to 2^(k-1),
# so mesh sharding (capacity % mesh.size == 0) is unaffected.
CAP_LADDER_MIN = 4 << 20


def bucket(n: int, minimum: int = 8) -> int:
    """Round a row count up to the capacity ladder: powers of two, plus
    3*2^(k-1) midpoints above CAP_LADDER_MIN rows."""
    c = max(int(n), minimum)
    p = 1 << (c - 1).bit_length()
    if p > CAP_LADDER_MIN:
        mid = 3 * (p >> 2)          # 0.75 * p, the step between p/2 and p
        if c <= mid:
            return mid
    return p


def phys_dtype(logical: str):
    x64 = jax.config.read("jax_enable_x64")
    if is_dec(logical):
        # scaled-int decimal: exact under x64 (TPU S64 is emulated dual-i32
        # — adds/compares, no MXU needed); i32 without x64 bounds SF (the
        # bench path keeps decimal_physical="f64" there)
        return jnp.int64 if x64 else jnp.int32
    return {
        "int": jnp.int64 if x64 else jnp.int32,
        "float": jnp.float64 if x64 else jnp.float32,
        "bool": jnp.bool_,
        "date": jnp.int32,
        "str": jnp.int32,
    }[logical]


@dataclass
class DCol:
    """A device column: padded values + always-materialized validity mask.

    Invariant: slots that are null (or dead rows) hold canonical zeros so
    grouping/sorting kernels see deterministic payloads.

    `codebook` (encoded execution): when set, `data` holds int32 CODES
    indexing this host-side SORTED array of engine-unit values (int/date/
    decN columns dictionary-encoded on the wire). The sorted order makes
    codes order-isomorphic to values, so filters, join keys, group keys and
    sorts run directly on the codes; `decode_col` materializes values only
    at arithmetic/aggregate/output sites (the generalization of the
    narrow-lane `widen_col` deferral from width to encoding). Null slots
    hold code 0 with valid=False, exactly like plain columns hold value 0.
    """
    dtype: str                 # logical: int | float | bool | date | str
    data: jax.Array
    valid: jax.Array           # bool, same length
    dictionary: Optional[np.ndarray] = None  # host object array for "str"
    parts: Optional[tuple] = None  # compound string: tuple[DCol] (lazy concat)
    codebook: Optional[np.ndarray] = None  # sorted engine-unit values

    def __len__(self) -> int:
        return int(self.data.shape[0])

    def canon(self) -> "DCol":
        zero = jnp.zeros((), dtype=self.data.dtype)
        return replace(self, data=jnp.where(self.valid, self.data, zero))


@dataclass
class DTable:
    names: list[str]
    cols: list[DCol]
    alive: jax.Array           # bool row mask, length == capacity

    @property
    def capacity(self) -> int:
        return int(self.alive.shape[0])

    def count(self) -> jax.Array:
        return jnp.sum(self.alive.astype(jnp.int32))


# -- pytree registration ------------------------------------------------------
# DCol/DTable flow through jax.jit as arguments and results of compiled whole
# -plan programs (executor.CompiledQuery). Dictionaries are host-side objects:
# they ride in aux_data, hashable by identity (scan caches keep them stable
# across calls, so jit cache keys match).

class _ById:
    """Identity-hashed wrapper so host objects can sit in pytree aux_data."""
    __slots__ = ("obj",)

    def __init__(self, obj):
        self.obj = obj

    def __hash__(self):
        return id(self.obj)

    def __eq__(self, other):
        return isinstance(other, _ById) and other.obj is self.obj


class _ByIds:
    """Element-identity-hashed wrapper for a TUPLE of host objects.

    PackedTable aux carries per-column host arrays (dictionaries,
    codebooks) in a tuple rebuilt on every pack; hashing the TUPLE by
    identity (_ById) made every morsel a fresh jit cache key — the
    compiled per-morsel program re-traced morsel after morsel even though
    the actual host objects (None slots, group-stable codebooks) never
    changed. Hashing by the ELEMENT identities keeps one cache entry per
    actual layout. The wrapper keeps the objects referenced, so their ids
    cannot be recycled while a cache key is alive."""
    __slots__ = ("objs", "_ids")

    def __init__(self, objs):
        self.objs = tuple(objs) if objs is not None else None
        self._ids = None if self.objs is None else \
            tuple(id(o) for o in self.objs)

    def __hash__(self):
        return hash(self._ids)

    def __eq__(self, other):
        return isinstance(other, _ByIds) and other._ids == self._ids

    @property
    def obj(self):
        return self.objs


def _dcol_flatten(c: DCol):
    return (c.data, c.valid, c.parts), (c.dtype, _ById(c.dictionary),
                                        _ById(c.codebook))


def _dcol_unflatten(aux, children):
    data, valid, parts = children
    return DCol(aux[0], data, valid, aux[1].obj, parts, aux[2].obj)


def _dtable_flatten(t: DTable):
    return (t.cols, t.alive), tuple(t.names)


def _dtable_unflatten(aux, children):
    cols, alive = children
    return DTable(list(aux), cols, alive)


jax.tree_util.register_pytree_node(DCol, _dcol_flatten, _dcol_unflatten)
jax.tree_util.register_pytree_node(DTable, _dtable_flatten, _dtable_unflatten)


# -- host <-> device bridging ------------------------------------------------

def _mem_leaves(dt) -> list:
    """[(id, nbytes)] of a pytree's device-array leaves — the unit the
    device-memory watermark accountant (obs/profile.DEVICE_MEM) tracks.
    Identity-keyed so add/free stay balanced even when the same buffer
    flows through several caches."""
    return [(id(leaf), int(leaf.size) * leaf.dtype.itemsize)
            for leaf in jax.tree_util.tree_leaves(dt)
            if hasattr(leaf, "size") and hasattr(leaf, "dtype")]


def to_device(table: Table, capacity: Optional[int] = None,
              device=None) -> DTable:
    from ...obs.profile import DEVICE_MEM
    from ...obs.trace import TRACER
    from ...resilience import FAULTS
    FAULTS.fire("device.put")
    n = table.num_rows
    cap = capacity if capacity is not None else bucket(n)
    with TRACER.span("upload", cat="upload", rows=n,
                     cols=len(table.columns), capacity=cap):
        out = _to_device(table, n, cap, device)
    DEVICE_MEM.add(_mem_leaves(out))
    return out


def _to_device(table: Table, n: int, cap: int, device) -> DTable:

    def put(arr):
        return jnp.asarray(arr) if device is None \
            else jax.device_put(arr, device)

    cols = []
    for c in table.columns:
        data = np.asarray(c.data)
        dt = phys_dtype(c.dtype)
        buf = np.zeros(cap, dtype=np.dtype(dt))
        v = np.zeros(cap, dtype=bool)
        v[:n] = c.validity
        buf[:n] = np.where(c.validity, data, 0)
        if c.dtype == "str":
            # canonical null slot for codes is 0 (valid=False marks them)
            buf[:n] = np.where(c.validity & (data >= 0), data, 0)
        cols.append(DCol(c.dtype, put(buf), put(v), c.dictionary))
    alive = np.zeros(cap, dtype=bool)
    alive[:n] = True
    return DTable(list(table.names), cols, put(alive))


# -- narrow-lane packed layout ------------------------------------------------
# Per-column physical lane on the tunnel wire. The device unpacks lazily
# (slice + bitcast + widen fuse into the compiled program), so the wire
# width and the device compute width are decoupled:
#
#   lane   wire bytes/row   device array      legal for
#   "b1"   1/8 (bit-packed) bool              bool
#   "u8"   1                int32             int, decN, date, str
#   "u16"  2                int32             int, decN, date, str
#   "u32"  4                int32             int, decN  (values < 2^31)
#   "i32"  4                int32             int, decN, date, str
#   "i64"  8                int64             int, decN       (x64 only)
#   "f32"  4                float32 (bitcast) float           (no-x64 tier)
#   "f64"  8                float64 (bitcast) float           (x64 only)
#
# Narrow unsigned lanes require non-negative values; every lane's value
# bounds are in _LANE_BOUNDS and packing VERIFIES the data fits (a lane
# too narrow for its column is a hard error, not silent truncation).
# Unpack targets are always SIGNED (i32/i64) so downstream sort/compare/
# negate kernels never meet unsigned wraparound; int columns whose range
# fits 32 bits execute on i32 device arrays — on chips that emulate S64
# as dual u32, filters/join keys/group keys over such columns run at half
# the gather/sort cost ("encoded execution"). 64-bit widening happens
# only at arithmetic/aggregation sites (see jexprs.widen_col callers).

_LANE_WIRE = {"b1": 0, "u8": 1, "u16": 2, "u32": 4, "i32": 4,
              "i64": 8, "f32": 4, "f64": 8}   # b1: special-cased, cap/8 B

# inclusive [lo, hi] value bounds per integer lane. i32 excludes INT32_MIN:
# descending sort negates key lanes in place and -INT32_MIN would wrap,
# breaking on/off bit-identity for that (pathological) value.
_LANE_BOUNDS = {
    "u8": (0, (1 << 8) - 1),
    "u16": (0, (1 << 16) - 1),
    "u32": (0, (1 << 31) - 1),
    "i32": (-(1 << 31) + 1, (1 << 31) - 1),
    "i64": (-(1 << 63), (1 << 63) - 1),
}

_LANE_NP = {"u8": np.uint8, "u16": np.uint16, "u32": np.uint32,
            "i32": np.int32, "i64": np.int64, "f32": np.float32,
            "f64": np.float64}


def lane_legal(lane: str, dtype: str) -> bool:
    """May a column of logical `dtype` ride this lane at all? (Static
    dtype-level legality; value-range legality is checked against stats by
    the verifier and against the actual data by pack_table.)"""
    if dtype == "float":
        return lane in ("f32", "f64")   # f32 = the no-x64 physical tier
    if dtype == "bool":
        return lane == "b1"
    if dtype in ("date", "str"):
        return lane in ("u8", "u16", "i32")
    if dtype == "int" or is_dec(dtype):
        return lane in ("u8", "u16", "u32", "i32", "i64")
    return False


def _lane_rows_bytes(lane: str, cap: int) -> int:
    if lane == "b1":
        return (cap + 7) // 8
    return _LANE_WIRE[lane] * cap


def lane_bytes(lanes: tuple, cap: int) -> int:
    """Total wire bytes of a packed table: per-column data sections plus
    (ncols + 1) bit-packed validity sections (last = alive mask)."""
    return sum(_lane_rows_bytes(ln, cap) for ln in lanes) + \
        (len(lanes) + 1) * ((cap + 7) // 8)


def _narrow_int_lane(lo: int, hi: int) -> str:
    if lo >= 0:
        for lane in ("u8", "u16", "u32"):
            if hi <= _LANE_BOUNDS[lane][1]:
                return lane
    if lo >= _LANE_BOUNDS["i32"][0] and hi <= _LANE_BOUNDS["i32"][1]:
        return "i32"
    return "i64"


def plan_lanes(dtypes: list, stats: Optional[list] = None,
               dict_sizes: Optional[list] = None,
               narrow: bool = True) -> Optional[tuple]:
    """Choose a per-column lane spec from logical dtypes + optional value
    stats. stats[i] is (min, max) in ENGINE units (scaled ints for decN,
    epoch days for date) or None (unknown -> widest legal lane, always
    safe); dict_sizes[i] is the dictionary cardinality for "str" columns.

    narrow=False restores the legacy wide layout (int/dec wire int64,
    date/str wire int32, floats f64; bool/str columns unpackable -> None,
    the per-column to_device fallback; requires x64 like the old int64
    carrier did) — the --no_narrow_lanes contract. Without x64, wide
    integer/float tiers are i32/f32 (the physical dtypes that mode runs
    anyway), so narrow packing works on the no-x64 tier too.

    Returns None when some column cannot pack at all."""
    x64 = jax.config.read("jax_enable_x64")
    if not narrow and not x64:
        return None
    wide_int = "i64" if x64 else "i32"
    lanes = []
    for i, dt in enumerate(dtypes):
        st = stats[i] if stats is not None else None
        if dt == "float":
            lanes.append("f64" if x64 else "f32")
        elif dt == "bool":
            if not narrow:
                return None
            lanes.append("b1")
        elif dt == "str":
            if not narrow:
                return None
            ds = dict_sizes[i] if dict_sizes is not None else None
            if ds is None:
                lanes.append("i32")
            elif ds <= _LANE_BOUNDS["u8"][1] + 1:
                lanes.append("u8")
            elif ds <= _LANE_BOUNDS["u16"][1] + 1:
                lanes.append("u16")
            else:
                lanes.append("i32")
        elif dt == "date":
            if not narrow or st is None:
                lanes.append("i32")
            else:
                lo, hi = int(st[0]), int(st[1])
                lane = _narrow_int_lane(lo, hi)
                lanes.append(lane if lane in ("u8", "u16") else "i32")
        elif dt == "int" or is_dec(dt):
            if not narrow or st is None:
                lanes.append(wide_int)
            else:
                lane = _narrow_int_lane(int(st[0]), int(st[1]))
                # no-x64 tier: values fit 32 bits by config contract
                lanes.append("i32" if lane == "i64" and not x64 else lane)
        else:
            return None
    return tuple(lanes)


class LaneOverflowError(ValueError):
    """A column's values do not fit its declared lane (stats drift or a
    rewrite bug) — surfaced loudly instead of wrapping silently."""


class EncodingOverflowError(ValueError):
    """A column's data violates its declared encoding spec — a value not in
    the planned dictionary, or more runs than the planned run capacity.
    Encoding specs are proven against recorded table stats (the verifier's
    "encoding" findings), so this means stats drift or a planner bug, and
    it surfaces loudly instead of shipping a wrong morsel."""


# -- encoded execution: per-column wire encodings -----------------------------
# The narrow-lane machinery generalized from *width* to *encoding*: a packed
# column may additionally ride one of
#
#   enc              wire layout (data section)             device view
#   "plain"          lane bytes * cap (the lane table)      values
#   ("dict", card)   CODE-lane bytes * cap; the sorted      i32 codes +
#                    value dictionary (codebook) stays      host codebook
#                    host-side, uploaded once per group     (DCol.codebook)
#   ("rle", runs)    value-lane bytes * runs_cap + i32      values (expanded
#                    run lengths * runs_cap                 on device)
#
# Encodings are chosen STATICALLY per scan group from per-table stats
# (cardinality for dict, total run count for rle — Session.column_enc_stats)
# so every morsel of a pass shares one compiled layout. Dictionary codebooks
# are SORTED, making codes order-isomorphic to values: execution stays on
# codes through filters/joins/group-bys/sorts and decodes per-site via
# decode_col. RLE expands at unpack (jnp.repeat with a static total), so it
# is purely wire compression — the unpacked arrays are bit-identical to the
# plain lane's. `runs` is the table-wide run-count BOUND: any contiguous
# morsel window holds at most that many runs, so the per-morsel run
# capacity derived from it can never overflow while the stats hold.

def _runs_cap(runs_bound: int, cap: int) -> int:
    """Static per-morsel run capacity for an RLE column: the table-wide
    bound (+1 for the capacity-pad run) bucketed, never above cap (every
    row its own run is always representable)."""
    return min(bucket(max(int(runs_bound) + 1, 8)), cap)


def enc_rows_bytes(lane: str, enc, cap: int) -> int:
    """Wire bytes of one column's data section under its encoding."""
    if isinstance(enc, tuple) and enc[0] == "rle":
        rc = _runs_cap(enc[1], cap)
        return _LANE_WIRE[lane] * rc + 4 * rc      # values + i32 lengths
    return _lane_rows_bytes(lane, cap)             # plain / dict codes


def _code_lane(card: int) -> Optional[str]:
    if card <= _LANE_BOUNDS["u8"][1] + 1:
        return "u8"
    if card <= _LANE_BOUNDS["u16"][1] + 1:
        return "u16"
    return None


def plan_encodings(dtypes: list, lanes: tuple, enc_stats: list,
                   cap_rows: int) -> Optional[tuple]:
    """Choose per-column encodings for a scan group from cardinality/run
    stats. `lanes` is the plan_lanes value-lane spec; `enc_stats[i]` is
    {"distinct": sorted np engine-unit array or None, "runs": int or None}
    or None (no stats -> plain, always safe). Returns
    (encs, wire_lanes, codebooks) — wire_lanes replaces dict columns' value
    lane with their code lane — or None when every column stays plain."""
    encs: list = []
    out_lanes: list = []
    books: list = []
    cap = bucket(max(int(cap_rows), 8))
    any_enc = False
    for dt, lane, st in zip(dtypes, lanes, enc_stats or [None] * len(lanes)):
        choice = ("plain", lane, None)
        if st and lane not in ("b1",) and dt not in ("str", "bool"):
            width = _LANE_WIRE[lane]
            best = width * cap                      # plain cost to beat
            dv = st.get("distinct")
            if dv is not None and dt != "float":
                dv = np.asarray(dv)
                book = dv.astype(np.int64 if lane == "i64" else np.int32)
                if len(book) == 0:
                    book = np.zeros(1, dtype=book.dtype)
                clane = _code_lane(len(book))
                if clane is not None and _LANE_WIRE[clane] < width:
                    cost = _LANE_WIRE[clane] * cap
                    if cost < best:
                        best = cost
                        choice = (("dict", len(book)), clane, book)
            runs = st.get("runs")
            if runs is not None:
                cost = enc_rows_bytes(lane, ("rle", int(runs)), cap)
                # rle must beat both plain and the dict candidate by 2x:
                # marginal savings don't earn the expansion pass
                if cost * 2 <= best:
                    choice = (("rle", int(runs)), lane, None)
        encs.append(choice[0])
        out_lanes.append(choice[1])
        books.append(choice[2])
        any_enc = any_enc or choice[0] != "plain"
    if not any_enc:
        return None
    return tuple(encs), tuple(out_lanes), tuple(books)


def enc_lane_bytes(lanes: tuple, cap: int, encs: Optional[tuple]) -> int:
    """lane_bytes generalized over encodings (None = all plain)."""
    if encs is None:
        return lane_bytes(lanes, cap)
    return sum(enc_rows_bytes(ln, e, cap) for ln, e in zip(lanes, encs)) + \
        (len(lanes) + 1) * ((cap + 7) // 8)


# -- device codebook cache (satellite: once-per-group dictionary upload) ------
# decode sites gather through the device copy of a group's codebook; the
# codebook object is morsel-invariant for a scan group, so the upload
# happens once and every later decode (and every later morsel's eager
# re-record) reuses it — counted via obs/metrics dict_uploads_saved.

_BOOK_CACHE: dict = {}          # id(book) -> (pinned np array, device array)
_BOOK_CACHE_MAX = 256

# decode-site observability: how many decode_col calls actually decoded,
# and how many column slots they materialized — the "execution stays on
# codes" evidence (a group key that never decodes at morsel scale shows up
# as decode_rows << morsels * capacity)
_DECODE_STATS = {"sites": 0, "rows": 0}


def decode_stats() -> dict:
    return dict(_DECODE_STATS)


def _codebook_device(book: np.ndarray) -> jax.Array:
    from ...obs.profile import DEVICE_MEM
    ent = _BOOK_CACHE.get(id(book))
    if ent is not None and ent[0] is book:
        from ...obs import metrics as _metrics
        _metrics.DICT_UPLOADS_SAVED.inc()
        return ent[1]
    if len(_BOOK_CACHE) >= _BOOK_CACHE_MAX:
        DEVICE_MEM.free([pair for e in _BOOK_CACHE.values()
                         for pair in _mem_leaves(e[1])])
        _BOOK_CACHE.clear()
    # the upload must happen OUTSIDE any live trace: a traced constant
    # would be a tracer, and caching a tracer across programs leaks it
    with jax.ensure_compile_time_eval():
        dev = jnp.asarray(book)
    _BOOK_CACHE[id(book)] = (book, dev)
    DEVICE_MEM.add(_mem_leaves(dev))
    return dev


def decode_col(c: DCol) -> DCol:
    """Materialize an encoded column's values: codes gather through the
    device-resident codebook (null/dead slots stay canonical zeros). The
    per-site decode seam — callers are the sites that genuinely need
    values: arithmetic/aggregate arguments, cross-codebook comparisons,
    and output materialization. Everything else (filters via trace-time
    literal remap, join keys, group keys, sorts) runs on the codes."""
    if c.codebook is None:
        return c
    book = _codebook_device(c.codebook)
    safe = jnp.clip(c.data, 0, book.shape[0] - 1)
    data = jnp.where(c.valid, book[safe], jnp.zeros((), book.dtype))
    _DECODE_STATS["sites"] += 1
    _DECODE_STATS["rows"] += int(c.data.shape[0])
    from ...obs import metrics as _metrics
    _metrics.DECODE_SITES.inc()
    return replace(c, data=data, codebook=None)


def encode_against(book: np.ndarray, c: DCol) -> jax.Array:
    """Map a PLAIN column's values into another column's code space: the
    exact code where the value is in the codebook, -1 (matches no code)
    otherwise. Join keys use this to keep the big encoded side on its i32
    codes — the small plain side pays one searchsorted instead of the big
    side paying a per-row decode."""
    dev = _codebook_device(book)
    vals = c.canon().data
    ct = jnp.promote_types(dev.dtype, vals.dtype)
    bw = dev.astype(ct)
    vw = vals.astype(ct)
    idx = jnp.clip(jnp.searchsorted(bw, vw), 0,
                   dev.shape[0] - 1).astype(jnp.int32)
    return jnp.where(bw[idx] == vw, idx, jnp.full((), -1, jnp.int32))


@dataclass
class PackedTable:
    """A columnar table packed for ONE-transfer upload through a tunneled
    device link: every column payload and every validity mask rides in a
    single contiguous uint8 buffer. Column sections use per-column narrow
    lanes (see the lane table above); validity masks (plus the alive mask,
    last) are bit-packed at 1 bit/row. Per-buffer transfers cost a fixed
    RTT each on tunneled platforms — a streamed morsel paid ~2*ncols RTTs
    per dispatch; packed it pays 1. Columns unpack INSIDE the traced
    program as zero-copy views (slice/bitcast/unpackbits fuse into the
    compiled plan). The lane spec is pytree aux_data, so compiled-program
    cache keys include the physical layout and a lane change can never
    replay a stale program. Requires x64 (i64/f64 lanes)."""
    names: list[str]
    dtypes: list[str]           # logical dtypes
    lanes: tuple                # per-column WIRE lane tags (code lane for
    #                             dict-encoded columns), see _LANE_WIRE
    cap: int                    # padded row capacity
    data: jax.Array             # uint8[enc_lane_bytes(lanes, cap, encs)]
    dictionaries: tuple = ()    # host dictionaries for "str" columns
    # per-column encoding tags ("plain" | ("dict", card) | ("rle", runs
    # bound)); () = all plain (the pre-encoding layout, byte-identical)
    encs: tuple = ()
    codebooks: tuple = ()       # host sorted value arrays for dict columns

    @property
    def capacity(self) -> int:
        return self.cap

    def col_enc(self, i: int):
        return self.encs[i] if self.encs else "plain"


def _packed_flatten(p: PackedTable):
    return (p.data,), (tuple(p.names), tuple(p.dtypes), p.lanes, p.cap,
                       _ByIds(p.dictionaries), p.encs, _ByIds(p.codebooks))


def _packed_unflatten(aux, children):
    return PackedTable(list(aux[0]), list(aux[1]), aux[2], aux[3],
                       children[0], aux[4].obj, aux[5], aux[6].obj)


jax.tree_util.register_pytree_node(PackedTable, _packed_flatten,
                                   _packed_unflatten)


def pack_table(table: Table, capacity: Optional[int] = None,
               lanes: Optional[tuple] = None, encs: Optional[tuple] = None,
               codebooks: Optional[tuple] = None) -> Optional[PackedTable]:
    """Host-side packing for upload; None if the table can't pack under the
    given lane spec (default: the legacy wide layout, which rejects
    strings/bools exactly like the pre-lane int64 carrier did).

    `lanes` is the STATIC per-column lane spec: streaming computes it once
    per scan group from table-wide column stats and passes it for every
    morsel, so morsel widths never drift mid-stream (a width change would
    be a different compiled program). Values are VERIFIED against the lane
    bounds — stats drift raises LaneOverflowError instead of wrapping."""
    if lanes is None:
        lanes = plan_lanes([c.dtype for c in table.columns], narrow=False)
        if lanes is None:
            return None
    if not jax.config.read("jax_enable_x64") and \
            any(ln in ("i64", "f64") for ln in lanes):
        return None     # 64-bit lanes unrepresentable on the no-x64 tier
    if len(lanes) != len(table.columns):
        raise ValueError(f"{len(lanes)} lanes for {len(table.columns)} "
                         "columns")
    n = table.num_rows
    cap = capacity if capacity is not None else bucket(n)
    from ...obs.trace import TRACER
    from ...resilience import FAULTS
    # the packed-upload twin of to_device's fault point: streamed morsels
    # ride this path exclusively, so chaos campaigns arming device.put
    # must reach them too (one firing per staged morsel upload)
    FAULTS.fire("device.put")
    with TRACER.span("lane.pack", cat="upload", rows=n,
                     cols=len(table.columns), capacity=cap):
        out = _pack_table(table, lanes, n, cap, encs, codebooks)
    from ...obs.profile import DEVICE_MEM
    DEVICE_MEM.add(_mem_leaves(out))
    return out


def _pack_table(table: Table, lanes: tuple, n: int, cap: int,
                encs: Optional[tuple] = None,
                codebooks: Optional[tuple] = None) -> PackedTable:
    payload, dicts = _pack_payload(table, lanes, n, cap, encs, codebooks)
    return PackedTable(list(table.names), [c.dtype for c in table.columns],
                       tuple(lanes), cap, jnp.asarray(payload), tuple(dicts),
                       tuple(encs) if encs else (),
                       tuple(codebooks) if codebooks else ())


def _pack_col_rle(name: str, buf: np.ndarray, lane: str, runs_bound: int,
                  cap: int) -> list[np.ndarray]:
    """(values, run-lengths) sections for one canonicalized cap-padded
    column buffer. Run lengths sum to cap exactly (the capacity pad rides
    the trailing run), so device expansion reconstructs the buffer
    bit-for-bit; more runs than the planned capacity is stats drift."""
    rc = _runs_cap(runs_bound, cap)
    if cap == 0:
        return [np.zeros(0, dtype=_LANE_NP[lane]).view(np.uint8),
                np.zeros(0, dtype=np.int32).view(np.uint8)]
    starts = np.concatenate(
        [[0], np.flatnonzero(buf[1:] != buf[:-1]) + 1])
    if len(starts) > rc:
        raise EncodingOverflowError(
            f"column {name!r}: {len(starts)} runs overflow the planned "
            f"run capacity {rc} (runs bound {runs_bound})")
    lengths = np.diff(np.concatenate([starts, [cap]]))
    vbuf = np.zeros(rc, dtype=_LANE_NP[lane])
    lbuf = np.zeros(rc, dtype=np.int32)
    vbuf[:len(starts)] = buf[starts]
    lbuf[:len(starts)] = lengths
    return [vbuf.view(np.uint8), lbuf.view(np.uint8)]


def _dict_codes(name: str, data: np.ndarray, v: np.ndarray, n: int,
                book: np.ndarray) -> np.ndarray:
    """Row codes into a sorted codebook; a VALID value missing from the
    book is stats drift (null/dead slots ride code 0 like plain zeros)."""
    idx = np.searchsorted(book, data)
    safe = np.clip(idx, 0, max(len(book) - 1, 0))
    ok = (idx < len(book)) & (book[safe] == data) if len(book) else \
        np.zeros(len(data), dtype=bool)
    bad = ~ok & v
    if n and bad[:n].any():
        missing = data[:n][bad[:n]][0]
        raise EncodingOverflowError(
            f"column {name!r}: value {int(missing)} not in the planned "
            f"dictionary (card {len(book)})")
    return np.where(v, safe, 0).astype(np.int64)


def _pack_payload(table: Table, lanes: tuple, n: int, cap: int,
                  encs: Optional[tuple] = None,
                  codebooks: Optional[tuple] = None) -> tuple[np.ndarray,
                                                              list]:
    """Host-side packed payload bytes (the PackedTable wire format) WITHOUT
    the device upload: sharded morsel staging packs one payload per replica
    row block and uploads the concatenation in a single row-sharded
    device_put (shard_exec.stage_sharded)."""
    parts: list[np.ndarray] = []
    vparts: list[np.ndarray] = []
    dicts = []
    for ci, (c, lane) in enumerate(zip(table.columns, lanes)):
        enc = encs[ci] if encs else "plain"
        dict_enc = isinstance(enc, tuple) and enc[0] == "dict"
        if not dict_enc and not lane_legal(lane, c.dtype):
            raise LaneOverflowError(
                f"column {table.names[ci]!r}: lane {lane!r} illegal for "
                f"dtype {c.dtype!r}")
        v = c.validity
        data = np.asarray(c.data)
        if c.dtype == "str":
            # canonical null slot for codes is 0 (valid=False marks them)
            data = np.where(v & (data >= 0), data, 0)
            dicts.append(c.dictionary)
        else:
            dicts.append(None)
            data = np.where(v, data, np.zeros((), dtype=data.dtype))
        if dict_enc:
            # data section holds codebook codes on the (narrower) code lane
            data = _dict_codes(table.names[ci], data, v, n, codebooks[ci])
        if lane == "b1":
            bits = np.zeros(cap, dtype=bool)
            bits[:n] = data.astype(bool)
            parts.append(np.packbits(bits, bitorder="little"))
        else:
            lo, hi = _LANE_BOUNDS.get(lane, (None, None))
            if lo is not None and n and data.size:
                dmin, dmax = int(data[:n].min()), int(data[:n].max())
                if dmin < lo or dmax > hi:
                    raise LaneOverflowError(
                        f"column {table.names[ci]!r} values "
                        f"[{dmin}, {dmax}] overflow lane {lane!r}")
            buf = np.zeros(cap, dtype=_LANE_NP[lane])
            buf[:n] = data
            if isinstance(enc, tuple) and enc[0] == "rle":
                parts.extend(_pack_col_rle(table.names[ci], buf, lane,
                                           enc[1], cap))
            else:
                parts.append(buf.view(np.uint8))
        vbits = np.zeros(cap, dtype=bool)
        vbits[:n] = v
        vparts.append(np.packbits(vbits, bitorder="little"))
    alive = np.zeros(cap, dtype=bool)
    alive[:n] = True
    vparts.append(np.packbits(alive, bitorder="little"))
    payload = np.concatenate(parts + vparts) if parts + vparts else \
        np.zeros(0, dtype=np.uint8)
    return payload, dicts


def _unpack_bits(seg: jax.Array, cap: int) -> jax.Array:
    return jnp.unpackbits(seg, count=cap, bitorder="little").astype(bool)


def _unpack_lane(seg: jax.Array, lane: str, cap: int) -> jax.Array:
    """Bytes -> device array for one column (traced or concrete); narrow
    unsigned lanes widen to SIGNED i32 so downstream kernels never meet
    unsigned wraparound."""
    from jax import lax

    if lane == "b1":
        return _unpack_bits(seg, cap)
    if lane == "u8":
        return seg.astype(jnp.int32)
    width = _LANE_WIRE[lane]
    carrier = {"u16": jnp.uint16, "u32": jnp.uint32, "i32": jnp.int32,
               "i64": jnp.int64, "f32": jnp.float32,
               "f64": jnp.float64}[lane]
    out = lax.bitcast_convert_type(seg.reshape(cap, width), carrier)
    if lane in ("u16", "u32"):
        out = out.astype(jnp.int32)     # u32 bound is 2^31-1: no overflow
    return out


def unpack_table(p: PackedTable) -> DTable:
    """Traced (or concrete) unpacking back into per-column device arrays:
    each column is a zero-copy byte-slice view of the single uploaded
    buffer, bitcast to its lane carrier and widened to its signed device
    dtype — all of which fuses into the consuming compiled program.
    Dict-encoded columns come up as i32 codes with the host codebook
    attached (execution stays on codes; decode_col materializes values
    per-site); RLE columns expand to row-aligned values right here (a
    static-shape jnp.repeat that fuses like the bitcasts do)."""
    from jax import lax

    vbytes = (p.cap + 7) // 8
    cols = []
    off = 0
    encs = p.encs or ("plain",) * len(p.dtypes)
    voff = sum(enc_rows_bytes(ln, e, p.cap)
               for ln, e in zip(p.lanes, encs))
    dicts = p.dictionaries or (None,) * len(p.dtypes)
    books = p.codebooks or (None,) * len(p.dtypes)
    for dtype, lane, dc, enc, book in zip(p.dtypes, p.lanes, dicts, encs,
                                          books):
        sz = enc_rows_bytes(lane, enc, p.cap)
        seg = p.data[off:off + sz]
        if isinstance(enc, tuple) and enc[0] == "rle":
            rc = _runs_cap(enc[1], p.cap)
            vsz = _LANE_WIRE[lane] * rc
            vals = _unpack_lane(seg[:vsz], lane, rc)
            lens = lax.bitcast_convert_type(
                seg[vsz:vsz + 4 * rc].reshape(rc, 4), jnp.int32)
            d = jnp.repeat(vals, lens, total_repeat_length=p.cap)
            book = None
        else:
            d = _unpack_lane(seg, lane, p.cap)
            if not (isinstance(enc, tuple) and enc[0] == "dict"):
                book = None
        valid = _unpack_bits(p.data[voff:voff + vbytes], p.cap)
        cols.append(DCol(dtype, d, valid, dc, codebook=book))
        off += sz
        voff += vbytes
    alive = _unpack_bits(p.data[voff:voff + vbytes], p.cap)
    return DTable(list(p.names), cols, alive)


def widen_col(c: DCol) -> DCol:
    """Physical-width view of a column: an encoded column decodes
    (decode_col) and a narrow-lane device array widens to the logical
    physical dtype. Callers are the sites that genuinely need 64-bit
    arithmetic — aggregate/window arguments and decimal rescaling —
    everything else (filters, join keys, group keys, sorts) runs on the
    narrow encoding."""
    c = decode_col(c)
    if c.dtype in ("bool", "str", "date", "float"):
        return c
    pd = phys_dtype(c.dtype)
    if c.data.dtype == pd or not jnp.issubdtype(c.data.dtype, jnp.integer):
        return c
    return replace(c, data=c.data.astype(pd))


def device_bytes(dt: "Optional[DTable | PackedTable]") -> int:
    """Device bytes held by a table (DTable or PackedTable — any pytree of
    device arrays). Streaming uses it to account uploaded morsel bytes
    (last_exec_stats.bytes_uploaded): on tunneled platforms upload volume
    is the cost the shared scan divides by the branch count."""
    if dt is None:
        return 0
    return sum(int(leaf.size) * leaf.dtype.itemsize
               for leaf in jax.tree_util.tree_leaves(dt)
               if hasattr(leaf, "size") and hasattr(leaf, "dtype"))


def free_dtable(dt: "Optional[DTable | PackedTable]") -> None:
    """Explicitly release a cached entry's device buffers (DTable or
    PackedTable — any pytree of device arrays).

    Dropping the Python reference leaves freeing to gc timing, and tunneled
    platforms can pin uploads client-side — streaming loops that rebind a
    morsel buffer hundreds of times must free eagerly or accumulate the
    whole scan on the host."""
    if dt is None:
        return
    from ...obs.profile import DEVICE_MEM
    DEVICE_MEM.free(_mem_leaves(dt))
    for leaf in jax.tree_util.tree_leaves(dt):
        if hasattr(leaf, "delete"):
            try:
                leaf.delete()
            except Exception:
                pass


def to_host(dt: DTable, count: Optional[int] = None) -> Table:
    """Materialize a device table back into a host Table (compacted).

    All buffers come back in ONE device_get: on tunneled platforms each
    D2H transfer pays a fixed RTT, so per-column np.asarray would multiply
    that latency by the column count.
    """
    dt = jax.device_get(dt)
    alive = np.asarray(dt.alive)
    idx = np.flatnonzero(alive)
    if count is not None:
        idx = idx[:count]
    cols = []
    for c in dt.cols:
        c = _flatten_compound(c)
        data = np.asarray(c.data)[idx]
        valid = np.asarray(c.valid)[idx]
        if c.codebook is not None:
            # output materialization IS a decode site: codes -> values
            book = c.codebook
            safe = np.clip(data, 0, max(len(book) - 1, 0))
            data = np.where(valid, book[safe] if len(book) else 0, 0)
        if c.dtype == "str":
            data = np.where(valid, data, _NULL_CODE).astype(np.int32)
        host_dtype = phys_np(c.dtype)
        cols.append(Column(c.dtype, data.astype(host_dtype),
                           None if bool(valid.all()) else valid, c.dictionary))
    return Table(list(dt.names), cols)


def _flatten_compound(c: DCol) -> DCol:
    """Materialize a lazy-concat compound string column into a real dictionary.

    Concrete path: string appends run once per *distinct* part-code tuple
    (rows deduplicated over stacked codes). Traced path (inside a compiled
    plan): the output dictionary must be data-INdependent, so it becomes the
    mixed-radix cross product of the part dictionaries (+ an empty-string
    slot per part for null/invalid codes) and row codes are computed on
    device — sized like the id-column dictionary for the typical
    literal||column||literal concat.
    """
    if c.parts is None:
        return c
    if any(isinstance(p.data, jax.core.Tracer) for p in c.parts) or \
            isinstance(c.valid, jax.core.Tracer):
        return _flatten_compound_traced(c)
    code_mat = np.stack([np.where(np.asarray(p.valid), np.asarray(p.data), -1)
                         for p in c.parts], axis=1)
    uniq_rows, inverse = np.unique(code_mat, axis=0, return_inverse=True)
    joined = np.full(len(uniq_rows), "", dtype=object)
    for j, p in enumerate(c.parts):
        d = p.dictionary if p.dictionary is not None else np.empty(0, dtype=object)
        codes = uniq_rows[:, j]
        safe = np.clip(codes, 0, max(len(d) - 1, 0))
        vals = np.where(codes >= 0,
                        d[safe] if len(d) else "", "")
        joined = np.asarray([a + b for a, b in zip(joined, vals)], dtype=object)
    uniq, remap = np.unique(joined.astype(str), return_inverse=True)
    codes = remap.astype(np.int32)[inverse]
    return DCol("str", jnp.asarray(codes), c.valid, uniq.astype(object))


def _flatten_compound_traced(c: DCol) -> DCol:
    """Trace-safe compound flatten: cross-product dictionary, device codes."""
    dicts = []
    for p in c.parts:
        d = p.dictionary if p.dictionary is not None \
            else np.empty(0, dtype=object)
        # slot len(d) holds "" for null/invalid part codes
        dicts.append(np.concatenate([d.astype(object),
                                     np.asarray([""], dtype=object)]))
    total = 1
    for d in dicts:
        total *= len(d)
    if total > (1 << 20):
        raise NotImplementedError(
            f"compound string cross dictionary too large ({total})")
    # mixed-radix joined dictionary, last part fastest-varying
    joined = np.asarray([""], dtype=object)
    for d in dicts:
        joined = np.asarray([a + b for a in joined for b in d], dtype=object)
    code = jnp.zeros(c.parts[0].data.shape, jnp.int32)
    for p, d in zip(c.parts, dicts):
        n = len(d)
        eff = jnp.where(p.valid & (p.data >= 0),
                        jnp.clip(p.data, 0, n - 2 if n > 1 else 0),
                        n - 1).astype(jnp.int32)
        code = code * n + eff
    return DCol("str", code, c.valid, joined)


def string_rank_maps(dictionary: Optional[np.ndarray]
                     ) -> tuple[np.ndarray, np.ndarray]:
    """Host LUTs for a string dictionary: (code -> dense lexicographic rank,
    dense rank -> representative code).

    Equal strings get EQUAL ranks (dictionaries from compound cross products
    may contain duplicates; distinct ranks would break equality compares),
    so mapping an aggregated rank back to a code must go through the
    rank->code table — NOT through argsort position.
    """
    if dictionary is None or len(dictionary) == 0:
        return np.zeros(1, dtype=np.int32), np.zeros(1, dtype=np.int32)
    vals = dictionary.astype(str)
    order = np.argsort(vals, kind="stable")
    svals = vals[order]
    dense = np.cumsum(np.concatenate(
        [[0], (svals[1:] != svals[:-1]).astype(np.int32)])).astype(np.int32)
    ranks = np.empty(len(vals), dtype=np.int32)
    ranks[order] = dense
    rank_to_code = np.zeros(int(dense[-1]) + 1, dtype=np.int32)
    # reversed assignment => the FIRST occurrence in sorted order wins
    rank_to_code[dense[::-1]] = order[::-1].astype(np.int32)
    return ranks, rank_to_code


def string_rank_lut(dictionary: Optional[np.ndarray]) -> np.ndarray:
    """Host LUT: dictionary code -> dense lexicographic rank."""
    return string_rank_maps(dictionary)[0]


def rank_key(c: DCol) -> jax.Array:
    """Device array usable as a grouping/ordering key for any logical dtype."""
    c = _flatten_compound(c)
    if c.dtype == "str":
        lut = jnp.asarray(string_rank_lut(c.dictionary))
        safe = jnp.clip(c.data, 0, lut.shape[0] - 1)
        return jnp.where(c.valid, lut[safe], 0)
    if c.dtype == "bool":
        return jnp.where(c.valid, c.data.astype(jnp.int32), 0)
    return jnp.where(c.valid, c.data, jnp.zeros((), dtype=c.data.dtype))
