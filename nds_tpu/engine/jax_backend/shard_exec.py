"""Multi-chip sharded morsel execution (ISSUE 8 / ROADMAP item 2).

The streaming path used to run every per-morsel program on one chip even
when a mesh was available. Here each ScanGroup's morsel stream partitions
across data-parallel replicas of the device mesh ("shards" axis,
parallel/mesh.make_mesh):

- `stage_sharded` packs one morsel as n equal per-replica payload blocks
  (narrow-lane PackedTable wire format included) and uploads the
  concatenation in a SINGLE device_put with NamedSharding(P("shards")) —
  the flat uint8 buffer divides evenly, so replica k's device slice is
  exactly row block k's packed bytes. Unpackable layouts fall back to a
  per-leaf row-sharded DTable upload.
- `ShardedMorselQuery` is the sharded analog of executor.CompiledQuery:
  every replica replays the SAME recorded capacity schedule over its local
  rows via shard_map (a shard-local JaxExecutor — no in-plan collectives,
  the shard_map boundary is the collective), producing device-local
  partial aggregates. A second compiled program — dist_ops.gather_partials
  — is the morsel's ONE collective: a tiled all_gather of the bounded
  decomposed partials, measured and attributed separately
  (`<query>/gather:<table>@mesh<n>`) so collective time and bytes are
  first-class numbers in the bench scaling record.

The host-side final merge is unchanged: gathered per-replica partials are
just more rows of the same partial schema streaming's _decompose /
_final_builder already merge across morsels, so results are bit-identical
to the single-chip path for order-independent (integer/decimal) partials —
the measured exact-decimal bench configuration.

Spark frame (SURVEY.md §2): replicas play the executors, the morsel
row-shard plays maxPartitionBytes input splits, and the partial gather
plays the partial/final aggregate exchange.
"""
from __future__ import annotations

import threading
import time
from typing import Optional

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ...obs import metrics as _metrics
from ...obs.device_time import PROGRAMS as _PROGRAMS
from ...obs.trace import TRACER
from ...parallel.dist_ops import gather_partials, shard_map
from ..column import Table
from ..streaming import partition_morsel_rows
from .device import (DTable, PackedTable, _pack_payload, bucket,
                     plan_lanes)
from .executor import JaxExecutor, ReplayMismatch, _no_load, _Recorder


# -- sharded morsel staging ---------------------------------------------------

def stage_sharded(table: Table, mesh, shard_cap: int,
                  lanes: Optional[tuple] = None,
                  encs: Optional[tuple] = None,
                  codebooks: Optional[tuple] = None):
    """Pack + upload one morsel row-sharded over `mesh`: per-replica row
    blocks (streaming.partition_morsel_rows) each packed at `shard_cap`
    capacity, concatenated, and committed with ONE device_put under
    NamedSharding(P("shards")). Returns a PackedTable whose `cap` is the
    PER-REPLICA capacity — inside the shard_map body each replica sees its
    own payload slice, so unpack_table yields that replica's rows. Encoded
    execution rides along unchanged: each replica block packs under the
    SAME static encoding spec (dict codes / rle pairs), so block payloads
    stay equal-length and the flat buffer still divides evenly. Falls
    back to a row-sharded plain DTable when the layout cannot pack."""
    n_shards = mesh.devices.size
    axis = mesh.axis_names[0]
    sharding = NamedSharding(mesh, P(axis))
    spans = partition_morsel_rows(table.num_rows, n_shards)
    if lanes is None:
        lanes = plan_lanes([c.dtype for c in table.columns], narrow=False)
    x64 = jax.config.read("jax_enable_x64")
    packable = lanes is not None and (
        x64 or not any(ln in ("i64", "f64") for ln in lanes))
    from ...obs.profile import DEVICE_MEM
    from .device import _mem_leaves
    with TRACER.span("morsel.stage_sharded", cat="upload",
                     rows=table.num_rows, shards=n_shards,
                     capacity=shard_cap * n_shards):
        if packable:
            payloads = []
            dicts: list = []
            for lo, hi in spans:
                payload, dicts = _pack_payload(table.slice(lo, hi),
                                               tuple(lanes), hi - lo,
                                               shard_cap, encs, codebooks)
                payloads.append(payload)
            flat = np.concatenate(payloads)
            data = jax.device_put(flat, sharding)
            out = PackedTable(list(table.names),
                              [c.dtype for c in table.columns],
                              tuple(lanes), shard_cap, data, tuple(dicts),
                              tuple(encs) if encs else (),
                              tuple(codebooks) if codebooks else ())
        else:
            out = _sharded_dtable(table, spans, shard_cap, sharding)
    DEVICE_MEM.add(_mem_leaves(out))
    return out


def _sharded_dtable(table: Table, spans, shard_cap: int,
                    sharding) -> DTable:
    """Wide fallback: per-replica row blocks laid out contiguously in each
    column buffer (block k at offset k * shard_cap), every leaf committed
    row-sharded in one device_put of the whole pytree."""
    n_shards = len(spans)
    from .device import DCol, phys_dtype
    cols_np = []
    for c in table.columns:
        data = np.asarray(c.data)
        dt = np.dtype(phys_dtype(c.dtype))
        buf = np.zeros(shard_cap * n_shards, dtype=dt)
        vbuf = np.zeros(shard_cap * n_shards, dtype=bool)
        for k, (lo, hi) in enumerate(spans):
            m = hi - lo
            if not m:
                continue
            v = c.validity[lo:hi]
            block = np.where(v, data[lo:hi], 0)
            if c.dtype == "str":
                block = np.where(v & (data[lo:hi] >= 0), data[lo:hi], 0)
            buf[k * shard_cap:k * shard_cap + m] = block
            vbuf[k * shard_cap:k * shard_cap + m] = v
        cols_np.append((buf, vbuf))
    alive = np.zeros(shard_cap * n_shards, dtype=bool)
    for k, (lo, hi) in enumerate(spans):
        alive[k * shard_cap:k * shard_cap + (hi - lo)] = True
    dt = DTable(list(table.names),
                [DCol(c.dtype, buf, vbuf, c.dictionary)
                 for c, (buf, vbuf) in zip(table.columns, cols_np)],
                alive)
    return jax.device_put(dt, sharding)


# -- sharded per-morsel program ----------------------------------------------

class ShardedMorselQuery:
    """One recorded per-morsel schedule replayed on every mesh replica.

    plan may be a list (shared-scan fused group: one multi-output program,
    one shared decision schedule) exactly like CompiledQuery. Two compiled
    programs per instance:

    - the LOCAL program: shard_map over the row-sharded morsel + replicated
      dimension scans; each replica traces the plan(s) through a
      shard-local replay JaxExecutor and returns its partial-aggregate
      block(s), still sharded, plus per-replica schedule-check scalars;
    - the GATHER program (dist_ops.gather_partials): the morsel's single
      collective — tiled all_gather of the bounded partials, so the fetched
      result is the concatenation of every replica's block.

    Schedule verification is shard-aware: capacity checks take the max over
    replicas (<= planned bucket), exact checks must agree on every replica
    (shard-local recording keeps them data-independent). A genuine overflow
    raises ReplayMismatch and the session re-records that morsel eagerly on
    one chip — correctness never depends on the recorded bound."""

    def __init__(self, plan, decisions: list, scan_keys: tuple, mesh,
                 morsel_key: str, label: str = "",
                 pallas_ops: frozenset = frozenset()):
        self.plan = plan
        self.decisions = decisions
        self.scan_keys = tuple(scan_keys)
        self.mesh = mesh
        self.n_shards = int(mesh.devices.size)
        self.morsel_key = morsel_key
        self.pallas_ops = frozenset(pallas_ops)
        base = label or "program"
        self.label = f"{base}@mesh{self.n_shards}"
        self.gather_label = base.replace("/morsel:", "/gather:", 1) \
            + f"@mesh{self.n_shards}"
        self._fn = None
        self._gather = None
        self._replicated: dict = {}     # scan key -> (src id, replicated)
        self._lock = threading.Lock()

    # -- trace body (runs inside shard_map, one replica's block) -------------
    def _trace_local(self, morsel, others: tuple):
        scans = dict(zip(self._other_keys, others))
        scans[self.morsel_key] = morsel
        rec = _Recorder("replay", self.decisions)
        ex = JaxExecutor(_no_load, recorder=rec, scan_tables=scans,
                         mesh=None, shard_local=True,
                         pallas_ops=self.pallas_ops)
        if isinstance(self.plan, (list, tuple)):
            outs = []
            for p in self.plan:
                ex._memo = {}           # per-plan memo reset, like record
                outs.append(ex.execute(p))
            out = tuple(outs)
        else:
            out = ex.execute(self.plan)
        if rec.idx != len(rec.decisions):
            raise ReplayMismatch("decision schedule length drift (sharded)")
        if ex.fallback_nodes:
            raise ReplayMismatch(
                f"fallback under sharded trace: {ex.fallback_nodes}")
        # checks ride out PER REPLICA as (1,)-shaped rows of a sharded
        # vector: the host sees all n values and verifies shard-aware
        checks = [c.reshape(1) for c in rec.checks]
        return out, checks

    @property
    def _other_keys(self) -> tuple:
        return tuple(k for k in self.scan_keys if k != self.morsel_key)

    def _build(self) -> None:
        axis = self.mesh.axis_names[0]
        local = shard_map(self._trace_local, mesh=self.mesh,
                          in_specs=(P(axis), P()),
                          out_specs=(P(axis), P(axis)), check_vma=False)
        self._fn = jax.jit(local)
        self._gather = jax.jit(gather_partials(self.mesh))

    def _replicate(self, key: str, dt):
        """Commit a dimension-scan table replicated over the mesh once; the
        session's stream executor uploads it single-device and every morsel
        of every group reuses this broadcast copy."""
        cached = self._replicated.get(key)
        if cached is not None and cached[0] == id(dt):
            return cached[1]
        rep = jax.device_put(dt, NamedSharding(self.mesh, P()))
        self._replicated[key] = (id(dt), rep)
        return rep

    def _verify(self, checks_host: list) -> None:
        for (kind, planned), arr in zip(self.decisions, checks_host):
            a = np.asarray(arr)
            if kind == "cap":
                amax = int(a.max()) if a.size else 0
                if amax > bucket(max(int(planned), 1)):
                    raise ReplayMismatch(
                        f"sharded capacity overflow: {amax} > planned "
                        f"{planned}")
            else:
                vals = set(int(v) for v in a.tolist())
                if vals != {int(planned)}:
                    raise ReplayMismatch(
                        f"sharded exact decision drift: {sorted(vals)} != "
                        f"{planned}")

    def run(self, scans: dict, stats: Optional[dict] = None):
        """Dispatch the local program + the partial gather for one morsel;
        returns the host partial DTable (or tuple, fused groups) whose rows
        are the concatenation of every replica's partial block. `stats`
        accumulates collective_bytes / collective_ms / local device_ms."""
        from ...resilience import FAULTS

        morsel = scans[self.morsel_key]
        others = tuple(self._replicate(k, scans[k])
                       for k in self._other_keys)
        with self._lock:
            first = self._fn is None
            if first:
                FAULTS.fire("jax.compile")
                self._build()
        if first:
            _metrics.COMPILES.inc(2)   # local + gather programs
        FAULTS.fire("jax.execute")
        with TRACER.span("exec", cat="device", label=self.label,
                         first=first, shards=self.n_shards):
            t0 = time.perf_counter()
            with jax.profiler.TraceAnnotation(self.label):
                out, checks = self._fn(morsel, others)
                checks_host = jax.device_get(checks)
            t1 = time.perf_counter()
        _PROGRAMS.record_run(self.label, round((t1 - t0) * 1000, 3),
                             first=first)
        self._verify(checks_host)
        # ONE collective: all_gather of the sharded partial blocks. Bytes
        # model: ring all-gather ingress per device — each replica receives
        # the other n-1 replicas' blocks, (n-1)/n of the gathered total.
        sharded_bytes = sum(
            int(leaf.size) * leaf.dtype.itemsize
            for leaf in jax.tree_util.tree_leaves(out)
            if hasattr(leaf, "size"))
        coll_bytes = sharded_bytes * (self.n_shards - 1) // self.n_shards
        with TRACER.span("collective", cat="device",
                         label=self.gather_label, bytes=coll_bytes):
            t2 = time.perf_counter()
            with jax.profiler.TraceAnnotation(self.gather_label):
                merged = self._gather(out)
                out_host = jax.device_get(merged)
            t3 = time.perf_counter()
        _PROGRAMS.record_run(self.gather_label,
                             round((t3 - t2) * 1000, 3), first=first)
        if stats is not None:
            stats["collective_bytes"] = \
                stats.get("collective_bytes", 0) + coll_bytes
            stats["collective_ms"] = round(
                stats.get("collective_ms", 0.0) + (t3 - t2) * 1000, 3)
            stats["device_ms"] = round(
                stats.get("device_ms", 0.0) + (t1 - t0) * 1000, 3)
        return out_host
