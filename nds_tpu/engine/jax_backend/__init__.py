"""JAX/XLA execution backend: the TPU compute path of the engine.

Padded static-shape columnar kernels (kernels.py), device expression
evaluation with host-dictionary string LUTs (jexprs.py), and a plan executor
with per-node fallback to the numpy oracle backend (executor.py).
"""
from .device import DCol, DTable, to_device, to_host, bucket  # noqa: F401
from .executor import JaxExecutor  # noqa: F401
