"""TPU Pallas implementations of the relational hot loops.

PR 6's per-program device-time attribution names three kernel families as
the whole slice's device time (q10+q7 = 85% at <0.5% roofline each, PERF.md
round 10): the segmented sorts behind dense_rank/group-by, the
factorize->scatter-add aggregation pipeline, and the join/late-mat
random-access gathers (q72: ~10-25 ns/element through XLA's generic
lowering).  Each family gets a hand-tiled Pallas kernel here, swapped in
behind a per-op flag (``EngineConfig.pallas_ops``, a subset of
{"sort", "groupby", "gather"}) with the existing XLA lowering as the
bit-identical fallback:

- ``sort_pairs``          VMEM-blocked bitonic/merge sort over (key, idx)
                          pairs.  Blocks sort locally in VMEM (the first
                          log2(B) stages of the global bitonic network are
                          intra-block), cross-block compare-exchange passes
                          (distance >= B) run as streaming elementwise XLA
                          (already bandwidth-optimal), and each stage's
                          trailing intra-block merge network runs as one
                          Pallas pass over VMEM-resident blocks.  The
                          comparator is the total order (key, idx), so the
                          result is BIT-IDENTICAL to the stable
                          ``lax.sort`` it replaces.
- ``seg_reduce[_multi]``  fused group-by partial aggregation: per tile of
                          rows, one (segments x tile) membership mask is
                          materialized in VMEM and every requested
                          SUM/COUNT/MIN/MAX operand reduces through it into
                          segment partials accumulated across the
                          (sequential) grid — replacing the serialized
                          scatter-adds ``jax.ops.segment_*`` lowers to.
                          Integer sums and min/max are order-independent,
                          so results are bit-identical; float sums stay on
                          the XLA path (reduction-order ULPs).
- ``take[_many]``         batched multi-column gather: the source columns
                          stage whole in VMEM and index tiles stream
                          through them — the q72 late-materialization
                          fusion class (scripts/kernel_bench.py, the
                          promoted exp_gather experiment, measures the
                          VMEM-staged form against the HBM gather).  Gather
                          is a pure permutation read: bit-identical by
                          construction.

Dispatch is a thread-local op set installed by the executor
(``set_active``); compiled replay traces under the same set because
``CompiledQuery`` carries it, and program caches key on it (the executor's
shared-program fingerprint and the session's stream-config key).

Platform handling (``probe``): on a TPU backend kernels compile through
Mosaic; on the CPU backend they run in Pallas interpret mode — tier-1 CI
exercises the real kernel bodies under ``JAX_PLATFORMS=cpu``; on any other
backend (or import failure) the module reports "off" with a reason, one
warning is logged through ``obs.log``, and every call site keeps the XLA
lowering (``pallas_fallback_reason`` lands in ``last_exec_stats``).
"""
from __future__ import annotations

import functools
import threading
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ...obs import metrics as _metrics
from ...obs.log import get_logger

_I32 = jnp.int32

#: the ops a config may enable
VALID_OPS = frozenset({"sort", "groupby", "gather"})

# -- tiling parameters (static; see ISSUE 7 / pallas_guide VMEM sizing) ------
#: rows per VMEM sort block (power of two; i64 key + i32 idx at 1<<10 rows
#: keeps the block working set ~12 KB, far under the ~16 MB/core VMEM)
SORT_BLOCK = 1 << 10
#: seg_reduce eligibility cap: the per-tile membership mask is
#: (segments x tile) in VMEM, bounded by GROUPBY_MASK_ELEMS — the tile
#: adapts so small segment counts take big tiles (few grid steps) and the
#: 2048-segment worst case stays at a 256-row tile (4 MB i64 broadcast)
GROUPBY_MAX_SEGMENTS = 1 << 11
GROUPBY_MASK_ELEMS = 1 << 19
GROUPBY_MAX_TILE = 1 << 12
#: index rows per gather tile
GATHER_BLOCK = 1 << 12
#: VMEM budget for the staged gather sources of ONE kernel call; larger
#: column batches split across calls, single columns past it fall back
GATHER_SRC_BYTES = 4 << 20
# Minimum row counts for a call site to ride the Pallas path at all.
# Small arrays keep the XLA lowering: kernel-launch overhead dominates
# them on TPU, and every pallas call SITE costs one compile — a q10-class
# plan has dozens of dimension-scale sorts/gathers whose kernels would
# never earn their compile back. Shapes are static per compiled program,
# so the gate is deterministic; both sides are bit-identical, so a
# record/replay shape difference (streaming inflation) is benign.
SORT_MIN_ROWS = 1 << 13
GATHER_MIN_ROWS = 1 << 12
GROUPBY_MIN_ROWS = 1 << 12


# ---------------------------------------------------------------------------
# platform probe + per-executor op activation
# ---------------------------------------------------------------------------

_PROBE: Optional[tuple] = None
_WARNED = False


def probe() -> tuple[str, str]:
    """-> (mode, reason): mode is "tpu" (compiled Mosaic), "interpret"
    (CPU backend, Pallas interpreter — the tier-1 CI configuration), or
    "off" (unusable; reason says why). Cached for the process."""
    global _PROBE
    if _PROBE is not None:
        return _PROBE
    try:
        from jax.experimental import pallas as _pl            # noqa: F401
        from jax.experimental.pallas import tpu as _pltpu     # noqa: F401
    except Exception as e:          # pragma: no cover - env-dependent
        _PROBE = ("off", f"pallas import failed: {type(e).__name__}: {e}")
        return _PROBE
    backend = jax.default_backend()
    if backend == "tpu":
        _PROBE = ("tpu", "")
    elif backend == "cpu":
        _PROBE = ("interpret", "cpu backend: pallas interpret mode")
    else:
        _PROBE = ("off", f"no TPU pallas lowering on backend {backend!r}")
    return _PROBE


def _reset_probe_for_tests() -> None:
    global _PROBE, _WARNED
    _PROBE = None
    _WARNED = False


def parse_ops(spec) -> frozenset:
    """Validated op set from a config tuple / comma string; unknown names
    are dropped with one warning (graceful degradation, never a crash)."""
    if spec is None:
        return frozenset()
    if isinstance(spec, str):
        spec = [s for s in spec.split(",")]
    ops = {s.strip() for s in spec if s and s.strip()}
    bad = ops - VALID_OPS
    if bad:
        get_logger("pallas").warning(
            "ignoring unknown pallas_ops %s (valid: %s)",
            sorted(bad), sorted(VALID_OPS))
    return frozenset(ops & VALID_OPS)


_tls = threading.local()


def set_active(ops: frozenset) -> None:
    """Install the executing plan's op set (thread-local: concurrent
    compile-pool traces each carry their executor's set)."""
    _tls.ops = ops


def active_ops() -> frozenset:
    return getattr(_tls, "ops", frozenset())


def op_active(op: str) -> bool:
    """Is `op` enabled for the in-flight execution AND usable here? A
    requested-but-unusable platform logs one warning and reports off."""
    global _WARNED
    if op not in active_ops():
        return False
    mode, reason = probe()
    if mode == "off":
        if not _WARNED:
            _WARNED = True
            get_logger("pallas").warning(
                "pallas_ops requested but unavailable (%s); "
                "keeping the XLA lowering", reason)
        return False
    return True


def fallback_reason() -> Optional[str]:
    """The platform reason pallas is off, or None when usable."""
    mode, reason = probe()
    return reason if mode == "off" else None


def _interpret() -> bool:
    return probe()[0] != "tpu"


def _pl():
    from jax.experimental import pallas as pl
    return pl


def _bspec(shape, index_map):
    """BlockSpec pinned to VMEM on real TPUs (interpret mode ignores
    memory spaces; passing them keeps one code path)."""
    pl = _pl()
    if _interpret():
        return pl.BlockSpec(shape, index_map)
    from jax.experimental.pallas import tpu as pltpu
    return pl.BlockSpec(shape, index_map, memory_space=pltpu.VMEM)


# ---------------------------------------------------------------------------
# (a) tiled segmented sort: VMEM-blocked bitonic/merge network
# ---------------------------------------------------------------------------

def _cmpex(kk: jax.Array, ii: jax.Array, d: int, s: int, start):
    """One bitonic compare-exchange pass at distance `d` of global stage
    `s` over flat (key, idx) arrays whose first element has global index
    `start` (python int for whole-array passes, traced for in-kernel
    blocks). Comparator: lexicographic (key, idx) — a total order, so the
    full network reproduces the stable sort exactly."""
    B = kk.shape[0]
    k3 = kk.reshape(-1, 2, d)
    i3 = ii.reshape(-1, 2, d)
    nb = k3.shape[0]
    gi = lax.broadcasted_iota(_I32, (nb, 1, 1), 0)
    # each (2d)-pair-group sits inside one direction block of size 2^(s+1)
    asc = (((start + gi * 2 * d) >> (s + 1)) & 1) == 0
    ka, kb = k3[:, 0:1], k3[:, 1:2]
    ia, ib = i3[:, 0:1], i3[:, 1:2]
    a_gt_b = (ka > kb) | ((ka == kb) & (ia > ib))
    b_gt_a = (kb > ka) | ((kb == ka) & (ib > ia))
    swap = jnp.where(asc, a_gt_b, b_gt_a)
    nka = jnp.where(swap, kb, ka)
    nkb = jnp.where(swap, ka, kb)
    nia = jnp.where(swap, ib, ia)
    nib = jnp.where(swap, ia, ib)
    kk = jnp.concatenate([nka, nkb], axis=1).reshape(B)
    ii = jnp.concatenate([nia, nib], axis=1).reshape(B)
    return kk, ii


@functools.lru_cache(maxsize=None)
def _sort_call(N: int, B: int, key_dtype: str, merge: bool,
               interpret: bool):
    """Cached pallas_call for the intra-block parts of the network.

    merge=False: the full local sort (global stages 0..log2(B)-1, every
    compare-exchange intra-block). merge=True: the trailing intra-block
    merge of ONE global stage s — distances B/2..1 after that stage's
    cross-block passes ran at the XLA level. The stage index rides as a
    scalar INPUT (it only feeds the direction shift), so one compiled
    kernel serves every merge stage of the array instead of one compile
    per stage."""
    pl = _pl()
    kd = jnp.dtype(key_dtype)
    lb = B.bit_length() - 1

    def local_kern(k_ref, i_ref, ok_ref, oi_ref):
        kk, ii = k_ref[:], i_ref[:]
        start = pl.program_id(0) * B
        for s in range(lb):
            for sub in range(s, -1, -1):
                kk, ii = _cmpex(kk, ii, 1 << sub, s, start)
        ok_ref[:] = kk
        oi_ref[:] = ii

    def merge_kern(s_ref, k_ref, i_ref, ok_ref, oi_ref):
        kk, ii = k_ref[:], i_ref[:]
        s = s_ref[0]
        start = pl.program_id(0) * B
        for sub in range(lb - 1, -1, -1):
            kk, ii = _cmpex(kk, ii, 1 << sub, s, start)
        ok_ref[:] = kk
        oi_ref[:] = ii

    blocked = _bspec((B,), lambda b: (b,))
    in_specs = [blocked, _bspec((B,), lambda b: (b,))]
    if merge:
        in_specs = [_bspec((1,), lambda b: (0,))] + in_specs
    return pl.pallas_call(
        merge_kern if merge else local_kern,
        grid=(N // B,),
        in_specs=in_specs,
        out_specs=[_bspec((B,), lambda b: (b,)),
                   _bspec((B,), lambda b: (b,))],
        out_shape=[jax.ShapeDtypeStruct((N,), kd),
                   jax.ShapeDtypeStruct((N,), _I32)],
        interpret=interpret,
    )


def sort_pairs(key: jax.Array, idx: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Sort (key, idx) pairs ascending by the total order (key, idx).

    Drop-in for ``lax.sort((key, idx), num_keys=1, is_stable=True)`` when
    `idx` holds distinct values (the engine always passes an iota or a
    permutation): stability under ties == the (key, idx) lexicographic
    order. Keys must be integer-typed (the engine's packed/sentinel keys
    are). Non-power-of-two lengths pad with (dtype-max, n..N) sentinels
    that sort strictly after every real row, then slice back.
    """
    n = int(key.shape[0])
    if n <= 1:
        return key, idx
    assert jnp.issubdtype(key.dtype, jnp.integer), key.dtype
    _metrics.PALLAS_SORT_CALLS.inc()
    N = 1 << (n - 1).bit_length()
    B = min(SORT_BLOCK, N)
    k, i = key, idx.astype(_I32)
    if N != n:
        k = jnp.concatenate([
            k, jnp.full(N - n, jnp.iinfo(k.dtype).max, k.dtype)])
        i = jnp.concatenate([i, jnp.arange(n, N, dtype=_I32)])
    interp = _interpret()
    k, i = _sort_call(N, B, k.dtype.name, False, interp)(k, i)
    lb, lN = B.bit_length() - 1, N.bit_length() - 1
    for s in range(lb, lN):
        d = 1 << s
        while d >= B:
            # cross-block pass: pure elementwise compare at distance d —
            # XLA streams it at bandwidth; VMEM staging buys nothing here
            k, i = _cmpex(k, i, d, s, 0)
            d >>= 1
        k, i = _sort_call(N, B, k.dtype.name, True, interp)(
            jnp.full(1, s, _I32), k, i)
    if N != n:
        k, i = k[:n], i[:n]
    return k, i


# ---------------------------------------------------------------------------
# (b) fused group-by partial aggregation
# ---------------------------------------------------------------------------

def _seg_init(dtype, op: str):
    """The reduction identity ``jax.ops.segment_*`` leaves in EMPTY
    segments — +-inf for float min/max, iinfo extremes for ints — so the
    Pallas output is bit-identical even in slots no caller reads."""
    if op == "sum":
        return jnp.zeros((), dtype)
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.asarray(jnp.inf if op == "min" else -jnp.inf, dtype)
    info = jnp.iinfo(dtype)
    return jnp.asarray(info.max if op == "min" else info.min, dtype)


@functools.lru_cache(maxsize=None)
def _seg_call(n_pad: int, tile: int, cap: int, specs: tuple,
              interpret: bool):
    """Cached pallas_call: specs is a static tuple of (dtype_name, op).
    One (cap x tile) membership mask per tile serves EVERY operand — the
    fused replacement for one scatter pass per aggregate."""
    pl = _pl()
    nd = len(specs)

    def kern(gid_ref, *refs):
        step = pl.program_id(0)
        g = gid_ref[:]
        seg = lax.broadcasted_iota(_I32, (cap, tile), 0)
        mask = g[None, :] == seg
        for j, (dt, op) in enumerate(specs):
            d_ref, o_ref = refs[j], refs[nd + j]
            init = _seg_init(jnp.dtype(dt), op)

            @pl.when(step == 0)
            def _(o_ref=o_ref, init=init):
                o_ref[:] = jnp.full((cap,), init)
            x = d_ref[:]
            if op == "sum":
                # pin the accumulator dtype: jnp.sum would promote i32 to
                # the platform int under x64, drifting off the output ref
                part = jnp.where(mask, x[None, :],
                                 jnp.zeros((), x.dtype)).sum(
                    axis=1, dtype=x.dtype)
                o_ref[:] = o_ref[:] + part
            else:
                fill = _seg_init(jnp.dtype(dt), op)
                red = jnp.min if op == "min" else jnp.max
                comb = jnp.minimum if op == "min" else jnp.maximum
                part = red(jnp.where(mask, x[None, :], fill), axis=1)
                o_ref[:] = comb(o_ref[:], part)

    blocked = _bspec((tile,), lambda b: (b,))
    return pl.pallas_call(
        kern,
        grid=(n_pad // tile,),
        in_specs=[blocked] + [_bspec((tile,), lambda b: (b,))
                              for _ in specs],
        out_specs=[_bspec((cap,), lambda b: (0,)) for _ in specs],
        out_shape=[jax.ShapeDtypeStruct((cap,), jnp.dtype(dt))
                   for dt, _ in specs],
        interpret=interpret,
    )


def seg_supported(data: jax.Array, num_segments: int, op: str) -> bool:
    """Static eligibility for one operand: bounded segment count (the
    membership mask is VMEM-resident) and order-independent math only —
    integer sums and any-dtype min/max are exact in every order, float
    sums are not (they keep the XLA path so flag-off stays bit-identical).
    """
    if not (1 <= num_segments <= GROUPBY_MAX_SEGMENTS):
        return False
    if data.ndim != 1 or data.dtype == jnp.bool_:
        return False
    if op == "sum":
        return bool(jnp.issubdtype(data.dtype, jnp.integer))
    return op in ("min", "max")


def seg_reduce_multi(operands: list, gid: jax.Array,
                     num_segments: int) -> list:
    """Fused segment partials: operands is [(data, op)] with every entry
    ``seg_supported``; one kernel pass computes them all. Rows whose gid
    falls outside [0, num_segments) contribute nothing (the engine's
    dead-row sentinel convention, same as segment_sum's out-of-range
    drop)."""
    _metrics.PALLAS_GROUPBY_CALLS.inc()
    n = int(gid.shape[0])
    tile = GROUPBY_MASK_ELEMS // max(1, num_segments)
    tile = 1 << min(GROUPBY_MAX_TILE.bit_length() - 1,
                    max(0, tile.bit_length() - 1))     # pow2, <= max tile
    tile = min(tile, 1 << max(0, (n - 1).bit_length()))
    n_pad = -(-n // tile) * tile
    g = gid.astype(_I32)
    datas = [d for d, _ in operands]
    if n_pad != n:
        g = jnp.concatenate([
            g, jnp.full(n_pad - n, num_segments, _I32)])
        datas = [jnp.concatenate([d, jnp.zeros(n_pad - n, d.dtype)])
                 for d in datas]
    specs = tuple((d.dtype.name, op) for d, (_, op) in zip(datas, operands))
    call = _seg_call(n_pad, tile, num_segments, specs, _interpret())
    out = call(g, *datas)
    return list(out)


def seg_reduce(data: jax.Array, gid: jax.Array, num_segments: int,
               op: str) -> jax.Array:
    """Single-operand convenience over ``seg_reduce_multi``."""
    return seg_reduce_multi([(data, op)], gid, num_segments)[0]


# ---------------------------------------------------------------------------
# (c) batched multi-column gather
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def _gather_call(n_pad: int, blk: int, src_specs: tuple, interpret: bool):
    """Cached pallas_call: src_specs is a static tuple of (rows, dtype
    name). Sources stage whole in VMEM (index maps pin block 0), index
    tiles stream through."""
    pl = _pl()

    def kern(idx_ref, *refs):
        nd = len(src_specs)
        iv = idx_ref[:]
        for j in range(nd):
            refs[nd + j][:] = refs[j][iv]

    in_specs = [_bspec((blk,), lambda b: (b,))]
    in_specs += [_bspec((rows,), lambda b: (0,)) for rows, _ in src_specs]
    return pl.pallas_call(
        kern,
        grid=(n_pad // blk,),
        in_specs=in_specs,
        out_specs=[_bspec((blk,), lambda b: (b,)) for _ in src_specs],
        out_shape=[jax.ShapeDtypeStruct((n_pad,), jnp.dtype(dt))
                   for _, dt in src_specs],
        interpret=interpret,
    )


def _src_bytes(src: jax.Array) -> int:
    return int(src.shape[0]) * src.dtype.itemsize


def gather_supported(src: jax.Array) -> bool:
    """One source column is VMEM-stageable: 1-D and within the budget."""
    return src.ndim == 1 and src.shape[0] >= 1 and \
        _src_bytes(src) <= GATHER_SRC_BYTES


def take_many(srcs: list, idx: jax.Array) -> list:
    """Gather ``[src[idx] for src in srcs]`` with VMEM-staged sources.

    Columns batch greedily into kernel calls under the VMEM budget (one
    index-tile pass serves the whole batch — the late-mat attribute-join
    shape gathers every dimension attribute with ONE index vector).
    Columns too large to stage fall back to the XLA gather individually;
    gather is a permutation read, so the mix is bit-identical."""
    n = int(idx.shape[0])
    out: list = [None] * len(srcs)
    todo: list[int] = []
    for j, s in enumerate(srcs):
        if gather_supported(s) and n >= 1:
            todo.append(j)
        else:
            out[j] = s[idx]
    if not todo:
        return out
    _metrics.PALLAS_GATHER_CALLS.inc()
    blk = min(GATHER_BLOCK, max(1, n))
    n_pad = -(-n // blk) * blk
    iv = idx.astype(_I32)
    if n_pad != n:
        iv = jnp.concatenate([iv, jnp.zeros(n_pad - n, _I32)])
    interp = _interpret()
    batch: list[int] = []
    budget = 0

    def flush(batch):
        arrs = []
        for j in batch:
            s = srcs[j]
            arrs.append(s.astype(jnp.uint8) if s.dtype == jnp.bool_ else s)
        specs = tuple((int(a.shape[0]), a.dtype.name) for a in arrs)
        res = _gather_call(n_pad, blk, specs, interp)(iv, *arrs)
        for j, r in zip(batch, res):
            r = r[:n] if n_pad != n else r
            out[j] = r.astype(bool) if srcs[j].dtype == jnp.bool_ else r

    for j in todo:
        b = _src_bytes(srcs[j])
        if batch and budget + b > GATHER_SRC_BYTES:
            flush(batch)
            batch, budget = [], 0
        batch.append(j)
        budget += b
    if batch:
        flush(batch)
    return out


def take(src: jax.Array, idx: jax.Array) -> jax.Array:
    """Single-column convenience over ``take_many``."""
    return take_many([src], idx)[0]
