"""Relational kernels as traceable JAX programs (static shapes, masked rows).

Design rules (TPU/XLA-first):
- No data-dependent shapes inside a kernel: outputs are padded to a capacity
  chosen by the caller; a row-`alive` mask carries the logical row set.
- No hashing: grouping and joins are sort-based (`lax.sort` is deterministic
  and maps well onto TPU); multi-column keys are reduced to a dense group id
  by a joint factorize, so every join/aggregate is single-int-key.
- Nulls ride as validity masks; null payload slots are canonical zeros.

These kernels are the device counterparts of engine/ops.py (the numpy oracle
backend, which mirrors what the reference gets from Spark SQL executors,
reference nds/nds_power.py:124-134).
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from ..plan import AggSpec, SortKey, WindowFunc

_I32 = jnp.int32


def _iota(n: int) -> jax.Array:
    return jnp.arange(n, dtype=_I32)


# ---------------------------------------------------------------------------
# factorize: joint dense ranking of key tuples
# ---------------------------------------------------------------------------

def dense_rank(key_data: list[jax.Array], key_valid: list[jax.Array],
               alive: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Assign each alive row a dense group id over its key tuple.

    Returns (gid, num_groups): gid[i] in [0, num_groups) for alive rows and
    == capacity (sentinel segment) for dead rows. Deterministic (sort-based).
    """
    n = alive.shape[0]
    operands: list[jax.Array] = [(~alive).astype(_I32)]
    for d, v in zip(key_data, key_valid):
        operands.append((~v).astype(_I32))
        operands.append(jnp.where(v & alive, d, jnp.zeros((), d.dtype)))
    num_keys = len(operands)
    out = lax.sort(tuple(operands) + (_iota(n),), num_keys=num_keys,
                   is_stable=True)
    perm = out[-1]
    alive_sorted = out[0] == 0
    diff = jnp.zeros(n, dtype=bool)
    for k in out[1:num_keys]:
        diff = diff | jnp.concatenate([jnp.ones(1, bool), k[1:] != k[:-1]])
    if num_keys == 1:  # no keys: single global group
        diff = jnp.concatenate([jnp.ones(1, bool), jnp.zeros(n - 1, bool)])
    new_group = diff & alive_sorted
    # first alive row must open a group even if `diff` logic missed it
    new_group = new_group | (alive_sorted &
                             jnp.concatenate([jnp.ones(1, bool), ~alive_sorted[:-1]]))
    gid_sorted = jnp.cumsum(new_group.astype(_I32)) - 1
    num_groups = jnp.max(jnp.where(alive_sorted, gid_sorted, -1)) + 1
    gid = jnp.zeros(n, _I32).at[perm].set(
        jnp.where(alive_sorted, gid_sorted, n))
    return gid, num_groups


# ---------------------------------------------------------------------------
# filter / compact / limit
# ---------------------------------------------------------------------------

def filter_alive(alive: jax.Array, mask_data: jax.Array,
                 mask_valid: jax.Array) -> jax.Array:
    return alive & mask_data.astype(bool) & mask_valid


def compaction_perm(alive: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Stable permutation bringing alive rows to the front; returns (perm, count)."""
    n = alive.shape[0]
    dead = (~alive).astype(_I32)
    _, perm = lax.sort((dead, _iota(n)), num_keys=1, is_stable=True)
    return perm, jnp.sum(alive.astype(_I32))


def limit_alive(alive: jax.Array, n_keep: int) -> jax.Array:
    """Keep the first `n_keep` alive rows in physical order."""
    pos = jnp.cumsum(alive.astype(_I32)) - 1
    return alive & (pos < n_keep)


# ---------------------------------------------------------------------------
# sort
# ---------------------------------------------------------------------------

def sort_perm(key_data: list[jax.Array], key_valid: list[jax.Array],
              keys: list[SortKey], alive: jax.Array) -> jax.Array:
    """Permutation realizing Spark ORDER BY semantics; dead rows go last."""
    n = alive.shape[0]
    operands: list[jax.Array] = [(~alive).astype(_I32)]
    for col, valid, k in zip(key_data, key_valid, keys):
        nulls_first = k.nulls_first if k.nulls_first is not None else k.asc
        # null rank: 0 => before values, 2 => after values; values rank 1
        null_rank = jnp.where(valid, 1, 0 if nulls_first else 2).astype(_I32)
        operands.append(null_rank)
        d = jnp.where(valid & alive, col, jnp.zeros((), col.dtype))
        if not k.asc:
            d = (~d) if d.dtype == jnp.bool_ else -d
        operands.append(d)
    out = lax.sort(tuple(operands) + (_iota(n),), num_keys=len(operands),
                   is_stable=True)
    return out[-1]


# ---------------------------------------------------------------------------
# aggregation
# ---------------------------------------------------------------------------

def _seg(data: jax.Array, gid: jax.Array, num_segments: int, op: str) -> jax.Array:
    if op == "sum":
        return jax.ops.segment_sum(data, gid, num_segments=num_segments)
    if op == "min":
        return jax.ops.segment_min(data, gid, num_segments=num_segments)
    if op == "max":
        return jax.ops.segment_max(data, gid, num_segments=num_segments)
    raise AssertionError(op)


def aggregate(gid: jax.Array, alive: jax.Array, specs: list[AggSpec],
              args: list, cap_out: int) -> list[tuple[jax.Array, jax.Array]]:
    """Per-group aggregates. `args` are (data, valid) tuples or None.

    Returns one (values, valid) per spec, each length cap_out. gid for dead
    rows must be >= cap_out (the sentinel from dense_rank works when
    cap_out == capacity + 1 is NOT required — callers pass num_segments-safe
    capacity; dead rows land in segment `capacity` and callers slice).
    """
    results = []
    counts_cache: dict[int, jax.Array] = {}

    def contrib_count(valid):
        key = id(valid)
        if key not in counts_cache:
            counts_cache[key] = jax.ops.segment_sum(
                (alive & valid).astype(jnp.int64 if jax.config.read("jax_enable_x64")
                 else _I32), gid, num_segments=cap_out)
        return counts_cache[key]

    for spec, arg in zip(specs, args):
        if spec.func == "count_star":
            ones = jnp.ones_like(alive, dtype=_I32)
            vals = jax.ops.segment_sum(jnp.where(alive, ones, 0), gid,
                                       num_segments=cap_out)
            results.append((vals.astype(jnp.int64) if jax.config.read("jax_enable_x64")
                            else vals, jnp.ones(cap_out, bool)))
            continue
        data, valid = arg
        contrib = alive & valid
        cnt = contrib_count(valid)
        if spec.func == "count":
            results.append((cnt, jnp.ones(cap_out, bool)))
        elif spec.func == "sum":
            z = jnp.where(contrib, data, jnp.zeros((), data.dtype))
            vals = _seg(z, gid, cap_out, "sum")
            results.append((vals, cnt > 0))
        elif spec.func in ("min", "max"):
            big = _extreme(data.dtype, spec.func)
            z = jnp.where(contrib, data, big)
            vals = _seg(z, gid, cap_out, spec.func)
            vals = jnp.where(cnt > 0, vals, jnp.zeros((), data.dtype))
            results.append((vals, cnt > 0))
        elif spec.func == "avg":
            z = jnp.where(contrib, data, jnp.zeros((), data.dtype)).astype(
                _float_dtype())
            s = _seg(z, gid, cap_out, "sum")
            vals = s / jnp.maximum(cnt, 1).astype(_float_dtype())
            results.append((vals, cnt > 0))
        elif spec.func == "stddev_samp":
            zf = jnp.where(contrib, data, 0).astype(_float_dtype())
            s = _seg(zf, gid, cap_out, "sum")
            s2 = _seg(zf * zf, gid, cap_out, "sum")
            nf = cnt.astype(_float_dtype())
            var = (s2 - s * s / jnp.maximum(nf, 1.0)) / jnp.maximum(nf - 1.0, 1.0)
            vals = jnp.sqrt(jnp.maximum(var, 0.0))
            results.append((vals, cnt > 1))
        else:
            raise NotImplementedError(f"device agg {spec.func}")
    return results


def _float_dtype():
    return jnp.float64 if jax.config.read("jax_enable_x64") else jnp.float32


def _extreme(dtype, func: str):
    info_fn = jnp.finfo if jnp.issubdtype(dtype, jnp.floating) else jnp.iinfo
    return jnp.asarray(info_fn(dtype).max if func == "min" else info_fn(dtype).min,
                       dtype=dtype)


def group_representatives(gid: jax.Array, alive: jax.Array,
                          data: jax.Array, valid: jax.Array,
                          cap_out: int) -> tuple[jax.Array, jax.Array]:
    """Per-group key value (all rows in a group share it): scatter any row."""
    safe_gid = jnp.where(alive, gid, cap_out)
    padded_vals = jnp.zeros(cap_out + 1, dtype=data.dtype).at[safe_gid].set(data)
    padded_valid = jnp.zeros(cap_out + 1, dtype=bool).at[safe_gid].set(valid)
    return padded_vals[:cap_out], padded_valid[:cap_out]


def distinct_within_group(gid: jax.Array, alive: jax.Array,
                          data: jax.Array, valid: jax.Array
                          ) -> jax.Array:
    """Alive-mask of one representative row per (gid, value) pair (for
    COUNT/SUM DISTINCT): joint rank then first-occurrence selection."""
    n = alive.shape[0]
    pair_gid, _ = dense_rank([gid, jnp.where(valid, data, 0).astype(
        data.dtype), (~valid).astype(_I32)],
        [jnp.ones(n, bool), jnp.ones(n, bool), jnp.ones(n, bool)],
        alive & valid)
    first = jnp.full(n + 1, n, dtype=_I32).at[
        jnp.where(alive & valid, pair_gid, n)].min(_iota(n))
    return (alive & valid) & (first[pair_gid] == _iota(n))


# ---------------------------------------------------------------------------
# joins
# ---------------------------------------------------------------------------

def build_side(gid_right: jax.Array, alive_right: jax.Array
               ) -> tuple[jax.Array, jax.Array]:
    """Sort right-side gids (dead rows pushed to +inf); returns (sorted_gid, perm)."""
    n = alive_right.shape[0]
    key = jnp.where(alive_right, gid_right, jnp.iinfo(_I32).max)
    sorted_gid, perm = lax.sort((key, _iota(n)), num_keys=1, is_stable=True)
    return sorted_gid, perm


def probe_counts(sorted_gid: jax.Array, probe_gid: jax.Array,
                 probe_alive: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Per-probe-row match range in the sorted build side: (start, count)."""
    lo = jnp.searchsorted(sorted_gid, probe_gid, side="left")
    hi = jnp.searchsorted(sorted_gid, probe_gid, side="right")
    cnt = jnp.where(probe_alive, hi - lo, 0)
    return lo.astype(_I32), cnt.astype(_I32)


def expand_join(lo: jax.Array, cnt: jax.Array, probe_alive: jax.Array,
                cap_out: int) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Materialize (left_row, build_sorted_pos) pairs for an inner join.

    cap_out must be >= total matches (caller host-syncs the total).
    Returns (left_idx, build_pos, alive_out) each of length cap_out.
    """
    n = cnt.shape[0]
    cum = jnp.cumsum(cnt)
    total = cum[-1]
    j = _iota(cap_out)
    left_pos = jnp.searchsorted(cum, j, side="right").astype(_I32)
    left_safe = jnp.minimum(left_pos, n - 1)
    prev = jnp.where(left_safe > 0, cum[jnp.maximum(left_safe - 1, 0)], 0)
    k = j - prev.astype(_I32)
    build_pos = lo[left_safe] + k
    alive_out = j < total
    return left_safe, build_pos, alive_out
