"""Relational kernels as traceable JAX programs (static shapes, masked rows).

Design rules (TPU/XLA-first):
- No data-dependent shapes inside a kernel: outputs are padded to a capacity
  chosen by the caller; a row-`alive` mask carries the logical row set.
- No hashing: grouping and joins are sort-based (`lax.sort` is deterministic
  and maps well onto TPU); multi-column keys are reduced to a dense group id
  by a joint factorize, so every join/aggregate is single-int-key.
- Nulls ride as validity masks; null payload slots are canonical zeros.

These kernels are the device counterparts of engine/ops.py (the numpy oracle
backend, which mirrors what the reference gets from Spark SQL executors,
reference nds/nds_power.py:124-134).
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from ..plan import AggSpec, SortKey, WindowFunc
from . import pallas_kernels as _pk

_I32 = jnp.int32


def _iota(n: int) -> jax.Array:
    return jnp.arange(n, dtype=_I32)


# ---------------------------------------------------------------------------
# Pallas dispatch seams (ISSUE 7): each helper swaps in the hand-tiled
# pallas_kernels implementation when its op flag is active for the in-flight
# executor (EngineConfig.pallas_ops via pallas_kernels.set_active) and keeps
# the existing XLA lowering — bit-identically — otherwise. No schedule
# decision may depend on which side runs: both sides return identical bits.
# ---------------------------------------------------------------------------

def _sort1(key: jax.Array, idx: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Single-integer-key stable sort carrying an iota/permutation payload:
    the (key, idx) comparator is a total order, so the tiled bitonic
    network reproduces `lax.sort(..., is_stable=True)` exactly. Fact-scale
    arrays only (SORT_MIN_ROWS): each pallas call SITE is one kernel
    compile, and dimension-scale sorts never earn it back."""
    if int(key.shape[0]) >= _pk.SORT_MIN_ROWS and _pk.op_active("sort"):
        return _pk.sort_pairs(key, idx)
    return lax.sort((key, idx), num_keys=1, is_stable=True)


def gather_many(arrays: list, idx: jax.Array) -> list:
    """Batched same-index gather (multi-column join/late-mat shape): one
    VMEM-staged pallas pass over all stageable columns when "gather" is
    active and the index vector is fact-scale, else the plain XLA gathers.
    Pure permutation reads — always bit-identical."""
    if int(idx.shape[0]) >= _pk.GATHER_MIN_ROWS and _pk.op_active("gather"):
        return _pk.take_many(list(arrays), idx)
    return [a[idx] for a in arrays]


def _seg_multi(pairs: list, gid: jax.Array, num_segments: int) -> list:
    """Several segment reductions over ONE gid vector. With "groupby"
    active, every eligible operand rides one fused pallas pass (a single
    per-tile membership mask serves them all); the rest — and the whole
    list when inactive — keep the per-operand `_seg` path."""
    out: list = [None] * len(pairs)
    fused: list[int] = []
    if int(gid.shape[0]) >= _pk.GROUPBY_MIN_ROWS and \
            _pk.op_active("groupby"):
        fused = [i for i, (d, op) in enumerate(pairs)
                 if _pk.seg_supported(d, num_segments, op)]
        if fused:
            res = _pk.seg_reduce_multi([pairs[i] for i in fused], gid,
                                       num_segments)
            for i, r in zip(fused, res):
                out[i] = r
    for i, (d, op) in enumerate(pairs):
        if out[i] is None:
            out[i] = _seg(d, gid, num_segments, op)
    return out


# ---------------------------------------------------------------------------
# factorize: joint dense ranking of key tuples
# ---------------------------------------------------------------------------

def dense_rank(key_data: list[jax.Array], key_valid: list[jax.Array],
               alive: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Assign each alive row a dense group id over its key tuple.

    Returns (gid, num_groups): gid[i] in [0, num_groups) for alive rows and
    == capacity (sentinel segment) for dead rows. Deterministic (sort-based).
    """
    n = alive.shape[0]
    if not key_data:
        # global group: every alive row is group 0 (no sort)
        gid = jnp.where(alive, 0, n).astype(_I32)
        return gid, jnp.any(alive).astype(_I32)
    operands: list[jax.Array] = [(~alive).astype(_I32)]
    for d, v in zip(key_data, key_valid):
        operands.append((~v).astype(_I32))
        operands.append(jnp.where(v & alive, d, jnp.zeros((), d.dtype)))
    num_keys = len(operands)
    out = lax.sort(tuple(operands) + (_iota(n),), num_keys=num_keys,
                   is_stable=True)
    perm = out[-1]
    alive_sorted = out[0] == 0
    diff = jnp.zeros(n, dtype=bool)
    for k in out[1:num_keys]:
        diff = diff | jnp.concatenate([jnp.ones(1, bool), k[1:] != k[:-1]])
    if num_keys == 1:  # no keys: single global group
        diff = jnp.concatenate([jnp.ones(1, bool), jnp.zeros(n - 1, bool)])
    new_group = diff & alive_sorted
    # first alive row must open a group even if `diff` logic missed it
    new_group = new_group | (alive_sorted &
                             jnp.concatenate([jnp.ones(1, bool), ~alive_sorted[:-1]]))
    return _gid_from_sorted(new_group, alive_sorted, perm, n)


def unscatter(perm: jax.Array, values: tuple) -> tuple:
    """Undo a permutation WITHOUT scatter: sort by `perm` (which is a
    permutation of 0..n-1, so sorting restores original row order) carrying
    `values` as payload operands. Measured on TPU: an n-sized scatter costs
    ~60x a 2-operand sort — .at[perm].set() is the single most expensive
    way to invert a permutation on this hardware.

    Pallas tier: sort only (perm, iota) — yielding argsort(perm), i.e. the
    inverse permutation — then gather the payloads through it in one
    batched pass instead of carrying every payload through the merge
    network. perm's values are distinct, so both forms are bit-identical.
    """
    if int(perm.shape[0]) >= _pk.SORT_MIN_ROWS and _pk.op_active("sort"):
        _, inv = _pk.sort_pairs(perm, _iota(perm.shape[0]))
        return tuple(gather_many(list(values), inv))
    out = lax.sort((perm,) + tuple(values), num_keys=1, is_stable=True)
    return out[1:]


def _gid_from_sorted(new_group: jax.Array, alive_sorted: jax.Array,
                     perm: jax.Array, n: int) -> tuple[jax.Array, jax.Array]:
    """Shared sorted->gid suffix: cumsum group opens, sort-unscatter back
    through the permutation (dead rows hold the `n` sentinel)."""
    gid_sorted = jnp.cumsum(new_group.astype(_I32)) - 1
    num_groups = jnp.max(jnp.where(alive_sorted, gid_sorted, -1)) + 1
    (gid,) = unscatter(perm, (jnp.where(alive_sorted, gid_sorted, n),))
    return gid, num_groups


# ---------------------------------------------------------------------------
# fast dense_rank tiers: direct-address / packed single-key sort
#
# The multi-operand lax.sort above is O(log^2 n) merge passes over EVERY
# operand (2K+2 arrays for K keys) — the dominant HBM traffic of group-by/
# join programs. When every key is integer-typed (rank_key yields ints for
# str/date/decimal too) and the mixed-radix domain product fits the integer
# dtype, the key tuple packs into ONE integer using runtime min/max ranges
# and a single-key sort (one operand instead of 2K+2) replaces the generic
# path. The packed tier orders groups exactly like the sort-based path
# (value-ascending, nulls last per key), so gids are bit-identical and the
# choice is purely a performance decision, recorded/replayed by the
# executor (_decide_exact_lazy).
# The reference gets this class of kernel from RAPIDS hash-groupby
# (reference nds/power_run_gpu.template); here the TPU-friendly equivalent
# is scatter+cumsum over a bounded domain.
# ---------------------------------------------------------------------------

def _pack_dtype():
    return jnp.int64 if jax.config.read("jax_enable_x64") else _I32


def _key_ranges(key_data: list[jax.Array], key_valid: list[jax.Array],
                alive: jax.Array):
    """Per-key runtime (norm, range, ok): norm in [0, range) with values
    mapped order-preserving to [0, span] and NULL to span+1 (nulls-last,
    matching dense_rank's sort operand order). ok guards span overflow
    (wrapped subtraction on extreme-range keys => key ineligible)."""
    norms, ranges, oks = [], [], []
    for d, v in zip(key_data, key_valid):
        contrib = alive & v
        cnt = jnp.sum(contrib.astype(_I32))
        big = jnp.iinfo(d.dtype).max
        small = jnp.iinfo(d.dtype).min
        m = jnp.min(jnp.where(contrib, d, big))
        mx = jnp.max(jnp.where(contrib, d, small))
        span = jnp.where(cnt > 0, mx - m, jnp.asarray(-1, d.dtype))
        ok = (cnt == 0) | (span >= 0)          # wrapped diff => negative
        span = jnp.maximum(span, -1)
        norm = jnp.where(v, jnp.clip(d - m, 0, span), span + 1)
        norms.append(norm)
        ranges.append((span + 2).astype(_pack_dtype()))
        oks.append(ok)
    return norms, ranges, oks


def _sat_product(ranges: list[jax.Array], cap: int) -> jax.Array:
    """Product of ranges, saturated at cap+1 without overflow: the multiply
    only happens when the result provably fits (the discarded wrapped
    product inside jnp.where is defined-but-unused)."""
    p = jnp.ones((), _pack_dtype())
    for r in ranges:
        rc = jnp.minimum(r, cap + 1)
        p = jnp.where(p > cap // rc, jnp.asarray(cap + 1, p.dtype), p * rc)
    return p


def group_tier(key_data: list[jax.Array], key_valid: list[jax.Array],
               alive: jax.Array) -> jax.Array:
    """Traced packability decision: 1 = the key tuple packs into one
    integer (single-key sort), 0 = the generic multi-operand sort.
    Recorded as an exact schedule decision. (An earlier direct-address
    scatter tier was removed: n-sized scatters measure ~60x a 2-operand
    sort on TPU, so packability is the only distinction that matters.)"""
    _, ranges, oks = _key_ranges(key_data, key_valid, alive)
    ok = jnp.ones((), bool)
    for o in oks:
        ok = ok & o
    pack_cap = (1 << 62) if jax.config.read("jax_enable_x64") else (1 << 30)
    p_pack = _sat_product(ranges, pack_cap)
    return jnp.where(ok & (p_pack <= pack_cap), 1, 0).astype(_I32)


def _pack_keys(key_data: list[jax.Array], key_valid: list[jax.Array],
               alive: jax.Array) -> jax.Array:
    """Mixed-radix packed key per row (caller guarantees the domain fits).

    Recomputes _key_ranges after the group_tier probe: under compiled
    replay the identical reductions CSE into one pass; eager record pays
    the extra pass once per query, on the host CPU."""
    norms, ranges, _ = _key_ranges(key_data, key_valid, alive)
    pd = _pack_dtype()
    c = jnp.zeros(alive.shape[0], pd)
    for norm, r in zip(norms, ranges):
        c = c * r + norm.astype(pd)
    return c


def dense_rank_packsort(key_data: list[jax.Array], key_valid: list[jax.Array],
                        alive: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Tier-2 dense_rank: single packed-key sort (one operand vs 2K+2)."""
    n = alive.shape[0]
    c = _pack_keys(key_data, key_valid, alive)
    key = jnp.where(alive, c, jnp.iinfo(c.dtype).max)
    skey, perm = _sort1(key, _iota(n))
    alive_s = alive[perm]
    new_group = alive_s & jnp.concatenate(
        [jnp.ones(1, bool), skey[1:] != skey[:-1]])
    return _gid_from_sorted(new_group, alive_s, perm, n)


# ---------------------------------------------------------------------------
# filter / compact / limit
# ---------------------------------------------------------------------------

def filter_alive(alive: jax.Array, mask_data: jax.Array,
                 mask_valid: jax.Array) -> jax.Array:
    return alive & mask_data.astype(bool) & mask_valid


def compaction_perm(alive: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Stable permutation bringing alive rows to the front; returns
    (perm, count). Sort-based: a 2-operand lax.sort measures ~60x cheaper
    than the n-sized scatter this used to do (TPU scatters serialize).
    Entries past `count` are dead-row indices (callers mask by count)."""
    n = alive.shape[0]
    _, perm = _sort1((~alive).astype(_I32), _iota(n))
    return perm, jnp.sum(alive.astype(_I32))


def limit_alive(alive: jax.Array, n_keep: int) -> jax.Array:
    """Keep the first `n_keep` alive rows in physical order."""
    pos = jnp.cumsum(alive.astype(_I32)) - 1
    return alive & (pos < n_keep)


# ---------------------------------------------------------------------------
# sort
# ---------------------------------------------------------------------------

def sort_perm(key_data: list[jax.Array], key_valid: list[jax.Array],
              key_specs: tuple, alive: jax.Array) -> jax.Array:
    """Permutation realizing Spark ORDER BY semantics; dead rows go last.

    key_specs: static tuple of (asc, nulls_first) per key (nulls_first may
    be None => Spark default: asc nulls first, desc nulls last).
    """
    n = alive.shape[0]
    operands: list[jax.Array] = [(~alive).astype(_I32)]
    for col, valid, (asc, nulls_first) in zip(key_data, key_valid, key_specs):
        if nulls_first is None:
            nulls_first = asc
        # null rank: 0 => before values, 2 => after values; values rank 1
        null_rank = jnp.where(valid, 1, 0 if nulls_first else 2).astype(_I32)
        operands.append(null_rank)
        d = jnp.where(valid & alive, col, jnp.zeros((), col.dtype))
        if not asc:
            d = (~d) if d.dtype == jnp.bool_ else -d
        operands.append(d)
    out = lax.sort(tuple(operands) + (_iota(n),), num_keys=len(operands),
                   is_stable=True)
    return out[-1]


def sort_specs(keys: list[SortKey]) -> tuple:
    """Static (asc, nulls_first) tuple for sort_perm from bound SortKeys."""
    return tuple((k.asc, k.nulls_first) for k in keys)


# ---------------------------------------------------------------------------
# aggregation
# ---------------------------------------------------------------------------

# below this segment count, a vectorized (S, n) masked reduce beats the
# scatter-add that segment_sum lowers to by ~600x on TPU (scatters
# serialize; the broadcast+select fuses into the reduction). COMPILED only:
# the eager record pass would materialize the (S, n) intermediate (no
# fusion outside jit), so concrete operands keep the O(n) segment path.
# For INTEGER operands the two forms compute bit-identical values, so
# record/replay schedules agree; float reduction order differs in final
# ULPs between the paths, so float data is kept on the segment path in
# both modes (the dtype gate below) — no schedule decision may ever be
# derived from a path-divergent float reduce.
_MASKED_SEG_MAX = 64


def _seg(data: jax.Array, gid: jax.Array, num_segments: int, op: str) -> jax.Array:
    # pallas tier first: the fused tile-masked partial-agg kernel replaces
    # the serialized scatter-add for bounded segment counts; eligibility is
    # static (dtype/op/cap/rows), so one compiled program is consistent
    if int(gid.shape[0]) >= _pk.GROUPBY_MIN_ROWS \
            and _pk.op_active("groupby") \
            and _pk.seg_supported(data, num_segments, op):
        return _pk.seg_reduce(data, gid, num_segments, op)
    if (num_segments <= _MASKED_SEG_MAX and isinstance(data, jax.core.Tracer)
            and jnp.issubdtype(data.dtype, jnp.integer)):
        seg_ids = jnp.arange(num_segments, dtype=gid.dtype)
        mask = gid[None, :] == seg_ids[:, None]
        if op == "sum":
            return jnp.where(mask, data[None, :],
                             jnp.zeros((), data.dtype)).sum(axis=1)
        fill = _extreme(data.dtype, op)
        red = jnp.min if op == "min" else jnp.max
        return red(jnp.where(mask, data[None, :], fill), axis=1)
    if op == "sum":
        return jax.ops.segment_sum(data, gid, num_segments=num_segments)
    if op == "min":
        return jax.ops.segment_min(data, gid, num_segments=num_segments)
    if op == "max":
        return jax.ops.segment_max(data, gid, num_segments=num_segments)
    raise AssertionError(op)


def agg_apply(gid: jax.Array, alive: jax.Array, func: str, arg,
              cap_out: int) -> tuple[jax.Array, jax.Array]:
    """One per-group aggregate. `arg` is a (data, valid) tuple or None.

    Returns (values, valid), each length cap_out. gid for dead rows must be
    >= cap_out so their contributions fall outside the segment range.
    """
    int_out = jnp.int64 if jax.config.read("jax_enable_x64") else _I32
    if func == "count_star":
        vals = _seg(jnp.where(alive, 1, 0).astype(_I32), gid, cap_out, "sum")
        return vals.astype(int_out), jnp.ones(cap_out, bool)
    data, valid = arg
    contrib = alive & valid
    # every aggregate needs the per-group contribution count alongside its
    # value reduction: batching both through _seg_multi lets the pallas
    # groupby tier compute them in ONE fused tile pass (one membership
    # mask, several operands) instead of one scatter pipeline each
    cnt_op = contrib.astype(int_out)
    if func == "count":
        return _seg(cnt_op, gid, cap_out, "sum"), jnp.ones(cap_out, bool)
    if func == "sum":
        z = jnp.where(contrib, data, jnp.zeros((), data.dtype))
        cnt, s = _seg_multi([(cnt_op, "sum"), (z, "sum")], gid, cap_out)
        return s, cnt > 0
    if func in ("min", "max"):
        big = _extreme(data.dtype, func)
        z = jnp.where(contrib, data, big)
        cnt, vals = _seg_multi([(cnt_op, "sum"), (z, func)], gid, cap_out)
        vals = jnp.where(cnt > 0, vals, jnp.zeros((), data.dtype))
        return vals, cnt > 0
    if func == "avg":
        # integer/decimal inputs under x64: sum EXACTLY in int64 and divide
        # on the tiny per-group output — a per-row f64 cast would run the
        # whole segment reduction in software-emulated f64 on TPU (measured
        # dominant in avg-heavy plans like q9/q22). x32 keeps the float
        # path: i32 sums would wrap past 2^31 on big groups.
        if jnp.issubdtype(data.dtype, jnp.integer) and \
                jax.config.read("jax_enable_x64"):
            z = jnp.where(contrib, data, jnp.zeros((), data.dtype))
        else:
            z = jnp.where(contrib, data, jnp.zeros((), data.dtype)).astype(
                _float_dtype())
        cnt, s = _seg_multi([(cnt_op, "sum"), (z, "sum")], gid, cap_out)
        return (s.astype(_float_dtype()) /
                jnp.maximum(cnt, 1).astype(_float_dtype())), cnt > 0
    if func == "stddev_samp":
        # the squares must accumulate in float (i64 would overflow), but
        # the plain sum stays exact-int for integer inputs (x64 only: i32
        # sums would wrap)
        zf = jnp.where(contrib, data, 0).astype(_float_dtype())
        if jnp.issubdtype(data.dtype, jnp.integer) and \
                jax.config.read("jax_enable_x64"):
            s_op = jnp.where(contrib, data, jnp.zeros((), data.dtype))
        else:
            s_op = zf
        cnt, s, s2 = _seg_multi([(cnt_op, "sum"), (s_op, "sum"),
                                 (zf * zf, "sum")], gid, cap_out)
        s = s.astype(_float_dtype())
        nf = cnt.astype(_float_dtype())
        var = (s2 - s * s / jnp.maximum(nf, 1.0)) / jnp.maximum(nf - 1.0, 1.0)
        return jnp.sqrt(jnp.maximum(var, 0.0)), cnt > 1
    raise NotImplementedError(f"device agg {func}")




def _float_dtype():
    return jnp.float64 if jax.config.read("jax_enable_x64") else jnp.float32


def _extreme(dtype, func: str):
    info_fn = jnp.finfo if jnp.issubdtype(dtype, jnp.floating) else jnp.iinfo
    return jnp.asarray(info_fn(dtype).max if func == "min" else info_fn(dtype).min,
                       dtype=dtype)


def group_representatives(gid: jax.Array, alive: jax.Array,
                          data: jax.Array, valid: jax.Array,
                          cap_out: int) -> tuple[jax.Array, jax.Array]:
    """Per-group key value (all rows in a group share it)."""
    if cap_out <= _MASKED_SEG_MAX and data.dtype != jnp.bool_:
        # masked max-reduce (any row works: the group shares the value);
        # avoids the serialized n-sized scatter
        filled = jnp.where(alive, data, _extreme(data.dtype, "max"))
        vals = _seg(filled, gid, cap_out, "max")
        occupied = _seg(alive.astype(_I32), gid, cap_out, "max") > 0
        pvalid = _seg((alive & valid).astype(_I32), gid, cap_out, "max") > 0
        return jnp.where(occupied, vals, jnp.zeros((), data.dtype)), pvalid
    safe_gid = jnp.where(alive, gid, cap_out)
    padded_vals = jnp.zeros(cap_out + 1, dtype=data.dtype).at[safe_gid].set(data)
    padded_valid = jnp.zeros(cap_out + 1, dtype=bool).at[safe_gid].set(valid)
    return padded_vals[:cap_out], padded_valid[:cap_out]


def distinct_within_group(gid: jax.Array, alive: jax.Array,
                          data: jax.Array, valid: jax.Array
                          ) -> jax.Array:
    """Alive-mask of one representative row per (gid, value) pair (for
    COUNT/SUM DISTINCT): joint rank then first-occurrence selection."""
    n = alive.shape[0]
    pair_gid, _ = dense_rank([gid, jnp.where(valid, data, 0).astype(
        data.dtype), (~valid).astype(_I32)],
        [jnp.ones(n, bool), jnp.ones(n, bool), jnp.ones(n, bool)],
        alive & valid)
    first = jnp.full(n + 1, n, dtype=_I32).at[
        jnp.where(alive & valid, pair_gid, n)].min(_iota(n))
    return (alive & valid) & (first[pair_gid] == _iota(n))


# ---------------------------------------------------------------------------
# sorted aggregation: scans over key-sorted rows instead of segment scatters
# ---------------------------------------------------------------------------

def sorted_agg_scan(vals: jax.Array, new_group: jax.Array, op) -> jax.Array:
    """Inclusive within-group scan over KEY-SORTED rows (group totals sit at
    group-end rows). This is the scatter-free replacement for
    segment_sum/min/max: TPU segment_* lowers to serialized scatter-adds
    (~100ns/row measured); a log-depth associative scan is ~25x cheaper."""
    return _seg_scan(vals, new_group, op)


def group_ends(new_group: jax.Array, alive_sorted: jax.Array) -> jax.Array:
    """Row mask of each group's LAST alive row in sorted order."""
    n = new_group.shape[0]
    next_new = jnp.concatenate([new_group[1:], jnp.ones(1, bool)])
    next_dead = jnp.concatenate([~alive_sorted[1:], jnp.ones(1, bool)])
    return alive_sorted & (next_new | next_dead)


# ---------------------------------------------------------------------------
# window functions
# ---------------------------------------------------------------------------

def _seg_scan(vals: jax.Array, new_part: jax.Array, op) -> jax.Array:
    """Inclusive within-segment scan of `op` (reset at new_part) — the
    classic reset-semiring associative_scan, TPU-friendly (log-depth)."""
    def comb(a, b):
        fa, va = a
        fb, vb = b
        return fa | fb, jnp.where(fb, vb, op(va, vb))
    _, out = lax.associative_scan(comb, (new_part, vals))
    return out


def window_ordered_core(sgid: jax.Array, tie_data: list[jax.Array],
                        tie_valid: list[jax.Array], arg, func: str
                        ) -> tuple[jax.Array, jax.Array]:
    """Ordered-window values over rows ALREADY sorted by (partition, order).

    sgid: sorted partition ids (dead rows hold a trailing sentinel id).
    tie_data/tie_valid: sorted order-key columns for RANGE tie detection.
    arg: (data, valid) in sorted order, or None (rank family / count_star).
    Returns (values, valid) in sorted order; caller scatters back via the
    sort permutation and masks by `alive`. RANGE frame semantics: every row
    of a tie run takes the run's last cumulative value (Spark default
    RANGE BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW).
    """
    n = sgid.shape[0]
    iota = _iota(n)
    true1 = jnp.ones(1, bool)
    new_part = jnp.concatenate([true1, sgid[1:] != sgid[:-1]])
    same = jnp.ones(n, bool)
    for d, v in zip(tie_data, tie_valid):
        eq = jnp.concatenate([jnp.zeros(1, bool),
                              (d[1:] == d[:-1]) & (v[1:] == v[:-1])])
        same = same & eq
    same = same & ~new_part
    # index of the row's partition start / tie-run start (starts are
    # monotically increasing, so a global cummax over flagged indices works)
    part_start = lax.cummax(jnp.where(new_part, iota, 0))
    pos_in_part = iota - part_start

    if func == "row_number":
        return pos_in_part + 1, jnp.ones(n, bool)
    if func == "rank":
        run_start = lax.cummax(jnp.where(~same, iota, 0))
        return run_start - part_start + 1, jnp.ones(n, bool)
    if func == "dense_rank":
        bump = (~same) & ~new_part
        cb = jnp.cumsum(bump.astype(_I32))
        return cb - cb[part_start] + 1, jnp.ones(n, bool)

    # cumulative aggregates (RANGE: ties share the run-final value)
    new_run = ~same  # run == maximal tie group; every new_part starts a run
    run_id = jnp.cumsum(new_run.astype(_I32)) - 1
    last_of_run = jax.ops.segment_max(iota, run_id, num_segments=n)

    def ties_last(x):
        return x[last_of_run[run_id]]

    if func == "count_star":
        return ties_last(pos_in_part + 1), jnp.ones(n, bool)
    data, valid = arg
    fd = _float_dtype()
    run_count = _seg_scan(valid.astype(_I32), new_part, jnp.add)
    run_count = ties_last(run_count)
    out_valid = run_count > 0
    if func == "count":
        return run_count, jnp.ones(n, bool)
    if func in ("sum", "avg"):
        # integer inputs accumulate in the integer dtype (exact, and avoids
        # per-row software-f64 scans on TPU; f32 would lose exactness past
        # 2^24) — avg divides only the final cumulative values. avg keeps
        # the float path in x32 (i32 cumsums would wrap on big partitions);
        # sum keeps historical int accumulation in both modes.
        int_in = jnp.issubdtype(data.dtype, jnp.integer)
        acc = data.dtype if (int_in and (
            func == "sum" or jax.config.read("jax_enable_x64"))) else fd
        w = jnp.where(valid, data.astype(acc), jnp.zeros((), acc))
        run_sum = ties_last(_seg_scan(w, new_part, jnp.add))
        if func == "sum":
            return run_sum, out_valid
        return (run_sum.astype(fd) /
                jnp.maximum(run_count, 1).astype(fd)), out_valid
    if func in ("min", "max"):
        # accumulate in the NATIVE dtype: int keys past 2^24 would round
        # in f32 (TPU x32), and f32 round-trips would corrupt exact mins
        ext = _extreme(data.dtype, func)
        vals = jnp.where(valid, data, ext)
        op = jnp.minimum if func == "min" else jnp.maximum
        out = ties_last(_seg_scan(vals, new_part, op))
        out = jnp.where(out_valid, out, jnp.zeros((), data.dtype))
        return out, out_valid
    raise NotImplementedError(f"device window {func}")


# ---------------------------------------------------------------------------
# joins
# ---------------------------------------------------------------------------

def build_side(gid_right: jax.Array, alive_right: jax.Array
               ) -> tuple[jax.Array, jax.Array]:
    """Sort right-side gids (dead rows pushed to +inf); returns (sorted_gid, perm)."""
    key = jnp.where(alive_right, gid_right, jnp.iinfo(_I32).max)
    return _sort1(key, _iota(alive_right.shape[0]))


def probe_counts_by_gid(build_gid: jax.Array, build_alive: jax.Array,
                        probe_gid: jax.Array, probe_alive: jax.Array,
                        gid_cap: int) -> tuple[jax.Array, jax.Array]:
    """Per-probe-row match range in the gid-sorted build side: (start, count).

    Sort-free probe (searchsorted's vmapped while-loop is pathologically slow
    on TPU inside large programs): per-gid build counts via segment_sum, run
    offsets via exclusive cumsum — the gid-sorted build side (build_side)
    lays runs out in exactly that order — then a gather per probe row.
    gid_cap: static bound on distinct gids (callers pass lcap+rcap).
    """
    counts = jax.ops.segment_sum(
        build_alive.astype(_I32),
        jnp.where(build_alive, build_gid, gid_cap), num_segments=gid_cap)
    offsets = jnp.cumsum(counts) - counts      # exclusive prefix per gid
    safe = jnp.clip(probe_gid, 0, gid_cap - 1)
    in_range = probe_alive & (probe_gid >= 0) & (probe_gid < gid_cap)
    lo = jnp.where(in_range, offsets[safe], 0)
    cnt = jnp.where(in_range, counts[safe], 0)
    return lo.astype(_I32), cnt.astype(_I32)


def expand_join(lo: jax.Array, cnt: jax.Array, probe_alive: jax.Array,
                cap_out: int) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Materialize (left_row, build_sorted_pos) pairs for an inner join.

    cap_out must be >= total matches (caller host-syncs the total).
    Returns (left_idx, build_pos, alive_out) each of length cap_out.
    Run expansion is scatter-markers + cummax (no searchsorted): each probe
    row with matches drops its row id at its output-run start; cummax
    propagates the id across the run.
    """
    n = cnt.shape[0]
    cum = jnp.cumsum(cnt)
    total = cum[-1]
    starts = cum - cnt
    rows = _iota(n)
    has = probe_alive & (cnt > 0)
    marker = jnp.zeros(cap_out + 1, _I32).at[
        jnp.where(has, jnp.minimum(starts, cap_out), cap_out)].max(rows)
    left_pos = lax.cummax(marker[:cap_out])
    left_safe = jnp.minimum(left_pos, n - 1)
    j = _iota(cap_out)
    k = j - starts[left_safe]
    build_pos = lo[left_safe] + k
    alive_out = j < total
    return left_safe, build_pos, alive_out
