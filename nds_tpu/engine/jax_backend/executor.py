"""Device plan executor: walks a bound plan over DTables (JAX arrays).

Two execution modes (the TPU answer to the reference's accelerated plans,
reference nds/nds_power.py:124-134 + RAPIDS plugin):

- **Eager record**: each node executes as XLA compute over padded buffers
  through jitted kernels; row counts are host-synced only at shape-decision
  points (post filter/join/aggregate capacity planning), and every such
  decision is RECORDED into a capacity schedule.
- **Compiled replay**: on the next execution of the same query (unchanged
  table registrations), the entire plan is traced into ONE `jax.jit`
  program. Capacities come from the recorded schedule (static), row-alive
  masks from traced counts, and the program returns one check scalar per
  decision so the runner can verify the schedule still fits (mismatch =>
  schedule invalidated, eager re-record). Scan tables enter as jit
  arguments, so device-resident tables are shared across the whole query
  stream with zero per-query H2D transfer.

Any node the device backend does not cover falls back to the numpy oracle
backend for that node only (eager mode; such plans are never compiled).
"""
from __future__ import annotations

import dataclasses
import os
import threading
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ...obs import metrics as _metrics
from ...obs.device_time import PROGRAMS as _PROGRAMS
from ...obs.trace import TRACER
from ..column import Table, dec_scale, is_dec
from ..executor import Executor as HostExecutor
from ..plan import (
    AggregateNode, AggSpec, BExpr, DistinctNode, FilterNode, JoinNode,
    LimitNode, MaterializedNode, PlanNode, ProjectNode, ScanNode, SetOpNode,
    SortNode, VirtualScanNode, WindowFunc, WindowNode, deparameterize_plan,
    iter_plan_nodes, parameterize_plan, replace_plan_nodes,
)
from . import jexprs, kernels
from . import pallas_kernels as _pallas
from .device import (DCol, DTable, PackedTable, bucket, decode_col,
                     encode_against, free_dtable, phys_dtype, rank_key,
                     string_rank_lut, to_device, to_host, unpack_table,
                     widen_col)

_I32 = jnp.int32


class NotJittable(Exception):
    """Raised at trace time when a plan needs host-side data-dependent work."""


class ReplayMismatch(Exception):
    """A compiled plan's capacity schedule no longer fits the data."""


class ArgSpecMismatch(ValueError):
    """Concrete arguments do not fit a compiled program's input contract.

    Raised with a PER-ARGUMENT expected-vs-got dtype/shape report (scan
    keys and parameter slots named) instead of the bare structural mismatch
    the JAX call site would produce — argument drift is the hardest
    compiled-replay failure to localize otherwise."""


_NOJIT_ERRORS = (NotJittable, NotImplementedError,
                 jax.errors.TracerArrayConversionError,
                 jax.errors.ConcretizationTypeError)

#: force cost_analysis capture on the jit (no-AOT) path even without
#: tracing — one extra lower+compile per program, on its first sighting
_COST_ANALYSIS = os.environ.get(
    "NDS_TPU_COST_ANALYSIS", "").lower() in ("1", "true", "yes", "on")


class _Recorder:
    """Capacity-decision schedule: recorded eagerly, consumed under trace."""
    __slots__ = ("mode", "decisions", "idx", "checks", "nodes")

    def __init__(self, mode: str, decisions: Optional[list] = None):
        self.mode = mode                    # "record" | "replay"
        self.decisions = decisions if decisions is not None else []
        self.idx = 0
        self.checks: list[jax.Array] = []   # traced actuals (replay only)
        # record mode: the plan node whose execution made each decision
        # (index-aligned with `decisions`; None = a decision with no row
        # semantics). Replay checks are index-aligned too, so per-node
        # ACTUAL row counts ride out of every compiled run for free
        # (ExecStats.node_stats — the schedule already fetches the checks
        # host-side for verification).
        self.nodes: list = []


# Cross-stream/-session compiled-program registry (VERDICT r4 #4): stream
# variants of one template parameterize to THE SAME plan (parameterize_plan),
# so the first stream's recorded schedule + compiled program can serve every
# later stream with different parameter VALUES — no re-record, no re-trace,
# no compile. Keyed by a structural plan fingerprint; capacity drift between
# streams is caught by _verify_schedule (caps are <=-checked) and handled by
# re-recording with per-slot max-merged caps, so the program converges to a
# shape serving all streams. Exact-decision drift marks the entry volatile
# (per-stream programs, the pre-registry behavior). The reference's analog
# is Spark reusing planned queries across streams (nds/nds_power.py:124-134).
_SHARED_PROGRAMS: dict = {}
_SHARED_LOCK = threading.Lock()

#: fault/ReplayMismatch strikes per shared fingerprint (quarantine below)
_PROGRAM_STRIKES: dict = {}
#: strikes before a shared entry is quarantined (evicted, re-recorded on
#: next use). One strike is normal life — a single capacity drift or a
#: transient device fault repairs through the serial fallback; the same
#: entry failing repeatedly means the PROGRAM is poisoned (bad schedule,
#: corrupted executable) and every adopter inherits the failure.
QUARANTINE_STRIKES = 3


def clear_shared_programs() -> None:
    """Test hook: drop all cross-session shared programs."""
    with _SHARED_LOCK:
        _SHARED_PROGRAMS.clear()
        _PROGRAM_STRIKES.clear()


def strike_shared_program(fp: Optional[str], reason: str = "") -> bool:
    """Record one fault/ReplayMismatch strike against a shared program.

    At QUARANTINE_STRIKES the entry is QUARANTINED: evicted from
    _SHARED_PROGRAMS (and its strike history cleared) so the next use
    re-records and re-publishes a fresh schedule/program instead of every
    adopter replaying the poisoned one. Returns True when this strike
    evicted the entry. Thread-safe; counted in ``quarantined_programs``
    and recorded as a flight ``quarantine`` event.
    """
    if fp is None:
        return False
    with _SHARED_LOCK:
        n = _PROGRAM_STRIKES.get(fp, 0) + 1
        _PROGRAM_STRIKES[fp] = n
        if n < QUARANTINE_STRIKES:
            return False
        _PROGRAM_STRIKES.pop(fp, None)
        if _SHARED_PROGRAMS.pop(fp, None) is None:
            return False
    from ...obs.flight import FLIGHT
    from ...obs.metrics import QUARANTINED_PROGRAMS
    QUARANTINED_PROGRAMS.inc()
    FLIGHT.record("quarantine", fp=fp[:12], strikes=n,
                  reason=reason or "repeated failures")
    return True


def shared_programs_snapshot() -> list:
    """``system.programs`` rows: one per shared compiled-program cache
    entry, cut atomically under the registry lock. ``hits`` counts
    cross-stream adoptions, ``compiles`` the programs published under
    the fingerprint, ``strikes`` the live quarantine strikes (an entry
    at QUARANTINE_STRIKES is already evicted, so live strikes are
    always below the threshold)."""
    with _SHARED_LOCK:
        return [{"fingerprint": fp,
                 "hits": sh.get("adoptions", 0),
                 "compiles": sh.get("compiles", 0),
                 "strikes": _PROGRAM_STRIKES.get(fp, 0),
                 "volatile": bool(sh.get("volatile")),
                 "nojit": bool(sh.get("nojit")),
                 "decisions": len(sh.get("decisions", ()))}
                for fp, sh in sorted(_SHARED_PROGRAMS.items())]


def absolve_shared_program(fp: Optional[str]) -> None:
    """A successful run through the shared entry: clear its strikes
    (strikes mark a PERSISTENTLY failing program, not one that hiccuped
    once between healthy runs)."""
    if fp is None:
        return
    with _SHARED_LOCK:
        _PROGRAM_STRIKES.pop(fp, None)


def shared_fingerprint(pplan, shard_min_rows: int,
                       pallas_ops: frozenset) -> str:
    """Registry key of a parameterized unit plan in _SHARED_PROGRAMS.

    Module-level so the query service's PLANNER stage (which must not touch
    the device-lane executor from its worker threads) computes the same key
    the executor publishes under: plan structure + the compile-relevant
    engine configuration (x64 tier, shard threshold, kernel choice)."""
    import hashlib
    x64 = jax.config.read("jax_enable_x64")
    body = _plan_fingerprint(pplan)
    pk = ",".join(sorted(pallas_ops))
    return hashlib.sha1(
        f"{body}|x64={x64}|smr={shard_min_rows}|pallas={pk}"
        .encode()).hexdigest()


def _node_rows(decisions: list, node_labels: tuple, actuals: list) -> dict:
    """{TypeName#k: actual rows} from index-aligned (decision, label,
    actual) triples — the per-node actual row counts the schedule already
    computes (capacity syncs at record, fetched checks at replay). Labels
    match the plan verifier's node identities, so profiles, findings, and
    ``ExecStats.node_stats`` all name the same node; a node with several
    decisions keeps its largest (the output-row sync dominates probes)."""
    rows: dict = {}
    for (kind, _planned), lbl, actual in zip(decisions, node_labels,
                                             actuals):
        if lbl is None or kind not in ("cap", "exact"):
            continue
        a = int(actual)
        if lbl not in rows or a > rows[lbl]:
            rows[lbl] = a
    return rows


def _verify_schedule(decisions: list, checks_host: list) -> None:
    for (kind, planned), actual in zip(decisions, checks_host):
        a = int(actual)
        if kind == "cap":
            if a > bucket(max(int(planned), 1)):
                raise ReplayMismatch(f"capacity overflow: {a} > planned "
                                     f"{planned}")
        else:  # exact
            if a != int(planned):
                raise ReplayMismatch(f"exact decision drift: {a} != {planned}")


class CompiledQuery:
    """One whole-plan XLA program built from a recorded capacity schedule.

    Scan tables enter as a TUPLE in first-touch order and hoisted stream
    literals as a parameter vector: the traced program is therefore
    byte-identical across streams/seeds of one template (same structure,
    same capacities), and the persistent XLA cache serves every stream
    after the first compile.

    `plan` may be a LIST of plans (shared-scan fused morsel groups,
    streaming.fuse_group): the plans trace in order under ONE decision
    schedule — recorded by JaxExecutor.record_plans — into one multi-output
    program, and run() returns a tuple of DTables. The fixed per-dispatch
    tunnel RTT is then paid once per morsel instead of once per branch."""

    def __init__(self, plan, decisions: list, scan_keys: tuple,
                 mesh=None, param_dtypes: tuple = (),
                 shard_min_rows: int = 1 << 18, label: str = "",
                 pallas_ops: frozenset = frozenset(),
                 decision_nodes: Optional[tuple] = None):
        self.plan = plan
        self.decisions = decisions
        self.scan_keys = scan_keys
        # per-decision TypeName#k attribution (record-time; index-aligned
        # with decisions/checks): lets every replay report the per-node
        # actual row counts its schedule checks already fetched
        self.decision_nodes = decision_nodes
        self.mesh = mesh
        self.param_dtypes = param_dtypes
        self.shard_min_rows = shard_min_rows
        # the kernel choice is part of the program's identity: replay must
        # trace the same pallas/XLA sides the recording executor took
        self.pallas_ops = frozenset(pallas_ops)
        # device-time attribution key (obs.device_time): "<query>/<unit>";
        # every run's measured dispatch wall accumulates under it, and the
        # jax.profiler annotation carries it into hardware profiles
        self.label = label or "program"
        self._fn = None
        self._aot = None     # AOT executable from precompile()
        self._aot_specs = None  # flat (shape, dtype) list the AOT was lowered for
        self._aot_arg_specs = None  # per-argument [(label, specs)] for reports
        self._cost_recorded = False  # cost_analysis captured once per program
        # _SHARED_PROGRAMS hands one CompiledQuery to every stream of a
        # template: concurrent multi-stream runs must not race the lazy
        # _fn/_aot initialization (ADVICE r5)
        self._lock = threading.Lock()

    def _trace(self, scan_tuple: tuple, params: tuple):
        scans = dict(zip(self.scan_keys, scan_tuple))
        rec = _Recorder("replay", self.decisions)
        # the mesh AND size thresholds MUST match the recording executor's:
        # static branches (compaction skip, shard-local aggregation, the
        # shuffle-join gate) key on them, and a mismatched replay would
        # consume a differently-shaped schedule
        ex = JaxExecutor(_no_load, recorder=rec, scan_tables=scans,
                         mesh=self.mesh, params=params,
                         shard_min_rows=self.shard_min_rows,
                         pallas_ops=self.pallas_ops)
        if isinstance(self.plan, (list, tuple)):
            outs = []
            for p in self.plan:
                # memo resets between member plans, mirroring the per-plan
                # record passes (record_plans) so both consume the shared
                # decision schedule identically
                ex._memo = {}
                outs.append(ex.execute(p))
            out = tuple(outs)
        else:
            out = ex.execute(self.plan)
        if rec.idx != len(rec.decisions):
            raise NotJittable("decision schedule length drift")
        if ex.fallback_nodes:
            raise NotJittable(f"fallback under trace: {ex.fallback_nodes}")
        return out, rec.checks

    def _args(self, scans: dict, values: tuple) -> tuple:
        missing = [k for k in self.scan_keys if k not in scans]
        if missing:
            raise ArgSpecMismatch(
                f"missing scan argument(s) {missing} "
                f"(program takes {len(self.scan_keys)} scan(s): "
                f"{list(self.scan_keys)})")
        if len(values) != len(self.param_dtypes):
            # zip would silently truncate: a short parameter vector would
            # execute with the wrong literals, not fail
            raise ArgSpecMismatch(
                f"parameter vector length mismatch: program expects "
                f"{len(self.param_dtypes)} hoisted parameter(s) with "
                f"dtypes {list(self.param_dtypes)}, got "
                f"{len(values)} value(s)")
        scan_tuple = tuple(scans[k] for k in self.scan_keys)
        params = tuple(jnp.asarray(v, dtype=phys_dtype(d))
                       for v, d in zip(values, self.param_dtypes))
        return scan_tuple, params

    def precompile(self, scan_specs: tuple, stats: Optional[dict] = None):
        """Trace + compile ahead of execution from abstract arg specs
        (jax.ShapeDtypeStruct trees mirroring the scan tables) WITHOUT
        uploading data. Raises the same _NOJIT_ERRORS a traced run would.
        The resulting AOT executable serves run() directly; compile RPCs
        through the tunnel parallelize, so callers fan precompile() calls
        out over a thread pool (one compile per segment/query at once
        instead of serial-at-first-execution)."""
        import time as _time

        from ...resilience import FAULTS
        FAULTS.fire("jax.compile")
        with self._lock:
            if self._fn is None:
                self._fn = jax.jit(self._trace)
            fn = self._fn
        params = tuple(jax.ShapeDtypeStruct((), phys_dtype(d))
                       for d in self.param_dtypes)
        t0 = _time.perf_counter()
        with TRACER.span("compile", cat="compile", label=self.label):
            aot = fn.lower(scan_specs, params).compile()
        _metrics.COMPILES.inc()
        self._record_cost(aot)
        with self._lock:
            self._aot = aot
            self._aot_specs = self._flat_specs((scan_specs, params))
            self._aot_arg_specs = self._arg_spec_table(scan_specs, params)
        if stats is not None:
            stats["precompile_s"] = round(_time.perf_counter() - t0, 3)

    def _record_cost(self, compiled) -> None:
        """Attach the program's static cost_analysis() FLOPs/bytes to the
        device-time registry ONCE — the per-program roofline denominator.
        Best-effort: cost data enriches attribution, never fails a run."""
        if self._cost_recorded:
            return
        try:
            _PROGRAMS.record_cost(self.label, compiled.cost_analysis())
            self._cost_recorded = True
        except Exception:
            self._cost_recorded = True   # unsupported backend: don't retry

    @staticmethod
    def _flat_specs(tree) -> Optional[list]:
        """Flat (shape, dtype) list of a pytree of arrays/specs; None when a
        leaf carries neither (spec checking is then unavailable)."""
        leaves = jax.tree_util.tree_leaves(tree)
        out = []
        for leaf in leaves:
            shape = getattr(leaf, "shape", None)
            dtype = getattr(leaf, "dtype", None)
            if shape is None or dtype is None:
                return None
            out.append((tuple(shape), np.dtype(dtype)))
        return out

    def _specs_match(self, args) -> bool:
        """Do concrete args structurally fit the AOT executable's input
        specs? Shape/dtype only — shardings/placement are re-checked by the
        runtime itself (the narrow except in run())."""
        if self._aot_specs is None:
            return False
        got = self._flat_specs(args)
        return got is not None and got == self._aot_specs

    def _arg_spec_table(self, scan_tuple, params) -> list:
        """[(argument label, flat specs)] with one entry per program
        argument: scan tables by their cache key, parameter slots by index
        and engine dtype — the unit of the expected-vs-got report."""
        table = []
        for k, s in zip(self.scan_keys, scan_tuple):
            table.append((f"scan {k!r}", self._flat_specs(s)))
        for i, (p, d) in enumerate(zip(params, self.param_dtypes)):
            table.append((f"param {i} ({d})", self._flat_specs((p,))))
        return table

    @staticmethod
    def _fmt_spec(spec) -> str:
        shape, dtype = spec
        return f"{dtype}[{','.join(map(str, shape))}]"

    def spec_mismatch_report(self, scans: dict, values: tuple = ()
                             ) -> Optional[str]:
        """Per-argument expected-vs-got dtype/shape report against the
        precompiled input specs; None when everything fits (or no AOT
        specs exist to validate against)."""
        if self._aot_arg_specs is None:
            return None
        scan_tuple, params = self._args(scans, values)
        got_table = self._arg_spec_table(scan_tuple, params)
        lines: list[str] = []
        for (label, exp), (_, got) in zip(self._aot_arg_specs, got_table):
            if exp == got:
                continue
            if exp is None or got is None:
                lines.append(f"{label}: argument is not inspectable")
                continue
            if len(exp) != len(got):
                lines.append(f"{label}: expected {len(exp)} array(s) "
                             f"(e.g. columns/validity), got {len(got)}")
                continue
            for j, (e, g) in enumerate(zip(exp, got)):
                if e != g:
                    lines.append(
                        f"{label} leaf {j}: expected "
                        f"{self._fmt_spec(e)}, got {self._fmt_spec(g)}")
        return "\n".join(lines) or None

    def validate_args(self, scans: dict, values: tuple = ()) -> None:
        """Raise ArgSpecMismatch naming every drifted argument (expected vs
        got dtype/shape) when the concrete args do not fit the compiled
        program; silently returns when they fit or nothing is compiled."""
        report = self.spec_mismatch_report(scans, values)
        if report:
            raise ArgSpecMismatch(
                "compiled program argument mismatch:\n" + report)

    def run(self, scans: dict, values: tuple = (),
            stats: Optional[dict] = None,
            keep_device: bool = False) -> DTable:
        import time as _time

        from ...resilience import FAULTS
        with self._lock:
            first = self._fn is None
            if first:
                FAULTS.fire("jax.compile")
                self._fn = jax.jit(self._trace)
            fn, aot = self._fn, self._aot
        if first:
            _metrics.COMPILES.inc()   # jit path compiles inside the call
        FAULTS.fire("jax.execute")
        # attribution boundary (the Flare lesson): the compiled-program
        # dispatch is the unit device time is measured at; the jax.profiler
        # annotation carries the same label into hardware profiles
        with TRACER.span("exec", cat="device", label=self.label,
                         first=first):
            t1 = _time.perf_counter()
            args = self._args(scans, values)
            if aot is not None and not self._specs_match(args):
                # shape/dtype drift against the precompiled specs: take the
                # jit path explicitly (the persistent compile cache still
                # serves the binary when the lowering matches) instead of
                # letting the AOT call fail and masking the error class.
                # The per-argument expected-vs-got report lands in stats so
                # the drift is attributable to a specific scan/param, not a
                # bare mismatch.
                if stats is not None:
                    report = self.spec_mismatch_report(scans, values)
                    if report:
                        stats["spec_mismatch"] = report
                with self._lock:
                    if self._aot is aot:
                        self._aot = None
                aot = None
            with jax.profiler.TraceAnnotation(self.label):
                if aot is not None:
                    try:
                        out, checks = aot(*args)
                    except (TypeError, ValueError) as aot_err:
                        # drift the shape check cannot see (committed-device
                        # / sharding mismatch). Retry via jit once; a jit
                        # failure of the SAME class is a genuine runtime
                        # error — re-raise it with the AOT error as explicit
                        # context instead of swallowing the original.
                        with self._lock:
                            if self._aot is aot:
                                self._aot = None
                        try:
                            out, checks = fn(*args)
                        except type(aot_err):
                            raise aot_err
                else:
                    out, checks = fn(*args)
                # ONE device_get for result + checks: tunneled platforms
                # charge a fixed RTT per transfer, so piecemeal np.asarray
                # would dominate. keep_device (segment outputs feeding
                # downstream programs): only the check scalars come back.
                if keep_device:
                    checks_host = jax.device_get(checks)
                    out_host = out
                else:
                    out_host, checks_host = jax.device_get((out, checks))
            t2 = _time.perf_counter()
        _verify_schedule(self.decisions, checks_host)
        if stats is not None:
            checks_int = [int(c) for c in checks_host]
            if "decision_rows" in stats:
                # raw index-aligned per-decision actuals, exported ONLY
                # when the caller pre-seeded the key (the adaptive
                # streaming loop feeding the feedback store) — an
                # unconditional write would leak the list into every
                # in-core ExecStats.extra and break the off-mode
                # bit-identity contract
                stats["decision_rows"] = checks_int
            if self.decision_nodes:
                rows = _node_rows(self.decisions, self.decision_nodes,
                                  checks_int)
                if rows:
                    stats["node_rows"] = rows
        device_ms = round((t2 - t1) * 1000, 3)
        _PROGRAMS.record_run(self.label, device_ms, first=first)
        if aot is not None:
            self._record_cost(aot)      # cheap: executable already built
        elif first and (TRACER.enabled or _COST_ANALYSIS):
            # jit path keeps no public handle on its executable: re-lower
            # once (host-side, paid on the untimed compile+run sighting
            # only, and only when attribution is wanted) to pull FLOPs/bytes
            try:
                self._record_cost(fn.lower(*args).compile())
            except Exception:
                self._cost_recorded = True
        if stats is not None:
            stats.update(mode="compile+run" if first else "compiled",
                         device_ms=device_ms)
        return out_host


class BatchedQuery:
    """One compiled program replayed over a STACKED batch of parameter
    vectors — the query service's compatible-plan batching unit.

    K admitted queries that parameterize to the same plan fingerprint
    (same structure, same recorded capacities, same scan tables, different
    hoisted literal VALUES) are served by a single dispatch: each parameter
    slot stacks into a (cap,)-vector and ``lax.map`` replays the SAME
    traced program per row, so row i's computation graph — and therefore
    its result — is exactly the single-query program's. The batch capacity
    rides the same ladder as row capacities (device.bucket), bounding the
    compile count to one batched program per (fingerprint, batch-capacity);
    short batches pad by duplicating the last real row (identical checks,
    discarded outputs).

    Schedule checks come back as (cap,)-vectors and verify batch-aware,
    exactly like sharded-morsel replays (shard_exec): cap decisions check
    max-over-batch <= bucket, exact decisions check all-equal — any row
    drifting raises ReplayMismatch and the caller serves the batch
    serially through the normal record/replay path instead."""

    def __init__(self, cq: CompiledQuery, cap: int):
        self.cq = cq
        self.cap = cap
        self.label = f"{cq.label}@batch{cap}"
        self._fn = None
        self._lock = threading.Lock()

    def _trace(self, scan_tuple: tuple, stacked: tuple):
        def one(params):
            out, checks = self.cq._trace(scan_tuple, tuple(params))
            return out, tuple(checks)
        return lax.map(one, stacked)

    def run(self, scans: dict, rows: list,
            stats: Optional[dict] = None) -> list:
        """Run ``rows`` (parameter-value tuples, len <= cap) in ONE
        dispatch; returns one HOST-side DTable per row (numpy leaves —
        device_get happens once for the whole stacked output)."""
        import time as _time

        from ...resilience import FAULTS
        dts = self.cq.param_dtypes
        full = list(rows) + [rows[-1]] * (self.cap - len(rows))
        stacked = tuple(
            jnp.asarray([r[j] for r in full], dtype=phys_dtype(d))
            for j, d in enumerate(dts))
        scan_tuple = tuple(scans[k] for k in self.cq.scan_keys)
        with self._lock:
            first = self._fn is None
            if first:
                FAULTS.fire("jax.compile")
                self._fn = jax.jit(self._trace)
            fn = self._fn
        if first:
            _metrics.COMPILES.inc()
        FAULTS.fire("jax.execute")
        with TRACER.span("exec", cat="device", label=self.label,
                         first=first, batch=len(rows)):
            t1 = _time.perf_counter()
            with jax.profiler.TraceAnnotation(self.label):
                out, checks = fn(scan_tuple, stacked)
                out_host, checks_host = jax.device_get((out, checks))
            t2 = _time.perf_counter()
        for (kind, planned), actual in zip(self.cq.decisions, checks_host):
            a = np.asarray(actual)
            if kind == "cap":
                if int(a.max()) > bucket(max(int(planned), 1)):
                    raise ReplayMismatch(
                        f"batched capacity overflow: {int(a.max())} > "
                        f"planned {planned}")
            elif not bool((a == int(planned)).all()):
                raise ReplayMismatch(
                    f"batched exact decision drift: {a.tolist()} != "
                    f"{planned}")
        device_ms = round((t2 - t1) * 1000, 3)
        _PROGRAMS.record_run(self.label, device_ms, first=first)
        if stats is not None:
            stats.update(mode="batched", device_ms=device_ms,
                         batch=len(rows))
        return [jax.tree_util.tree_map(lambda x: x[i], out_host)
                for i in range(len(rows))]


def _no_load(name: str) -> Table:
    raise NotJittable(f"table load of {name!r} under trace")


class JaxExecutor:
    """Executes bound plans on the JAX backend with per-node host fallback.

    One instance lives on the Session (scan cache + compiled plans persist
    across the query stream); replay instances are created per trace.
    """

    def __init__(self, load_table: Callable[[str], Table],
                 trace: Optional[Callable[[str, float, int], None]] = None,
                 recorder: Optional[_Recorder] = None,
                 scan_tables: Optional[dict] = None,
                 jit_plans: bool = True,
                 mesh=None,
                 shard_min_rows: int = 1 << 18,
                 segment_plan_nodes: int = 18,
                 segment_min_cte_nodes: int = 8,
                 segment_cache_entries: int = 16,
                 scan_budget_bytes: int = 10 << 30,
                 params: Optional[tuple] = None,
                 pallas_ops=frozenset(),
                 shard_local: bool = False):
        self._load_table = load_table
        # the plan node currently executing (execute() maintains it):
        # capacity decisions made while it runs attribute to it, so the
        # recorded schedule doubles as a per-node actual-row-count source
        self._cur_node = None
        # per-decision node list of the last record_plan/record_plans pass
        self._last_record_nodes: Optional[list] = None
        # shard-local mode (sharded morsel execution, shard_exec): this
        # executor's trace runs INSIDE a shard_map body, one replica's rows
        # at a time. Schedule-shaping gates behave like the mesh path (no
        # data-dependent tier probes, no compaction — per-shard data would
        # drift the recorded exact decisions), but execution strategies stay
        # single-device (no in-plan collectives: the shard_map boundary IS
        # the collective).
        self._shard_local = bool(shard_local)
        # per-op Pallas kernel activation (EngineConfig.pallas_ops): off
        # under a GSPMD mesh — pack probes and in-plan shard_map
        # partitioning assume the generic lowering there. Shard-LOCAL
        # executors run the kernels: inside shard_map every operand is one
        # replica's block, exactly the single-chip shapes the kernels tile.
        self._pallas_ops = frozenset() if mesh is not None \
            else _pallas.parse_ops(pallas_ops)
        # hoisted literal values for the in-flight execution: python scalars
        # under eager record, traced 0-d arrays under compiled replay
        self._params = params
        self._memo: dict[int, DTable] = {}
        self._scan_cache: dict[str, DTable] = scan_tables if scan_tables \
            is not None else {}           # accelerator-resident tables
        self._trace = trace
        self._rec = recorder
        self._replay = recorder is not None and recorder.mode == "replay"
        self._jit_plans = jit_plans
        self._plans: dict = {}           # query key -> plan/schedule entry
        self._touched_scans: dict[str, None] = {}   # ordered set (first touch)
        self._scan_meta: dict[str, tuple] = {}   # key -> (table, cols, names)
        self.fallback_nodes: list[str] = []   # observability: who fell back
        # label of the in-flight query (Session.sql sets it); compile units
        # recorded during the run inherit "<label>/<unit>" program labels
        # for device-time attribution
        self.query_label: str = ""
        # SPMD execution: with a mesh, fact-sized scans upload row-sharded
        # (NamedSharding over the first axis); GSPMD partitions the compiled
        # whole-plan program and inserts the collectives (the Spark-shuffle
        # role, SURVEY.md §2 parallelism table last row). Dimension-sized
        # tables replicate (broadcast-join layout).
        self._mesh = mesh
        self._shard_min_rows = shard_min_rows
        # CTE-boundary compile segmentation (VERDICT r2 #1): plans above the
        # node threshold split each large CTE into its own compile unit
        self._seg_plan_nodes = segment_plan_nodes
        self._seg_min_cte = segment_min_cte_nodes
        self._seg_cache_entries = segment_cache_entries
        self._segment_lru: list[str] = []
        self._pinned_segments: set[str] = set()
        # HBM accounting for the accelerator-resident cache: key -> bytes,
        # in LRU order (python dicts preserve insertion; re-touch moves to
        # the end). Evicting frees the arrays for XLA to reuse.
        self._scan_budget = scan_budget_bytes
        self._resident: dict[str, int] = {}
        # fingerprint whose shared program just ReplayMismatched here: the
        # post-mismatch re-record must not re-adopt it (see _adopt_shared)
        self._fp_block: Optional[str] = None
        # batched compiled programs (query-service compatible-plan
        # batching): (fingerprint, batch capacity) -> BatchedQuery
        self._batched: dict = {}
        # Eager (record / fallback) execution runs on the host CPU backend
        # when the default device is an accelerator: per-op dispatch latency
        # through a device tunnel is catastrophic, and the record pass only
        # needs the capacity schedule + a correct result. Compiled replay
        # runs on the accelerator.
        self._eager_device = None
        self._scan_cache_rec: dict[str, DTable] = self._scan_cache
        if not self._replay and jax.default_backend() != "cpu":
            try:
                self._eager_device = jax.devices("cpu")[0]
                self._scan_cache_rec = {}
            except RuntimeError:
                pass
        if mesh is not None and self._scan_cache_rec is self._scan_cache:
            # single-host CPU mesh (tests/dryrun): record single-device,
            # execute sharded — the caches hold different layouts
            self._scan_cache_rec = {}

    def _exec_sharding(self, capacity: int):
        """Placement for an accelerator-resident scan of given capacity."""
        if self._mesh is None:
            return None
        from jax.sharding import NamedSharding, PartitionSpec as P
        axis = self._mesh.axis_names[0]
        if capacity >= max(self._shard_min_rows,
                           self._mesh.size) and capacity % self._mesh.size == 0:
            return NamedSharding(self._mesh, P(axis))
        return NamedSharding(self._mesh, P())

    # -- public --------------------------------------------------------------
    def run_query(self, key, plan_factory: Callable[[], PlanNode]) -> DTable:
        """Session entry point: cached compiled execution when possible.

        key: hashable query identity (SQL text); None disables caching.

        Large multi-CTE plans are segmented at CTE boundaries into several
        compile units (see _segment_plan): each CTE materializes once as a
        device-resident table, shared across this query's parts AND across
        statements with an identical WITH clause (q14/q23 parts). Bounded
        XLA compile time replaces the reference's rely-on-Spark-planner
        property (nds/nds_power.py:124-134) that q4-class plans broke here.
        """
        self.fallback_nodes = []
        self.last_stats: dict = {}
        meta_key = ("segmeta", key) if key is not None else None
        meta = self._plans.get(meta_key) if meta_key is not None else None
        if meta is None:
            plan = plan_factory()
            units = self._segment_plan(plan)
            if meta_key is not None and self._jit_plans:
                self._plans[meta_key] = {"units": units}
        else:
            units = meta["units"]
        if len(units) == 1:
            return self._run_unit(key, units[0][1])
        seg_ms = 0.0
        segs_run = 0
        out = None
        # second sighting of a multi-unit query: every unit has a recorded
        # schedule but no program yet — compile them CONCURRENTLY before
        # executing (q22's 7 rollup segments compile in max() not sum())
        if key is not None and self._jit_plans:
            unit_keys = [((key, "root") if sk is None else (key, sk))
                         for sk, _ in units]
            if any(self._plans.get(uk, {}).get("decisions") is not None
                   and self._plans[uk].get("cq") is None
                   and not self._plans[uk].get("nojit")
                   for uk in unit_keys):
                self.precompile_parallel(keys=set(unit_keys))
        # pin this query's segments: LRU pressure from binding segment N
        # must never evict segment M still needed by a later unit
        self._pinned_segments = {sk for sk, _ in units if sk is not None}
        try:
            for seg_key, uplan in units:
                self.last_stats = {}     # per-unit stats; no cross-unit leaks
                if seg_key is None:
                    root_key = (key, "root") if key is not None else None
                    out = self._run_unit(root_key, uplan)
                    continue
                if seg_key in self._scan_cache or \
                        seg_key in self._scan_cache_rec:
                    self._touch_segment(seg_key)
                    continue
                unit_key = (key, seg_key) if key is not None else None
                seg_out = self._run_unit(unit_key, uplan, keep_device=True)
                self._bind_segment(seg_key, seg_out)
                segs_run += 1
                seg_ms += self.last_stats.get("device_ms", 0.0)
        finally:
            self._pinned_segments = set()
        root_stats = dict(self.last_stats)
        root_stats.update(segments=len(units) - 1, segments_run=segs_run,
                          seg_device_ms=round(seg_ms, 3))
        self.last_stats = root_stats
        return out

    # -- segmentation ---------------------------------------------------------
    def _segment_plan(self, plan: PlanNode) -> list:
        """Split a big plan into [(seg_key, unit_plan)...] + [(None, root)].

        Two cut classes, both yielding bounded XLA programs:
        - CTE boundaries (planner-fingerprinted, shared across statements);
        - rollup grouping-set boundaries (q67-class plans have no CTEs but
          compile one giant program per grouping set: the aggregate's child
          materializes once and each rollup level becomes its own unit).
        Units are in dependency order; a later unit sees earlier outputs as
        VirtualScanNodes resolved against the segment cache."""
        if not self._jit_plans or self._seg_plan_nodes <= 0:
            return [(None, plan)]
        out = []
        for seg_key, uplan in self._cte_units(plan):
            out.extend(self._rollup_units(seg_key, uplan))
        return out

    def _cte_units(self, plan: PlanNode) -> list:
        segs = getattr(plan, "cte_segments", None)
        if not segs:
            return [(None, plan)]
        nodes = list(iter_plan_nodes(plan))
        if len(nodes) < self._seg_plan_nodes:
            return [(None, plan)]
        reachable = {id(n) for n in nodes}
        mapping: dict[int, PlanNode] = {}
        units: list = []
        seen_keys: set[str] = set()
        for fp, node in segs:
            if id(node) not in reachable:
                continue
            if sum(1 for _ in iter_plan_nodes(node)) < self._seg_min_cte:
                continue
            seg_key = "seg:" + fp
            virt = VirtualScanNode(key=seg_key, label="cte",
                                   out_names=list(node.out_names),
                                   out_dtypes=list(node.out_dtypes))
            if seg_key not in seen_keys:
                seen_keys.add(seg_key)
                units.append((seg_key,
                              replace_plan_nodes(node, mapping)
                              if mapping else node))
            mapping[id(node)] = virt
        if not units:
            return [(None, plan)]
        units.append((None, replace_plan_nodes(plan, mapping)))
        return units

    def _rollup_units(self, seg_key, uplan: PlanNode) -> list:
        """Split big rollup aggregates in one compile unit into per-level
        units: [(child_seg, child), (level_seg, level_agg)..., (seg_key,
        rewritten)]. The rewrite unions per-level VirtualScans, which is
        exactly the concat the in-program rollup performs."""
        nodes = list(iter_plan_nodes(uplan))
        if len(nodes) < self._seg_plan_nodes:
            return [(seg_key, uplan)]
        units: list = []
        mapping: dict[int, PlanNode] = {}
        cands = [n for n in nodes
                 if isinstance(n, AggregateNode) and n.rollup
                 and n.rollup_levels is None and len(n.group_exprs) >= 2]
        # innermost first: a rollup nested in another rollup's child must be
        # rewritten before the outer child unit is cut, or the outer unit
        # would still compile the inner one as a giant in-program rollup
        cands.sort(key=lambda a: sum(1 for _ in iter_plan_nodes(a)))
        for orig in cands:
            child_nodes = list(iter_plan_nodes(orig.child))
            if len(child_nodes) < self._seg_min_cte or \
                    any(isinstance(m, MaterializedNode) for m in child_nodes):
                continue
            child = replace_plan_nodes(orig.child, mapping) if mapping \
                else orig.child
            agg = dataclasses.replace(orig, child=child) if child \
                is not orig.child else orig
            ckey = "seg:" + _plan_fingerprint(child)
            virt_child = VirtualScanNode(
                key=ckey, label="rollup-src",
                out_names=list(child.out_names),
                out_dtypes=list(child.out_dtypes))
            units.append((ckey, child))
            branches: list[PlanNode] = []
            for lvl in range(len(agg.group_exprs), -1, -1):
                lnode = dataclasses.replace(agg, child=virt_child,
                                            rollup_levels=[lvl])
                lkey = "seg:" + _plan_fingerprint(lnode)
                units.append((lkey, lnode))
                branches.append(VirtualScanNode(
                    key=lkey, label=f"rollup-lvl{lvl}",
                    out_names=list(agg.out_names),
                    out_dtypes=list(agg.out_dtypes)))
            chain = branches[0]
            for v in branches[1:]:
                chain = SetOpNode(op="union", all=True, left=chain, right=v,
                                  out_names=list(agg.out_names),
                                  out_dtypes=list(agg.out_dtypes))
            mapping[id(orig)] = chain     # keyed by the ORIGINAL node id
        if not mapping:
            return [(seg_key, uplan)]
        return units + [(seg_key, replace_plan_nodes(uplan, mapping))]

    def _bind_segment(self, seg_key: str, out: DTable) -> None:
        """Stash a segment output for downstream units; LRU-bounded."""
        if self.last_stats.get("mode") in ("compiled", "compile+run"):
            self._scan_cache[seg_key] = out
            self._account_resident(seg_key, out)
        else:          # record/eager output lives on the record-side device
            self._scan_cache_rec[seg_key] = out
        self._touch_segment(seg_key)

    def _touch_segment(self, seg_key: str) -> None:
        if seg_key in self._segment_lru:
            self._segment_lru.remove(seg_key)
        self._segment_lru.append(seg_key)
        pinned = getattr(self, "_pinned_segments", set())
        evictable = [k for k in self._segment_lru if k not in pinned]
        while len(self._segment_lru) > self._seg_cache_entries and evictable:
            old = evictable.pop(0)
            self._segment_lru.remove(old)
            # free eagerly: tunneled platforms pin buffers until gc, so a
            # dropped reference alone would not reclaim HBM promptly
            free_dtable(self._scan_cache.pop(old, None))
            self._resident.pop(old, None)
            if self._scan_cache_rec is not self._scan_cache:
                self._scan_cache_rec.pop(old, None)

    def _unit_label(self, key) -> str:
        """Attribution label for a compile unit: "<query>/<unit>" — the key
        the device-time registry ranks programs by (segments keep a short
        fingerprint so q14/q23-style shared CTEs stay distinguishable)."""
        base = self.query_label or "query"
        if isinstance(key, tuple) and len(key) == 2 and \
                isinstance(key[1], str):
            if key[1].startswith("seg:"):
                return f"{base}/{key[1][:12]}"
            if key[1] == "root":
                return f"{base}/root"
        return base

    def _run_unit(self, key, plan, keep_device: bool = False) -> DTable:
        """One compile unit through the record -> compile -> replay
        lifecycle (the pre-segmentation run_query body)."""
        fb0 = len(self.fallback_nodes)
        plan_factory = plan if callable(plan) else (lambda: plan)
        ent = self._plans.get(key) if key is not None else None
        if ent is not None:
            _metrics.PROGRAM_CACHE_HITS.inc()
            if ent["cq"] is not None:                  # steady state
                try:
                    out = self._run_compiled(ent["cq"], ent, keep_device)
                    ent["rt_failures"] = 0
                    return out
                except _NOJIT_ERRORS as e:
                    # reachable when precompile_parallel installed the cq
                    # from specs and the real args re-trace differently
                    ent["cq"] = None
                    ent["nojit"] = True
                    ent["nojit_reason"] = f"{type(e).__name__}: {e}"
                    self.last_stats["mode"] = "eager"
                    self.last_stats["nojit_reason"] = ent["nojit_reason"]
                    return self._eager_ent(ent)
                except ReplayMismatch:
                    _metrics.REPLAY_MISMATCHES.inc()
                    self._fp_block = ent.get("fp")
                    self._plans.pop(key, None)
                    ent = None
                except jax.errors.JaxRuntimeError as e:
                    # transient infra failure (e.g. remote compile service
                    # hiccup): serve this call eagerly. Two consecutive
                    # failing episodes = deterministic runtime failure
                    # (e.g. device OOM); drop the program so the query
                    # re-records instead of re-running a doomed binary
                    ent["rt_failures"] = ent.get("rt_failures", 0) + 1
                    if ent["rt_failures"] >= 2:
                        self._plans.pop(key, None)
                    self.last_stats.update(mode="eager",
                                           transient=f"{e}"[:200])
                    return self._eager_ent(ent)
            elif ent["nojit"]:
                self.last_stats["mode"] = "eager"
                return self._eager_ent(ent)
            else:                                      # second sighting
                cq = CompiledQuery(ent["plan"], ent["decisions"],
                                   ent["scan_keys"], mesh=self._mesh,
                                   param_dtypes=ent.get("param_dtypes", ()),
                                   shard_min_rows=self._shard_min_rows,
                                   label=ent.get("label",
                                                 self._unit_label(key)),
                                   pallas_ops=self._pallas_ops,
                                   decision_nodes=ent.get("decision_nodes"))
                try:
                    out = self._run_compiled(cq, ent, keep_device)
                    ent["cq"] = cq
                    ent["rt_failures"] = 0
                    self._publish_cq(ent)
                    return out
                except _NOJIT_ERRORS as e:
                    ent["nojit"] = True
                    ent["nojit_reason"] = f"{type(e).__name__}: {e}"
                    self.last_stats["mode"] = "eager"
                    self.last_stats["nojit_reason"] = ent["nojit_reason"]
                    return self._eager_ent(ent)
                except ReplayMismatch:
                    _metrics.REPLAY_MISMATCHES.inc()
                    self._fp_block = ent.get("fp")
                    self._plans.pop(key, None)
                    ent = None
                except jax.errors.JaxRuntimeError as e:
                    # transient: don't mark nojit — the next execution
                    # retries compilation (bounded like the steady state)
                    ent["rt_failures"] = ent.get("rt_failures", 0) + 1
                    if ent["rt_failures"] >= 2:
                        self._plans.pop(key, None)
                    self.last_stats.update(mode="eager",
                                           transient=f"{e}"[:200])
                    return self._eager_ent(ent)
        # first sighting (or invalidated): eager run, recording the schedule
        _metrics.PROGRAM_CACHE_MISSES.inc()
        plan = plan_factory()
        fp = None
        if key is not None and self._jit_plans:
            pplan, pvalues, pdtypes = parameterize_plan(plan)
            fp = self._shared_fp(pplan)
            if self._adopt_shared(key, fp, tuple(pvalues), tuple(pdtypes)):
                self.last_stats["mode"] = "adopted"
                _metrics.PROGRAMS_ADOPTED.inc()
                return self._run_unit(key, plan, keep_device)
        else:       # uncached one-shot: skip the rewrite, nothing reuses it
            pplan, pvalues, pdtypes = plan, [], []
        self.last_stats["mode"] = "record"
        with TRACER.span("record", label=self._unit_label(key)):
            out, decisions, scan_keys = self.record_plan(pplan,
                                                         tuple(pvalues))
        nodes_attr = self._decision_labels(pplan)
        if nodes_attr:
            # the record pass's decision VALUES are the actuals: the same
            # per-node row counts a later replay reads from its checks
            rows = _node_rows(decisions, nodes_attr,
                              [v for _k, v in decisions])
            if rows:
                self.last_stats["node_rows"] = rows
        if key is not None and self._jit_plans:
            ent = {
                "plan": pplan, "decisions": decisions,
                "scan_keys": scan_keys,
                "params": tuple(pvalues), "param_dtypes": tuple(pdtypes),
                "decision_nodes": nodes_attr,
                "cq": None, "nojit": len(self.fallback_nodes) > fb0,
                "fp": fp, "label": self._unit_label(key)}
            self._publish_recorded(ent)
            self._plans[key] = ent
            self._fp_block = None
        return out

    def _decision_labels(self, pplan) -> Optional[tuple]:
        """Per-decision TypeName#k attribution of the just-recorded
        schedule (record_plan): verify.node_labels over the parameterized
        plan, so the labels match the session-side plan's labels exactly
        (parameterization rewrites literals, never node structure/order).
        None when no decision carries row semantics."""
        nodes = self._last_record_nodes
        if not nodes or all(n is None for n in nodes):
            return None
        from ..verify import node_labels
        labs = node_labels(pplan)
        return tuple(labs.get(id(n)) if n is not None else None
                     for n in nodes)

    # -- cross-stream program sharing ----------------------------------------
    def _shared_fp(self, pplan) -> Optional[str]:
        """Registry key for a parameterized unit plan, or None when sharing
        is off (mesh runs lower against sharded args; jit disabled)."""
        if self._mesh is not None or not self._jit_plans:
            return None
        return shared_fingerprint(pplan, self._shard_min_rows,
                                  self._pallas_ops)

    def _adopt_shared(self, key, fp, pvalues: tuple, pdtypes: tuple) -> bool:
        """Install another stream's entry (schedule + program) for `key`."""
        if fp is None or fp == getattr(self, "_fp_block", None):
            return False
        with _SHARED_LOCK:
            sh = _SHARED_PROGRAMS.get(fp)
            if sh is None or sh.get("volatile") or sh.get("nojit") \
                    or sh.get("param_dtypes") != pdtypes:
                return False
            # system.programs accounting: cross-stream adoptions served
            sh["adoptions"] = sh.get("adoptions", 0) + 1
            ent = {"plan": sh["plan"], "decisions": list(sh["decisions"]),
                   "scan_keys": sh["scan_keys"], "params": pvalues,
                   "param_dtypes": pdtypes, "cq": sh.get("cq"),
                   "decision_nodes": sh.get("decision_nodes"),
                   "nojit": False, "fp": fp}
            scan_meta = dict(sh["scan_meta"])
        for k, v in scan_meta.items():
            self._scan_meta.setdefault(k, v)
        self._plans[key] = ent
        return True

    def _publish_recorded(self, ent) -> None:
        """Publish a freshly recorded schedule; cap-merge with any previous
        stream's so the eventual program serves every stream seen so far."""
        fp = ent.get("fp")
        if fp is None:
            return
        entry = {"plan": ent["plan"], "decisions": list(ent["decisions"]),
                 "scan_keys": ent["scan_keys"],
                 "param_dtypes": ent.get("param_dtypes", ()),
                 "decision_nodes": ent.get("decision_nodes"),
                 "scan_meta": {k: self._scan_meta[k]
                               for k in ent["scan_keys"]
                               if k in self._scan_meta},
                 "cq": None, "nojit": ent.get("nojit", False)}
        with _SHARED_LOCK:
            old = _SHARED_PROGRAMS.get(fp)
            if old is not None and old.get("volatile"):
                return   # proven stream-dependent: stays per-stream forever
            if old is not None \
                    and len(old["decisions"]) == len(entry["decisions"]):
                pairs = list(zip(old["decisions"], entry["decisions"]))
                if any(k1 != k2 for (k1, _), (k2, _) in pairs):
                    entry["volatile"] = True
                elif any(k == "exact" and v1 != v2
                         for (k, v1), (_, v2) in pairs):
                    # structure differs per stream: sharing would replay the
                    # wrong branch — revert to per-stream programs
                    entry["volatile"] = True
                else:
                    merged = [(k, max(v1, v2) if k == "cap" else v1)
                              for (k, v1), (_, v2) in pairs]
                    if merged == old["decisions"] and old.get("cq") is not None:
                        ent["decisions"] = list(merged)
                        ent["cq"] = old["cq"]
                        return          # old program already covers this
                    entry["decisions"] = merged
                    ent["decisions"] = list(merged)
            elif old is not None and len(old["decisions"]) != \
                    len(entry["decisions"]):
                entry["volatile"] = True
            _SHARED_PROGRAMS[fp] = entry

    def _publish_cq(self, ent) -> None:
        """Publish a compiled program for adoption by other streams."""
        fp = ent.get("fp")
        if fp is None or ent.get("cq") is None:
            return
        with _SHARED_LOCK:
            sh = _SHARED_PROGRAMS.get(fp)
            if sh is not None and not sh.get("volatile") \
                    and sh.get("cq") is None \
                    and sh["decisions"] == ent["decisions"]:
                sh["cq"] = ent["cq"]
                # system.programs accounting: compiled programs published
                # under this fingerprint (re-published after cap-merge or
                # quarantine re-record counts again)
                sh["compiles"] = sh.get("compiles", 0) + 1

    def evict_fp(self, fp: Optional[str]) -> int:
        """Drop every LOCAL plan entry (and batched wrapper) published
        under shared fingerprint ``fp`` — the quarantine follow-through:
        after ``strike_shared_program`` evicts the shared entry, the
        owning session must also forget its local copy so the next
        sighting re-records and re-publishes a fresh schedule/program
        instead of replaying the poisoned one. Returns entries dropped.
        Call on the device lane / under the session statement lock (plan
        caches are single-writer there)."""
        if fp is None:
            return 0
        gone = [k for k, ent in self._plans.items()
                if isinstance(ent, dict) and ent.get("fp") == fp]
        for k in gone:
            del self._plans[k]
        for k in [k for k in self._batched if k[0] == fp]:
            del self._batched[k]
        return len(gone)

    def run_param_batch(self, fp: Optional[str], rows: list,
                        ) -> Optional[list]:
        """Serve several COMPATIBLE parameterized queries — same shared
        fingerprint, different hoisted literal values (``rows``) — through
        one batched dispatch (BatchedQuery: one compiled program over a
        stacked parameter matrix). Returns one host-side DTable per row,
        or None when batching is unavailable (no published shared program
        yet, volatile/nojit entry, parameterless plan, mesh/jit off) — the
        caller then serves each query through the normal record/replay
        path. Raises ReplayMismatch when some row's data drifts past the
        recorded schedule; the caller falls back to serial for that batch
        (serial re-records and cap-merges the shared entry as usual)."""
        if fp is None or self._mesh is not None or not self._jit_plans \
                or not rows:
            return None
        with _SHARED_LOCK:
            sh = _SHARED_PROGRAMS.get(fp)
            if sh is None or sh.get("volatile") or sh.get("nojit") \
                    or sh.get("cq") is None or not sh.get("param_dtypes"):
                return None
            cq = sh["cq"]
            scan_meta = dict(sh["scan_meta"])
        if any(len(r) != len(cq.param_dtypes) for r in rows):
            return None
        for k, v in scan_meta.items():
            self._scan_meta.setdefault(k, v)
        cap = bucket(len(rows), minimum=1)
        bq = self._batched.get((fp, cap))
        if bq is None or bq.cq is not cq:
            # a re-published program (cap-merged schedule) obsoletes the
            # batched wrapper: rebuild against the current shared cq
            bq = BatchedQuery(cq, cap)
            self._batched[(fp, cap)] = bq
        self.fallback_nodes = []
        # batch-shape observability: the service's dispatch spans and
        # ExecStats extras report how the stacked matrix actually looked
        self.last_stats = {"batch_rows": len(rows), "batch_cap": cap}
        return bq.run(self._scans_for({"scan_keys": cq.scan_keys}), rows,
                      stats=self.last_stats)

    def _scan_specs(self, ent) -> Optional[tuple]:
        """jax.ShapeDtypeStruct tree mirroring _scans_for(ent) WITHOUT
        uploading anything: shapes come from whichever side already holds
        the table (exec cache, record cache, or segment-output cache).
        None when some scan's shape is not yet known (never recorded)."""
        specs = []
        for k in ent["scan_keys"]:
            src = self._scan_cache.get(k)
            if src is None:
                src = self._scan_cache_rec.get(k)
            if src is None:
                return None
            specs.append(jax.tree_util.tree_map(
                lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), src))
        return tuple(specs)

    def precompile_parallel(self, keys=None, max_workers: Optional[int] = None
                            ) -> dict:
        """Compile every recorded-but-uncompiled plan entry concurrently.

        The remote-compile tunnel serves parallel compile RPCs (measured
        ~3.4x with 4 threads), so a cold stream's programs compile in
        max(single) instead of sum(serial) — the reference pays ~ms of
        Spark planning per query (nds/nds_power.py:124-134) where this
        engine pays XLA compiles; this is the batching lever that makes a
        cold pass wall-clock comparable. Single-device only: mesh runs
        lower against sharded committed args, which ShapeDtypeStructs here
        do not carry.

        keys: restrict to these plan-entry keys (None = all cached).
        Returns {key: "compiled"|"nojit"|"skipped"} for observability.
        """
        import os as _os
        from concurrent.futures import ThreadPoolExecutor

        if self._mesh is not None:
            return {}
        todo = []
        for k, ent in list(self._plans.items()):
            if not isinstance(ent, dict) or "decisions" not in ent:
                continue
            if keys is not None and k not in keys:
                continue
            if ent.get("cq") is not None or ent.get("nojit"):
                continue
            specs = self._scan_specs(ent)
            if specs is None:
                continue
            cq = CompiledQuery(ent["plan"], ent["decisions"],
                               ent["scan_keys"], mesh=self._mesh,
                               param_dtypes=ent.get("param_dtypes", ()),
                               shard_min_rows=self._shard_min_rows,
                               label=ent.get("label", self._unit_label(k)),
                               pallas_ops=self._pallas_ops,
                               decision_nodes=ent.get("decision_nodes"))
            todo.append((k, ent, cq, specs))
        if not todo:
            return {}
        workers = max_workers or int(_os.environ.get(
            "NDS_TPU_COMPILE_WORKERS", "8"))
        results: dict = {}

        def one(item):
            k, ent, cq, specs = item
            try:
                cq.precompile(specs)
                return k, ent, cq, "compiled"
            except _NOJIT_ERRORS as e:
                ent["nojit"] = True
                ent["nojit_reason"] = f"{type(e).__name__}: {e}"
                return k, ent, None, "nojit"
            except Exception as e:          # infra hiccup: leave lazy path
                return k, ent, None, f"skipped: {type(e).__name__}"

        with ThreadPoolExecutor(min(workers, len(todo))) as pool:
            for k, ent, cq, status in pool.map(one, todo):
                if cq is not None:
                    ent["cq"] = cq
                    self._publish_cq(ent)
                results[k] = status
        return results

    def compiled_hlo(self, key) -> Optional[str]:
        """Optimized (post-GSPMD) HLO of the steady-state program for `key`
        (the root unit when segmented) — collective-volume inspection for
        the mesh test-suite (SURVEY.md §2 parallelism table: shuffle must
        repartition, not rebuild, sharded fact tables)."""
        for k in ((key, "root"), key):
            ent = self._plans.get(k)
            if ent is not None and ent.get("cq") is not None \
                    and ent["cq"]._fn is not None:
                cq = ent["cq"]
                lowered = cq._fn.lower(*cq._args(self._scans_for(ent),
                                                 ent.get("params", ())))
                return lowered.compile().as_text()
        return None

    def record_plan(self, plan: PlanNode, params: tuple = (),
                    shard_local: bool = False):
        """Eager run that records the capacity schedule; returns
        (result, decisions, scan_keys). scan_keys keep FIRST-TOUCH order
        (plan-traversal order, stream-invariant) — sorting would let
        stream-specific segment fingerprints permute the compiled
        program's argument order and break cross-stream HLO identity.

        shard_local=True records the schedule a sharded-morsel replay will
        consume (shard_exec.ShardedMorselQuery): the shard-local gates
        apply for this call only, so the same session executor records
        both single-chip and per-replica schedules."""
        from ...resilience import FAULTS
        FAULTS.fire("jax.execute")
        rec = _Recorder("record")
        self._rec = rec
        self._touched_scans = {}
        old_params = self._params
        old_shard_local = self._shard_local
        self._params = params
        self._shard_local = self._shard_local or shard_local
        try:
            out = self._eager(plan)
        finally:
            self._rec = None
            self._params = old_params
            self._shard_local = old_shard_local
        self._last_record_nodes = rec.nodes
        return out, rec.decisions, tuple(self._touched_scans)

    def record_plans(self, plans: list, params: tuple = (),
                     shard_local: bool = False):
        """Record several plans under ONE shared decision schedule (shared-
        scan fused morsel groups): the plans run in order with a single
        recorder, and the memo resets per plan exactly like the multi-plan
        replay in CompiledQuery._trace. Returns (outs, decisions,
        scan_keys) — scan_keys is the union in first-touch order across
        plans, so the fused program's argument order is deterministic.
        shard_local: see record_plan."""
        from ...resilience import FAULTS
        FAULTS.fire("jax.execute")
        rec = _Recorder("record")
        self._rec = rec
        self._touched_scans = {}
        old_params = self._params
        old_shard_local = self._shard_local
        self._params = params
        self._shard_local = self._shard_local or shard_local
        outs = []
        try:
            for p in plans:
                outs.append(self._eager(p))
        finally:
            self._rec = None
            self._params = old_params
            self._shard_local = old_shard_local
        return outs, rec.decisions, tuple(self._touched_scans)

    def _load_columns(self, table: str, columns) -> Table:
        from ..executor import load_columns
        return load_columns(self._load_table, table, columns)

    def _run_compiled(self, cq: CompiledQuery, ent,
                      keep_device: bool = False) -> DTable:
        """Run a compiled plan, retrying once on transient runtime errors
        (the remote compile/execute service can drop a connection)."""
        values = ent.get("params", ())
        try:
            return cq.run(self._scans_for(ent), values, stats=self.last_stats,
                          keep_device=keep_device)
        except jax.errors.JaxRuntimeError:
            return cq.run(self._scans_for(ent), values, stats=self.last_stats,
                          keep_device=keep_device)

    def _eager_ent(self, ent) -> DTable:
        """Eager-run a cached entry's (parameterized) plan with its values."""
        old = self._params
        self._params = ent.get("params", ())
        try:
            return self._eager(ent["plan"])
        finally:
            self._params = old

    def _eager(self, plan: PlanNode) -> DTable:
        self._memo = {}
        if self._eager_device is not None:
            with jax.default_device(self._eager_device):
                return self.execute(plan)
        return self.execute(plan)

    @staticmethod
    def _dtable_bytes(t) -> int:
        """Device bytes of a cached entry (DTable or PackedTable)."""
        return sum(int(leaf.size) * leaf.dtype.itemsize
                   for leaf in jax.tree_util.tree_leaves(t))

    def _account_resident(self, key: str, t: DTable,
                          pinned: Optional[set] = None) -> None:
        """Track an accelerator-resident entry; evict LRU past the budget.

        _resident strictly mirrors _scan_cache (stale keys pruned here), so
        budget math never counts phantom entries."""
        for k in [k for k in self._resident if k not in self._scan_cache]:
            del self._resident[k]
        self._resident.pop(key, None)
        self._resident[key] = self._dtable_bytes(t)
        if self._scan_budget <= 0:
            return
        pinned = pinned or set()
        pinned = pinned | getattr(self, "_pinned_segments", set())
        total = sum(self._resident.values())
        for old in list(self._resident):
            if total <= self._scan_budget:
                break
            if old == key or old in pinned:
                continue
            total -= self._resident.pop(old)
            # evicted entries are unpinned and not inputs of the in-flight
            # run: free their device buffers now (see free_dtable rationale)
            free_dtable(self._scan_cache.pop(old, None))
            if old in self._segment_lru:
                self._segment_lru.remove(old)

    def _scans_for(self, ent) -> dict:
        """Accelerator-resident scan tables for a compiled run (uploaded
        lazily on first use, then shared by every compiled query)."""
        out = {}
        for k in ent["scan_keys"]:
            if k not in self._scan_cache:
                if k.startswith("seg:"):
                    # segment output known only on the record side: move it
                    # to the execution device SHAPE-PRESERVED (capacities are
                    # part of the recorded schedule)
                    rec = self._scan_cache_rec.get(k)
                    if rec is None:
                        raise ReplayMismatch(f"segment output miss: {k}")
                    sharding = self._exec_sharding(rec.capacity) or \
                        jax.devices()[0]
                    self._scan_cache[k] = jax.tree_util.tree_map(
                        lambda x: jax.device_put(x, sharding), rec)
                    out[k] = self._scan_cache[k]
                    continue
                if k not in self._scan_meta:
                    raise ReplayMismatch(f"scan meta miss: {k}")
                table, columns, names = self._scan_meta[k]
                t = self._load_columns(table, columns)
                index = {n: i for i, n in enumerate(t.names)}
                cols = [t.columns[index[c]] for c in columns]
                host = Table(list(names), cols)
                from .device import bucket as _bucket
                self._scan_cache[k] = to_device(
                    host, device=self._exec_sharding(_bucket(host.num_rows)))
            out[k] = self._scan_cache[k]
        pinned = set(ent["scan_keys"])
        for k in ent["scan_keys"]:
            self._account_resident(k, out[k], pinned)
        return out

    def execute(self, node: PlanNode) -> DTable:
        # install this executor's kernel choice for every kernel dispatched
        # below (thread-local: concurrent compile-pool traces don't race)
        _pallas.set_active(self._pallas_ops)
        key = id(node)
        if key in self._memo:
            return self._memo[key]
        prev_node = self._cur_node
        self._cur_node = node
        try:
            result = self._run(node)
        except NotImplementedError as e:
            if self._replay:
                raise
            self.fallback_nodes.append(f"{type(node).__name__}: {e}")
            result = self._host_fallback(node)
        finally:
            self._cur_node = prev_node
        self._memo[key] = result
        return result

    def execute_to_host(self, node: PlanNode) -> Table:
        return to_host(self.execute(node))

    # -- capacity decisions (record / replay) --------------------------------
    def _decide_cap(self, scalar: jax.Array) -> int:
        """Host-sync a row count for capacity planning; schedule-aware."""
        rec = self._rec
        if rec is None:
            return int(scalar)
        if rec.mode == "record":
            v = int(scalar)
            rec.decisions.append(("cap", v))
            rec.nodes.append(self._cur_node)
            return v
        kind, v = rec.decisions[rec.idx]
        rec.idx += 1
        if kind != "cap":
            raise NotJittable("decision kind drift (cap)")
        rec.checks.append(jnp.asarray(scalar, _I32))
        return v

    def _decide_exact(self, scalar: jax.Array) -> int:
        """Host-sync a value that selects program structure (must replay ==)."""
        rec = self._rec
        if rec is None:
            return int(scalar)
        if rec.mode == "record":
            v = int(scalar)
            rec.decisions.append(("exact", v))
            rec.nodes.append(self._cur_node)
            return v
        kind, v = rec.decisions[rec.idx]
        rec.idx += 1
        if kind != "exact":
            raise NotJittable("decision kind drift (exact)")
        rec.checks.append(jnp.asarray(scalar, _I32))
        return v

    def _decide_exact_lazy(self, fn: Callable[[], jax.Array]) -> int:
        """Exact decision whose traced scalar is computed lazily: when the
        recorded value is falsy, replay skips the computation entirely and
        checks a constant (one-sided verification — taking the general path
        is always correct, so an ineligible-recorded fast path must not pay
        its eligibility probe in the compiled program, nor force a
        re-record when data drifts eligible-ward)."""
        rec = self._rec
        if rec is None:
            return int(fn())
        if rec.mode == "record":
            v = int(fn())
            rec.decisions.append(("exact", v))
            rec.nodes.append(None)   # eligibility probe: no row semantics
            return v
        kind, v = rec.decisions[rec.idx]
        rec.idx += 1
        if kind != "exact":
            raise NotJittable("decision kind drift (exact)")
        rec.checks.append(jnp.asarray(fn(), _I32) if v
                          else jnp.zeros((), _I32))
        return v

    def _decide_branch(self, value: bool) -> bool:
        """Record/replay a CAPACITY-DEPENDENT structural branch.

        Capacities drift between record and replay by design (streaming
        inflates every cap decision to the morsel bound, inflate_schedule),
        so a branch gated on `capacity >= X` must take the RECORDED side
        under replay — both sides are semantically correct, and replaying
        the record-time choice keeps the decision schedule aligned. The
        check is a constant equal to the recorded value (trivially passing:
        the branch is a performance choice, not a data property)."""
        rec = self._rec
        if rec is None:
            return value
        if rec.mode == "record":
            rec.decisions.append(("exact", int(value)))
            rec.nodes.append(None)   # performance branch: not a row count
            return value
        kind, v = rec.decisions[rec.idx]
        rec.idx += 1
        if kind != "exact":
            raise NotJittable("decision kind drift (branch)")
        rec.checks.append(jnp.full((), int(v), _I32))
        return bool(v)

    # -- helpers -------------------------------------------------------------
    def _eval(self, expr: BExpr, table: DTable) -> DCol:
        return jexprs.evaluate(expr, table, subquery_eval=self._ectx())

    def _ectx(self) -> "jexprs.EvalCtx":
        return jexprs.EvalCtx(subquery=self._scalar, param=self._param)

    def _param(self, expr, n: int) -> DCol:
        if self._params is None:
            raise NotJittable("parameter slot without bound values")
        v = self._params[expr.index]
        pd = phys_dtype(expr.dtype)
        data = jnp.broadcast_to(jnp.asarray(v, dtype=pd), (n,))
        return DCol(expr.dtype, data, jnp.ones(n, bool))

    def _dense_rank(self, key_data: list, key_valid: list,
                    alive) -> tuple:
        """dense_rank with record-time fast-tier selection (kernels.group_tier):
        the packed single-key sort replaces the multi-operand lax.sort when
        the key domain fits the integer dtype. Static gates keep record and
        replay on the same schedule; the mesh path stays on the generic
        kernel (pack ranges are data-dependent reductions that would force
        GSPMD gathers)."""
        n = int(alive.shape[0])
        if (self._mesh is None and not self._shard_local and key_data
                and all(jnp.issubdtype(d.dtype, jnp.integer)
                        for d in key_data)):
            # the size cutoff is capacity-derived: replay must follow the
            # record-time branch (streaming inflates capacities)
            if self._decide_branch(n >= (1 << 13)) and \
                    self._decide_exact_lazy(
                        lambda: kernels.group_tier(key_data, key_valid,
                                                   alive)):
                return kernels.dense_rank_packsort(key_data, key_valid, alive)
        return kernels.dense_rank(key_data, key_valid, alive)

    def _scalar(self, plan: PlanNode):
        """Uncorrelated scalar subquery -> (value, validity).

        Eager: host python value (validity None == derive from value).
        Replay: traced device scalars so the subquery stays inside the
        compiled program (strings can't: their dictionary would be
        data-dependent at trace time).
        """
        if self._replay:
            dt = self.execute(plan)
            col = decode_col(dt.cols[0])
            if col.dtype == "str" or col.parts is not None:
                raise NotJittable("string scalar subquery under trace")
            perm, cnt = kernels.compaction_perm(dt.alive)
            first = perm[0]
            value = col.data[first]
            valid = (cnt > 0) & col.valid[first]
            return value, valid
        t = to_host(self.execute(plan))
        if t.num_rows == 0:
            return None, None
        col = t.columns[0]
        if not bool(col.validity[0]):
            return None, None
        if col.dtype == "str":
            return col.decode()[0], None
        return np.asarray(col.data)[0].item(), None

    def _host_fallback(self, node: PlanNode) -> DTable:
        repl = {}
        for f in ("child", "left", "right"):
            sub = getattr(node, f, None)
            if isinstance(sub, PlanNode):
                t = to_host(self.execute(sub))
                repl[f] = MaterializedNode(
                    table=t, label=f"device:{f}",
                    out_names=list(sub.out_names), out_dtypes=list(sub.out_dtypes))
        host_node = dataclasses.replace(node, **repl) if repl else node
        if self._params is not None:
            # the numpy expression engine evaluates literals, not slots
            host_node = deparameterize_plan(host_node, list(self._params))
        # expression-embedded subplans can still reference segmented CTEs:
        # the host executor has no segment cache, so materialize them
        vmap = {}
        for n in iter_plan_nodes(host_node):
            if isinstance(n, VirtualScanNode):
                src = self._scan_cache_rec.get(n.key,
                                               self._scan_cache.get(n.key))
                if src is None:
                    raise RuntimeError(f"segment {n.key!r} not materialized")
                vmap[id(n)] = MaterializedNode(
                    table=to_host(src), label=n.key,
                    out_names=list(n.out_names),
                    out_dtypes=list(n.out_dtypes))
        if vmap:
            host_node = replace_plan_nodes(host_node, vmap)
        host = HostExecutor(self._load_table)
        return to_device(host.execute(host_node))

    def _maybe_compact(self, t: DTable) -> DTable:
        count_t = t.count()
        count = self._decide_cap(count_t)
        cap = bucket(count)
        if self._mesh is not None or self._shard_local:
            # compaction is a global permutation (sort/cumsum/gather): under
            # SPMD it would force GSPMD to all-gather the sharded buffer.
            # Alive-masked ops stay shard-local, so larger masked capacities
            # beat rebuilding the table across the ICI. (The cap decision
            # above still records, keeping schedules mode-agnostic.)
            # Shard-local replays skip it for the same schedule shape: the
            # record pass sees one replica-sized slice, and a capacity-
            # relative branch would drift per shard.
            return t
        if t.capacity <= 2 * cap:
            return t
        perm, _ = kernels.compaction_perm(t.alive)
        perm = perm[:cap]
        cols = _gather_cols(t.cols, perm)
        alive = jnp.arange(cap, dtype=_I32) < count_t
        return DTable(t.names, cols, alive)

    # -- node dispatch -------------------------------------------------------
    def _run(self, node: PlanNode) -> DTable:
        if isinstance(node, MaterializedNode):
            return to_device(node.table)
        if isinstance(node, VirtualScanNode):
            return self._run_virtual(node)
        if isinstance(node, ScanNode):
            return self._run_scan(node)
        if isinstance(node, FilterNode):
            child = self.execute(node.child)
            mask = self._eval(node.predicate, child)
            alive = kernels.filter_alive(child.alive, mask.data, mask.valid)
            return self._maybe_compact(DTable(list(node.out_names),
                                              child.cols, alive))
        if isinstance(node, ProjectNode):
            child = self.execute(node.child)
            cols = [self._eval(e, child) for e in node.exprs]
            return DTable(list(node.out_names), cols, child.alive)
        if isinstance(node, JoinNode):
            return self._run_join(node)
        if isinstance(node, AggregateNode):
            return self._run_aggregate(node)
        if isinstance(node, WindowNode):
            return self._run_window(node)
        if isinstance(node, SortNode):
            return self._run_sort(node)
        if isinstance(node, LimitNode):
            child = self.execute(node.child)
            alive = kernels.limit_alive(child.alive, node.n)
            return self._maybe_compact(DTable(list(node.out_names),
                                              child.cols, alive))
        if isinstance(node, DistinctNode):
            child = self.execute(node.child)
            alive = self._distinct_alive(child, list(range(len(child.cols))))
            return self._maybe_compact(DTable(list(node.out_names),
                                              child.cols, alive))
        if isinstance(node, SetOpNode):
            return self._run_setop(node)
        raise NotImplementedError(type(node).__name__)

    def _run_setop(self, node: SetOpNode) -> DTable:
        left = self.execute(node.left)
        right = self.execute(node.right)
        names = list(node.out_names)
        both = _concat_dtables([left, right], names)
        if node.op == "union":
            if node.all:
                return both
            alive = self._distinct_alive(both, list(range(len(both.cols))))
            return self._maybe_compact(DTable(names, both.cols, alive))
        # intersect / except: distinct-row semantics (mirrors host ops.set_op)
        lcap = left.capacity
        n = both.capacity
        iota = jnp.arange(n, dtype=_I32)
        is_left = iota < lcap
        keys = [rank_key(c) for c in both.cols]
        valids = [c.valid for c in both.cols]
        gid, _ = self._dense_rank(keys, valids, both.alive)
        safe_gid = jnp.where(both.alive, gid, n)
        in_left = jnp.zeros(n + 1, bool).at[
            jnp.where(is_left, safe_gid, n)].set(True)
        in_right = jnp.zeros(n + 1, bool).at[
            jnp.where(~is_left, safe_gid, n)].set(True)
        keep = (in_left & in_right) if node.op == "intersect" \
            else (in_left & ~in_right)
        first_left = jnp.full(n + 1, n, dtype=_I32).at[
            jnp.where(both.alive & is_left, gid, n)].min(iota)
        alive = both.alive & is_left & keep[jnp.clip(gid, 0, n)] & \
            (first_left[jnp.clip(gid, 0, n)] == iota)
        return self._maybe_compact(DTable(names, both.cols, alive))

    def _run_virtual(self, node: VirtualScanNode) -> DTable:
        """A segmented-CTE output: resolved against the segment cache (the
        orchestrator in run_query materializes segments before consumers)."""
        self._touched_scans.setdefault(node.key)
        cache = self._scan_cache if self._replay else self._scan_cache_rec
        t = cache.get(node.key)
        if t is None:
            if self._replay:
                raise NotJittable(f"segment {node.key!r} missing under trace")
            other = self._scan_cache.get(node.key)
            if other is None:
                raise RuntimeError(      # orchestration bug, never fallback
                    f"segment {node.key!r} not materialized")
            # bridge device output to the record-side device SHAPE-PRESERVED
            dev = self._eager_device or jax.devices()[0]
            cache[node.key] = jax.tree_util.tree_map(
                lambda x: jax.device_put(x, dev), other)
            t = cache[node.key]
        return DTable(list(node.out_names), t.cols, t.alive)

    def _run_scan(self, node: ScanNode) -> DTable:
        cache_key = node.table + "//" + ",".join(node.columns)
        cache = self._scan_cache if self._replay else self._scan_cache_rec
        if cache_key not in cache:
            if self._replay:
                raise NotJittable(f"scan {cache_key!r} missing under trace")
            t = self._load_columns(node.table, node.columns)
            index = {n: i for i, n in enumerate(t.names)}
            cols = [t.columns[index[c]] for c in node.columns]
            cache[cache_key] = to_device(Table(list(node.out_names), cols),
                                         device=self._eager_device)
        self._touched_scans.setdefault(cache_key)
        self._scan_meta[cache_key] = (node.table, list(node.columns),
                                      list(node.out_names))
        cached = cache[cache_key]
        if isinstance(cached, PackedTable):
            # packed morsel upload: column slicing/bitcasts fuse into the
            # compiled program (see PackedTable)
            cached = unpack_table(cached)
        return DTable(list(node.out_names), cached.cols, cached.alive)

    # -- sort / distinct -----------------------------------------------------
    def _run_sort(self, node: SortNode) -> DTable:
        child = self.execute(node.child)
        key_cols = [self._eval(k.expr, child) for k in node.keys]
        key_data = [rank_key(c) for c in key_cols]
        key_valid = [c.valid for c in key_cols]
        perm = kernels.sort_perm(key_data, key_valid,
                                 kernels.sort_specs(node.keys), child.alive)
        cols = _gather_cols(child.cols, perm)
        return DTable(list(node.out_names), cols, child.alive[perm])

    def _distinct_alive(self, t: DTable, col_idx: list[int]) -> jax.Array:
        keys = [rank_key(t.cols[i]) for i in col_idx]
        valids = [t.cols[i].valid for i in col_idx]
        gid, _ = self._dense_rank(keys, valids, t.alive)
        n = t.capacity
        iota = jnp.arange(n, dtype=_I32)
        first = jnp.full(n + 1, n, dtype=_I32).at[
            jnp.where(t.alive, gid, n)].min(iota)
        return t.alive & (first[jnp.clip(gid, 0, n)] == iota)

    # -- window functions ----------------------------------------------------
    def _run_window(self, node: WindowNode) -> DTable:
        child = self.execute(node.child)
        out_cols = list(child.cols)
        for wf in node.funcs:
            out_cols.append(self._window_one(wf, child))
        return DTable(list(node.out_names), out_cols, child.alive)

    def _window_one(self, wf: WindowFunc, child: DTable) -> DCol:
        n = child.capacity
        pcols = [self._eval(e, child) for e in wf.partition_by]
        gid, _ = self._dense_rank([rank_key(c) for c in pcols],
                                  [c.valid for c in pcols], child.alive)
        arg_col = None if wf.arg is None else widen_col(
            self._eval(wf.arg, child))
        if arg_col is not None and arg_col.dtype == "str":
            raise NotImplementedError("window function over strings (device)")
        func = wf.func
        if arg_col is None:
            if func in ("count", "count_star"):
                func = "count_star"
            arg = None
        else:
            arg = (arg_col.canon().data, arg_col.valid)

        if not wf.order_by:
            if func in ("rank", "dense_rank", "row_number"):
                raise NotImplementedError(f"{func} requires ORDER BY")
            vals, valid = kernels.agg_apply(gid, child.alive, func, arg, n)
            safe = jnp.clip(gid, 0, n - 1)
            data, dvalid = vals[safe], valid[safe]
        else:
            ocols = [self._eval(k.expr, child) for k in wf.order_by]
            okd = [rank_key(c) for c in ocols]
            okv = [c.valid for c in ocols]
            specs = ((True, None),) + kernels.sort_specs(wf.order_by)
            perm = kernels.sort_perm([gid] + okd,
                                     [jnp.ones(n, bool)] + okv,
                                     specs, child.alive)
            sarg = None if arg is None else (arg[0][perm], arg[1][perm])
            vals_s, valid_s = kernels.window_ordered_core(
                gid[perm], [d[perm] for d in okd], [v[perm] for v in okv],
                sarg, func)
            data, dvalid = kernels.unscatter(perm, (vals_s, valid_s))
        if arg_col is not None and is_dec(arg_col.dtype) and wf.func == "avg":
            data = data / 10.0 ** dec_scale(arg_col.dtype)  # descale
        pd = phys_dtype(wf.dtype)
        return DCol(wf.dtype, data.astype(pd), dvalid & child.alive)

    # -- aggregate -----------------------------------------------------------
    def _run_aggregate(self, node: AggregateNode) -> DTable:
        child = self.execute(node.child)
        if node.rollup_levels is not None:
            grouping_sets = [list(range(k)) for k in node.rollup_levels]
        elif node.rollup:
            grouping_sets = [list(range(k))
                             for k in range(len(node.group_exprs), -1, -1)]
        else:
            grouping_sets = [list(range(len(node.group_exprs)))]
        if self._sorted_agg_eligible(node, child, grouping_sets):
            return self._aggregate_sorted(node, child, grouping_sets)
        pieces = [self._aggregate_one_sharded(node, child, keep)
                  if self._mesh_agg_eligible(node, keep)
                  else self._aggregate_one(node, child, keep)
                  for keep in grouping_sets]
        if len(pieces) == 1:
            return pieces[0]
        return _concat_dtables(pieces, list(node.out_names))

    def _sorted_agg_eligible(self, node: AggregateNode, child: DTable,
                             grouping_sets: list) -> bool:
        """Static gate for the sorted aggregation path: ONE key sort shared
        by every rollup prefix level, within-group scans instead of the
        serialized segment scatters, S-sized gathers for output assembly.
        Single-device only (the mesh path has its own shard-local plan, and
        sharded-morsel replays must not re-probe per-shard key ranges)."""
        if self._mesh is not None or self._shard_local:
            return False
        if not node.group_exprs:
            return False          # global aggregate: masked reduces suffice
        for s in node.aggs:
            if s.distinct or s.func not in (
                    "count_star", "count", "sum", "min", "max", "avg",
                    "stddev_samp"):
                return False
            if s.arg is not None and s.arg.dtype == "str":
                return False
        # capacity cutoff LAST (after the static gates) so the recorded
        # branch sits at a deterministic schedule position; replay follows
        # the record-time choice (streaming inflates capacities)
        return self._decide_branch(child.capacity >= (1 << 13))

    def _aggregate_sorted(self, node: AggregateNode, child: DTable,
                          grouping_sets: list) -> DTable:
        n = child.capacity
        alive = child.alive
        group_cols = [self._eval(e, child) for e in node.group_exprs]
        keys = [rank_key(c) for c in group_cols]
        kvalids = [c.valid for c in group_cols]
        # aggregate arguments widen off narrow lanes: the within-group scan
        # accumulates in the payload dtype, and an i32 sum over a morsel of
        # narrow-lane values would overflow (group KEYS stay narrow)
        arg_cols = [None if s.arg is None else widen_col(
            self._eval(s.arg, child)) for s in node.aggs]
        x64 = jax.config.read("jax_enable_x64")
        fd = jnp.float64 if x64 else jnp.float32

        # the pack probe only handles integer rank keys (float group keys —
        # legal SQL — have no iinfo range); static gate so record and replay
        # stay on one schedule
        int_keys = all(jnp.issubdtype(k.dtype, jnp.integer) for k in keys)
        tier = self._decide_exact_lazy(
            lambda: kernels.group_tier(keys, kvalids, alive)) if int_keys \
            else self._decide_exact(jnp.zeros((), _I32))

        # ---- ONE sort: keys (packed when possible) + agg args as payload,
        # deduplicated by expression so SUM(x)/AVG(x) carry x once
        payloads: list = []
        pay_idx: list = []        # per spec: index into payloads or None
        seen_args: dict[str, int] = {}
        for s, ac in zip(node.aggs, arg_cols):
            if ac is None:
                pay_idx.append(None)
                continue
            akey = repr(s.arg)
            if akey in seen_args:
                pay_idx.append(seen_args[akey])
                continue
            seen_args[akey] = len(payloads)
            pay_idx.append(len(payloads))
            payloads.append(ac.canon().data)
            payloads.append(ac.valid)
        iota = jnp.arange(n, dtype=_I32)
        if tier:
            norms, ranges, _ = kernels._key_ranges(keys, kvalids, alive)
            pd = kernels._pack_dtype()
            pack = jnp.zeros(n, pd)
            for norm, r in zip(norms, ranges):
                pack = pack * r + norm.astype(pd)
            key_ops = [jnp.where(alive, pack, jnp.iinfo(pd).max)]
            nkey_ops = 1
        else:
            ranges = None
            key_ops = [(~alive).astype(_I32)]
            for d, v in zip(keys, kvalids):
                key_ops.append((~v).astype(_I32))
                key_ops.append(jnp.where(v & alive, d,
                                         jnp.zeros((), d.dtype)))
            nkey_ops = len(key_ops)
        if nkey_ops == 1 and _pallas.op_active("sort"):
            # tiled segmented sort: the packed key rides the VMEM-blocked
            # bitonic network with ONLY the row index as payload, and the
            # agg payloads follow via one batched gather — instead of every
            # payload riding every merge pass of the multi-operand lax.sort
            skey, perm = kernels._sort1(key_ops[0], iota)
            sorted_keys = (skey,)
            sorted_pays = tuple(kernels.gather_many(list(payloads), perm))
        else:
            out = lax.sort(tuple(key_ops) + tuple(payloads) + (iota,),
                           num_keys=nkey_ops, is_stable=True)
            sorted_keys = out[:nkey_ops]
            sorted_pays = out[nkey_ops:-1]
            perm = out[-1]
        iota_s = iota
        alive_sorted = iota_s < jnp.sum(alive.astype(_I32))

        def level_new_group(k: int) -> jax.Array:
            first = alive_sorted & (iota_s == 0)
            if k == 0:
                return first
            if ranges is not None:
                stride = jnp.ones((), sorted_keys[0].dtype)
                for r in ranges[k:]:
                    stride = stride * r
                ck = sorted_keys[0] // stride
                diff = jnp.concatenate([jnp.ones(1, bool),
                                        ck[1:] != ck[:-1]])
            else:
                diff = jnp.zeros(n, bool)
                for i in range(k):
                    for op in (sorted_keys[1 + 2 * i],
                               sorted_keys[2 + 2 * i]):
                        diff = diff | jnp.concatenate(
                            [jnp.ones(1, bool), op[1:] != op[:-1]])
            return (alive_sorted & diff) | first

        pieces: list[DTable] = []
        for keep in grouping_sets:
            k = len(keep)
            if k == 0:
                # grand total: one group — the masked-reduce path is exact
                # and cheap, and handles the empty-input one-row semantics
                pieces.append(self._aggregate_one(node, child, keep))
                continue
            new_group = level_new_group(k)
            gid_sorted = jnp.cumsum(new_group.astype(_I32)) - 1
            num_groups_t = jnp.max(jnp.where(alive_sorted, gid_sorted, -1)) + 1
            cap_out = bucket(max(self._decide_cap(num_groups_t), 1))
            is_end = kernels.group_ends(new_group, alive_sorted)
            end_perm, _ = kernels.compaction_perm(is_end)
            sel = end_perm[:cap_out]
            orig = perm[sel]
            alive_out = jnp.arange(cap_out, dtype=_I32) < num_groups_t

            out_cols: list[DCol] = []
            for i, gc in enumerate(group_cols):
                if i < k:
                    cd = gc.canon().data
                    # sort/scan ran on codes; decode the group-sized output
                    out_cols.append(decode_col(DCol(
                        gc.dtype, cd[orig], gc.valid[orig] & alive_out,
                        gc.dictionary, codebook=gc.codebook)))
                else:
                    out_cols.append(DCol(
                        gc.dtype, jnp.zeros(cap_out, phys_dtype(gc.dtype)),
                        jnp.zeros(cap_out, bool), gc.dictionary))

            ones_i = jnp.where(alive_sorted, 1, 0).astype(_I32)
            for spec, ac, pi in zip(node.aggs, arg_cols, pay_idx):
                if spec.func == "count_star":
                    cnt_s = kernels.sorted_agg_scan(ones_i, new_group,
                                                    jnp.add)
                    int_out = jnp.int64 if x64 else _I32
                    vals = cnt_s[sel].astype(int_out)
                    out_cols.append(DCol(spec.dtype,
                                         vals.astype(phys_dtype(spec.dtype)),
                                         jnp.ones(cap_out, bool)))
                    continue
                data_s = sorted_pays[pi]
                valid_s = sorted_pays[pi + 1] & alive_sorted
                contrib_i = valid_s.astype(
                    jnp.int64 if x64 else _I32)
                cnt_s = kernels.sorted_agg_scan(contrib_i, new_group, jnp.add)
                cnt_sel = cnt_s[sel]
                func = spec.func
                if func == "count":
                    out_cols.append(DCol(
                        spec.dtype, cnt_sel.astype(phys_dtype(spec.dtype)),
                        jnp.ones(cap_out, bool)))
                    continue
                int_in = jnp.issubdtype(data_s.dtype, jnp.integer)
                if func in ("sum", "avg"):
                    acc = data_s.dtype if (int_in and (func == "sum" or x64)) \
                        else fd
                    w = jnp.where(valid_s, data_s.astype(acc),
                                  jnp.zeros((), acc))
                    sum_sel = kernels.sorted_agg_scan(w, new_group,
                                                      jnp.add)[sel]
                    if func == "sum":
                        vals = sum_sel
                        dvalid = cnt_sel > 0
                    else:
                        vals = (sum_sel.astype(fd) /
                                jnp.maximum(cnt_sel, 1).astype(fd))
                        dvalid = cnt_sel > 0
                elif func in ("min", "max"):
                    ext = kernels._extreme(data_s.dtype, func)
                    w = jnp.where(valid_s, data_s, ext)
                    op = jnp.minimum if func == "min" else jnp.maximum
                    vals = kernels.sorted_agg_scan(w, new_group, op)[sel]
                    dvalid = cnt_sel > 0
                    vals = jnp.where(dvalid, vals,
                                     jnp.zeros((), data_s.dtype))
                else:           # stddev_samp
                    zf = jnp.where(valid_s, data_s, 0).astype(fd)
                    s1 = (kernels.sorted_agg_scan(
                        jnp.where(valid_s, data_s,
                                  jnp.zeros((), data_s.dtype)), new_group,
                        jnp.add)[sel].astype(fd) if int_in and x64 else
                        kernels.sorted_agg_scan(zf, new_group, jnp.add)[sel])
                    s2 = kernels.sorted_agg_scan(zf * zf, new_group,
                                                 jnp.add)[sel]
                    nf = cnt_sel.astype(fd)
                    var = (s2 - s1 * s1 / jnp.maximum(nf, 1.0)) / \
                        jnp.maximum(nf - 1.0, 1.0)
                    vals = jnp.sqrt(jnp.maximum(var, 0.0))
                    dvalid = cnt_sel > 1
                if ac is not None and is_dec(ac.dtype) and \
                        spec.func in ("avg", "stddev_samp"):
                    vals = vals / 10.0 ** dec_scale(ac.dtype)
                out_cols.append(DCol(spec.dtype,
                                     vals.astype(phys_dtype(spec.dtype)),
                                     dvalid & alive_out))
            if node.rollup:
                gid_val = sum(1 << (len(node.group_exprs) - 1 - i)
                              for i in range(len(node.group_exprs))
                              if i >= k)
                out_cols.append(DCol("int",
                                     jnp.full(cap_out, gid_val,
                                              phys_dtype("int")),
                                     jnp.ones(cap_out, bool)))
            pieces.append(DTable(list(node.out_names), out_cols, alive_out))
        if len(pieces) == 1:
            return pieces[0]
        return _concat_dtables(pieces, list(node.out_names))

    def _mesh_agg_eligible(self, node: AggregateNode, keep: list[int]) -> bool:
        """Shard-local grouped aggregation (partial agg + bounded-partials
        all_gather + replicated merge — the Spark partial/final aggregate
        plan, SURVEY.md §2 parallelism table). Static eligibility so record
        and replay take the same branch."""
        if self._mesh is None or not keep:
            return False
        for s in node.aggs:
            if s.distinct or s.func not in ("sum", "count", "count_star",
                                            "min", "max", "avg"):
                return False
        return True

    def _aggregate_one_sharded(self, node: AggregateNode, child: DTable,
                               keep: list[int]) -> DTable:
        """GROUP BY over row-sharded data WITHOUT gathering the fact table:
        each shard dense-ranks its local rows and aggregates into n_partial
        slots; only the bounded partials ride the ICI (all_gather), and the
        replicated merge re-ranks 8*n_partial candidate groups. GSPMD's
        fallback for the same plan all-gathers the whole child (measured:
        q3-class group-by gathered cap-sized s32 buffers)."""
        from jax.sharding import PartitionSpec

        from ...parallel.dist_ops import shard_map
        from .device import string_rank_maps

        mesh = self._mesh
        axis = mesh.axis_names[0]
        Pax, Prep = PartitionSpec(axis), PartitionSpec()
        group_cols = [self._eval(e, child) for e in node.group_exprs]
        active = [group_cols[i] for i in keep]
        rank_keys = tuple(rank_key(c) for c in active)
        kvalids = tuple(c.valid for c in active)
        codes = tuple(c.canon().data for c in active)
        alive = child.alive

        # per-spec local inputs + merge recipes (streaming.py-style
        # decomposition into mergeable pieces)
        spec_args: list = []
        recipes: list[tuple] = []     # (kind, extra) per spec
        for spec in node.aggs:
            if spec.arg is None:
                spec_args.append(None)
                recipes.append(("count_star", None))
                continue
            ac = widen_col(self._eval(spec.arg, child))
            post = None
            data, valid = ac.canon().data, ac.valid
            if ac.dtype == "str":
                if spec.func == "count":
                    recipes.append(("count", None))
                elif spec.func in ("min", "max"):
                    ranks, rank_to_code = string_rank_maps(ac.dictionary)
                    data = jexprs._lut_gather(ac.data, ranks)
                    post = ("str", rank_to_code, ac.dictionary)
                    recipes.append((spec.func, post))
                else:
                    raise NotImplementedError(
                        f"device {spec.func} over strings")
            elif spec.func == "avg":
                if is_dec(ac.dtype):
                    post = ("dec_avg", dec_scale(ac.dtype))
                recipes.append(("avg", post))
            else:
                if spec.func == "sum" and (ac.dtype == "int"
                                           or is_dec(ac.dtype)):
                    data = data.astype(phys_dtype("int"))
                recipes.append((spec.func, None))
            spec_args.append((data, valid))
        spec_args = tuple(spec_args)

        nsh = mesh.devices.size

        def probe(rk, kv, al):
            _, ng = kernels.dense_rank(list(rk), list(kv), al)
            return ng.reshape(1)

        ng_sh = shard_map(probe, mesh=mesh, in_specs=(Pax, Pax, Pax),
                          out_specs=Pax, check_vma=False)(
            rank_keys, kvalids, alive)
        n_partial = bucket(max(self._decide_cap(jnp.max(ng_sh)), 1))
        cap_out = n_partial * nsh

        def seg_sum(vals, mask, m_gid, occ):
            sg = jnp.where(occ & mask, m_gid, cap_out)
            return jax.ops.segment_sum(jnp.where(occ & mask, vals, 0), sg,
                                       num_segments=cap_out + 1)[:cap_out]

        def seg_any(mask, m_gid, occ):
            sg = jnp.where(occ, m_gid, cap_out)
            return jax.ops.segment_max(
                (occ & mask).astype(_I32), sg,
                num_segments=cap_out + 1)[:cap_out] > 0

        def local(rk, kv, cd, al, sa):
            gid, _ = kernels.dense_rank(list(rk), list(kv), al)
            occ = jnp.zeros(n_partial + 1, bool).at[
                jnp.where(al & (gid < n_partial), gid, n_partial)
            ].set(True)[:n_partial]
            rreps, creps, cvals = [], [], []
            for r, v, c in zip(rk, kv, cd):
                rr, _ = kernels.group_representatives(gid, al, r, v,
                                                      n_partial)
                cc, vv = kernels.group_representatives(gid, al, c, v,
                                                       n_partial)
                rreps.append(rr)
                creps.append(cc)
                cvals.append(vv)
            parts = []          # flat pieces per recipe, (vals, valid)
            for (kind, _x), a in zip(recipes, sa):
                if kind == "count_star":
                    v, _ = kernels.agg_apply(gid, al, "count_star", None,
                                             n_partial)
                    parts.append((v, jnp.ones(n_partial, bool)))
                elif kind == "count":
                    v, _ = kernels.agg_apply(gid, al, "count", a, n_partial)
                    parts.append((v, jnp.ones(n_partial, bool)))
                elif kind == "avg":
                    s, sv = kernels.agg_apply(
                        gid, al, "sum",
                        (a[0].astype(phys_dtype("int"))
                         if jnp.issubdtype(a[0].dtype, jnp.integer)
                         else a[0], a[1]), n_partial)
                    c, _ = kernels.agg_apply(gid, al, "count", a, n_partial)
                    parts.append((s, sv))
                    parts.append((c, jnp.ones(n_partial, bool)))
                else:           # sum / min / max
                    v, vv = kernels.agg_apply(gid, al, kind, a, n_partial)
                    parts.append((v, vv))
            ga = lambda x: jax.lax.all_gather(x, axis, tiled=True)  # noqa: E731
            g_occ = ga(occ)
            g_rr = [ga(x) for x in rreps]
            g_cc = [ga(x) for x in creps]
            g_cv = [ga(x) for x in cvals]
            g_parts = [(ga(v), ga(m)) for v, m in parts]
            m_gid, _ = kernels.dense_rank(g_rr, g_cv, g_occ)
            out_codes, out_cvals = [], []
            for cc, vv in zip(g_cc, g_cv):
                oc, ov = kernels.group_representatives(m_gid, g_occ, cc, vv,
                                                       cap_out)
                out_codes.append(oc)
                out_cvals.append(ov)
            out_occ = jnp.zeros(cap_out + 1, bool).at[
                jnp.where(g_occ, m_gid, cap_out)].set(True)[:cap_out]
            merged = []
            pi = 0
            for kind, _x in recipes:
                if kind in ("count_star", "count"):
                    gv, gm = g_parts[pi]
                    pi += 1
                    merged.append((seg_sum(gv, gm, m_gid, g_occ),
                                   jnp.ones(cap_out, bool)))
                elif kind == "sum":
                    gv, gm = g_parts[pi]
                    pi += 1
                    merged.append((seg_sum(gv, gm, m_gid, g_occ),
                                   seg_any(gm, m_gid, g_occ)))
                elif kind in ("min", "max"):
                    gv, gm = g_parts[pi]
                    pi += 1
                    ext = kernels._extreme(gv.dtype, kind)
                    sg = jnp.where(g_occ & gm, m_gid, cap_out)
                    seg = jax.ops.segment_min if kind == "min" \
                        else jax.ops.segment_max
                    vals = seg(jnp.where(g_occ & gm, gv, ext), sg,
                               num_segments=cap_out + 1)[:cap_out]
                    valid = seg_any(gm, m_gid, g_occ)
                    merged.append((jnp.where(valid, vals,
                                             jnp.zeros((), gv.dtype)), valid))
                else:           # avg: sum piece + count piece
                    gs, gsm = g_parts[pi]
                    gc, gcm = g_parts[pi + 1]
                    pi += 2
                    sm = seg_sum(gs, gsm, m_gid, g_occ)
                    cm = seg_sum(gc, gcm, m_gid, g_occ)
                    fdt = jnp.float64 if jax.config.read("jax_enable_x64") \
                        else jnp.float32
                    vals = sm.astype(fdt) / jnp.maximum(cm, 1).astype(fdt)
                    merged.append((vals, cm > 0))
            return (tuple(out_codes), tuple(out_cvals), out_occ,
                    tuple(x for pair in merged for x in pair))

        out_codes, out_cvals, out_occ, flat = shard_map(
            local, mesh=mesh, in_specs=(Pax, Pax, Pax, Pax, Pax),
            out_specs=(Prep, Prep, Prep, Prep), check_vma=False)(
            rank_keys, kvalids, codes, alive, spec_args)
        merged = [(flat[i], flat[i + 1]) for i in range(0, len(flat), 2)]

        out_cols: list[DCol] = []
        keep_set = set(keep)
        ai = 0
        for i, gc in enumerate(group_cols):
            if i in keep_set:
                out_cols.append(decode_col(DCol(
                    gc.dtype, out_codes[ai], out_cvals[ai], gc.dictionary,
                    codebook=gc.codebook)))
                ai += 1
            else:
                out_cols.append(DCol(gc.dtype,
                                     jnp.zeros(cap_out, phys_dtype(gc.dtype)),
                                     jnp.zeros(cap_out, bool), gc.dictionary))
        for spec, (kind, post), (vals, valid) in zip(node.aggs, recipes,
                                                     merged):
            if isinstance(post, tuple) and post[0] == "str":
                codes_out = jexprs._lut_gather(vals.astype(_I32), post[1])
                out_cols.append(DCol("str", codes_out, valid, post[2]))
                continue
            if isinstance(post, tuple) and post[0] == "dec_avg":
                vals = vals / 10.0 ** post[1]
            out_cols.append(DCol(spec.dtype,
                                 vals.astype(phys_dtype(spec.dtype)), valid))
        if node.rollup:
            gid_val = sum(1 << (len(node.group_exprs) - 1 - i)
                          for i in range(len(node.group_exprs))
                          if i not in keep_set)
            out_cols.append(DCol("int",
                                 jnp.full(cap_out, gid_val,
                                          phys_dtype("int")),
                                 jnp.ones(cap_out, bool)))
        return DTable(list(node.out_names), out_cols, out_occ)

    def _aggregate_one(self, node: AggregateNode, child: DTable,
                       keep: list[int]) -> DTable:
        group_cols = [self._eval(e, child) for e in node.group_exprs]
        active = [group_cols[i] for i in keep]
        gid, num_groups_t = self._dense_rank(
            [rank_key(c) for c in active], [c.valid for c in active],
            child.alive)
        num_groups = self._decide_cap(num_groups_t)
        if not active:
            # a global aggregate (incl. a rollup's grand-total grouping set)
            # over empty input still yields one row
            num_groups = max(num_groups, 1)
            num_groups_t = jnp.maximum(num_groups_t, 1)
        alive_for_agg = child.alive
        cap_out = bucket(max(num_groups, 1))

        out_cols: list[DCol] = []
        keep_set = set(keep)
        for i, gc in enumerate(group_cols):
            if i in keep_set:
                vals, valid = kernels.group_representatives(
                    gid, alive_for_agg, gc.canon().data, gc.valid, cap_out)
                # grouping ran on codes (rank_key); the group-output
                # representative is the decode site — group-sized, not
                # row-sized
                out_cols.append(decode_col(DCol(gc.dtype, vals, valid,
                                                gc.dictionary,
                                                codebook=gc.codebook)))
            else:  # rolled-up column: NULL
                out_cols.append(DCol(gc.dtype,
                                     jnp.zeros(cap_out, phys_dtype(gc.dtype)),
                                     jnp.zeros(cap_out, bool), gc.dictionary))

        agg_results = self._compute_aggs(node.aggs, child, gid,
                                         alive_for_agg, cap_out)
        out_cols.extend(agg_results)
        if node.rollup:
            gid_val = sum(1 << (len(node.group_exprs) - 1 - i)
                          for i in range(len(node.group_exprs))
                          if i not in keep_set)
            out_cols.append(DCol("int",
                                 jnp.full(cap_out, gid_val, phys_dtype("int")),
                                 jnp.ones(cap_out, bool)))
        alive = jnp.arange(cap_out, dtype=_I32) < num_groups_t
        names = list(node.out_names)
        return DTable(names, out_cols, alive)

    def _compute_aggs(self, specs: list[AggSpec], child: DTable,
                      gid: jax.Array, alive: jax.Array,
                      cap_out: int) -> list[DCol]:
        out: list[DCol] = []
        for spec in specs:
            arg_col = None if spec.arg is None else widen_col(
                self._eval(spec.arg, child))
            use_alive = alive
            if spec.distinct and arg_col is not None:
                use_alive = kernels.distinct_within_group(
                    gid, alive, rank_key(arg_col), arg_col.valid)
            if arg_col is not None and arg_col.dtype == "str":
                out.append(self._agg_string(spec, arg_col, gid, use_alive,
                                            cap_out))
                continue
            arg = None
            if arg_col is not None:
                data = arg_col.canon().data
                if spec.func == "sum" and (arg_col.dtype == "int"
                                           or is_dec(arg_col.dtype)):
                    data = data.astype(phys_dtype("int"))
                arg = (data, arg_col.valid)
            vals, valid = kernels.agg_apply(gid, use_alive, spec.func, arg,
                                            cap_out)
            if arg_col is not None and is_dec(arg_col.dtype) and \
                    spec.func in ("avg", "stddev_samp"):
                # the kernel averaged SCALED ints; descale to float value
                vals = vals / 10.0 ** dec_scale(arg_col.dtype)
            out.append(DCol(spec.dtype, vals.astype(phys_dtype(spec.dtype)),
                            valid))
        return out

    def _agg_string(self, spec: AggSpec, arg_col: DCol, gid: jax.Array,
                    alive: jax.Array, cap_out: int) -> DCol:
        if spec.func == "count":
            vals, valid = kernels.agg_apply(
                gid, alive, "count", (jnp.zeros_like(arg_col.data),
                                      arg_col.valid), cap_out)
            return DCol("int", vals.astype(phys_dtype("int")), valid)
        if spec.func not in ("min", "max"):
            raise NotImplementedError(f"device {spec.func} over strings")
        from .device import string_rank_maps
        ranks, rank_to_code = string_rank_maps(arg_col.dictionary)
        rank_data = jexprs._lut_gather(arg_col.data, ranks)
        vals, valid = kernels.agg_apply(gid, alive, spec.func,
                                        (rank_data, arg_col.valid), cap_out)
        codes = jexprs._lut_gather(vals.astype(_I32), rank_to_code)
        return DCol("str", codes, valid, arg_col.dictionary)

    # -- joins ---------------------------------------------------------------
    def _run_join(self, node: JoinNode) -> DTable:
        if node.kind == "right":
            return self._right_join(node)
        left = self.execute(node.left)
        right = self.execute(node.right)
        return self._join(node, left, right)

    def _right_join(self, node: JoinNode) -> DTable:
        # right join == left join with sides swapped, columns re-ordered
        residual = node.residual
        nl = len(node.left.out_names)
        nr = len(node.right.out_names)
        if residual is not None:
            # rebase combined-schema column indices [left|right] -> [right|left]
            residual = _shift_residual(residual, nl, nr)
        swapped = dataclasses.replace(
            node, kind="left", left=node.right, right=node.left,
            left_keys=node.right_keys, right_keys=node.left_keys,
            residual=residual,
            out_names=[f"__r{i}" for i in range(len(node.out_names))])
        lt = self.execute(node.left)
        rt = self.execute(node.right)
        out = self._join(swapped, rt, lt)
        cols = out.cols[len(rt.cols):] + out.cols[:len(rt.cols)]
        assert len(cols) == nl + len(rt.cols)
        return DTable(list(node.out_names), cols, out.alive)

    def _join(self, node: JoinNode, left: DTable, right: DTable) -> DTable:
        kind = node.kind
        # Every anti branch below consults null_aware only when residual is
        # None; the combination is planner-rejected (planner.py _decorrelate)
        # — a real raise (assert strips under -O) so a future planner change
        # can't silently keep rows that NOT IN semantics exclude.
        if node.null_aware and node.residual is not None:
            raise NotImplementedError(
                "null-aware anti join with residual is unsupported")
        lcap, rcap = left.capacity, right.capacity
        if kind == "cross":
            lo = jnp.zeros(lcap, _I32)
            perm, rcount_t = kernels.compaction_perm(right.alive)
            cnt = jnp.where(left.alive, rcount_t, 0).astype(_I32)
            out, _, _ = self._expand_combine(node, left, right, lo, cnt, perm,
                                             residual=node.residual)
            return self._maybe_compact(out)

        lkeys = [self._eval(e, left) for e in node.left_keys]
        rkeys = [self._eval(e, right) for e in node.right_keys]
        lvalid = jnp.ones(lcap, bool)
        rvalid = jnp.ones(rcap, bool)
        for c in lkeys:
            lvalid = lvalid & c.valid
        for c in rkeys:
            rvalid = rvalid & c.valid

        if len(lkeys) == 1 and kind in ("inner", "left", "semi", "anti"):
            # direct-address fast path: the NDS star-join shape (single int
            # key, unique build side with a bounded key range — dimension
            # primary keys are dense). Replaces the sort-based machinery
            # (dense_rank over lcap+rcap rows + build sort + expansion)
            # with one scatter + gathers: TPU lax.sort is O(log^2 n) merge
            # passes over every operand, the dominant HBM traffic of a
            # power-run query program.
            out = self._fast_join(node, left, right, lkeys[0], rkeys[0],
                                  left.alive & lvalid, right.alive & rvalid,
                                  lvalid, rvalid)
            if out is not None:
                return out

        if self._mesh is not None and kind == "inner":
            out = self._mesh_shuffle_join(node, left, right, lkeys, rkeys,
                                          lvalid, rvalid)
            if out is not None:
                return out

        key_data = []
        for lc, rc in zip(lkeys, rkeys):
            ld, rd = _joinable_pair(lc, rc)
            key_data.append(jnp.concatenate([ld, rd]))
        match_alive = jnp.concatenate([left.alive & lvalid,
                                       right.alive & rvalid])
        gid, _ = self._dense_rank(
            key_data, [jnp.ones(lcap + rcap, bool)] * len(key_data),
            match_alive)
        l_gid, r_gid = gid[:lcap], gid[lcap:]

        _, perm_r = kernels.build_side(
            jnp.where(match_alive[lcap:], r_gid, jnp.iinfo(_I32).max),
            right.alive & rvalid)
        lo, cnt = kernels.probe_counts_by_gid(
            r_gid, right.alive & rvalid, l_gid, left.alive & lvalid,
            gid_cap=lcap + rcap)

        if kind in ("semi", "anti") and node.residual is None:
            matched = cnt > 0
            if kind == "semi":
                alive = left.alive & matched
            else:
                if node.null_aware:
                    build_has_null = bool(self._decide_exact(
                        jnp.any(right.alive & ~rvalid)))
                    if build_has_null:
                        alive = jnp.zeros(lcap, bool)
                    else:
                        alive = left.alive & lvalid & ~matched
                else:
                    alive = left.alive & ~matched
            return self._maybe_compact(
                DTable(list(node.out_names), left.cols, alive))

        if kind in ("semi", "anti"):
            # residual semi/anti: expand, evaluate, reduce to a left-row flag
            combined, left_idx, _ = self._expand_combine(
                node, left, right, lo, cnt, perm_r,
                residual=node.residual)
            hit = jax.ops.segment_sum(
                combined.alive.astype(_I32),
                jnp.where(combined.alive, left_idx, lcap),
                num_segments=lcap + 1)[:lcap] > 0
            alive = left.alive & hit if kind == "semi" else left.alive & ~hit
            return self._maybe_compact(
                DTable(list(node.out_names), left.cols, alive))

        inner, left_idx, right_rows = self._expand_combine(
            node, left, right, lo, cnt, perm_r, residual=node.residual)
        if kind == "inner":
            return self._maybe_compact(inner)
        matched_left = jax.ops.segment_sum(
            inner.alive.astype(_I32),
            jnp.where(inner.alive, left_idx, lcap),
            num_segments=lcap + 1)[:lcap] > 0
        unmatched_l = left.alive & ~matched_left
        pieces = [inner, _null_extend(left, right, unmatched_l, side="right",
                                      names=list(node.out_names))]
        if kind == "full":
            matched_right = jnp.zeros(rcap + 1, bool).at[
                jnp.where(inner.alive, right_rows, rcap)].set(True)[:rcap]
            unmatched_r = right.alive & ~matched_right
            pieces.append(_null_extend_left(left, right, unmatched_r,
                                            names=list(node.out_names)))
        return _concat_dtables(pieces, list(node.out_names))

    def _mesh_shuffle_join(self, node: JoinNode, left: DTable, right: DTable,
                           lkeys: list, rkeys: list, lvalid, rvalid
                           ) -> Optional[DTable]:
        """Partitioned shuffle join for fact-fact joins on a mesh: hash-
        repartition BOTH sides by the join key (all_to_all of bounded
        blocks), then join shard-locally — the fact sides never gather
        (Spark shuffle join; SURVEY.md §2 parallelism table last row).
        GSPMD's fallback for the generic sort-based join pulls fact-sized
        buffers to every device. Column/dtype eligibility is static; the
        capacity gate is a RECORDED branch (replay follows the record-time
        choice — capacities drift under streaming inflation), and the max
        hash-block / per-shard match counts are recorded schedule
        decisions."""
        from ...parallel import dist_ops

        mesh = self._mesh
        nsh = mesh.devices.size
        lcap, rcap = left.capacity, right.capacity
        if any(c.parts is not None for c in left.cols + right.cols):
            return None
        pairs = [_joinable_pair(a, b) for a, b in zip(lkeys, rkeys)]
        if not pairs or any(not jnp.issubdtype(a.dtype, jnp.integer)
                            for a, _ in pairs):
            return None
        # capacity gate AFTER the static gates: the recorded branch must sit
        # at a deterministic schedule position, and replay follows the
        # record-time choice (capacities drift under streaming inflation).
        # Only the min-rows threshold is a pure perf choice; divisibility is
        # a STRUCTURAL precondition (shard_rows = cap // nsh truncates rows
        # otherwise), so it is re-verified against the replay-time
        # capacities — drift to a non-divisible cap forces a re-record
        # instead of silently dropping trailing rows.
        if not self._decide_branch(
                min(lcap, rcap) >= max(self._shard_min_rows, nsh)
                and lcap % nsh == 0 and rcap % nsh == 0):
            return None
        if lcap % nsh != 0 or rcap % nsh != 0:
            # ReplayMismatch (not NotJittable): the caller routes it to a
            # fresh record, which re-evaluates the gate against the drifted
            # capacities and takes the generic join — NotJittable would mark
            # the entry permanently eager instead
            raise ReplayMismatch(
                f"shuffle-join capacities ({lcap}, {rcap}) drifted off the "
                f"shard-count multiple ({nsh}); re-record required")
        lkd = [a for a, _ in pairs]
        rkd = [b for _, b in pairs]
        l_ok = left.alive & lvalid
        r_ok = right.alive & rvalid

        def repart(kd, ok, cols):
            cap = int(ok.shape[0])
            shard_rows = cap // nsh
            iota = jnp.arange(cap, dtype=_I32)
            dest = dist_ops._multi_hash(kd, nsh)
            pair_id = jnp.where(ok, (iota // shard_rows) * nsh + dest,
                                nsh * nsh)
            # _seg picks per mode: masked fused reduce under trace (a
            # fact-sized segment_sum scatter would serialize inside every
            # compiled run), O(n) segment_sum on the eager record pass (the
            # masked form would materialize an (nsh^2, n) intermediate).
            # The dead-row sentinel id nsh*nsh falls outside num_segments
            # and drops out on either path.
            sizes = kernels._seg(ok.astype(_I32), pair_id, nsh * nsh, "sum")
            per_pair = bucket(max(self._decide_cap(jnp.max(sizes)), 1))
            fn = dist_ops.repartition_by_key(mesh, per_pair, emit_key=False)
            out_flat, out_alive, _, overflow = fn(list(kd) + list(cols),
                                                  ok, list(kd))
            # per_pair covers the recorded max block; drift re-records
            self._decide_exact(overflow)
            return out_flat[:len(kd)], out_flat[len(kd):], out_alive

        l_flat = [x for c in left.cols for x in (c.data, c.valid)]
        r_flat = [x for c in right.cols for x in (c.data, c.valid)]
        lkd2, l_cols2, l_al2 = repart(lkd, l_ok, l_flat)
        rkd2, r_cols2, r_al2 = repart(rkd, r_ok, r_flat)

        counts, lo, cnt, perm_r = dist_ops.shuffle_join_counts(mesh)(
            tuple(lkd2), l_al2, tuple(rkd2), r_al2)
        cap_out_shard = bucket(max(self._decide_cap(jnp.max(counts)), 1))
        out_l, out_r, out_alive = dist_ops.shuffle_join_expand(
            mesh, cap_out_shard)(lo, cnt, perm_r, l_al2,
                                 tuple(l_cols2), tuple(r_cols2))

        def rebuild(cols_src, flat):
            out = []
            for i, c in enumerate(cols_src):
                out.append(dataclasses.replace(
                    c, data=flat[2 * i],
                    valid=flat[2 * i + 1].astype(bool), parts=None))
            return out
        cols = rebuild(left.cols, list(out_l)) + rebuild(right.cols,
                                                         list(out_r))
        out = DTable(self._combined_names(node, len(cols)), cols, out_alive)
        return self._apply_residual(node.residual, out)

    @staticmethod
    def _combined_names(node: JoinNode, ncols: int) -> list[str]:
        return list(node.out_names) if len(node.out_names) == ncols \
            else [f"__c{i}" for i in range(ncols)]

    def _apply_residual(self, residual, out: DTable) -> DTable:
        if residual is None:
            return out
        mask = jexprs.evaluate(residual, out, subquery_eval=self._ectx())
        return DTable(out.names, out.cols,
                      kernels.filter_alive(out.alive, mask.data, mask.valid))

    def _fast_join(self, node: JoinNode, left: DTable, right: DTable,
                   lkey: DCol, rkey: DCol, l_ok: jax.Array, r_ok: jax.Array,
                   lvalid: jax.Array, rvalid: jax.Array) -> Optional[DTable]:
        """Direct-address single-key join against a unique build side.

        Build: scatter build-row indices into a [LIMIT] table addressed by
        (key - min_key). Probe: one gather + a key-equality confirm (which
        also makes the path immune to range-arithmetic overflow). 1:1 match
        means the output keeps the probe capacity — no expansion step, no
        capacity decision, no sorts. Eligibility (unique keys, bounded
        range) is data-dependent: decided at record time and replayed as an
        exact schedule decision, so record and replay always take the same
        branch.
        """
        kind = node.kind
        lcap, rcap = left.capacity, right.capacity
        ld, rd = _joinable_pair(lkey, rkey)
        if not jnp.issubdtype(rd.dtype, jnp.integer):
            return None    # float keys: no address arithmetic
        limit = min(4 * rcap, 1 << 24)
        big = jnp.iinfo(rd.dtype).max
        small = jnp.iinfo(rd.dtype).min
        state: dict = {}

        def probe() -> jax.Array:
            rmin = jnp.min(jnp.where(r_ok, rd, big))
            rmax = jnp.max(jnp.where(r_ok, rd, small))
            cnt_r = jnp.sum(r_ok.astype(_I32))
            span_ok = (rmax - rmin) < limit
            lut_idx = jnp.clip(rd - rmin, 0, limit - 1)
            scatter_idx = jnp.where(r_ok, lut_idx, limit)
            hist = jnp.zeros(limit + 1, _I32).at[scatter_idx].add(1)[:limit]
            unique = jnp.max(hist) <= 1
            state.update(rmin=rmin, scatter_idx=scatter_idx)
            return (span_ok & unique & (cnt_r > 0)).astype(_I32)

        if not self._decide_exact_lazy(probe):
            return None
        rmin, scatter_idx = state["rmin"], state["scatter_idx"]

        lut = jnp.full(limit + 1, -1, _I32).at[scatter_idx].set(
            jnp.arange(rcap, dtype=_I32))[:limit]
        pidx = ld - rmin
        in_range = (pidx >= 0) & (pidx < limit)
        r_row = lut[jnp.clip(pidx, 0, limit - 1)]
        safe_r = jnp.clip(r_row, 0, rcap - 1)
        # key-equality confirm: correctness never rests on range arithmetic
        matched = l_ok & in_range & (r_row >= 0) & (rd[safe_r] == ld)

        if kind in ("semi", "anti") and node.residual is None:
            if kind == "semi":
                alive = left.alive & matched
            elif node.null_aware:
                build_has_null = bool(self._decide_exact(
                    jnp.any(right.alive & ~rvalid)))
                alive = jnp.zeros(lcap, bool) if build_has_null \
                    else left.alive & lvalid & ~matched
            else:
                alive = left.alive & ~matched
            return self._maybe_compact(
                DTable(list(node.out_names), left.cols, alive))

        rcols = _gather_cols(right.cols, safe_r)
        names = list(node.out_names) if len(node.out_names) == \
            len(left.cols) + len(rcols) else \
            [f"__c{i}" for i in range(len(left.cols) + len(rcols))]
        combined = DTable(names, list(left.cols) + rcols, left.alive)
        if node.residual is not None:
            mask = jexprs.evaluate(node.residual, combined,
                                   subquery_eval=self._ectx())
            matched = matched & mask.data.astype(bool) & mask.valid

        if kind == "semi":
            return self._maybe_compact(DTable(
                list(node.out_names), left.cols, left.alive & matched))
        if kind == "anti":
            return self._maybe_compact(DTable(
                list(node.out_names), left.cols, left.alive & ~matched))
        if kind == "inner":
            return self._maybe_compact(DTable(
                combined.names, combined.cols, left.alive & matched))
        # left join: 1:1 — unmatched probe rows keep a NULL right side
        # (canonical zeros under ~matched: DCol's null-payload invariant)
        def null_out(c: DCol) -> DCol:
            data = jnp.where(matched, c.data, jnp.zeros((), c.data.dtype))
            return dataclasses.replace(
                c, data=data, valid=c.valid & matched,
                parts=None if c.parts is None else tuple(
                    null_out(p) for p in c.parts))
        out_cols = list(left.cols) + [null_out(c) for c in rcols]
        return DTable(list(node.out_names), out_cols, left.alive)

    def _expand_combine(self, node: JoinNode, left: DTable, right: DTable,
                        lo, cnt, perm_r, residual=None
                        ) -> tuple[DTable, jax.Array, jax.Array]:
        """Materialize matched pairs; returns (combined, left_idx, right_rows)
        — all padded to the planned output capacity, uncompacted."""
        total_t = jnp.sum(cnt)
        total = self._decide_cap(total_t)
        cap_out = bucket(max(total, 1))
        left_idx, build_pos, alive_out = kernels.expand_join(
            lo, cnt, left.alive, cap_out)
        right_rows = perm_r[jnp.clip(build_pos, 0, right.capacity - 1)]
        cols = _gather_cols(left.cols, left_idx) + \
            _gather_cols(right.cols, right_rows)
        out = DTable(self._combined_names(node, len(cols)), cols, alive_out)
        out = self._apply_residual(residual, out)
        return out, left_idx, right_rows


# -- plan utilities -----------------------------------------------------------

def _plan_fingerprint(node) -> str:
    """Stable structural hash of a plan subtree (for executor-synthesized
    segment keys; CTE segments use planner AST fingerprints instead). Two
    structurally identical subtrees — including literals, so stream-
    parameterized plans never collide — share a segment cache slot.
    MaterializedNodes hash by identity (callers exclude them)."""
    import dataclasses as _dc
    import hashlib

    parts: list[str] = []

    def rec(x):
        if isinstance(x, MaterializedNode):
            parts.append(f"mat:{id(x)}")
            return
        if isinstance(x, np.ndarray):
            # repr truncates long arrays -> collision risk; hash content
            parts.append(f"nd{x.dtype}{x.shape}:" + (
                repr(x.tolist()) if x.dtype == object
                else hashlib.sha1(x.tobytes()).hexdigest()))
            return
        if _dc.is_dataclass(x) and not isinstance(x, type):
            parts.append(type(x).__name__ + "(")
            for f in _dc.fields(x):
                parts.append(f.name + "=")
                rec(getattr(x, f.name))
                parts.append(",")
            parts.append(")")
        elif isinstance(x, (list, tuple)):
            parts.append("[")
            for v in x:
                rec(v)
                parts.append(",")
            parts.append("]")
        else:
            parts.append(repr(x))

    rec(node)
    return hashlib.sha1("".join(parts).encode()).hexdigest()[:16]


# -- expression utilities -----------------------------------------------------

def _shift_residual(expr: BExpr, nl: int, nr: int) -> BExpr:
    """Rebase bound column indices from [left|right] to [right|left]."""
    from ..plan import BCall, BCol

    if isinstance(expr, BCol):
        idx = expr.index + nr if expr.index < nl else expr.index - nl
        return dataclasses.replace(expr, index=idx)
    if isinstance(expr, BCall):
        return dataclasses.replace(
            expr, args=[_shift_residual(a, nl, nr) for a in expr.args])
    return expr


# -- column utilities --------------------------------------------------------

def _gather_col(c: DCol, idx: jax.Array) -> DCol:
    parts = None
    if c.parts is not None:
        parts = tuple(dataclasses.replace(p, data=p.data[idx],
                                          valid=p.valid[idx])
                      for p in c.parts)
    return dataclasses.replace(c, data=c.data[idx], valid=c.valid[idx],
                               parts=parts)


def _gather_cols(cols: list, idx: jax.Array) -> list:
    """Gather EVERY column of a table by one index vector — the join /
    sort / late-materialization shape. With the "gather" pallas op active
    the flattened (data, valid, parts...) arrays ride batched VMEM-staged
    kernel passes (kernels.gather_many); otherwise per-column XLA gathers
    exactly as before. Both sides are pure permutation reads."""
    if not _pallas.op_active("gather"):
        return [_gather_col(c, idx) for c in cols]
    arrays: list = []
    for c in cols:
        arrays.append(c.data)
        arrays.append(c.valid)
        if c.parts is not None:
            for p in c.parts:
                arrays.append(p.data)
                arrays.append(p.valid)
    flat = kernels.gather_many(arrays, idx)
    out: list = []
    i = 0
    for c in cols:
        data, valid = flat[i], flat[i + 1]
        i += 2
        parts = None
        if c.parts is not None:
            ps = []
            for p in c.parts:
                ps.append(dataclasses.replace(p, data=flat[i],
                                              valid=flat[i + 1]))
                i += 2
            parts = tuple(ps)
        out.append(dataclasses.replace(c, data=data, valid=valid,
                                       parts=parts))
    return out


def _joinable_pair(a: DCol, b: DCol) -> tuple[jax.Array, jax.Array]:
    """Comparable device key arrays for a join key pair.

    Encoded execution: when one side carries a dictionary codebook the
    join runs ON CODES — the plain side's values remap into the encoded
    side's code space (device.encode_against: exact code or -1, which
    matches nothing), so the big encoded side keeps its i32 codes through
    dense-rank/build/probe instead of decoding every row. Codes are only
    ever compared against codes of the SAME codebook; equality of codes is
    equality of values by construction, and validity masks carry the null
    semantics exactly as on the plain path."""
    if a.dtype == "str" or b.dtype == "str":
        return jexprs._string_pair_keys(a, b)
    if a.codebook is not None or b.codebook is not None:
        if a.codebook is b.codebook:
            return a.canon().data, b.canon().data
        if a.codebook is not None and b.codebook is None:
            return a.canon().data, encode_against(a.codebook, b)
        if b.codebook is not None and a.codebook is None:
            return encode_against(b.codebook, a), b.canon().data
        a, b = decode_col(a), decode_col(b)   # distinct codebooks
    da, db = a.canon().data, b.canon().data
    if da.dtype != db.dtype:
        ct = jnp.promote_types(da.dtype, db.dtype)
        da, db = da.astype(ct), db.astype(ct)
    return da, db


def _null_extend(left: DTable, right: DTable, left_mask: jax.Array,
                 side: str, names: list[str]) -> DTable:
    """Left rows selected by mask, with the right side all-NULL (outer join)."""
    cols = [dataclasses.replace(c) for c in left.cols]
    for c in right.cols:
        cols.append(dataclasses.replace(
            c, data=jnp.zeros(left.capacity, c.data.dtype),
            valid=jnp.zeros(left.capacity, bool), parts=None))
    return DTable(names, cols, left_mask)


def _null_extend_left(left: DTable, right: DTable, right_mask: jax.Array,
                      names: list[str]) -> DTable:
    """Right rows selected by mask, with the left side all-NULL (full outer)."""
    cols = [dataclasses.replace(
        c, data=jnp.zeros(right.capacity, c.data.dtype),
        valid=jnp.zeros(right.capacity, bool), parts=None)
        for c in left.cols]
    cols += [dataclasses.replace(c) for c in right.cols]
    return DTable(names, cols, right_mask)


def _concat_dtables(pieces: list[DTable], names: list[str]) -> DTable:
    """Row-concatenate device tables (merging string dictionaries on host)."""
    ncols = len(pieces[0].cols)
    out_cols: list[DCol] = []
    for ci in range(ncols):
        cols = [_flatten_for_concat(p.cols[ci]) for p in pieces]
        dtype = cols[0].dtype
        if dtype == "str":
            dictionary, datas = jexprs._merge_branch_strings(cols)
            data = jnp.concatenate(datas)
            out_cols.append(DCol("str", data,
                                 jnp.concatenate([c.valid for c in cols]),
                                 dictionary))
        else:
            pd = cols[0].data.dtype
            data = jnp.concatenate([c.data.astype(pd) for c in cols])
            out_cols.append(DCol(dtype, data,
                                 jnp.concatenate([c.valid for c in cols])))
    alive = jnp.concatenate([p.alive for p in pieces])
    return DTable(names, out_cols, alive)


def _flatten_for_concat(c: DCol) -> DCol:
    # pieces may mix encodings (an encoded inner-join piece concatenated
    # with a plain null-extension): codes from different codebooks must
    # never share a buffer, so concatenation is a decode site
    c = decode_col(c)
    if c.parts is None:
        return c
    from .device import _flatten_compound
    return _flatten_compound(c)
