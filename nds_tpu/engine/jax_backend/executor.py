"""Device plan executor: walks a bound plan over DTables (JAX arrays).

Robust-mode contract: each node executes as XLA compute over padded buffers;
row counts are host-synced only at shape-decision points (post filter/join/
aggregate capacity planning). Any node the device backend does not yet cover
falls back to the numpy oracle backend for that node only — results are
bridged host<->device at the node boundary, so every query always runs.

Mirrors engine/executor.py (which plays the role of Spark executors in the
reference, nds/nds_power.py:124-134).
"""
from __future__ import annotations

import dataclasses
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import ops as host_ops
from ..column import Table
from ..executor import Executor as HostExecutor
from ..plan import (
    AggregateNode, AggSpec, BExpr, DistinctNode, FilterNode, JoinNode,
    LimitNode, MaterializedNode, PlanNode, ProjectNode, ScanNode, SetOpNode,
    SortNode, WindowNode,
)
from . import jexprs, kernels
from .device import (DCol, DTable, bucket, phys_dtype, rank_key,
                     string_rank_lut, to_device, to_host)

_I32 = jnp.int32


class JaxExecutor:
    """Executes bound plans on the JAX backend with per-node host fallback."""

    def __init__(self, load_table: Callable[[str], Table],
                 trace: Optional[Callable[[str, float, int], None]] = None):
        self._load_table = load_table
        self._memo: dict[int, DTable] = {}
        self._scan_cache: dict[str, DTable] = {}
        self._trace = trace
        self.fallback_nodes: list[str] = []   # observability: who fell back

    # -- public --------------------------------------------------------------
    def execute(self, node: PlanNode) -> DTable:
        key = id(node)
        if key in self._memo:
            return self._memo[key]
        try:
            result = self._run(node)
        except NotImplementedError as e:
            self.fallback_nodes.append(f"{type(node).__name__}: {e}")
            result = self._host_fallback(node)
        self._memo[key] = result
        return result

    def execute_to_host(self, node: PlanNode) -> Table:
        return to_host(self.execute(node))

    # -- helpers -------------------------------------------------------------
    def _eval(self, expr: BExpr, table: DTable) -> DCol:
        return jexprs.evaluate(expr, table, subquery_eval=self._scalar)

    def _scalar(self, plan: PlanNode):
        t = to_host(self.execute(plan))
        if t.num_rows == 0:
            return None
        col = t.columns[0]
        if not bool(col.validity[0]):
            return None
        if col.dtype == "str":
            return col.decode()[0]
        return np.asarray(col.data)[0].item()

    def _host_fallback(self, node: PlanNode) -> DTable:
        repl = {}
        for f in ("child", "left", "right"):
            sub = getattr(node, f, None)
            if isinstance(sub, PlanNode):
                t = to_host(self.execute(sub))
                repl[f] = MaterializedNode(
                    table=t, label=f"device:{f}",
                    out_names=list(sub.out_names), out_dtypes=list(sub.out_dtypes))
        host_node = dataclasses.replace(node, **repl) if repl else node
        host = HostExecutor(self._load_table)
        return to_device(host.execute(host_node))

    def _maybe_compact(self, t: DTable) -> DTable:
        count = int(t.count())
        cap = bucket(count)
        if t.capacity <= 2 * cap:
            return t
        perm, _ = kernels.compaction_perm(t.alive)
        perm = perm[:cap]
        cols = [DCol(c.dtype, c.data[perm], c.valid[perm], c.dictionary,
                     None if c.parts is None else tuple(
                         DCol(p.dtype, p.data[perm], p.valid[perm], p.dictionary)
                         for p in c.parts))
                for c in t.cols]
        alive = jnp.arange(cap, dtype=_I32) < count
        return DTable(t.names, cols, alive)

    # -- node dispatch -------------------------------------------------------
    def _run(self, node: PlanNode) -> DTable:
        if isinstance(node, MaterializedNode):
            return to_device(node.table)
        if isinstance(node, ScanNode):
            return self._run_scan(node)
        if isinstance(node, FilterNode):
            child = self.execute(node.child)
            mask = self._eval(node.predicate, child)
            alive = kernels.filter_alive(child.alive, mask.data, mask.valid)
            return self._maybe_compact(DTable(list(node.out_names),
                                              child.cols, alive))
        if isinstance(node, ProjectNode):
            child = self.execute(node.child)
            cols = [self._eval(e, child) for e in node.exprs]
            return DTable(list(node.out_names), cols, child.alive)
        if isinstance(node, JoinNode):
            return self._run_join(node)
        if isinstance(node, AggregateNode):
            return self._run_aggregate(node)
        if isinstance(node, WindowNode):
            raise NotImplementedError("window functions (device) pending")
        if isinstance(node, SortNode):
            return self._run_sort(node)
        if isinstance(node, LimitNode):
            child = self.execute(node.child)
            alive = kernels.limit_alive(child.alive, node.n)
            return self._maybe_compact(DTable(list(node.out_names),
                                              child.cols, alive))
        if isinstance(node, DistinctNode):
            child = self.execute(node.child)
            alive = self._distinct_alive(child, list(range(len(child.cols))))
            return self._maybe_compact(DTable(list(node.out_names),
                                              child.cols, alive))
        if isinstance(node, SetOpNode):
            return self._run_setop(node)
        raise NotImplementedError(type(node).__name__)

    def _run_setop(self, node: SetOpNode) -> DTable:
        left = self.execute(node.left)
        right = self.execute(node.right)
        names = list(node.out_names)
        both = _concat_dtables([left, right], names)
        if node.op == "union":
            if node.all:
                return both
            alive = self._distinct_alive(both, list(range(len(both.cols))))
            return self._maybe_compact(DTable(names, both.cols, alive))
        # intersect / except: distinct-row semantics (mirrors host ops.set_op)
        lcap = left.capacity
        n = both.capacity
        iota = jnp.arange(n, dtype=_I32)
        is_left = iota < lcap
        keys = [rank_key(c) for c in both.cols]
        valids = [c.valid for c in both.cols]
        gid, _ = kernels.dense_rank(keys, valids, both.alive)
        safe_gid = jnp.where(both.alive, gid, n)
        in_left = jnp.zeros(n + 1, bool).at[
            jnp.where(is_left, safe_gid, n)].set(True)
        in_right = jnp.zeros(n + 1, bool).at[
            jnp.where(~is_left, safe_gid, n)].set(True)
        keep = (in_left & in_right) if node.op == "intersect" \
            else (in_left & ~in_right)
        first_left = jnp.full(n + 1, n, dtype=_I32).at[
            jnp.where(both.alive & is_left, gid, n)].min(iota)
        alive = both.alive & is_left & keep[jnp.clip(gid, 0, n)] & \
            (first_left[jnp.clip(gid, 0, n)] == iota)
        return self._maybe_compact(DTable(names, both.cols, alive))

    def _run_scan(self, node: ScanNode) -> DTable:
        cache_key = node.table + "//" + ",".join(node.columns)
        if cache_key not in self._scan_cache:
            t = self._load_table(node.table)
            index = {n: i for i, n in enumerate(t.names)}
            cols = [t.columns[index[c]] for c in node.columns]
            self._scan_cache[cache_key] = to_device(
                Table(list(node.out_names), cols))
        cached = self._scan_cache[cache_key]
        return DTable(list(node.out_names), cached.cols, cached.alive)

    # -- sort / distinct -----------------------------------------------------
    def _run_sort(self, node: SortNode) -> DTable:
        child = self.execute(node.child)
        key_cols = [self._eval(k.expr, child) for k in node.keys]
        key_data = [rank_key(c) for c in key_cols]
        key_valid = [c.valid for c in key_cols]
        perm = kernels.sort_perm(key_data, key_valid, node.keys, child.alive)
        cols = [_gather_col(c, perm) for c in child.cols]
        return DTable(list(node.out_names), cols, child.alive[perm])

    def _distinct_alive(self, t: DTable, col_idx: list[int]) -> jax.Array:
        keys = [rank_key(t.cols[i]) for i in col_idx]
        valids = [t.cols[i].valid for i in col_idx]
        gid, _ = kernels.dense_rank(keys, valids, t.alive)
        n = t.capacity
        iota = jnp.arange(n, dtype=_I32)
        first = jnp.full(n + 1, n, dtype=_I32).at[
            jnp.where(t.alive, gid, n)].min(iota)
        return t.alive & (first[jnp.clip(gid, 0, n)] == iota)

    # -- aggregate -----------------------------------------------------------
    def _run_aggregate(self, node: AggregateNode) -> DTable:
        child = self.execute(node.child)
        grouping_sets = [list(range(len(node.group_exprs)))]
        if node.rollup:
            grouping_sets = [list(range(k))
                             for k in range(len(node.group_exprs), -1, -1)]
        pieces = [self._aggregate_one(node, child, keep)
                  for keep in grouping_sets]
        if len(pieces) == 1:
            return pieces[0]
        return _concat_dtables(pieces, list(node.out_names))

    def _aggregate_one(self, node: AggregateNode, child: DTable,
                       keep: list[int]) -> DTable:
        group_cols = [self._eval(e, child) for e in node.group_exprs]
        active = [group_cols[i] for i in keep]
        gid, num_groups_t = kernels.dense_rank(
            [rank_key(c) for c in active], [c.valid for c in active],
            child.alive)
        num_groups = int(num_groups_t)
        if not active:
            # a global aggregate (incl. a rollup's grand-total grouping set)
            # over empty input still yields one row
            num_groups = max(num_groups, 1)
        alive_for_agg = child.alive
        cap_out = bucket(max(num_groups, 1))

        out_cols: list[DCol] = []
        keep_set = set(keep)
        for i, gc in enumerate(group_cols):
            if i in keep_set:
                vals, valid = kernels.group_representatives(
                    gid, alive_for_agg, gc.canon().data, gc.valid, cap_out)
                out_cols.append(DCol(gc.dtype, vals, valid, gc.dictionary))
            else:  # rolled-up column: NULL
                out_cols.append(DCol(gc.dtype,
                                     jnp.zeros(cap_out, phys_dtype(gc.dtype)),
                                     jnp.zeros(cap_out, bool), gc.dictionary))

        agg_results = self._compute_aggs(node.aggs, child, gid,
                                         alive_for_agg, cap_out)
        out_cols.extend(agg_results)
        if node.rollup:
            gid_val = sum(1 << (len(node.group_exprs) - 1 - i)
                          for i in range(len(node.group_exprs))
                          if i not in keep_set)
            out_cols.append(DCol("int",
                                 jnp.full(cap_out, gid_val, phys_dtype("int")),
                                 jnp.ones(cap_out, bool)))
        alive = jnp.arange(cap_out, dtype=_I32) < num_groups
        names = list(node.out_names)
        return DTable(names, out_cols, alive)

    def _compute_aggs(self, specs: list[AggSpec], child: DTable,
                      gid: jax.Array, alive: jax.Array,
                      cap_out: int) -> list[DCol]:
        out: list[DCol] = []
        for spec in specs:
            arg_col = None if spec.arg is None else self._eval(spec.arg, child)
            use_alive = alive
            if spec.distinct and arg_col is not None:
                use_alive = kernels.distinct_within_group(
                    gid, alive, rank_key(arg_col), arg_col.valid)
            if arg_col is not None and arg_col.dtype == "str":
                out.append(self._agg_string(spec, arg_col, gid, use_alive,
                                            cap_out))
                continue
            arg = None
            if arg_col is not None:
                data = arg_col.canon().data
                if spec.func == "sum" and arg_col.dtype == "int":
                    data = data.astype(phys_dtype("int"))
                arg = (data, arg_col.valid)
            (vals, valid), = kernels.aggregate(gid, use_alive, [spec], [arg],
                                               cap_out)
            out.append(DCol(spec.dtype, vals.astype(phys_dtype(spec.dtype)),
                            valid))
        return out

    def _agg_string(self, spec: AggSpec, arg_col: DCol, gid: jax.Array,
                    alive: jax.Array, cap_out: int) -> DCol:
        if spec.func == "count":
            (vals, valid), = kernels.aggregate(
                gid, alive, [spec], [(jnp.zeros_like(arg_col.data),
                                      arg_col.valid)], cap_out)
            return DCol("int", vals.astype(phys_dtype("int")), valid)
        if spec.func not in ("min", "max"):
            raise NotImplementedError(f"device {spec.func} over strings")
        d = arg_col.dictionary if arg_col.dictionary is not None \
            else np.empty(0, dtype=object)
        ranks = string_rank_lut(d)
        order = np.argsort(d.astype(str), kind="stable") if len(d) \
            else np.zeros(1, dtype=np.int64)
        rank_data = jexprs._lut_gather(arg_col.data, ranks)
        mm_spec = AggSpec(func=spec.func, arg=spec.arg, distinct=False,
                          name=spec.name)
        (vals, valid), = kernels.aggregate(gid, alive, [mm_spec],
                                           [(rank_data, arg_col.valid)],
                                           cap_out)
        codes = jexprs._lut_gather(vals.astype(_I32),
                                   order.astype(np.int32))
        return DCol("str", codes, valid, arg_col.dictionary)

    # -- joins ---------------------------------------------------------------
    def _run_join(self, node: JoinNode) -> DTable:
        if node.kind == "right":
            return self._right_join(node)
        left = self.execute(node.left)
        right = self.execute(node.right)
        return self._join(node, left, right)

    def _right_join(self, node: JoinNode) -> DTable:
        # right join == left join with sides swapped, columns re-ordered
        swapped = dataclasses.replace(
            node, kind="left", left=node.right, right=node.left,
            left_keys=node.right_keys, right_keys=node.left_keys,
            residual=None,
            out_names=[f"__r{i}" for i in range(len(node.out_names))])
        if node.residual is not None:
            raise NotImplementedError("right join with residual (device)")
        lt = self.execute(node.left)
        rt = self.execute(node.right)
        out = self._join(swapped, rt, lt)
        nl = len(lt.cols)
        cols = out.cols[len(rt.cols):] + out.cols[:len(rt.cols)]
        assert len(cols) == nl + len(rt.cols)
        return DTable(list(node.out_names), cols, out.alive)

    def _join(self, node: JoinNode, left: DTable, right: DTable) -> DTable:
        kind = node.kind
        lcap, rcap = left.capacity, right.capacity
        if kind == "cross":
            lo = jnp.zeros(lcap, _I32)
            perm, rcount_t = kernels.compaction_perm(right.alive)
            rcount = int(rcount_t)
            cnt = jnp.where(left.alive, rcount, 0).astype(_I32)
            return self._expand_combine(node, left, right, lo, cnt, perm,
                                        residual=node.residual)

        lkeys = [self._eval(e, left) for e in node.left_keys]
        rkeys = [self._eval(e, right) for e in node.right_keys]
        lvalid = jnp.ones(lcap, bool)
        rvalid = jnp.ones(rcap, bool)
        for c in lkeys:
            lvalid = lvalid & c.valid
        for c in rkeys:
            rvalid = rvalid & c.valid

        key_data = []
        for lc, rc in zip(lkeys, rkeys):
            ld, rd = _joinable_pair(lc, rc)
            key_data.append(jnp.concatenate([ld, rd]))
        match_alive = jnp.concatenate([left.alive & lvalid,
                                       right.alive & rvalid])
        gid, _ = kernels.dense_rank(
            key_data, [jnp.ones(lcap + rcap, bool)] * len(key_data),
            match_alive)
        l_gid, r_gid = gid[:lcap], gid[lcap:]

        sorted_gid, perm_r = kernels.build_side(
            jnp.where(match_alive[lcap:], r_gid, jnp.iinfo(_I32).max),
            right.alive & rvalid)
        lo, cnt = kernels.probe_counts(sorted_gid,
                                       jnp.where(match_alive[:lcap], l_gid,
                                                 jnp.iinfo(_I32).max - 1),
                                       left.alive & lvalid)

        if kind in ("semi", "anti") and node.residual is None:
            matched = cnt > 0
            if kind == "semi":
                alive = left.alive & matched
            else:
                if node.null_aware:
                    build_has_null = bool(jnp.any(right.alive & ~rvalid))
                    if build_has_null:
                        alive = jnp.zeros(lcap, bool)
                    else:
                        alive = left.alive & lvalid & ~matched
                else:
                    alive = left.alive & ~matched
            return self._maybe_compact(
                DTable(list(node.out_names), left.cols, alive))

        if kind in ("semi", "anti"):
            # residual semi/anti: expand, evaluate, reduce to a left-row flag
            expanded = self._expand_combine(node, left, right, lo, cnt, perm_r,
                                            residual=node.residual,
                                            keep_left_idx=True)
            combined, left_idx = expanded
            hit = jax.ops.segment_sum(
                combined.alive.astype(_I32),
                jnp.where(combined.alive, left_idx, lcap),
                num_segments=lcap + 1)[:lcap] > 0
            alive = left.alive & hit if kind == "semi" else left.alive & ~hit
            return self._maybe_compact(
                DTable(list(node.out_names), left.cols, alive))

        if kind == "full":
            raise NotImplementedError("full outer join (device) pending")
        inner = self._expand_combine(node, left, right, lo, cnt, perm_r,
                                     residual=node.residual,
                                     keep_left_idx=(kind == "left"))
        if kind == "inner":
            return inner
        combined, left_idx = inner
        matched_left = jax.ops.segment_sum(
            combined.alive.astype(_I32),
            jnp.where(combined.alive, left_idx, lcap),
            num_segments=lcap + 1)[:lcap] > 0
        unmatched = left.alive & ~matched_left
        pieces = [combined, _null_extend(left, right, unmatched, side="right",
                                         names=list(node.out_names))]
        return _concat_dtables(pieces, list(node.out_names))

    def _expand_combine(self, node: JoinNode, left: DTable, right: DTable,
                        lo, cnt, perm_r, residual=None, keep_left_idx=False):
        total = int(jnp.sum(cnt))
        cap_out = bucket(max(total, 1))
        left_idx, build_pos, alive_out = kernels.expand_join(
            lo, cnt, left.alive, cap_out)
        right_rows = perm_r[jnp.clip(build_pos, 0, right.capacity - 1)]
        cols = [_gather_col(c, left_idx) for c in left.cols] + \
               [_gather_col(c, right_rows) for c in right.cols]
        names = list(node.out_names) if len(node.out_names) == len(cols) \
            else [f"__c{i}" for i in range(len(cols))]
        out = DTable(names, cols, alive_out)
        if residual is not None:
            mask = jexprs.evaluate(residual, out, subquery_eval=self._scalar)
            out = DTable(out.names, out.cols,
                         kernels.filter_alive(out.alive, mask.data, mask.valid))
        if keep_left_idx:
            return out, left_idx
        return self._maybe_compact(out)


# -- column utilities --------------------------------------------------------

def _gather_col(c: DCol, idx: jax.Array) -> DCol:
    parts = None
    if c.parts is not None:
        parts = tuple(DCol(p.dtype, p.data[idx], p.valid[idx], p.dictionary)
                      for p in c.parts)
    return DCol(c.dtype, c.data[idx], c.valid[idx], c.dictionary, parts)


def _joinable_pair(a: DCol, b: DCol) -> tuple[jax.Array, jax.Array]:
    """Comparable device key arrays for a join key pair."""
    if a.dtype == "str" or b.dtype == "str":
        return jexprs._string_pair_keys(a, b)
    da, db = a.canon().data, b.canon().data
    if da.dtype != db.dtype:
        ct = jnp.promote_types(da.dtype, db.dtype)
        da, db = da.astype(ct), db.astype(ct)
    return da, db


def _null_extend(left: DTable, right: DTable, left_mask: jax.Array,
                 side: str, names: list[str]) -> DTable:
    """Left rows selected by mask, with the right side all-NULL (outer join)."""
    cols = [DCol(c.dtype, c.data, c.valid, c.dictionary, c.parts)
            for c in left.cols]
    for c in right.cols:
        cols.append(DCol(c.dtype,
                         jnp.zeros(left.capacity, c.data.dtype),
                         jnp.zeros(left.capacity, bool), c.dictionary))
    return DTable(names, cols, left_mask)


def _concat_dtables(pieces: list[DTable], names: list[str]) -> DTable:
    """Row-concatenate device tables (merging string dictionaries on host)."""
    ncols = len(pieces[0].cols)
    out_cols: list[DCol] = []
    for ci in range(ncols):
        cols = [_flatten_for_concat(p.cols[ci]) for p in pieces]
        dtype = cols[0].dtype
        if dtype == "str":
            dictionary, datas = jexprs._merge_branch_strings(cols)
            data = jnp.concatenate(datas)
            out_cols.append(DCol("str", data,
                                 jnp.concatenate([c.valid for c in cols]),
                                 dictionary))
        else:
            pd = cols[0].data.dtype
            data = jnp.concatenate([c.data.astype(pd) for c in cols])
            out_cols.append(DCol(dtype, data,
                                 jnp.concatenate([c.valid for c in cols])))
    alive = jnp.concatenate([p.alive for p in pieces])
    return DTable(names, out_cols, alive)


def _flatten_for_concat(c: DCol) -> DCol:
    if c.parts is None:
        return c
    from .device import _flatten_compound
    return _flatten_compound(c)
