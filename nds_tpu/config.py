"""Engine-wide configuration.

One typed config object replaces the reference's three-tier config zoo
(argparse + bash template `SPARK_CONF` arrays + key=value property files,
see reference nds/base.template and nds/nds_power.py:306-312). Property files
are still accepted for interface parity (`load_properties`).
"""
from __future__ import annotations

import os
from dataclasses import dataclass, field


def _env_bool(name: str, default: bool) -> bool:
    v = os.environ.get(name)
    if v is None:
        return default
    return v.lower() not in ("0", "false", "no")


@dataclass
class EngineConfig:
    # Physical type for DECIMAL columns:
    #   "f64" (default) — doubles; exact enough under the validator epsilon
    #   "i64" — exact scaled-int64 ("decN" engine dtype): sums/compares on
    #           integers, SURVEY.md §7's decimal plan (requires x64 for the
    #           full int64 range; TPU runs S64 as emulated dual-i32)
    decimal_physical: str = "f64"
    # device mesh axis for data-parallel table sharding
    mesh_shape: tuple[int, ...] = ()
    mesh_axis_names: tuple[str, ...] = ("shards",)
    # multi-chip sharded morsel execution: partition every streamed scan
    # group's morsels across this many data-parallel replicas of the device
    # mesh ("shards" axis, parallel/mesh.make_mesh). Each morsel's packed
    # upload lands row-sharded (NamedSharding; the narrow-lane buffer
    # shards as equal per-replica payload blocks) and every replica runs
    # the same compiled per-morsel program via shard_map on its rows, with
    # device-local partial aggregation and ONE all_gather of the bounded
    # decomposed partials before the existing host-side final merge.
    # 0 / 1 = off: the single-chip path, bit-identical to before the knob
    # existed. Only out-of-core streamed queries shard; in-core queries
    # keep the single-chip (or mesh_shape/GSPMD) path. Virtual-device
    # testing: XLA_FLAGS=--xla_force_host_platform_device_count=8.
    # Property: nds.tpu.mesh_shards; runners expose --mesh_shards.
    mesh_shards: int = 0
    # rows per morsel when streaming host->device. Sized to amortize the
    # tunnel RTT per dispatch (measured ~6 s/morsel at 1M rows, RTT-bound:
    # an SF100 scan is hundreds of morsels) while keeping the record pass
    # and device working set bounded.
    chunk_rows: int = 1 << 22
    # out-of-core execution: stream aggregates over one large scan in
    # chunk_rows morsels (bounded peak memory; SURVEY.md §5 long-context
    # analog). Eligible plans only; others run in-core. Default ON with a
    # big-table threshold well above SF10 fact sizes, so small scales keep
    # the scan-resident fast path and SF100-class scans stream.
    out_of_core: bool = True
    # a scan streams (rather than pinning device-resident) when its table
    # exceeds this row count
    out_of_core_min_rows: int = 48_000_000
    # accumulated streamed-partial rows that trigger a host-side compaction
    # (partial-schema-preserving re-aggregation): bounds host memory when
    # group cardinality is large (customer-grained q4-class aggregates)
    stream_compact_rows: int = 8_000_000
    # shared-scan morsel fusion: ALL streaming branches of one query that
    # scan the same big table share ONE morsel pass — the union of their
    # pruned column sets packs/uploads once per morsel and each branch reads
    # its subset as zero-copy views of the staged buffer. q9-class plans
    # carry 15 scalar-subquery jobs over store_sales; without sharing the
    # dominant scan+upload cost is paid 15 times per query. Property:
    # nds.tpu.shared_scan; the power runner exposes --no_shared_scan for A/B.
    shared_scan: bool = True
    # fuse a shared-scan group's per-branch partial programs into a single
    # multi-output per-morsel XLA program (the fixed per-dispatch tunnel RTT
    # is then paid once per morsel, not once per branch per morsel) when the
    # group has at most this many branches; larger groups keep per-branch
    # programs over the shared staged buffer (bounded compile time).
    # 0 = fuse unconditionally.
    stream_fusion_max_branches: int = 16
    # narrow-lane packed uploads + encoded execution: streamed morsels pack
    # each column at its minimal physical width (u8/u16/u32/i32 lanes chosen
    # statically from per-table column min/max stats + bit-packed validity,
    # device.plan_lanes/pack_table) instead of widening everything to int64,
    # and columns whose range fits 32 bits execute on i32 device arrays —
    # widening to 64-bit happens only at arithmetic/aggregation sites.
    # 2-4x fewer uploaded bytes per morsel on NDS fact tables, compounding
    # with shared-scan fusion. Property: nds.tpu.narrow_lanes; the power
    # runner exposes --no_narrow_lanes restoring the wide int64 layout
    # bit-identically for A/B runs.
    narrow_lanes: bool = True
    # encoded execution end-to-end (the narrow-lane machinery generalized
    # from width to ENCODING, device.plan_encodings): low-cardinality
    # int/date/decimal columns upload as dictionary CODES on u8/u16 lanes
    # plus a once-per-group host codebook, and clustered columns upload as
    # (value, run-length) pairs expanded on device — chosen statically per
    # scan group from per-table cardinality/run stats
    # (Session.column_enc_stats). Execution stays on codes where legality
    # allows (equality/IN filters remap literals through the dictionary at
    # trace time, join/group keys factorize codes directly, sorts ride the
    # order-preserving dictionary); device.decode_col materializes values
    # only at arithmetic/aggregate/output sites. Bit-identical on/off;
    # requires narrow_lanes (encodings extend the packed layout). Property:
    # nds.tpu.encoded_exec; the power runner exposes --no_encoded_exec and
    # bench.py reads NDS_TPU_BENCH_ENCODED for A/B runs.
    encoded_exec: bool = True
    # late materialization for join-heavy aggregates (planner.
    # _late_materialization): group by the dimension's surrogate join key and
    # gather dimension attributes AFTER aggregation instead of materializing
    # them at fact scale (q72-class 16M-row gathers). Property:
    # nds.tpu.late_materialization; runners expose --no_late_mat for A/B.
    late_materialization: bool = True
    # the rewrite only fires when some scan under the aggregate is at least
    # this big (small plans gain nothing and pay an extra small join + merge
    # aggregate). 0 fires unconditionally.
    late_mat_min_rows: int = 1 << 20
    # TPU Pallas kernels for the sort/group-by/gather hot loops
    # (engine/jax_backend/pallas_kernels.py): a subset of
    # {"sort", "groupby", "gather"} enables the hand-tiled kernel for that
    # op family — (a) VMEM-blocked bitonic segmented sort behind
    # dense_rank/compaction/build-side, (b) fused tile-masked group-by
    # partial aggregation replacing the factorize->scatter-add pipeline,
    # (c) VMEM-staged batched multi-column gather for join/late-mat row
    # materialization. Results are BIT-IDENTICAL to the XLA lowering (the
    # default, empty = all off); program caches key on the choice. On a
    # CPU backend the kernels run in Pallas interpret mode (CI exercises
    # the real kernel bodies); on backends without TPU Pallas the engine
    # logs one warning, falls back to XLA, and records
    # pallas_fallback_reason in last_exec_stats. Property:
    # nds.tpu.pallas_ops=sort,groupby,gather; power --pallas_ops.
    pallas_ops: tuple[str, ...] = ()
    # EXPLAIN ANALYZE: profiled execution mode (obs/profile.py). When on,
    # every sql() statement executes node-by-node EAGERLY through the
    # existing executor (children memoized, so each node's wall is its
    # own work) with exact per-node row counts, output bytes, a static-
    # estimate-vs-actual cardinality audit, and device-memory watermarks
    # — results BIT-IDENTICAL to normal execution (streamed queries run
    # their unchanged morsel path and only read counters). The profile
    # lands on Session.last_profile / ExecStats.node_stats; render via
    # PlanProfile.render() / scripts/explain_report.py. OFF by default:
    # the disabled path adds zero counters and zero per-node work.
    # Property: nds.tpu.profile_plans; power exposes --explain;
    # Session.explain_analyze() profiles one statement without the flag.
    profile_plans: bool = False
    # cardinality-audit threshold: a node whose actual row count diverges
    # from the planner's static estimate by at least this ratio (either
    # direction) is flagged as a misestimate finding
    profile_misestimate_ratio: float = 8.0
    # static plan-IR verification between planner rewrite passes
    # (engine/verify.py via planner.PassPipeline):
    #   "off"      — zero verification cost (bench/production default)
    #   "final"    — verify the fully rewritten plan once per statement
    #   "per-pass" — verify between every rewrite pass, with shared-node
    #                freeze checks and pass attribution (PlanVerifyError
    #                names the node and the pass that introduced it)
    # Property: nds.tpu.verify_plans; NDS_TPU_VERIFY_PLANS sets the default
    # (CI exports "final"; bench runs keep "off").
    verify_plans: str = field(default_factory=lambda: os.environ.get(
        "NDS_TPU_VERIFY_PLANS", "off"))
    # run jitted per-op kernels (True) or pure-numpy fallback (False, debug only)
    use_jax: bool = True
    # compile whole plans to one XLA program on re-execution (record/replay);
    # NDS_TPU_JIT_PLANS=0 disables globally (e.g. compile-bound CI runs)
    jit_plans: bool = field(default_factory=lambda: _env_bool(
        "NDS_TPU_JIT_PLANS", True))
    # CTE-boundary compile segmentation: plans with at least this many nodes
    # split each sufficiently large CTE subtree into its own XLA program
    # whose output stays device-resident (bounds q4-class compile times and
    # shares materialized CTEs across q14/q23 parts). 0 disables.
    # 18: every CTE-bearing NDS plan with a >= 8-node CTE segments — the
    # whole-plan compile pathology (q4/q11/q74 year_total class) scales
    # with the CTE body, not the total node count
    segment_plan_nodes: int = 18
    segment_min_cte_nodes: int = 8
    # device-resident segment outputs kept before LRU eviction
    segment_cache_entries: int = 16
    # row-shard a scan over the mesh only above this row count; smaller
    # tables replicate (the broadcast-join layout: building a replicated
    # join LUT from a SHARDED build side costs dim-sized collectives, so
    # dimension tables — date_dim 73k, item 204k at SF100 — stay whole)
    shard_min_rows: int = 1 << 18
    # HBM budget (GB) for device-resident scans + segment outputs; the
    # least-recently-used unpinned entries evict when the cap is exceeded
    # (reference analog: Spark executors bound storage memory and re-read
    # from the warehouse; here eviction forces a re-upload on next use).
    # 0 disables eviction.
    scan_budget_gb: float = 10.0
    # -- transactional warehouse (warehouse.py _snapshots log) -------------
    # wrap each LF_*/DF_* maintenance function in ONE atomic multi-table
    # warehouse transaction (write-ahead intent record, fsync-atomic
    # CURRENT publication, crash recovery at next open) and PIN reader
    # registrations to the latest published warehouse version, so a
    # statement never sees table A at version k beside table B at k+1.
    # False = the pre-transactional per-table commit path, bit-identical
    # behavior, no _snapshots log ever created, and all three txn_*
    # counters stay zero. Property: nds.tpu.warehouse_transactions.
    warehouse_transactions: bool = True
    # -- semantic result cache (engine/result_cache.py) --------------------
    # cross-client result reuse keyed by parameterized-plan fingerprint +
    # parameter vector: a repeat dashboard load is answered from the cache
    # without touching the planner or the device. Invalidated by per-table
    # catalog generations (Session.table_generation) and the optional TTL;
    # bit-identical to recompute by construction (the entry IS a previous
    # execution's result). All tiers are OPT-IN — the default engine
    # behaves exactly as before. Property: nds.tpu.result_cache; the
    # query service reads these when ServiceConfig.result_cache is unset.
    result_cache: bool = False
    # cached entries before LRU eviction (capacity bound)
    result_cache_entries: int = 256
    # seconds before a cached entry expires (0 = no TTL)
    result_cache_ttl_s: float = 0.0
    # subsumption tier: answer a provably-narrower filter/date-window over
    # the same group keys by re-filtering a cached coarser aggregate on
    # host (the PR 4 verifier fingerprint machinery is the proof engine);
    # falls back to normal execution on any proof failure
    result_cache_subsumption: bool = False
    # incremental view maintenance: entries for decomposable aggregates
    # keep the mergeable partial state streaming._decompose produces, and
    # LF_*/DF_* maintenance deltas UPDATE those partials (merge inserted-
    # row partials; recompute only delta-touched groups for deletes)
    # instead of invalidating — dashboards stay warm across maintenance
    result_cache_ivm: bool = False
    # -- durable query log + system tables (obs/query_log.py, obs/
    #    system_tables.py) ---------------------------------------------------
    # append one flat row per completed statement to the in-memory ring
    # system.query_log serves SQL over (O(row) dict flattening at
    # _finish_exec_stats time, no plan walk). OFF by default: the
    # disabled path is one branch per statement and zero new counters.
    # Property: nds.tpu.query_log; runners expose --query_log PATH
    # (which also sets query_log_path). The system.* catalog itself is
    # always queryable — only the log rows are opt-in.
    query_log: bool = False
    # ring rows kept for live system.query_log SQL
    query_log_capacity: int = 4096
    # opt-in durable JSONL sink ("" = ring only): buffered appends with
    # size-capped rotation (<path>.1, .2, ... monotonic; oldest deleted
    # past query_log_max_files) so a long service run cannot grow the
    # log unboundedly
    query_log_path: str = ""
    query_log_max_bytes: int = 64 << 20
    query_log_max_files: int = 4
    # -- adaptive execution (engine/feedback.py) ---------------------------
    # close the loop from observed actuals to plans: a per-template
    # feedback store records per-node actual row counts (TypeName#k),
    # exact streamed table rows, and per-decision schedule maxima; the
    # NEXT sighting of a template right-sizes its capacity-ladder
    # buckets from them (instead of inflating every cap to the morsel
    # bound) and prefers observed table rows over static est_rows. An
    # observed cap is a CEILING HINT: an under-observed actual raises
    # ReplayMismatch at replay and re-records eagerly — never a wrong
    # answer. OFF by default: no store is constructed, plans and
    # schedules are bit-identical, zero new counters.
    # Property: nds.tpu.adaptive_plans; bench exposes --adaptive /
    # NDS_TPU_BENCH_ADAPTIVE.
    adaptive_plans: bool = False
    # crash-consistent JSON document the store persists to ("" = derive
    # a plan_feedback.json beside query_log_path when that is set,
    # otherwise in-memory only); loaded at session attach
    # Property: nds.tpu.feedback_path
    feedback_path: str = ""
    # drift sentinel: when a template's observed profile diverges from
    # its own history past this ratio (bucket scale, either direction),
    # the store refreshes the history and the next sighting re-records
    # instead of replaying a stale schedule
    # Property: nds.tpu.feedback_drift_ratio
    feedback_drift_ratio: float = 4.0
    # -- resilience (nds_tpu/resilience.py) --------------------------------
    # per-query wall-clock budget in seconds; an overrun abandons the query
    # and records Failed (DeadlineExceeded). 0 = unbounded.
    query_timeout_s: float = 0.0
    # timed attempts per query: transient failures retry with exponential
    # backoff before the query records Failed. 1 = no retry.
    query_attempts: int = 1
    # base backoff between retry attempts (doubles per attempt, capped)
    retry_backoff_s: float = 0.1
    # per-stream wall-clock budget for the throughput supervisor; a stream
    # past it is killed (process mode) or abandoned (thread mode). 0 = none.
    stream_timeout_s: float = 0.0
    # spawn attempts per throughput stream (crash/timeout => restart with
    # backoff until exhausted). 1 = no restart.
    stream_attempts: int = 1
    # armed fault-injection specs, e.g. ("jax.execute:hang:5#1",
    # "arrow.read:raise@0.1") — see resilience.FaultSpec for the grammar;
    # property file: nds.tpu.fault_points=point:action,point:action
    fault_points: tuple[str, ...] = ()

    @staticmethod
    def from_property_file(path: str | None) -> "EngineConfig":
        cfg = EngineConfig()
        for k, v in load_properties(path).items():
            key = k.replace("nds.tpu.", "").replace(".", "_")
            if not hasattr(cfg, key):
                continue
            cur = getattr(cfg, key)
            if isinstance(cur, bool):
                setattr(cfg, key, v.lower() in ("1", "true", "yes"))
            elif isinstance(cur, int):
                setattr(cfg, key, int(v))
            elif isinstance(cur, float):
                setattr(cfg, key, float(v))
            elif isinstance(cur, str):
                setattr(cfg, key, v)
            elif isinstance(cur, tuple):
                parts = [x.strip() for x in v.split(",") if x.strip()]
                try:
                    setattr(cfg, key, tuple(int(x) for x in parts))
                except ValueError:
                    setattr(cfg, key, tuple(parts))
        return cfg


def load_properties(path: str | None) -> dict[str, str]:
    """Parse a java-style key=value property file (reference nds_power.py:306-312)."""
    props: dict[str, str] = {}
    if not path:
        return props
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            name, _, value = line.partition("=")
            props[name.strip()] = value.strip()
    return props


def enable_x64() -> None:
    """Enable 64-bit JAX types; required for int64 keys and f64 decimals on CPU."""
    import jax

    jax.config.update("jax_enable_x64", True)


def apply_decimal(config: "EngineConfig", decimal: str | None) -> None:
    """Apply a runner-level decimal override and its preconditions.

    i64 (exact scaled-int64 decimals, the spec-faithful measured
    configuration; reference DecimalType nds_schema.py:43-47) needs 64-bit
    lanes. One shared helper so every runner enforces the same rules."""
    if decimal:
        if decimal not in ("f64", "i64"):
            raise ValueError(f"unknown decimal physical type {decimal!r} "
                             "(expected f64 or i64)")
        config.decimal_physical = decimal
    if config.decimal_physical == "i64":
        enable_x64()


def maybe_enable_compile_cache() -> None:
    """Default-on persistent compile cache for every runner (power,
    throughput, maintenance, orchestrator) — the reference reuses Spark's
    compiled plans across the whole stream (nds/nds_power.py:124-134);
    recompiling per process would bill XLA compile time to every phase.
    Opt out with NDS_TPU_COMPILE_CACHE=0 (or =off)."""
    raw = os.environ.get("NDS_TPU_COMPILE_CACHE", "1")
    v = raw.lower()
    if v in ("0", "false", "no", "off"):
        return
    if v in ("1", "true", "yes", "on"):
        # explicit default path: enable_compile_cache(None) would re-read
        # the env var and mint a directory literally named after the token
        path = os.path.join(os.path.expanduser("~"), ".cache", "nds_tpu_xla")
    elif os.sep in raw or (os.altsep and os.altsep in raw) or \
            raw.startswith(("~", ".")):
        path = raw           # case-preserved custom directory
    else:
        # a bare unrecognized token ('2', 'enabled') is almost certainly a
        # typo'd boolean — erroring beats minting a directory of that name
        raise ValueError(
            f"NDS_TPU_COMPILE_CACHE={raw!r}: use 0/1/true/false/on/off, "
            "or a directory path (must contain a path separator)")
    enable_compile_cache(path)


def enable_compile_cache(path: str | None = None) -> None:
    """Persist XLA compilations on disk (kernels recur across sessions with
    the same shape buckets, so a query stream's compile cost is paid once).
    """
    import jax

    cache_dir = path or os.environ.get(
        "NDS_TPU_COMPILE_CACHE", os.path.join(os.path.expanduser("~"),
                                              ".cache", "nds_tpu_xla"))
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
