"""Parquet warehouse with snapshot manifests: insert/delete/time-travel.

The capability subset of Iceberg/Delta that the benchmark actually uses
(SURVEY.md §5 checkpoint/resume): ACID-ish table snapshots for the
maintenance test's INSERT/DELETE refresh functions (reference
nds/nds_maintenance.py) and timestamp rollback (reference
nds/nds_rollback.py:36-55 calls Iceberg's rollback_to_timestamp over the 6
fact tables maintenance touches).

Layout per table:
    <root>/<table>/manifest.json         (snapshot list, newest last)
    <root>/<table>/data/part-*.parquet   (immutable data files)
    <root>/<table>/data/<part_col>=<v>/part-*.parquet  (partitioned tables)

A snapshot is {"version", "timestamp_ms", "files": [...]} — files are
relative paths. Writers never mutate data files; insert appends files,
delete rewrites affected files into new ones. Readers pin a snapshot.

Warehouse-level transactions (``<root>/_snapshots/``): per-table
manifests give each table its own history, but a query racing
maintenance could still see table A at generation k and table B at
k+1. The snapshot log makes cross-table commits atomic:

    <root>/_snapshots/v<N>.json              (version record: every
                                              table's manifest version)
    <root>/_snapshots/CURRENT                (the published version —
                                              THE commit point)
    <root>/_snapshots/txn-<id>.inprogress.json  (write-ahead intent:
                                              the base versions an open
                                              transaction started from)

``Warehouse.transaction()`` writes the intent record, lets any number
of per-table commits land, then publishes one version record and swings
``CURRENT`` — all via fsync + atomic rename, so a kill at ANY byte
leaves either the previous or the next snapshot current, never a
blend. Recovery at open truncates per-table manifests back past any
orphaned in-progress transaction (``max(base, published)`` per table —
committed work and non-transactional commits are never touched).
``register_all`` pins reader sessions to the published version, so a
statement resolves every table against ONE warehouse snapshot.
"""
from __future__ import annotations

import glob
import json
import os
import time
import uuid

import pyarrow as pa
import pyarrow.parquet as pq

from .resilience import FAULTS

# fact-table partition keys (reference nds_transcode.py:45-53)
TABLE_PARTITIONING = {
    "catalog_sales": "cs_sold_date_sk",
    "catalog_returns": "cr_returned_date_sk",
    "inventory": "inv_date_sk",
    "store_sales": "ss_sold_date_sk",
    "store_returns": "sr_returned_date_sk",
    "web_sales": "ws_sold_date_sk",
    "web_returns": "wr_returned_date_sk",
}


def _fsync_dir(path: str) -> None:
    """fsync a directory so a just-renamed entry survives power loss
    (best-effort: some filesystems refuse directory fds)."""
    try:
        fd = os.open(path or ".", os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _atomic_write_json(path: str, doc: dict) -> None:
    """Crash-consistent JSON publication: unique temp file + flush +
    fsync(file) + atomic rename + fsync(dir). A reader opening ``path``
    sees either the previous complete document or this one — never a
    prefix; a crash at any byte leaves at worst an orphaned ``*.tmp``
    no reader ever opens."""
    tmp = f"{path}.{uuid.uuid4().hex[:8]}.tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    _fsync_dir(os.path.dirname(path))


def _read_file(path: str) -> pa.Table:
    """Read ONE data file by exact path. A bare pq.read_table infers hive
    partitioning from the `<col>=<val>` directory component and then
    refuses to merge the inferred dictionary field with the identical
    column KEPT in the file — warehouse files always carry their partition
    column, so partition inference must stay off."""
    return pq.read_table(path, partitioning=None)


def _partition_value(path: str):
    """Partition value from a file path's `<col>=<val>` directory component
    (None for unpartitioned files; the null partition yields "null")."""
    d = os.path.basename(os.path.dirname(path))
    if "=" not in d:
        return None
    return d.split("=", 1)[1]


# Per-file [min, max] column metrics land in the manifest at write time
# for EVERY integer/date/decimal column (decimals stored as exact SCALED
# ints — engine units under decimal_physical="i64", JSON-safe either way):
# ticket/order numbers drive metadata-pruned DF_* deletes (the original
# use; reference analog Iceberg column metrics, nds_maintenance.py:146-185),
# and the full-column coverage feeds narrow-lane upload planning
# (Session.column_stats -> device.plan_lanes) without touching data files.
STATS_COLUMN_SUFFIXES = ("_number",)   # kept: delete-prune probe columns


def _stats_value(t: pa.DataType, v):
    """Manifest-serializable engine-unit stat for one arrow scalar value."""
    if pa.types.is_date(t):
        import datetime
        return (v - datetime.date(1970, 1, 1)).days
    if pa.types.is_decimal(t):
        return int(v.scaleb(t.scale))
    return int(v)


def _file_stats(table: pa.Table) -> dict:
    import pyarrow.compute as pc
    out = {}
    for name in table.column_names:
        col = table.column(name)
        t = col.type
        if not (pa.types.is_integer(t) or pa.types.is_date(t)
                or pa.types.is_decimal(t)):
            continue
        mm = pc.min_max(col)
        mn, mx = mm["min"].as_py(), mm["max"].as_py()
        if mn is None:
            continue
        out[name] = [_stats_value(t, mn), _stats_value(t, mx)]
    return out


# Per-file encoding stats (cardinality + run counts, engine units) feed
# encoded-execution planning (Session.column_enc_stats ->
# device.plan_encodings) the way [min, max] feeds lane planning: computed
# once at write time with the data in hand, aggregated manifest-first at
# query time with no data read. Distinct-value lists are capped so the
# manifest stays small — a column past the cap records only the count
# (high cardinality: dictionary encoding would not pay anyway).
ENC_MANIFEST_MAX_DISTINCT = 1024


def _enc_file_stats(table: pa.Table) -> dict:
    from .engine.arrow_bridge import column_enc_stat

    out = {}
    for name in table.column_names:
        st = None
        try:
            st = column_enc_stat(table.column(name), dec_as_int=True)
        except Exception:
            st = None       # stats are an optimization, never a failure
        if st is None:
            continue
        dv = st["distinct"]
        ent = {"runs": int(st["runs"]), "rows": int(st["rows"]),
               "distinct_count": None if dv is None else int(len(dv))}
        if dv is not None and len(dv) <= ENC_MANIFEST_MAX_DISTINCT:
            ent["distinct"] = [int(v) for v in dv]
        out[name] = ent
    return out


class WarehouseTable:
    def __init__(self, root: str, name: str, warehouse=None):
        self.dir = os.path.join(root, name)
        self.name = name
        self.manifest_path = os.path.join(self.dir, "manifest.json")
        #: owning Warehouse (set by Warehouse.table): commits notify its
        #: open transaction; a bare WarehouseTable commits untracked
        self._warehouse = warehouse

    # -- manifest ------------------------------------------------------------
    def _load_doc(self) -> dict:
        if not os.path.exists(self.manifest_path):
            return {"table": self.name, "snapshots": [], "file_stats": {},
                    "enc_stats": {}}
        # manifests are published fsync-atomically (_store_doc), so a
        # torn read is impossible by construction — a decode failure is
        # real corruption and fails loudly, naming the file
        try:
            with open(self.manifest_path) as f:
                doc = json.load(f)
        except json.JSONDecodeError as e:
            raise RuntimeError(
                f"corrupt warehouse manifest {self.manifest_path}: "
                f"{e}") from e
        doc.setdefault("file_stats", {})
        doc.setdefault("enc_stats", {})
        return doc

    def _load(self) -> list[dict]:
        return self._load_doc()["snapshots"]

    def _store_doc(self, doc: dict) -> None:
        FAULTS.fire("manifest.write", self.name)
        _atomic_write_json(self.manifest_path, doc)

    def _store(self, snapshots: list[dict]) -> None:
        doc = self._load_doc()
        doc["snapshots"] = snapshots
        self._store_doc(doc)

    def _commit(self, files: list[str]) -> dict:
        # an open warehouse transaction hears about the commit BEFORE any
        # byte lands (txn.between_tables fires here on the second
        # distinct table — a kill leaves table A committed-but-
        # unpublished and this table untouched; rollback/recovery
        # truncates A back to its base)
        if self._warehouse is not None:
            self._warehouse._txn_touch(self.name)
        doc = self._load_doc()
        snapshots = doc["snapshots"]
        snap = {"version": len(snapshots) + 1,
                "timestamp_ms": int(time.time() * 1000),
                "files": sorted(files)}
        snapshots.append(snap)
        # stats of files written since the last commit; never GC'd — a
        # rollback snapshot may resurrect any older file
        doc["file_stats"].update(getattr(self, "_new_stats", {}))
        self._new_stats = {}
        doc["enc_stats"].update(getattr(self, "_new_enc_stats", {}))
        self._new_enc_stats = {}
        self._store_doc(doc)
        return snap

    def manifest_version(self) -> int:
        """Number of committed snapshots (the table's manifest version;
        0 = no snapshot yet)."""
        return len(self._load())

    def files_at_version(self, version: int) -> list[str]:
        """Absolute data-file paths of manifest snapshot ``version``
        (1-based; snapshot versions are sequential by construction)."""
        snaps = self._load()
        if not 1 <= version <= len(snaps):
            raise ValueError(
                f"table {self.name} has no manifest version {version} "
                f"(have 1..{len(snaps)})")
        return [os.path.join(self.dir, f)
                for f in snaps[version - 1]["files"]]

    def file_stats(self) -> dict:
        """{relative file path: {column: [min, max]}} for files written
        with stats (older warehouses: empty — those files never prune)."""
        return self._load_doc()["file_stats"]

    def column_stats(self, files, dec_as_int: bool = False) -> dict:
        """Table-wide {column: (lo, hi)} over the given snapshot files, in
        engine units. Manifest-recorded per-file stats aggregate for free;
        columns some file lacks stats for (older warehouses, partial
        manifests) fall back to ONE parquet-metadata pass — still no data
        read. Feeds narrow-lane upload planning (device.plan_lanes)."""
        from .engine.arrow_bridge import parquet_column_stats

        rec = self.file_stats()
        per_file = [rec.get(os.path.relpath(f, self.dir)) for f in files]
        agg: dict = {}
        if per_file and all(p is not None for p in per_file):
            common = set(per_file[0])
            for p in per_file[1:]:
                common &= set(p)
            for col in common:
                agg[col] = (min(p[col][0] for p in per_file),
                            max(p[col][1] for p in per_file))
        if not agg and files:
            agg = parquet_column_stats(list(files), dec_as_int)
        return agg

    def enc_stats(self) -> dict:
        """{relative file path: {column: {distinct/distinct_count/runs/
        rows}}} for files written with encoding stats."""
        return self._load_doc()["enc_stats"]

    def column_enc_stats(self, files) -> dict:
        """Table-wide encoding stats over the given snapshot files in the
        Session.column_enc_stats shape: {column: {"distinct": sorted int64
        array or None, "runs": int}}. Manifest-first, no data read; a
        column missing stats in ANY file is omitted (no encoding — always
        safe). Distinct sets union (None when any file only recorded the
        count — high cardinality); run counts SUM, which bounds the runs
        of any morsel window under any file order."""
        import numpy as np

        rec = self.enc_stats()
        per_file = [rec.get(os.path.relpath(f, self.dir)) for f in files]
        if not per_file or any(p is None for p in per_file):
            return {}
        common = set(per_file[0])
        for p in per_file[1:]:
            common &= set(p)
        out: dict = {}
        for col in common:
            ents = [p[col] for p in per_file]
            distinct = None
            if all(e.get("distinct") is not None for e in ents):
                distinct = np.unique(np.concatenate(
                    [np.asarray(e["distinct"], dtype=np.int64)
                     for e in ents]))
            out[col] = {"distinct": distinct,
                        "runs": sum(int(e["runs"]) for e in ents),
                        "rows": sum(int(e.get("rows", 0)) for e in ents)}
        return out

    def exists(self) -> bool:
        return os.path.exists(self.manifest_path)

    def current_files(self) -> list[str]:
        snaps = self._load()
        if not snaps:
            return []
        return [os.path.join(self.dir, f) for f in snaps[-1]["files"]]

    # -- writes --------------------------------------------------------------
    def _write_file(self, table: pa.Table, partition_val=None) -> str:
        base = f"part-{uuid.uuid4().hex[:12]}.parquet"
        if partition_val is not None:
            part_col = TABLE_PARTITIONING[self.name]
            sub = f"{part_col}={partition_val}"
            os.makedirs(os.path.join(self.dir, "data", sub), exist_ok=True)
            rel = os.path.join("data", sub, base)
        else:
            os.makedirs(os.path.join(self.dir, "data"), exist_ok=True)
            rel = os.path.join("data", base)
        pq.write_table(table, os.path.join(self.dir, rel))
        stats = _file_stats(table)
        if stats:
            if not hasattr(self, "_new_stats"):
                self._new_stats = {}
            self._new_stats[rel] = stats
        enc = _enc_file_stats(table)
        if enc:
            if not hasattr(self, "_new_enc_stats"):
                self._new_enc_stats = {}
            self._new_enc_stats[rel] = enc
        return rel

    def _partitioned_files(self, table: pa.Table) -> list[str]:
        """Write one file per partition value (partition column KEPT in the
        file so explicit-file reads need no hive discovery).

        Sort-then-slice: one sort by the partition key, then zero-copy
        contiguous slices per value — O(n log n), not O(values * n) repeated
        full-table filters (the reference's transcode repartitions by the
        same key before writing, nds_transcode.py:68-151).
        """
        part_col = TABLE_PARTITIONING.get(self.name)
        if part_col is None or part_col not in table.column_names:
            return [self._write_file(table)]
        import numpy as np
        import pyarrow.compute as pc
        sorted_tbl = table.sort_by(part_col)  # nulls last (pyarrow default)
        col = sorted_tbl.column(part_col)
        vals = col.to_numpy(zero_copy_only=False)
        null_mask = np.asarray(pc.is_null(col))
        n = len(vals)
        first_null = int(np.argmax(null_mask)) if null_mask.any() else n
        body = vals[:first_null]
        bounds = np.flatnonzero(np.concatenate(
            [[True], body[1:] != body[:-1]])) if first_null else np.empty(0, int)
        files = []
        for i, start in enumerate(bounds):
            end = bounds[i + 1] if i + 1 < len(bounds) else first_null
            # name the partition from the arrow scalar: to_numpy turns a
            # nullable int column into float64, and "sk=2450815.0" would be
            # a different layout than the int path ever produced
            part_val = col[int(start)].as_py()
            files.append(self._write_file(
                sorted_tbl.slice(start, int(end) - int(start)), part_val))
        if first_null < n:
            files.append(self._write_file(
                sorted_tbl.slice(first_null, n - first_null), "null"))
        return files

    def create(self, table: pa.Table, partition: bool = True) -> dict:
        os.makedirs(self.dir, exist_ok=True)
        files = (self._partitioned_files(table) if partition
                 else [self._write_file(table)])
        return self._commit(files)

    def insert(self, table: pa.Table, partition: bool = True) -> dict:
        """Append rows as new files (Iceberg-style append snapshot)."""
        old = self._load()[-1]["files"] if self._load() else []
        files = (self._partitioned_files(table) if partition
                 else [self._write_file(table)])
        return self._commit(old + files)

    def delete_where(self, keep_filter, batch_rows: int = 4_000_000,
                     part_prune=None, stats_prune=None) -> dict:
        """Rewrite files keeping rows where keep_filter(table) is True.

        keep_filter: callable(pa.Table) -> pa.BooleanArray of rows to KEEP.
        Files are processed in BATCHES of at most `batch_rows` rows, so peak
        memory is bounded at benchmark scale (SF10k store_sales does not fit
        on one host) while per-call overhead stays amortized when a table is
        spread over thousands of small partition files. The predicate is
        row-wise, so batch boundaries cannot change results. Files with
        nothing deleted are reused untouched; the rest are rewritten from
        their kept slice.

        part_prune: optional callable(partition-value string or None) ->
        bool; False promises the file contains no rows to delete, so it is
        kept untouched WITHOUT being read. The DF_* date-window deletes
        touch a handful of the date partitions the fact tables are laid out
        by (reference analog: Iceberg metadata-pruned deletes,
        nds/nds_maintenance.py:146-185).

        stats_prune: optional callable(per-file stats dict or None) ->
        bool; False promises the file's column [min, max] ranges exclude
        every deletable row (ticket-number IN-subquery deletes — the other
        half of the reference's Iceberg metric pruning). Files without
        recorded stats always process.
        """
        import pyarrow.compute as pc

        paths = self.current_files()
        if not paths:
            return self._commit([])

        new_files: list[str] = []
        if part_prune is not None or stats_prune is not None:
            stats = self.file_stats() if stats_prune is not None else {}
            kept_paths = []
            for path in paths:
                rel = os.path.relpath(path, self.dir)
                process = True
                if part_prune is not None and \
                        not part_prune(_partition_value(path)):
                    process = False
                if process and stats_prune is not None and \
                        not stats_prune(stats.get(rel)):
                    process = False
                if process:
                    kept_paths.append(path)
                else:
                    new_files.append(rel)
            paths = kept_paths
            if not paths:
                return self._commit(new_files)

        def flush(batch_paths, batch_tables):
            whole = batch_tables[0] if len(batch_tables) == 1 else \
                pa.concat_tables(batch_tables, promote_options="permissive")
            keep = pa.array(keep_filter(whole), type=pa.bool_())
            offset = 0
            for path, t in zip(batch_paths, batch_tables):
                part = keep.slice(offset, t.num_rows)
                offset += t.num_rows
                n_keep = pc.sum(pc.cast(part, pa.int64())).as_py() or 0
                rel = os.path.relpath(path, self.dir)
                if n_keep == t.num_rows:
                    new_files.append(rel)
                    continue
                if n_keep == 0:
                    continue
                kept = t.filter(part)
                base = f"part-{uuid.uuid4().hex[:12]}.parquet"
                new_rel = os.path.join(os.path.dirname(rel), base)
                pq.write_table(kept, os.path.join(self.dir, new_rel))
                st = _file_stats(kept)
                if st:
                    # rewritten files keep pruning on later delete rounds
                    if not hasattr(self, "_new_stats"):
                        self._new_stats = {}
                    self._new_stats[new_rel] = st
                enc = _enc_file_stats(kept)
                if enc:
                    if not hasattr(self, "_new_enc_stats"):
                        self._new_enc_stats = {}
                    self._new_enc_stats[new_rel] = enc
                new_files.append(new_rel)

        batch_paths: list[str] = []
        batch_tables: list[pa.Table] = []
        rows = 0
        for path in paths:
            t = _read_file(path)
            batch_paths.append(path)
            batch_tables.append(t)
            rows += t.num_rows
            if rows >= batch_rows:
                flush(batch_paths, batch_tables)
                batch_paths, batch_tables, rows = [], [], 0
        if batch_paths:
            flush(batch_paths, batch_tables)
        return self._commit(new_files)

    # -- time travel ---------------------------------------------------------
    def rollback_to_timestamp(self, ts_ms: int) -> dict:
        """New snapshot restoring the latest state at or before ts_ms
        (reference nds_rollback.py rolls the 6 maintenance-touched fact
        tables back to the pre-maintenance timestamp)."""
        snaps = self._load()
        target = None
        for s in snaps:
            if s["timestamp_ms"] <= ts_ms:
                target = s
        if target is None:
            raise ValueError(f"no snapshot at or before {ts_ms}")
        return self._commit(list(target["files"]))

    def read(self) -> pa.Table:
        files = self.current_files()
        if not files:
            raise FileNotFoundError(f"table {self.name} has no snapshot")
        return pa.concat_tables([_read_file(f) for f in files],
                                promote_options="permissive")


class WarehouseTransaction:
    """One atomic multi-table commit over a Warehouse (single writer).

    ``__enter__`` writes the fsync-atomic intent record
    (``txn-<id>.inprogress.json``) naming every table's base manifest
    version; per-table commits inside the body append manifests as
    usual; ``__exit__`` publishes ONE version record and swings
    ``CURRENT`` (the commit point), or — on any exception, including a
    fired ``txn.commit``/``txn.between_tables`` fault — truncates every
    touched manifest back to its base, so the previous snapshot stays
    current. A kill at any point is repaired by recovery at next open.
    """

    def __init__(self, warehouse: "Warehouse", committer: str = ""):
        self.wh = warehouse
        self.committer = committer
        self.txn_id = uuid.uuid4().hex[:12]
        self.base: dict[str, int] = {}
        self.touched: set[str] = set()
        self._path = os.path.join(warehouse.snapshots_dir,
                                  f"txn-{self.txn_id}.inprogress.json")

    def __enter__(self) -> "WarehouseTransaction":
        if self.wh._txn is not None:
            raise RuntimeError("warehouse transaction already open")
        os.makedirs(self.wh.snapshots_dir, exist_ok=True)
        self.base = {n: self.wh.table(n).manifest_version()
                     for n in self.wh.table_names()}
        _atomic_write_json(self._path, {
            "txn": self.txn_id, "committer": self.committer,
            "pid": os.getpid(),
            "started_ms": int(time.time() * 1000), "base": self.base})
        self.wh._txn = self
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if exc_type is not None:
            self._rollback()
            return False
        try:
            self._commit()
        except BaseException:
            self._rollback()
            raise
        return False

    def _commit(self) -> None:
        from .obs.flight import FLIGHT
        from .obs.metrics import TXN_COMMITS

        FAULTS.fire("txn.commit", self.committer or self.txn_id)
        version = self.wh.current_version() + 1
        tables = {n: self.wh.table(n).manifest_version()
                  for n in self.wh.table_names()}
        tables = {n: v for n, v in tables.items() if v > 0}
        _atomic_write_json(
            os.path.join(self.wh.snapshots_dir, f"v{version}.json"),
            {"version": version, "timestamp_ms": int(time.time() * 1000),
             "committer": self.committer, "tables": tables})
        # THE commit point: everything before this rename rolls back on
        # recovery, everything after survives
        _atomic_write_json(self.wh.current_path, {"version": version})
        try:
            os.unlink(self._path)
        except FileNotFoundError:
            pass
        self.wh._txn = None
        TXN_COMMITS.inc()
        FLIGHT.record("txn_commit", committer=self.committer,
                      version=version, tables=len(self.touched))

    def _rollback(self) -> None:
        from .obs.flight import FLIGHT
        from .obs.metrics import TXN_ROLLBACKS

        clean = True
        for name in sorted(set(self.base) | set(self.wh.table_names())):
            wt = self.wh.table(name)
            if not wt.exists():
                continue
            try:
                doc = wt._load_doc()
                target = self.base.get(name, 0)
                if len(doc["snapshots"]) > target:
                    doc["snapshots"] = doc["snapshots"][:target]
                    wt._store_doc(doc)
            except BaseException:
                # a fault firing mid-rollback (manifest.write armed):
                # keep the intent record — recovery at next open
                # finishes the truncation from the same base map
                clean = False
        if clean:
            try:
                os.unlink(self._path)
            except FileNotFoundError:
                pass
        self.wh._txn = None
        TXN_ROLLBACKS.inc()
        FLIGHT.record("txn_rollback", committer=self.committer,
                      tables=len(self.touched), clean=clean)


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except OSError:
        return False
    return True


class Warehouse:
    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)
        self.snapshots_dir = os.path.join(root, "_snapshots")
        self.current_path = os.path.join(self.snapshots_dir, "CURRENT")
        #: the open WarehouseTransaction (single writer per Warehouse)
        self._txn: WarehouseTransaction | None = None
        # warehouses that never opened a transaction have no _snapshots
        # directory and skip recovery entirely (bit-identical legacy path)
        if os.path.isdir(self.snapshots_dir):
            self._recover()

    def table(self, name: str) -> WarehouseTable:
        return WarehouseTable(self.root, name, warehouse=self)

    def table_names(self) -> list[str]:
        return sorted(
            os.path.basename(os.path.dirname(m)) for m in
            glob.glob(os.path.join(self.root, "*", "manifest.json")))

    # -- warehouse-level snapshot log ---------------------------------------
    def transaction(self, committer: str = "") -> WarehouseTransaction:
        """Open one atomic cross-table commit (context manager)."""
        return WarehouseTransaction(self, committer)

    def _txn_touch(self, name: str) -> None:
        """A per-table commit is about to land: record it on the open
        transaction and fire ``txn.between_tables`` when a SECOND
        distinct table joins (the mid-commit kill window campaigns
        target). No-op without an open transaction."""
        txn = self._txn
        if txn is None:
            return
        if name not in txn.touched:
            if txn.touched:
                FAULTS.fire("txn.between_tables", name)
            txn.touched.add(name)

    def current_version(self) -> int:
        """The published warehouse version (0 = no snapshot log)."""
        try:
            with open(self.current_path) as f:
                return int(json.load(f)["version"])
        except (FileNotFoundError, json.JSONDecodeError, KeyError,
                ValueError):
            return 0

    def versions(self) -> list[int]:
        """Published warehouse versions, ascending (orphans excluded)."""
        cur = self.current_version()
        out = []
        for p in glob.glob(os.path.join(self.snapshots_dir, "v*.json")):
            try:
                v = int(os.path.basename(p)[1:-5])
            except ValueError:
                continue
            if 1 <= v <= cur:
                out.append(v)
        return sorted(out)

    def version_record(self, version: int) -> dict:
        """One version record: {"version", "timestamp_ms", "committer",
        "tables": {name: manifest version}}."""
        path = os.path.join(self.snapshots_dir, f"v{version}.json")
        with open(path) as f:
            return json.load(f)

    def snapshot_records(self) -> list[dict]:
        """Every published version record, ascending (system.snapshots
        and the rollback CLI's --list view)."""
        return [self.version_record(v) for v in self.versions()]

    def rollback_to_version(self, version: int,
                            committer: str = "") -> int:
        """Restore every table to its state at warehouse ``version`` via
        one new atomic commit (Iceberg-style: history only grows — the
        restored state becomes the NEXT published version). Tables
        created after ``version`` restore to empty."""
        from .obs.flight import FLIGHT
        from .obs.metrics import TXN_ROLLBACKS

        rec = self.version_record(version)
        with self.transaction(committer=committer
                              or f"rollback:v{version}"):
            for name in self.table_names():
                wt = self.table(name)
                target = rec["tables"].get(name, 0)
                files = (wt._load()[target - 1]["files"] if target
                         else [])
                wt._commit(list(files))
        TXN_ROLLBACKS.inc()
        FLIGHT.record("txn_rollback", committer=committer or "rollback",
                      to_version=version)
        return self.current_version()

    def _recover(self) -> None:
        """Discard orphaned partial commits left by a crash: for every
        leftover in-progress record (whose writer process is gone), each
        table truncates back to ``max(base, published)`` — uncommitted
        transactional work rolls back, anything a published version (or
        a non-transactional commit predating the transaction) names is
        never touched. Version records past CURRENT (a kill between the
        record write and the CURRENT swing) are deleted."""
        leftovers = sorted(glob.glob(os.path.join(
            self.snapshots_dir, "txn-*.inprogress.json")))
        cur = self.current_version()
        published: dict[str, int] = {}
        if cur:
            published = {str(k): int(v) for k, v in
                         self.version_record(cur)["tables"].items()}
        for path in leftovers:
            try:
                with open(path) as f:
                    rec = json.load(f)
                base = {str(k): int(v)
                        for k, v in rec.get("base", {}).items()}
            except (json.JSONDecodeError, ValueError, OSError):
                rec, base = {}, {}
            # a LIVE writer's open transaction is not a crash: skip it
            # (its own commit/rollback path owns the record)
            pid = rec.get("pid")
            if pid is not None and _pid_alive(int(pid)):
                continue
            for name in set(base) | set(self.table_names()):
                wt = self.table(name)
                if not wt.exists():
                    continue
                target = max(base.get(name, 0), published.get(name, 0))
                doc = wt._load_doc()
                if len(doc["snapshots"]) > target:
                    doc["snapshots"] = doc["snapshots"][:target]
                    wt._store_doc(doc)
            try:
                os.unlink(path)
            except FileNotFoundError:
                pass
            from .obs.flight import FLIGHT
            from .obs.metrics import TXN_RECOVERIES
            TXN_RECOVERIES.inc()
            FLIGHT.record("txn_recover", committer=rec.get("committer"),
                          txn=rec.get("txn"), base_tables=len(base))
        # orphaned version records past the commit point
        for p in glob.glob(os.path.join(self.snapshots_dir, "v*.json")):
            try:
                v = int(os.path.basename(p)[1:-5])
            except ValueError:
                continue
            if v > cur:
                try:
                    os.unlink(p)
                except FileNotFoundError:
                    pass

    def _pin_record(self, session, at_version: int | None):
        """The version record reader registrations resolve against, or
        None for manifest-latest (no snapshot log, pinning disabled, or
        this Warehouse owns the OPEN transaction — the writer session
        must see its own uncommitted state)."""
        if at_version is not None:
            return self.version_record(at_version)
        if self._txn is not None:
            return None
        if not getattr(session.config, "warehouse_transactions", True):
            return None
        cur = self.current_version()
        return self.version_record(cur) if cur else None

    def register_all(self, session, est_rows: dict[str, int] | None = None,
                     at_version: int | None = None):
        """Register every warehouse table on an engine Session.

        With a published snapshot log (and warehouse_transactions on),
        registrations PIN to one warehouse version: every table's files
        come from the same version record, so a statement never sees
        table A at version k beside table B at k+1. ``at_version`` time-
        travels the whole warehouse to an older published version."""
        import pyarrow.dataset as pa_dataset

        from .engine import arrow_bridge

        pin = self._pin_record(session, at_version)
        snap_versions = getattr(session, "_table_snapshot_versions", None)
        for name in self.table_names():
            wt = self.table(name)
            if pin is not None:
                mv = pin["tables"].get(name, 0)
                if snap_versions is not None:
                    if mv > 0:
                        snap_versions[name] = mv
                    else:
                        snap_versions.pop(name, None)
                if mv <= 0:
                    continue        # table not in the pinned snapshot
                files = wt.files_at_version(mv)
            else:
                if snap_versions is not None:
                    snap_versions.pop(name, None)
                files = wt.current_files()
            if not files:
                continue
            # skip tables whose snapshot is UNCHANGED since this session
            # registered them: the loaders still point at the same
            # immutable files, so re-registering would only bump the
            # table's generation and cold every cache keyed on it (device
            # scan cache, stream cache, result cache). A maintenance
            # INSERT into store_sales then re-registers ONE table, not 24.
            dec = session._dec_as_int()
            src_key = (tuple(files), dec,
                       (est_rows or {}).get(name))
            if name in session._schemas and \
                    session._source_files.get(name) == src_key:
                continue
            # dictionary-encoded string chunks pass through as codes +
            # dictionary (arrow_bridge.parquet_dataset_format): the staging
            # thread stops re-running dictionary_encode() per morsel
            fmt = arrow_bridge.parquet_dataset_format(files) or "parquet"
            dataset = pa_dataset.dataset(files, format=fmt)
            names, dtypes = arrow_bridge.engine_schema(dataset.schema, dec)
            session._schemas[name] = (names, dtypes)
            # NDS dimension surrogate keys are unique by spec: declare them
            # so the late-materialization legality analysis sees warehouse
            # registrations exactly like register_parquet ones
            session._set_unique_cols(name, names, None)
            session._est_rows[name] = (est_rows or {}).get(
                name, dataset.count_rows())

            def load(columns=None, ds=dataset, dec=dec):
                cols = list(columns) if columns is not None else None
                return arrow_bridge.from_arrow(ds.to_table(columns=cols), dec)
            session._loaders[name] = load

            def batches(columns, ds=dataset):
                cols = list(columns) if columns is not None else None
                yield from ds.to_batches(columns=cols)
            session._batch_sources[name] = batches
            session._stats_sources[name] = \
                lambda wt=wt, files=tuple(files), dec=dec: \
                wt.column_stats(files, dec)
            session._enc_stats_sources[name] = session._manifest_enc_source(
                wt, tuple(files), dataset, dec)
            session._source_files[name] = src_key
            session._drop_cached(name)
            session._bump_generation(name)
        if hasattr(session, "_warehouse_version"):
            session._warehouse_version = pin["version"] if pin else None
