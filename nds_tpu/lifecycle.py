"""Scored lifecycle runner: the reference's WHOLE deliverable, one command.

PAPER.md §0 defines the benchmark as a lifecycle — datagen → load
(transcode) → query-stream generation → power → throughput ×2 →
maintenance ×2 → geometric-mean score — and until this module nothing
ran it end to end: ``nds_tpu/bench.py`` is YAML-driven with manual skip
flags, and a crash anywhere lost the run. This runner adds the two
properties a multi-hour scored run actually needs:

- **per-phase checkpointing** — ``lifecycle_state.json`` in the report
  dir records each phase's status/elapsed atomically; a crash (or an
  injected fault) mid-lifecycle resumes with ``--resume`` from the last
  completed phase, and the power phase additionally resumes at QUERY
  granularity through its flushed partial time log. The score is always
  recomputed from the phase time logs, so a resumed run's per-phase
  timing-log inputs are identical to an uninterrupted run's.
- **chaos mode** — the two throughput rounds run maintenance
  CONCURRENTLY with service-mode query streams against the shared
  warehouse (the scenario pinned snapshots and warehouse generations
  exist for) under an armed fault campaign, with the flight recorder
  dumping per firing; phase failures retry under ``phase_attempts``
  (counted in ``lifecycle_phase_retries``).

``scripts/run_lifecycle.py`` is the CLI.
"""
from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import asdict, dataclass
from typing import Optional

from . import datagen, maintenance, streams, transcode
from .bench import (get_load_end_timestamp, get_load_time,
                    get_maintenance_time, get_perf_metric, get_power_time,
                    get_stream_range, round_up_tenth, write_metrics_report)
from .obs.flight import FLIGHT
from .obs.metrics import LIFECYCLE_PHASE_RETRIES
from .power import run_query_stream
from .resilience import FAULTS, FaultSpec
from .throughput import run_throughput, stream_log_path, throughput_elapsed

#: phase order; each is checkpointed in lifecycle_state.json
PHASES = ("datagen", "load", "streams", "power", "throughput1",
          "maintenance1", "throughput2", "maintenance2")

STATE_VERSION = 1


@dataclass
class LifecycleConfig:
    """One scored run's shape. Paths default under ``report_dir`` so a
    single ``--sf``/``--report_dir`` pair is a complete invocation."""
    scale_factor: float = 0.01
    num_streams: int = 3            # odd >= 3; stream 0 is the power stream
    report_dir: str = "./lifecycle_report"
    data_path: str = ""             # default: <report_dir>/data
    warehouse_path: str = ""        # default: <report_dir>/warehouse
    stream_dir: str = ""            # default: <report_dir>/streams
    datagen_parallel: int = 2
    use_decimal: bool = False
    decimal: Optional[str] = None
    backend: Optional[str] = None
    sub_queries: Optional[list] = None
    warmup: int = 0
    rngseed: Optional[int] = None   # None: seeded by the load end stamp
    throughput_mode: str = "thread"
    stream_timeout: Optional[float] = None
    #: attempts per phase; failures beyond the first count into the
    #: lifecycle_phase_retries metric
    phase_attempts: int = 1
    #: durable query log (obs/query_log.py): when set, every statement
    #: any phase completes appends one flat JSONL row here — the scored
    #: run's self-describing artifact for scripts/slo_report.py. "" = off
    query_log: str = ""
    # -- chaos mode ----------------------------------------------------------
    #: run maintenance concurrently with SERVICE-mode query streams under
    #: an armed fault campaign during both throughput rounds
    chaos: bool = False
    chaos_seed: int = 0xC0FFEE
    #: the commit-path points (manifest.write / txn.*) kill maintenance
    #: transactions mid-commit — recovery at the next warehouse open is
    #: what makes the phase re-enterable
    chaos_points: tuple = ("device.put", "jax.compile", "jax.execute",
                           "query.run", "txn.between_tables")
    chaos_times_per_point: int = 2

    def __post_init__(self):
        rd = self.report_dir
        self.data_path = self.data_path or os.path.join(rd, "data")
        self.warehouse_path = self.warehouse_path \
            or os.path.join(rd, "warehouse")
        self.stream_dir = self.stream_dir or os.path.join(rd, "streams")

    def fingerprint(self) -> dict:
        """The resume-compatibility surface: a state file written by a
        run with different workload-shaping knobs must not be resumed."""
        return {"scale_factor": self.scale_factor,
                "num_streams": self.num_streams,
                "use_decimal": self.use_decimal,
                "decimal": self.decimal,
                "backend": self.backend,
                "sub_queries": list(self.sub_queries or []),
                "chaos": self.chaos}


class LifecycleStateError(RuntimeError):
    """The state file refuses the requested run (exists without --resume,
    or was written by an incompatible configuration)."""


def _refresh_dir(data_path: str, stream: int) -> str:
    return f"{data_path.rstrip('/')}_update_{stream}"


class LifecycleRunner:
    """Run (or resume) one scored lifecycle; see the module docstring."""

    def __init__(self, config: LifecycleConfig):
        self.cfg = config
        self.state_path = os.path.join(config.report_dir,
                                       "lifecycle_state.json")
        self.state: dict = {"version": STATE_VERSION,
                            "config": config.fingerprint(),
                            "phases": {}}

    # -- state ---------------------------------------------------------------
    def _save_state(self) -> None:
        os.makedirs(self.cfg.report_dir, exist_ok=True)
        tmp = self.state_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.state, f, indent=2, sort_keys=True)
        os.replace(tmp, self.state_path)   # atomic: a crash never corrupts

    def _load_state(self) -> None:
        with open(self.state_path) as f:
            self.state = json.load(f)
        if self.state.get("version") != STATE_VERSION:
            raise LifecycleStateError(
                f"state {self.state_path} has version "
                f"{self.state.get('version')}, expected {STATE_VERSION}")
        if self.state.get("config") != self.cfg.fingerprint():
            raise LifecycleStateError(
                f"state {self.state_path} was written by an incompatible "
                f"configuration {self.state.get('config')!r}; use a fresh "
                f"report_dir or matching flags")

    def _phase_done(self, name: str) -> bool:
        return self.state["phases"].get(name, {}).get("status") == "done"

    # -- phase bodies --------------------------------------------------------
    def _phase_datagen(self) -> None:
        cfg = self.cfg
        datagen.generate_data_local(cfg.data_path, cfg.scale_factor,
                                    cfg.datagen_parallel, overwrite=True)
        for s in range(1, cfg.num_streams):
            datagen.generate_data_local(
                _refresh_dir(cfg.data_path, s), cfg.scale_factor,
                cfg.datagen_parallel, update=s, overwrite=True)

    def _load_report(self) -> str:
        return os.path.join(self.cfg.report_dir, "load_report.txt")

    def _phase_load(self) -> None:
        transcode.transcode(self.cfg.data_path, self.cfg.warehouse_path,
                            self._load_report(),
                            use_decimal=self.cfg.use_decimal)

    def _phase_streams(self) -> None:
        cfg = self.cfg
        seed = cfg.rngseed
        if seed is None:    # the reference contract: seeded by load end
            seed = get_load_end_timestamp(self._load_report())
        streams.generate_query_streams(cfg.stream_dir,
                                       streams=cfg.num_streams,
                                       rngseed=int(seed))

    def _power_log(self) -> str:
        return os.path.join(self.cfg.report_dir, "power.csv")

    def _phase_power(self) -> None:
        cfg = self.cfg
        run_query_stream(
            cfg.warehouse_path,
            os.path.join(cfg.stream_dir, "query_0.sql"),
            self._power_log(), input_format="parquet",
            json_summary_folder=os.path.join(cfg.report_dir, "json"),
            sub_queries=cfg.sub_queries, backend=cfg.backend,
            warmup=cfg.warmup, decimal=cfg.decimal,
            # query-granular resume: the phase-level checkpoint re-enters
            # here after a crash and the flushed partial log carries on
            resume=True)

    def _dm_log(self, stream: int) -> str:
        return os.path.join(self.cfg.report_dir,
                            f"maintenance_{stream}.csv")

    def _run_maintenance_round(self, ids: list) -> None:
        """Crash-RESUMABLE: each refresh function commits one atomic
        warehouse transaction, so a kill mid-round leaves the previous
        published snapshot current and re-entry (the phase-attempts
        loop, or a whole fresh lifecycle run resuming from checkpoints)
        starts by discarding the orphaned partial commit at warehouse
        open — ``txn_recoveries`` below counts exactly those sweeps."""
        from .obs.metrics import METRICS

        before = METRICS.snapshot()
        for s in ids:
            maintenance.run_maintenance(
                self.cfg.warehouse_path,
                _refresh_dir(self.cfg.data_path, s), self._dm_log(s),
                backend=self.cfg.backend, decimal=self.cfg.decimal)
        delta = METRICS.delta(before)
        self.state.setdefault("txn", {})
        for k in ("txn_commits", "txn_rollbacks", "txn_recoveries"):
            self.state["txn"][k] = (self.state["txn"].get(k, 0)
                                    + delta.get(k, 0))

    def _phase_throughput(self, rnd: int) -> None:
        cfg = self.cfg
        ids = get_stream_range(cfg.num_streams, rnd)
        if not cfg.chaos:
            run_throughput(cfg.warehouse_path, cfg.stream_dir, ids,
                           cfg.report_dir, input_format="parquet",
                           sub_queries=cfg.sub_queries,
                           backend=cfg.backend, mode=cfg.throughput_mode,
                           warmup=cfg.warmup, decimal=cfg.decimal,
                           stream_timeout=cfg.stream_timeout)
            return
        self._chaos_round(rnd, ids)

    def _chaos_round(self, rnd: int, ids: list) -> None:
        """The full-system chaos scenario: maintenance mutates the shared
        warehouse (new generations) CONCURRENTLY with service-mode query
        streams reading their pinned snapshots, while a seeded fault
        campaign is armed — the flight recorder keeps the interleaving
        and dumps per firing."""
        from .service import CircuitBreakerConfig, ServiceConfig

        cfg = self.cfg
        flight_dir = os.path.join(cfg.report_dir, f"flight_round{rnd}")
        FLIGHT.configure(enabled=True, dump_dir=flight_dir,
                         trip_cooldown_s=0.0, clear=False)
        armed = [FAULTS.arm(FaultSpec(
            point=p, action="raise", times=cfg.chaos_times_per_point))
            for p in cfg.chaos_points]
        FLIGHT.record("lifecycle_phase", phase=f"throughput{rnd}",
                      status="chaos_armed",
                      points=list(cfg.chaos_points))
        dm_error: list = []

        def run_dm():
            try:
                self._run_maintenance_round(ids)
            except BaseException as e:      # surfaced after join
                dm_error.append(e)

        dm_thread = threading.Thread(target=run_dm, daemon=True,
                                     name=f"lifecycle-dm-{rnd}")
        try:
            dm_thread.start()
            run_throughput(
                cfg.warehouse_path, cfg.stream_dir, ids, cfg.report_dir,
                input_format="parquet", sub_queries=cfg.sub_queries,
                backend=cfg.backend, mode="service", warmup=cfg.warmup,
                decimal=cfg.decimal, stream_timeout=cfg.stream_timeout,
                service_config=ServiceConfig(
                    max_pending=max(256, 8 * len(ids)),
                    breaker=CircuitBreakerConfig(),
                    retry_budget=64, ticket_attempts=2))
            dm_thread.join()
        finally:
            fired = [{"point": s.point, "fired": s.fired} for s in armed]
            for s in armed:
                FAULTS.disarm(s)
            self.state["phases"].setdefault(
                f"throughput{rnd}", {})["chaos_fired"] = fired
        if dm_error:
            raise dm_error[0]
        # the round already ran maintenance: checkpoint it as done so the
        # maintenance phase body below only validates its logs
        for s in ids:
            if not os.path.exists(self._dm_log(s)):
                raise FileNotFoundError(
                    f"chaos round {rnd}: maintenance log "
                    f"{self._dm_log(s)} missing after concurrent round")

    def _phase_maintenance(self, rnd: int) -> None:
        ids = get_stream_range(self.cfg.num_streams, rnd)
        if self.cfg.chaos and all(os.path.exists(self._dm_log(s))
                                  for s in ids):
            return      # ran concurrently inside the throughput phase
        self._run_maintenance_round(ids)

    # -- orchestration -------------------------------------------------------
    def _run_phase(self, name: str, fn) -> None:
        cfg = self.cfg
        attempts = max(1, cfg.phase_attempts)
        entry = self.state["phases"].setdefault(name, {})
        for attempt in range(1, attempts + 1):
            entry["status"] = "running"
            entry["attempts"] = entry.get("attempts", 0) + 1
            entry["started_at"] = time.time()
            self._save_state()
            FLIGHT.record("lifecycle_phase", phase=name, status="start",
                          attempt=entry["attempts"])
            t0 = time.perf_counter()
            try:
                fn()
            except Exception as e:
                entry["status"] = "failed"
                entry["error"] = f"{type(e).__name__}: {e}"
                self._save_state()
                FLIGHT.record("lifecycle_phase", phase=name,
                              status="failed", error=type(e).__name__)
                if attempt >= attempts:
                    raise
                LIFECYCLE_PHASE_RETRIES.inc()
                continue
            entry["status"] = "done"
            entry.pop("error", None)
            entry["elapsed_s"] = round(time.perf_counter() - t0, 3)
            entry["finished_at"] = time.time()
            self._save_state()
            FLIGHT.record("lifecycle_phase", phase=name, status="done",
                          elapsed_s=entry["elapsed_s"])
            return

    def scrape_times(self) -> dict:
        """The per-phase timing-log inputs to the score, re-read from the
        phase artifacts (NOT from checkpoint wall clocks): a resumed run
        scrapes the same logs an uninterrupted run wrote, so its score
        inputs are identical by construction."""
        cfg = self.cfg
        times = {"load": round_up_tenth(get_load_time(self._load_report())),
                 "power": round_up_tenth(get_power_time(self._power_log()))}
        for rnd in (1, 2):
            ids = get_stream_range(cfg.num_streams, rnd)
            times[f"throughput{rnd}"] = round_up_tenth(throughput_elapsed(
                [stream_log_path(cfg.report_dir, s) for s in ids]))
            times[f"maintenance{rnd}"] = round_up_tenth(sum(
                get_maintenance_time(self._dm_log(s)) for s in ids))
        return times

    def score(self) -> dict:
        """Compute the primary metric from the scraped times and write
        metrics.csv + the score block into the state file."""
        cfg = self.cfg
        times = self.scrape_times()
        metric = get_perf_metric(
            cfg.scale_factor, cfg.num_streams, times["load"],
            times["power"], times["throughput1"], times["throughput2"],
            times["maintenance1"], times["maintenance2"])
        sq = cfg.num_streams // 2
        rows = [["scale_factor", cfg.scale_factor],
                ["num_streams", cfg.num_streams], ["Sq", sq]]
        rows += [[k, v] for k, v in times.items()]
        rows.append(["perf_metric", metric])
        write_metrics_report(os.path.join(cfg.report_dir, "metrics.csv"),
                             rows)
        self.state["score"] = {"times": times, "perf_metric": metric}
        self._save_state()
        return {"times": times, "metric": metric}

    def run(self, resume: bool = False) -> dict:
        """Run every phase (skipping checkpointed ones on resume), then
        score. Returns {"times": {...}, "metric": N}."""
        if os.path.exists(self.state_path):
            if not resume:
                raise LifecycleStateError(
                    f"{self.state_path} exists: pass resume=True "
                    "(--resume) to continue it, or use a fresh report_dir")
            self._load_state()
        os.makedirs(self.cfg.report_dir, exist_ok=True)
        if self.cfg.query_log:
            # one durable log across every phase of the scored run
            # (clear=False: a resumed run appends to the same artifact)
            from .obs.query_log import QUERY_LOG
            QUERY_LOG.configure(enabled=True, path=self.cfg.query_log,
                                clear=False)
        plan = [("datagen", self._phase_datagen),
                ("load", self._phase_load),
                ("streams", self._phase_streams),
                ("power", self._phase_power),
                ("throughput1", lambda: self._phase_throughput(1)),
                ("maintenance1", lambda: self._phase_maintenance(1)),
                ("throughput2", lambda: self._phase_throughput(2)),
                ("maintenance2", lambda: self._phase_maintenance(2))]
        assert tuple(n for n, _ in plan) == PHASES
        for name, fn in plan:
            if self._phase_done(name):
                print(f"lifecycle: {name} already done "
                      f"({self.state['phases'][name].get('elapsed_s')}s), "
                      "skipping", flush=True)
                continue
            print(f"lifecycle: phase {name} ...", flush=True)
            self._run_phase(name, fn)
        out = self.score()
        if self.cfg.query_log:
            from .obs.query_log import QUERY_LOG
            QUERY_LOG.flush()
            print(f"lifecycle: query log {self.cfg.query_log}", flush=True)
        print(f"lifecycle: score {out['metric']} "
              f"(times {out['times']})", flush=True)
        return out


def run_lifecycle(config: LifecycleConfig, resume: bool = False) -> dict:
    """Module-level convenience mirroring the CLI."""
    return LifecycleRunner(config).run(resume=resume)


def config_to_dict(config: LifecycleConfig) -> dict:
    return asdict(config)
