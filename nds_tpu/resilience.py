"""Resilience layer: retry policies, deadlines, and fault injection.

The NDS lifecycle runs for hours at real scale factors, and the reference
harness's only answer to failure is detection (record ``Failed`` in the
JSON summary and keep the stream going). Production SQL engines treat
query-level fault tolerance and bounded execution as table stakes; this
module supplies the primitives the runners build on:

- :class:`RetryPolicy` — deterministic exponential backoff with a
  transient/fatal exception classification, used by ``report.BenchReport``
  for per-query attempts and by ``bench`` for phase-level retry.
- :class:`Deadline` / :func:`run_with_deadline` — wall-clock budgets for a
  query or a stream; a budget overrun raises :class:`DeadlineExceeded`
  (the worker thread is abandoned, not killed — the caller records the
  failure and moves on).
- :class:`AdmissionRejected` — typed overload rejection raised by bounded
  admission points (the query service's bounded queue, ``nds_tpu/service``)
  so overload surfaces as an immediate, classifiable error instead of an
  unbounded pile-up behind the accelerator.
- :class:`CircuitOpen` / :class:`CircuitBreaker` — a per-error-class
  failure-rate breaker for admission points: a class of failures crossing
  its windowed rate trips the breaker open, new work is refused with the
  typed :class:`CircuitOpen`, and after a cooldown a bounded number of
  half-open PROBES test recovery (success closes, failure re-opens).
- :class:`FaultRegistry` — named engine-level fault points
  (``arrow.read``, ``device.put``, ``jax.compile``, ``jax.execute``,
  ``stream.spawn``, ``query.run``) threaded through the engine and
  harness, armable to raise, delay, or hang at a given point/probability.
  This generalizes the ad-hoc ``--fault_inject`` query list the power
  runner grew (now sugar over ``query.run`` specs) and lets the retry /
  deadline / restart machinery be tested without a flaky device.

**RetryPolicy classification table** (how each typed failure class is
handled by default — fatal wins when a type matches both lists):

==================  =========  ==============================================
exception           class      why
==================  =========  ==============================================
TransientError      transient  declared retryable by its raiser
FaultError          transient  injected faults model transient infra failures
JaxRuntimeError     transient  tunnel drops / remote-compile hiccups
ConnectionError     transient  network blips
TimeoutError        transient  slow dependency, not a broken one
BrokenPipeError     transient  peer restarted; a retry reconnects
AdmissionRejected   transient  overload: back off and resubmit is the
                               intended client response (depth/limit carried)
DeadlineExceeded    fatal      the budget is spent; retrying double-spends it
CircuitOpen         fatal      permanent-until-probe: the breaker re-opens on
                               every submit until a half-open probe succeeds,
                               so client-side retry is wasted work — wait for
                               ``retry_after_s`` or route elsewhere
KeyboardInterrupt   fatal      interrupts must propagate
SystemExit          fatal      interpreter is leaving
<anything else>     transient  a mid-stream failure is worth one more try;
                               the attempt bound caps the cost
==================  =========  ==============================================

Everything here is deterministic: backoff schedules (jitter included) are
pure functions of the attempt number, and probabilistic fault draws come
from PER-SPEC seeded RNGs in that spec's firing order — so a spec's
firing-index set is a pure function of the registry seed and arming
order, independent of which service thread happens to hit the point.
"""
from __future__ import annotations

import atexit
import os
import random
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Iterable, Optional


class FaultError(RuntimeError):
    """Raised by an armed fault point (a deliberately injected failure)."""


class TransientError(RuntimeError):
    """Base class for errors a RetryPolicy treats as retryable."""


class DeadlineExceeded(RuntimeError):
    """A per-query or per-stream wall-clock budget expired."""


class AdmissionRejected(RuntimeError):
    """A query was refused at a bounded admission point (service queue full,
    service closed) INSTEAD of piling up behind the accelerator. Carries the
    observed depth/limit so clients can back off proportionally; classified
    transient by RetryPolicy (retry-after-backoff is the intended client
    response to overload)."""

    def __init__(self, message: str, depth: int | None = None,
                 limit: int | None = None):
        super().__init__(message)
        self.depth = depth
        self.limit = limit


class CircuitOpen(AdmissionRejected):
    """A per-error-class circuit breaker is refusing admissions.

    Subclasses AdmissionRejected (it IS a typed admission refusal), but
    classifies FATAL under RetryPolicy — fatal wins over the inherited
    transient name — because the breaker stays open until a half-open
    probe succeeds: immediate client retry cannot help, only waiting
    ``retry_after_s`` (or routing elsewhere) can."""

    def __init__(self, message: str, error_class: str | None = None,
                 retry_after_s: float | None = None):
        super().__init__(message)
        self.error_class = error_class
        self.retry_after_s = retry_after_s


# -- retry --------------------------------------------------------------------

#: exception type names (searched over the whole MRO) retried by default.
#: JaxRuntimeError covers tunnel drops / remote-compile hiccups without
#: importing jax here; FaultError is transient by design (injected faults
#: simulate transient infrastructure failures unless armed to repeat);
#: AdmissionRejected is the overload signal whose intended client response
#: IS retry-after-backoff. Full rationale: module-docstring table.
_TRANSIENT_NAMES = ("TransientError", "FaultError", "JaxRuntimeError",
                    "ConnectionError", "TimeoutError", "BrokenPipeError",
                    "AdmissionRejected")
#: never retried: a blown deadline already consumed its budget, interrupts
#: must propagate, and an open circuit re-rejects until a probe succeeds
#: (CircuitOpen's MRO also carries AdmissionRejected — fatal wins).
_FATAL_NAMES = ("DeadlineExceeded", "CircuitOpen", "KeyboardInterrupt",
                "SystemExit")


@dataclass
class RetryPolicy:
    """Deterministic bounded retry: ``max_attempts`` tries, exponential
    backoff ``backoff_s * factor**(attempt-1)`` capped at ``max_backoff_s``.

    ``jitter`` (0..1) spreads synchronized retriers: attempt k's backoff
    stretches by up to ``jitter`` of itself using a DETERMINISTIC
    pseudo-random fraction of the attempt number (a Weyl sequence — no
    RNG state, so a failing run still replays identically), and the
    jittered value stays capped at ``max_backoff_s``.

    Classification ("transient" retries, "fatal" re-raises) follows the
    module-docstring table; fatal wins when a type's MRO matches both.
    """
    max_attempts: int = 3
    backoff_s: float = 0.1
    backoff_factor: float = 2.0
    max_backoff_s: float = 30.0
    jitter: float = 0.0
    transient_names: tuple = _TRANSIENT_NAMES
    fatal_names: tuple = _FATAL_NAMES

    def classify(self, exc: BaseException) -> str:
        """"transient" (retryable) or "fatal". Fatal wins on conflict;
        unknown exception types default to transient — a mid-stream query
        failure is worth one more try, and the attempt bound caps the cost.
        """
        names = {c.__name__ for c in type(exc).__mro__}
        if names & set(self.fatal_names):
            return "fatal"
        if names & set(self.transient_names):
            return "transient"
        return "transient"

    def backoff(self, attempt: int) -> float:
        """Seconds to wait after failed attempt `attempt` (1-based)."""
        base = self.backoff_s * self.backoff_factor ** (attempt - 1)
        if self.jitter > 0:
            # golden-ratio Weyl fraction of the attempt number: well
            # spread across attempts, zero state, replays identically
            frac = (attempt * 0.6180339887498949) % 1.0
            base *= 1.0 + self.jitter * frac
        return min(self.max_backoff_s, base)

    def call(self, fn: Callable, *args, label: str = "",
             sleep: Callable[[float], None] = time.sleep,
             on_attempt: Optional[Callable] = None, **kwargs):
        """Run ``fn`` under this policy; re-raises the last error when
        attempts are exhausted or the error classifies fatal. ``on_attempt``
        (attempt#, exception|None) observes every try."""
        from .obs.metrics import RETRIES
        for attempt in range(1, self.max_attempts + 1):
            try:
                out = fn(*args, **kwargs)
                if on_attempt is not None:
                    on_attempt(attempt, None)
                return out
            except Exception as e:
                if on_attempt is not None:
                    on_attempt(attempt, e)
                if attempt >= self.max_attempts or \
                        self.classify(e) == "fatal":
                    raise
                RETRIES.inc()
                sleep(self.backoff(attempt))


# -- deadlines ----------------------------------------------------------------

class Deadline:
    """A wall-clock budget. ``seconds=None`` (or <= 0) never expires."""

    def __init__(self, seconds: Optional[float],
                 clock: Callable[[], float] = time.monotonic):
        self.seconds = seconds if seconds and seconds > 0 else None
        self._clock = clock
        self._t0 = clock()

    def remaining(self) -> Optional[float]:
        if self.seconds is None:
            return None
        return self.seconds - (self._clock() - self._t0)

    def expired(self) -> bool:
        rem = self.remaining()
        return rem is not None and rem <= 0

    def check(self, label: str = "") -> None:
        if self.expired():
            raise DeadlineExceeded(
                f"{label or 'deadline'} exceeded {self.seconds}s budget")


#: deadline workers abandoned mid-flight, drained (bounded) at exit: a
#: daemon thread killed while inside XLA compute aborts interpreter
#: teardown (std::terminate from the C++ runtime), turning an otherwise
#: clean run into a spurious nonzero exit the stream supervisor would
#: retry. Truly hung workers still abandon after the grace.
_ABANDONED: list[threading.Thread] = []
_ABANDONED_LOCK = threading.Lock()


def _drain_abandoned(grace_s: Optional[float] = None) -> None:
    grace = float(os.environ.get("NDS_TPU_DEADLINE_DRAIN_S", "10")) \
        if grace_s is None else grace_s
    until = time.monotonic() + grace
    with _ABANDONED_LOCK:
        workers = list(_ABANDONED)
        _ABANDONED.clear()
    for t in workers:
        t.join(max(0.0, until - time.monotonic()))


atexit.register(_drain_abandoned)


def run_with_deadline(fn: Callable, timeout_s: Optional[float], *args,
                      label: str = "", **kwargs):
    """Run ``fn`` bounded by ``timeout_s`` wall seconds.

    The call runs in a daemon worker thread; on overrun the worker is
    ABANDONED (python threads cannot be killed) and DeadlineExceeded
    raises in the caller, which records the failure and continues — the
    same containment posture the reference gets from per-app process
    isolation. Abandoned workers get a bounded join at interpreter exit
    (NDS_TPU_DEADLINE_DRAIN_S, default 10) so a worker still inside XLA
    doesn't abort teardown. timeout_s None/<=0 calls ``fn`` inline.
    """
    if not timeout_s or timeout_s <= 0:
        return fn(*args, **kwargs)
    box: dict = {}

    def work():
        try:
            box["result"] = fn(*args, **kwargs)
        except BaseException as e:      # delivered to the caller below
            box["error"] = e

    t = threading.Thread(target=work, daemon=True,
                         name=f"deadline-worker:{label or fn.__name__}")
    t.start()
    t.join(timeout_s)
    if t.is_alive():
        with _ABANDONED_LOCK:
            _ABANDONED[:] = [w for w in _ABANDONED if w.is_alive()]
            _ABANDONED.append(t)
        raise DeadlineExceeded(
            f"{label or 'call'} exceeded {timeout_s}s budget "
            "(worker abandoned)")
    if "error" in box:
        raise box["error"]
    return box.get("result")


# -- circuit breaker ----------------------------------------------------------

@dataclass
class CircuitBreakerConfig:
    """Knobs of one :class:`CircuitBreaker` (per-error-class windows)."""
    #: outcomes tracked per error class (sliding window; successes count
    #: toward every tracked class so rates decay as the engine heals)
    window: int = 16
    #: failures of one class required inside its window before the rate
    #: can trip (a floor so one early failure at 1/1 = 100% never trips)
    min_failures: int = 4
    #: windowed failure fraction at/above which the class trips open
    failure_rate: float = 0.5
    #: seconds a tripped class stays open before half-open probes start
    open_s: float = 2.0
    #: concurrent probe admissions allowed while half-open
    probes: int = 1
    #: error-class names the breaker never counts (a ticket blowing its
    #: OWN deadline budget says nothing about engine health)
    exclude: tuple = ("DeadlineExceeded",)


class _BreakerClass:
    """One error class's window + state. Mutated only under the breaker
    lock."""
    __slots__ = ("state", "outcomes", "opened_at", "probes_out", "trips")

    def __init__(self, window: int):
        self.state = "closed"               # closed | open | half_open
        self.outcomes: deque = deque(maxlen=window)   # True = failure
        self.opened_at = 0.0
        self.probes_out = 0
        self.trips = 0


class CircuitBreaker:
    """Per-error-class circuit breaker for admission points.

    The service reports every ticket outcome through :meth:`record`; each
    FAILURE class (exception type name) keeps its own sliding window, so a
    storm of one class (say FaultError from a sick device path) trips
    without a healthy class's successes masking the rate. While a class is
    OPEN, :meth:`admit` raises the typed :class:`CircuitOpen` (fatal under
    RetryPolicy: permanent-until-probe). After ``open_s`` the class goes
    HALF-OPEN: up to ``probes`` admissions pass through as probes — a
    probe success closes the class (window cleared), a probe failure
    re-opens it for another cooldown.

    Trips and probes land in the flight recorder (``trip``/``probe``
    events; a trip also dumps the ring — the moments post-mortems exist
    for) and in the ``circuit_trips`` metric. ``clock`` is injectable for
    deterministic tests.
    """

    def __init__(self, config: Optional[CircuitBreakerConfig] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.config = config or CircuitBreakerConfig()
        self._clock = clock
        self._lock = threading.Lock()
        self._classes: dict[str, _BreakerClass] = {}

    def admit(self, label: str = "") -> Optional[str]:  # lint: thread-entry (every service client thread submits through this)
        """Gate one admission. Raises :class:`CircuitOpen` when some error
        class is open (or half-open with its probe slots taken). Returns
        the error-class name this admission PROBES for (caller must pass
        it back to :meth:`record`), or None for a normal admission."""
        cfg = self.config
        now = self._clock()
        probe_for = None
        with self._lock:
            for cls, st in self._classes.items():
                if st.state == "open":
                    waited = now - st.opened_at
                    if waited < cfg.open_s:
                        raise CircuitOpen(
                            f"circuit open for {cls} "
                            f"({cfg.open_s - waited:.2f}s until probes)",
                            error_class=cls,
                            retry_after_s=cfg.open_s - waited)
                    st.state = "half_open"
                    st.probes_out = 0
                if st.state == "half_open":
                    if st.probes_out >= cfg.probes:
                        raise CircuitOpen(
                            f"circuit half-open for {cls}: probe slots "
                            f"full ({cfg.probes} in flight)",
                            error_class=cls, retry_after_s=0.0)
                    if probe_for is None:
                        st.probes_out += 1
                        probe_for = cls
        if probe_for is not None:
            from .obs.flight import FLIGHT
            FLIGHT.record("probe", error_class=probe_for, label=label)
        return probe_for

    def record(self, error_name: Optional[str] = None,
               probe: Optional[str] = None, label: str = "") -> None:  # lint: thread-entry (device lane + client threads report outcomes)
        """Report one outcome: ``error_name`` is the failure's type name
        (None = success); ``probe`` is the class name admit() returned."""
        cfg = self.config
        excluded = error_name is not None and error_name in cfg.exclude
        now = self._clock()
        tripped: list[tuple[str, int, int]] = []
        closed: Optional[str] = None
        with self._lock:
            if probe is not None:
                st = self._classes.get(probe)
                if st is not None and st.state == "half_open":
                    st.probes_out = max(0, st.probes_out - 1)
                    if excluded:
                        pass    # no health signal: slot freed, stay half-open
                    elif error_name is None:
                        st.state = "closed"
                        st.outcomes.clear()
                        closed = probe
                    else:
                        # ANY failure of a probe (even another class) says
                        # the engine is still sick: re-open for a cooldown
                        st.state = "open"
                        st.opened_at = now
                        st.trips += 1
                        tripped.append((probe, st.trips,
                                        sum(st.outcomes)))
            if excluded:
                pass            # an excluded class teaches the windows nothing
            elif error_name is None:
                for st in self._classes.values():
                    st.outcomes.append(False)
            else:
                st = self._classes.get(error_name)
                if st is None:
                    st = self._classes[error_name] = _BreakerClass(
                        cfg.window)
                st.outcomes.append(True)
                fails = sum(st.outcomes)
                if st.state == "closed" and fails >= cfg.min_failures \
                        and fails / len(st.outcomes) >= cfg.failure_rate:
                    st.state = "open"
                    st.opened_at = now
                    st.trips += 1
                    tripped.append((error_name, st.trips, fails))
        if closed is not None:
            from .obs.flight import FLIGHT
            FLIGHT.record("probe", error_class=closed, outcome="closed",
                          label=label)
        for cls, trips, fails in tripped:
            from .obs.flight import FLIGHT
            from .obs.metrics import CIRCUIT_TRIPS
            CIRCUIT_TRIPS.inc()
            # the onset of a failure storm is exactly the window the
            # flight ring should preserve: trip (and dump) per class
            FLIGHT.trip(f"circuit:{cls}", error_class=cls, trips=trips,
                        window_failures=fails, label=label)

    def release(self, probe: Optional[str]) -> None:
        """Free a granted probe slot without a health signal (the probe
        admission was refused downstream before it could run)."""
        if probe is None:
            return
        with self._lock:
            st = self._classes.get(probe)
            if st is not None and st.state == "half_open":
                st.probes_out = max(0, st.probes_out - 1)

    def state(self) -> dict[str, dict]:
        """{error_class: {state, trips, window_failures}} snapshot."""
        with self._lock:
            return {cls: {"state": st.state, "trips": st.trips,
                          "window_failures": sum(st.outcomes)}
                    for cls, st in self._classes.items()}


# -- fault injection ----------------------------------------------------------

#: engine/harness fault points. Each is fired exactly once per logical
#: event by the owning layer:
#:   arrow.read   - host-side Arrow -> engine table conversion (arrow_bridge)
#:   device.put   - host -> device upload of a padded table (device.to_device)
#:   jax.compile  - XLA trace/compile of a whole-plan program (CompiledQuery)
#:   jax.execute  - execution of a device program (compiled run / eager record)
#:   stream.spawn - throughput supervisor starting a stream attempt
#:   query.run    - power runner starting a timed query (detail = query name)
#:   manifest.write     - warehouse manifest publication, BEFORE any byte
#:                        lands (warehouse.WarehouseTable._store_doc)
#:   txn.commit         - warehouse transaction about to publish its
#:                        version record + CURRENT (the commit point)
#:   txn.between_tables - a SECOND distinct table joining an open
#:                        warehouse transaction (the mid-commit kill
#:                        window: table A committed, table B untouched)
#:   frontdoor.drop     - a front-door connection handler about to write
#:                        a response (service/frontdoor.py): a raise-spec
#:                        makes the server sever the socket instead —
#:                        the client sees an abrupt EOF mid-frame
#:   frontdoor.kill     - the engine process serving a front-door query
#:                        (fired before dispatch): a raise-spec makes the
#:                        server process exit hard (os._exit) — the
#:                        chaos topology campaign's mid-query kill
FAULT_POINTS = ("arrow.read", "device.put", "jax.compile", "jax.execute",
                "stream.spawn", "query.run",
                "manifest.write", "txn.commit", "txn.between_tables",
                "frontdoor.drop", "frontdoor.kill")

#: default sleep for a ``hang`` spec with no explicit duration: long enough
#: that only a deadline/supervisor kill ends the attempt.
HANG_SECONDS = 3600.0


@dataclass
class FaultSpec:
    """One armed fault. Spec-string grammar (property-file friendly):

        point:action[:seconds][@probability][#times][/match]

    e.g. ``jax.execute:hang:5#1`` (hang 5s, first firing only),
    ``arrow.read:raise``, ``device.put:delay:0.2@0.5``,
    ``query.run:raise/query1`` (only when the fired detail is query1).
    """
    point: str
    action: str = "raise"           # raise | delay | hang
    seconds: float = 0.0            # delay/hang duration (hang: 0 => HANG_SECONDS)
    probability: float = 1.0
    times: Optional[int] = None     # max firings; None = unlimited
    match: Optional[str] = None     # exact match on the fire() detail
    source: str = "manual"          # "config" specs replaced on reconfigure
    fired: int = field(default=0, compare=False)
    #: per-spec probability RNG, seeded at arm time from (registry seed,
    #: arm index, spec identity): the spec's firing-index set is a pure
    #: function of the seed + arming order even when service threads hit
    #: the point in nondeterministic interleavings (seeded chaos
    #: campaigns rely on this). None until armed; draws under the
    #: registry lock.
    rng: Optional[random.Random] = field(default=None, compare=False,
                                         repr=False)

    @classmethod
    def parse(cls, text: str, source: str = "manual") -> "FaultSpec":
        body, match = (text.split("/", 1) + [None])[:2] \
            if "/" in text else (text, None)
        body, times = body.split("#", 1) if "#" in body else (body, None)
        body, prob = body.split("@", 1) if "@" in body else (body, None)
        parts = body.split(":")
        point = parts[0].strip()
        if point not in FAULT_POINTS:
            raise ValueError(f"unknown fault point {point!r} "
                             f"(expected one of {FAULT_POINTS})")
        action = parts[1].strip() if len(parts) > 1 else "raise"
        if action not in ("raise", "delay", "hang"):
            raise ValueError(f"unknown fault action {action!r} in {text!r} "
                             "(expected raise, delay, or hang)")
        seconds = float(parts[2]) if len(parts) > 2 else 0.0
        return cls(point=point, action=action, seconds=seconds,
                   probability=float(prob) if prob is not None else 1.0,
                   times=int(times) if times is not None else None,
                   match=match, source=source)

    def applies(self, detail: str) -> bool:
        if self.times is not None and self.fired >= self.times:
            return False
        return self.match is None or self.match == detail


class FaultRegistry:
    """Process-global registry of armed fault points.

    Engine/harness code calls :meth:`fire` at each point; the fast path
    (nothing armed) is one attribute read, so the hooks cost nothing in
    production. Probability draws come from PER-SPEC RNGs seeded at arm
    time, so a spec's firing-index set is deterministic in that spec's
    own firing order — chaos campaigns replay their schedules even when
    concurrent service threads interleave the points nondeterministically.

    Thread contract (audited for armed-under-live-traffic chaos runs):
    every mutation of the spec list AND every iteration over it — firing,
    certainty queries, arming, disarming, reconfiguring — happens under
    ``_lock``; ``fire`` collects the triggered specs under the lock and
    acts (sleeps/raises) outside it. The only unlocked read is the
    nothing-armed fast path, a single attribute load of the list object
    (atomic in CPython; a spec armed concurrently with that read is
    simply not yet visible, same as arming one instruction later).
    """

    def __init__(self, seed: int = 0x5E51):
        self._specs: list[FaultSpec] = []
        self._lock = threading.Lock()
        self._rng = random.Random(seed)     # fallback for unarmed specs
        self._seed = seed
        self._armed_total = 0               # arm-order index for spec seeds

    def _seed_spec(self, spec: FaultSpec) -> None:
        """Give the spec its deterministic RNG (under ``_lock``)."""
        self._armed_total += 1
        spec.rng = random.Random(
            f"{self._seed}:{self._armed_total}:{spec.point}:"
            f"{spec.action}:{spec.probability}:{spec.match}")

    def arm(self, spec, **kwargs) -> FaultSpec:  # lint: thread-entry (campaign drivers arm while service threads fire)
        """Arm a FaultSpec (or parse a spec string). Returns the armed spec
        so callers can :meth:`disarm` it."""
        if isinstance(spec, str):
            spec = FaultSpec.parse(spec, **kwargs)
        elif spec.point not in FAULT_POINTS:
            # parse() validates spec strings; directly-constructed specs
            # must not arm a point no engine layer will ever fire (a
            # typo'd chaos campaign would otherwise "pass" as a no-op)
            raise ValueError(f"unknown fault point {spec.point!r} "
                             f"(expected one of {FAULT_POINTS})")
        with self._lock:
            self._seed_spec(spec)
            self._specs.append(spec)
        return spec

    def disarm(self, spec: FaultSpec) -> None:  # lint: thread-entry (campaign drivers disarm while service threads fire)
        with self._lock:
            if spec in self._specs:
                self._specs.remove(spec)

    def configure(self, texts: Iterable[str]) -> list[FaultSpec]:  # lint: thread-entry (sessions build on service/stream threads)
        """Install config-sourced specs, replacing any previous config batch
        (manually armed specs are untouched). Called by Session.__init__
        from ``EngineConfig.fault_points``."""
        parsed = [FaultSpec.parse(t, source="config") for t in texts if t]
        with self._lock:
            self._specs = [s for s in self._specs if s.source != "config"]
            for s in parsed:
                self._seed_spec(s)
            self._specs.extend(parsed)
        return parsed

    def clear(self, point: Optional[str] = None) -> None:  # lint: thread-entry (campaign teardown races in-flight queries)
        with self._lock:
            self._specs = [] if point is None else \
                [s for s in self._specs if s.point != point]
            self._rng = random.Random(self._seed)
            if point is None:
                self._armed_total = 0

    def specs(self) -> list[FaultSpec]:
        with self._lock:
            return list(self._specs)

    def would_raise(self, point: str, detail: str = "",
                    aliases: tuple = ()) -> bool:
        """Is a certain (p=1) raise-spec armed for this point/detail?
        Lets the power runner skip warmup for queries whose timed run is
        guaranteed to fail, without consuming the spec."""
        with self._lock:
            return any(s.point == point and s.action == "raise"
                       and s.probability >= 1.0
                       and any(s.applies(d) for d in (detail, *aliases))
                       for s in self._specs)

    def fire(self, point: str, detail: str = "", aliases: tuple = ()) -> None:  # lint: thread-entry (every engine layer fires from service/staging threads)
        """Trigger any armed specs for ``point``. Raise-specs raise
        FaultError; delay-specs sleep; hang-specs sleep (default
        HANG_SECONDS) and then raise, so an abandoned deadline worker dies
        cleanly when it wakes instead of touching shared state."""
        if not self._specs:         # fast path: nothing armed
            return
        triggered: list[FaultSpec] = []
        with self._lock:
            for s in self._specs:
                if s.point != point or \
                        not any(s.applies(d) for d in (detail, *aliases)):
                    continue
                if s.probability < 1.0 and \
                        (s.rng or self._rng).random() >= s.probability:
                    continue
                s.fired += 1
                triggered.append(s)
        if triggered:
            from .obs.flight import FLIGHT
            from .obs.metrics import FAULT_FIRINGS
            FAULT_FIRINGS.inc(len(triggered))
            # a firing fault point is exactly the post-mortem moment the
            # flight recorder exists for: record it and auto-dump the
            # surrounding lifecycle window (no-op while disabled)
            FLIGHT.record("fault", point=point, detail=detail,
                          actions=[s.action for s in triggered])
            FLIGHT.trip("fault", point=point)
        for s in triggered:         # act outside the lock (sleeps)
            where = f"{point} ({detail})" if detail else point
            if s.action == "delay":
                time.sleep(s.seconds)
            elif s.action == "hang":
                time.sleep(s.seconds if s.seconds > 0 else HANG_SECONDS)
                raise FaultError(f"hung fault point woke at {where}")
            else:
                raise FaultError(f"injected fault at {where}")


#: the process-global registry every engine/harness fault point fires into.
FAULTS = FaultRegistry()
