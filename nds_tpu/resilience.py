"""Resilience layer: retry policies, deadlines, and fault injection.

The NDS lifecycle runs for hours at real scale factors, and the reference
harness's only answer to failure is detection (record ``Failed`` in the
JSON summary and keep the stream going). Production SQL engines treat
query-level fault tolerance and bounded execution as table stakes; this
module supplies the primitives the runners build on:

- :class:`RetryPolicy` — deterministic exponential backoff with a
  transient/fatal exception classification, used by ``report.BenchReport``
  for per-query attempts and by ``bench`` for phase-level retry.
- :class:`Deadline` / :func:`run_with_deadline` — wall-clock budgets for a
  query or a stream; a budget overrun raises :class:`DeadlineExceeded`
  (the worker thread is abandoned, not killed — the caller records the
  failure and moves on).
- :class:`AdmissionRejected` — typed overload rejection raised by bounded
  admission points (the query service's bounded queue, ``nds_tpu/service``)
  so overload surfaces as an immediate, classifiable error instead of an
  unbounded pile-up behind the accelerator.
- :class:`FaultRegistry` — named engine-level fault points
  (``arrow.read``, ``device.put``, ``jax.compile``, ``jax.execute``,
  ``stream.spawn``, ``query.run``) threaded through the engine and
  harness, armable to raise, delay, or hang at a given point/probability.
  This generalizes the ad-hoc ``--fault_inject`` query list the power
  runner grew (now sugar over ``query.run`` specs) and lets the retry /
  deadline / restart machinery be tested without a flaky device.

Everything here is deterministic: backoff schedules are pure functions of
the attempt number, and probabilistic fault draws come from a registry-
seeded RNG, so a failing run replays identically.
"""
from __future__ import annotations

import atexit
import os
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Iterable, Optional


class FaultError(RuntimeError):
    """Raised by an armed fault point (a deliberately injected failure)."""


class TransientError(RuntimeError):
    """Base class for errors a RetryPolicy treats as retryable."""


class DeadlineExceeded(RuntimeError):
    """A per-query or per-stream wall-clock budget expired."""


class AdmissionRejected(RuntimeError):
    """A query was refused at a bounded admission point (service queue full,
    service closed) INSTEAD of piling up behind the accelerator. Carries the
    observed depth/limit so clients can back off proportionally; classified
    transient by RetryPolicy (retry-after-backoff is the intended client
    response to overload)."""

    def __init__(self, message: str, depth: int | None = None,
                 limit: int | None = None):
        super().__init__(message)
        self.depth = depth
        self.limit = limit


# -- retry --------------------------------------------------------------------

#: exception type names (searched over the whole MRO) retried by default.
#: JaxRuntimeError covers tunnel drops / remote-compile hiccups without
#: importing jax here; FaultError is transient by design (injected faults
#: simulate transient infrastructure failures unless armed to repeat).
_TRANSIENT_NAMES = ("TransientError", "FaultError", "JaxRuntimeError",
                    "ConnectionError", "TimeoutError", "BrokenPipeError")
#: never retried: a blown deadline already consumed its budget, and
#: interrupts must propagate.
_FATAL_NAMES = ("DeadlineExceeded", "KeyboardInterrupt", "SystemExit")


@dataclass
class RetryPolicy:
    """Deterministic bounded retry: ``max_attempts`` tries, exponential
    backoff ``backoff_s * factor**(attempt-1)`` capped at ``max_backoff_s``.
    """
    max_attempts: int = 3
    backoff_s: float = 0.1
    backoff_factor: float = 2.0
    max_backoff_s: float = 30.0
    transient_names: tuple = _TRANSIENT_NAMES
    fatal_names: tuple = _FATAL_NAMES

    def classify(self, exc: BaseException) -> str:
        """"transient" (retryable) or "fatal". Fatal wins on conflict;
        unknown exception types default to transient — a mid-stream query
        failure is worth one more try, and the attempt bound caps the cost.
        """
        names = {c.__name__ for c in type(exc).__mro__}
        if names & set(self.fatal_names):
            return "fatal"
        if names & set(self.transient_names):
            return "transient"
        return "transient"

    def backoff(self, attempt: int) -> float:
        """Seconds to wait after failed attempt `attempt` (1-based)."""
        return min(self.max_backoff_s,
                   self.backoff_s * self.backoff_factor ** (attempt - 1))

    def call(self, fn: Callable, *args, label: str = "",
             sleep: Callable[[float], None] = time.sleep,
             on_attempt: Optional[Callable] = None, **kwargs):
        """Run ``fn`` under this policy; re-raises the last error when
        attempts are exhausted or the error classifies fatal. ``on_attempt``
        (attempt#, exception|None) observes every try."""
        from .obs.metrics import RETRIES
        for attempt in range(1, self.max_attempts + 1):
            try:
                out = fn(*args, **kwargs)
                if on_attempt is not None:
                    on_attempt(attempt, None)
                return out
            except Exception as e:
                if on_attempt is not None:
                    on_attempt(attempt, e)
                if attempt >= self.max_attempts or \
                        self.classify(e) == "fatal":
                    raise
                RETRIES.inc()
                sleep(self.backoff(attempt))


# -- deadlines ----------------------------------------------------------------

class Deadline:
    """A wall-clock budget. ``seconds=None`` (or <= 0) never expires."""

    def __init__(self, seconds: Optional[float],
                 clock: Callable[[], float] = time.monotonic):
        self.seconds = seconds if seconds and seconds > 0 else None
        self._clock = clock
        self._t0 = clock()

    def remaining(self) -> Optional[float]:
        if self.seconds is None:
            return None
        return self.seconds - (self._clock() - self._t0)

    def expired(self) -> bool:
        rem = self.remaining()
        return rem is not None and rem <= 0

    def check(self, label: str = "") -> None:
        if self.expired():
            raise DeadlineExceeded(
                f"{label or 'deadline'} exceeded {self.seconds}s budget")


#: deadline workers abandoned mid-flight, drained (bounded) at exit: a
#: daemon thread killed while inside XLA compute aborts interpreter
#: teardown (std::terminate from the C++ runtime), turning an otherwise
#: clean run into a spurious nonzero exit the stream supervisor would
#: retry. Truly hung workers still abandon after the grace.
_ABANDONED: list[threading.Thread] = []
_ABANDONED_LOCK = threading.Lock()


def _drain_abandoned(grace_s: Optional[float] = None) -> None:
    grace = float(os.environ.get("NDS_TPU_DEADLINE_DRAIN_S", "10")) \
        if grace_s is None else grace_s
    until = time.monotonic() + grace
    with _ABANDONED_LOCK:
        workers = list(_ABANDONED)
        _ABANDONED.clear()
    for t in workers:
        t.join(max(0.0, until - time.monotonic()))


atexit.register(_drain_abandoned)


def run_with_deadline(fn: Callable, timeout_s: Optional[float], *args,
                      label: str = "", **kwargs):
    """Run ``fn`` bounded by ``timeout_s`` wall seconds.

    The call runs in a daemon worker thread; on overrun the worker is
    ABANDONED (python threads cannot be killed) and DeadlineExceeded
    raises in the caller, which records the failure and continues — the
    same containment posture the reference gets from per-app process
    isolation. Abandoned workers get a bounded join at interpreter exit
    (NDS_TPU_DEADLINE_DRAIN_S, default 10) so a worker still inside XLA
    doesn't abort teardown. timeout_s None/<=0 calls ``fn`` inline.
    """
    if not timeout_s or timeout_s <= 0:
        return fn(*args, **kwargs)
    box: dict = {}

    def work():
        try:
            box["result"] = fn(*args, **kwargs)
        except BaseException as e:      # delivered to the caller below
            box["error"] = e

    t = threading.Thread(target=work, daemon=True,
                         name=f"deadline-worker:{label or fn.__name__}")
    t.start()
    t.join(timeout_s)
    if t.is_alive():
        with _ABANDONED_LOCK:
            _ABANDONED[:] = [w for w in _ABANDONED if w.is_alive()]
            _ABANDONED.append(t)
        raise DeadlineExceeded(
            f"{label or 'call'} exceeded {timeout_s}s budget "
            "(worker abandoned)")
    if "error" in box:
        raise box["error"]
    return box.get("result")


# -- fault injection ----------------------------------------------------------

#: engine/harness fault points. Each is fired exactly once per logical
#: event by the owning layer:
#:   arrow.read   - host-side Arrow -> engine table conversion (arrow_bridge)
#:   device.put   - host -> device upload of a padded table (device.to_device)
#:   jax.compile  - XLA trace/compile of a whole-plan program (CompiledQuery)
#:   jax.execute  - execution of a device program (compiled run / eager record)
#:   stream.spawn - throughput supervisor starting a stream attempt
#:   query.run    - power runner starting a timed query (detail = query name)
FAULT_POINTS = ("arrow.read", "device.put", "jax.compile", "jax.execute",
                "stream.spawn", "query.run")

#: default sleep for a ``hang`` spec with no explicit duration: long enough
#: that only a deadline/supervisor kill ends the attempt.
HANG_SECONDS = 3600.0


@dataclass
class FaultSpec:
    """One armed fault. Spec-string grammar (property-file friendly):

        point:action[:seconds][@probability][#times][/match]

    e.g. ``jax.execute:hang:5#1`` (hang 5s, first firing only),
    ``arrow.read:raise``, ``device.put:delay:0.2@0.5``,
    ``query.run:raise/query1`` (only when the fired detail is query1).
    """
    point: str
    action: str = "raise"           # raise | delay | hang
    seconds: float = 0.0            # delay/hang duration (hang: 0 => HANG_SECONDS)
    probability: float = 1.0
    times: Optional[int] = None     # max firings; None = unlimited
    match: Optional[str] = None     # exact match on the fire() detail
    source: str = "manual"          # "config" specs replaced on reconfigure
    fired: int = field(default=0, compare=False)

    @classmethod
    def parse(cls, text: str, source: str = "manual") -> "FaultSpec":
        body, match = (text.split("/", 1) + [None])[:2] \
            if "/" in text else (text, None)
        body, times = body.split("#", 1) if "#" in body else (body, None)
        body, prob = body.split("@", 1) if "@" in body else (body, None)
        parts = body.split(":")
        point = parts[0].strip()
        if point not in FAULT_POINTS:
            raise ValueError(f"unknown fault point {point!r} "
                             f"(expected one of {FAULT_POINTS})")
        action = parts[1].strip() if len(parts) > 1 else "raise"
        if action not in ("raise", "delay", "hang"):
            raise ValueError(f"unknown fault action {action!r} in {text!r} "
                             "(expected raise, delay, or hang)")
        seconds = float(parts[2]) if len(parts) > 2 else 0.0
        return cls(point=point, action=action, seconds=seconds,
                   probability=float(prob) if prob is not None else 1.0,
                   times=int(times) if times is not None else None,
                   match=match, source=source)

    def applies(self, detail: str) -> bool:
        if self.times is not None and self.fired >= self.times:
            return False
        return self.match is None or self.match == detail


class FaultRegistry:
    """Process-global registry of armed fault points.

    Engine/harness code calls :meth:`fire` at each point; the fast path
    (nothing armed) is one attribute read, so the hooks cost nothing in
    production. Probability draws come from a seeded RNG in fire order, so
    a run with probabilistic faults replays deterministically.
    """

    def __init__(self, seed: int = 0x5E51):
        self._specs: list[FaultSpec] = []
        self._lock = threading.Lock()
        self._rng = random.Random(seed)
        self._seed = seed

    def arm(self, spec, **kwargs) -> FaultSpec:
        """Arm a FaultSpec (or parse a spec string). Returns the armed spec
        so callers can :meth:`disarm` it."""
        if isinstance(spec, str):
            spec = FaultSpec.parse(spec, **kwargs)
        with self._lock:
            self._specs.append(spec)
        return spec

    def disarm(self, spec: FaultSpec) -> None:
        with self._lock:
            if spec in self._specs:
                self._specs.remove(spec)

    def configure(self, texts: Iterable[str]) -> list[FaultSpec]:
        """Install config-sourced specs, replacing any previous config batch
        (manually armed specs are untouched). Called by Session.__init__
        from ``EngineConfig.fault_points``."""
        parsed = [FaultSpec.parse(t, source="config") for t in texts if t]
        with self._lock:
            self._specs = [s for s in self._specs if s.source != "config"]
            self._specs.extend(parsed)
        return parsed

    def clear(self, point: Optional[str] = None) -> None:
        with self._lock:
            self._specs = [] if point is None else \
                [s for s in self._specs if s.point != point]
            self._rng = random.Random(self._seed)

    def specs(self) -> list[FaultSpec]:
        with self._lock:
            return list(self._specs)

    def would_raise(self, point: str, detail: str = "",
                    aliases: tuple = ()) -> bool:
        """Is a certain (p=1) raise-spec armed for this point/detail?
        Lets the power runner skip warmup for queries whose timed run is
        guaranteed to fail, without consuming the spec."""
        with self._lock:
            return any(s.point == point and s.action == "raise"
                       and s.probability >= 1.0
                       and any(s.applies(d) for d in (detail, *aliases))
                       for s in self._specs)

    def fire(self, point: str, detail: str = "", aliases: tuple = ()) -> None:
        """Trigger any armed specs for ``point``. Raise-specs raise
        FaultError; delay-specs sleep; hang-specs sleep (default
        HANG_SECONDS) and then raise, so an abandoned deadline worker dies
        cleanly when it wakes instead of touching shared state."""
        if not self._specs:         # fast path: nothing armed
            return
        triggered: list[FaultSpec] = []
        with self._lock:
            for s in self._specs:
                if s.point != point or \
                        not any(s.applies(d) for d in (detail, *aliases)):
                    continue
                if s.probability < 1.0 and \
                        self._rng.random() >= s.probability:
                    continue
                s.fired += 1
                triggered.append(s)
        if triggered:
            from .obs.flight import FLIGHT
            from .obs.metrics import FAULT_FIRINGS
            FAULT_FIRINGS.inc(len(triggered))
            # a firing fault point is exactly the post-mortem moment the
            # flight recorder exists for: record it and auto-dump the
            # surrounding lifecycle window (no-op while disabled)
            FLIGHT.record("fault", point=point, detail=detail,
                          actions=[s.action for s in triggered])
            FLIGHT.trip("fault", point=point)
        for s in triggered:         # act outside the lock (sleeps)
            where = f"{point} ({detail})" if detail else point
            if s.action == "delay":
                time.sleep(s.seconds)
            elif s.action == "hang":
                time.sleep(s.seconds if s.seconds > 0 else HANG_SECONDS)
                raise FaultError(f"hung fault point woke at {where}")
            else:
                raise FaultError(f"injected fault at {where}")


#: the process-global registry every engine/harness fault point fires into.
FAULTS = FaultRegistry()
