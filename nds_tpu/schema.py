"""Schema registry: single source of truth for the NDS table schemas.

24 source (query) tables plus 12 data-maintenance staging tables, expressed in a
compact column-spec DSL and materializable as pyarrow schemas (for CSV ingest and
the Parquet warehouse) or engine logical types.

Capability parity with the reference registry (``/root/reference/nds/nds_schema.py``:
``get_schemas`` :49-568, ``get_maintenance_schemas`` :570-716), including its
``use_decimal`` toggle (decimal vs double, :43-47) and the identifier-width policy
(int32 surrogate keys except the two 64-bit ticket/order columns, :61-65,328-331).
The representation here is original: a parsed DSL rather than Spark StructTypes.
"""
from __future__ import annotations

import re
from dataclasses import dataclass
from enum import Enum
from functools import lru_cache

import pyarrow as pa


class Kind(Enum):
    ID = "id"          # surrogate key, int32
    ID64 = "id64"      # surrogate key, int64 (ss_ticket_number, sr_ticket_number)
    INT = "int"        # general integer (int64, matches reference LongType)
    INT32 = "int32"    # 32-bit integer (maintenance staging tables)
    DEC = "dec"        # decimal(precision, scale)
    STR = "str"        # char(n)/varchar(n)/string — all logical strings
    DATE = "date"      # calendar date


@dataclass(frozen=True)
class ColType:
    kind: Kind
    precision: int = 0
    scale: int = 0
    length: int = 0

    @property
    def is_numeric(self) -> bool:
        return self.kind in (Kind.ID, Kind.ID64, Kind.INT, Kind.INT32, Kind.DEC)


@dataclass(frozen=True)
class Column:
    name: str
    ctype: ColType
    nullable: bool = True


@dataclass(frozen=True)
class TableSchema:
    name: str
    columns: tuple[Column, ...]

    @property
    def column_names(self) -> list[str]:
        return [c.name for c in self.columns]

    def column(self, name: str) -> Column:
        for c in self.columns:
            if c.name == name:
                return c
        raise KeyError(f"{self.name} has no column {name}")

    def arrow_schema(self, use_decimal: bool = True) -> pa.Schema:
        return pa.schema(
            [pa.field(c.name, _arrow_type(c.ctype, use_decimal), nullable=c.nullable)
             for c in self.columns]
        )


def _arrow_type(t: ColType, use_decimal: bool) -> pa.DataType:
    if t.kind == Kind.ID:
        return pa.int32()
    if t.kind == Kind.ID64:
        return pa.int64()
    if t.kind == Kind.INT:
        return pa.int64()
    if t.kind == Kind.INT32:
        return pa.int32()
    if t.kind == Kind.DEC:
        return pa.decimal128(t.precision, t.scale) if use_decimal else pa.float64()
    if t.kind == Kind.DATE:
        return pa.date32()
    return pa.string()


_SPEC_RE = re.compile(
    r"^(?P<name>\w+)\s+"
    r"(?P<type>id64|id|int32|int|date|str|dec\((\d+),(\d+)\)|(?:char|varchar)\((\d+)\))"
    r"(?P<nn>!)?$"
)


def _parse_col(spec: str) -> Column:
    m = _SPEC_RE.match(spec.strip())
    if not m:
        raise ValueError(f"bad column spec: {spec!r}")
    t = m.group("type")
    if t == "id":
        ctype = ColType(Kind.ID)
    elif t == "id64":
        ctype = ColType(Kind.ID64)
    elif t == "int":
        ctype = ColType(Kind.INT)
    elif t == "int32":
        ctype = ColType(Kind.INT32)
    elif t == "date":
        ctype = ColType(Kind.DATE)
    elif t == "str":
        ctype = ColType(Kind.STR)
    elif t.startswith("dec"):
        ctype = ColType(Kind.DEC, precision=int(m.group(3)), scale=int(m.group(4)))
    else:  # char(n)/varchar(n)
        ctype = ColType(Kind.STR, length=int(m.group(5)))
    return Column(m.group("name"), ctype, nullable=m.group("nn") is None)


def _table(name: str, *col_specs: str) -> TableSchema:
    cols = []
    for group in col_specs:
        # split on commas that are not inside a type's parentheses
        for spec in re.split(r",(?![^(]*\))", group):
            spec = spec.strip()
            if spec:
                cols.append(_parse_col(spec))
    return TableSchema(name, tuple(cols))


# ---------------------------------------------------------------------------
# 24 source tables (reference nds_schema.py:67-567)
# ---------------------------------------------------------------------------

_ADDRESS_COLS = ("street_number char(10), street_name varchar(60), street_type char(15), "
                 "suite_number char(10), city varchar(60), county varchar(30), state char(2), "
                 "zip char(10), country varchar(20)")


def _addr(prefix: str) -> str:
    return ", ".join(f"{prefix}_{c.strip()}" for c in _ADDRESS_COLS.split(","))


_SOURCE_TABLES: tuple[TableSchema, ...] = (
    _table(
        "customer_address",
        "ca_address_sk id!, ca_address_id char(16)!",
        _addr("ca"),
        "ca_gmt_offset dec(5,2), ca_location_type char(20)",
    ),
    _table(
        "customer_demographics",
        "cd_demo_sk id!, cd_gender char(1), cd_marital_status char(1)",
        "cd_education_status char(20), cd_purchase_estimate int, cd_credit_rating char(10)",
        "cd_dep_count int, cd_dep_employed_count int, cd_dep_college_count int",
    ),
    _table(
        "date_dim",
        "d_date_sk id!, d_date_id char(16)!, d_date date",
        "d_month_seq int, d_week_seq int, d_quarter_seq int, d_year int, d_dow int",
        "d_moy int, d_dom int, d_qoy int, d_fy_year int, d_fy_quarter_seq int",
        "d_fy_week_seq int, d_day_name char(9), d_quarter_name char(6), d_holiday char(1)",
        "d_weekend char(1), d_following_holiday char(1), d_first_dom int, d_last_dom int",
        "d_same_day_ly int, d_same_day_lq int, d_current_day char(1), d_current_week char(1)",
        "d_current_month char(1), d_current_quarter char(1), d_current_year char(1)",
    ),
    _table(
        "warehouse",
        "w_warehouse_sk id!, w_warehouse_id char(16)!, w_warehouse_name varchar(20)",
        "w_warehouse_sq_ft int",
        _addr("w"),
        "w_gmt_offset dec(5,2)",
    ),
    _table(
        "ship_mode",
        "sm_ship_mode_sk id!, sm_ship_mode_id char(16)!, sm_type char(30)",
        "sm_code char(10), sm_carrier char(20), sm_contract char(20)",
    ),
    _table(
        "time_dim",
        "t_time_sk id!, t_time_id char(16)!, t_time int!, t_hour int, t_minute int",
        "t_second int, t_am_pm char(2), t_shift char(20), t_sub_shift char(20)",
        "t_meal_time char(20)",
    ),
    _table("reason", "r_reason_sk id!, r_reason_id char(16)!, r_reason_desc char(100)"),
    _table("income_band", "ib_income_band_sk id!, ib_lower_bound int, ib_upper_bound int"),
    _table(
        "item",
        "i_item_sk id!, i_item_id char(16)!, i_rec_start_date date, i_rec_end_date date",
        "i_item_desc varchar(200), i_current_price dec(7,2), i_wholesale_cost dec(7,2)",
        "i_brand_id int, i_brand char(50), i_class_id int, i_class char(50)",
        "i_category_id int, i_category char(50), i_manufact_id int, i_manufact char(50)",
        "i_size char(20), i_formulation char(20), i_color char(20), i_units char(10)",
        "i_container char(10), i_manager_id int, i_product_name char(50)",
    ),
    _table(
        "store",
        "s_store_sk id!, s_store_id char(16)!, s_rec_start_date date, s_rec_end_date date",
        "s_closed_date_sk id, s_store_name varchar(50), s_number_employees int",
        "s_floor_space int, s_hours char(20), s_manager varchar(40), s_market_id int",
        "s_geography_class varchar(100), s_market_desc varchar(100)",
        "s_market_manager varchar(40), s_division_id int, s_division_name varchar(50)",
        "s_company_id int, s_company_name varchar(50)",
        _addr("s").replace("s_street_number char(10)", "s_street_number varchar(10)"),
        "s_gmt_offset dec(5,2), s_tax_precentage dec(5,2)",
    ),
    _table(
        "call_center",
        "cc_call_center_sk id!, cc_call_center_id char(16)!",
        "cc_rec_start_date date, cc_rec_end_date date, cc_closed_date_sk id",
        "cc_open_date_sk id, cc_name varchar(50), cc_class varchar(50), cc_employees int",
        "cc_sq_ft int, cc_hours char(20), cc_manager varchar(40), cc_mkt_id int",
        "cc_mkt_class char(50), cc_mkt_desc varchar(100), cc_market_manager varchar(40)",
        "cc_division int, cc_division_name varchar(50), cc_company int",
        "cc_company_name char(50)",
        _addr("cc"),
        "cc_gmt_offset dec(5,2), cc_tax_percentage dec(5,2)",
    ),
    _table(
        "customer",
        "c_customer_sk id!, c_customer_id char(16)!, c_current_cdemo_sk id",
        "c_current_hdemo_sk id, c_current_addr_sk id, c_first_shipto_date_sk id",
        "c_first_sales_date_sk id, c_salutation char(10), c_first_name char(20)",
        "c_last_name char(30), c_preferred_cust_flag char(1), c_birth_day int",
        "c_birth_month int, c_birth_year int, c_birth_country varchar(20), c_login char(13)",
        "c_email_address char(50), c_last_review_date_sk id",
    ),
    _table(
        "web_site",
        "web_site_sk id!, web_site_id char(16)!, web_rec_start_date date",
        "web_rec_end_date date, web_name varchar(50), web_open_date_sk id",
        "web_close_date_sk id, web_class varchar(50), web_manager varchar(40)",
        "web_mkt_id int, web_mkt_class varchar(50), web_mkt_desc varchar(100)",
        "web_market_manager varchar(40), web_company_id int, web_company_name char(50)",
        _addr("web"),
        "web_gmt_offset dec(5,2), web_tax_percentage dec(5,2)",
    ),
    _table(
        "store_returns",
        "sr_returned_date_sk id, sr_return_time_sk id, sr_item_sk id!, sr_customer_sk id",
        "sr_cdemo_sk id, sr_hdemo_sk id, sr_addr_sk id, sr_store_sk id, sr_reason_sk id",
        # 64-bit per accepted TPC-DS benchmark practice (reference nds_schema.py:328-331)
        "sr_ticket_number id64!",
        "sr_return_quantity int, sr_return_amt dec(7,2), sr_return_tax dec(7,2)",
        "sr_return_amt_inc_tax dec(7,2), sr_fee dec(7,2), sr_return_ship_cost dec(7,2)",
        "sr_refunded_cash dec(7,2), sr_reversed_charge dec(7,2), sr_store_credit dec(7,2)",
        "sr_net_loss dec(7,2)",
    ),
    _table(
        "household_demographics",
        "hd_demo_sk id!, hd_income_band_sk id, hd_buy_potential char(15)",
        "hd_dep_count int, hd_vehicle_count int",
    ),
    _table(
        "web_page",
        "wp_web_page_sk id!, wp_web_page_id char(16)!, wp_rec_start_date date",
        "wp_rec_end_date date, wp_creation_date_sk id, wp_access_date_sk id",
        "wp_autogen_flag char(1), wp_customer_sk id, wp_url varchar(100), wp_type char(50)",
        "wp_char_count int, wp_link_count int, wp_image_count int, wp_max_ad_count int",
    ),
    _table(
        "promotion",
        "p_promo_sk id!, p_promo_id char(16)!, p_start_date_sk id, p_end_date_sk id",
        "p_item_sk id, p_cost dec(15,2), p_response_target int, p_promo_name char(50)",
        "p_channel_dmail char(1), p_channel_email char(1), p_channel_catalog char(1)",
        "p_channel_tv char(1), p_channel_radio char(1), p_channel_press char(1)",
        "p_channel_event char(1), p_channel_demo char(1), p_channel_details varchar(100)",
        "p_purpose char(15), p_discount_active char(1)",
    ),
    _table(
        "catalog_page",
        "cp_catalog_page_sk id!, cp_catalog_page_id char(16)!, cp_start_date_sk id",
        "cp_end_date_sk id, cp_department varchar(50), cp_catalog_number int",
        "cp_catalog_page_number int, cp_description varchar(100), cp_type varchar(100)",
    ),
    _table(
        "inventory",
        "inv_date_sk id!, inv_item_sk id!, inv_warehouse_sk id!, inv_quantity_on_hand int",
    ),
    _table(
        "catalog_returns",
        "cr_returned_date_sk id, cr_returned_time_sk id, cr_item_sk id!",
        "cr_refunded_customer_sk id, cr_refunded_cdemo_sk id, cr_refunded_hdemo_sk id",
        "cr_refunded_addr_sk id, cr_returning_customer_sk id, cr_returning_cdemo_sk id",
        "cr_returning_hdemo_sk id, cr_returning_addr_sk id, cr_call_center_sk id",
        "cr_catalog_page_sk id, cr_ship_mode_sk id, cr_warehouse_sk id, cr_reason_sk id",
        "cr_order_number id!, cr_return_quantity int, cr_return_amount dec(7,2)",
        "cr_return_tax dec(7,2), cr_return_amt_inc_tax dec(7,2), cr_fee dec(7,2)",
        "cr_return_ship_cost dec(7,2), cr_refunded_cash dec(7,2)",
        "cr_reversed_charge dec(7,2), cr_store_credit dec(7,2), cr_net_loss dec(7,2)",
    ),
    _table(
        "web_returns",
        "wr_returned_date_sk id, wr_returned_time_sk id, wr_item_sk id!",
        "wr_refunded_customer_sk id, wr_refunded_cdemo_sk id, wr_refunded_hdemo_sk id",
        "wr_refunded_addr_sk id, wr_returning_customer_sk id, wr_returning_cdemo_sk id",
        "wr_returning_hdemo_sk id, wr_returning_addr_sk id, wr_web_page_sk id",
        "wr_reason_sk id, wr_order_number id!, wr_return_quantity int",
        "wr_return_amt dec(7,2), wr_return_tax dec(7,2), wr_return_amt_inc_tax dec(7,2)",
        "wr_fee dec(7,2), wr_return_ship_cost dec(7,2), wr_refunded_cash dec(7,2)",
        "wr_reversed_charge dec(7,2), wr_account_credit dec(7,2), wr_net_loss dec(7,2)",
    ),
    _table(
        "web_sales",
        "ws_sold_date_sk id, ws_sold_time_sk id, ws_ship_date_sk id, ws_item_sk id!",
        "ws_bill_customer_sk id, ws_bill_cdemo_sk id, ws_bill_hdemo_sk id",
        "ws_bill_addr_sk id, ws_ship_customer_sk id, ws_ship_cdemo_sk id",
        "ws_ship_hdemo_sk id, ws_ship_addr_sk id, ws_web_page_sk id, ws_web_site_sk id",
        "ws_ship_mode_sk id, ws_warehouse_sk id, ws_promo_sk id, ws_order_number id!",
        "ws_quantity int, ws_wholesale_cost dec(7,2), ws_list_price dec(7,2)",
        "ws_sales_price dec(7,2), ws_ext_discount_amt dec(7,2), ws_ext_sales_price dec(7,2)",
        "ws_ext_wholesale_cost dec(7,2), ws_ext_list_price dec(7,2), ws_ext_tax dec(7,2)",
        "ws_coupon_amt dec(7,2), ws_ext_ship_cost dec(7,2), ws_net_paid dec(7,2)",
        "ws_net_paid_inc_tax dec(7,2), ws_net_paid_inc_ship dec(7,2)",
        "ws_net_paid_inc_ship_tax dec(7,2), ws_net_profit dec(7,2)",
    ),
    _table(
        "catalog_sales",
        "cs_sold_date_sk id, cs_sold_time_sk id, cs_ship_date_sk id",
        "cs_bill_customer_sk id, cs_bill_cdemo_sk id, cs_bill_hdemo_sk id",
        "cs_bill_addr_sk id, cs_ship_customer_sk id, cs_ship_cdemo_sk id",
        "cs_ship_hdemo_sk id, cs_ship_addr_sk id, cs_call_center_sk id",
        "cs_catalog_page_sk id, cs_ship_mode_sk id, cs_warehouse_sk id, cs_item_sk id!",
        "cs_promo_sk id, cs_order_number id!, cs_quantity int, cs_wholesale_cost dec(7,2)",
        "cs_list_price dec(7,2), cs_sales_price dec(7,2), cs_ext_discount_amt dec(7,2)",
        "cs_ext_sales_price dec(7,2), cs_ext_wholesale_cost dec(7,2)",
        "cs_ext_list_price dec(7,2), cs_ext_tax dec(7,2), cs_coupon_amt dec(7,2)",
        "cs_ext_ship_cost dec(7,2), cs_net_paid dec(7,2), cs_net_paid_inc_tax dec(7,2)",
        "cs_net_paid_inc_ship dec(7,2), cs_net_paid_inc_ship_tax dec(7,2)",
        "cs_net_profit dec(7,2)",
    ),
    _table(
        "store_sales",
        "ss_sold_date_sk id, ss_sold_time_sk id, ss_item_sk id!, ss_customer_sk id",
        "ss_cdemo_sk id, ss_hdemo_sk id, ss_addr_sk id, ss_store_sk id, ss_promo_sk id",
        "ss_ticket_number id64!",
        "ss_quantity int, ss_wholesale_cost dec(7,2), ss_list_price dec(7,2)",
        "ss_sales_price dec(7,2), ss_ext_discount_amt dec(7,2), ss_ext_sales_price dec(7,2)",
        "ss_ext_wholesale_cost dec(7,2), ss_ext_list_price dec(7,2), ss_ext_tax dec(7,2)",
        "ss_coupon_amt dec(7,2), ss_net_paid dec(7,2), ss_net_paid_inc_tax dec(7,2)",
        "ss_net_profit dec(7,2)",
    ),
)

# ---------------------------------------------------------------------------
# 12 maintenance staging tables (reference nds_schema.py:570-716)
# ---------------------------------------------------------------------------

_MAINTENANCE_TABLES: tuple[TableSchema, ...] = (
    _table(
        "s_purchase_lineitem",
        "plin_purchase_id int32!, plin_line_number int32!, plin_item_id char(16)",
        "plin_promotion_id char(16), plin_quantity int32, plin_sale_price dec(7,2)",
        "plin_coupon_amt dec(7,2), plin_comment varchar(100)",
    ),
    _table(
        "s_purchase",
        "purc_purchase_id int32!, purc_store_id char(16), purc_customer_id char(16)",
        "purc_purchase_date char(10), purc_purchase_time int32, purc_register_id int32",
        "purc_clerk_id int32, purc_comment char(100)",
    ),
    _table(
        "s_catalog_order",
        "cord_order_id int32!, cord_bill_customer_id char(16)",
        "cord_ship_customer_id char(16), cord_order_date char(10), cord_order_time int32",
        "cord_ship_mode_id char(16), cord_call_center_id char(16)",
        "cord_order_comments varchar(100)",
    ),
    _table(
        "s_web_order",
        "word_order_id int32!, word_bill_customer_id char(16)",
        "word_ship_customer_id char(16), word_order_date char(10), word_order_time int32",
        "word_ship_mode_id char(16), word_web_site_id char(16)",
        "word_order_comments char(100)",
    ),
    _table(
        "s_catalog_order_lineitem",
        "clin_order_id int32!, clin_line_number int32!, clin_item_id char(16)",
        "clin_promotion_id char(16), clin_quantity int32, clin_sales_price dec(7,2)",
        "clin_coupon_amt dec(7,2), clin_warehouse_id char(16), clin_ship_date char(10)",
        "clin_catalog_number int32, clin_catalog_page_number int32, clin_ship_cost dec(7,2)",
    ),
    _table(
        "s_web_order_lineitem",
        "wlin_order_id int32!, wlin_line_number int32!, wlin_item_id char(16)",
        "wlin_promotion_id char(16), wlin_quantity int32, wlin_sales_price dec(7,2)",
        "wlin_coupon_amt dec(7,2), wlin_warehouse_id char(16), wlin_ship_date char(10)",
        "wlin_ship_cost dec(7,2), wlin_web_page_id char(16)",
    ),
    _table(
        "s_store_returns",
        "sret_store_id char(16), sret_purchase_id char(16)!, sret_line_number int32!",
        "sret_item_id char(16)!, sret_customer_id char(16), sret_return_date char(10)",
        "sret_return_time char(10), sret_ticket_number int, sret_return_qty int32",
        "sret_return_amt dec(7,2), sret_return_tax dec(7,2), sret_return_fee dec(7,2)",
        "sret_return_ship_cost dec(7,2), sret_refunded_cash dec(7,2)",
        "sret_reversed_charge dec(7,2), sret_store_credit dec(7,2), sret_reason_id char(16)",
    ),
    _table(
        "s_catalog_returns",
        "cret_call_center_id char(16), cret_order_id int32!, cret_line_number int32!",
        "cret_item_id char(16)!, cret_return_customer_id char(16)",
        "cret_refund_customer_id char(16), cret_return_date char(10)",
        "cret_return_time char(10), cret_return_qty int32, cret_return_amt dec(7,2)",
        "cret_return_tax dec(7,2), cret_return_fee dec(7,2)",
        "cret_return_ship_cost dec(7,2), cret_refunded_cash dec(7,2)",
        "cret_reversed_charge dec(7,2), cret_merchant_credit dec(7,2)",
        "cret_reason_id char(16), cret_shipmode_id char(16)",
        "cret_catalog_page_id char(16), cret_warehouse_id char(16)",
    ),
    _table(
        "s_web_returns",
        "wret_web_page_id char(16), wret_order_id int32!, wret_line_number int32!",
        "wret_item_id char(16)!, wret_return_customer_id char(16)",
        "wret_refund_customer_id char(16), wret_return_date char(10)",
        "wret_return_time char(10), wret_return_qty int32, wret_return_amt dec(7,2)",
        "wret_return_tax dec(7,2), wret_return_fee dec(7,2)",
        "wret_return_ship_cost dec(7,2), wret_refunded_cash dec(7,2)",
        "wret_reversed_charge dec(7,2), wret_account_credit dec(7,2)",
        "wret_reason_id char(16)",
    ),
    _table(
        "s_inventory",
        "invn_warehouse_id char(16)!, invn_item_id char(16)!, invn_date char(10)!",
        "invn_qty_on_hand int32",
    ),
    _table("delete", "date1 str!, date2 str!"),
    _table("inventory_delete", "date1 str!, date2 str!"),
)


# Single-column unique keys (TPC-DS spec §2 primary keys): every dimension
# table's surrogate key is unique; fact, returns, and inventory tables have
# COMPOSITE primary keys and deliberately list nothing here (inv_date_sk is
# the first column of inventory but repeats per item/warehouse). Consumed by
# the planner's late-materialization legality analysis: a join against one of
# these keys is provably 1:1 per matched probe row, so dimension attributes
# may be gathered after aggregation.
UNIQUE_KEYS: dict[str, tuple[str, ...]] = {
    "customer_address": ("ca_address_sk",),
    "customer_demographics": ("cd_demo_sk",),
    "date_dim": ("d_date_sk",),
    "warehouse": ("w_warehouse_sk",),
    "ship_mode": ("sm_ship_mode_sk",),
    "time_dim": ("t_time_sk",),
    "reason": ("r_reason_sk",),
    "income_band": ("ib_income_band_sk",),
    "item": ("i_item_sk",),
    "store": ("s_store_sk",),
    "call_center": ("cc_call_center_sk",),
    "customer": ("c_customer_sk",),
    "web_site": ("web_site_sk",),
    "household_demographics": ("hd_demo_sk",),
    "web_page": ("wp_web_page_sk",),
    "promotion": ("p_promo_sk",),
    "catalog_page": ("cp_catalog_page_sk",),
}


@lru_cache(maxsize=None)
def get_schemas(use_decimal: bool = True) -> dict[str, TableSchema]:
    """All 24 source-table schemas, keyed by table name.

    ``use_decimal`` is kept for interface parity; the logical schema is identical,
    only ``arrow_schema(use_decimal=...)`` changes the physical decimal mapping.
    """
    del use_decimal
    return {t.name: t for t in _SOURCE_TABLES}


@lru_cache(maxsize=None)
def get_maintenance_schemas(use_decimal: bool = True) -> dict[str, TableSchema]:
    """All 12 maintenance staging-table schemas, keyed by table name."""
    del use_decimal
    return {t.name: t for t in _MAINTENANCE_TABLES}


def all_schemas() -> dict[str, TableSchema]:
    return {**get_schemas(), **get_maintenance_schemas()}


if __name__ == "__main__":
    for nm, sch in all_schemas().items():
        print(f"{nm}: {len(sch.columns)} columns")
