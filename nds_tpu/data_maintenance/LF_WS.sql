-- LF_WS: refresh-insert web_sales from web-order staging tables
-- (role of reference nds/data_maintenance/LF_WS.sql, original SQL).
CREATE TEMP VIEW wsv AS
SELECT d1.d_date_sk AS ws_sold_date_sk,
       t_time_sk AS ws_sold_time_sk,
       d2.d_date_sk AS ws_ship_date_sk,
       i_item_sk AS ws_item_sk,
       c1.c_customer_sk AS ws_bill_customer_sk,
       c1.c_current_cdemo_sk AS ws_bill_cdemo_sk,
       c1.c_current_hdemo_sk AS ws_bill_hdemo_sk,
       c1.c_current_addr_sk AS ws_bill_addr_sk,
       c2.c_customer_sk AS ws_ship_customer_sk,
       c2.c_current_cdemo_sk AS ws_ship_cdemo_sk,
       c2.c_current_hdemo_sk AS ws_ship_hdemo_sk,
       c2.c_current_addr_sk AS ws_ship_addr_sk,
       wp_web_page_sk AS ws_web_page_sk,
       web_site_sk AS ws_web_site_sk,
       sm_ship_mode_sk AS ws_ship_mode_sk,
       w_warehouse_sk AS ws_warehouse_sk,
       p_promo_sk AS ws_promo_sk,
       word_order_id AS ws_order_number,
       wlin_quantity AS ws_quantity,
       i_wholesale_cost AS ws_wholesale_cost,
       i_current_price AS ws_list_price,
       wlin_sales_price AS ws_sales_price,
       (i_current_price - wlin_sales_price) * wlin_quantity AS ws_ext_discount_amt,
       wlin_sales_price * wlin_quantity AS ws_ext_sales_price,
       i_wholesale_cost * wlin_quantity AS ws_ext_wholesale_cost,
       i_current_price * wlin_quantity AS ws_ext_list_price,
       ROUND(wlin_sales_price * wlin_quantity * 0.08, 2) AS ws_ext_tax,
       wlin_coupon_amt AS ws_coupon_amt,
       wlin_ship_cost * wlin_quantity AS ws_ext_ship_cost,
       wlin_sales_price * wlin_quantity - wlin_coupon_amt AS ws_net_paid,
       ROUND((wlin_sales_price * wlin_quantity - wlin_coupon_amt) * 1.08, 2) AS ws_net_paid_inc_tax,
       wlin_sales_price * wlin_quantity - wlin_coupon_amt
         + wlin_ship_cost * wlin_quantity AS ws_net_paid_inc_ship,
       ROUND((wlin_sales_price * wlin_quantity - wlin_coupon_amt) * 1.08, 2)
         + wlin_ship_cost * wlin_quantity AS ws_net_paid_inc_ship_tax,
       wlin_sales_price * wlin_quantity - wlin_coupon_amt
         - i_wholesale_cost * wlin_quantity AS ws_net_profit
-- join kinds mirror the reference row-for-row (LF_WS.sql: all dimension
-- lookups LEFT OUTER; the SCD tables item/web_page/web_site restrict to
-- the CURRENT record, *_rec_end_date IS NULL, via pre-filtered builds)
FROM s_web_order
JOIN s_web_order_lineitem ON word_order_id = wlin_order_id
LEFT JOIN (SELECT i_item_sk, i_item_id, i_wholesale_cost, i_current_price
           FROM item WHERE i_rec_end_date IS NULL) item
  ON i_item_id = wlin_item_id
LEFT JOIN date_dim d1 ON d1.d_date = CAST(word_order_date AS DATE)
LEFT JOIN date_dim d2 ON d2.d_date = CAST(wlin_ship_date AS DATE)
LEFT JOIN time_dim ON t_time = word_order_time
LEFT JOIN customer c1 ON c1.c_customer_id = word_bill_customer_id
LEFT JOIN customer c2 ON c2.c_customer_id = word_ship_customer_id
LEFT JOIN (SELECT wp_web_page_sk, wp_web_page_id FROM web_page
           WHERE wp_rec_end_date IS NULL) web_page
  ON wp_web_page_id = wlin_web_page_id
LEFT JOIN (SELECT web_site_sk, web_site_id FROM web_site
           WHERE web_rec_end_date IS NULL) web_site
  ON web_site_id = word_web_site_id
LEFT JOIN ship_mode ON sm_ship_mode_id = word_ship_mode_id
LEFT JOIN warehouse ON w_warehouse_id = wlin_warehouse_id
LEFT JOIN promotion ON p_promo_id = wlin_promotion_id;
INSERT INTO web_sales SELECT * FROM wsv;
DROP VIEW wsv
