-- LF_SS: refresh-insert store_sales from the purchase staging tables.
-- Same transformation the reference's LF_SS performs (reference
-- nds/data_maintenance/LF_SS.sql: staging -> dimension joins -> INSERT),
-- written for this framework's dialect and staging schemas.
CREATE TEMP VIEW ssv AS
SELECT d_date_sk AS ss_sold_date_sk,
       t_time_sk AS ss_sold_time_sk,
       i_item_sk AS ss_item_sk,
       c_customer_sk AS ss_customer_sk,
       c_current_cdemo_sk AS ss_cdemo_sk,
       c_current_hdemo_sk AS ss_hdemo_sk,
       c_current_addr_sk AS ss_addr_sk,
       s_store_sk AS ss_store_sk,
       p_promo_sk AS ss_promo_sk,
       purc_purchase_id AS ss_ticket_number,
       plin_quantity AS ss_quantity,
       i_wholesale_cost AS ss_wholesale_cost,
       i_current_price AS ss_list_price,
       plin_sale_price AS ss_sales_price,
       (i_current_price - plin_sale_price) * plin_quantity AS ss_ext_discount_amt,
       plin_sale_price * plin_quantity AS ss_ext_sales_price,
       i_wholesale_cost * plin_quantity AS ss_ext_wholesale_cost,
       i_current_price * plin_quantity AS ss_ext_list_price,
       ROUND(plin_sale_price * plin_quantity * 0.08, 2) AS ss_ext_tax,
       plin_coupon_amt AS ss_coupon_amt,
       plin_sale_price * plin_quantity - plin_coupon_amt AS ss_net_paid,
       ROUND((plin_sale_price * plin_quantity - plin_coupon_amt) * 1.08, 2) AS ss_net_paid_inc_tax,
       plin_sale_price * plin_quantity - plin_coupon_amt
         - i_wholesale_cost * plin_quantity AS ss_net_profit
-- join kinds mirror the reference row-for-row (LF_SS.sql: every dimension
-- lookup LEFT OUTER so failed lookups still insert with NULL surrogate
-- keys; only the order->lineitem join is INNER)
FROM s_purchase
JOIN s_purchase_lineitem ON purc_purchase_id = plin_purchase_id
LEFT JOIN item ON i_item_id = plin_item_id
LEFT JOIN date_dim ON d_date = CAST(purc_purchase_date AS DATE)
LEFT JOIN time_dim ON t_time = purc_purchase_time
LEFT JOIN customer ON c_customer_id = purc_customer_id
LEFT JOIN store ON s_store_id = purc_store_id
LEFT JOIN promotion ON p_promo_id = plin_promotion_id;
INSERT INTO store_sales SELECT * FROM ssv;
DROP VIEW ssv
