-- DF_CS: delete catalog channel rows in the [DATE1, DATE2] sales-date window
-- (role of reference nds/data_maintenance/DF_CS.sql).
DELETE FROM catalog_returns WHERE cr_order_number IN
  (SELECT cs_order_number FROM catalog_sales WHERE cs_sold_date_sk IN
    (SELECT d_date_sk FROM date_dim
     WHERE d_date BETWEEN CAST('DATE1' AS DATE) AND CAST('DATE2' AS DATE)));
DELETE FROM catalog_sales WHERE cs_sold_date_sk IN
  (SELECT d_date_sk FROM date_dim
   WHERE d_date BETWEEN CAST('DATE1' AS DATE) AND CAST('DATE2' AS DATE))
