-- LF_SR: refresh-insert store_returns from the returns staging table
-- (role of reference nds/data_maintenance/LF_SR.sql, original SQL).
CREATE TEMP VIEW srv AS
SELECT d_date_sk AS sr_returned_date_sk,
       t_time_sk AS sr_return_time_sk,
       i_item_sk AS sr_item_sk,
       c_customer_sk AS sr_customer_sk,
       c_current_cdemo_sk AS sr_cdemo_sk,
       c_current_hdemo_sk AS sr_hdemo_sk,
       c_current_addr_sk AS sr_addr_sk,
       s_store_sk AS sr_store_sk,
       r_reason_sk AS sr_reason_sk,
       sret_ticket_number AS sr_ticket_number,
       sret_return_qty AS sr_return_quantity,
       sret_return_amt AS sr_return_amt,
       sret_return_tax AS sr_return_tax,
       sret_return_amt + sret_return_tax AS sr_return_amt_inc_tax,
       sret_return_fee AS sr_fee,
       sret_return_ship_cost AS sr_return_ship_cost,
       sret_refunded_cash AS sr_refunded_cash,
       sret_reversed_charge AS sr_reversed_charge,
       sret_store_credit AS sr_store_credit,
       sret_return_amt + sret_return_tax + sret_return_fee
         + sret_return_ship_cost - sret_refunded_cash
         - sret_reversed_charge - sret_store_credit AS sr_net_loss
-- join kinds mirror the reference row-for-row (LF_SR.sql: every lookup
-- LEFT OUTER — failed lookups insert with NULL surrogate keys)
FROM s_store_returns
LEFT JOIN item ON i_item_id = sret_item_id
LEFT JOIN date_dim ON d_date = CAST(sret_return_date AS DATE)
LEFT JOIN time_dim ON t_time = CAST(sret_return_time AS INT)
LEFT JOIN customer ON c_customer_id = sret_customer_id
LEFT JOIN store ON s_store_id = sret_store_id
LEFT JOIN reason ON r_reason_id = sret_reason_id;
INSERT INTO store_returns SELECT * FROM srv;
DROP VIEW srv
