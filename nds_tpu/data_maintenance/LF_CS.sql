-- LF_CS: refresh-insert catalog_sales from catalog-order staging tables
-- (role of reference nds/data_maintenance/LF_CS.sql, original SQL).
CREATE TEMP VIEW csv AS
SELECT d1.d_date_sk AS cs_sold_date_sk,
       t_time_sk AS cs_sold_time_sk,
       d2.d_date_sk AS cs_ship_date_sk,
       c1.c_customer_sk AS cs_bill_customer_sk,
       c1.c_current_cdemo_sk AS cs_bill_cdemo_sk,
       c1.c_current_hdemo_sk AS cs_bill_hdemo_sk,
       c1.c_current_addr_sk AS cs_bill_addr_sk,
       c2.c_customer_sk AS cs_ship_customer_sk,
       c2.c_current_cdemo_sk AS cs_ship_cdemo_sk,
       c2.c_current_hdemo_sk AS cs_ship_hdemo_sk,
       c2.c_current_addr_sk AS cs_ship_addr_sk,
       cc_call_center_sk AS cs_call_center_sk,
       cp_catalog_page_sk AS cs_catalog_page_sk,
       sm_ship_mode_sk AS cs_ship_mode_sk,
       w_warehouse_sk AS cs_warehouse_sk,
       i_item_sk AS cs_item_sk,
       p_promo_sk AS cs_promo_sk,
       cord_order_id AS cs_order_number,
       clin_quantity AS cs_quantity,
       i_wholesale_cost AS cs_wholesale_cost,
       i_current_price AS cs_list_price,
       clin_sales_price AS cs_sales_price,
       (i_current_price - clin_sales_price) * clin_quantity AS cs_ext_discount_amt,
       clin_sales_price * clin_quantity AS cs_ext_sales_price,
       i_wholesale_cost * clin_quantity AS cs_ext_wholesale_cost,
       i_current_price * clin_quantity AS cs_ext_list_price,
       ROUND(clin_sales_price * clin_quantity * 0.08, 2) AS cs_ext_tax,
       clin_coupon_amt AS cs_coupon_amt,
       clin_ship_cost * clin_quantity AS cs_ext_ship_cost,
       clin_sales_price * clin_quantity - clin_coupon_amt AS cs_net_paid,
       ROUND((clin_sales_price * clin_quantity - clin_coupon_amt) * 1.08, 2) AS cs_net_paid_inc_tax,
       clin_sales_price * clin_quantity - clin_coupon_amt
         + clin_ship_cost * clin_quantity AS cs_net_paid_inc_ship,
       ROUND((clin_sales_price * clin_quantity - clin_coupon_amt) * 1.08, 2)
         + clin_ship_cost * clin_quantity AS cs_net_paid_inc_ship_tax,
       clin_sales_price * clin_quantity - clin_coupon_amt
         - i_wholesale_cost * clin_quantity AS cs_net_profit
-- join kinds mirror the reference row-for-row (LF_CS.sql: all dimension
-- lookups LEFT OUTER; SCD tables item/call_center restrict to the CURRENT
-- record, *_rec_end_date IS NULL, via pre-filtered builds)
FROM s_catalog_order
JOIN s_catalog_order_lineitem ON cord_order_id = clin_order_id
LEFT JOIN (SELECT i_item_sk, i_item_id, i_wholesale_cost, i_current_price
           FROM item WHERE i_rec_end_date IS NULL) item
  ON i_item_id = clin_item_id
LEFT JOIN date_dim d1 ON d1.d_date = CAST(cord_order_date AS DATE)
LEFT JOIN date_dim d2 ON d2.d_date = CAST(clin_ship_date AS DATE)
LEFT JOIN time_dim ON t_time = cord_order_time
LEFT JOIN customer c1 ON c1.c_customer_id = cord_bill_customer_id
LEFT JOIN customer c2 ON c2.c_customer_id = cord_ship_customer_id
LEFT JOIN (SELECT cc_call_center_sk, cc_call_center_id FROM call_center
           WHERE cc_rec_end_date IS NULL) call_center
  ON cc_call_center_id = cord_call_center_id
LEFT JOIN catalog_page ON cp_catalog_number = clin_catalog_number
  AND cp_catalog_page_number = clin_catalog_page_number
LEFT JOIN ship_mode ON sm_ship_mode_id = cord_ship_mode_id
LEFT JOIN warehouse ON w_warehouse_id = clin_warehouse_id
LEFT JOIN promotion ON p_promo_id = clin_promotion_id;
INSERT INTO catalog_sales SELECT * FROM csv;
DROP VIEW csv
