-- DF_WS: delete web channel rows in the [DATE1, DATE2] sales-date window
-- (role of reference nds/data_maintenance/DF_WS.sql).
DELETE FROM web_returns WHERE wr_order_number IN
  (SELECT ws_order_number FROM web_sales WHERE ws_sold_date_sk IN
    (SELECT d_date_sk FROM date_dim
     WHERE d_date BETWEEN CAST('DATE1' AS DATE) AND CAST('DATE2' AS DATE)));
DELETE FROM web_sales WHERE ws_sold_date_sk IN
  (SELECT d_date_sk FROM date_dim
   WHERE d_date BETWEEN CAST('DATE1' AS DATE) AND CAST('DATE2' AS DATE))
