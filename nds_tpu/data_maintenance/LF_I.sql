-- LF_I: refresh-insert inventory from the inventory staging table
-- (role of reference nds/data_maintenance/LF_I.sql, original SQL).
CREATE TEMP VIEW iv AS
SELECT d_date_sk AS inv_date_sk,
       i_item_sk AS inv_item_sk,
       w_warehouse_sk AS inv_warehouse_sk,
       invn_qty_on_hand AS inv_quantity_on_hand
-- join kinds mirror the reference row-for-row (LF_I.sql: every lookup
-- LEFT OUTER; item restricts to the CURRENT SCD record)
FROM s_inventory
LEFT JOIN warehouse ON w_warehouse_id = invn_warehouse_id
LEFT JOIN (SELECT i_item_sk, i_item_id FROM item
           WHERE i_rec_end_date IS NULL) item
  ON i_item_id = invn_item_id
LEFT JOIN date_dim ON d_date = CAST(invn_date AS DATE);
INSERT INTO inventory SELECT * FROM iv;
DROP VIEW iv
