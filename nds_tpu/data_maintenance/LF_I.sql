-- LF_I: refresh-insert inventory from the inventory staging table
-- (role of reference nds/data_maintenance/LF_I.sql, original SQL).
CREATE TEMP VIEW iv AS
SELECT d_date_sk AS inv_date_sk,
       i_item_sk AS inv_item_sk,
       w_warehouse_sk AS inv_warehouse_sk,
       invn_qty_on_hand AS inv_quantity_on_hand
FROM s_inventory
JOIN warehouse ON w_warehouse_id = invn_warehouse_id
JOIN item ON i_item_id = invn_item_id
JOIN date_dim ON d_date = CAST(invn_date AS DATE);
INSERT INTO inventory SELECT * FROM iv;
DROP VIEW iv
