-- LF_CR: refresh-insert catalog_returns from the returns staging table
-- (role of reference nds/data_maintenance/LF_CR.sql, original SQL).
CREATE TEMP VIEW crv AS
SELECT d_date_sk AS cr_returned_date_sk,
       t_time_sk AS cr_returned_time_sk,
       i_item_sk AS cr_item_sk,
       c1.c_customer_sk AS cr_refunded_customer_sk,
       c1.c_current_cdemo_sk AS cr_refunded_cdemo_sk,
       c1.c_current_hdemo_sk AS cr_refunded_hdemo_sk,
       c1.c_current_addr_sk AS cr_refunded_addr_sk,
       c2.c_customer_sk AS cr_returning_customer_sk,
       c2.c_current_cdemo_sk AS cr_returning_cdemo_sk,
       c2.c_current_hdemo_sk AS cr_returning_hdemo_sk,
       c2.c_current_addr_sk AS cr_returning_addr_sk,
       cc_call_center_sk AS cr_call_center_sk,
       cp_catalog_page_sk AS cr_catalog_page_sk,
       sm_ship_mode_sk AS cr_ship_mode_sk,
       w_warehouse_sk AS cr_warehouse_sk,
       r_reason_sk AS cr_reason_sk,
       cret_order_id AS cr_order_number,
       cret_return_qty AS cr_return_quantity,
       cret_return_amt AS cr_return_amount,
       cret_return_tax AS cr_return_tax,
       cret_return_amt + cret_return_tax AS cr_return_amt_inc_tax,
       cret_return_fee AS cr_fee,
       cret_return_ship_cost AS cr_return_ship_cost,
       cret_refunded_cash AS cr_refunded_cash,
       cret_reversed_charge AS cr_reversed_charge,
       cret_merchant_credit AS cr_store_credit,
       cret_return_amt + cret_return_tax + cret_return_fee
         + cret_return_ship_cost - cret_refunded_cash
         - cret_reversed_charge - cret_merchant_credit AS cr_net_loss
-- join kinds mirror the reference row-for-row (LF_CR.sql: every lookup
-- LEFT OUTER — failed lookups insert with NULL surrogate keys)
FROM s_catalog_returns
LEFT JOIN item ON i_item_id = cret_item_id
LEFT JOIN date_dim ON d_date = CAST(cret_return_date AS DATE)
LEFT JOIN time_dim ON t_time = CAST(cret_return_time AS INT)
LEFT JOIN customer c1 ON c1.c_customer_id = cret_refund_customer_id
LEFT JOIN customer c2 ON c2.c_customer_id = cret_return_customer_id
LEFT JOIN call_center ON cc_call_center_id = cret_call_center_id
LEFT JOIN catalog_page ON cp_catalog_page_id = cret_catalog_page_id
LEFT JOIN ship_mode ON sm_ship_mode_id = cret_shipmode_id
LEFT JOIN warehouse ON w_warehouse_id = cret_warehouse_id
LEFT JOIN reason ON r_reason_id = cret_reason_id;
INSERT INTO catalog_returns SELECT * FROM crv;
DROP VIEW crv
