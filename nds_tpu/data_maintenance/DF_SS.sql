-- DF_SS: delete store channel rows in the [DATE1, DATE2] sales-date window
-- (role of reference nds/data_maintenance/DF_SS.sql: returns first via the
-- ticket-number subquery, then the sales rows).
DELETE FROM store_returns WHERE sr_ticket_number IN
  (SELECT ss_ticket_number FROM store_sales WHERE ss_sold_date_sk IN
    (SELECT d_date_sk FROM date_dim
     WHERE d_date BETWEEN CAST('DATE1' AS DATE) AND CAST('DATE2' AS DATE)));
DELETE FROM store_sales WHERE ss_sold_date_sk IN
  (SELECT d_date_sk FROM date_dim
   WHERE d_date BETWEEN CAST('DATE1' AS DATE) AND CAST('DATE2' AS DATE))
