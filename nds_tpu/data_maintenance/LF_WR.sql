-- LF_WR: refresh-insert web_returns from the returns staging table
-- (role of reference nds/data_maintenance/LF_WR.sql, original SQL).
CREATE TEMP VIEW wrv AS
SELECT d_date_sk AS wr_returned_date_sk,
       t_time_sk AS wr_returned_time_sk,
       i_item_sk AS wr_item_sk,
       c1.c_customer_sk AS wr_refunded_customer_sk,
       c1.c_current_cdemo_sk AS wr_refunded_cdemo_sk,
       c1.c_current_hdemo_sk AS wr_refunded_hdemo_sk,
       c1.c_current_addr_sk AS wr_refunded_addr_sk,
       c2.c_customer_sk AS wr_returning_customer_sk,
       c2.c_current_cdemo_sk AS wr_returning_cdemo_sk,
       c2.c_current_hdemo_sk AS wr_returning_hdemo_sk,
       c2.c_current_addr_sk AS wr_returning_addr_sk,
       wp_web_page_sk AS wr_web_page_sk,
       r_reason_sk AS wr_reason_sk,
       wret_order_id AS wr_order_number,
       wret_return_qty AS wr_return_quantity,
       wret_return_amt AS wr_return_amt,
       wret_return_tax AS wr_return_tax,
       wret_return_amt + wret_return_tax AS wr_return_amt_inc_tax,
       wret_return_fee AS wr_fee,
       wret_return_ship_cost AS wr_return_ship_cost,
       wret_refunded_cash AS wr_refunded_cash,
       wret_reversed_charge AS wr_reversed_charge,
       wret_account_credit AS wr_account_credit,
       wret_return_amt + wret_return_tax + wret_return_fee
         + wret_return_ship_cost - wret_refunded_cash
         - wret_reversed_charge - wret_account_credit AS wr_net_loss
-- join kinds mirror the reference row-for-row (LF_WR.sql: every lookup
-- LEFT OUTER — failed lookups insert with NULL surrogate keys)
FROM s_web_returns
LEFT JOIN item ON i_item_id = wret_item_id
LEFT JOIN date_dim ON d_date = CAST(wret_return_date AS DATE)
LEFT JOIN time_dim ON t_time = CAST(wret_return_time AS INT)
LEFT JOIN customer c1 ON c1.c_customer_id = wret_refund_customer_id
LEFT JOIN customer c2 ON c2.c_customer_id = wret_return_customer_id
LEFT JOIN web_page ON wp_web_page_id = wret_web_page_id
LEFT JOIN reason ON r_reason_id = wret_reason_id;
INSERT INTO web_returns SELECT * FROM wrv;
DROP VIEW wrv
