-- DF_I: delete inventory snapshots in the [DATE1, DATE2] window
-- (role of reference nds/data_maintenance/DF_I.sql).
DELETE FROM inventory WHERE inv_date_sk IN
  (SELECT d_date_sk FROM date_dim
   WHERE d_date BETWEEN CAST('DATE1' AS DATE) AND CAST('DATE2' AS DATE))
