"""CLI entry point: ``python -m nds_tpu.analysis [--json] <path>...``"""
import sys

from . import main

if __name__ == "__main__":
    sys.exit(main())
