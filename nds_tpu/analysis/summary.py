"""Per-module summary pass: one AST walk per file extracts every fact the
whole-program rules need, so ENG003-ENG006 run off summaries instead of
re-walking trees.

Per function (methods keep their enclosing class) the pass records:

- lock acquisitions (``with <lock>:``): raw dotted name, the lexical
  held-lock stack at the acquisition, and the lock-order-exempt pragma;
- call sites with the held-lock stack, receiver shape (``self.m()`` vs
  ``x.m()`` vs bare ``f()``), and the mode string of ``open()`` calls —
  the lock-order propagation (ENG003) and device-lane purity (ENG004)
  inputs;
- raise sites with the statically-resolvable class name (ENG005);
- whether the def carries the ``thread-entry`` / ``device-lane`` marker.

Per module it also records class definitions with base-class names (the
program-wide hierarchy ENG005 resolves typed-ness through), metric
declarations/uses (ENG006), the ``TYPED_ERRORS`` literal, and the
``cls == "X"`` branch strings of ``reconstruct_error`` (the wire table).
"""
from __future__ import annotations

import ast
from dataclasses import dataclass, field

from .base import (def_header_pragma, dotted, has_pragma, iter_py_files,
                   lock_ctx_name, root_name)

#: attribute-method names whose call is a metric write (Counter.inc,
#: Gauge.set/dec/add, Histogram.observe)
METRIC_WRITE_METHODS = frozenset({"inc", "dec", "add", "set", "observe"})
METRIC_CTORS = frozenset({"counter", "gauge", "histogram"})


@dataclass
class LockAcq:
    raw: str                  # dotted source spelling ('self._sql_lock')
    line: int
    held: tuple[str, ...]     # raw dotted names held at this acquisition
    cls: str                  # enclosing class name ('' at module scope)
    exempt: bool              # lock-order-exempt pragma on the line


@dataclass
class CallSite:
    name: str                 # terminal name ('inc', 'sleep', 'foo')
    dot: str                  # best-effort dotted ('time.sleep', '')
    recv_root: str            # leftmost Name of the receiver chain
    is_self: bool             # self.m(...) call
    is_bare: bool             # f(...) call (no receiver)
    line: int
    held: tuple[str, ...]     # raw lock names held at the call
    in_lane: bool             # lexically inside a device-lane def
    open_mode: str | None     # literal mode of an open() call, if any
    lock_exempt: bool         # lock-order-exempt pragma on the line
    lane_exempt: bool         # device-lane-exempt pragma on the line


@dataclass
class RaiseSite:
    cls: str | None           # 'ValueError' for raise ValueError(...);
    line: int                 # None for bare raise / raise <variable>
    exempt: bool              # typed-error-exempt pragma on the line
    from_except: bool         # re-raise of a caught name


@dataclass
class MetricDecl:
    name: str                 # metric name (first literal arg)
    kind: str                 # counter | gauge | histogram
    has_help: bool
    const: str | None         # CONST = METRICS.counter(...) binding
    line: int


@dataclass
class MetricUse:
    const: str                # terminal ALL_CAPS receiver name
    method: str
    line: int
    exempt: bool              # counter-exempt pragma on the line


@dataclass
class FunctionSummary:
    module: str               # file path
    cls: str                  # enclosing class ('' for module functions)
    name: str
    line: int
    end_line: int
    lane: bool                # device-lane marker on the def header
    thread_entry: bool
    locks: list[LockAcq] = field(default_factory=list)
    calls: list[CallSite] = field(default_factory=list)
    raises_: list[RaiseSite] = field(default_factory=list)

    @property
    def qualname(self) -> str:
        return f"{self.cls}.{self.name}" if self.cls else self.name


@dataclass
class ModuleSummary:
    path: str
    lines: list[str]
    functions: list[FunctionSummary] = field(default_factory=list)
    classes: dict[str, list[str]] = field(default_factory=dict)
    metric_decls: list[MetricDecl] = field(default_factory=list)
    metric_uses: list[MetricUse] = field(default_factory=list)
    typed_errors: frozenset | None = None     # TYPED_ERRORS literal
    wire_branches: dict[str, int] | None = None   # reconstruct_error table
    wire_table_line: int = 0
    parse_error: tuple[int, str] | None = None
    #: 1-based line numbers that belong to a def header (def line through
    #: the line before the first body statement) — the only place marker
    #: pragmas (thread-entry / device-lane) are meaningful
    header_lines: set[int] = field(default_factory=set)


@dataclass
class ProgramSummary:
    modules: list[ModuleSummary]

    def __post_init__(self):
        self.functions: list[FunctionSummary] = [
            f for m in self.modules for f in m.functions]
        # name -> [FunctionSummary]; methods and functions share the index
        self.by_name: dict[str, list[FunctionSummary]] = {}
        for f in self.functions:
            self.by_name.setdefault(f.name, []).append(f)
        # class -> base names (program-wide, by simple name)
        self.class_bases: dict[str, list[str]] = {}
        for m in self.modules:
            for cname, bases in m.classes.items():
                self.class_bases.setdefault(cname, bases)
        self.typed_errors: frozenset | None = None
        for m in self.modules:
            if m.typed_errors is not None:
                self.typed_errors = m.typed_errors
                break

    def ancestors(self, cls: str) -> set[str]:
        """Transitive base-class names of ``cls`` (name-resolved across
        the whole linted tree; builtins terminate the walk)."""
        out: set[str] = set()
        stack = [cls]
        while stack:
            c = stack.pop()
            for b in self.class_bases.get(c, ()):  # unknown => builtin/ext
                if b not in out:
                    out.add(b)
                    stack.append(b)
        return out

    def methods_of(self, cls: str, name: str) -> list[FunctionSummary]:
        return [f for f in self.by_name.get(name, ()) if f.cls == cls]


class _ModuleWalker(ast.NodeVisitor):
    def __init__(self, mod: ModuleSummary):
        self.mod = mod
        self._class_stack: list[str] = []
        self._fn_stack: list[FunctionSummary] = []
        self._lock_stack: list[str] = []
        self._lane_depth = 0
        self._except_names: set[str] = set()

    # -- structure -----------------------------------------------------------
    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.mod.classes[node.name] = [
            dotted(b).rsplit(".", 1)[-1] for b in node.bases if dotted(b)]
        self._class_stack.append(node.name)
        self.generic_visit(node)
        self._class_stack.pop()

    def _visit_fn(self, node) -> None:
        lines = self.mod.lines
        header_end = node.body[0].lineno if node.body else node.lineno
        self.mod.header_lines.update(range(node.lineno, header_end + 1))
        fn = FunctionSummary(
            module=self.mod.path,
            cls=self._class_stack[-1] if self._class_stack else "",
            name=node.name, line=node.lineno,
            end_line=getattr(node, "end_lineno", node.lineno) or node.lineno,
            lane=def_header_pragma(lines, node, "device-lane"),
            thread_entry=def_header_pragma(lines, node, "thread-entry"))
        self.mod.functions.append(fn)
        self._fn_stack.append(fn)
        lane = fn.lane
        if lane:
            self._lane_depth += 1
        if node.name == "reconstruct_error":
            self._collect_wire_table(node)
        self.generic_visit(node)
        if lane:
            self._lane_depth -= 1
        self._fn_stack.pop()

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn

    def visit_With(self, node: ast.With) -> None:
        names = [lock_ctx_name(i.context_expr) for i in node.items]
        names = [n for n in names if n]
        fn = self._fn_stack[-1] if self._fn_stack else None
        for n in names:
            if fn is not None:
                fn.locks.append(LockAcq(
                    raw=n, line=node.lineno, held=tuple(self._lock_stack),
                    cls=fn.cls,
                    exempt=has_pragma(self.mod.lines, node.lineno,
                                      "lock-order-exempt")))
            self._lock_stack.append(n)
        self.generic_visit(node)
        for _ in names:
            self._lock_stack.pop()

    def visit_Try(self, node: ast.Try) -> None:
        for stmt in node.body + node.orelse + node.finalbody:
            self.visit(stmt)
        for h in node.handlers:
            added = h.name if h.name else None
            if added:
                self._except_names.add(added)
            for stmt in h.body:
                self.visit(stmt)
            if added:
                self._except_names.discard(added)

    # -- facts ---------------------------------------------------------------
    def visit_Raise(self, node: ast.Raise) -> None:
        fn = self._fn_stack[-1] if self._fn_stack else None
        if fn is not None:
            cls = None
            from_except = node.exc is None
            exc = node.exc
            if isinstance(exc, ast.Call):
                d = dotted(exc.func)
                cls = d.rsplit(".", 1)[-1] if d else None
                if cls and not cls[:1].isupper():
                    cls = None       # lowercase factory call: unresolvable
            elif isinstance(exc, ast.Name):
                if exc.id in self._except_names:
                    from_except = True
                elif exc.id[:1].isupper():
                    cls = exc.id          # raise SomeError (no-arg class)
            fn.raises_.append(RaiseSite(
                cls=cls, line=node.lineno, from_except=from_except,
                exempt=has_pragma(self.mod.lines, node.lineno,
                                  "typed-error-exempt")))
        self.generic_visit(node)

    def visit_Assign(self, node: ast.Assign) -> None:
        # TYPED_ERRORS = frozenset({...}) — the typed-degradation contract
        targets = [t.id for t in node.targets if isinstance(t, ast.Name)]
        if "TYPED_ERRORS" in targets:
            lits = self._str_literals(node.value)
            if lits is not None:
                self.mod.typed_errors = frozenset(lits)
        # CONST = METRICS.counter("name", "help")
        if len(targets) == 1 and isinstance(node.value, ast.Call):
            self._maybe_metric_decl(node.value, const=targets[0])
        self.generic_visit(node)

    @staticmethod
    def _str_literals(node):
        if isinstance(node, ast.Call) and node.args:
            node = node.args[0]
        if isinstance(node, (ast.Set, ast.Tuple, ast.List)):
            vals = [e.value for e in node.elts
                    if isinstance(e, ast.Constant) and isinstance(e.value,
                                                                  str)]
            return vals
        return None

    def _maybe_metric_decl(self, call: ast.Call, const: str | None) -> None:
        if not isinstance(call.func, ast.Attribute) or \
                call.func.attr not in METRIC_CTORS:
            return
        recv = dotted(call.func.value)
        if not recv.rsplit(".", 1)[-1] == "METRICS":
            return
        if not (call.args and isinstance(call.args[0], ast.Constant)
                and isinstance(call.args[0].value, str)):
            return                       # dynamic name: out of scope
        has_help = any(
            isinstance(a, ast.Constant) and isinstance(a.value, str)
            and a.value.strip() for a in call.args[1:]) or any(
            kw.arg == "help" and isinstance(kw.value, ast.Constant)
            and str(kw.value.value).strip() for kw in call.keywords)
        # string-concat help ("a" "b") parses as one Constant; a
        # help built by + or f-string still counts as present
        if not has_help and len(call.args) > 1:
            has_help = not (isinstance(call.args[1], ast.Constant)
                            and not str(call.args[1].value).strip())
        self.mod.metric_decls.append(MetricDecl(
            name=call.args[0].value, kind=call.func.attr,
            has_help=has_help, const=const, line=call.lineno))

    def visit_Call(self, node: ast.Call) -> None:
        self._maybe_metric_decl(node, const=None)
        f = node.func
        name = ""
        dot = ""
        recv_root = ""
        is_self = False
        is_bare = False
        if isinstance(f, ast.Attribute):
            name = f.attr
            dot = dotted(f)
            recv_root = root_name(f.value)
            is_self = recv_root == "self" and isinstance(f.value, ast.Name)
            # metric write through an ALL_CAPS constant
            if name in METRIC_WRITE_METHODS:
                term = dotted(f.value).rsplit(".", 1)[-1]
                if term and term.isupper() and not term.startswith("_MET"):
                    self.mod.metric_uses.append(MetricUse(
                        const=term, method=name, line=node.lineno,
                        exempt=has_pragma(self.mod.lines, node.lineno,
                                          "counter-exempt")))
        elif isinstance(f, ast.Name):
            name = f.id
            dot = f.id
            is_bare = True
        fn = self._fn_stack[-1] if self._fn_stack else None
        if fn is not None and name:
            open_mode = None
            if name == "open":
                if len(node.args) > 1 and \
                        isinstance(node.args[1], ast.Constant):
                    open_mode = str(node.args[1].value)
                for kw in node.keywords:
                    if kw.arg == "mode" and isinstance(kw.value,
                                                      ast.Constant):
                        open_mode = str(kw.value.value)
                if open_mode is None:
                    open_mode = "r"
            fn.calls.append(CallSite(
                name=name, dot=dot, recv_root=recv_root, is_self=is_self,
                is_bare=is_bare, line=node.lineno,
                held=tuple(self._lock_stack),
                in_lane=self._lane_depth > 0, open_mode=open_mode,
                lock_exempt=has_pragma(self.mod.lines, node.lineno,
                                       "lock-order-exempt"),
                lane_exempt=has_pragma(self.mod.lines, node.lineno,
                                       "device-lane-exempt")))
        self.generic_visit(node)

    def _collect_wire_table(self, node) -> None:
        branches: dict[str, int] = {}
        for n in ast.walk(node):
            if isinstance(n, ast.Compare) and len(n.ops) == 1 and \
                    isinstance(n.ops[0], ast.Eq) and \
                    isinstance(n.left, ast.Name) and n.left.id == "cls" and \
                    isinstance(n.comparators[0], ast.Constant):
                branches[str(n.comparators[0].value)] = n.lineno
        self.mod.wire_branches = branches
        self.mod.wire_table_line = node.lineno


def summarize_source(path: str, src: str) -> ModuleSummary:
    mod = ModuleSummary(path=path, lines=src.splitlines())
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        mod.parse_error = (e.lineno or 0, e.msg or "syntax error")
        return mod
    _ModuleWalker(mod).visit(tree)
    return mod


def summarize_paths(paths: list[str]) -> ProgramSummary:
    mods = []
    for f in iter_py_files(paths):
        with open(f, encoding="utf-8") as fh:
            mods.append(summarize_source(f, fh.read()))
    return ProgramSummary(mods)
