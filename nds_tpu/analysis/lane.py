"""ENG004 — device-lane purity: no blocking calls on the device lane.

The device lane is ONE thread; anything that blocks it (an fsync-bound
commit, a socket write, a sleep) stalls every tenant's queries at once.
PR 16 hand-routed the transactional warehouse's fsync commits off-lane
and PR 18 hand-routed wire serialization onto client threads; this rule
makes that discipline static: a blocking call is flagged when it sits
LEXICALLY

- inside a function carrying the ``# lint: device-lane (<reason>)``
  def-line marker (the service's lane loop and its dispatch helpers),
  including nested defs; or
- inside any ``with <...>._sql_lock:`` block anywhere in the tree — the
  statement lock IS the lane: whoever holds it is serializing the
  device, so blocking under it blocks the lane by proxy.

``# lint: device-lane-exempt (<reason>)`` on the call line is the
audited escape hatch.

The blocking-call set is curated, not inferred: scheduler sleeps,
fsync/rename-class filesystem commits, sockets, subprocesses, writes
through ``open(..., 'w'/'a'/'x'/'+')``, and the project's own known
fsync-bound / wire-bound helpers (``_atomic_write_json``,
``write_frame``/``read_frame``). Plain reads stay legal — scans must
read their inputs.
"""
from __future__ import annotations

from .base import Finding, suggestion_for
from .summary import ProgramSummary

BLOCKING_DOTTED = frozenset({
    "time.sleep", "os.fsync", "os.replace", "os.rename", "os.remove",
    "os.unlink", "os.makedirs", "shutil.rmtree", "shutil.copy",
    "shutil.copytree", "socket.create_connection",
})
BLOCKING_BARE = frozenset({
    "sleep", "fsync", "_atomic_write_json", "write_frame", "read_frame",
})
BLOCKING_METHODS = frozenset({
    "sendall", "recv", "recv_into", "accept", "fsync",
})
#: dotted prefixes that always block (process spawn + wait)
BLOCKING_PREFIXES = ("subprocess.",)


def _is_blocking(cs) -> str | None:
    """Human-readable description of why a call blocks, or None."""
    if cs.dot in BLOCKING_DOTTED:
        return f"'{cs.dot}'"
    if cs.is_bare and cs.name in BLOCKING_BARE:
        return f"'{cs.name}'"
    if not cs.is_bare and cs.name in BLOCKING_BARE:
        return f"'{cs.dot or cs.name}'"
    if cs.dot and any(cs.dot.startswith(p) for p in BLOCKING_PREFIXES):
        return f"'{cs.dot}'"
    if not cs.is_bare and cs.name in BLOCKING_METHODS:
        return f"socket/file op '{cs.dot or cs.name}'"
    if cs.name == "open" and cs.open_mode is not None and \
            any(c in cs.open_mode for c in "wax+"):
        return f"file write (open mode {cs.open_mode!r})"
    return None


def check_lane_purity(prog: ProgramSummary) -> list[Finding]:
    findings: list[Finding] = []
    sug = suggestion_for("ENG004")
    for fn in prog.functions:
        for cs in fn.calls:
            under_sql = any(h.rsplit(".", 1)[-1] == "_sql_lock"
                            for h in cs.held)
            if not (cs.in_lane or under_sql):
                continue
            why = _is_blocking(cs)
            if why is None:
                continue
            where = "under _sql_lock" if under_sql else \
                "in a device-lane function"
            findings.append(Finding(
                fn.module, cs.line, 0, "ENG004",
                f"blocking call {why} {where}: the device lane must "
                "never wait on I/O — route this off-lane (client/"
                "maintenance thread) like PR 16's commits and PR 18's "
                "wire serialization, or exempt the audited site",
                suggestion=sug, suppressed=cs.lane_exempt))
    return findings
