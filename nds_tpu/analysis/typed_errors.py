"""ENG005 — typed-error discipline at the serving entry points.

The chaos campaigns' headline invariant is "all failures typed": every
error a client can observe must carry a class from the
``chaos.TYPED_ERRORS`` contract (matched over the MRO, so subclasses
count). Two static checks keep that true before anything executes:

1. **Raise sites.** Every ``raise SomeClass(...)`` in the serving layer
   (files under a ``service/`` directory — ``service.py``,
   ``frontdoor.py``) must name a class whose MRO intersects
   ``TYPED_ERRORS``, resolved through the program-wide class hierarchy
   the summary pass extracts (``ConnectionDropped -> TransientError`` is
   typed two modules away from its base). Bare re-raises and
   ``raise caught_name`` pass through unchanged — they preserve an
   already-classified error. ``# lint: typed-error-exempt (<reason>)``
   covers the audited exceptions (e.g. a ValueError answered to a peer
   that has provably lost framing).

2. **Wire-table exhaustiveness, both directions.** The front door's
   ``reconstruct_error`` branch table must cover (a) every name in
   ``TYPED_ERRORS`` — a contract class with no branch silently arrives
   client-side as ``RemoteQueryError``, outside the retry-policy
   classification it was designed for; and (b) every typed-error class
   DEFINED in the tree that any code raises — a newly added
   ``QuotaExceeded(AdmissionRejected)`` must fail this gate until the
   wire table learns it. Branches naming classes that no longer exist
   anywhere (tree or builtins) are flagged as stale.
"""
from __future__ import annotations

import builtins

from .base import Finding, suggestion_for
from .summary import ProgramSummary

#: fallback contract when the linted tree does not define TYPED_ERRORS
#: (fixture trees): raise-site checks still run against this core set
DEFAULT_TYPED_ERRORS = frozenset({
    "FaultError", "TransientError", "AdmissionRejected", "CircuitOpen",
    "ServiceClosed", "DeadlineExceeded", "TimeoutError",
})


def _in_service_scope(path: str) -> bool:
    norm = path.replace("\\", "/")
    return "/service/" in norm or norm.endswith("/frontdoor.py")


def _is_typed(cls: str, typed: frozenset, prog: ProgramSummary) -> bool:
    if cls in typed:
        return True
    return bool(prog.ancestors(cls) & typed)


def check_typed_errors(prog: ProgramSummary) -> list[Finding]:
    typed = prog.typed_errors or DEFAULT_TYPED_ERRORS
    findings: list[Finding] = []
    sug = suggestion_for("ENG005")

    # 1. raise sites in the serving layer
    for fn in prog.functions:
        if not _in_service_scope(fn.module):
            continue
        for rs in fn.raises_:
            if rs.cls is None or rs.from_except:
                continue
            if _is_typed(rs.cls, typed, prog):
                continue
            findings.append(Finding(
                fn.module, rs.line, 0, "ENG005",
                f"raise of untyped '{rs.cls}' in the serving layer: "
                "errors reaching clients must be (or wrap into) a "
                "chaos.TYPED_ERRORS class so retry policies classify "
                "them — subclass a typed base, wrap at the boundary, "
                "or exempt the audited site",
                suggestion=sug, suppressed=rs.exempt))

    # 2. wire-table exhaustiveness (runs when the tree has the table)
    wire_mod = next((m for m in prog.modules
                     if m.wire_branches is not None), None)
    if wire_mod is not None:
        branches = wire_mod.wire_branches
        line = wire_mod.wire_table_line
        for name in sorted(typed):
            if name not in branches:
                findings.append(Finding(
                    wire_mod.path, line, 0, "ENG005",
                    f"wire table not exhaustive: TYPED_ERRORS class "
                    f"'{name}' has no reconstruct_error branch — it "
                    "would arrive client-side as RemoteQueryError, "
                    "outside its retry classification"))
        # every typed class defined in the tree that is actually raised
        raised = {rs.cls for fn in prog.functions for rs in fn.raises_
                  if rs.cls}
        for cls in sorted(prog.class_bases):
            if cls in branches or cls not in raised:
                continue
            if _is_typed(cls, typed, prog):
                findings.append(Finding(
                    wire_mod.path, line, 0, "ENG005",
                    f"wire table not exhaustive: typed error class "
                    f"'{cls}' is raised in the tree but has no "
                    "reconstruct_error branch — it degrades to "
                    "RemoteQueryError on the wire"))
        # stale branches: a branch naming a class that exists nowhere
        for name, bline in sorted(branches.items()):
            if name in prog.class_bases or hasattr(builtins, name):
                continue
            findings.append(Finding(
                wire_mod.path, bline, 0, "ENG005",
                f"stale wire-table branch: '{name}' names a class that "
                "no longer exists in the tree or builtins"))
    return findings
