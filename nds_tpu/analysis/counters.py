"""ENG006 — counter discipline: metrics, glossary, and gate stay in sync.

The metrics contract has three legs that historically drifted apart by
hand-editing:

1. **Glossary.** Every ``METRICS.counter/gauge/histogram("name", ...)``
   declaration must carry non-empty help text — ``describe()`` is the
   operator-facing glossary, and a help-less metric is invisible there.
2. **Write sites resolve.** Every ``SOME_CONST.inc()/dec()/add()/set()/
   observe()`` through an ALL_CAPS constant must resolve to a metric
   declaration somewhere in the tree — a renamed declaration leaves the
   old write sites incrementing a constant that no longer exists (an
   ImportError at best, a silently re-registered orphan at worst).
3. **Gate cross-check, both directions.** Every name in
   ``scripts/metrics_gate.py``'s ``STRICT_ZERO`` tuple and every key in
   ``cicd/metrics_baseline.json``'s ``gated`` dict must name a metric
   that still exists (orphan gate rows assert about nothing); and every
   gate-shaped declaration (counter/gauge whose name is not
   report-only) must have a baseline row (a new counter nobody baselines
   is a regression the gate cannot catch).

``# lint: counter-exempt (<reason>)`` on the write site / declaration
line is the audited escape hatch.
"""
from __future__ import annotations

import ast
import json
import os

from .base import Finding, has_pragma, suggestion_for
from .summary import ProgramSummary

#: ALL_CAPS constants whose inc/add/set/observe-shaped methods are NOT
#: metric writes (trackers/recorders that share the verb vocabulary)
NON_METRIC_CONSTS = frozenset({
    "DEVICE_MEM", "FLIGHT", "TRACER", "METRICS", "PROGRAMS",
})

#: fallback when the gate module cannot be parsed for its own constant
DEFAULT_REPORT_ONLY_SUFFIXES = ("_ms", "_bytes", "bytes_uploaded")


def _gate_artifacts(root: str | None):
    """(gate_py, baseline_json) paths when both exist under ``root``."""
    if not root:
        return None, None
    gate = os.path.join(root, "scripts", "metrics_gate.py")
    base = os.path.join(root, "cicd", "metrics_baseline.json")
    if os.path.isfile(gate) and os.path.isfile(base):
        return gate, base
    return None, None


def _parse_gate(gate_path: str):
    """(STRICT_ZERO [(name, line)], REPORT_ONLY_SUFFIXES) from the gate
    module's AST — the gate file is data here, never imported."""
    strict: list[tuple[str, int]] = []
    suffixes = DEFAULT_REPORT_ONLY_SUFFIXES
    try:
        with open(gate_path, encoding="utf-8") as fh:
            tree = ast.parse(fh.read(), filename=gate_path)
    except (OSError, SyntaxError):
        return strict, suffixes
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        names = [t.id for t in node.targets if isinstance(t, ast.Name)]
        if not names or not isinstance(node.value, (ast.Tuple, ast.List,
                                                    ast.Set)):
            continue
        vals = [(e.value, e.lineno) for e in node.value.elts
                if isinstance(e, ast.Constant) and isinstance(e.value, str)]
        if "STRICT_ZERO" in names:
            strict = vals
        elif "REPORT_ONLY_SUFFIXES" in names and vals:
            suffixes = tuple(v for v, _ in vals)
    return strict, suffixes


def check_counters(prog: ProgramSummary, root: str | None) -> list[Finding]:
    findings: list[Finding] = []
    sug = suggestion_for("ENG006")
    decls = {}                              # metric name -> (decl, module)
    consts: set[str] = set()                # CONST bindings of declarations
    for m in prog.modules:
        for d in m.metric_decls:
            decls.setdefault(d.name, (d, m))
            if d.const:
                consts.add(d.const)

    # 1. glossary: every metric FAMILY carries help somewhere (labeled-
    #    child lookups like ``METRICS.histogram("x", tenant=t)`` inherit
    #    the family help, so help is a per-name property, not per-site)
    family_help = {}
    for m in prog.modules:
        for d in m.metric_decls:
            family_help[d.name] = family_help.get(d.name, False) or \
                d.has_help
    for m in prog.modules:
        for d in m.metric_decls:
            if family_help.get(d.name):
                continue
            findings.append(Finding(
                m.path, d.line, 0, "ENG006",
                f"metric '{d.name}' declared without help text: "
                "METRICS.describe() is the operator glossary and must "
                "cover every registered series",
                suggestion=sug,
                suppressed=has_pragma(m.lines, d.line, "counter-exempt")))

    # 2. write sites resolve to a live declaration
    for m in prog.modules:
        for u in m.metric_uses:
            if u.const in consts or u.const in NON_METRIC_CONSTS:
                continue
            findings.append(Finding(
                m.path, u.line, 0, "ENG006",
                f"metric write '{u.const}.{u.method}()' does not resolve "
                "to any METRICS declaration in the tree — the constant "
                "was renamed/removed, or this tracker belongs in the "
                "checker stoplist",
                suggestion=sug, suppressed=u.exempt))

    # 3. gate cross-check (only when the tree ships the gate artifacts)
    gate_py, baseline_json = _gate_artifacts(root)
    if gate_py is None:
        return findings
    strict_zero, suffixes = _parse_gate(gate_py)
    for name, line in strict_zero:
        if name in decls:
            continue
        findings.append(Finding(
            gate_py, line, 0, "ENG006",
            f"orphan STRICT_ZERO row '{name}': no metric with that name "
            "is declared anywhere in the tree — the gate asserts about "
            "nothing"))
    try:
        with open(baseline_json, encoding="utf-8") as fh:
            gated = json.load(fh).get("gated", {})
    except (OSError, ValueError):
        gated = {}
    for name in sorted(gated):
        if name in decls:
            continue
        findings.append(Finding(
            baseline_json, 0, 0, "ENG006",
            f"orphan baseline row '{name}': no metric with that name is "
            "declared anywhere in the tree"))
    for name, (d, m) in sorted(decls.items()):
        if d.kind not in ("counter", "gauge"):
            continue                        # histograms are report-only
        if any(name.endswith(s) for s in suffixes):
            continue
        if name in gated:
            continue
        findings.append(Finding(
            m.path, d.line, 0, "ENG006",
            f"metric '{name}' ({d.kind}) has no cicd/metrics_baseline."
            "json row: gate-shaped series must be baselined or the "
            "regression gate cannot see them drift",
            suggestion=sug,
            suppressed=has_pragma(m.lines, d.line, "counter-exempt")))
    return findings
