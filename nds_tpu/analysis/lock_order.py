"""ENG003 — whole-program lock-order deadlock detection.

The engine holds 25+ locks across session/service/frontdoor/cache/
metrics; a deadlock needs only two threads acquiring two of them in
opposite orders. This pass makes the acquisition ORDER a static,
CI-gated property:

1. every ``with <lock>:`` site is canonicalized to the lock OBJECT it
   names (``self._lock`` inside ``Session`` and ``session._lock`` from a
   service thread are the same node; ``Counter._lock`` aliases the
   metrics registry's shared value lock it was constructed with);
2. nested acquisitions add edges held-lock -> acquired-lock, and calls
   made while holding a lock add edges to every lock the callee may
   (transitively) acquire — resolved through the per-module summary
   pass's program-wide function index;
3. the resulting graph must be acyclic AND respect the declared
   hierarchy table below (an edge from an inner lock back out to an
   outer one is flagged even before a second thread closes the cycle).

``# lint: lock-order-exempt (<reason>)`` on the acquisition (or call)
line drops that edge — the audited exceptions.

The declared hierarchy (outer acquired first, LOWER level number):

====  ======================================================================
  10  ``QueryService._cv`` — service scheduler state (admission, queues)
  15  ``Ticket._mat_lock`` — per-ticket deferred materialization cell
  20  ``Session._sql_lock`` — whole-statement serialization (device lane)
  30  ``Session._lock`` — session shared caches (stats/loaders/streams)
  40  ``executor._SHARED_LOCK`` — cross-stream shared-program registry
  42  ``CompiledQuery._lock`` / ``BatchedQuery._lock`` — per-program state
  44  ``ShardedMorselQuery._lock`` — sharded stream bookkeeping
  50  leaf stores: ``ResultCache._lock``, ``FeedbackStore._lock``,
      ``QueryLog._lock``, ``FaultRegistry._lock``, ``CircuitBreaker._lock``,
      ``ProgramRegistry._lock``, ``DeviceMemTracker._lock``,
      ``resilience._ABANDONED_LOCK``
  55  observability sinks callable from under any leaf store:
      ``FlightRecorder._lock``, ``Tracer._lock``
  60  ``MetricsRegistry._lock`` — metric registration
  70  ``MetricsRegistry._values`` — the shared value lock (innermost:
      every counter inc lands here, so everything may hold-and-enter)
====  ======================================================================
"""
from __future__ import annotations

import os
from dataclasses import dataclass

from .base import Finding, suggestion_for
from .summary import CallSite, FunctionSummary, ProgramSummary

#: lock attribute names unique enough to identify the object program-wide
UNIQUE_LOCK_ATTRS = {
    "_sql_lock": "Session._sql_lock",
    "_values": "MetricsRegistry._values",
    "locked": "MetricsRegistry._values",       # METRICS.locked() accessor
    "_SHARED_LOCK": "executor._SHARED_LOCK",
    "_ABANDONED_LOCK": "resilience._ABANDONED_LOCK",
    "_mat_lock": "Ticket._mat_lock",
    "_cv": "QueryService._cv",
}

#: receiver-variable spellings that identify the owning class of a
#: generic ``_lock`` attribute when the write is not through ``self``
VAR_CLASS_HINTS = {
    "session": "Session",
    "registry": "MetricsRegistry",
    "cache": "ResultCache",
    "ticket": "Ticket",
}

#: module-level singletons: an ALL_CAPS receiver pins the callee class
#: exactly, so ``FLIGHT.record(...)`` resolves to FlightRecorder.record
#: instead of every ``record`` method in the program
CONST_CLASS_HINTS = {
    "FLIGHT": "FlightRecorder",
    "TRACER": "Tracer",
    "METRICS": "MetricsRegistry",
    "QUERY_LOG": "QueryLog",
    "PROGRAMS": "ProgramRegistry",
    "DEVICE_MEM": "DeviceMemTracker",
}

#: classes whose ``self._lock`` IS another class's canonical lock (the
#: metrics registry hands every Counter/Gauge/Histogram its shared value
#: lock, so their method bodies acquire MetricsRegistry._values)
LOCK_CLASS_ALIASES = {
    "Counter": "MetricsRegistry._values",
    "Gauge": "MetricsRegistry._values",
    "Histogram": "MetricsRegistry._values",
}

#: declared hierarchy: canonical lock -> level (outer = lower). Every
#: observed edge must go strictly downward (outer -> inner). Locks absent
#: from this table participate in cycle detection only.
LOCK_LEVELS = {
    "QueryService._cv": 10,
    "Ticket._mat_lock": 15,
    "Session._sql_lock": 20,
    "Session._lock": 30,
    "executor._SHARED_LOCK": 40,
    "CompiledQuery._lock": 42,
    "BatchedQuery._lock": 42,
    "ShardedMorselQuery._lock": 44,
    "ResultCache._lock": 50,
    "FeedbackStore._lock": 50,
    "QueryLog._lock": 50,
    "FaultRegistry._lock": 50,
    "CircuitBreaker._lock": 50,
    "ProgramRegistry._lock": 50,
    "DeviceMemTracker._lock": 50,
    "resilience._ABANDONED_LOCK": 50,
    "FlightRecorder._lock": 55,
    "Tracer._lock": 55,
    "MetricsRegistry._lock": 60,
    "MetricsRegistry._values": 70,
}

#: method names too generic to resolve by name across the program —
#: calls through them are not followed (a dict ``.get`` must not alias
#: ``ResultCache.get``). Distinctive engine entry points stay followable.
GENERIC_METHOD_NAMES = frozenset({
    "get", "put", "set", "add", "pop", "popleft", "append", "appendleft",
    "extend", "update", "insert", "remove", "discard", "clear", "copy",
    "items", "keys", "values", "sort", "split", "join", "strip", "read",
    "write", "flush", "close", "open", "send", "recv", "encode", "decode",
    "wait", "notify", "notify_all", "acquire", "release", "start", "run",
    "result", "done", "next", "submit", "map", "format", "count", "index",
    "setdefault", "sum", "min", "max", "mean", "render", "name", "group",
})


def canonical_lock(raw: str, cls: str, module: str) -> str:
    """Canonical node name for one lock spelling at one site."""
    attr = raw.rsplit(".", 1)[-1]
    root = raw.split(".", 1)[0]
    if attr in UNIQUE_LOCK_ATTRS:
        return UNIQUE_LOCK_ATTRS[attr]
    owner = None
    if root == "self" and cls:
        owner = cls
    elif root in VAR_CLASS_HINTS:
        owner = VAR_CLASS_HINTS[root]
    if owner is not None:
        alias = LOCK_CLASS_ALIASES.get(owner)
        if alias:
            return alias
        return f"{owner}.{attr}"
    # unresolved receiver: a per-module node that cannot alias another
    # class's lock (sound for cycle detection, invisible to levels)
    base = os.path.basename(module)
    return f"?{base}:{raw}"


@dataclass
class _Edge:
    src: str
    dst: str
    path: str
    line: int
    via: str          # '' for a lexical nesting, else the callee chain
    exempt: bool


def _resolve_call(cs: CallSite, fn: FunctionSummary,
                  prog: ProgramSummary) -> list[FunctionSummary]:
    """Best-effort static callee resolution (union semantics — the
    over-approximation is what makes the edge set a superset of the real
    acquisition graph)."""
    if cs.is_self and fn.cls:
        found = prog.methods_of(fn.cls, cs.name)
        if found:
            return found
        return []
    if cs.is_bare:
        same_mod = [f for f in prog.by_name.get(cs.name, ())
                    if f.module == fn.module and not f.cls]
        if same_mod:
            return same_mod
        glob = [f for f in prog.by_name.get(cs.name, ()) if not f.cls]
        return glob if len(glob) == 1 else []
    # x.m(...): a known receiver pins the class exactly (and overrides
    # the generic-name stoplist — the receiver disambiguates)
    if cs.recv_root in CONST_CLASS_HINTS:
        return prog.methods_of(CONST_CLASS_HINTS[cs.recv_root], cs.name)
    if cs.recv_root in VAR_CLASS_HINTS:
        found = prog.methods_of(VAR_CLASS_HINTS[cs.recv_root], cs.name)
        if found:
            return found
    # otherwise follow only distinctive method names
    if cs.name in GENERIC_METHOD_NAMES:
        return []
    return [f for f in prog.by_name.get(cs.name, ()) if f.cls]


def _transitive_acquires(prog: ProgramSummary) -> dict[int, set[str]]:
    """id(fn) -> canonical locks the function may acquire, directly or
    through resolved callees (fixpoint union)."""
    direct: dict[int, set[str]] = {}
    callees: dict[int, list[int]] = {}
    for fn in prog.functions:
        direct[id(fn)] = {canonical_lock(la.raw, la.cls, fn.module)
                          for la in fn.locks}
        callees[id(fn)] = [id(g) for cs in fn.calls
                           for g in _resolve_call(cs, fn, prog)]
    acq = {k: set(v) for k, v in direct.items()}
    changed = True
    while changed:
        changed = False
        for k, cs in callees.items():
            merged = acq[k]
            before = len(merged)
            for c in cs:
                merged |= acq.get(c, set())
            if len(merged) != before:
                changed = True
    return acq


def _build_edges(prog: ProgramSummary) -> list[_Edge]:
    acq = _transitive_acquires(prog)
    edges: list[_Edge] = []
    for fn in prog.functions:
        for la in fn.locks:
            dst = canonical_lock(la.raw, la.cls, fn.module)
            for h in la.held:
                src = canonical_lock(h, fn.cls, fn.module)
                if src != dst:
                    edges.append(_Edge(src, dst, fn.module, la.line, "",
                                       la.exempt))
        for cs in fn.calls:
            if not cs.held:
                continue
            targets = _resolve_call(cs, fn, prog)
            if not targets:
                continue
            dsts: set[str] = set()
            for g in targets:
                dsts |= acq.get(id(g), set())
            for h in cs.held:
                src = canonical_lock(h, fn.cls, fn.module)
                for dst in dsts:
                    if src != dst:
                        edges.append(_Edge(src, dst, fn.module, cs.line,
                                           cs.dot or cs.name,
                                           cs.lock_exempt))
    return edges


def _find_cycles(edges: list[_Edge]) -> list[list[_Edge]]:
    """Edges participating in cycles, grouped per strongly-connected
    component with >1 node (or a self-loop)."""
    graph: dict[str, set[str]] = {}
    for e in edges:
        graph.setdefault(e.src, set()).add(e.dst)
        graph.setdefault(e.dst, set())
    index: dict[str, int] = {}
    low: dict[str, int] = {}
    on_stack: set[str] = set()
    stack: list[str] = []
    sccs: list[set[str]] = []
    counter = [0]

    def strongconnect(v: str) -> None:  # iterative Tarjan
        work = [(v, iter(sorted(graph[v])))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(graph[w]))))
                    advanced = True
                    break
                if w in on_stack:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                scc = set()
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.add(w)
                    if w == node:
                        break
                sccs.append(scc)

    for v in sorted(graph):
        if v not in index:
            strongconnect(v)
    out = []
    for scc in sccs:
        if len(scc) > 1:
            out.append([e for e in edges
                        if e.src in scc and e.dst in scc])
    return out


def check_lock_order(prog: ProgramSummary) -> list[Finding]:
    edges = _build_edges(prog)
    findings: list[Finding] = []
    sug = suggestion_for("ENG003")

    # 1. hierarchy: every live edge between DECLARED locks goes outer ->
    #    inner (strictly downward in level)
    seen: set[tuple] = set()
    for e in edges:
        la, lb = LOCK_LEVELS.get(e.src), LOCK_LEVELS.get(e.dst)
        if la is None or lb is None or la < lb:
            continue
        key = (e.src, e.dst, e.path, e.line)
        if key in seen:
            continue
        seen.add(key)
        via = f" (via {e.via})" if e.via else ""
        rel = "same-level" if la == lb else "inverted"
        findings.append(Finding(
            e.path, e.line, 0, "ENG003",
            f"lock-order violation: acquiring '{e.dst}' (level {lb}) "
            f"while holding '{e.src}' (level {la}){via} — the declared "
            f"hierarchy (analysis/lock_order.py) is {rel} here; reorder "
            "the acquisitions or exempt the audited site",
            suggestion=sug, suppressed=e.exempt))

    # 2. cycles over the live (non-exempt) edge set — a cycle among
    #    undeclared locks deadlocks just as hard
    live = [e for e in edges if not e.exempt]
    for cyc in _find_cycles(live):
        nodes = " -> ".join(sorted({e.src for e in cyc}))
        reported: set[tuple] = set()
        for e in cyc:
            key = (e.src, e.dst, e.path, e.line)
            if key in reported:
                continue
            reported.add(key)
            via = f" (via {e.via})" if e.via else ""
            findings.append(Finding(
                e.path, e.line, 0, "ENG003",
                f"lock-acquisition cycle [{nodes}]: this edge "
                f"'{e.src}' -> '{e.dst}'{via} closes an order two "
                "threads can interleave into a deadlock",
                suggestion=sug))
    return findings
