"""ENG007 — pragma hygiene: every escape hatch stays audited and live.

Pragmas are the lint's only escape hatches, so they get their own rule:

- **unknown** — ``# lint: <name>`` outside the declared vocabulary is a
  typo that silently silences nothing;
- **unexplained** — every pragma must carry a non-empty ``(<reason>)``:
  the reason IS the audit trail reviewers approved;
- **stale suppression** — a suppressing pragma on a line where its rule
  no longer fires is dead weight that hides future regressions on that
  line (checkers emit suppressed findings precisely so this pass can
  tell "still needed" from "stale" in a single run);
- **stale marker** — ``thread-entry`` / ``device-lane`` markers are
  meaningful only on a def header; anywhere else they declare nothing.

Only real comments count: the pass tokenizes each module, so pragma
spellings quoted in docstrings and messages (this package is full of
them) are invisible to it.
"""
from __future__ import annotations

import io
import tokenize

from .base import KNOWN_PRAGMAS, MARKER_PRAGMAS, PRAGMA_RE, PRAGMA_RULES, \
    Finding
from .summary import ProgramSummary


def _comment_pragmas(source: str):
    """[(line, pragma, reason)] from COMMENT tokens only."""
    out = []
    try:
        toks = tokenize.generate_tokens(io.StringIO(source).readline)
        for tok in toks:
            if tok.type != tokenize.COMMENT:
                continue
            for m in PRAGMA_RE.finditer(tok.string):
                out.append((tok.start[0], m.group(1),
                            (m.group(2) or "").strip()))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        pass                      # unparsable file: ENG000 covers it
    return out


def check_pragmas(prog: ProgramSummary,
                  all_findings: list[Finding]) -> list[Finding]:
    suppressed_at = {(f.path, f.line, f.rule)
                     for f in all_findings if f.suppressed}
    findings: list[Finding] = []
    for m in prog.modules:
        src = "\n".join(m.lines) + "\n"
        for line, name, reason in _comment_pragmas(src):
            if name not in KNOWN_PRAGMAS:
                known = ", ".join(sorted(KNOWN_PRAGMAS))
                findings.append(Finding(
                    m.path, line, 0, "ENG007",
                    f"unknown pragma 'lint: {name}': not in the "
                    f"vocabulary ({known}) — a typo here silences "
                    "nothing"))
                continue
            if not reason:
                findings.append(Finding(
                    m.path, line, 0, "ENG007",
                    f"pragma 'lint: {name}' missing its (<reason>): the "
                    "reason is the audit trail — say why this site is "
                    "exempt"))
            if name in PRAGMA_RULES:
                rule = PRAGMA_RULES[name]
                if (m.path, line, rule) not in suppressed_at:
                    findings.append(Finding(
                        m.path, line, 0, "ENG007",
                        f"stale pragma 'lint: {name}': {rule} no longer "
                        "fires on this line — remove it so a future "
                        "regression here is not pre-silenced"))
            elif name in MARKER_PRAGMAS and line not in m.header_lines:
                findings.append(Finding(
                    m.path, line, 0, "ENG007",
                    f"misplaced marker 'lint: {name}': markers are only "
                    "meaningful on a def header line"))
    return findings
