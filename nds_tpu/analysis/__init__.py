"""Engine-discipline lint: AST-based static passes for nds_tpu/.

Six rule families, all guarding invariants the runtime cannot check (or
can only check by deadlocking/corrupting first). Pure stdlib — the CI
``static`` stage runs ``python -m nds_tpu.analysis nds_tpu`` before
anything executes, budgeted under 10 s for the whole tree.

ENG001 — **frozen plan IR** (engine_rules). Plan nodes and bound
  expressions are immutable everywhere; rewrite passes rebuild
  copy-on-write because plans are DAGs — an in-place mutation on a node
  shared by several parents silently shifts bindings for every other
  consumer. Pragma: ``# lint: frozen-exempt (<reason>)``.

ENG002 — **cross-thread writes take the lock** (engine_rules). Thread
  targets (``Thread(target=...)``, ``pool.submit/map``) and
  ``# lint: thread-entry``-marked entry points must write shared
  attributes under a lock-shaped ``with``. Pragma:
  ``# lint: lock-exempt (<reason>)``.

ENG003 — **lock-order deadlock detection** (lock_order). Every
  ``with <lock>:`` is canonicalized to the lock object it names; nested
  acquisitions and calls into functions that (transitively) acquire add
  edges to a whole-program acquisition graph, which must be acyclic AND
  respect the declared hierarchy table (``lock_order.LOCK_LEVELS``:
  ``QueryService._cv`` before ``Session._sql_lock`` before
  ``Session._lock`` before the leaf stores before the metrics value
  lock). Pragma: ``# lint: lock-order-exempt (<reason>)``.

ENG004 — **device-lane purity** (lane). No blocking call — sleeps,
  fsync/rename-class filesystem commits, sockets, subprocesses, file
  writes, the project's own fsync-/wire-bound helpers — lexically inside
  a ``# lint: device-lane``-marked function or under ``_sql_lock``: the
  device lane is one thread and whatever blocks it stalls every tenant.
  Pragma: ``# lint: device-lane-exempt (<reason>)``.

ENG005 — **typed-error discipline** (typed_errors). Every ``raise`` in
  the serving layer must name a class whose MRO intersects
  ``chaos.TYPED_ERRORS``; the front door's ``reconstruct_error`` wire
  table must be exhaustive over the contract in both directions (every
  typed class has a branch, every branch names a live class). Pragma:
  ``# lint: typed-error-exempt (<reason>)``.

ENG006 — **counter discipline** (counters). Every metric declaration
  carries help (the ``describe()`` glossary), every ALL_CAPS write site
  resolves to a declaration, and the metrics gate
  (``scripts/metrics_gate.py`` + ``cicd/metrics_baseline.json``) names
  only live metrics while every gate-shaped metric is baselined.
  Pragma: ``# lint: counter-exempt (<reason>)``.

ENG007 — **pragma hygiene** (pragmas). Unknown pragmas, pragmas without
  a non-empty ``(<reason>)``, suppressing pragmas whose rule no longer
  fires on their line, and markers off a def header are all flagged.
  No escape hatch — hygiene findings are fixed, not exempted.

``scripts/lint_engine.py`` remains as a thin CLI shim for callers of the
historical entry point; the package is the implementation.
"""
from __future__ import annotations

import json
import os
import sys

from .base import Finding, iter_py_files
from .counters import check_counters
from .engine_rules import lint_source, lint_source_all
from .lane import check_lane_purity
from .lock_order import check_lock_order
from .pragmas import check_pragmas
from .summary import ProgramSummary, summarize_source
from .typed_errors import check_typed_errors

__all__ = ["Finding", "lint_source", "lint_paths", "main"]


def _tree_root(paths: list[str]) -> str | None:
    """Directory holding scripts/ + cicd/ for the gate cross-check: the
    parent of the first linted package directory."""
    for p in paths:
        ap = os.path.abspath(p)
        if os.path.isdir(ap):
            return os.path.dirname(ap)
    if paths:
        return os.path.dirname(os.path.dirname(os.path.abspath(paths[0])))
    return None


def lint_paths(paths: list[str]) -> list[Finding]:
    """All six rule families plus pragma hygiene over ``paths``; returns
    live (non-suppressed) findings sorted by location."""
    findings: list[Finding] = []
    mods = []
    for f in iter_py_files(paths):
        with open(f, encoding="utf-8") as fh:
            src = fh.read()
        mods.append(summarize_source(f, src))
        findings += lint_source_all(f, src)
    prog = ProgramSummary(mods)
    findings += check_lock_order(prog)
    findings += check_lane_purity(prog)
    findings += check_typed_errors(prog)
    findings += check_counters(prog, _tree_root(paths))
    findings += check_pragmas(prog, findings)
    live = [f for f in findings if not f.suppressed]
    return sorted(live, key=lambda f: (f.path, f.line, f.col, f.rule))


def main(argv: list[str] | None = None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    as_json = "--json" in args
    args = [a for a in args if a != "--json"]
    if not args:
        print("usage: python -m nds_tpu.analysis [--json] <path>...",
              file=sys.stderr)
        return 2
    findings = lint_paths(args)
    if as_json:
        counts: dict[str, int] = {}
        for f in findings:
            counts[f.rule] = counts.get(f.rule, 0) + 1
        print(json.dumps({"ok": not findings,
                          "counts": counts,
                          "findings": [f.to_dict() for f in findings]},
                         indent=2, sort_keys=True))
    else:
        for f in findings:
            print(f)
    if findings:
        if not as_json:
            print(f"{len(findings)} finding(s)", file=sys.stderr)
        return 1
    return 0
