"""Shared lint plumbing: findings, pragma vocabulary, AST helpers.

Everything in ``nds_tpu.analysis`` is pure stdlib and must stay importable
without jax/pyarrow — the CI ``static`` stage runs it BEFORE anything
executes, and the whole-tree run is budgeted under 10 s.
"""
from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field

#: the complete pragma vocabulary. Suppressing pragmas silence ONE rule on
#: the line they annotate; marker pragmas declare a property of a def
#: (thread-entry: concurrently entered, ENG002 applies; device-lane: runs
#: on the device-lane thread, ENG004 applies). Every pragma must carry a
#: non-empty ``(<reason>)`` — enforced by the ENG007 hygiene pass.
PRAGMA_RULES = {
    "frozen-exempt": "ENG001",
    "lock-exempt": "ENG002",
    "lock-order-exempt": "ENG003",
    "device-lane-exempt": "ENG004",
    "typed-error-exempt": "ENG005",
    "counter-exempt": "ENG006",
}
MARKER_PRAGMAS = ("thread-entry", "device-lane")
KNOWN_PRAGMAS = tuple(PRAGMA_RULES) + MARKER_PRAGMAS

#: one regex finds every pragma occurrence with its optional reason
PRAGMA_RE = re.compile(r"#\s*lint:\s*([a-z][a-z0-9-]*)\s*(?:\(([^)]*)\))?")


@dataclass
class Finding:
    path: str
    line: int
    col: int
    rule: str
    message: str
    #: the pragma string that would silence this finding (``--json``
    #: consumers print it as the actionable escape hatch)
    suggestion: str = ""
    #: True when a pragma on the line suppressed it: excluded from output,
    #: but the stale-pragma pass uses suppressed findings as evidence that
    #: the pragma still fires
    suppressed: bool = False

    def __str__(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: "
                f"{self.rule} {self.message}")

    def to_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "col": self.col, "message": self.message,
                "pragma_suggestion": self.suggestion}


def suggestion_for(rule: str) -> str:
    for pragma, r in PRAGMA_RULES.items():
        if r == rule:
            return f"# lint: {pragma} (<reason>)"
    return ""


def line_pragmas(lines: list[str], lineno: int) -> list[tuple[str, str]]:
    """[(pragma, reason)] on one 1-based source line."""
    if not (1 <= lineno <= len(lines)):
        return []
    return [(m.group(1), (m.group(2) or "").strip())
            for m in PRAGMA_RE.finditer(lines[lineno - 1])]


def has_pragma(lines: list[str], lineno: int, pragma: str) -> bool:
    return any(name == pragma for name, _ in line_pragmas(lines, lineno))


def def_header_pragma(lines: list[str], node, pragma: str) -> bool:
    """Does a def's header (decorator-free def line through the line
    before the first body statement) carry ``pragma``? Multi-line
    signatures keep the pragma on any header line."""
    end = node.body[0].lineno if node.body else node.lineno
    return any(has_pragma(lines, ln, pragma)
               for ln in range(node.lineno, min(end, len(lines)) + 1))


def dotted(node) -> str:
    """Best-effort dotted name of an expression ('self._lock', '')."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def root_name(node) -> str:
    """Leftmost Name of an attribute/subscript chain ('' when complex)."""
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else ""


def lock_ctx_name(ctx_expr) -> str:
    """Dotted name of a lock-shaped ``with`` context expression, or ''.

    Recognized shapes: any dotted name ending in ``lock`` (``self._lock``,
    ``_SHARED_LOCK``, ``session._sql_lock``), a Condition named ``*_cv``
    (its internal lock serializes exactly like a lock), and the
    ``METRICS.locked()`` accessor (returns the registry's shared value
    lock)."""
    if isinstance(ctx_expr, ast.Call):
        d = dotted(ctx_expr.func)
        if d.endswith(".locked") or d == "locked":
            return d
        return ""
    d = dotted(ctx_expr)
    if d.lower().endswith("lock") or d.endswith("_cv") or d == "_cv":
        return d
    return ""


def iter_py_files(paths: list[str]) -> list[str]:
    files: list[str] = []
    for p in paths:
        if os.path.isdir(p):
            for base_dir, _dirs, names in os.walk(p):
                if "__pycache__" in base_dir:
                    continue
                files += [os.path.join(base_dir, n) for n in sorted(names)
                          if n.endswith(".py")]
        else:
            files.append(p)
    return files
