"""ENG001/ENG002 — the original per-file engine-discipline rules.

ENG001 — frozen plan IR. Plan nodes and bound expressions (engine/plan.py
dataclasses) are treated as immutable everywhere: rewrite passes rebuild
copy-on-write (``dataclasses.replace``), because plans are DAGs — a node
reachable from several parents (shared CTE subtrees, segment-cache slots)
that is mutated in place silently shifts positional bindings for every
other consumer. Flags attribute assignments, augmented assignments,
subscript stores, and mutating container calls on plan-IR fields, except
builder-style writes to objects constructed in the same function,
``self.<field>`` in non-IR classes, and ``# lint: frozen-exempt`` lines.

ENG002 — cross-thread writes take the lock. Functions handed to worker
threads (``threading.Thread(target=...)``, ``pool.submit/map``) — or
marked concurrently-entered with the ``# lint: thread-entry`` def-header
pragma — must write shared attributes under a lock-shaped ``with``;
thread-local objects (constructed in-function) and
``# lint: lock-exempt`` lines pass.

Unlike the pre-package linter, pragma'd sites still EMIT findings, with
``suppressed=True`` — the runner filters them from output, and the
ENG007 hygiene pass uses them as proof the pragma is not stale.
"""
from __future__ import annotations

import ast

from .base import (Finding, def_header_pragma, dotted, has_pragma,
                   lock_ctx_name, root_name, suggestion_for)

# Plan-IR dataclass fields whose names are distinctive enough to identify a
# plan node / bound expression at a write site (engine/plan.py; keep in
# sync when the IR grows fields). Deliberately excludes names too generic
# to attribute (table, plan, index, dtype, name, value, op, args, extra,
# func, arg, kind, label, key, n, all, distinct, asc, left, right).
PLAN_FIELDS = frozenset({
    "out_names", "out_dtypes", "child", "predicate", "exprs",
    "left_keys", "right_keys", "residual", "null_aware", "late_mat",
    "group_exprs", "aggs", "rollup", "rollup_levels", "funcs", "keys",
    "columns", "partition_by", "order_by", "nulls_first", "cte_segments",
})

# classes whose OWN attributes legitimately carry plan-field names: the IR
# dataclasses themselves (self-writes inside them are still flagged)
IR_CLASSES = frozenset({
    "PlanNode", "ScanNode", "FilterNode", "ProjectNode", "JoinNode",
    "AggregateNode", "WindowNode", "SortNode", "LimitNode", "DistinctNode",
    "SetOpNode", "MaterializedNode", "VirtualScanNode", "BExpr", "BCol",
    "BLit", "BCall", "BParam", "BScalarSubquery", "AggSpec", "SortKey",
    "WindowFunc",
})

MUTATOR_METHODS = frozenset({
    "append", "extend", "insert", "pop", "remove", "clear", "sort",
    "reverse", "update", "setdefault",
})


class _FunctionInfo:
    """Per-function facts shared by both rules."""

    def __init__(self, fn: ast.AST):
        self.fn = fn
        # local names bound from a direct ClassName(...) constructor call:
        # attribute writes through them are builder-style initialization
        self.owned: set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call) and \
                    isinstance(node.value.func, ast.Name) and \
                    node.value.func.id[:1].isupper():
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        self.owned.add(t.id)


class _Linter(ast.NodeVisitor):
    def __init__(self, path: str, src: str, engine_scope: bool):
        self.path = path
        self.lines = src.splitlines()
        self.engine_scope = engine_scope   # rule ENG001 applies here
        self.findings: list[Finding] = []
        self._class_stack: list[str] = []
        self._fn_stack: list[_FunctionInfo] = []
        # thread-target function names collected in a pre-pass
        self.thread_targets: set[str] = set()
        self._thread_depth = 0
        self._lock_depth = 0

    # -- helpers -------------------------------------------------------------
    def _add(self, node, rule: str, message: str, pragma: str) -> None:
        self.findings.append(Finding(
            self.path, node.lineno, node.col_offset, rule, message,
            suggestion=suggestion_for(rule),
            suppressed=has_pragma(self.lines, node.lineno, pragma)))

    def _owned(self, root: str) -> bool:
        return any(root in fi.owned for fi in self._fn_stack)

    def _in_ir_class(self) -> bool:
        return bool(self._class_stack) and \
            self._class_stack[-1] in IR_CLASSES

    # -- pre-pass: thread targets ---------------------------------------------
    def collect_thread_targets(self, tree: ast.AST) -> None:
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            cands: list[ast.expr] = []
            if isinstance(node.func, ast.Attribute):
                if node.func.attr == "Thread" or \
                        dotted(node.func).endswith("threading.Thread"):
                    cands += [k.value for k in node.keywords
                              if k.arg == "target"]
                elif node.func.attr in ("submit", "map") and node.args:
                    # pool.submit(fn, ...) / pool.map(fn, it): first arg
                    cands.append(node.args[0])
            elif isinstance(node.func, ast.Name) and \
                    node.func.id == "Thread":
                cands += [k.value for k in node.keywords
                          if k.arg == "target"]
            for c in cands:
                if isinstance(c, ast.Name):
                    self.thread_targets.add(c.id)
                elif isinstance(c, ast.Attribute):
                    self.thread_targets.add(c.attr)

    # -- traversal -------------------------------------------------------------
    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._class_stack.append(node.name)
        self.generic_visit(node)
        self._class_stack.pop()

    def _visit_fn(self, node) -> None:
        entered_thread = node.name in self.thread_targets \
            or def_header_pragma(self.lines, node, "thread-entry")
        self._fn_stack.append(_FunctionInfo(node))
        if entered_thread:
            self._thread_depth += 1
        self.generic_visit(node)
        if entered_thread:
            self._thread_depth -= 1
        self._fn_stack.pop()

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn

    def visit_With(self, node: ast.With) -> None:
        locked = any(lock_ctx_name(i.context_expr) for i in node.items)
        if locked:
            self._lock_depth += 1
        self.generic_visit(node)
        if locked:
            self._lock_depth -= 1

    # -- write sites ------------------------------------------------------------
    def _check_store(self, target, stmt) -> None:
        # unwrap subscript stores: node.out_names[0] = x mutates out_names
        sub = target
        while isinstance(sub, ast.Subscript):
            sub = sub.value
        if isinstance(sub, ast.Attribute):
            self._check_attr_write(sub, stmt,
                                   subscript=sub is not target)
        # plain Name / Tuple targets mutate no object attribute

    def _check_attr_write(self, attr: ast.Attribute, stmt,
                          subscript: bool = False) -> None:
        root = root_name(attr.value)
        # ENG001: frozen plan IR
        if self.engine_scope and attr.attr in PLAN_FIELDS:
            allowed = (root == "self" and not self._in_ir_class()) or \
                (root != "self" and self._owned(root))
            if not allowed:
                how = "subscript store into" if subscript else \
                    "in-place assignment to"
                self._add(stmt, "ENG001",
                          f"{how} plan-IR field "
                          f"'{dotted(attr) or attr.attr}': plan nodes and "
                          "bound expressions are frozen — rebuild "
                          "copy-on-write (dataclasses.replace), or mark a "
                          "sanctioned builder with "
                          "'# lint: frozen-exempt (<reason>)'",
                          "frozen-exempt")
        # ENG002: unlocked write from a thread-target function
        if self._thread_depth > 0 and self._lock_depth == 0:
            if root and root != "self" and self._owned(root):
                return          # thread-local object, not shared state
            self._add(stmt, "ENG002",
                      f"attribute write '{dotted(attr) or attr.attr}' in "
                      "a thread-target function outside any lock: shared "
                      "session/streaming state must be written under its "
                      "lock ('with <lock>:'), or mark thread-local state "
                      "with '# lint: lock-exempt (<reason>)'",
                      "lock-exempt")

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            self._check_store(t, node)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._check_store(node.target, node)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._check_store(node.target, node)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        # mutating container calls on plan-IR fields:
        # node.out_names.append(x)
        f = node.func
        if self.engine_scope and isinstance(f, ast.Attribute) and \
                f.attr in MUTATOR_METHODS and \
                isinstance(f.value, ast.Attribute) and \
                f.value.attr in PLAN_FIELDS:
            root = root_name(f.value.value)
            allowed = (root == "self" and not self._in_ir_class()) or \
                (root != "self" and self._owned(root))
            if not allowed:
                self._add(node, "ENG001",
                          f"mutating call '{dotted(f)}()' on a plan-IR "
                          "field: plan nodes are frozen — rebuild the list "
                          "copy-on-write", "frozen-exempt")
        self.generic_visit(node)


def lint_source_all(path: str, src: str,
                    engine_scope: bool | None = None) -> list[Finding]:
    """Per-file rules INCLUDING pragma-suppressed findings (the hygiene
    pass's evidence that a pragma still fires)."""
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [Finding(path, e.lineno or 0, 0, "ENG000",
                        f"syntax error: {e.msg}")]
    if engine_scope is None:
        engine_scope = True      # plan IR may be touched from anywhere
    linter = _Linter(path, src, engine_scope)
    linter.collect_thread_targets(tree)
    linter.visit(tree)
    return sorted(linter.findings, key=lambda f: (f.path, f.line, f.col))


def lint_source(path: str, src: str,
                engine_scope: bool | None = None) -> list[Finding]:
    """Lint one file's source with the per-file rules (ENG001/ENG002);
    engine_scope controls ENG001. Pragma-suppressed findings are
    filtered — the historical single-file contract."""
    return [f for f in lint_source_all(path, src, engine_scope)
            if not f.suppressed]
