"""Recursive-descent parser for the NDS SQL dialect (Spark-SQL subset).

Covers the constructs used by the 99 TPC-DS query templates in their Spark
dialect form plus the LF_*/DF_* maintenance statements (CREATE TEMP VIEW,
INSERT INTO, DELETE FROM): CTEs, explicit/comma joins, scalar/IN/EXISTS
subqueries, CASE, CAST, BETWEEN/LIKE/IS NULL, interval arithmetic, window
functions, GROUP BY ROLLUP, set operations, ORDER BY w/ NULLS ordering, LIMIT.
"""
from __future__ import annotations

from .ast_nodes import (
    Between, BinOp, Case, Cast, ColumnRef, CreateView, Delete, DropView, Exists,
    FuncCall, GroupBy, InList, InSubquery, Insert, Interval, IsNull, Join, Like,
    Literal, Query, ScalarSubquery, Select, SelectItem, SetOp, SortItem, Star,
    SubqueryRef, TableRef, UnaryOp, WindowSpec,
)
from .lexer import Token, tokenize


class SqlParseError(ValueError):
    def __init__(self, msg: str, token: Token | None = None, sql: str = ""):
        ctx = ""
        if token is not None and sql:
            lo = max(0, token.pos - 40)
            ctx = f" near ...{sql[lo:token.pos + 20]!r}"
        super().__init__(msg + ctx)


_CMP_OPS = {"=", "<>", "!=", "<", "<=", ">", ">="}

# keywords that may still be used as plain identifiers (column/table/alias names)
_NONRESERVED = {
    "date", "first", "last", "current", "row", "rows", "range", "temp",
    "temporary", "view", "table", "if", "values", "using", "replace",
    "partition", "over", "asc", "desc", "rollup", "nulls", "year",
}


class _Parser:
    def __init__(self, sql: str):
        self.sql = sql
        self.tokens = tokenize(sql)
        self.i = 0

    # -- token helpers -----------------------------------------------------
    def peek(self, offset: int = 0) -> Token:
        return self.tokens[min(self.i + offset, len(self.tokens) - 1)]

    def next(self) -> Token:
        tok = self.tokens[self.i]
        if tok.kind != "EOF":
            self.i += 1
        return tok

    def at_kw(self, *words: str) -> bool:
        t = self.peek()
        return t.kind == "KW" and t.value in words

    def at_op(self, *ops: str) -> bool:
        t = self.peek()
        return t.kind == "OP" and t.value in ops

    def accept_kw(self, *words: str) -> bool:
        if self.at_kw(*words):
            self.next()
            return True
        return False

    def accept_op(self, *ops: str) -> bool:
        if self.at_op(*ops):
            self.next()
            return True
        return False

    def expect_kw(self, word: str) -> None:
        if not self.accept_kw(word):
            self.fail(f"expected {word.upper()}")

    def expect_op(self, op: str) -> None:
        if not self.accept_op(op):
            self.fail(f"expected {op!r}")

    def fail(self, msg: str):
        raise SqlParseError(msg, self.peek(), self.sql)

    def ident(self) -> str:
        t = self.peek()
        # non-reserved keywords double as identifiers in TPC-DS output columns
        if t.kind == "IDENT" or (t.kind == "KW" and t.value in _NONRESERVED):
            self.next()
            return t.value
        self.fail("expected identifier")

    # -- statements --------------------------------------------------------
    def parse_statements(self) -> list:
        stmts = []
        while self.peek().kind != "EOF":
            if self.accept_op(";"):
                continue
            stmts.append(self.parse_statement())
        return stmts

    def parse_statement(self):
        if self.at_kw("create"):
            return self.create_view()
        if self.at_kw("insert"):
            return self.insert()
        if self.at_kw("delete"):
            return self.delete()
        if self.at_kw("drop"):
            return self.drop()
        return self.query()

    def create_view(self) -> CreateView:
        self.expect_kw("create")
        if self.accept_kw("or"):
            self.expect_kw("replace")
        temp = self.accept_kw("temp") or self.accept_kw("temporary")
        self.expect_kw("view")
        name = self.ident()
        self.expect_kw("as")
        wrapped = self.accept_op("(")
        q = self.query()
        if wrapped:
            self.expect_op(")")
        return CreateView(name, q, temp=temp)

    def insert(self) -> Insert:
        self.expect_kw("insert")
        self.expect_kw("into")
        self.accept_kw("table")
        name = self.ident()
        wrapped = self.accept_op("(")
        q = self.query()
        if wrapped:
            self.expect_op(")")
        return Insert(name, q)

    def delete(self) -> Delete:
        self.expect_kw("delete")
        self.expect_kw("from")
        name = self.ident()
        where = None
        if self.accept_kw("where"):
            where = self.expr()
        return Delete(name, where)

    def drop(self) -> DropView:
        self.expect_kw("drop")
        if not (self.accept_kw("view") or self.accept_kw("table")):
            self.fail("expected VIEW or TABLE")
        self.accept_kw("if")
        self.accept_kw("exists")
        return DropView(self.ident())

    # -- queries -----------------------------------------------------------
    def query(self) -> Query:
        ctes: list[tuple[str, Query]] = []
        if self.accept_kw("with"):
            while True:
                name = self.ident()
                self.expect_kw("as")
                self.expect_op("(")
                ctes.append((name, self.query()))
                self.expect_op(")")
                if not self.accept_op(","):
                    break
        body = self.set_expr()
        order_by: list[SortItem] = []
        limit = None
        if self.accept_kw("order"):
            self.expect_kw("by")
            order_by = self.sort_items()
        if self.accept_kw("limit"):
            t = self.next()
            if t.kind != "NUMBER":
                self.fail("expected number after LIMIT")
            limit = int(t.value)
        return Query(body=body, ctes=ctes, order_by=order_by, limit=limit)

    def set_expr(self):
        # INTERSECT binds tighter than UNION/EXCEPT
        left = self.intersect_expr()
        while self.at_kw("union", "except"):
            op = self.next().value
            all_ = self.accept_kw("all")
            self.accept_kw("distinct")
            right = self.intersect_expr()
            left = SetOp(op, all_, left, right)
        return left

    def intersect_expr(self):
        left = self.select_core()
        while self.at_kw("intersect"):
            self.next()
            all_ = self.accept_kw("all")
            self.accept_kw("distinct")
            right = self.select_core()
            left = SetOp("intersect", all_, left, right)
        return left

    def select_core(self):
        if self.accept_op("("):
            # parenthesized query or set-expr
            q = self.query()
            self.expect_op(")")
            return q
        self.expect_kw("select")
        distinct = self.accept_kw("distinct")
        self.accept_kw("all")
        items = [self.select_item()]
        while self.accept_op(","):
            items.append(self.select_item())
        sel = Select(items=items, distinct=distinct)
        if self.accept_kw("from"):
            sel.from_ = self.from_clause()
        if self.accept_kw("where"):
            sel.where = self.expr()
        if self.accept_kw("group"):
            self.expect_kw("by")
            sel.group_by = self.group_by()
        if self.accept_kw("having"):
            sel.having = self.expr()
        return sel

    def select_item(self) -> SelectItem:
        if self.at_op("*"):
            self.next()
            return SelectItem(Star())
        # qualified star: alias.*
        if (self.peek().kind in ("IDENT", "KW") and self.peek(1).kind == "OP"
                and self.peek(1).value == "." and self.peek(2).value == "*"):
            qual = self.ident()
            self.next()  # .
            self.next()  # *
            return SelectItem(Star(qualifier=qual))
        e = self.expr()
        alias = None
        if self.accept_kw("as"):
            alias = self.ident()
        elif self.peek().kind == "IDENT":
            alias = self.ident()
        return SelectItem(e, alias)

    def group_by(self) -> GroupBy:
        if self.accept_kw("rollup"):
            self.expect_op("(")
            exprs = [self.expr()]
            while self.accept_op(","):
                exprs.append(self.expr())
            self.expect_op(")")
            return GroupBy(exprs, rollup=True)
        exprs = [self.expr()]
        while self.accept_op(","):
            exprs.append(self.expr())
        return GroupBy(exprs, rollup=False)

    def sort_items(self) -> list[SortItem]:
        items = [self.sort_item()]
        while self.accept_op(","):
            items.append(self.sort_item())
        return items

    def sort_item(self) -> SortItem:
        e = self.expr()
        asc = True
        if self.accept_kw("asc"):
            asc = True
        elif self.accept_kw("desc"):
            asc = False
        nulls_first = None
        if self.accept_kw("nulls"):
            if self.accept_kw("first"):
                nulls_first = True
            elif self.accept_kw("last"):
                nulls_first = False
            else:
                self.fail("expected FIRST or LAST")
        return SortItem(e, asc=asc, nulls_first=nulls_first)

    # -- FROM --------------------------------------------------------------
    def from_clause(self):
        rel = self.table_primary()
        while True:
            if self.accept_op(","):
                rel = Join(rel, self.table_primary(), kind="cross")
                continue
            kind = None
            if self.accept_kw("cross"):
                kind = "cross"
            elif self.accept_kw("inner"):
                kind = "inner"
            elif self.at_kw("left", "right", "full"):
                kind = self.next().value
                self.accept_kw("outer")
            if kind is not None:
                self.expect_kw("join")
            elif self.accept_kw("join"):
                kind = "inner"
            else:
                break
            right = self.table_primary()
            on = None
            if kind != "cross" and self.accept_kw("on"):
                on = self.expr()
            rel = Join(rel, right, kind=kind, on=on)
        return rel

    def table_primary(self):
        if self.accept_op("("):
            q = self.query()
            self.expect_op(")")
            self.accept_kw("as")
            alias = self.ident()
            return SubqueryRef(q, alias)
        name = self.ident()
        # qualified (catalog-dotted) table name: system.query_log etc. —
        # the parts join into ONE catalog key, same token shapes as the
        # dotted column reference below
        while self.at_op(".") and (
                self.peek(1).kind == "IDENT"
                or (self.peek(1).kind == "KW"
                    and self.peek(1).value in _NONRESERVED)):
            self.next()
            name = f"{name}.{self.ident()}"
        alias = None
        if self.accept_kw("as"):
            alias = self.ident()
        elif self.peek().kind == "IDENT":
            alias = self.ident()
        return TableRef(name, alias)

    # -- expressions -------------------------------------------------------
    def expr(self):
        return self.or_expr()

    def or_expr(self):
        left = self.and_expr()
        while self.accept_kw("or"):
            left = BinOp("or", left, self.and_expr())
        return left

    def and_expr(self):
        left = self.not_expr()
        while self.accept_kw("and"):
            left = BinOp("and", left, self.not_expr())
        return left

    def not_expr(self):
        if self.accept_kw("not"):
            return UnaryOp("not", self.not_expr())
        return self.predicate()

    def predicate(self):
        left = self.add_expr()
        while True:
            if self.at_op(*_CMP_OPS):
                op = self.next().value
                if op == "!=":
                    op = "<>"
                right = self.add_expr()
                left = BinOp(op, left, right)
                continue
            negated = False
            save = self.i
            if self.accept_kw("not"):
                if not self.at_kw("between", "in", "like"):
                    self.i = save
                    return left
                negated = True
            if self.accept_kw("between"):
                low = self.add_expr()
                self.expect_kw("and")
                high = self.add_expr()
                left = Between(left, low, high, negated)
                continue
            if self.accept_kw("in"):
                self.expect_op("(")
                if self.at_kw("select", "with") or self.at_op("("):
                    q = self.query()
                    left = InSubquery(left, q, negated)
                else:
                    items = [self.expr()]
                    while self.accept_op(","):
                        items.append(self.expr())
                    left = InList(left, items, negated)
                self.expect_op(")")
                continue
            if self.accept_kw("like"):
                left = Like(left, self.add_expr(), negated)
                continue
            if self.accept_kw("is"):
                neg = self.accept_kw("not")
                self.expect_kw("null")
                left = IsNull(left, negated=neg)
                continue
            return left

    def add_expr(self):
        left = self.mul_expr()
        while self.at_op("+", "-", "||"):
            op = self.next().value
            left = BinOp(op, left, self.mul_expr())
        return left

    def mul_expr(self):
        left = self.unary_expr()
        while self.at_op("*", "/", "%"):
            op = self.next().value
            left = BinOp(op, left, self.unary_expr())
        return left

    def unary_expr(self):
        if self.at_op("+", "-"):
            op = self.next().value
            return UnaryOp(op, self.unary_expr())
        return self.primary()

    def primary(self):
        t = self.peek()
        if t.kind == "NUMBER":
            self.next()
            text = t.value
            if "." in text or "e" in text or "E" in text:
                return Literal(float(text))
            return Literal(int(text))
        if t.kind == "STRING":
            self.next()
            return Literal(t.value)
        if self.at_kw("null"):
            self.next()
            return Literal(None)
        if self.at_kw("date") and self.peek(1).kind == "STRING":
            self.next()
            lit = self.next()
            return Literal(lit.value, type_hint="date")
        if self.at_kw("interval"):
            self.next()
            value = self.unary_expr()
            unit = self.ident().rstrip("s")  # day/days, month/months, year/years
            return Interval(value, unit)
        if self.at_kw("case"):
            return self.case_expr()
        if self.at_kw("cast"):
            return self.cast_expr()
        if self.at_kw("exists"):
            self.next()
            self.expect_op("(")
            q = self.query()
            self.expect_op(")")
            return Exists(q)
        if self.accept_op("("):
            if self.at_kw("select", "with"):
                q = self.query()
                self.expect_op(")")
                return ScalarSubquery(q)
            e = self.expr()
            self.expect_op(")")
            return e
        if t.kind == "IDENT" or (t.kind == "KW" and t.value in _NONRESERVED):
            return self.name_or_call()
        self.fail("expected expression")

    def case_expr(self) -> Case:
        self.expect_kw("case")
        operand = None
        if not self.at_kw("when"):
            operand = self.expr()
        whens = []
        while self.accept_kw("when"):
            cond = self.expr()
            self.expect_kw("then")
            whens.append((cond, self.expr()))
        else_ = None
        if self.accept_kw("else"):
            else_ = self.expr()
        self.expect_kw("end")
        return Case(operand, whens, else_)

    def cast_expr(self) -> Cast:
        self.expect_kw("cast")
        self.expect_op("(")
        e = self.expr()
        self.expect_kw("as")
        to_type = self.type_name()
        self.expect_op(")")
        return Cast(e, to_type)

    def type_name(self) -> str:
        base = self.ident()
        if self.accept_op("("):
            nums = [self.next().value]
            while self.accept_op(","):
                nums.append(self.next().value)
            self.expect_op(")")
            return f"{base}({','.join(nums)})"
        return base

    def name_or_call(self):
        name = self.ident()
        # function call
        if self.at_op("(") and name != "date":
            self.next()
            distinct = self.accept_kw("distinct")
            args: list = []
            if self.at_op("*"):
                self.next()
                args.append(Star())
            elif not self.at_op(")"):
                args.append(self.expr())
                while self.accept_op(","):
                    args.append(self.expr())
            self.expect_op(")")
            over = None
            if self.accept_kw("over"):
                over = self.window_spec()
            return FuncCall(name, args, distinct=distinct, over=over)
        # dotted column reference
        parts = [name]
        while self.at_op(".") and (
                self.peek(1).kind == "IDENT"
                or (self.peek(1).kind == "KW" and self.peek(1).value in _NONRESERVED)):
            self.next()
            parts.append(self.ident())
        return ColumnRef(tuple(parts))

    def window_spec(self) -> WindowSpec:
        self.expect_op("(")
        spec = WindowSpec()
        if self.accept_kw("partition"):
            self.expect_kw("by")
            spec.partition_by.append(self.expr())
            while self.accept_op(","):
                spec.partition_by.append(self.expr())
        if self.accept_kw("order"):
            self.expect_kw("by")
            spec.order_by = self.sort_items()
        # frame clause: consume tokens up to the closing paren (frames beyond the
        # default are recorded but not interpreted; TPC-DS uses default frames)
        frame_toks = []
        depth = 0
        while not (depth == 0 and self.at_op(")")):
            tok = self.next()
            if tok.kind == "EOF":
                self.fail("unterminated window spec")
            if tok.kind == "OP" and tok.value == "(":
                depth += 1
            elif tok.kind == "OP" and tok.value == ")":
                depth -= 1
            frame_toks.append(tok.value)
        self.expect_op(")")
        if frame_toks:
            spec.frame = " ".join(frame_toks)
        return spec


def parse_sql(sql: str) -> Query:
    """Parse a single SELECT query (the power-run path)."""
    p = _Parser(sql)
    q = p.parse_statement()
    p.accept_op(";")
    if p.peek().kind != "EOF":
        p.fail("trailing tokens after statement")
    if not isinstance(q, Query):
        raise SqlParseError("expected a SELECT query")
    return q


def parse_statements(sql: str) -> list:
    """Parse a ;-separated script (maintenance functions)."""
    return _Parser(sql).parse_statements()
