"""SQL frontend: lexer, parser, AST, and logical planner for the NDS dialect.

The dialect is the Spark-SQL subset that TPC-DS query streams and the
LF_*/DF_* maintenance functions use (reference templates.patch rewrites the
stock templates into exactly this dialect: `interval N days` arithmetic and
backtick-quoted identifiers; see reference nds/README.md:246-250).
"""
from .parser import SqlParseError, parse_sql, parse_statements

__all__ = ["SqlParseError", "parse_sql", "parse_statements"]
