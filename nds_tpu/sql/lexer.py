"""SQL tokenizer for the NDS (Spark-SQL subset) dialect."""
from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class Token:
    kind: str   # KW, IDENT, NUMBER, STRING, OP, EOF
    value: str  # keywords/idents lowercased; strings unquoted; ops literal
    pos: int = 0


_MULTI_OPS = ("<=", ">=", "<>", "!=", "||")
_SINGLE_OPS = "+-*/%(),.;=<>"

_KEYWORDS = {
    "select", "from", "where", "group", "by", "having", "order", "limit",
    "with", "as", "distinct", "all", "union", "intersect", "except",
    "join", "inner", "left", "right", "full", "outer", "cross", "on",
    "and", "or", "not", "in", "exists", "between", "like", "is", "null",
    "case", "when", "then", "else", "end", "cast", "interval", "asc",
    "desc", "nulls", "first", "last", "over", "partition", "rollup",
    "date", "rows", "range", "unbounded", "preceding", "following",
    "current", "row", "create", "temp", "temporary", "view", "insert",
    "into", "delete", "drop", "table", "if", "replace", "values", "using",
}


class SqlLexError(ValueError):
    pass


def tokenize(sql: str) -> list[Token]:
    tokens: list[Token] = []
    i, n = 0, len(sql)
    while i < n:
        ch = sql[i]
        if ch.isspace():
            i += 1
            continue
        # comments
        if sql.startswith("--", i):
            j = sql.find("\n", i)
            i = n if j < 0 else j + 1
            continue
        if sql.startswith("/*", i):
            j = sql.find("*/", i)
            if j < 0:
                raise SqlLexError(f"unterminated block comment at {i}")
            i = j + 2
            continue
        # string literal (single quotes, '' escape)
        if ch == "'":
            j = i + 1
            buf = []
            while j < n:
                if sql[j] == "'":
                    if j + 1 < n and sql[j + 1] == "'":
                        buf.append("'")
                        j += 2
                        continue
                    break
                buf.append(sql[j])
                j += 1
            else:
                raise SqlLexError(f"unterminated string at {i}")
            tokens.append(Token("STRING", "".join(buf), i))
            i = j + 1
            continue
        # quoted identifier: backticks (Spark) or double quotes
        if ch in "`\"":
            j = sql.find(ch, i + 1)
            if j < 0:
                raise SqlLexError(f"unterminated quoted identifier at {i}")
            tokens.append(Token("IDENT", sql[i + 1:j].lower(), i))
            i = j + 1
            continue
        # number
        if ch.isdigit() or (ch == "." and i + 1 < n and sql[i + 1].isdigit()):
            j = i
            seen_dot = seen_exp = False
            while j < n:
                c = sql[j]
                if c.isdigit():
                    j += 1
                elif c == "." and not seen_dot and not seen_exp:
                    seen_dot = True
                    j += 1
                elif c in "eE" and not seen_exp and j + 1 < n and (
                        sql[j + 1].isdigit() or sql[j + 1] in "+-"):
                    seen_exp = True
                    j += 2
                else:
                    break
            tokens.append(Token("NUMBER", sql[i:j], i))
            i = j
            continue
        # identifier / keyword
        if ch.isalpha() or ch == "_":
            j = i
            while j < n and (sql[j].isalnum() or sql[j] == "_"):
                j += 1
            word = sql[i:j].lower()
            tokens.append(Token("KW" if word in _KEYWORDS else "IDENT", word, i))
            i = j
            continue
        # operators
        if sql[i:i + 2] in _MULTI_OPS:
            tokens.append(Token("OP", sql[i:i + 2], i))
            i += 2
            continue
        if ch in _SINGLE_OPS:
            tokens.append(Token("OP", ch, i))
            i += 1
            continue
        raise SqlLexError(f"unexpected character {ch!r} at {i}")
    tokens.append(Token("EOF", "", n))
    return tokens
