"""AST node definitions for the SQL frontend."""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Union


class Node:
    pass


# --------------------------------------------------------------------------
# expressions
# --------------------------------------------------------------------------

@dataclass
class Literal(Node):
    value: object          # int | float | str | bool | None
    type_hint: str = ""    # "date" for DATE '...' literals


@dataclass
class ColumnRef(Node):
    parts: tuple[str, ...]  # ("alias", "col") or ("col",)

    @property
    def name(self) -> str:
        return self.parts[-1]

    @property
    def qualifier(self) -> Optional[str]:
        return self.parts[0] if len(self.parts) > 1 else None


@dataclass
class Star(Node):
    qualifier: Optional[str] = None


@dataclass
class WindowSpec(Node):
    partition_by: list[Node] = field(default_factory=list)
    order_by: list["SortItem"] = field(default_factory=list)
    frame: Optional[str] = None  # raw text of frame clause, informational


@dataclass
class FuncCall(Node):
    name: str
    args: list[Node]
    distinct: bool = False
    over: Optional[WindowSpec] = None


@dataclass
class BinOp(Node):
    op: str  # + - * / % = <> < <= > >= and or ||
    left: Node
    right: Node


@dataclass
class UnaryOp(Node):
    op: str  # - + not
    operand: Node


@dataclass
class Case(Node):
    operand: Optional[Node]             # CASE x WHEN ... (simple) if not None
    whens: list[tuple[Node, Node]]      # (condition/value, result)
    else_: Optional[Node] = None


@dataclass
class Cast(Node):
    expr: Node
    to_type: str  # normalized lowercase type text, e.g. "decimal(15,2)", "int"


@dataclass
class Between(Node):
    expr: Node
    low: Node
    high: Node
    negated: bool = False


@dataclass
class InList(Node):
    expr: Node
    items: list[Node]
    negated: bool = False


@dataclass
class InSubquery(Node):
    expr: Node
    query: "Query"
    negated: bool = False


@dataclass
class Exists(Node):
    query: "Query"
    negated: bool = False


@dataclass
class ScalarSubquery(Node):
    query: "Query"


@dataclass
class Like(Node):
    expr: Node
    pattern: Node
    negated: bool = False


@dataclass
class IsNull(Node):
    expr: Node
    negated: bool = False


@dataclass
class Interval(Node):
    value: Node
    unit: str  # singular: "day", "month", "year" (parser normalizes plurals)


# --------------------------------------------------------------------------
# relations / query structure
# --------------------------------------------------------------------------

@dataclass
class SortItem(Node):
    expr: Node
    asc: bool = True
    nulls_first: Optional[bool] = None  # None => dialect default (asc: first, desc: last)


@dataclass
class SelectItem(Node):
    expr: Node
    alias: Optional[str] = None


@dataclass
class TableRef(Node):
    name: str
    alias: Optional[str] = None


@dataclass
class SubqueryRef(Node):
    query: "Query"
    alias: str


@dataclass
class Join(Node):
    left: Node
    right: Node
    kind: str = "inner"  # inner, left, right, full, cross
    on: Optional[Node] = None


@dataclass
class GroupBy(Node):
    exprs: list[Node] = field(default_factory=list)
    rollup: bool = False


@dataclass
class Select(Node):
    items: list[SelectItem] = field(default_factory=list)
    distinct: bool = False
    from_: Optional[Node] = None  # TableRef | SubqueryRef | Join
    where: Optional[Node] = None
    group_by: Optional[GroupBy] = None
    having: Optional[Node] = None


@dataclass
class SetOp(Node):
    op: str  # union, intersect, except
    all: bool
    left: Node  # Select | SetOp | Query
    right: Node


@dataclass
class Query(Node):
    body: Node  # Select | SetOp
    ctes: list[tuple[str, "Query"]] = field(default_factory=list)
    order_by: list[SortItem] = field(default_factory=list)
    limit: Optional[int] = None


# --------------------------------------------------------------------------
# statements (maintenance functions)
# --------------------------------------------------------------------------

@dataclass
class CreateView(Node):
    name: str
    query: Query
    temp: bool = True


@dataclass
class Insert(Node):
    table: str
    query: Query


@dataclass
class Delete(Node):
    table: str
    where: Optional[Node] = None


@dataclass
class DropView(Node):
    name: str


Statement = Union[Query, CreateView, Insert, Delete, DropView]
