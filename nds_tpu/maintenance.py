"""Data Maintenance test: run the LF_*/DF_* refresh functions, timed.

Capability parity with the reference maintenance runner (reference
nds/nds_maintenance.py): the function lists (:45-58), delete-date tuples
read from the ``delete``/``inventory_delete`` tables (get_delete_date
:60-73), ordered DATE1/DATE2 substitution producing one statement set per
tuple (replace_date :75-96 — 3 tuples => 3x each delete), staging CSVs
registered as temp views (register_temp_views :267-271), and per-function
timing + CSV/JSON reporting identical in shape to the power run
(run_query :204-265).
"""
from __future__ import annotations

import argparse
import csv
import os
import sys
import time

from .config import EngineConfig
from .engine import Session
from .report import BenchReport
from .schema import get_maintenance_schemas
from .warehouse import Warehouse

SQL_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "data_maintenance")

INSERT_FUNCS = ["LF_CR", "LF_CS", "LF_I", "LF_SR", "LF_SS", "LF_WR", "LF_WS"]
DELETE_FUNCS = ["DF_CS", "DF_SS", "DF_WS"]
INVENTORY_DELETE_FUNCS = ["DF_I"]
MAINTENANCE_FUNCS = INSERT_FUNCS + DELETE_FUNCS + INVENTORY_DELETE_FUNCS


def get_delete_date(refresh_dir: str) -> tuple[list, list]:
    """Read DATE1/DATE2 tuples from the delete-date staging files."""
    import pyarrow.csv as pa_csv

    def read_pairs(table):
        path = os.path.join(refresh_dir, table)
        files = ([os.path.join(path, f) for f in sorted(os.listdir(path))]
                 if os.path.isdir(path) else [path])
        pairs = []
        for f in files:
            t = pa_csv.read_csv(
                f, read_options=pa_csv.ReadOptions(
                    column_names=["date1", "date2"]),
                parse_options=pa_csv.ParseOptions(delimiter="|"))
            pairs += list(zip(t.column("date1").to_pylist(),
                              t.column("date2").to_pylist()))
        return pairs

    return read_pairs("delete"), read_pairs("inventory_delete")


def replace_date(statements: str, pair: tuple[str, str]) -> str:
    """Substitute the ordered DATE1/DATE2 pair (reference :75-96)."""
    d1, d2 = sorted(str(d) for d in pair)
    return statements.replace("DATE1", d1).replace("DATE2", d2)


def load_function_sql(func: str) -> str:
    from .power import strip_sql_comments

    with open(os.path.join(SQL_DIR, f"{func}.sql")) as f:
        return strip_sql_comments(f.read())


def register_staging(session: Session, refresh_dir: str) -> None:
    for name, sch in get_maintenance_schemas().items():
        if name in ("delete", "inventory_delete"):
            continue
        path = os.path.join(refresh_dir, name)
        if os.path.exists(path):
            session.register_csv(name, path,
                                 sch.arrow_schema(use_decimal=False))


def run_maintenance(warehouse_path: str, refresh_dir: str, time_log: str,
                    maintenance_queries: list[str] | None = None,
                    json_summary_folder: str | None = None,
                    backend: str | None = None,
                    decimal: str | None = None,
                    session: Session | None = None
                    ) -> list[tuple[str, int, int, int]]:
    """``session``: reuse a caller-owned Session (warehouse attached and
    staging registered here) instead of building a fresh one — the
    chaos-mode lifecycle runs maintenance beside live service traffic and
    the flight recorder keeps the interleaving (``maintenance`` events
    per refresh function)."""
    from .config import maybe_enable_compile_cache
    from .obs.flight import FLIGHT

    maybe_enable_compile_cache()
    if session is None:
        config = EngineConfig()
        from .config import apply_decimal
        apply_decimal(config, decimal)
        session = Session(config)
    else:
        config = session.config
    wh = Warehouse(warehouse_path)
    session.attach_warehouse(wh)
    register_staging(session, refresh_dir)
    delete_dates, inventory_dates = get_delete_date(refresh_dir)

    funcs = maintenance_queries or MAINTENANCE_FUNCS
    rows = []
    test_start = int(time.time() * 1000)
    for func in funcs:
        sql = load_function_sql(func)
        if func in DELETE_FUNCS:
            variants = [replace_date(sql, p) for p in delete_dates]
        elif func in INVENTORY_DELETE_FUNCS:
            variants = [replace_date(sql, p) for p in inventory_dates]
        else:
            variants = [sql]
        report = BenchReport(config, app_name=f"NDS-TPU maintenance {func}")
        start = int(time.time() * 1000)
        from .obs.metrics import METRICS
        before = METRICS.snapshot()
        use_txn = getattr(config, "warehouse_transactions", True)

        def run_all(variants=variants, func=func):
            # one atomic warehouse transaction per refresh function: a
            # kill between its table writes (DF_SS touches store_sales
            # AND store_returns) leaves the previous published snapshot
            # current, and the orphaned partial commit is discarded by
            # recovery at next open — the phase is crash-RESUMABLE, not
            # re-runnable-and-hope
            if use_txn:
                with wh.transaction(committer=func):
                    for v in variants:
                        session.execute(v, backend=backend)
            else:
                for v in variants:
                    session.execute(v, backend=backend)
        report.report_on(run_all)
        if use_txn:
            # re-pin the writer session to the version it just published
            # (mid-transaction registrations are deliberately unpinned)
            session.refresh_warehouse()
        elapsed = report.summary["queryTimes"][-1]
        status = report.summary["queryStatus"][-1]
        rows.append((func, start, start + elapsed, elapsed))
        delta = METRICS.delta(before)
        # the chaos-mode post-mortem view: refresh functions interleaved
        # with live service admissions/dispatches in one flight ring —
        # including how the semantic result cache absorbed this function's
        # row delta (updated-in-place entries vs invalidated ones)
        FLIGHT.record("maintenance", func=func, status=status, ms=elapsed,
                      variants=len(variants),
                      ivm_updates=delta.get("result_cache_ivm_updates"),
                      cache_invalidations=delta.get(
                          "result_cache_invalidations"))
        print(f"{func}: {status} in {elapsed} ms", flush=True)
        if json_summary_folder:
            report.write_summary(
                func, prefix=os.path.join(json_summary_folder, "maintenance"))
    test_end = int(time.time() * 1000)

    os.makedirs(os.path.dirname(time_log) or ".", exist_ok=True)
    with open(time_log, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["query", "start_time", "end_time", "time"])
        w.writerow(["Maintenance Start Time", test_start, "", ""])
        for r in rows:
            w.writerow(r)
        w.writerow(["Maintenance End Time", test_end, "", ""])
        w.writerow(["Maintenance Test Time", "", "", test_end - test_start])
    return rows


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(prog="nds_tpu.maintenance")
    p.add_argument("warehouse_path")
    p.add_argument("refresh_dir", help="raw refresh (update-set) data dir")
    p.add_argument("time_log")
    p.add_argument("--maintenance_queries", default=None,
                   help="comma-separated subset of LF_*/DF_* functions")
    p.add_argument("--json_summary_folder", default=None)
    p.add_argument("--backend", default=None, choices=["jax", "numpy"])
    p.add_argument("--decimal", default=None, choices=["f64", "i64"])
    a = p.parse_args(argv)
    funcs = (a.maintenance_queries.split(",") if a.maintenance_queries
             else None)
    run_maintenance(a.warehouse_path, a.refresh_dir, a.time_log, funcs,
                    a.json_summary_folder, a.backend, a.decimal)
    return 0


if __name__ == "__main__":
    sys.exit(main())
