-- define [DMS] = uniform_int(1176, 1224)
WITH web_v1 AS (
  SELECT ws_item_sk AS item_sk, d_date,
         SUM(SUM(ws_sales_price)) OVER
             (PARTITION BY ws_item_sk ORDER BY d_date
              ROWS BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW)
             AS cume_sales
  FROM web_sales, date_dim
  WHERE ws_sold_date_sk = d_date_sk
    AND d_month_seq BETWEEN [DMS] AND [DMS] + 11
    AND ws_item_sk IS NOT NULL
  GROUP BY ws_item_sk, d_date
),
store_v1 AS (
  SELECT ss_item_sk AS item_sk, d_date,
         SUM(SUM(ss_sales_price)) OVER
             (PARTITION BY ss_item_sk ORDER BY d_date
              ROWS BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW)
             AS cume_sales
  FROM store_sales, date_dim
  WHERE ss_sold_date_sk = d_date_sk
    AND d_month_seq BETWEEN [DMS] AND [DMS] + 11
    AND ss_item_sk IS NOT NULL
  GROUP BY ss_item_sk, d_date
)
SELECT *
FROM (SELECT item_sk, d_date, web_sales, store_sales,
             MAX(web_cumulative) OVER
                 (PARTITION BY item_sk ORDER BY d_date
                  ROWS BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW)
                 AS web_cumulative,
             MAX(store_cumulative) OVER
                 (PARTITION BY item_sk ORDER BY d_date
                  ROWS BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW)
                 AS store_cumulative
      FROM (SELECT CASE WHEN web.item_sk IS NOT NULL
                        THEN web.item_sk ELSE store.item_sk END AS item_sk,
                   CASE WHEN web.d_date IS NOT NULL
                        THEN web.d_date ELSE store.d_date END AS d_date,
                   web.cume_sales AS web_sales,
                   store.cume_sales AS store_sales,
                   web.cume_sales AS web_cumulative,
                   store.cume_sales AS store_cumulative
            FROM web_v1 web FULL OUTER JOIN store_v1 store ON
                 (web.item_sk = store.item_sk
                  AND web.d_date = store.d_date)) x) y
WHERE web_cumulative > store_cumulative
ORDER BY item_sk, d_date
LIMIT 100
